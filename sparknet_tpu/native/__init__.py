"""ctypes binding + build-on-demand for the native data pipeline.

Where the reference is native, this framework is native too (SURVEY.md §2.3
build rule): the data-plane hot loops live in C++
(``data_pipeline.cpp``), compiled once on demand with the system toolchain
and loaded over ctypes — replacing the reference's JNA + libccaffe FFI
surface (reference: src/main/java/libs/CaffeLibrary.java:8-67,
libccaffe/ccaffe.h:5-69) for the parts that still belong on the host.  The
TPU compute path needs no FFI at all; everything here is batch-granular and
falls back to numpy when no compiler is available.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import sys
import threading

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "data_pipeline.cpp")
_LIB_PATH = os.path.join(_HERE, "_build", "libsparknet_data.so")

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_build_error: str | None = None


def _build() -> str | None:
    os.makedirs(os.path.dirname(_LIB_PATH), exist_ok=True)
    if (os.path.exists(_LIB_PATH)
            and os.path.getmtime(_LIB_PATH) >= os.path.getmtime(_SRC)):
        return None
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", _SRC,
           "-ljpeg", "-o", _LIB_PATH]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True, timeout=300)
    except (OSError, subprocess.TimeoutExpired) as e:
        return f"{type(e).__name__}: {e}"
    if proc.returncode != 0:
        return proc.stderr[-2000:]
    return None


def get_lib() -> ctypes.CDLL | None:
    """The loaded native library, building it on first use; None if the
    toolchain/libjpeg is unavailable (callers fall back to numpy)."""
    global _lib, _build_error
    with _lock:
        if _lib is not None or _build_error is not None:
            return _lib
        err = _build()
        if err is not None:
            _build_error = err
            print(f"sparknet_tpu.native: build failed, using numpy fallback\n"
                  f"{err}", file=sys.stderr)
            return None
        lib = ctypes.CDLL(_LIB_PATH)
        i64, i32p, f32p, f64p, u8p = (
            ctypes.c_int64,
            np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS"),
        )
        lib.sn_decode_cifar.argtypes = [u8p, i64, f32p, i32p]
        lib.sn_decode_cifar.restype = ctypes.c_int
        lib.sn_crop_batch_f32.argtypes = [
            f32p, i64, ctypes.c_int, ctypes.c_int, ctypes.c_int, f32p,
            ctypes.c_int, i32p, i32p, i32p, ctypes.c_void_p, i64]
        lib.sn_crop_batch_f32.restype = ctypes.c_int
        lib.sn_accumulate_mean.argtypes = [f32p, i64, i64, f64p]
        lib.sn_accumulate_mean.restype = ctypes.c_int
        lib.sn_decode_jpeg_resize.argtypes = [
            u8p, i64, ctypes.c_int, ctypes.c_int, f32p]
        lib.sn_decode_jpeg_resize.restype = ctypes.c_int
        i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
        lib.sn_parse_datum_batch.argtypes = [
            u8p, i64p, i64p, i64, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            f32p, i32p]
        lib.sn_parse_datum_batch.restype = ctypes.c_int
        _lib = lib
        return _lib


def available() -> bool:
    return get_lib() is not None


# ---------------------------------------------------------------------------
# numpy-signature wrappers (with automatic fallback)
# ---------------------------------------------------------------------------

def decode_cifar(records: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """records: uint8 [N, 3073] -> (images f32 [N,3,32,32], labels i32 [N])."""
    records = np.ascontiguousarray(records, np.uint8)
    n = records.shape[0]
    lib = get_lib()
    if lib is None:
        labels = records[:, 0].astype(np.int32)
        images = records[:, 1:].reshape(n, 3, 32, 32).astype(np.float32)
        return images, labels
    images = np.empty((n, 3, 32, 32), np.float32)
    labels = np.empty((n,), np.int32)
    rc = lib.sn_decode_cifar(records.reshape(-1), n, images.reshape(-1), labels)
    if rc != 0:
        raise RuntimeError(f"sn_decode_cifar failed: {rc}")
    return images, labels


def crop_batch(batch: np.ndarray, crop: int, ys: np.ndarray, xs: np.ndarray,
               flips: np.ndarray, mean: np.ndarray | float | None = None,
               out: np.ndarray | None = None) -> np.ndarray:
    """Crop+mirror+mean-subtract a f32 NCHW batch (ByteImage.cropInto,
    batched).  ``out``: optional preallocated (n, c, crop, crop) f32
    C-contiguous result buffer (e.g. from ``pipeline.BufferRing``) —
    shape/dtype mismatches fall back to a fresh allocation."""
    batch = np.ascontiguousarray(batch, np.float32)
    n, c, h, w = batch.shape
    ys = np.ascontiguousarray(ys, np.int32)
    xs = np.ascontiguousarray(xs, np.int32)
    flips = np.ascontiguousarray(flips, np.int32)
    if (out is None or out.shape != (n, c, crop, crop)
            or out.dtype != np.float32
            or not out.flags["C_CONTIGUOUS"]):
        out = np.empty((n, c, crop, crop), np.float32)
    mean_arr: np.ndarray | None = None
    if mean is not None:
        m = np.asarray(mean, np.float32)
        if m.ndim == 0:
            mean_arr = m.reshape(1)
        else:
            mean_arr = np.ascontiguousarray(
                np.broadcast_to(m, (c, crop, crop)), np.float32)
    lib = get_lib()
    if lib is None:
        for i in range(n):
            img = batch[i, :, ys[i]:ys[i] + crop, xs[i]:xs[i] + crop]
            out[i] = img[:, :, ::-1] if flips[i] else img
        if mean_arr is not None:
            out -= (mean_arr if mean_arr.size > 1 else mean_arr[0])
        return out
    mean_ptr = mean_arr.ctypes.data_as(ctypes.c_void_p) if mean_arr is not None else None
    rc = lib.sn_crop_batch_f32(
        batch.reshape(-1), n, c, h, w, out.reshape(-1), crop, ys, xs, flips,
        mean_ptr, 0 if mean_arr is None else mean_arr.size)
    if rc != 0:
        raise RuntimeError(f"sn_crop_batch_f32 failed: {rc}")
    return out


def accumulate_mean(images: np.ndarray, acc: np.ndarray) -> None:
    """Add per-pixel sums of a f32 [N, ...] batch into a float64 accumulator
    (ComputeMean partition sums)."""
    images = np.ascontiguousarray(images, np.float32)
    n = images.shape[0]
    plane = images.size // max(n, 1)
    if acc.size != plane or acc.dtype != np.float64:
        raise ValueError(
            f"accumulator mismatch: acc {acc.shape}/{acc.dtype}, "
            f"image plane has {plane} elements")
    lib = get_lib()
    if lib is None:
        acc += images.reshape(n, -1).sum(axis=0, dtype=np.float64).reshape(acc.shape)
        return
    rc = lib.sn_accumulate_mean(images.reshape(-1), n, plane, acc.reshape(-1))
    if rc != 0:
        raise RuntimeError(f"sn_accumulate_mean failed: {rc}")


def decode_jpeg_resize(data: bytes, out_h: int, out_w: int) -> np.ndarray | None:
    """JPEG bytes -> f32 [3, out_h, out_w] (force-resize, aspect ignored —
    ScaleAndConvert semantics); None for undecodable input (caller drops)."""
    lib = get_lib()
    if lib is None:
        try:
            from PIL import Image
            import io
            img = Image.open(io.BytesIO(data)).convert("RGB")
            img = img.resize((out_w, out_h), Image.BILINEAR)
            arr = np.asarray(img, np.float32)
            return np.ascontiguousarray(arr.transpose(2, 0, 1))
        except Exception:
            return None
    buf = np.frombuffer(data, np.uint8)
    out = np.empty((3, out_h, out_w), np.float32)
    rc = lib.sn_decode_jpeg_resize(buf, buf.size, out_h, out_w, out.reshape(-1))
    if rc != 0:
        return None
    return out


def parse_datum_batch(records: list[bytes], c: int, h: int, w: int,
                      ) -> tuple[np.ndarray, np.ndarray] | None:
    """Parse serialized Datum protos into (f32 [n,c,h,w], i32 labels) in
    one native pass (the data_reader + C++ protobuf role of the reference;
    reference: caffe/src/caffe/data_reader.cpp, protobuf parse in C++).
    Returns None when unavailable or when the batch has encoded/mismatched
    records — callers fall back to the per-record Python decoder."""
    lib = get_lib()
    if lib is None or not records:
        return None
    sizes = np.asarray([len(r) for r in records], np.int64)
    offsets = np.zeros(len(records), np.int64)
    np.cumsum(sizes[:-1], out=offsets[1:])
    buf = np.frombuffer(b"".join(records), np.uint8)
    out = np.empty((len(records), c, h, w), np.float32)
    labels = np.empty((len(records),), np.int32)
    rc = lib.sn_parse_datum_batch(buf, offsets, sizes, len(records),
                                  c, h, w, out.reshape(-1), labels)
    if rc != 0:
        return None
    return out, labels
