"""Drive the PR 3 surfaces end-to-end: record quarantine, prefetch
watchdog, object-store checksums, and the cross-replica parameter audit.
Run from the repo root: python .drive_r8.py  -> expect DRIVE OK."""
import os

os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
os.environ.pop("SPARKNET_FAULT", None)
os.environ.pop("SPARKNET_FAULT_ATTEMPT", None)

import jax

jax.config.update("jax_platforms", "cpu")

import tempfile
import time

import numpy as np

from sparknet_tpu.data import (
    DataCorruptionError, FeedStalled, PrefetchIterator, Quarantine,
    QuarantineExceeded, QuarantinePolicy, device_feed,
)
from sparknet_tpu.data.db import array_to_datum, datum_to_array, db_feed
from sparknet_tpu.data.lmdb_io import write_lmdb
from sparknet_tpu.data.objectstore import LocalStore, VerifyingStore
from sparknet_tpu.models import lenet
from sparknet_tpu.models.dsl import layer
from sparknet_tpu.parallel import DistributedTrainer, TrainerConfig, make_mesh
from sparknet_tpu.proto import load_solver_prototxt_with_net
from sparknet_tpu.proto.caffe_pb import Phase
from sparknet_tpu.utils import faults

td = tempfile.mkdtemp(prefix="drive_r8_")

# ---- 1. record quarantine through the public Data-layer feed ------------
rng = np.random.default_rng(0)
imgs = rng.integers(0, 256, size=(60, 3, 8, 8)).astype(np.uint8)
labels = rng.integers(0, 10, size=60)
dbp = os.path.join(td, "lmdb")
write_lmdb(dbp, [(b"%08d" % i, array_to_datum(imgs[i], int(labels[i])))
                 for i in range(60)])
lp = layer("d", "Data", [], ["data", "label"],
           data_param={"source": dbp, "batch_size": 8, "backend": "LMDB"})

os.environ["SPARKNET_FAULT"] = "corrupt_record:0.1"
faults.reset_injector()
q = Quarantine(QuarantinePolicy(max_fraction=0.5), epoch_size=60, source=dbp)
feed = db_feed(lp, Phase.TEST, quarantine=q)
for _ in range(20):
    b = next(feed)
    assert b["data"].shape == (8, 3, 8, 8)
rep = q.report()
assert rep["total_bad"] > 0 and rep["by_source"] == {dbp: rep["total_bad"]}
print(f"1. quarantine: {rep['total_bad']} bad records skipped+attributed "
      f"over {rep['epochs_completed']} epochs, feed kept serving")

faults.reset_injector()
q0 = Quarantine(QuarantinePolicy(), epoch_size=60, source=dbp)
try:
    for _ in range(20):
        next(db_feed(lp, Phase.TEST, quarantine=q0))
    raise SystemExit("FAIL: zero-tolerance budget did not trip")
except QuarantineExceeded as e:
    assert dbp in str(e)
    print("1b. budget exceeded -> typed QuarantineExceeded with attribution")

try:
    datum_to_array(b"\xde\xad" * 20, key=b"k7", source="probe")
    raise SystemExit("FAIL: garbage datum did not raise")
except DataCorruptionError as e:
    assert e.key == b"k7"
    print("1c. datum_to_array -> DataCorruptionError with key context")

# ---- 2. prefetch watchdog ----------------------------------------------
os.environ["SPARKNET_FAULT"] = "feeder_die@round:5"
faults.reset_injector()
assert list(PrefetchIterator(iter(range(20)), depth=2)) == list(range(20))
print("2. feeder_die -> one-shot restart, stream lossless")

os.environ["SPARKNET_FAULT"] = "feeder_hang:30s@round:3"
faults.reset_injector()
t0 = time.monotonic()
out = list(PrefetchIterator(iter(range(10)), depth=2, stall_timeout=0.3))
assert out == list(range(10)) and time.monotonic() - t0 < 5
print("2b. feeder_hang -> stall timeout fired, restart recovered")

os.environ["SPARKNET_FAULT"] = "feeder_die@round:1"
os.environ["SPARKNET_HEARTBEAT_DIR"] = os.path.join(td, "hb")
os.environ["SPARKNET_PROC_ID"] = "2"
faults.reset_injector()
it = PrefetchIterator(iter(range(5)), depth=1, restarts=0)
next(it)
try:
    next(it)
    raise SystemExit("FAIL: no FeedStalled")
except FeedStalled:
    from sparknet_tpu.parallel import health
    beat = health.read_beat(os.path.join(td, "hb"), 2)
    assert beat and beat.phase == "feed_stalled"
    print("2c. FeedStalled raised + feed_stalled heartbeat attributed")
del os.environ["SPARKNET_HEARTBEAT_DIR"]
os.environ.pop("SPARKNET_PROC_ID", None)
os.environ.pop("SPARKNET_FAULT", None)
faults.reset_injector()

# ---- 3. object-store checksums -----------------------------------------
obj = os.path.join(td, "obj")
os.makedirs(obj)
with open(os.path.join(obj, "rec"), "wb") as f:
    f.write(bytes(range(256)))
vs = VerifyingStore(LocalStore(obj))
vs.checksum_range("rec", 32, 64)
assert vs.open_range("rec", 32, 64) == bytes(range(32, 96))
with open(os.path.join(obj, "rec"), "r+b") as f:
    f.seek(40)
    f.write(b"\xff")
vs.close()
try:
    vs.open_range("rec", 32, 64)
    raise SystemExit("FAIL: rotted range not detected")
except DataCorruptionError as e:
    assert e.offset == 32
    print("3. VerifyingStore: clean range verifies, rot raises with offset")

# ---- 4. cross-replica audit on an 8-way mesh ---------------------------
def make(d, lr=0.05, **kw):
    sp = load_solver_prototxt_with_net(
        f'base_lr: {lr}\nmomentum: 0.9\nlr_policy: "fixed"\n', lenet(16, 16))
    return DistributedTrainer(
        sp, make_mesh(8),
        TrainerConfig(strategy="local_sgd", tau=2,
                      checkpoint_dir=d, **kw), seed=0)


def batch(r):
    g = np.random.default_rng(100 + r)
    return {"data": g.normal(size=(2, 16, 1, 28, 28)).astype(np.float32),
            "label": g.integers(0, 10, size=(2, 16)).astype(np.float32)}


clean = make(os.path.join(td, "cka"), audit_every=1)
while clean.round < 4:
    clean.train_round(batch(clean.round))
assert clean.audit_trips == 0

os.environ["SPARKNET_FAULT"] = "bitflip_params@rank:5@round:3"
faults.reset_injector()
tr = make(os.path.join(td, "ckb"), audit_every=1)
while tr.round < 4:
    tr.train_round(batch(tr.round))
assert tr.audit_trips == 1
np.testing.assert_array_equal(np.asarray(tr.params["conv1"][0]),
                              np.asarray(clean.params["conv1"][0]))
np.testing.assert_array_equal(np.asarray(tr.params["ip2"][0]),
                              np.asarray(clean.params["ip2"][0]))
print("4. audit: replica 5 bit flip caught at round 3, rollback+replay, "
      "final params bit-for-bit fault-free on the 8-way mesh")
os.environ.pop("SPARKNET_FAULT", None)
faults.reset_injector()

# ---- 5. error paths -----------------------------------------------------
try:
    make(None, audit_every=1)
    raise SystemExit("FAIL: audit without checkpoint_dir accepted")
except ValueError as e:
    assert "audit_every needs" in str(e)
try:
    make(os.path.join(td, "ckc"), audit_every=9)
    raise SystemExit("FAIL: cadence past retention accepted")
except ValueError as e:
    assert "outruns" in str(e)
try:
    faults.parse_faults("bitflip_params@round:1")
    raise SystemExit("FAIL: rankless bitflip accepted")
except ValueError:
    pass
print("5. error paths: config + grammar misuse named loudly")

# ---- 6. device_feed still composes with the trainer --------------------
stable = make(os.path.join(td, "ckd"), lr=0.005)
src = (batch(100 + i) for i in range(3))
fed = device_feed(src, depth=2, sharding=stable.input_sharding)
losses = [stable.train_round(b) for b in fed]
assert all(np.isfinite(l) for l in losses)
print("6. device_feed(watchdog) -> train_round composes, losses finite")

import shutil

shutil.rmtree(td, ignore_errors=True)
print("DRIVE OK")
