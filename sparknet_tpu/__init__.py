"""sparknet_tpu — a TPU-native distributed deep-learning framework.

Re-implements the capabilities of SparkNet (AMPLab; Spark driver + embedded
Caffe/CUDA workers over JNA) as an idiomatic JAX/XLA stack:

- Caffe-compatible prototxt front end (``sparknet_tpu.proto``) so the
  reference model zoo (LeNet, cifar10_quick/full, AlexNet/CaffeNet,
  GoogLeNet, VGG-16) loads unmodified.
- A functional graph compiler (``sparknet_tpu.graph``) that lowers
  ``NetParameter`` graphs to pure ``init``/``apply`` functions compiled by
  ``jax.jit`` — replacing Caffe's ``Net::Init`` + 107 CUDA kernel files.
- All six Caffe solvers with all seven LR policies (``sparknet_tpu.solvers``).
- A host data plane with background prefetch (``sparknet_tpu.data``) and an
  optional C++ fast path (``sparknet_tpu.native``), replacing the
  JNA-callback JavaDataLayer feed.
- Parallel training strategies (``sparknet_tpu.parallel``): synchronous
  per-step gradient ``psum`` (Caffe P2PSync semantics) and τ-step local SGD
  with weight averaging (SparkNet semantics), both as single compiled
  ``shard_map`` programs over a ``jax.sharding.Mesh`` — the driver bottleneck
  of the reference is gone.

Reference survey: SURVEY.md at the repo root.
"""

__version__ = "0.1.0"
