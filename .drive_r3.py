"""Round-3 drive: stochastic pooling e2e, debug_info pre-update forward,
leveldb writer round-trip, metadata-driven distributed eval."""
import os

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")
import jax

jax.config.update("jax_platforms", "cpu")

import itertools
import shutil

import numpy as np

NET = """
name: "stoch"
layer { name: "d" type: "JavaData" top: "data" top: "label"
  java_data_param { shape { dim: 32 dim: 1 dim: 8 dim: 8 } shape { dim: 32 } } }
layer { name: "conv1" type: "Convolution" bottom: "data" top: "conv1"
  convolution_param { num_output: 8 kernel_size: 3 stride: 1
    weight_filler { type: "xavier" } } }
layer { name: "relu1" type: "ReLU" bottom: "conv1" top: "conv1" }
layer { name: "pool1" type: "Pooling" bottom: "conv1" top: "pool1"
  pooling_param { pool: STOCHASTIC kernel_size: 2 stride: 2 } }
layer { name: "fc" type: "InnerProduct" bottom: "pool1" top: "fc"
  inner_product_param { num_output: 4 weight_filler { type: "xavier" } } }
layer { name: "loss" type: "SoftmaxWithLoss" bottom: "fc" bottom: "label" }
"""

from sparknet_tpu.data import device_feed
from sparknet_tpu.data.minibatch import batch_feed
from sparknet_tpu.proto import load_net_prototxt, load_solver_prototxt_with_net
from sparknet_tpu.solvers import Solver

rng = np.random.default_rng(0)
# separable synthetic data: class k has mean +2 in quadrant k
xs, ys = [], []
for _ in range(8):
    lab = rng.integers(0, 4, size=32)
    x = rng.normal(size=(32, 1, 8, 8)).astype(np.float32) * 0.1
    for i, l in enumerate(lab):
        x[i, 0, (l // 2) * 4:(l // 2) * 4 + 4, (l % 2) * 4:(l % 2) * 4 + 4] += 2.0
    xs.append(x)
    ys.append(lab.astype(np.float32))
batches = list(zip(xs, ys))

net = load_net_prototxt(NET)
solver = Solver(load_solver_prototxt_with_net(
    'base_lr: 0.05\nmomentum: 0.9\ndebug_info: true\ndisplay: 20\n', net),
    seed=0)
solver.set_train_data(device_feed(batch_feed(itertools.cycle(batches), None)))
l0 = solver.step(1)  # debug_info prints pre-update forward magnitudes
lN = solver.step(60)
print(f"stochastic-pool net: loss {l0:.3f} -> {lN:.3f}")
assert lN < 0.5 * l0, "stochastic-pool net failed to learn"

# leveldb writer round-trip through the public reader
from sparknet_tpu.data.leveldb_io import LeveldbReader, write_leveldb

shutil.rmtree("/tmp/ldb_drive", ignore_errors=True)
n = write_leveldb("/tmp/ldb_drive",
                  [(f"k{i:03d}".encode(), f"v{i}".encode() * 50)
                   for i in range(100)])
rd = dict(LeveldbReader("/tmp/ldb_drive").items())
assert n == 100 and len(rd) == 100 and rd[b"k007"] == b"v7" * 50
# manifest is now a crc'd log with a VersionEdit, not an empty stub
assert os.path.getsize("/tmp/ldb_drive/MANIFEST-000002") > 20
print("leveldb writer round-trip ok (manifest carries VersionEdit)")

# distributed eval: per-class accuracy vector length == batch size (the
# advisor's coincidence case) must NOT be batch-summed
NET2 = NET.replace('pool: STOCHASTIC', 'pool: MAX').replace(
    'num_output: 4', 'num_output: 32') + """
layer { name: "acc" type: "Accuracy" bottom: "fc" bottom: "label"
  top: "accuracy" top: "per_class" include { phase: TEST } }
"""
from sparknet_tpu.parallel import DistributedTrainer, TrainerConfig

net2 = load_net_prototxt(NET2)
sp2 = load_solver_prototxt_with_net('base_lr: 0.01\nmomentum: 0.9\n', net2)
tr = DistributedTrainer(sp2, config=TrainerConfig(strategy="sync", tau=1),
                        seed=0)
lab32 = (np.arange(32) % 32).astype(np.float32)
feed = iter(itertools.cycle([{"data": xs[0], "label": lab32}]))
scores = tr.test(feed, num_steps=2)
assert np.asarray(scores["per_class"]).shape == (32,), scores["per_class"].shape
assert np.ndim(scores["accuracy"]) == 0
# per-worker element-wise accumulation (zipPartitions semantics): each
# per-class entry <= valid worker-batches, never ~batch-sized sums
nb = scores["__test_batches__"]
assert nb == 16.0  # 8 workers x 2 steps
assert float(np.max(np.asarray(scores["per_class"]))) <= nb + 1e-6
print(f"distributed eval ok: per_class shape "
      f"{np.asarray(scores['per_class']).shape}, "
      f"accuracy {float(scores['accuracy']) / nb:.3f} over {nb:.0f} "
      f"worker-batches")

# error probe: WindowData with no sampleable windows raises clearly
from sparknet_tpu.data.db import window_data_feed
from sparknet_tpu.models.dsl import layer as mklayer
from sparknet_tpu.proto.caffe_pb import Phase

with open("/tmp/win_drive.txt", "w") as f:
    f.write("# 0\n/tmp/none.jpg\n3 8 8\n1\n1 0.4 0 0 4 4\n")
wlp = mklayer("w", "WindowData", [], ["data", "label"],
              window_data_param={"source": "/tmp/win_drive.txt",
                                 "batch_size": 2, "fg_threshold": 0.5,
                                 "bg_threshold": 0.3})
try:
    next(window_data_feed(wlp, Phase.TRAIN))
    raise SystemExit("expected ValueError for empty fg+bg pools")
except ValueError as e:
    assert "no sampleable windows" in str(e), e
    print(f"window-data error probe ok: {e}")

# user-defined Python layers: the reference's own pyloss.py runs
# unmodified through the pycaffe-compat shim inside a jitted solver step
import sys

from sparknet_tpu import pycaffe_compat

pycaffe_compat.install()
sys.path.insert(0, "/root/reference/caffe/examples/pycaffe/layers")
LINREG = open("/root/reference/caffe/examples/pycaffe/linreg.prototxt").read()
from sparknet_tpu.graph import Net
from sparknet_tpu.proto.caffe_pb import NetState

lin_net = Net(load_net_prototxt(LINREG), NetState(Phase.TRAIN))
lp_params = lin_net.init(jax.random.PRNGKey(0))
out = lin_net.apply(lp_params, {}, rng=jax.random.PRNGKey(1))
g = jax.grad(lambda p: lin_net.apply(p, {}, rng=jax.random.PRNGKey(1)).loss)(
    lp_params)
gmax = max(float(np.max(np.abs(np.asarray(v))))
           for v in jax.tree_util.tree_leaves(g))
assert np.isfinite(float(out.loss)) and gmax > 0
print(f"python-layer linreg ok: loss {float(out.loss):.4f}, "
      f"max |grad| {gmax:.4f}")

# error probe: unknown python module fails with a clear message
try:
    Net(load_net_prototxt("""
      name: 'bad' input: 'data' input_shape { dim: 2 }
      layer { type: 'Python' name: 'p' bottom: 'data' top: 'p'
        python_param { module: 'nope_xyz' layer: 'L' } }"""),
        NetState(Phase.TRAIN))
    raise SystemExit("expected ImportError")
except ImportError as e:
    assert "nope_xyz" in str(e)
    print("python-layer import error probe ok")

print("DRIVE OK")
