"""``caffe.proto.caffe_pb2`` shim — protobuf-message-style access over
this framework's own wire codecs (reference: the generated caffe_pb2
module; schema caffe/src/caffe/proto/caffe.proto).

pycaffe data scripts build LMDBs and read mean files through message
objects::

    blob = caffe.proto.caffe_pb2.BlobProto()
    blob.ParseFromString(open("mean.binaryproto", "rb").read())
    mean = caffe.io.blobproto_to_array(blob)

    datum = caffe.io.array_to_datum(img, label)
    txn.put(key, datum.SerializeToString())

This module provides that surface without protoc: each class wraps a
``textformat.PMessage`` and serializes through ``wireformat.decode`` /
``encode`` (the same codecs behind .caffemodel/.binaryproto IO, already
round-trip-pinned across the zoo).  Protobuf semantics honored:

- repeated fields present the list API (append/extend/indexing), with
  packed numeric fields (``blob.data``) stored as numpy chunks — one
  chunk per append/extend, concatenated on read, so element-wise fill
  loops stay linear;
- nested singular messages auto-vivify on first access
  (``blob.shape.dim``) but attach to the parent only on first MUTATION —
  reads never set field presence (HasField stays false);
- enum fields read and compare as their INTEGER values
  (``rule.phase == caffe_pb2.TEST``) and accept int or identifier on
  write;
- ``str()`` renders prototxt text.

Cardinality comes from ``_REPEATED`` below — the fields the reference's
python surface actually touches; all other fields behave as singular
(proto2 optional).
"""

from __future__ import annotations

from typing import Any, Callable

import numpy as np

from .proto.textformat import PMessage, serialize
from .proto.wireformat import ENUMS, MESSAGES, decode, encode

_ENUM_REV = {name: {v: k for k, v in table.items()}
             for name, table in ENUMS.items()}

TRAIN = 0
TEST = 1

# (message type, field) pairs that are `repeated` in caffe.proto —
# the python-visible subset (caffe.proto:6-41, 64-100, 102-243, 306-425)
_REPEATED: set[tuple[str, str]] = {
    ("BlobShape", "dim"),
    ("BlobProto", "data"), ("BlobProto", "diff"),
    ("BlobProto", "double_data"), ("BlobProto", "double_diff"),
    ("BlobProtoVector", "blobs"),
    ("Datum", "float_data"),
    ("NetParameter", "input"), ("NetParameter", "input_shape"),
    ("NetParameter", "input_dim"), ("NetParameter", "layer"),
    ("NetParameter", "layers"),
    ("SolverParameter", "test_net"), ("SolverParameter", "test_iter"),
    ("SolverParameter", "test_net_param"),
    ("SolverParameter", "test_state"), ("SolverParameter", "stepvalue"),
    ("LayerParameter", "bottom"), ("LayerParameter", "top"),
    ("LayerParameter", "loss_weight"), ("LayerParameter", "param"),
    ("LayerParameter", "blobs"), ("LayerParameter", "include"),
    ("LayerParameter", "exclude"), ("LayerParameter", "propagate_down"),
    ("NetState", "stage"), ("NetStateRule", "stage"),
    ("NetStateRule", "not_stage"),
}

_PACKED_KINDS = {"pfloat32", "pfloat64", "pint64"}
_PACKED_DTYPES = {"pfloat32": np.float32, "pfloat64": np.float64,
                  "pint64": np.int64}

_SCALAR_DEFAULTS = {
    "int32": 0, "int64": 0, "uint32": 0, "uint64": 0,
    "float": 0.0, "double": 0.0, "bool": False,
    "string": "", "bytes": b"",
}


def _field_table(msg_type: str) -> dict[str, str]:
    """{field name: kind} for a schema message."""
    return {name: kind for name, kind in MESSAGES[msg_type].values()}


def _enum_default(ename: str) -> int:
    table = ENUMS[ename]
    return 0 if 0 in table else min(table)


class _RepeatedScalar:
    """List API over a repeated scalar field.  Packed numeric fields
    (BlobProto.data etc.) are stored as numpy CHUNKS in the underlying
    PMessage — append/extend add one chunk (O(1)); readers (this view,
    the wire encoder, blob_to_array) concatenate."""

    def __init__(self, pmsg: PMessage, name: str, kind: str,
                 on_mutate: Callable[[], None] | None = None):
        self._p, self._name, self._kind = pmsg, name, kind
        self._on_mutate = on_mutate

    def _mutate(self) -> None:
        if self._on_mutate is not None:
            self._on_mutate()

    def _packed(self) -> bool:
        return self._kind in _PACKED_KINDS

    def _flat(self):
        vals = self._p.get_all(self._name)
        if not self._packed():
            return vals
        if not vals:
            return np.zeros((0,), _PACKED_DTYPES[self._kind])
        if len(vals) == 1:  # the common case: no copy per read
            return np.atleast_1d(np.asarray(vals[0]))
        # consolidate storage so element-wise read loops stay linear
        # (semantically neutral: readers concatenate chunks anyway)
        flat = np.concatenate([np.atleast_1d(np.asarray(v)) for v in vals])
        self._p.clear(self._name)
        self._p.set(self._name, flat)
        return flat

    def append(self, v) -> None:
        self._mutate()
        if self._packed():
            self._p.add(self._name,
                        np.atleast_1d(np.asarray(v, _PACKED_DTYPES[self._kind])))
        else:
            self._p.add(self._name, v)

    def extend(self, vs) -> None:
        self._mutate()
        if self._packed():
            arr = np.asarray(list(vs), _PACKED_DTYPES[self._kind])
            if arr.size:
                self._p.add(self._name, arr)
        else:
            for v in vs:
                self._p.add(self._name, v)

    def __len__(self) -> int:
        if self._packed():
            return int(sum(np.size(v) for v in self._p.get_all(self._name)))
        return len(self._p.get_all(self._name))

    def __iter__(self):
        return iter(self._flat())

    def __getitem__(self, i):
        return self._flat()[i]

    def __eq__(self, other) -> bool:
        return list(self._flat()) == list(other)

    def __repr__(self) -> str:
        return repr(list(self._flat()))


class _RepeatedMessage:
    """List API over a repeated message field, protobuf-style:
    ``add()`` appends and returns a new element."""

    def __init__(self, pmsg: PMessage, name: str, msg_type: str,
                 on_mutate: Callable[[], None] | None = None):
        self._p, self._name, self._type = pmsg, name, msg_type
        self._on_mutate = on_mutate

    def _mutate(self) -> None:
        if self._on_mutate is not None:
            self._on_mutate()

    def add(self) -> "Message":
        self._mutate()
        sub = PMessage()
        self._p.add(self._name, sub)
        return _class_for(self._type)(sub)

    def extend(self, msgs) -> None:
        self._mutate()
        for m in msgs:
            # protobuf extend COPIES: later edits to the source must not
            # reach into this container (wire round trip = deep copy)
            self._p.add(self._name,
                        decode(encode(m._p, self._type), self._type))

    def __len__(self) -> int:
        return len(self._p.get_all(self._name))

    def __iter__(self):
        cls = _class_for(self._type)
        return (cls(v) for v in self._p.get_all(self._name))

    def __getitem__(self, i) -> "Message":
        return _class_for(self._type)(self._p.get_all(self._name)[i])


class Message:
    """Base wrapper: one PMessage + the schema table of its type.

    ``_on_mutate`` implements protobuf presence semantics for vivified
    nested messages: reading ``blob.shape`` returns a DETACHED wrapper;
    the first mutation anywhere beneath it attaches it to the parent
    (and so on up the chain), so reads never set HasField."""

    TYPE = ""  # set per subclass

    def __init__(self, pmsg: PMessage | None = None,
                 _on_mutate: Callable[[], None] | None = None):
        object.__setattr__(self, "_p", pmsg if pmsg is not None
                           else PMessage())
        object.__setattr__(self, "_on_mutate", _on_mutate)
        object.__setattr__(self, "_viv", {})  # vivified children by field

    def _mutate(self) -> None:
        cb = self._on_mutate
        if cb is not None:
            object.__setattr__(self, "_on_mutate", None)
            cb()

    # -- protobuf wire API ------------------------------------------------
    def ParseFromString(self, data: bytes) -> None:
        self._mutate()
        decoded = decode(bytes(data), self.TYPE)
        self._p._fields.clear()  # in place: parents keep holding this pmsg
        self._p._fields.update(decoded._fields)

    def SerializeToString(self) -> bytes:
        return encode(self._p, self.TYPE)

    def CopyFrom(self, other: "Message") -> None:
        self.ParseFromString(other.SerializeToString())

    def __str__(self) -> str:  # prototxt text, like protobuf text_format
        return serialize(self._p)

    # -- field access -----------------------------------------------------
    def _kind(self, name: str) -> str:
        table = _field_table(self.TYPE)
        if name not in table:
            raise AttributeError(
                f"{self.TYPE} has no field {name!r} "
                f"(fields: {sorted(table)})")
        return table[name]

    def __getattr__(self, name: str):
        kind = self._kind(name)
        repeated = (self.TYPE, name) in _REPEATED
        if kind.startswith("msg:"):
            sub_type = kind[4:]
            if repeated:
                return _RepeatedMessage(self._p, name, sub_type,
                                        on_mutate=self._mutate)
            sub = self._p.get(name)
            if sub is None:
                # auto-vivify DETACHED (blob.shape.dim.extend(...)):
                # attach to self only when the child first mutates.  The
                # wrapper is cached so repeated reads of the same unset
                # field share ONE child, as protobuf does.
                cached = self._viv.get(name)
                if cached is not None:
                    return cached
                sub_p = PMessage()

                def attach(parent=self, nm=name, sp=sub_p):
                    parent._mutate()
                    parent._p.set(nm, sp)
                child = _class_for(sub_type)(sub_p, _on_mutate=attach)
                self._viv[name] = child
                return child
            return _class_for(sub_type)(sub, _on_mutate=self._mutate)
        if repeated or kind in _PACKED_KINDS:
            return _RepeatedScalar(self._p, name, kind,
                                   on_mutate=self._mutate)
        if kind.startswith("enum:"):
            ename = kind[5:]
            v = self._p.get(name)
            if v is None:
                return _enum_default(ename)
            if isinstance(v, str):  # identifier (text parse / wire decode)
                return _ENUM_REV[ename].get(str(v), _enum_default(ename))
            return int(v)
        return self._p.get(name, _SCALAR_DEFAULTS.get(kind, 0))

    def __setattr__(self, name: str, value: Any) -> None:
        kind = self._kind(name)
        if kind.startswith("msg:") or (self.TYPE, name) in _REPEATED \
                or kind in _PACKED_KINDS:
            raise AttributeError(
                f"{self.TYPE}.{name} is not a singular scalar; use "
                f".extend()/.append()/.add() or CopyFrom")
        self._mutate()
        if kind.startswith("enum:"):
            # store an EnumToken identifier (bare in prototxt text, the
            # convention the text/wire codecs share); accept int or a
            # VALID identifier
            from .proto.textformat import EnumToken
            table = ENUMS[kind[5:]]
            if isinstance(value, str):
                if value not in _ENUM_REV[kind[5:]]:
                    raise ValueError(
                        f"{self.TYPE}.{name}: unknown enum identifier "
                        f"{value!r} (one of {sorted(table.values())})")
            else:
                if int(value) not in table:
                    raise ValueError(
                        f"{self.TYPE}.{name}: no enum value {value!r}")
                value = table[int(value)]
            value = EnumToken(value)
        self._p.set(name, value)

    def HasField(self, name: str) -> bool:
        self._kind(name)
        return self._p.has(name)


_CLASS_CACHE: dict[str, type] = {}


def _class_for(msg_type: str) -> type:
    cls = _CLASS_CACHE.get(msg_type)
    if cls is None:
        cls = type(msg_type, (Message,), {"TYPE": msg_type})
        _CLASS_CACHE[msg_type] = cls
    return cls


def __getattr__(name: str):
    """Every schema message is constructible: caffe_pb2.BlobProto(),
    caffe_pb2.Datum(), caffe_pb2.NetParameter(), ..."""
    if name in MESSAGES:
        return _class_for(name)
    raise AttributeError(name)
