"""Record-shard format + feed tests: the pre-decoded shard format's
write/read round trip and typed corruption, the converter, streaming
ingestion through a VerifyingStore, the tiered ShardCache (RAM + disk
spill), records_feed bit-parity against the serial LMDB decode path
(clean AND under corrupt_record faults), thread-safe LocalStore ranged
reads under a concurrent pool, and device-vs-host augmentation
bit-identity at a shared RNG seed."""

import itertools
import os
import threading

import numpy as np
import pytest

from sparknet_tpu.data import PartitionedDataset
from sparknet_tpu.data.db import array_to_datum, db_feed
from sparknet_tpu.data.integrity import (
    DataCorruptionError, Quarantine, QuarantinePolicy,
)
from sparknet_tpu.data.lmdb_io import write_lmdb
from sparknet_tpu.data.objectstore import LocalStore, VerifyingStore
from sparknet_tpu.data.pipeline import FeedStats, ShardCache
from sparknet_tpu.data.records import (
    RecordShard, ShardSet, ShardWriter, convert_to_shards,
    is_records_source, records_feed, write_shard,
)
from sparknet_tpu.models.dsl import layer
from sparknet_tpu.proto.caffe_pb import Phase
from sparknet_tpu.utils import faults


def _records(n, c=3, h=8, w=8, seed=0):
    rng = np.random.default_rng(seed)
    return [(rng.integers(0, 256, size=(c, h, w)).astype(np.uint8),
             int(rng.integers(0, 10))) for i in range(n)]


def _write_lmdb_of(path, recs):
    write_lmdb(path, [(b"%08d" % i, array_to_datum(img, label))
                      for i, (img, label) in enumerate(recs)])


def _data_layer(source, batch, backend):
    return layer("d", "Data", [], ["data", "label"],
                 data_param={"source": source, "batch_size": batch,
                             "backend": backend},
                 transform_param={"scale": 0.5, "mean_value": [16.0]})


# ---------------------------------------------------------------------------
# Shard format round trip + typed corruption
# ---------------------------------------------------------------------------

def test_shard_roundtrip_bit_exact(tmp_path):
    recs = _records(7)
    path = str(tmp_path / "a.rec")
    assert write_shard(path, recs) == 7
    shard = RecordShard.open(path)
    assert shard.count == 7 and len(shard) == 7
    assert (shard.c, shard.h, shard.w) == (3, 8, 8)
    for i, (img, label) in enumerate(recs):
        got, glabel = shard.read(i)
        assert got.dtype == np.uint8
        assert np.array_equal(img, got)
        assert label == glabel
    # the lazy-partition surface: slicing and iteration
    assert len(shard[2:5]) == 3
    assert np.array_equal(shard[3][0], recs[3][0])
    assert sum(1 for _ in shard) == 7


def test_shard_flipped_byte_is_typed_corruption_with_attribution(tmp_path):
    recs = _records(5)
    path = str(tmp_path / "a.rec")
    write_shard(path, recs)
    shard = RecordShard.open(path)
    pos = shard.offset(3) + 5
    with open(path, "r+b") as f:
        f.seek(pos)
        orig = f.read(1)[0]
        f.seek(pos)
        f.write(bytes([orig ^ 0xFF]))
    shard = RecordShard.open(path)
    with pytest.raises(DataCorruptionError) as ei:
        shard.read(3)
    assert ei.value.key == 3
    assert ei.value.offset == shard.offset(3)
    # neighbours still read clean — corruption is per-record, not per-shard
    assert np.array_equal(shard.read(2)[0], recs[2][0])


def test_shard_writer_rejects_non_uint8(tmp_path):
    w = ShardWriter(str(tmp_path / "a.rec"), 1, 2, 2)
    with pytest.raises(DataCorruptionError):
        w.add(np.full((1, 2, 2), 0.5, np.float32), 0)
    w.add(np.zeros((1, 2, 2), np.uint8), 1)
    assert w.close() == 1


def test_garbage_file_is_typed_corruption(tmp_path):
    path = str(tmp_path / "junk.rec")
    with open(path, "wb") as f:
        f.write(b"not a shard at all, far too short?" * 3)
    with pytest.raises(DataCorruptionError):
        RecordShard.open(path)


# ---------------------------------------------------------------------------
# Converter + ShardSet
# ---------------------------------------------------------------------------

def test_convert_rolls_shards_and_shardset_replays_in_order(tmp_path):
    recs = _records(10, c=2, h=4, w=4)
    stride = 2 * 4 * 4 + 8
    out = convert_to_shards(iter(recs), str(tmp_path / "s"),
                            shard_bytes=3 * stride)
    assert out["records"] == 10 and len(out["shards"]) > 1
    assert out["geometry"] == (2, 4, 4)
    ss = ShardSet.open(str(tmp_path / "s"))
    assert ss.count == 10
    for i, (img, label) in enumerate(recs):
        shard, j = ss.locate(i)
        got, glabel = shard.read(j)
        assert np.array_equal(img, got) and label == glabel
    assert is_records_source(str(tmp_path / "s"))
    assert not is_records_source(str(tmp_path))
    ss.close()


def test_convert_quarantines_bad_records(tmp_path):
    def stream():
        yield np.zeros((1, 2, 2), np.uint8), 0
        yield np.full((1, 2, 2), 0.5, np.float32), 1   # not representable
        yield np.ones((1, 2, 2), np.uint8), 2

    q = Quarantine(QuarantinePolicy(max_fraction=0.5), epoch_size=3)
    out = convert_to_shards(stream(), str(tmp_path / "s"), quarantine=q)
    assert out["records"] == 2
    assert q.report()["total_bad"] == 1


def test_shardset_verifying_store_reads_bit_exact(tmp_path):
    recs = _records(9)
    convert_to_shards(iter(recs), str(tmp_path / "s"),
                      shard_bytes=4 * (3 * 8 * 8 + 8))
    ss = ShardSet.open(str(tmp_path / "s"), verify=True)
    assert all(isinstance(s.store, VerifyingStore) for s in ss.shards)
    for i, (img, label) in enumerate(recs):
        shard, j = ss.locate(i)
        got, glabel = shard.read(j)
        assert np.array_equal(img, got) and label == glabel
    ss.close()


def test_partitioned_dataset_from_records(tmp_path):
    recs = _records(8)
    convert_to_shards(iter(recs), str(tmp_path / "s"),
                      shard_bytes=3 * (3 * 8 * 8 + 8))
    ds = PartitionedDataset.from_records(str(tmp_path / "s"))
    assert sum(len(p) for p in ds.partitions) == 8
    flat = [r for part in ds.partitions for r in part]
    for (img, label), (gimg, glabel) in zip(recs, flat):
        assert np.array_equal(img, gimg) and label == glabel


# ---------------------------------------------------------------------------
# LocalStore under a concurrent ranged-read pool
# ---------------------------------------------------------------------------

def test_local_store_concurrent_ranged_reads():
    import tempfile
    with tempfile.TemporaryDirectory() as root:
        blobs = {}
        for k in range(3):
            payload = bytes((k * 17 + i) % 256 for i in range(4096))
            with open(os.path.join(root, f"b{k}"), "wb") as f:
                f.write(payload)
            blobs[f"b{k}"] = payload
        store = LocalStore(root)
        errs = []

        def reader(tid):
            rng = np.random.default_rng(tid)
            try:
                for _ in range(300):
                    key = f"b{int(rng.integers(3))}"
                    off = int(rng.integers(0, 4000))
                    ln = int(rng.integers(1, 96))
                    got = store.open_range(key, off, ln)
                    if got != blobs[key][off:off + ln]:
                        errs.append((tid, key, off, ln))
            except Exception as e:  # noqa: BLE001 — collected for assert
                errs.append((tid, repr(e)))

        threads = [threading.Thread(target=reader, args=(t,))
                   for t in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs
        store.close()


# ---------------------------------------------------------------------------
# Tiered ShardCache
# ---------------------------------------------------------------------------

def test_shard_cache_tiers_spill_and_promote(tmp_path):
    stats = FeedStats()
    cache = ShardCache(max_shards=2, stats=stats,
                       spill_dir=str(tmp_path / "spill"), max_spill=8)
    payloads = {k: bytes([k]) * 64 for k in range(4)}
    for k in range(4):   # k=2,3 evict k=0,1 to disk
        assert cache.get(k, lambda k=k: payloads[k]) == payloads[k]
    tiers = cache.tier_counts()
    assert tiers["ram_shards"] == 2 and tiers["disk_shards"] == 2
    # RAM hit
    assert cache.get(3, lambda: b"wrong") == payloads[3]
    # disk hit promotes back to RAM (and evicts another to disk)
    assert cache.get(0, lambda: b"wrong") == payloads[0]
    snap = stats.snapshot()
    assert snap["cache_hits"] == 1
    assert snap["cache_disk_hits"] == 1
    assert snap["cache_misses"] == 4
    assert cache.tier_counts()["spills"] >= 3


def test_shard_cache_spill_bound_deletes_oldest(tmp_path):
    cache = ShardCache(max_shards=1, spill_dir=str(tmp_path / "spill"),
                       max_spill=2)
    for k in range(5):
        cache.get(k, lambda k=k: bytes([k]))
    assert cache.tier_counts()["disk_shards"] <= 2
    spilled = os.listdir(str(tmp_path / "spill"))
    assert len(spilled) <= 2


def test_shard_cache_without_spill_dir_just_evicts():
    cache = ShardCache(max_shards=1, spill_dir="")
    cache.get("a", lambda: b"a")
    cache.get("b", lambda: b"b")
    assert cache.tier_counts()["disk_shards"] == 0
    # "a" was dropped, not spilled: re-materializes
    assert cache.get("a", lambda: b"a2") == b"a2"


# ---------------------------------------------------------------------------
# records_feed bit-parity vs the serial LMDB decode reference
# ---------------------------------------------------------------------------

def _pull_batches(feed, n):
    out = []
    for _ in range(n):
        b = next(feed)
        out.append({k: np.array(v) for k, v in b.items()})
    feed.close()
    return out


def _norm_quarantine(rep):
    rep = dict(rep)
    rep.pop("examples", None)
    rep.pop("by_source", None)   # source names differ across backends
    return rep


@pytest.mark.parametrize("corrupt", [False, True])
def test_records_feed_bit_identical_to_serial_lmdb(tmp_path, monkeypatch,
                                                   corrupt):
    if corrupt:
        monkeypatch.setenv("SPARKNET_FAULT", "corrupt_record:0.1")
        monkeypatch.setenv("SPARKNET_FAULT_ATTEMPT", "0")
    n, batch, batches = 48, 8, 13   # 13*8 > 2 epochs: epoch rolls covered
    recs = _records(n, seed=7)
    db = str(tmp_path / "lmdb")
    _write_lmdb_of(db, recs)
    shards = str(tmp_path / "shards")
    convert_to_shards(iter(recs), shards,
                      shard_bytes=20 * (3 * 8 * 8 + 8))

    faults.reset_injector()
    qa = Quarantine(QuarantinePolicy(max_fraction=0.5), epoch_size=n)
    ref = _pull_batches(db_feed(_data_layer(db, batch, "LMDB"),
                                Phase.TRAIN, seed=0, quarantine=qa,
                                workers=0), batches)

    faults.reset_injector()
    qb = Quarantine(QuarantinePolicy(max_fraction=0.5), epoch_size=n)
    stats = FeedStats()
    got = _pull_batches(records_feed(_data_layer(shards, batch, "RECORDS"),
                                     Phase.TRAIN, seed=0, quarantine=qb,
                                     workers=4, stats=stats), batches)

    for a, b in zip(ref, got):
        assert np.array_equal(a["data"], b["data"])
        assert np.array_equal(a["label"], b["label"])
    assert _norm_quarantine(qa.report()) == _norm_quarantine(qb.report())
    if corrupt:
        assert qb.report()["total_bad"] > 0
        assert any(shards in s for s in qb.report()["by_source"])
    snap = stats.snapshot()
    assert snap["read_s"] > 0     # the IO stage books under "read"
    assert snap["decode_s"] >= 0 and snap["batches"] == batches


def test_db_feed_dispatches_records_backend(tmp_path):
    """A Data layer whose source holds ``*.rec`` flows through db_feed
    unchanged — the dispatch point every prototxt already uses."""
    recs = _records(16, seed=2)
    shards = str(tmp_path / "s")
    convert_to_shards(iter(recs), shards)
    faults.reset_injector()
    feed = db_feed(_data_layer(shards, 4, "RECORDS"), Phase.TRAIN, seed=0)
    a = _pull_batches(feed, 2)
    faults.reset_injector()
    b = _pull_batches(records_feed(_data_layer(shards, 4, "RECORDS"),
                                   Phase.TRAIN, seed=0), 2)
    for x, y in zip(a, b):
        assert np.array_equal(x["data"], y["data"])


def test_records_feed_from_verifying_store_with_tiered_cache(tmp_path):
    recs = _records(24, seed=5)
    shards = str(tmp_path / "s")
    convert_to_shards(iter(recs), shards, shard_bytes=8 * (3 * 8 * 8 + 8))
    faults.reset_injector()
    ref = _pull_batches(records_feed(_data_layer(shards, 8, "RECORDS"),
                                     Phase.TRAIN, seed=0, workers=0), 6)
    stats = FeedStats()
    cache = ShardCache(max_shards=1, stats=stats,
                       spill_dir=str(tmp_path / "spill"), max_spill=8)
    faults.reset_injector()
    got = _pull_batches(records_feed(_data_layer(shards, 8, "RECORDS"),
                                     Phase.TRAIN, seed=0, workers=2,
                                     verify=True, cache=cache), 6)
    for a, b in zip(ref, got):
        assert np.array_equal(a["data"], b["data"])
        assert np.array_equal(a["label"], b["label"])
    snap = stats.snapshot()
    assert snap["cache_misses"] >= 3          # one cold miss per shard
    assert snap["cache_hits"] > 0             # within-shard locality
    assert snap["cache_disk_hits"] > 0        # epoch 2 rereads spilled


# ---------------------------------------------------------------------------
# Device-side augmentation bit-parity
# ---------------------------------------------------------------------------

def test_device_and_host_augment_arrays_bit_identical():
    import jax

    from sparknet_tpu.ops.augment import AugmentSpec, augment_batch
    from sparknet_tpu.data.transforms import augment_batch_host
    rng = np.random.default_rng(0)
    imgs = rng.integers(0, 256, size=(6, 3, 12, 12)).astype(np.uint8)
    spec = AugmentSpec(crop=8, mirror=True, mean=16.0, scale=0.25,
                       train=True)
    key = jax.random.PRNGKey(123)
    dev = np.asarray(augment_batch(imgs, key, spec))
    host = augment_batch_host(imgs, key, spec)
    assert dev.shape == (6, 3, 8, 8)
    assert np.array_equal(dev, host)          # bit-identical, not close
    # test phase: deterministic center crop, no mirror
    tspec = spec._replace(train=False)
    dev_t = np.asarray(augment_batch(imgs, key, tspec))
    host_t = augment_batch_host(imgs, key, tspec)
    assert np.array_equal(dev_t, host_t)


def test_solver_device_augment_losses_bit_identical():
    """set_augment(device=True) — augmentation traced into the jitted
    step — must reproduce the host-numpy path's losses bit for bit at
    the same seed (tame LR so losses stay finite and comparable)."""
    import itertools as it

    from sparknet_tpu.models import lenet
    from sparknet_tpu.ops.augment import AugmentSpec
    from sparknet_tpu.proto import load_solver_prototxt_with_net
    from sparknet_tpu.solvers import Solver

    txt = ("base_lr: 0.0005\nmomentum: 0.9\nweight_decay: 0.004\n"
           "lr_policy: \"fixed\"\n")
    spec = AugmentSpec(crop=28, mirror=True, mean=[16.0], scale=1.0 / 255,
                       train=True)
    rng = np.random.default_rng(0)
    host = [{"data": rng.integers(0, 256, size=(8, 1, 32, 32)
                                  ).astype(np.uint8),
             "label": rng.integers(0, 10, size=8).astype(np.float32)}
            for _ in range(4)]

    def run(device):
        sp = load_solver_prototxt_with_net(txt, lenet(16, 16))
        solver = Solver(sp, seed=0)
        solver.set_augment(spec, device=device)
        solver.set_train_data(it.cycle(host))
        return [float(solver.step(1)) for _ in range(5)]

    a, b = run(True), run(False)
    assert all(np.isfinite(a)), a
    assert a == b                              # bit-identical losses


def test_augment_spec_from_transform_param():
    from sparknet_tpu.ops.augment import AugmentSpec, out_shape
    spec = AugmentSpec.from_transform_param(
        {"crop_size": 24, "mirror": True, "mean_value": [10.0, 20.0, 30.0],
         "scale": 0.5}, Phase.TRAIN)
    assert spec.crop == 24 and spec.mirror and spec.train
    assert spec.scale == 0.5
    assert np.asarray(spec.mean).shape == (3, 1, 1)
    assert out_shape((4, 3, 32, 32), spec) == (4, 3, 24, 24)


# ---------------------------------------------------------------------------
# Converter CLI
# ---------------------------------------------------------------------------

def test_convert_cli_lmdb_roundtrip(tmp_path, capsys):
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    import convert as convert_cli
    recs = _records(12, seed=11)
    db = str(tmp_path / "lmdb")
    _write_lmdb_of(db, recs)
    out_dir = str(tmp_path / "shards")
    assert convert_cli.main(["--source", db, "--out", out_dir]) == 0
    ss = ShardSet.open(out_dir)
    assert ss.count == 12
    for i, (img, label) in enumerate(recs):
        shard, j = ss.locate(i)
        got, glabel = shard.read(j)
        assert np.array_equal(img, got) and label == glabel
    ss.close()
