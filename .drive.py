"""Verify drive: prototxt front door -> Solver train -> test -> caffe-format
snapshot/restore -> error paths.  Run: python .drive.py"""
import itertools

import jax
jax.config.update("jax_platforms", "cpu")

import numpy as np

from sparknet_tpu.proto import (
    load_net_prototxt, load_solver_prototxt_with_net, replace_data_layers,
)
from sparknet_tpu.solvers import Solver

NET = """
name: "drive"
layer { name: "conv1" type: "Convolution" bottom: "data" top: "conv1"
  convolution_param { num_output: 8 kernel_size: 5 stride: 2
    weight_filler { type: "xavier" } } }
layer { name: "relu1" type: "ReLU" bottom: "conv1" top: "conv1" }
layer { name: "ip1" type: "InnerProduct" bottom: "conv1" top: "ip1"
  inner_product_param { num_output: 10 weight_filler { type: "xavier" } } }
layer { name: "loss" type: "SoftmaxWithLoss" bottom: "ip1" bottom: "label" top: "loss" }
layer { name: "acc" type: "Accuracy" bottom: "ip1" bottom: "label" top: "acc"
  include { phase: TEST } }
"""

net = replace_data_layers(load_net_prototxt(NET), 32, 32, 1, 28, 28)
solver = Solver(load_solver_prototxt_with_net(
    'base_lr: 0.05\nmomentum: 0.9\n', net), seed=0)

# synthetic separable data: class k has a bright stripe at row k
rng = np.random.default_rng(0)
batches = []
for _ in range(8):
    y = rng.integers(0, 10, size=(32,))
    x = rng.normal(scale=0.3, size=(32, 1, 28, 28)).astype(np.float32)
    for i, k in enumerate(y):
        x[i, :, int(k), :] += 2.0
    batches.append({"data": x, "label": y.astype(np.float32)})

solver.set_train_data(iter(itertools.cycle(batches)))
l0 = solver.step(5)
l1 = solver.step(35)
print(f"loss {l0:.3f} -> {l1:.3f}")
assert l1 < l0 and l1 < 0.5, "loss did not drop"

solver.set_test_data(lambda: iter(batches))
scores = solver.test(8)
acc = scores["acc"] / 8  # accuracy top is already a per-batch mean
print("test accuracy:", acc)
assert acc > 0.9

# NEW: caffe-format snapshot/restore + caffemodel weight interchange
model, state = solver.snapshot_caffe("/tmp/drive_snap")
print("wrote", model, state)
s2 = Solver(load_solver_prototxt_with_net(
    'base_lr: 0.05\nmomentum: 0.9\n', net), seed=1)
s2.load_weights(model)
s2.restore_caffe(state)
assert s2.iter == solver.iter
s2.set_test_data(lambda: iter(batches))
acc2 = s2.test(8)["acc"] / 8
print("restored accuracy:", acc2)
assert abs(acc2 - acc) < 1e-6

# error paths
try:
    solver.load_weights("/tmp/does_not_exist.caffemodel")
    raise AssertionError("expected FileNotFoundError")
except FileNotFoundError:
    pass
from sparknet_tpu.proto.wireformat import decode, WireError
try:
    decode(b"\x0a\xff\xff\xff\xff\xff", "NetParameter")
    raise AssertionError("expected WireError")
except WireError as e:
    print("truncated decode rejected:", e)

# per-blob param sharing: train a weight-shared stack, round-trip caffemodel
SHARED = """
name: "shared"
layer { name: "d" type: "JavaData" top: "a" top: "label"
        java_data_param { shape { dim: 8 dim: 6 } shape { dim: 8 } } }
layer { name: "ip_a" type: "InnerProduct" bottom: "a" top: "fa"
        param { name: "w" lr_mult: 1 }
        inner_product_param { num_output: 6
                              weight_filler { type: "xavier" }
                              bias_filler { type: "constant" value: 1 } } }
layer { name: "ip_b" type: "InnerProduct" bottom: "fa" top: "fb"
        param { name: "w" }
        inner_product_param { num_output: 6
                              weight_filler { type: "xavier" }
                              bias_filler { type: "constant" value: 2 } } }
layer { name: "loss" type: "EuclideanLoss" bottom: "fb" bottom: "a" top: "loss" }
"""
sp = load_solver_prototxt_with_net('base_lr: 0.01\n', load_net_prototxt(SHARED))
ss = Solver(sp, seed=0)
assert len(ss.params["ip_a"]) == 2 and len(ss.params["ip_b"]) == 1


def shared_feed():
    while True:
        yield {"a": rng.normal(size=(8, 6)).astype(np.float32),
               "label": np.zeros(8, np.float32)}


ss.set_train_data(shared_feed())
sl0 = ss.step(1)
sl1 = ss.step(30)
print(f"shared-net loss {sl0:.3f} -> {sl1:.3f}")
assert sl1 < sl0
smodel, sstate = ss.snapshot_caffe("/tmp/drive_shared")
from sparknet_tpu.proto.caffemodel import load_net_binaryproto
saved = {lp.name: lp.blobs for lp in load_net_binaryproto(smodel).layer
         if lp.blobs}
assert len(saved["ip_a"]) == 2 and len(saved["ip_b"]) == 2  # full lists
np.testing.assert_allclose(saved["ip_a"][0], saved["ip_b"][0])
fresh = Solver(sp, seed=3)
fresh.load_weights(smodel)
fresh.restore_caffe(sstate)
for k in ss.params:
    for a, b in zip(ss.params[k], fresh.params[k]):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)
print("shared caffemodel round-trip ok")

# sharing error paths: shape mismatch + lr_mult conflict + Filter taint
from sparknet_tpu.graph import Net
try:
    Net(load_net_prototxt(SHARED.replace(
        'name: "ip_b" type: "InnerProduct" bottom: "fa" top: "fb"\n'
        '        param { name: "w" }',
        'name: "ip_b" type: "InnerProduct" bottom: "fa" top: "fb"\n'
        '        param { name: "w" lr_mult: 5 }')))
    raise AssertionError("expected lr_mult mismatch")
except ValueError as e:
    assert "lr_mult mismatch" in str(e), e
try:
    Net(load_net_prototxt("""
    layer { name: "d" type: "Input" top: "x" top: "s"
            input_param { shape { dim: 4 dim: 3 } shape { dim: 4 } } }
    layer { name: "f" type: "Filter" bottom: "x" bottom: "s" top: "fx" }
    layer { name: "ip" type: "InnerProduct" bottom: "fx" top: "y"
            inner_product_param { num_output: 2 axis: 0
                                  weight_filler { type: "xavier" } } }
    """))
    raise AssertionError("expected taint rejection")
except ValueError as e:
    assert "data-dependent" in str(e), e
print("sharing error paths ok")

# full-size-mean random crop: Caffe subtracts the mean at the crop window
from sparknet_tpu.data.transforms import random_crop_mirror
imgs = rng.normal(size=(4, 3, 12, 10)).astype(np.float32)
mean_img = rng.normal(size=(3, 12, 10)).astype(np.float32)
out = random_crop_mirror(imgs, 8, np.random.default_rng(0), mean=mean_img)
r2 = np.random.default_rng(0)
ys = r2.integers(0, 5, size=4)
xs = r2.integers(0, 3, size=4)
flips = r2.integers(0, 2, size=4)
sub = imgs - mean_img
for i in range(4):
    w = sub[i, :, ys[i]:ys[i] + 8, xs[i]:xs[i] + 8]
    if flips[i]:
        w = w[:, :, ::-1]
    np.testing.assert_allclose(out[i], w, rtol=1e-5)
print("mean-window crop ok")

# standalone DB-backed training through the CLI tool chain:
# convert_imageset -> compute_image_mean -> caffe train -> caffe test
import tempfile
from PIL import Image

from sparknet_tpu.tools import caffe_cli, compute_image_mean, convert_imageset

tooldir = tempfile.mkdtemp()
for i in range(8):
    arr = rng.integers(0, 256, size=(10, 10, 3)).astype(np.uint8)
    Image.fromarray(arr).save(f"{tooldir}/im{i}.png")
with open(f"{tooldir}/list.txt", "w") as f:
    f.write("".join(f"im{i}.png {i % 2}\n" for i in range(8)))
assert convert_imageset.main(
    [tooldir, f"{tooldir}/list.txt", f"{tooldir}/db",
     "--resize_height", "8", "--resize_width", "8"]) == 0
assert compute_image_mean.main(
    [f"{tooldir}/db", f"{tooldir}/mean.binaryproto"]) == 0
with open(f"{tooldir}/net.prototxt", "w") as f:
    f.write(f"""
layer {{ name: "data" type: "Data" top: "data" top: "label"
        transform_param {{ mean_file: "{tooldir}/mean.binaryproto" }}
        data_param {{ source: "{tooldir}/db" batch_size: 4 backend: LMDB }} }}
layer {{ name: "ip" type: "InnerProduct" bottom: "data" top: "ip"
        inner_product_param {{ num_output: 2
                              weight_filler {{ type: "xavier" }} }} }}
layer {{ name: "loss" type: "SoftmaxWithLoss" bottom: "ip" bottom: "label"
        top: "loss" include {{ phase: TRAIN }} }}
layer {{ name: "acc" type: "Accuracy" bottom: "ip" bottom: "label"
        top: "acc" include {{ phase: TEST }} }}
""")
with open(f"{tooldir}/solver.prototxt", "w") as f:
    f.write(f'net: "{tooldir}/net.prototxt"\nbase_lr: 0.01\n'
            f'lr_policy: "fixed"\nmax_iter: 4\ntest_iter: 2\n'
            f'test_interval: 2\nsnapshot_prefix: "{tooldir}/s"\nsnapshot: 1\n')
assert caffe_cli.main(["train", "--solver", f"{tooldir}/solver.prototxt"]) == 0
assert caffe_cli.main(["test", "--model", f"{tooldir}/net.prototxt",
                       "--weights", f"{tooldir}/s_iter_4.caffemodel",
                       "--iterations", "2"]) == 0
print("CLI tool chain ok")

# V0-format net upgrade (padding folding + nested V0LayerParameter)
v0 = load_net_prototxt("""
input: "data"
input_dim: 1 input_dim: 1 input_dim: 8 input_dim: 8
layers { layer { name: "pad" type: "padding" pad: 1 } bottom: "data" top: "p" }
layers { layer { name: "c" type: "conv" num_output: 2 kernelsize: 3
                 weight_filler { type: "xavier" } } bottom: "p" top: "c" }
""")
net_v0 = Net(v0)
assert net_v0.blob_shapes["c"] == (1, 2, 8, 8)  # pad folded into conv
print("V0 upgrade ok")

# pallas LRN kernel (opt-in) matches the XLA path through the layer API
import os as _os

import jax.numpy as jnp

from sparknet_tpu.ops import get_layer_impl as _gli
from sparknet_tpu.models.dsl import layer as _mk_layer

_lrn_lp = _mk_layer("n", "LRN", ["x"], ["y"],
                    lrn_param={"local_size": 5, "alpha": 0.01, "beta": 0.75})
_lx = jnp.asarray(rng.normal(size=(2, 6, 5, 7)).astype(np.float32))
_ref_y = _gli("LRN").apply(_lrn_lp, [], [_lx], True, None)[0]
_os.environ["SPARKNET_PALLAS_LRN"] = "1"
try:
    _pal_y = _gli("LRN").apply(_lrn_lp, [], [_lx], True, None)[0]
finally:
    _os.environ.pop("SPARKNET_PALLAS_LRN")
np.testing.assert_allclose(np.asarray(_pal_y), np.asarray(_ref_y),
                           rtol=1e-5, atol=1e-6)
print("pallas LRN ok")

# streaming ingestion: multi-tar -> lazy index -> bounded decodes
import io
import tarfile as tarmod

from sparknet_tpu.apps.common import RoundFeed
from sparknet_tpu.data.imagenet import load_imagenet

streamdir = tempfile.mkdtemp()
slabels = []
for t in range(2):
    with tarmod.open(f"{streamdir}/part{t}.tar", "w") as tf:
        for i in range(10):
            buf = io.BytesIO()
            Image.fromarray((rng.integers(0, 256, size=(16, 16, 3))
                             ).astype(np.uint8)).save(buf, format="JPEG")
            data = buf.getvalue()
            info = tarmod.TarInfo(f"s_{t}_{i}.JPEG")
            info.size = len(data)
            tf.addfile(info, io.BytesIO(data))
            slabels.append(f"s_{t}_{i}.JPEG {i % 3}")
with open(f"{streamdir}/train.txt", "w") as f:
    f.write("\n".join(slabels))
ds = load_imagenet(f"file://{streamdir}", f"{streamdir}/train.txt",
                   num_partitions=2, size=12)
assert ds.count() == 20
assert all(p.decoded_count == 0 for p in ds.partitions)  # index only
rf = RoundFeed(ds, per_worker_batch=2, batches_per_round=2, seed=0)
r = rf.next_round()
assert r["data"].shape == (2, 4, 3, 12, 12)
touched = sum(p.decoded_count for p in ds.partitions)
assert touched == 8, touched  # only the sampled slices decoded
print("streaming ingestion ok")

print("DRIVE OK")
