"""Drive the PR-5 zero-stall outer loop surfaces end-to-end (CPU mesh).

Run from the repo root: python .drive_r10.py   -> expect "DRIVE OK".

Flows: (1) pipelined trainer (harvest_lag=2 + async ckpt writer) with
ckpt+guard+audit all on is bit-identical to the synchronous loop and
shrinks per-round host stalls; (2) a deferred guard trip (nan_inject
harvested 2 rounds late) rolls back + replays to the fault-free result
bit-for-bit; (3) crash_in_ckpt on the WRITER thread leaves the torn
window (npz durable, no manifest) and resume skips the orphan;
(4) SPARKNET_ASYNC_CKPT=0 escape hatch restores synchronous durability;
(5) bench round_overhead leg emits the stall JSON (BENCH_r06-ready).
"""
import json
import os
import subprocess
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORM_NAME", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np

from sparknet_tpu.models import lenet
from sparknet_tpu.parallel import DistributedTrainer, TrainerConfig, make_mesh
from sparknet_tpu.proto import load_solver_prototxt_with_net
from sparknet_tpu.utils import faults

SP = 'base_lr: 0.005\nmomentum: 0.9\nlr_policy: "fixed"\n'


def make(d, lag, **kw):
    cfg = TrainerConfig(strategy="local_sgd", tau=2, checkpoint_dir=d,
                        checkpoint_keep=4, harvest_lag=lag, **kw)
    return DistributedTrainer(load_solver_prototxt_with_net(SP, lenet(16, 16)),
                              make_mesh(4), cfg, seed=0)


def batch(r):
    rng = np.random.default_rng(100 + r)
    return {"data": rng.normal(size=(2, 16, 1, 28, 28)).astype(np.float32),
            "label": rng.integers(0, 10, size=(2, 16)).astype(np.float32)}


def run(d, lag, rounds=5, **kw):
    tr = make(d, lag, **kw)
    while tr.round < rounds:
        tr.train_round(batch(tr.round))
    losses = tr.drain()
    while tr.round < rounds:      # a drain-trip rewinds; replay
        while tr.round < rounds:
            tr.train_round(batch(tr.round))
        losses = tr.drain()
    return tr, losses


# 1) parity: sync vs pipelined with every safety feature on
with tempfile.TemporaryDirectory() as d1, tempfile.TemporaryDirectory() as d2:
    sync, sl = run(d1, 0, guard_numerics=True, audit_every=1)
    pipe, pl = run(d2, 2, guard_numerics=True, audit_every=1)
    assert [pl[r] for r in range(5)] == [sl[r] for r in range(5)], "losses"
    np.testing.assert_array_equal(np.asarray(sync.params["conv1"][0]),
                                  np.asarray(pipe.params["conv1"][0]))
    s_stall = sum(sync.stall_s.values())
    p_stall = sum(pipe.stall_s.values())
    assert p_stall < s_stall, (s_stall, p_stall)
    print(f"1) parity: 5 rounds bit-identical; host stall "
          f"{s_stall:.3f}s sync -> {p_stall:.3f}s pipelined")

# 2) deferred guard trip bit-for-bit vs fault-free
with tempfile.TemporaryDirectory() as d1, tempfile.TemporaryDirectory() as d2:
    clean, cl = run(d1, 0, guard_numerics=True)
    os.environ["SPARKNET_FAULT"] = "nan_inject@round:2"
    faults.reset_injector()
    tr, losses = run(d2, 2, guard_numerics=True)
    os.environ.pop("SPARKNET_FAULT")
    faults.reset_injector()
    assert tr.guard_trips == 1
    assert [losses[r] for r in range(5)] == [cl[r] for r in range(5)]
    np.testing.assert_array_equal(np.asarray(tr.params["ip2"][0]),
                                  np.asarray(clean.params["ip2"][0]))
    print("2) guard trip harvested 2 rounds late: rollback+replay "
          "bit-for-bit vs fault-free")

# 3) crash_in_ckpt on the writer thread: torn window + resume skips orphan
with tempfile.TemporaryDirectory() as d:
    os.environ["SPARKNET_FAULT"] = "crash_in_ckpt@round:2"
    faults.reset_injector()

    class _Killed(BaseException):
        pass

    inj = faults.get_injector()
    inj._exit = lambda code: (_ for _ in ()).throw(_Killed())
    tr = make(d, 0)
    tr.train_round(batch(0))
    tr.train_round(batch(1))
    try:
        tr.flush_checkpoints()
        raise AssertionError("writer crash did not surface at flush")
    except _Killed:
        pass
    names = set(os.listdir(d))
    assert "ckpt_round_00000002.npz" in names
    assert "manifest_00000002.json" not in names
    os.environ["SPARKNET_FAULT_ATTEMPT"] = "1"
    faults.reset_injector()
    tr2 = make(d, 0)
    assert tr2.resumed is not None and tr2.round == 1
    os.environ.pop("SPARKNET_FAULT")
    os.environ.pop("SPARKNET_FAULT_ATTEMPT")
    faults.reset_injector()
    print("3) crash_in_ckpt on writer thread: npz orphaned, no manifest, "
          "error at flush, resume lands round 1")

# 4) escape hatch: synchronous durability, no writer thread
with tempfile.TemporaryDirectory() as d:
    os.environ["SPARKNET_ASYNC_CKPT"] = "0"
    tr = make(d, 0)
    tr.train_round(batch(0))
    assert tr._ckpt_writer is None
    assert "manifest_00000001.json" in os.listdir(d)
    os.environ.pop("SPARKNET_ASYNC_CKPT")
    print("4) SPARKNET_ASYNC_CKPT=0: durable before return, no writer")

# 5) bench round_overhead leg emits BENCH_r06-ready JSON
env = dict(os.environ, BENCH_PLATFORM="cpu", BENCH_MODEL="lenet",
           BENCH_BATCH="16", BENCH_ITERS="2", BENCH_REPS="1",
           BENCH_WINDOWS="1", BENCH_DTYPE="f32", BENCH_FEED="0",
           BENCH_ROUND_N="2", BENCH_ROUND_TAU="2", BENCH_ROUND_BATCH="16")
env.pop("XLA_FLAGS", None)
out = subprocess.run([sys.executable, "bench.py", "--child"],
                     capture_output=True, timeout=500, env=env,
                     cwd=os.path.dirname(os.path.abspath(__file__)))
assert out.returncode == 0, out.stderr.decode()[-500:]
rec = json.loads(out.stdout.decode().strip().splitlines()[-1])
ro = rec["round_overhead"]
assert {"bare", "sync", "async", "stall_reduction_x"} <= set(ro), ro
assert ro["sync"]["stall_total_s_per_round"] > 0
print(f"5) bench round_overhead: sync stall "
      f"{ro['sync']['stall_total_s_per_round']}s/round -> async "
      f"{ro['async']['stall_total_s_per_round']}s/round "
      f"({ro['stall_reduction_x']}x)")

print("DRIVE OK")
