"""Platform selection workaround.

The installed axon TPU plugin does not honor ``JAX_PLATFORMS``/
``JAX_PLATFORM_NAME`` env vars (and hangs backend init when its tunnel is
unreachable); the ``jax_platforms`` config route is honored.  Call
``honor_platform_env()`` before first backend use so
``JAX_PLATFORMS=cpu python -m sparknet_tpu.apps...`` behaves as documented.
"""

from __future__ import annotations

import os


def honor_platform_env() -> None:
    plats = os.environ.get("JAX_PLATFORMS", "") or os.environ.get(
        "JAX_PLATFORM_NAME", "")
    if plats and "axon" not in plats.lower():
        import jax
        jax.config.update("jax_platforms", plats.lower())
