#!/usr/bin/env python
"""Long-lived inference server over the serving plane.

A thin stdlib-HTTP shell around ``sparknet_tpu.parallel.serving``: the
engine owns dynamic micro-batching, admission control, hot-load/evict,
and health beacons; this process owns the sockets and the JSON wire
format.  Models load (and warm-up compile every serving batch shape)
BEFORE the socket opens — the request path never compiles.

Endpoints:
  POST /v1/classify      {"model": m, "tenant": t, "shape": [C,H,W],
                          "dtype": "float32"|"uint8",
                          "data_b64": <raw little-endian bytes>}
                         (or "data": nested lists) ->
                         {"probs": [...], "top": k, "queue_ms": ...,
                          "infer_ms": ..., "total_ms": ...,
                          "batch_n": n, "padded_to": s}
                         429 on admission rejection (typed reason),
                         404 unknown model, 503 engine dead.
  GET  /healthz          engine liveness + stats (503 when dead).
  GET  /slo              declared-SLO verdict (p99 bound + rejection
                         budget evaluated burn-rate-style over fast and
                         slow windows; see serving.SLOMonitor) —
                         200 while healthy, 503 on breach (breaching
                         windows are dumped through the telemetry
                         FlightRecorder).
  GET  /metrics          Prometheus text exposition of the telemetry
                         registry (queue depth, p50/p99, rejections,
                         request/infer latency histograms; see
                         sparknet_tpu/utils/telemetry.py).
  GET  /v1/models        loaded models with shapes/classes/bytes (and
                         version + channel for registry loads).
  POST /v1/models/load   {"name": m, "weights": path?} — hot-load; or
                         {"model": m, "version": v} — load a published
                         registry version (needs SPARKNET_REGISTRY_DIR)
                         under its versioned key m@v.
  POST /v1/models/evict  {"name": m}.

/v1/classify accepts an optional "version": v — the request pins to
that published version (serving name m@v) bit-identically, bypassing
any canary split the router may be running.  --models accepts versioned
specs ("lenet@mv-abc123") that load from the registry.

Usage:
  python tools/serve.py --models lenet,cifar10_quick --port 8100 \
      --shapes 1,4,16,64 --max-delay-ms 5 --queue-depth 256 \
      --quota acme=200 --hbm-budget-mb 2048 --dtype bf16

With SPARKNET_HEARTBEAT_DIR set (e.g. by the fleet launcher), the
engine publishes serving beacons (queue depth, in-flight, p50/p99) that
``tools/fleet.py status`` folds into the fleet table.

``--fleet N`` switches to fleet mode (WALKTHROUGH §6.14): N replica
subprocesses per model, each THIS program in single mode on an
ephemeral port, placed as ``JobSpec(kind="serve")`` tenants by the
fleet scheduler; the front serves the request router (consistent-hash
home, depth spill, typed failover, drain-before-stop) plus fleet
observability:
  GET  /healthz            router table + device budget (503 when no
                           live replica remains).
  GET  /slo[?model=m]      per-replica SLO verdicts, 200 only while
                           every (scoped) replica's declared SLO holds.
  GET  /fleet              the scheduler's status document.
  POST /v1/scale           {"model": m, "replicas": n} operator resize
                           (scale-down drains; lossless).
``--endpoint-file`` (single mode) publishes {url, pid, models}
atomically once the socket is up — the channel fleet-launched replicas
use to hand their endpoint to the router.
"""

from __future__ import annotations

import argparse
import base64
import json
import os
import signal
import sys
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def decode_array(payload: dict) -> np.ndarray:
    """The wire formats the server accepts: raw-bytes b64 (fast path,
    what RemoteClassifier sends) or nested lists (curl-friendly)."""
    if "data_b64" in payload:
        dtype = np.dtype(payload.get("dtype", "float32"))
        arr = np.frombuffer(
            base64.b64decode(payload["data_b64"]), dtype=dtype)
        shape = payload.get("shape")
        if shape:
            arr = arr.reshape([int(d) for d in shape])
        return arr.astype(np.float32)
    if "data" in payload:
        return np.asarray(payload["data"], np.float32)
    raise ValueError("payload needs data_b64 (+shape/dtype) or data")


def make_handler(engine, house):
    from sparknet_tpu.parallel.serving import (
        EngineDead, OverBudget, Overloaded, ServingError, UnknownModel,
    )

    class Handler(BaseHTTPRequestHandler):
        # quiet access log: the load generator would drown stderr
        def log_message(self, fmt, *args):  # noqa: N802
            pass

        def _send(self, code: int, obj: dict) -> None:
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _read_json(self) -> dict:
            n = int(self.headers.get("Content-Length", "0") or 0)
            if not n:
                return {}
            return json.loads(self.rfile.read(n).decode())

        def do_GET(self):  # noqa: N802
            if self.path == "/healthz":
                st = engine.stats()
                self._send(200 if st["alive"] else 503, st)
            elif self.path == "/slo":
                st = engine.slo.evaluate()
                self._send(200 if st["state"] == "ok" else 503, st)
            elif self.path == "/metrics":
                from sparknet_tpu.utils import telemetry
                body = telemetry.get_registry().render().encode()
                self.send_response(200)
                self.send_header(
                    "Content-Type",
                    "text/plain; version=0.0.4; charset=utf-8")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            elif self.path == "/v1/models":
                models = house.loaded()
                reg = None
                if any(info.get("version") for info in models.values()):
                    from sparknet_tpu.parallel.registry import (
                        active_registry,
                    )
                    reg = active_registry()
                if reg is not None:
                    for info in models.values():
                        if info.get("version"):
                            info["channel"] = reg.channel_of(
                                info["name"].partition("@")[0],
                                info["version"])
                self._send(200, {"models": models})
            else:
                self._send(404, {"error": f"no route {self.path!r}"})

        def do_POST(self):  # noqa: N802
            try:
                payload = self._read_json()
            except (ValueError, json.JSONDecodeError) as e:
                return self._send(400, {"error": f"bad JSON: {e}"})
            try:
                if self.path == "/v1/classify":
                    model = payload.get("model", "")
                    if payload.get("version"):
                        # version pin: the request hits exactly that
                        # published version, rollout splits never apply
                        model = f"{model}@{payload['version']}"
                    res = engine.classify(
                        model, decode_array(payload),
                        tenant=str(payload.get("tenant", "anon")),
                        timeout=float(payload.get("timeout_s", 30.0)))
                    return self._send(200, {
                        "model": res.model, "request_id": res.request_id,
                        "probs": [float(p) for p in res.probs],
                        "top": res.top, "queue_ms": res.queue_ms,
                        "infer_ms": res.infer_ms, "total_ms": res.total_ms,
                        "batch_n": res.batch_n, "padded_to": res.padded_to})
                if self.path == "/v1/models/load":
                    if payload.get("version"):
                        # registry path: {"model": m, "version": v} loads
                        # the published bundle under its versioned key
                        lm = house.load_version(
                            payload.get("model") or payload.get("name"),
                            payload["version"],
                            force=(True if payload.get("force")
                                   else None))
                        return self._send(200, {"loaded": lm.info()})
                    lm = house.load(payload["name"],
                                    weights=payload.get("weights"),
                                    force=(True if payload.get("force")
                                           else None))
                    return self._send(200, {"loaded": lm.info()})
                if self.path == "/v1/models/evict":
                    gone = house.evict(payload["name"])
                    return self._send(200 if gone else 404,
                                      {"evicted": bool(gone),
                                       "name": payload["name"]})
                return self._send(404, {"error": f"no route {self.path!r}"})
            except Overloaded as e:
                self._send(429, {"error": str(e), "reason": e.reason})
            except OverBudget as e:
                # 507 Insufficient Storage: the model alone cannot fit
                # the HBM budget — retry with {"force": true} to admit
                self._send(507, {"error": str(e), "reason": "over_budget",
                                 "param_mb": round(e.param_mb, 1),
                                 "budget_mb": e.budget_mb})
            except UnknownModel as e:
                self._send(404, {"error": str(e), "reason": "unknown_model"})
            except EngineDead as e:
                self._send(503, {"error": str(e), "reason": "engine_dead"})
            except (ServingError, TimeoutError, KeyError, ValueError) as e:
                self._send(400, {"error": str(e)})

    return Handler


def make_fleet_handler(fleet):
    """The front endpoint of ``--fleet`` mode: same wire format as a
    single replica, but /v1/classify routes through the request router
    (consistent-hash home + spill + failover) and the observability
    routes aggregate the whole fleet."""
    from sparknet_tpu.classify import http_json
    from sparknet_tpu.parallel.serving import (
        EngineDead, Overloaded, ServingError, UnknownModel,
    )

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):  # noqa: N802
            pass

        def _send(self, code: int, obj: dict) -> None:
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _read_json(self) -> dict:
            n = int(self.headers.get("Content-Length", "0") or 0)
            return json.loads(self.rfile.read(n).decode()) if n else {}

        def do_GET(self):  # noqa: N802
            from urllib.parse import parse_qs, urlparse
            u = urlparse(self.path)
            if u.path == "/healthz":
                st = fleet.router.stats()
                live = [r for r, v in st["replicas"].items()
                        if v["state"] == "ACTIVE"]
                self._send(200 if live else 503, {
                    "alive": bool(live), "router": st,
                    "devices": {
                        "total": fleet.sched.allocator.total,
                        "free": fleet.sched.allocator.free_count}})
            elif u.path == "/slo":
                # per-replica verdicts, scoped to ?model= when given —
                # tenant isolation is judged per model, not fleet-wide
                model = (parse_qs(u.query).get("model") or [None])[0]
                docs, ok = {}, True
                for rid in fleet.router.replica_ids(model=model,
                                                    live_only=False):
                    url = fleet._endpoints.get(rid)
                    if not url:
                        continue
                    try:
                        docs[rid] = http_json(f"{url}/slo", timeout=10.0)
                    except RuntimeError as e:
                        if "HTTP 503" in str(e):
                            docs[rid] = {"state": "breach",
                                         "error": str(e)}
                        else:
                            docs[rid] = {"state": "unknown",
                                         "error": str(e)}
                    except OSError as e:
                        docs[rid] = {"state": "unknown",
                                     "error": repr(e)}
                    ok = ok and docs[rid].get("state") == "ok"
                self._send(200 if (ok and docs) else 503,
                           {"state": "ok" if (ok and docs) else "breach",
                            "model": model, "replicas": docs})
            elif u.path == "/fleet":
                self._send(200, fleet.sched.status())
            elif u.path == "/v1/models":
                models: dict = {}
                for r in fleet.router.stats()["replicas"].values():
                    for m in r["models"]:
                        models.setdefault(m, {"replicas": 0})
                        models[m]["replicas"] += 1
                reg = None
                if any("@" in m for m in models):
                    from sparknet_tpu.parallel.registry import (
                        active_registry,
                    )
                    reg = active_registry()
                for m, info in models.items():
                    base, sep, ver = m.partition("@")
                    if sep:
                        info["version"] = ver
                        if reg is not None:
                            info["channel"] = reg.channel_of(base, ver)
                self._send(200, {"models": models})
            elif u.path == "/metrics":
                from sparknet_tpu.utils import telemetry
                body = telemetry.get_registry().render().encode()
                self.send_response(200)
                self.send_header(
                    "Content-Type",
                    "text/plain; version=0.0.4; charset=utf-8")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            else:
                self._send(404, {"error": f"no route {self.path!r}"})

        def do_POST(self):  # noqa: N802
            try:
                payload = self._read_json()
            except (ValueError, json.JSONDecodeError) as e:
                return self._send(400, {"error": f"bad JSON: {e}"})
            try:
                if self.path == "/v1/classify":
                    res = fleet.router.classify(
                        payload.get("model", ""), decode_array(payload),
                        tenant=str(payload.get("tenant", "anon")),
                        timeout=float(payload.get("timeout_s", 30.0)),
                        version=payload.get("version") or None)
                    return self._send(200, {
                        "model": res.model, "request_id": res.request_id,
                        "probs": [float(p) for p in res.probs],
                        "top": res.top, "queue_ms": res.queue_ms,
                        "infer_ms": res.infer_ms, "total_ms": res.total_ms,
                        "batch_n": res.batch_n, "padded_to": res.padded_to})
                if self.path == "/v1/scale":
                    model = payload["model"]
                    want = int(payload["replicas"])
                    have = fleet.active_replica_jobs(model)
                    while len(have) < want and fleet.scale_up(model):
                        have = fleet.active_replica_jobs(model)
                    while len(have) > want \
                            and fleet.scale_down(model) is not None:
                        have = fleet.active_replica_jobs(model)
                    return self._send(200, {"model": model,
                                            "replicas": len(have)})
                return self._send(404, {"error": f"no route {self.path!r}"})
            except Overloaded as e:
                self._send(429, {"error": str(e), "reason": e.reason})
            except UnknownModel as e:
                self._send(404, {"error": str(e),
                                 "reason": "unknown_model"})
            except EngineDead as e:
                self._send(503, {"error": str(e), "reason": "engine_dead"})
            except (ServingError, TimeoutError, KeyError, ValueError) as e:
                self._send(400, {"error": str(e)})

    return Handler


def parse_models(specs) -> list[tuple[str, str | None]]:
    """``lenet,caffenet=weights.caffemodel`` -> [(name, weights|None)]."""
    out = []
    for chunk in specs or ():
        for item in chunk.split(","):
            item = item.strip()
            if not item:
                continue
            name, _, weights = item.partition("=")
            out.append((name, weights or None))
    return out


def parse_quotas(pairs) -> dict[str, float]:
    quotas: dict[str, float] = {}
    for p in pairs or ():
        name, _, val = p.partition("=")
        if not name or not val:
            raise SystemExit(f"bad --quota {p!r} (want tenant=qps)")
        try:
            quotas[name] = float(val)
        except ValueError:
            raise SystemExit(f"bad --quota {p!r}: {val!r} is not a number")
    return quotas


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="micro-batched inference server")
    ap.add_argument("--models", action="append", required=True,
                    metavar="NAME[=WEIGHTS]",
                    help="zoo models to pre-load (comma-separable, "
                         "repeatable); optional =path to .caffemodel/npz "
                         "weights")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8100,
                    help="0 picks an ephemeral port (printed on ready)")
    ap.add_argument("--shapes", default=None,
                    help="compiled batch shapes, e.g. 1,4,16,64 "
                         "(default SPARKNET_SERVE_SHAPES)")
    ap.add_argument("--max-delay-ms", type=float, default=None,
                    help="micro-batch coalesce deadline "
                         "(default SPARKNET_SERVE_MAX_DELAY_MS)")
    ap.add_argument("--queue-depth", type=int, default=None,
                    help="admission bound (default SPARKNET_SERVE_QUEUE)")
    ap.add_argument("--hbm-budget-mb", type=float, default=None,
                    help="model-house budget (default SPARKNET_SERVE_HBM_MB)")
    ap.add_argument("--dtype", choices=("bf16", "f32"), default=None,
                    help="compute dtype (default SPARKNET_SERVE_DTYPE)")
    ap.add_argument("--quota", action="append", default=[],
                    metavar="TENANT=QPS",
                    help="per-tenant QPS cap (repeatable; '*' caps "
                         "tenants without an explicit entry)")
    ap.add_argument("--slo-p99-ms", type=float, default=None,
                    help="declared p99 latency bound for GET /slo "
                         "(default SPARKNET_SLO_P99_MS; unset = latency "
                         "SLO undeclared)")
    ap.add_argument("--slo-reject-budget", type=float, default=None,
                    help="rejection-rate error budget as a fraction "
                         "(default SPARKNET_SLO_REJECT_BUDGET, 0.02)")
    ap.add_argument("--slo-window-s", type=float, default=None,
                    help="slow burn window seconds "
                         "(default SPARKNET_SLO_WINDOW_S, 60)")
    ap.add_argument("--endpoint-file", default=None,
                    help="publish {url, pid, models} here (atomic) once "
                         "the socket is up — how fleet-launched replicas "
                         "hand their ephemeral endpoint to the router")
    ap.add_argument("--fleet", type=int, default=0, metavar="N",
                    help="fleet mode: run N serving replicas per model "
                         "as fleet tenants behind a request router + "
                         "autoscaler, and serve the router at --port")
    ap.add_argument("--fleet-devices", type=int, default=None,
                    help="device budget for the replica fleet "
                         "(default: N x models)")
    ap.add_argument("--fleet-workdir", default=None,
                    help="fleet state dir (journal, replica job dirs, "
                         "autoscale.json/router.json; default: a temp "
                         "dir)")
    ap.add_argument("--fleet-tenant", default="serving",
                    help="tenant the replica jobs bill against")
    ap.add_argument("--fleet-priority", type=int, default=0,
                    help="priority of the replica jobs (training jobs "
                         "above it can preempt them — through drain)")
    args = ap.parse_args(argv)

    from sparknet_tpu.parallel.serving import (
        InferenceEngine, ModelHouse, ServeConfig,
    )

    base = ServeConfig()   # env defaults
    cfg = ServeConfig(
        batch_shapes=(tuple(int(s) for s in args.shapes.split(","))
                      if args.shapes else base.batch_shapes),
        max_delay_ms=(args.max_delay_ms if args.max_delay_ms is not None
                      else base.max_delay_ms),
        max_queue=(args.queue_depth if args.queue_depth is not None
                   else base.max_queue),
        hbm_budget_mb=(args.hbm_budget_mb if args.hbm_budget_mb is not None
                       else base.hbm_budget_mb),
        dtype=args.dtype or base.dtype,
        tenant_qps=parse_quotas(args.quota),
        slo_p99_ms=(args.slo_p99_ms if args.slo_p99_ms is not None
                    else base.slo_p99_ms),
        slo_reject_budget=(args.slo_reject_budget
                           if args.slo_reject_budget is not None
                           else base.slo_reject_budget),
        slo_window_s=(args.slo_window_s if args.slo_window_s is not None
                      else base.slo_window_s))

    # signal handlers FIRST: a replica preempted/shut down while still
    # warm-up-compiling must exit cleanly (checkpoint-and-stop
    # semantics), not die to the default SIGTERM disposition
    stop = threading.Event()

    def on_signal(signum, frame):
        stop.set()

    signal.signal(signal.SIGTERM, on_signal)
    signal.signal(signal.SIGINT, on_signal)

    if args.fleet:
        return fleet_main(args, cfg, stop)

    house = ModelHouse(cfg)
    declared_p99: list[float] = []
    for name, weights in parse_models(args.models):
        if stop.is_set():
            # preempted while warming up: checkpoint-and-stop semantics
            # (the fleet requeues us; nothing was serving yet)
            print("[serve] stopped during warm-up", file=sys.stderr,
                  flush=True)
            return 0
        if "@" in name:
            # registry spec ("lenet@mv-abc123"): the published bundle
            # resolves the weights; =path would be a second truth
            if weights:
                raise SystemExit(f"--models {name}={weights}: a "
                                 f"versioned spec takes no =weights "
                                 f"(the registry bundle IS the weights)")
            base, version = name.split("@", 1)
            lm = house.load_version(base, version)
            slo = getattr(lm, "declared_slo", None)
            if isinstance(slo, dict) and slo.get("p99_ms"):
                declared_p99.append(float(slo["p99_ms"]))
        else:
            lm = house.load(name, weights=weights)
        print(f"[serve] loaded {name}: in={lm.in_shape} "
              f"classes={lm.classes} {lm.param_bytes / 2**20:.1f} MB, "
              f"compiled {len(cfg.batch_shapes)} shapes in "
              f"{lm.compile_s:.1f}s", file=sys.stderr, flush=True)

    engine = InferenceEngine(house, cfg)
    if cfg.slo_p99_ms is None and declared_p99:
        # adopt the strictest manifest-declared p99 across versioned
        # loads — a version that declared its SLO is judged against it
        engine.slo.p99_ms = min(declared_p99)
    httpd = ThreadingHTTPServer((args.host, args.port),
                                make_handler(engine, house))
    httpd.daemon_threads = True
    host, port = httpd.server_address[:2]

    server_thread = threading.Thread(target=httpd.serve_forever,
                                     daemon=True)
    server_thread.start()
    # the ready line: tests and operators key off this exact prefix
    print(f"serving on http://{host}:{port} "
          f"(models: {', '.join(sorted(house.loaded()))})", flush=True)
    if args.endpoint_file:
        write_endpoint(args.endpoint_file, host, port,
                       sorted(house.loaded()))
    stop.wait()
    print("[serve] shutting down", file=sys.stderr, flush=True)
    httpd.shutdown()
    engine.stop()
    return 0


def write_endpoint(path: str, host, port: int, models: list) -> None:
    """Atomic endpoint publication (tmp + rename — a reader never sees
    a torn doc, the heartbeat-file contract)."""
    doc = {"url": f"http://{host}:{port}", "pid": os.getpid(),
           "models": models}
    tmp = f"{path}.tmp.{os.getpid()}"
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, path)


def serve_env_from(cfg) -> dict:
    """The ServeConfig as env knobs — how fleet replicas inherit the
    front's serving configuration with no per-replica CLI."""
    env = {
        "SPARKNET_SERVE_SHAPES": ",".join(str(s)
                                          for s in cfg.batch_shapes),
        "SPARKNET_SERVE_MAX_DELAY_MS": str(cfg.max_delay_ms),
        "SPARKNET_SERVE_QUEUE": str(cfg.max_queue),
        "SPARKNET_SERVE_INFLIGHT": str(cfg.inflight_batches),
        "SPARKNET_SERVE_HBM_MB": str(cfg.hbm_budget_mb),
        "SPARKNET_SERVE_DTYPE": cfg.dtype,
        "SPARKNET_SLO_REJECT_BUDGET": str(cfg.slo_reject_budget),
        "SPARKNET_SLO_WINDOW_S": str(cfg.slo_window_s),
        "SPARKNET_SLO_FAST_S": str(cfg.slo_fast_window_s),
    }
    if cfg.tenant_qps:
        env["SPARKNET_SERVE_QUOTAS"] = ",".join(
            f"{t}={q:g}" for t, q in sorted(cfg.tenant_qps.items()))
    if cfg.slo_p99_ms is not None:
        env["SPARKNET_SLO_P99_MS"] = str(cfg.slo_p99_ms)
    return env


def fleet_main(args, cfg, stop) -> int:
    """``--fleet N``: N replicas per model as serve-kind fleet tenants,
    the request router at the front, the autoscaler closing the SLO
    loop.  The front process owns no engine — replicas are subprocesses
    the FleetScheduler placed, each a full single-model server."""
    import tempfile

    from sparknet_tpu.parallel.autoscale import Autoscaler, fleet_stats_fn
    from sparknet_tpu.parallel.router import ServingFleet

    model_specs = [name if not weights else f"{name}={weights}"
                   for name, weights in parse_models(args.models)]
    if not model_specs:
        raise SystemExit("--fleet needs at least one --models entry")
    devices = args.fleet_devices or args.fleet * len(model_specs)
    workdir = args.fleet_workdir or tempfile.mkdtemp(
        prefix="sparknet-servefleet-")
    fleet = ServingFleet(
        workdir, devices, tenant=args.fleet_tenant,
        priority=args.fleet_priority, serve_env=serve_env_from(cfg))
    autoscaler = Autoscaler(
        fleet_stats_fn(fleet), fleet.scale_up, fleet.scale_down,
        state_path=os.path.join(workdir, "autoscale.json"))
    fleet.attach_autoscaler(autoscaler)
    for spec in model_specs:
        fleet.ensure(spec, args.fleet)
    fleet.run_background()
    try:
        for spec in model_specs:
            fleet.wait_ready(spec, args.fleet, timeout_s=300.0)
    except TimeoutError as e:
        print(f"[serve] fleet never became ready: {e}", file=sys.stderr,
              flush=True)
        fleet.stop()
        return 1

    httpd = ThreadingHTTPServer((args.host, args.port),
                                make_fleet_handler(fleet))
    httpd.daemon_threads = True
    host, port = httpd.server_address[:2]
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    print(f"serving on http://{host}:{port} "
          f"(fleet: {args.fleet} replica(s) x "
          f"{', '.join(model_specs)}; workdir {workdir})", flush=True)
    if args.endpoint_file:
        write_endpoint(args.endpoint_file, host, port, model_specs)
    stop.wait()
    print("[serve] shutting the fleet down", file=sys.stderr, flush=True)
    httpd.shutdown()
    fleet.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
