from .textformat import PMessage, parse, serialize, ParseError
from .caffe_pb import (
    BlobShape,
    FillerParameter,
    LayerParameter,
    NetParameter,
    NetState,
    NetStateRule,
    SolverParameter,
    Phase,
    load_net_prototxt,
    load_solver_prototxt,
    load_solver_prototxt_with_net,
    replace_data_layers,
)
