"""Lowering autotuner tests (graph/tuner.py library, tools/tune.py CLI
surface, ops/vision.py resolve_lowering seams): key grammar, the
measure-key contract (typed skips, numerics disqualification, winner
eligibility), the versioned table's FusionPlan-style refusal of drifted
files, SPARKNET_TUNE resolution modes, table-pinned lowerings through
the production layer paths (the pin path that replaced the retired
PR-12 env shims), staleness detection, perf-ledger ingestion, and — against the committed CPU
table — off-vs-tuned forward bit-parity across the zoo shapes."""

import json
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sparknet_tpu.graph import tuner
from sparknet_tpu.models.dsl import (
    convolution_layer,
    inner_product_layer,
    layer,
    lrn_layer,
    net_param,
    pooling_layer,
    relu_layer,
    softmax_with_loss_layer,
)
from sparknet_tpu.ops.registry import get_layer_impl
from sparknet_tpu.proto import NetState, Phase

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

pytestmark = pytest.mark.tune

# fast timing knobs: these tests check contracts, not numbers
FAST = dict(reps=3, target_s=0.005, warmup=1)
TINY_LRN = tuner.TuneKey("lrn", (2, 8, 6, 6), "f32", tuner.lrn_extra(5))


@pytest.fixture(autouse=True)
def _clean_tuner_state(monkeypatch):
    monkeypatch.delenv("SPARKNET_TUNE", raising=False)
    tuner._clear_caches()
    yield
    tuner.clear_extra_candidates()
    tuner._clear_caches()


_MEASURED = {}


def _measured(key=TINY_LRN):
    """One shared tiny measurement per key — measure_key is seconds, not
    milliseconds, so contract tests reuse it."""
    s = str(key)
    if s not in _MEASURED:
        _MEASURED[s] = tuner.measure_key(key, **FAST)
    return _MEASURED[s]


# ---------------------------------------------------------------------------
# key grammar + registry surface
# ---------------------------------------------------------------------------

def test_key_string_roundtrip():
    keys = [
        TINY_LRN,
        tuner.TuneKey("conv", (4, 3, 9, 9), "bf16",
                      tuner.conv_extra(3, 3, 1, 1, 1, 1, 1, 1, 8, 2)),
        tuner.TuneKey("pool", (4, 8, 9, 9), "f32",
                      tuner.pool_extra(3, 3, 2, 2, 0, 0)),
        tuner.TuneKey("lrn_epilogue", (4, 8, 9, 9), "f32",
                      tuner.epilogue_extra(5, True)),
    ]
    for k in keys:
        back = tuner.parse_key(str(k))
        assert back == k, (str(k), str(back))


def test_registry_covers_the_env_pinned_families():
    ops = tuner.ops()
    # every lowering family PR 1-10 pinned by env knob or heuristic is
    # now a measured candidate set
    assert {"lrn", "conv", "pool", "lrn_epilogue"} <= set(ops)
    lrn = {c.name for c in tuner.candidates_for("lrn")}
    assert {"reduce_window", "cumsum", "closed_vjp", "pallas"} <= lrn
    conv = {c.name for c in tuner.candidates_for("conv")}
    assert {"native", "s2d", "im2col"} <= conv
    pool = {c.name for c in tuner.candidates_for("pool")}
    assert {"reduce_window", "patches_max"} <= pool


# ---------------------------------------------------------------------------
# measure_key contract
# ---------------------------------------------------------------------------

def test_measure_key_shape_and_typed_pallas_skip():
    e = _measured()
    assert e["key"] == str(TINY_LRN) and e["op"] == "lrn"
    assert e["winner"] in e["timings"]
    assert e["default"] == "reduce_window"  # CPU default heuristic
    win = e["timings"][e["winner"]]
    assert "ms" in win and "disqualified" not in win
    assert "ineligible" not in win
    assert e["flip"] == (e["winner"] != e["default"])
    if jax.default_backend() != "tpu":
        # the Pallas candidate must be a TYPED skip, not an abort
        assert e["timings"]["pallas"]["skipped"].startswith("requires tpu")
    assert 0.05 <= e["noise_band"]


def test_numerics_failing_candidate_is_disqualified_never_wins():
    def bad_factory(key, prob):
        base = prob.fns["reduce_window"]
        return lambda x: base(x) * 1.001  # ~1e-3 off, declared exact

    tuner.register_candidate(
        "lrn", tuner.Candidate("planted_bad", exact=True), bad_factory)
    e = tuner.measure_key(TINY_LRN, **FAST)
    rec = e["timings"]["planted_bad"]
    assert "disqualified" in rec and "ms" in rec  # timed for the record
    assert e["winner"] != "planted_bad"
    # ...and a table built from this measurement can never persist it
    table = tuner.TuningTable(tuner._backend(), [e])
    assert table.winner(str(TINY_LRN)) != "planted_bad"


def test_raising_candidate_records_typed_skip_and_run_continues():
    def boom_factory(key, prob):
        def boom(x):
            raise RuntimeError("boom: no such kernel")
        return boom

    tuner.register_candidate(
        "lrn", tuner.Candidate("planted_raise", exact=False), boom_factory)
    e = tuner.measure_key(TINY_LRN, **FAST)
    assert e["timings"]["planted_raise"]["skipped"].startswith(
        "RuntimeError: boom")
    assert e["winner"] != "planted_raise"  # run continued and picked one


def test_inexact_candidate_is_ineligible_unless_allowed():
    def off_factory(key, prob):
        base = prob.fns["reduce_window"]
        # within the declared rtol but not bit-identical
        return lambda x: base(x) * (1.0 + 1e-7)

    cand = tuner.Candidate("planted_near", exact=False, rtol=1e-5,
                           grad_rtol=1e-3)
    tuner.register_candidate("lrn", cand, off_factory)
    e = tuner.measure_key(TINY_LRN, **FAST)
    rec = e["timings"]["planted_near"]
    assert "disqualified" not in rec and "ineligible" in rec
    assert e["winner"] != "planted_near"


# ---------------------------------------------------------------------------
# the versioned table: FusionPlan-style refusal discipline
# ---------------------------------------------------------------------------

def _tiny_table():
    # deep copy: table docs get mutated by the drift tests below, and
    # the measurement is cached across tests
    return tuner.TuningTable(tuner._backend(),
                             [json.loads(json.dumps(_measured()))])


def test_table_roundtrip(tmp_path):
    t = _tiny_table()
    p = str(tmp_path / "tuning.json")
    t.save(p)
    back = tuner.TuningTable.load(p)
    assert back.table_id() == t.table_id()
    assert back.winner(str(TINY_LRN)) == t.winner(str(TINY_LRN))
    assert back.winner("lrn/9x9x9x9/f32/s5") is None  # miss


@pytest.mark.parametrize("mutate, hint", [
    (lambda d: d.update(kind="op_table"), "not a tuning table"),
    (lambda d: d.update(version="one"), "no integer schema version"),
    (lambda d: d.update(version=tuner.TABLE_VERSION + 1), "newer"),
    (lambda d: d.pop("backend"), "refusing a drifted file"),
    (lambda d: d["entries"][0].pop("winner"), "refusing a drifted file"),
])
def test_drifted_table_refused_loudly(tmp_path, mutate, hint):
    doc = _tiny_table().to_doc()
    mutate(doc)
    p = str(tmp_path / "tuning.json")
    with open(p, "w") as f:
        json.dump(doc, f)
    with pytest.raises(ValueError, match=hint):
        tuner.TuningTable.load(p)


def test_unparseable_table_refused(tmp_path):
    p = str(tmp_path / "tuning.json")
    with open(p, "w") as f:
        f.write("{not json")
    with pytest.raises(ValueError, match="unparseable"):
        tuner.TuningTable.load(p)


def test_cross_backend_table_refused(tmp_path, monkeypatch):
    doc = _tiny_table().to_doc()
    doc["backend"] = "tpu" if tuner._backend() != "tpu" else "cpu"
    p = str(tmp_path / "tuning.json")
    with open(p, "w") as f:
        json.dump(doc, f)
    monkeypatch.setenv("SPARKNET_TUNE", p)
    with pytest.raises(ValueError, match="do not transfer across backends"):
        tuner.active_table()


# ---------------------------------------------------------------------------
# SPARKNET_TUNE resolution modes
# ---------------------------------------------------------------------------

def test_resolve_modes(tmp_path, monkeypatch):
    t = _tiny_table()
    p = str(tmp_path / "tuning.json")
    t.save(p)
    want = t.winner(str(TINY_LRN))

    monkeypatch.setenv("SPARKNET_TUNE", "off")
    assert tuner.active_table() is None
    assert tuner.active_plan_id() == "off"
    assert tuner.resolve_lowering("lrn", TINY_LRN.shape, jnp.float32,
                                  extra=TINY_LRN.extra) is None

    monkeypatch.setenv("SPARKNET_TUNE", p)
    assert tuner.active_plan_id() == t.table_id()
    assert tuner.resolve_lowering("lrn", TINY_LRN.shape, jnp.float32,
                                  extra=TINY_LRN.extra) == want
    # table miss -> None -> hardcoded default
    assert tuner.resolve_lowering("lrn", (1, 2, 3, 3), jnp.float32,
                                  extra="s5") is None


def test_tune_typo_is_loud(monkeypatch):
    monkeypatch.setenv("SPARKNET_TUNE", "/no/such/tuning.json")
    with pytest.raises(ValueError, match="typo"):
        tuner.active_table()
    # ...and a Net build (which latches the plan id) is just as loud
    netp = net_param("t", [
        layer("data", "Input", tops=["data"],
              input_param={"shape": [{"dim": [1, 3, 6, 6]}]}),
    ])
    with pytest.raises(ValueError, match="typo"):
        from sparknet_tpu.graph.net import Net
        Net(netp, NetState(Phase.TEST))


# ---------------------------------------------------------------------------
# table pins (the path that replaced the PR-12 env shims)
# ---------------------------------------------------------------------------

def _pin_table(tmp_path, pins: dict, name="pins.json") -> str:
    """Write a minimal one-backend tuning table mapping key -> winner."""
    path = tmp_path / name
    tuner.TuningTable(tuner._backend(), [
        {"key": k, "winner": w, "timings": {}} for k, w in pins.items()
    ]).save(str(path))
    return str(path)


def test_table_pins_lrn_window_sum(monkeypatch, tmp_path):
    """The exact pre-tuner pin semantics, now spelled as a table: each
    lrn key resolves to its pinned winner; unpinned keys fall through
    to None (the hardcoded default)."""
    key1 = tuner.key_str("lrn", (2, 8, 6, 6), jnp.float32,
                         tuner.lrn_extra(5))
    key2 = tuner.key_str("lrn", (4, 4, 4, 4), jnp.float32,
                         tuner.lrn_extra(3))
    path = _pin_table(tmp_path, {key1: "cumsum", key2: "reduce_window"})
    monkeypatch.setenv("SPARKNET_TUNE", path)
    tuner._clear_caches()
    assert tuner.resolve_lowering("lrn", (2, 8, 6, 6), jnp.float32,
                                  extra="s5") == "cumsum"
    assert tuner.resolve_lowering("lrn", (4, 4, 4, 4), jnp.float32,
                                  extra="s3") == "reduce_window"
    # unpinned key: hardcoded default, no shim fallback anymore
    assert tuner.resolve_lowering("lrn", (9, 9, 9, 9), jnp.float32,
                                  extra="s5") is None


def test_table_pin_reaches_the_production_layer(monkeypatch, tmp_path):
    """A pinned lrn winner steers the production LRNLayer exactly like
    the retired env pin did: both forms agree numerically and the pin
    selects between them through resolve_lowering."""
    lp = lrn_layer("n1", "x", "y", local_size=5, alpha=1e-4, beta=0.75)
    impl = get_layer_impl("LRN")
    x = jnp.asarray(np.random.default_rng(0).normal(size=(2, 9, 5, 5)),
                    jnp.float32)
    key = tuner.key_str("lrn", x.shape, x.dtype, tuner.lrn_extra(5))
    outs = {}
    for winner in ("cumsum", "reduce_window"):
        path = _pin_table(tmp_path, {key: winner}, name=f"{winner}.json")
        monkeypatch.setenv("SPARKNET_TUNE", path)
        tuner._clear_caches()
        outs[winner] = np.asarray(impl.apply(lp, [], [x], True, None)[0])
    np.testing.assert_allclose(outs["cumsum"], outs["reduce_window"],
                               rtol=2e-6, atol=2e-6)


def test_table_pins_epilogue_reference(monkeypatch, tmp_path):
    key = tuner.key_str("lrn_epilogue", (2, 8, 6, 6), jnp.float32,
                        "s5:relu1")
    path = _pin_table(tmp_path, {key: "reference"})
    monkeypatch.setenv("SPARKNET_TUNE", path)
    tuner._clear_caches()
    assert tuner.resolve_lowering("lrn_epilogue", (2, 8, 6, 6),
                                  jnp.float32, extra="s5:relu1") \
        == "reference"
    monkeypatch.setenv("SPARKNET_TUNE", "off")
    tuner._clear_caches()
    assert tuner.resolve_lowering("lrn_epilogue", (2, 8, 6, 6),
                                  jnp.float32, extra="s5:relu1") is None


# ---------------------------------------------------------------------------
# keys_for_net + Net latching
# ---------------------------------------------------------------------------

def _zoo_netp():
    wf = {"type": "gaussian", "std": 0.05}
    return net_param("t", [
        layer("data", "Input", tops=["data", "label"],
              input_param={"shape": [{"dim": [2, 3, 12, 12]},
                                     {"dim": [2]}]}),
        convolution_layer("c1", "data", "c1", num_output=8, kernel=3,
                          pad=1, weight_filler=wf,
                          bias_filler={"type": "constant", "value": 0.1}),
        relu_layer("r1", "c1", "c1"),
        pooling_layer("p1", "c1", "p1", kernel=2, stride=2),
        lrn_layer("n1", "p1", "n1", local_size=5, alpha=1e-4, beta=0.75),
        inner_product_layer("ip", "n1", "ip", num_output=5,
                            weight_filler={"type": "gaussian",
                                           "std": 0.01}),
        softmax_with_loss_layer("loss", ["ip", "label"]),
    ])


def _build_net(fuse="off"):
    from sparknet_tpu.graph.net import Net
    os.environ["SPARKNET_FUSE"] = fuse
    try:
        return Net(_zoo_netp(), NetState(Phase.TRAIN))
    finally:
        os.environ.pop("SPARKNET_FUSE", None)


def test_keys_for_net_unfused():
    keys = tuner.keys_for_net(_build_net("off"))
    by_op = {k.op: k for k in keys}
    assert set(by_op) == {"conv", "pool", "lrn"}
    assert by_op["conv"].shape == (2, 3, 12, 12)
    assert by_op["pool"].shape == (2, 8, 12, 12)
    assert by_op["lrn"].shape == (2, 8, 6, 6)


def test_keys_for_net_fused_lrn_becomes_epilogue_key():
    net = _build_net("all")
    assert net._fuse_plan.chains, "chain should have fused"
    keys = tuner.keys_for_net(net)
    ops = [k.op for k in keys]
    assert "lrn_epilogue" in ops and "lrn" not in ops
    epi = next(k for k in keys if k.op == "lrn_epilogue")
    assert epi.shape == (2, 8, 6, 6)  # the LRN member's bottom
    assert epi.extra == tuner.epilogue_extra(5, False)


def test_net_latches_tune_plan_id(tmp_path, monkeypatch):
    monkeypatch.setenv("SPARKNET_TUNE", "off")
    assert _build_net().tune_plan_id() == "off"
    t = _tiny_table()
    p = str(tmp_path / "tuning.json")
    t.save(p)
    monkeypatch.setenv("SPARKNET_TUNE", p)
    net = _build_net()
    assert net.tune_plan_id() == t.table_id()
    from sparknet_tpu.utils.profiling import record_tuning
    assert record_tuning(net) == t.table_id()
    out = str(tmp_path / "cap")
    os.makedirs(out, exist_ok=True)
    record_tuning(net, out)
    saved = tuner.TuningTable.load(os.path.join(out, "tuning.json"))
    assert saved.table_id() == t.table_id()


# ---------------------------------------------------------------------------
# staleness gate
# ---------------------------------------------------------------------------

def test_staleness_fresh_table_passes_and_planted_rot_fails():
    e = _measured()
    fresh = tuner.staleness_check(tuner.TuningTable(tuner._backend(), [e]),
                                  budget_s=30.0, **FAST)
    assert fresh["ok"] and fresh["checked"] == 1

    rot_e = json.loads(json.dumps(e))
    rot_e["winner"] = "cumsum" if e["winner"] != "cumsum" else \
        "reduce_window"
    # pretend the loser won by a huge margin so noise can't excuse it
    rot = tuner.staleness_check(
        tuner.TuningTable(tuner._backend(), [rot_e]), budget_s=30.0,
        **FAST)
    if rot["ok"]:
        # the two candidates were within the noise band this run — the
        # gate correctly refuses to flag ties; force a decisive fake
        rot_e["winner"] = "__gone__"
        rot = tuner.staleness_check(
            tuner.TuningTable(tuner._backend(), [rot_e]), budget_s=30.0,
            **FAST)
    assert not rot["ok"]
    assert rot["rotten"][0]["fresh_timings"]  # re-probed evidence


# ---------------------------------------------------------------------------
# perf-ledger ingestion
# ---------------------------------------------------------------------------

def test_fingerprint_has_tune_plan_with_off_default():
    from sparknet_tpu.utils import perfledger as pl
    fp = pl.fingerprint(model="m", dtype="f32", batch=1)
    assert fp["tune_plan"] == "off"
    assert pl.fingerprint(model="m", dtype="f32", batch=1,
                          tune_plan="tt1-abc")["tune_plan"] == "tt1-abc"
    assert "tune_plan" in pl.FINGERPRINT_FIELDS


def test_entries_from_tuning_table_and_any_dispatch():
    from sparknet_tpu.utils import perfledger as pl
    doc = _tiny_table().to_doc()
    entries = pl.entries_from_any(doc, "profiles/cpu/tuning.json",
                                  round_tag="r13")
    assert entries, "tuning_table doc must be ingestible"
    mets = {m for e in entries for m in e["metrics"]}
    win_metric = f"tune_ms/{TINY_LRN}"
    assert win_metric in mets
    assert any(m.startswith(f"tune_margin/") for m in mets)
    for e in entries:
        assert e["fp"]["tune_plan"] == _tiny_table().table_id()
        assert e["fp"]["model"] == "tuner"
        assert e["source"] == "tuning"
    # non-table docs still route elsewhere
    assert pl.entries_from_tuning_table({"kind": "bench"}, "x") == []


# ---------------------------------------------------------------------------
# perf_probe inherits the typed-skip contract (satellite 2)
# ---------------------------------------------------------------------------

def test_perf_probe_time_block_typed_skip(capsys):
    import perf_probe

    def bad_iter(s):
        raise ValueError("no backend for this op")

    got = perf_probe.time_block("probe_bad", bad_iter, extra={"tag": 1})
    assert got is None
    out = [json.loads(line) for line in
           capsys.readouterr().out.strip().splitlines() if line]
    rec = next(r for r in out if r.get("exp") == "probe_bad")
    assert rec["skipped"].startswith("ValueError: no backend")
    assert rec["tag"] == 1


# ---------------------------------------------------------------------------
# the committed CPU table: parity + self-consistency (acceptance)
# ---------------------------------------------------------------------------

COMMITTED = os.path.join(REPO, "profiles", "cpu", "tuning.json")

needs_committed_table = pytest.mark.skipif(
    jax.default_backend() != "cpu" or not os.path.isfile(COMMITTED),
    reason="committed CPU tuning table applies to CPU hosts only")


@needs_committed_table
def test_committed_table_is_self_consistent():
    table = tuner.TuningTable.load(COMMITTED)
    assert table.backend == "cpu" and table.entries
    flips = 0
    for e in table.entries:
        win = e["timings"][e["winner"]]
        assert "ms" in win and "disqualified" not in win \
            and "ineligible" not in win, e["key"]
        # the winner was measured faster than every disqualified-or-
        # losing candidate at its key (the acceptance bar: the table is
        # evidence, not opinion)
        for name, rec in e["timings"].items():
            if name == e["winner"] or "ms" not in rec:
                continue
            assert win["ms"] <= rec["ms"], (e["key"], name)
        flips += bool(e["flip"])
    assert flips >= 1, "capture found no selection flip vs defaults"
    # the r10 probe verdict, rediscovered by measurement: reduce_window
    # beats cumsum on ALL FOUR zoo LRN shapes on CPU
    lrns = [e for e in table.entries if e["op"] == "lrn"]
    assert len(lrns) == 4
    for e in lrns:
        rw = e["timings"]["reduce_window"]
        cs = e["timings"]["cumsum"]
        assert rw["ms"] < cs["ms"], e["key"]


def _apply_lrn(x, tune):
    impl = get_layer_impl("LRN")
    lp = layer("n", "LRN", ["x"], ["y"],
               lrn_param={"local_size": 5, "alpha": 1e-4, "beta": 0.75})
    os.environ["SPARKNET_TUNE"] = tune
    try:
        return impl.apply(lp, [], [x], True, None)[0]
    finally:
        os.environ.pop("SPARKNET_TUNE", None)


def _apply_conv(x, w, b, tune, *, num_output, kernel, stride=1, pad=0,
                group=1):
    impl = get_layer_impl("Convolution")
    lp = layer("c", "Convolution", ["x"], ["y"],
               convolution_param={"num_output": num_output,
                                  "kernel_size": kernel,
                                  "stride": stride, "pad": pad,
                                  "group": group})
    os.environ["SPARKNET_TUNE"] = tune
    try:
        return impl.apply(lp, [w, b], [x], True, None)[0]
    finally:
        os.environ.pop("SPARKNET_TUNE", None)


def _apply_pool(x, tune):
    impl = get_layer_impl("Pooling")
    lp = layer("p", "Pooling", ["x"], ["y"],
               pooling_param={"pool": "MAX", "kernel_size": 3,
                              "stride": 2})
    os.environ["SPARKNET_TUNE"] = tune
    try:
        return impl.apply(lp, [], [x], True, None)[0]
    finally:
        os.environ.pop("SPARKNET_TUNE", None)


def _parity(fn, args):
    """off-vs-committed-table: forward bit-identical, grads <= 1e-5."""
    def mean_out(*a):
        return jnp.mean(fn(*a, "off")).astype(jnp.float32)

    def mean_out_tuned(*a):
        return jnp.mean(fn(*a, COMMITTED)).astype(jnp.float32)

    y_off = np.asarray(fn(*args, "off"))
    y_tab = np.asarray(fn(*args, COMMITTED))
    assert y_off.tobytes() == y_tab.tobytes(), "forward not bit-identical"
    g_off = jax.grad(mean_out)(*args)
    g_tab = jax.grad(mean_out_tuned)(*args)
    a64 = np.asarray(g_off, np.float64)
    b64 = np.asarray(g_tab, np.float64)
    denom = float(np.max(np.abs(a64))) or 1.0
    rel = float(np.max(np.abs(a64 - b64))) / denom
    assert rel <= 1e-5, f"grad divergence {rel:.3e}"


@needs_committed_table
@pytest.mark.parametrize("shape", [
    (8, 64, 56, 56), (8, 192, 56, 56), (16, 96, 55, 55),
    (16, 256, 27, 27),
])
def test_committed_parity_lrn_zoo_shapes(shape):
    """All four zoo LRN shapes: tuned vs SPARKNET_TUNE=off must be
    forward-bit-identical with grads <= 1e-5 rel — these keys HIT the
    committed table (the tuned path is really exercised)."""
    table = tuner.TuningTable.load(COMMITTED)
    ks = tuner.key_str("lrn", shape, "f32", "s5")
    assert table.winner(ks) is not None, f"{ks} missing from the table"
    r = np.random.default_rng(0)
    x = jnp.asarray(r.normal(size=shape), jnp.float32)
    _parity(_apply_lrn, (x,))


@needs_committed_table
def test_committed_parity_conv_shape():
    """CaffeNet conv3 at the captured batch: tuned vs off parity through
    the production Convolution layer."""
    table = tuner.TuningTable.load(COMMITTED)
    ks = tuner.key_str("conv", (16, 256, 13, 13), "f32",
                       tuner.conv_extra(3, 3, 1, 1, 1, 1, 1, 1, 384, 1))
    assert table.winner(ks) is not None, f"{ks} missing from the table"
    r = np.random.default_rng(0)
    x = jnp.asarray(r.normal(size=(16, 256, 13, 13)), jnp.float32)
    w = jnp.asarray(r.normal(size=(384, 256, 3, 3)) * 0.05, jnp.float32)
    b = jnp.asarray(r.normal(size=(384,)) * 0.1, jnp.float32)

    def fn(x, tune):
        return _apply_conv(x, w, b, tune, num_output=384, kernel=3, pad=1)

    _parity(fn, (x,))


@needs_committed_table
def test_committed_parity_pool_shape():
    """CaffeNet pool5 at the captured batch: tuned vs off parity through
    the production Pooling layer."""
    table = tuner.TuningTable.load(COMMITTED)
    ks = tuner.key_str("pool", (16, 256, 13, 13), "f32",
                       tuner.pool_extra(3, 3, 2, 2, 0, 0))
    assert table.winner(ks) is not None, f"{ks} missing from the table"
    r = np.random.default_rng(0)
    x = jnp.asarray(r.normal(size=(16, 256, 13, 13)), jnp.float32)
    _parity(_apply_pool, (x,))


@needs_committed_table
def test_zoo_keys_match_the_committed_capture():
    """tools/tune.py's default key set is exactly what the committed
    table holds — `tune.py staleness` re-probes what `run` captured."""
    import tune as tune_cli
    table = tuner.TuningTable.load(COMMITTED)
    want = {str(k) for k in tune_cli.zoo_keys(16)}
    have = {e["key"] for e in table.entries}
    assert want == have
