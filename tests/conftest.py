"""Test rig: force the host-CPU backend with 8 virtual devices.

This is the analog of the reference's CPU_ONLY cmake fallback
(reference: libccaffe/CMakeLists.txt:44-47) — it lets every test, including
the multi-chip collective paths, run with no TPU attached (SURVEY.md §4.3).
Must run before jax initializes its backends, hence the env mutation at
import time of conftest.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["JAX_PLATFORM_NAME"] = "cpu"  # the axon plugin ignores JAX_PLATFORMS
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest


@pytest.fixture
def rng():
    import jax
    return jax.random.PRNGKey(0)


@pytest.fixture
def np_rng():
    return np.random.default_rng(0)
