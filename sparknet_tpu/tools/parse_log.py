"""parse_log — split a training log into train/test CSVs (reference:
caffe/tools/extra/parse_log.py, which greps glog output for
"Iteration N, loss" / "Iteration N, lr" and "Test net output" lines and
mines the glog timestamp prefix for a Seconds column via
tools/extra/extract_seconds.py; this framework's Solver prints the same
shapes — solver.py step/solve/_print_test_scores through
utils/glog.log_line).

Usage:
  python -m sparknet_tpu.tools.parse_log LOGFILE [OUT_DIR]

Writes LOGFILE.train (NumIters,Seconds,LearningRate,loss) and
LOGFILE.test (NumIters,Seconds,TestNet,<output columns>) into OUT_DIR
(default: the log's directory), mirroring the reference's
<log>.train/<log>.test CSVs.  Logs without glog prefixes (or without lr
lines) still parse — the Seconds/LearningRate cells are left empty, and
the plot tool refuses the chart types that would need them.
"""

from __future__ import annotations

import argparse
import calendar
import csv
import datetime
import os
import re

_FLOAT = r"([-+]?(?:[0-9][0-9.]*(?:[eE][-+]?\d+)?|nan|inf))"
_ITER_RE = re.compile(r"Iteration (\d+), loss = " + _FLOAT)
_LR_RE = re.compile(r"Iteration (\d+), lr = " + _FLOAT)
_TESTING_RE = re.compile(r"Iteration (\d+), Testing net \(#(\d+)\)")
_TEST_RE = re.compile(
    r"Test net(?: #(\d+))? output: (\S+?)(?:\[(\d+)\])? = " + _FLOAT)
# glog prefix: I<mmdd> <HH:MM:SS.ffffff> <pid> <source>]  (the reference's
# extract_seconds.py format; utils/glog.log_line emits the same shape)
_GLOG_RE = re.compile(
    r"^[IWEF](\d{2})(\d{2}) (\d{2}):(\d{2}):(\d{2})\.(\d+)\b")


def _log_year(path: str) -> int:
    """The year the glog prefix omits, recovered from the log file's
    mtime (the reference extract_seconds.py uses ctime the same way —
    the log was last written in the year it logged, modulo a New Year
    boundary handled by the wrap logic in parse_log).  If the log
    carries a Feb 29 stamp but the mtime year is not leap (the file was
    copied or touched later), walk back to the nearest leap year — the
    log cannot postdate its mtime, and ONE year must govern the whole
    log or neighboring lines would land a year apart."""
    try:
        year = datetime.datetime.fromtimestamp(
            os.path.getmtime(path)).year
    except OSError:
        year = datetime.date.today().year
    if not calendar.isleap(year):
        try:
            with open(path) as f:
                has_feb29 = any(line[1:5] == "0229"
                                and _GLOG_RE.match(line) for line in f)
        except OSError:
            has_feb29 = False
        if has_feb29:
            while not calendar.isleap(year):
                year -= 1
    return year


def _glog_datetime(line: str, year: int) -> datetime.datetime | None:
    """Full datetime of a glog-prefixed line in ``year``.  Computing
    deltas from real datetimes (not a fixed-leap-year day-of-year
    table) keeps Feb 28 → Mar 1 spans exact: the old 2024-anchored
    scheme charged every non-leap-year log a phantom Feb 29 (+86400 s).
    A Feb 29 stamp with a non-leap ``year`` walks back to the nearest
    leap year — the log predates its mtime, it can't postdate it."""
    m = _GLOG_RE.match(line)
    if not m:
        return None
    mo, d, h, mi, s, frac = m.groups()
    us = round(int(frac) / 10 ** len(frac) * 1e6)
    for y in range(year, year - 8, -1):
        try:
            return datetime.datetime(y, int(mo), int(d), int(h),
                                     int(mi), int(s), us)
        except ValueError:
            if (int(mo), int(d)) != (2, 29):
                return None  # regex-shaped but not a date — unprefixed
    return None


def _glog_seconds(line: str, year: int | None = None) -> float | None:
    """Seconds since ``year``'s Jan 1 of a glog-prefixed line (year
    defaults to the current one — prefer passing _log_year(path))."""
    if year is None:
        year = datetime.date.today().year
    dt = _glog_datetime(line, year)
    if dt is None:
        return None
    return (dt - datetime.datetime(dt.year, 1, 1)).total_seconds()


def parse_log(path: str):
    """-> (train_rows, test_rows): train [(iter, loss, seconds|None,
    lr|None)], test {(iter, net_id): {column: value, "Seconds": s}} in
    encounter order.  For back-compat, train rows unpack as
    ``for it, loss in train`` too (see _TrainRow)."""
    train: list[_TrainRow] = []
    test: dict[tuple[int, int], dict[str, float]] = {}
    cur_iter = 0
    cur_test_net = 0
    year = _log_year(path)
    first_dt: datetime.datetime | None = None
    cur_lr: float | None = None
    lr_by_iter: dict[int, float] = {}
    with open(path) as f:
        for line in f:
            ts: float | None = None
            dt = _glog_datetime(line, year)
            if dt is not None:
                if first_dt is None:
                    first_dt = dt
                if dt < first_dt:  # new-year wrap within one log
                    try:
                        dt = dt.replace(year=dt.year + 1)
                    except ValueError:  # Feb 29 wrapped into a non-leap
                        dt = (dt.replace(year=dt.year + 1, day=28)
                              + datetime.timedelta(days=1))
                ts = (dt - first_dt).total_seconds()
            m = _LR_RE.search(line)
            if m:
                cur_lr = float(m.group(2))
                lr_by_iter[int(m.group(1))] = cur_lr
                continue
            m = _ITER_RE.search(line)
            if m:
                cur_iter = int(m.group(1))
                # lr in effect NOW (last lr line seen so far); a
                # same-iteration lr line printed just after this loss
                # line overrides it below
                train.append(_TrainRow(cur_iter, float(m.group(2)), ts,
                                       cur_lr))
                continue
            m = _TESTING_RE.search(line)
            if m:  # the authoritative iteration for following scores —
                #    covers the pre-training pass on resume, where no
                #    "Iteration N, loss" line has printed yet
                cur_iter = int(m.group(1))
                cur_test_net = int(m.group(2))
                if ts is not None:
                    test.setdefault((cur_iter, cur_test_net), {})[
                        "Seconds"] = ts
                continue
            m = _TEST_RE.search(line)
            if m:
                net_id = int(m.group(1) or cur_test_net)
                col = m.group(2)
                if m.group(3) is not None:  # indexed per-class outputs
                    col = f"{col}[{m.group(3)}]"
                test.setdefault((cur_iter, net_id), {})[col] = \
                    float(m.group(4))
    # the lr line prints at the same display boundary as (just after)
    # the loss line; prefer the exact same-iteration lr over the
    # scan-time "last seen" value each row was stamped with, so the
    # display-pair rows get their own boundary's rate and the
    # solve()-chunk-boundary rows keep the rate in effect at that point
    for row in train:
        row.lr = lr_by_iter.get(row.iter, row.lr)
    return train, test


class _TrainRow:
    """(iter, loss) tuple-compatible row carrying seconds + lr."""

    __slots__ = ("iter", "loss", "seconds", "lr")

    def __init__(self, it: int, loss: float, seconds: float | None,
                 lr: float | None = None):
        self.iter, self.loss, self.seconds, self.lr = it, loss, seconds, lr

    def __iter__(self):  # back-compat: `for it, loss in train`
        return iter((self.iter, self.loss))

    def __getitem__(self, i):
        return (self.iter, self.loss)[i]

    def __eq__(self, other):
        try:
            return tuple(self) == tuple(other)
        except TypeError:
            return NotImplemented

    def __hash__(self):
        return hash(tuple(self))

    def __repr__(self):
        return (f"_TrainRow({self.iter}, {self.loss}, "
                f"seconds={self.seconds}, lr={self.lr})")


def write_csvs(path: str, out_dir: str | None = None) -> tuple[str, str]:
    train, test = parse_log(path)
    out_dir = out_dir or (os.path.dirname(os.path.abspath(path)))
    base = os.path.join(out_dir, os.path.basename(path))
    train_path, test_path = base + ".train", base + ".test"
    fmt = lambda v: "" if v is None else v
    with open(train_path, "w", newline="") as f:
        w = csv.writer(f)
        # the reference's column set (parse_log.py train_dict_names)
        w.writerow(["NumIters", "Seconds", "LearningRate", "loss"])
        for row in train:
            w.writerow([row.iter, fmt(row.seconds), fmt(row.lr), row.loss])
    cols: list[str] = []
    for row in test.values():
        for k in row:
            if k not in cols and k != "Seconds":
                cols.append(k)
    with open(test_path, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(["NumIters", "Seconds", "TestNet"] + cols)
        for (it, net_id), row in test.items():
            w.writerow([it, fmt(row.get("Seconds")), net_id]
                       + [row.get(c, "") for c in cols])
    return train_path, test_path


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("logfile")
    ap.add_argument("out_dir", nargs="?", default=None)
    args = ap.parse_args(argv)
    train_path, test_path = write_csvs(args.logfile, args.out_dir)
    print(train_path)
    print(test_path)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
