#!/usr/bin/env python
"""Serial-vs-parallel feed microbench + parity gate (the CI teeth of the
parallel input pipeline).

Builds a small synthetic LMDB, then streams the SAME batches through
``db_feed`` twice — once on the serial reference path (``workers=0``) and
once through the decode pool — and verifies the parallel stream is
bit-identical: same pixels, same labels, and (with ``--corrupt``) the same
quarantine accounting (same records quarantined, same replacement pulls).
Any divergence is a correctness regression in the pipeline's ordering
guarantees and fails the run (exit 1).

Wall time is bounded (default ~2 s): the serial leg runs until its time
budget, the parallel leg replays the same batch count — parity needs equal
streams, not equal durations.  Prints ONE JSON verdict line on stdout.

``--records-leg`` extends the parity triangle to pre-decoded record
shards: the LMDB is converted once (``tools/convert.py`` path), then the
SAME batches are replayed from local shards through the parallel
ranged-read pool AND from a ``VerifyingStore`` through a tiered
``ShardCache`` (RAM + disk spill) — all three streams must be
pixel/label/quarantine bit-identical to the serial LMDB reference,
including under ``--corrupt`` fault injection (admissions attributed to
shard sources), plus a planted on-disk corrupt record block that must
quarantine with source attribution, and cold/warm cache-tier counters
must show the spill tier working.

Usage:
  python tools/feedbench.py [--seconds 2] [--batch 32] [--records 256]
                            [--workers N] [--corrupt] [--records-leg]
                            [--out FILE]
Wired into tools/run_tier1.sh behind SPARKNET_FEEDBENCH=1 (or --feedbench);
the records triangle behind SPARKNET_RECORDBENCH=1 (or --recordbench).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def build_db(path: str, n: int, shape=(3, 16, 16), seed: int = 0) -> None:
    from sparknet_tpu.data.db import array_to_datum
    from sparknet_tpu.data.lmdb_io import write_lmdb
    rng = np.random.default_rng(seed)
    imgs = rng.integers(0, 256, size=(n,) + shape).astype(np.uint8)
    labels = rng.integers(0, 10, size=n)
    write_lmdb(path, [(b"%08d" % i, array_to_datum(imgs[i], int(labels[i])))
                      for i in range(n)])


def run_leg(path: str, batch: int, workers: int, n_batches: int | None,
            seconds: float, seed: int, records: int = 0) -> dict:
    """Stream batches off one fresh db_feed; returns arrays + quarantine
    report + throughput.  Bounded by ``n_batches`` when given (the parity
    replay), else by the time budget."""
    from sparknet_tpu.data.db import db_feed
    from sparknet_tpu.data.integrity import Quarantine, QuarantinePolicy
    from sparknet_tpu.data.pipeline import FeedStats
    from sparknet_tpu.models.dsl import layer
    from sparknet_tpu.proto.caffe_pb import Phase
    from sparknet_tpu.utils import faults

    faults.reset_injector()   # each leg re-arms one-shot fault state
    lp = layer("d", "Data", [], ["data", "label"],
               data_param={"source": path, "batch_size": batch,
                           "backend": "LMDB"},
               transform_param={"scale": 0.5, "mean_value": [16.0]})
    quarantine = Quarantine(QuarantinePolicy(max_fraction=0.5),
                            epoch_size=records or None, source=path)
    stats = FeedStats()
    feed = db_feed(lp, Phase.TRAIN, seed=seed, quarantine=quarantine,
                   workers=workers, stats=stats)
    batches = []
    t0 = time.perf_counter()
    deadline = t0 + seconds
    while (len(batches) < n_batches if n_batches is not None
           else time.perf_counter() < deadline):
        b = next(feed)
        # copy: db_feed may rotate/reuse buffers; the parity compare
        # holds every batch at once
        batches.append({k: np.array(v) for k, v in b.items()})
    dt = time.perf_counter() - t0
    feed.close()
    images = sum(b["data"].shape[0] for b in batches)
    return {"batches": batches, "quarantine": quarantine.report(),
            "stats": stats.snapshot(), "seconds": round(dt, 3),
            "img_s": round(images / dt, 1) if dt > 0 else 0.0}


def compare(serial: dict, parallel: dict, cross_source: bool = False,
            label: str = "parallel") -> list[str]:
    errs = []
    a, b = serial["batches"], parallel["batches"]
    if len(a) != len(b):
        return [f"batch count mismatch: serial {len(a)} vs {label} "
                f"{len(b)}"]
    for i, (x, y) in enumerate(zip(a, b)):
        for k in x:
            if not np.array_equal(x[k], y[k]):
                errs.append(f"batch {i} key {k!r} differs vs {label} "
                            f"(max abs diff "
                            f"{np.abs(x[k] - y[k]).max():.3g})")
    qa, qb = dict(serial["quarantine"]), dict(parallel["quarantine"])
    for q in (qa, qb):   # examples carry reprs; counts are the contract
        q.pop("examples", None)
        if cross_source:
            # LMDB and records legs attribute to different source names
            # by construction; admission COUNTS are the cross-source
            # contract (positions are proven by the pixel parity above)
            q.pop("by_source", None)
    if qa != qb:
        errs.append(f"quarantine accounting differs: serial {qa} vs "
                    f"{label} {qb}")
    return errs


def run_records_leg(shards: str, batch: int, workers: int, n_batches: int,
                    seed: int, records: int = 0, verify: bool = False,
                    cache=None) -> dict:
    """Replay ``n_batches`` from a record-shard source through
    ``records_feed`` — same transform/quarantine configuration as
    :func:`run_leg`, so the streams must be bit-identical."""
    from sparknet_tpu.data.integrity import Quarantine, QuarantinePolicy
    from sparknet_tpu.data.pipeline import FeedStats
    from sparknet_tpu.data.records import records_feed
    from sparknet_tpu.models.dsl import layer
    from sparknet_tpu.proto.caffe_pb import Phase
    from sparknet_tpu.utils import faults

    faults.reset_injector()
    lp = layer("d", "Data", [], ["data", "label"],
               data_param={"source": shards, "batch_size": batch,
                           "backend": "RECORDS"},
               transform_param={"scale": 0.5, "mean_value": [16.0]})
    quarantine = Quarantine(QuarantinePolicy(max_fraction=0.5),
                            epoch_size=records or None, source=shards)
    stats = FeedStats()
    feed = records_feed(lp, Phase.TRAIN, seed=seed, quarantine=quarantine,
                        workers=workers, stats=stats, verify=verify,
                        cache=cache)
    batches = []
    t0 = time.perf_counter()
    for _ in range(n_batches):
        b = next(feed)
        batches.append({k: np.array(v) for k, v in b.items()})
    dt = time.perf_counter() - t0
    feed.close()
    images = sum(b["data"].shape[0] for b in batches)
    return {"batches": batches, "quarantine": quarantine.report(),
            "stats": stats.snapshot(), "seconds": round(dt, 3),
            "img_s": round(images / dt, 1) if dt > 0 else 0.0}


def convert_db_to_shards(db: str, out_dir: str, shard_bytes: int) -> dict:
    """LMDB → shards in cursor order (the tools/convert.py lmdb path)."""
    from sparknet_tpu.data.records import convert_to_shards
    import tools.convert as convert
    return convert_to_shards(convert.iter_db(db, "LMDB"), out_dir,
                             shard_bytes=shard_bytes)


def check_planted_corruption(shards_dir: str, tmp: str, batch: int,
                             records: int, seed: int) -> list[str]:
    """Flip one byte inside a record block of a COPY of the shard set;
    the records feed must quarantine exactly that record, attributed to
    the shard source — never yield wrong pixels, never crash."""
    import shutil
    from sparknet_tpu.data.records import RecordShard
    from sparknet_tpu.utils import faults

    faults.reset_injector()
    planted = os.path.join(tmp, "planted")
    shutil.copytree(shards_dir, planted)
    name = sorted(n for n in os.listdir(planted) if n.endswith(".rec"))[0]
    victim = os.path.join(planted, name)
    shard = RecordShard.open(victim)
    pos = shard.offset(0) + shard.stride // 2
    with open(victim, "r+b") as f:     # flip a byte mid-block of record 0
        f.seek(pos)
        orig = f.read(1)[0]
        f.seek(pos)
        f.write(bytes([orig ^ 0xFF]))
    leg = run_records_leg(planted, batch, 2,
                          max(1, records // batch), seed, records=records)
    rep = leg["quarantine"]
    errs = []
    if rep["total_bad"] < 1:
        errs.append("planted corrupt record block was NOT quarantined")
    if not any(name in src for src in rep.get("by_source", {})):
        errs.append(f"planted corruption not attributed to shard "
                    f"{name!r}: by_source={rep.get('by_source')}")
    return errs


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--seconds", type=float, default=2.0,
                    help="wall budget for the serial leg (default 2)")
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--records", type=int, default=256)
    ap.add_argument("--workers", type=int, default=None,
                    help="parallel-leg pool width (default "
                         "SPARKNET_FEED_WORKERS, min 2 so the pool is "
                         "actually exercised)")
    ap.add_argument("--corrupt", action="store_true",
                    help="run with corrupt_record:0.1 fault injection — "
                         "parity must hold through the quarantine path")
    ap.add_argument("--records-leg", action="store_true",
                    help="also convert to record shards and replay through "
                         "records_feed (local, object-store+tiered-cache, "
                         "warm-cache) — all bit-identical to the serial leg")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--out", default=None, help="also write the JSON here")
    args = ap.parse_args(argv)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if args.corrupt:
        os.environ["SPARKNET_FAULT"] = "corrupt_record:0.1"
        os.environ["SPARKNET_FAULT_ATTEMPT"] = "0"

    from sparknet_tpu.data.pipeline import feed_workers
    workers = args.workers if args.workers is not None \
        else max(2, feed_workers())

    rec: dict = {}
    with tempfile.TemporaryDirectory() as tmp:
        db = os.path.join(tmp, "lmdb")
        build_db(db, args.records, seed=args.seed)
        serial = run_leg(db, args.batch, 0, None, args.seconds / 2,
                         args.seed, records=args.records)
        parallel = run_leg(db, args.batch, workers,
                           len(serial["batches"]), args.seconds, args.seed,
                           records=args.records)
        errs = compare(serial, parallel)
        if args.records_leg:
            from sparknet_tpu.data.pipeline import FeedStats, ShardCache
            shards_dir = os.path.join(tmp, "shards")
            n_batches = len(serial["batches"])
            stride = 3 * 16 * 16 + 8   # build_db geometry + i64 label
            conv = convert_db_to_shards(
                db, shards_dir,
                shard_bytes=max(stride, args.records * stride // 4))
            n_shards = len(conv["shards"])
            per_shard = -(-args.records // max(1, n_shards))
            rec_local = run_records_leg(shards_dir, args.batch, workers,
                                        n_batches, args.seed,
                                        records=args.records)
            errs += compare(serial, rec_local, cross_source=True,
                            label="records")
            cache_stats = FeedStats()
            cache = ShardCache(max_shards=2, stats=cache_stats,
                               spill_dir=os.path.join(tmp, "spill"),
                               max_spill=16)
            rec_store = run_records_leg(shards_dir, args.batch, workers,
                                        n_batches, args.seed,
                                        records=args.records,
                                        verify=True, cache=cache)
            errs += compare(serial, rec_store, cross_source=True,
                            label="records+store")
            cold = cache_stats.snapshot()
            rec_warm = run_records_leg(shards_dir, args.batch, workers,
                                       n_batches, args.seed,
                                       records=args.records,
                                       verify=True, cache=cache)
            errs += compare(serial, rec_warm, cross_source=True,
                            label="records+warm-cache")
            warm = cache_stats.snapshot()
            if cold["cache_misses"] < 1:
                errs.append("cold records replay never missed the cache "
                            "(cache not exercised)")
            if not (warm["cache_hits"] + warm["cache_disk_hits"]
                    > cold["cache_hits"] + cold["cache_disk_hits"]):
                errs.append("warm records replay produced no new cache "
                            "hits")
            # The disk tier only fires once the cold pass streamed past
            # the 2-shard RAM tier (evictions spilled, warm pass rereads)
            if (n_shards > 2 and n_batches * args.batch > 2 * per_shard
                    and warm["cache_disk_hits"] < 1):
                errs.append(
                    f"disk spill tier never hit (shards={n_shards}, "
                    f"tiers={cache.tier_counts()}, warm={warm})")
            if args.corrupt:
                rep = rec_local["quarantine"]
                if rep["total_bad"] and not any(
                        shards_dir in s for s in rep.get("by_source", {})):
                    errs.append(
                        "injected corruption not attributed to the shard "
                        f"source: by_source={rep.get('by_source')}")
            else:
                errs += check_planted_corruption(shards_dir, tmp,
                                                 args.batch, args.records,
                                                 args.seed)
            rec = {
                "records_leg": True,
                "shards": n_shards,
                "records_img_s": rec_local["img_s"],
                "records_store_img_s": rec_store["img_s"],
                "records_warm_img_s": rec_warm["img_s"],
                "records_speedup": round(
                    rec_local["img_s"] / serial["img_s"], 2)
                if serial["img_s"] else None,
                "records_read_s": rec_local["stats"].get("read_s"),
                "cache_cold": {k: cold[k] for k in
                               ("cache_hits", "cache_disk_hits",
                                "cache_misses")},
                "cache_warm": {k: warm[k] for k in
                               ("cache_hits", "cache_disk_hits",
                                "cache_misses")},
            }
    verdict = {
        "metric": "feed_parity",
        "ok": not errs,
        "errors": errs,
        "batches": len(serial["batches"]),
        "batch": args.batch,
        "workers": workers,
        "corrupt": bool(args.corrupt),
        "serial_img_s": serial["img_s"],
        "parallel_img_s": parallel["img_s"],
        "speedup": round(parallel["img_s"] / serial["img_s"], 2)
        if serial["img_s"] else None,
        "quarantined": serial["quarantine"]["total_bad"],
        **rec,
    }
    line = json.dumps(verdict)
    print(line, flush=True)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    if errs:
        for e in errs:
            print(f"feedbench: PARITY FAIL: {e}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
