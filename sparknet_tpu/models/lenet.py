"""LeNet on MNIST — the minimal zoo model.

Architecture per the reference zoo's lenet_train_test config
(reference: caffe/examples/mnist/lenet_train_test.prototxt), built with the
DSL the way LayerSpec builds its Scala-DSL LeNet (reference:
src/test/scala/libs/LayerSpec.scala).
"""

from __future__ import annotations

from ..proto.caffe_pb import NetParameter, Phase
from .dsl import (
    accuracy_layer, convolution_layer, inner_product_layer, java_data_layer,
    net_param, pooling_layer, relu_layer, softmax_with_loss_layer,
)

_XAVIER = {"type": "xavier"}
_ZERO = {"type": "constant"}
_LRB = [{"lr_mult": 1.0}, {"lr_mult": 2.0}]


def lenet(train_batch: int = 64, test_batch: int = 100,
          image: tuple[int, int, int] = (1, 28, 28)) -> NetParameter:
    c, h, w = image
    return net_param("LeNet", [
        java_data_layer("mnist_train", ["data", "label"], Phase.TRAIN,
                        (train_batch, c, h, w), (train_batch,)),
        java_data_layer("mnist_test", ["data", "label"], Phase.TEST,
                        (test_batch, c, h, w), (test_batch,)),
        convolution_layer("conv1", "data", "conv1", num_output=20, kernel=5,
                          weight_filler=_XAVIER, bias_filler=_ZERO, param=_LRB),
        pooling_layer("pool1", "conv1", "pool1", pool="MAX", kernel=2, stride=2),
        convolution_layer("conv2", "pool1", "conv2", num_output=50, kernel=5,
                          weight_filler=_XAVIER, bias_filler=_ZERO, param=_LRB),
        pooling_layer("pool2", "conv2", "pool2", pool="MAX", kernel=2, stride=2),
        inner_product_layer("ip1", "pool2", "ip1", num_output=500,
                            weight_filler=_XAVIER, bias_filler=_ZERO, param=_LRB),
        relu_layer("relu1", "ip1"),
        inner_product_layer("ip2", "ip1", "ip2", num_output=10,
                            weight_filler=_XAVIER, bias_filler=_ZERO, param=_LRB),
        softmax_with_loss_layer("loss", ["ip2", "label"]),
        accuracy_layer("accuracy", ["ip2", "label"], phase=Phase.TEST),
    ])
