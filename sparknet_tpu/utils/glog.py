"""glog-style training-log lines.

The reference logs through glog — `I0416 13:23:03.089758 21823
solver.cpp:218] Iteration 80, loss = ...` — and its log tooling mines
the prefix for wall-clock axes (reference:
caffe/tools/extra/extract_seconds.py, which subtracts the first line's
timestamp to get a Seconds column).  The Solver routes its training-loop
prints through ``log_line`` so ``tools/parse_log`` can recover Seconds
and ``tools/plot_training_log`` can draw the *-vs-Seconds chart types.

Lines keep the reference's field order (level+date, time, pid,
source]) so the prefix regex in parse_log matches either producer's
logs.
"""

from __future__ import annotations

import datetime
import os
import sys

_PID = os.getpid()


def log_line(msg: str, *, file=None, now: datetime.datetime | None = None,
             tag: str = "solver.py") -> None:
    """Print ``msg`` with a glog-'I' prefix (INFO severity; the reference
    trains at INFO — sgd_solver.cpp logs rate/loss via LOG(INFO))."""
    now = now or datetime.datetime.now()
    print(f"{now:I%m%d %H:%M:%S.%f} {_PID} {tag}] {msg}",
          file=file or sys.stdout)
