"""Host-side image preprocessing.

The numpy equivalents of the reference's preprocessing tier: mean-image
computation (reference: src/main/scala/preprocessing/ComputeMean.scala:8-44),
random-crop + mean-subtract train preprocessing and center-crop test
preprocessing closures (reference: src/main/scala/apps/ImageNetApp.scala:
155-169 and :117-131), the crop-into-float-buffer hot path
(reference: src/main/java/libs/ByteImage.java:77-95 cropInto), and Caffe's
DataTransformer crop/mirror/scale semantics (reference:
caffe/src/caffe/data_transformer.cpp).

These run vectorized over whole minibatches (the reference loops per image
per pixel through JNA — its measured hot spot, CallbackBenchmarkSpec).  An
optional C++ fast path lives in sparknet_tpu.native.

Allocation discipline (the feed-pipeline hot path): every function takes
``np.asarray(..., np.float32)`` — a no-op when the input is already f32,
where the old ``.astype`` unconditionally copied — and accepts an optional
preallocated ``out`` buffer (pair with ``pipeline.BufferRing`` for an
allocation-free steady state; the ring's aliasing contract is the
caller's).
"""

from __future__ import annotations

import numpy as np


def _take(out: np.ndarray | None, shape: tuple) -> np.ndarray:
    """``out`` when it matches (f32, C-contiguous, right shape), else a
    fresh buffer — a wrong buffer silently degrades to an allocation, it
    never degrades to wrong results."""
    if (out is not None and out.shape == shape and out.dtype == np.float32
            and out.flags["C_CONTIGUOUS"]):
        return out
    return np.empty(shape, np.float32)


def compute_mean_image(images: np.ndarray) -> np.ndarray:
    """Mean image over the dataset (ComputeMean.apply analog — the
    distributed pixel-sum reduce collapses to one vectorized mean here;
    per-partition sums for the Spark tier are just np.sum per partition)."""
    return images.astype(np.float64).mean(axis=0).astype(np.float32)


def subtract_mean(batch: np.ndarray, mean: np.ndarray | float,
                  out: np.ndarray | None = None) -> np.ndarray:
    x = np.asarray(batch, np.float32)
    dest = _take(out, x.shape)
    np.subtract(x, mean, out=dest)
    return dest


def random_crop_mirror(batch: np.ndarray, crop: int,
                       rng: np.random.Generator,
                       mirror: bool = True,
                       mean: np.ndarray | float | None = None,
                       out: np.ndarray | None = None) -> np.ndarray:
    """Random crop to (crop, crop) + horizontal mirror
    (DataTransformer train path; ImageNetApp train preprocessing closure).
    Runs through the C++ pipeline when available."""
    from .. import native
    n, c, h, w = batch.shape
    ys = rng.integers(0, h - crop + 1, size=n).astype(np.int32)
    xs = rng.integers(0, w - crop + 1, size=n).astype(np.int32)
    flips = (rng.integers(0, 2, size=n) if mirror
             else np.zeros(n)).astype(np.int32)
    if isinstance(mean, np.ndarray) and mean.shape[-2:] != (crop, crop):
        # Full-size mean: Caffe's DataTransformer indexes the mean at each
        # sample's crop window (data_transformer.cpp Transform, data_index
        # uses h_off/w_off), i.e. crop(img - mean) — subtract before crop.
        batch = np.asarray(batch, np.float32) - np.asarray(mean, np.float32)
        mean = None
    return native.crop_batch(np.asarray(batch, np.float32), crop,
                             ys, xs, flips, mean, out=out)


def center_crop(batch: np.ndarray, crop: int,
                mean: np.ndarray | float | None = None,
                out: np.ndarray | None = None) -> np.ndarray:
    """Deterministic center crop (test path; ImageNetApp.scala:117-131)."""
    n, c, h, w = batch.shape
    y = (h - crop) // 2
    x = (w - crop) // 2
    dest = _take(out, (n, c, crop, crop))
    dest[...] = batch[:, :, y:y + crop, x:x + crop]
    if mean is not None:
        if isinstance(mean, np.ndarray) and mean.shape[-2:] != (crop, crop):
            mean = center_crop_mean(mean, crop)
        np.subtract(dest, mean, out=dest)
    return dest


def center_crop_mean(mean: np.ndarray, crop: int) -> np.ndarray:
    h, w = mean.shape[-2], mean.shape[-1]
    y, x = (h - crop) // 2, (w - crop) // 2
    return mean[..., y:y + crop, x:x + crop]


def scale(batch: np.ndarray, factor: float,
          out: np.ndarray | None = None) -> np.ndarray:
    """DataTransformer `scale` (e.g. 1/255 for LeNet/MNIST)."""
    x = np.asarray(batch, np.float32)
    dest = _take(out, x.shape)
    np.multiply(x, factor, out=dest)
    return dest


def augment_batch_host(imgs: np.ndarray, key, spec) -> np.ndarray:
    """Numpy implementation of ``ops.augment.AugmentSpec`` — the host
    half of the device-augmentation bit-parity contract.

    Randomness comes from the SAME traced-key draws as the device path
    (``ops.augment.draw_offsets``, jax threefry — counter-based, so CPU
    and TPU produce identical offsets), and every op here (u8→f32 cast,
    f32 subtract, slice, flip, f32 multiply) is IEEE-exact in both numpy
    and XLA, so ``Solver.set_augment(spec, device=False)`` training is
    bit-identical to ``device=True`` at the same seed.  Order matches
    ``db.DataTransformer``: cast → full-size mean subtract → crop →
    mirror → scale."""
    from ..ops.augment import draw_offsets
    n, c, h, w = imgs.shape
    ys, xs, flips = (np.asarray(a) for a in
                     draw_offsets(key, n, h, w, spec))
    x = np.asarray(imgs).astype(np.float32)
    if spec.mean is not None:
        x = x - np.asarray(spec.mean, np.float32)
    if spec.crop:
        cropped = np.empty((n, c, spec.crop, spec.crop), np.float32)
        for i in range(n):
            cropped[i] = x[i, :, ys[i]:ys[i] + spec.crop,
                           xs[i]:xs[i] + spec.crop]
        x = cropped
    if spec.mirror and spec.train:
        x[flips == 1] = x[flips == 1, :, :, ::-1]
    if spec.scale != 1.0:
        x = x * np.float32(spec.scale)
    return x
