"""Graph compiler: NetParameter -> pure init/apply functions.

The TPU-native replacement for Caffe's ``Net`` (reference:
caffe/src/caffe/net.cpp:40 ``Init`` — phase filtering, topological wiring via
AppendTop/AppendBottom at net.cpp:385/444, per-layer SetUp with shape
inference) and its executor (``ForwardFromTo``/``BackwardFromTo``,
net.cpp:565/635).  Differences by design:

- The graph lowers to one pure function; ``jax.jit`` compiles forward, and
  backward is ``jax.grad`` of it — there are no per-layer Backward
  implementations and no topological scheduler to maintain.
- ``InsertSplits`` (reference: caffe/src/caffe/util/insert_splits.cpp:12) is
  unnecessary: fan-out in a functional graph is just reusing a value; XLA
  accumulates the cotangents.
- Blob memory management (``SyncedMemory`` CPU/GPU state machine, reference:
  caffe/src/caffe/syncedmem.hpp:62) is XLA's problem, not ours.

Parameter storage is a flat ``{key: [blobs...]}`` dict keyed by layer name,
with cross-layer sharing via ``ParamSpec.name`` (reference: net.cpp
AppendParam sharing semantics) resolved to owner keys.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Mapping, Sequence

import jax
import jax.numpy as jnp

from ..ops.registry import LayerImpl, Shape, get_layer_impl
from ..proto.caffe_pb import (
    LayerParameter,
    NetParameter,
    NetState,
    Phase,
)

# WeightCollection — the {layer name -> list of arrays} container the driver
# averages (reference: src/main/scala/libs/Net.scala:14-47).  Here it is just
# a pytree alias; elementwise add / scalarDivide are jax.tree_util one-liners.
WeightCollection = dict[str, list[jax.Array]]


@dataclasses.dataclass
class NetOutputs:
    """Result of one forward pass."""

    blobs: dict[str, jax.Array]      # net-output blobs (unconsumed tops)
    loss: jax.Array                  # Σ loss_weight · top
    params: WeightCollection         # params incl. forward-state updates (BN)


@dataclasses.dataclass
class _LayerNode:
    lp: LayerParameter
    impl: LayerImpl
    bottoms: list[str]
    tops: list[str]
    param_key: str            # this layer's own storage key (== lp.name)
    lr_mults: list[float]
    decay_mults: list[float]
    # per-blob sharing (reference: net.cpp AppendParam — each ParamSpec with
    # a name shares that one blob with the first layer that declared it):
    # blob index -> (owner layer name, owner *stored* position)
    shared_refs: dict[int, tuple[str, int]] = dataclasses.field(
        default_factory=dict)
    # blob index -> position in params[lp.name] for non-shared blobs
    own_map: dict[int, int] = dataclasses.field(default_factory=dict)
    n_blobs: int | None = None     # total blobs (known when probed)

    def owner_keys(self) -> set[str]:
        """Storage keys holding any of this node's blobs."""
        keys = {o for o, _ in self.shared_refs.values()}
        if self.own_map or not self.shared_refs:
            keys.add(self.param_key)
        return keys

    def loss_weights(self) -> list[float]:
        """Per-top loss weights — Layer::SetLossWeights resolution
        (explicit loss_weight, else 1 on a loss layer's first top)."""
        weights = list(self.lp.loss_weight)
        if not weights and self.impl.is_loss():
            weights = [1.0] + [0.0] * (len(self.tops) - 1)
        return weights


class Net:
    """A phase-filtered, shape-inferred, executable network."""

    def __init__(self, net_param: NetParameter, state: NetState | None = None,
                 *, compute_dtype=None, input_overrides=None):
        if state is None:
            state = net_param.state or NetState()
        self.state = state
        self.param = net_param.filtered(state)
        self.name = net_param.name
        self.compute_dtype = compute_dtype
        self.nodes: list[_LayerNode] = []
        self.blob_shapes: dict[str, Shape] = {}
        self.input_blobs: dict[str, Shape] = {}
        # input_overrides: {input blob name: shape} replacing the declared
        # shape of net-level inputs / Input-layer tops — the pycaffe
        # Net::Reshape path (net.cpp:Reshape propagates new bottom shapes;
        # here downstream shapes re-infer from the overridden inputs)
        overrides = {k: tuple(int(d) for d in v)
                     for k, v in (input_overrides or {}).items()}

        # net-level input declarations (legacy `input:` + `input_shape:`)
        for i, name in enumerate(self.param.input):
            shape = overrides.get(name,
                                  tuple(self.param.input_shape[i].dim))
            self.blob_shapes[name] = shape
            self.input_blobs[name] = shape

        shared_owner: dict[str, tuple[str, int]] = {}  # ParamSpec.name -> (layer, idx)
        self._probe_cache: dict[str, list] = {}
        self._node_by_name: dict[str, _LayerNode] = {}
        # blobs whose batch dim is data-dependent (downstream of Filter):
        # their declared shapes are placeholders — building params from them
        # would silently mis-size blobs (reference: filter_layer.cpp Reshape
        # runs per batch; our shapes are static)
        tainted: set[str] = set()

        for lp in self.param.layer:
            # per_net_copy: layers with per-net host state (Python layers)
            # get a fresh impl per Net — caffe instantiates layer objects
            # per net (net.cpp Init); stateless impls stay singletons
            impl = get_layer_impl(lp.type).per_net_copy()
            tops = list(lp.top)
            bottoms = list(lp.bottom)
            for b in bottoms:
                if b not in self.blob_shapes:
                    raise ValueError(
                        f"layer {lp.name!r} bottom {b!r} unknown "
                        f"(known: {sorted(self.blob_shapes)})")
            bshapes = [self.blob_shapes[b] for b in bottoms]
            if any(b in tainted for b in bottoms):
                self._check_batch_insensitive(lp, impl, bottoms, bshapes,
                                              tainted)
            oshapes = impl.out_shapes(lp, bshapes)
            taints = (getattr(impl, "dynamic_batch", False)
                      or any(b in tainted for b in bottoms))
            if not tops:
                tops = [lp.name] if oshapes else []
            while len(tops) < len(oshapes):
                tops.append(f"{lp.name}_top{len(tops)}")
            for t, s in zip(tops, oshapes):
                self.blob_shapes[t] = tuple(int(d) for d in s)
            if taints:
                tainted.update(tops)
            if getattr(impl, "is_input", lambda: False)():
                if overrides:
                    oshapes = [overrides.get(t, tuple(int(d) for d in s))
                               for t, s in zip(tops, oshapes)]
                    for t, s in zip(tops, oshapes):
                        self.blob_shapes[t] = tuple(int(d) for d in s)
                for t, s in zip(tops, oshapes):
                    self.input_blobs[t] = tuple(int(d) for d in s)

            # param sharing resolution — per ParamSpec entry, as in
            # net.cpp AppendParam (each named spec shares exactly one blob
            # with the first declarer of that name)
            specs = lp.param
            lr_mults = [ps.lr_mult for ps in specs]
            decay_mults = [ps.decay_mult for ps in specs]
            raw_refs: dict[int, tuple[str, int]] = {}
            for i, ps in enumerate(specs):
                if not ps.name:
                    continue
                owner = shared_owner.get(ps.name)
                if owner is None:
                    shared_owner[ps.name] = (lp.name, i)
                else:
                    raw_refs[i] = owner
            if lp.type == "BatchNorm":
                lr_mults = [0.0, 0.0, 0.0]
                decay_mults = [0.0, 0.0, 0.0]
            node = _LayerNode(
                lp=lp, impl=impl, bottoms=bottoms, tops=tops,
                param_key=lp.name, lr_mults=lr_mults, decay_mults=decay_mults,
            )
            if raw_refs:
                self._resolve_sharing(node, raw_refs)
            self.nodes.append(node)
            self._node_by_name[lp.name] = node

        # net outputs via Caffe's available-blob walk (net.cpp AppendTop/
        # AppendBottom: a bottom is erased from the available set, a top
        # re-inserted — so a trailing IN-PLACE layer's blob remains an
        # output, unlike a naive produced-minus-consumed difference).
        # Survivors are listed in FIRST-production order (stable for
        # consumers indexing output_blobs, e.g. classify.py), not Caffe's
        # reinsertion order.
        available: dict[str, None] = {}
        order: dict[str, None] = {}
        for n in self.nodes:
            for b in n.bottoms:
                available.pop(b, None)
            for t in n.tops:
                available[t] = None
                order[t] = None
        self.output_blobs = [t for t in order
                             if t in available and t not in self.input_blobs]
        unknown = set(overrides) - set(self.input_blobs)
        if unknown:
            raise ValueError(
                f"input_overrides for non-input blobs: {sorted(unknown)}")
        self._detect_hfuse_groups()
        self._detect_vfuse_chains()
        self._latch_tune_plan()
        self._fuse_skip_noted: set[str] = set()

    def _detect_hfuse_groups(self) -> None:
        """Horizontal fusion of sibling 1x1 convolutions (default ON,
        SPARKNET_NO_HFUSE=1 disables): inception blocks run 3 pointwise
        convs over the SAME input (bvlc_googlenet: 1x1 / 3x3_reduce /
        5x5_reduce per block), each too narrow to fill the MXU's 128-lane
        tiles.
        conv(x,W1) || conv(x,W2) == split(conv(x, concat(W1,W2))) exactly
        (per-output-channel reductions are untouched), so the executor
        can run ONE wider conv and slice — a TPU-shape optimization with
        no reference analog (the GPU reference gains nothing from it).
        Members must read the same VERSION of the bottom (in-place chains
        reassign names), hence the producer-version group key.

        The env toggle is read ONCE here (at Net construction): flipping
        SPARKNET_NO_HFUSE after the first jitted step can never retrace
        the cached executable, so a per-trace read would silently ignore
        the flip.  Per-Net-instance it is at least deterministic."""
        from ..ops.vision import conv_geometry
        from ..utils import knobs
        self._hfuse_enabled = knobs.raw("SPARKNET_NO_HFUSE") != "1"
        ver: dict[str, int] = {}
        groups: dict[tuple, list[_LayerNode]] = {}
        for node in self.nodes:
            if (node.lp.type == "Convolution" and len(node.bottoms) == 1
                    and len(node.tops) == 1):
                kh, kw, sh, sw, ph, pw, dh, dw, _, group, bias = \
                    conv_geometry(node.lp)
                if (kh, kw, sh, sw, ph, pw, dh, dw, group) == (
                        1, 1, 1, 1, 0, 0, 1, 1, 1):
                    b = node.bottoms[0]
                    groups.setdefault((b, ver.get(b, 0), bias),
                                      []).append(node)
            for t in node.tops:
                ver[t] = ver.get(t, 0) + 1
        # first member name -> all member nodes; later members -> stash
        self._hfuse_first: dict[str, list[_LayerNode]] = {}
        self._hfuse_member: set[str] = set()
        for members in groups.values():
            if len(members) >= 2:
                self._hfuse_first[members[0].lp.name] = members
                self._hfuse_member.update(m.lp.name for m in members[1:])

    def _detect_vfuse_chains(self) -> None:
        """Vertical conv+bias+relu(+pool/LRN) chain fusion, planned by
        ``graph/fusion.py`` from the SPARKNET_FUSE source (off | auto
        [default, profile-worklist-driven] | all | <plan.json>) —
        latched at Net construction like the hfuse toggle.  Runs AFTER
        hfuse detection: horizontal groups keep their members, vertical
        chains take what's left."""
        from . import fusion
        self._fuse_plan = fusion.resolve_plan(self)
        self._vfuse_head: dict[str, fusion.FusedChain] = {}
        self._vfuse_member: set[str] = set()
        if self._fuse_plan is None:
            return
        for ch in self._fuse_plan.chains:
            if not all(m in self._node_by_name for m in ch.members):
                continue   # plan from another net's namespace
            self._vfuse_head[ch.members[0]] = ch
            self._vfuse_member.update(ch.members[1:])

    def fuse_plan_id(self) -> str:
        """Short id of the active vertical-fusion plan (``off`` when
        none) — the perf-ledger fingerprint field that keeps fused and
        unfused captures out of each other's baseline bands."""
        plan = getattr(self, "_fuse_plan", None)
        return plan.plan_id() if plan is not None else "off"

    def _latch_tune_plan(self) -> None:
        """Resolve SPARKNET_TUNE ONCE at Net construction (the hfuse/
        vfuse latch discipline: flipping the env after jit never
        retraces) so a typo'd table path or a drifted/wrong-backend
        table fails HERE, loudly, not mid-training — and so the
        tune_plan fingerprint the ledger stamps is the table the traced
        lowerings actually consulted."""
        from . import tuner
        self._tune_plan_id = tuner.active_plan_id()

    def tune_plan_id(self) -> str:
        """Short id of the lowering-autotuner table active when this net
        was built (``off`` when none) — the perf-ledger fingerprint
        field that keeps tuned and untuned captures out of each other's
        baseline bands (graph/tuner.py)."""
        return getattr(self, "_tune_plan_id", "off")

    def _note_unfused_run(self, reason: str) -> None:
        """A fusable net executing unfused (ranged run, eps injection,
        blob introspection) used to be silent — a profile captured from
        such a run would pool into the fused baseline band.  One
        instant() per (net, reason) plus an always-on counter make the
        mislabel visible; trace-time cost only."""
        from ..utils import telemetry
        telemetry.get_registry().counter(
            "fusion_unfused_runs_total",
            "runs of a fusable net that skipped fusion").inc(
                reason=reason)
        if reason not in self._fuse_skip_noted:
            self._fuse_skip_noted.add(reason)
            telemetry.instant(
                "fusion.unfused_run", cat="graph", reason=reason,
                net=self.name or "?",
                hfuse_groups=len(getattr(self, "_hfuse_first", {})),
                vfuse_chains=len(getattr(self, "_vfuse_head", {})))

    @staticmethod
    def _check_batch_insensitive(lp, impl, bottoms, bshapes, tainted) -> None:
        """A consumer of Filter output sees a placeholder batch dim (the
        real one is data-dependent, filter_layer.cpp Reshape).  Reject only
        layers whose *parameter* shapes would change with that dim —
        standard layers (InnerProduct axis=1, Convolution, ...) size params
        off non-batch dims and stay valid eager."""
        def probe(shapes):
            return jax.eval_shape(lambda r: impl.init(r, lp, shapes),
                                  jax.ShapeDtypeStruct((2,), jnp.uint32))
        bumped = [tuple([s[0] + 1] + list(s[1:])) if b in tainted and s
                  else s for b, s in zip(bottoms, bshapes)]
        try:
            a, c = probe(bshapes), probe(bumped)
            sensitive = [x.shape for x in a] != [x.shape for x in c]
        except Exception:
            sensitive = bool(probe(bshapes))  # bump broke init: be strict
        if sensitive:
            raise ValueError(
                f"layer {lp.name!r} ({lp.type}) builds parameters from "
                f"blobs with a data-dependent batch dim (downstream of a "
                f"Filter layer) — its declared shapes are unreliable")

    def _probe_blob_shapes(self, node: _LayerNode) -> list[tuple[Shape, Any]]:
        """(shape, dtype) of each learnable blob without allocating them.
        Cached per layer — sharing-heavy graphs probe owners repeatedly."""
        cached = self._probe_cache.get(node.lp.name)
        if cached is not None:
            return cached
        bshapes = [self.blob_shapes[b] for b in node.bottoms]
        structs = jax.eval_shape(
            lambda r: node.impl.init(r, node.lp, bshapes),
            jax.ShapeDtypeStruct((2,), jnp.uint32))
        out = [(tuple(s.shape), s.dtype) for s in structs]
        self._probe_cache[node.lp.name] = out
        return out

    @staticmethod
    def _merge_shared_mult(node: _LayerNode, owner: _LayerNode,
                           i: int, oidx: int, attr: str, label: str) -> None:
        """net.cpp AppendParam lr_mult/decay_mult semantics for a shared
        blob: the sharer's explicit value propagates to the owner when the
        owner left it unset; both explicit and different is an error."""
        raw = f"raw_{label}"
        specs, ospecs = node.lp.param, owner.lp.param
        mine = getattr(specs[i], raw, None) if i < len(specs) else None
        if mine is None:
            return
        owners = getattr(ospecs[oidx], raw, None) if oidx < len(ospecs) else None
        if owners is None:
            mults = getattr(owner, attr)
            while len(mults) <= oidx:
                mults.append(1.0)
            mults[oidx] = mine
        elif owners != mine:
            raise ValueError(
                f"shared param {label} mismatch: layer {node.lp.name!r} "
                f"blob {i} sets {mine}, owner {owner.lp.name!r} blob {oidx} "
                f"sets {owners} (reference: net.cpp AppendParam CHECK)")

    def _resolve_sharing(self, node: _LayerNode,
                         raw_refs: dict[int, tuple[str, int]]) -> None:
        """Map each shared blob index to (owner key, owner stored position),
        validating shapes against the owner (net.cpp AppendParam CHECKs)."""
        mine = self._probe_blob_shapes(node)
        node.n_blobs = len(mine)
        for i, (oname, oidx) in raw_refs.items():
            if i >= len(mine):
                continue  # named spec beyond the layer's blob count
            owner = self._node_by_name.get(oname)
            if owner is None:
                raise ValueError(
                    f"layer {node.lp.name!r} shares param {i} with unknown "
                    f"layer {oname!r}")
            oshapes = self._probe_blob_shapes(owner)
            if oidx >= len(oshapes):
                raise ValueError(
                    f"layer {node.lp.name!r} param {i} shares blob {oidx} of "
                    f"{oname!r}, which has only {len(oshapes)} blobs")
            if oshapes[oidx][0] != mine[i][0]:
                raise ValueError(
                    f"shared param shape mismatch: {node.lp.name!r} blob {i} "
                    f"{mine[i][0]} vs owner {oname!r} blob {oidx} "
                    f"{oshapes[oidx][0]} (reference: net.cpp AppendParam)")
            self._merge_shared_mult(node, owner, i, oidx, "lr_mults", "lr_mult")
            self._merge_shared_mult(node, owner, i, oidx,
                                    "decay_mults", "decay_mult")
            # owner stored position: identity unless the owner itself shares
            opos = owner.own_map.get(oidx, oidx) if owner.shared_refs else oidx
            node.shared_refs[i] = (oname, opos)
        node.own_map = {
            i: pos for pos, i in enumerate(
                j for j in range(len(mine)) if j not in node.shared_refs)
        }

    # -- construction -----------------------------------------------------
    def init(self, rng: jax.Array) -> WeightCollection:
        """Create all learnable blobs with Caffe-filler init (the SetUp pass
        of reference net.cpp:73-133).  Shared blobs are created only by
        their owner layer."""
        params: WeightCollection = {}
        for node in self.nodes:
            rng, sub = jax.random.split(rng)
            bshapes = [self.blob_shapes[b] for b in node.bottoms]
            blobs = node.impl.init(sub, node.lp, bshapes)
            if not blobs:
                continue
            if node.shared_refs:
                own = [b for i, b in enumerate(blobs)
                       if i not in node.shared_refs]
                if own:
                    params[node.lp.name] = own
            else:
                params[node.lp.name] = list(blobs)
        return params

    def node_params(self, params: WeightCollection,
                    node: _LayerNode) -> list[jax.Array]:
        """Assemble the blob list a node sees, following shared refs."""
        if not node.shared_refs:
            return params.get(node.param_key, [])
        out = []
        for i in range(node.n_blobs or 0):
            ref = node.shared_refs.get(i)
            if ref is None:
                out.append(params[node.param_key][node.own_map[i]])
            else:
                out.append(params[ref[0]][ref[1]])
        return out

    def _scatter_node_params(self, params: dict, node: _LayerNode,
                             updated: Sequence[jax.Array]) -> None:
        """Write a node's (possibly shared) updated blobs back to owners."""
        if not node.shared_refs:
            params[node.param_key] = list(updated)
            return
        own = list(params.get(node.param_key, []))
        for i, b in enumerate(updated):
            ref = node.shared_refs.get(i)
            if ref is None:
                own[node.own_map[i]] = b
            else:
                oname, opos = ref
                oblobs = list(params[oname])
                oblobs[opos] = b
                params[oname] = oblobs
        if own:
            params[node.param_key] = own

    def lr_mult_tree(self, params: WeightCollection) -> WeightCollection:
        """Per-blob lr multipliers, same pytree structure as params
        (ParamSpec.lr_mult, reference: caffe.proto ParamSpec)."""
        return self._mult_tree(params, "lr_mults", 1.0)

    def decay_mult_tree(self, params: WeightCollection) -> WeightCollection:
        return self._mult_tree(params, "decay_mults", 1.0)

    def _mult_tree(self, params, attr, default):
        out: WeightCollection = {}
        by_name = {n.lp.name: n for n in self.nodes}
        for key, blobs in params.items():
            node = by_name.get(key)
            mults = getattr(node, attr, []) if node is not None else []
            if node is not None and node.shared_refs:
                # stored position -> original blob index (storage compacts
                # away shared blobs)
                orig = {pos: i for i, pos in node.own_map.items()}
                idxs = [orig.get(p, p) for p in range(len(blobs))]
            else:
                idxs = list(range(len(blobs)))
            out[key] = [
                jnp.asarray(mults[i] if i < len(mults) else default)
                for i in idxs
            ]
        return out

    # -- execution --------------------------------------------------------
    def apply(self, params: WeightCollection, inputs: Mapping[str, jax.Array],
              *, train: bool | None = None, rng: jax.Array | None = None,
              ) -> NetOutputs:
        """One forward pass.  ``inputs`` binds every input blob (data-layer
        top).  Returns net outputs, the weighted loss sum, and params with
        any forward-state updates (BatchNorm running stats) applied."""
        blobs, loss, new_params = self._run(params, inputs, train, rng)
        out = {t: blobs[t] for t in self.output_blobs}
        return NetOutputs(blobs=out, loss=loss, params=new_params)

    def apply_all(self, params, inputs, *, train=None, rng=None,
                  upto: str | None = None,
                  eps: Mapping[str, jax.Array] | None = None,
                  start: str | None = None,
                  ) -> dict[str, jax.Array]:
        """Forward returning every intermediate blob (debug; the analog of
        reading arbitrary blobs over the reference's FFI introspection,
        libccaffe/ccaffe.cpp:86-139).  ``upto`` stops execution after the
        named layer (pycaffe's ``forward(end=...)`` truncation).  ``start``
        begins execution AT the named layer (pycaffe ``forward(start=...)``,
        pycaffe.py:105): layers before it are skipped and every bottom they
        would have produced must be supplied in ``inputs``.  ``eps`` maps
        blob names to zero-valued perturbations added at each blob's final
        assignment — differentiating w.r.t. them yields d(out)/d(blob) for
        INTERMEDIATE blobs (pycaffe ``backward(diffs=[...])``)."""
        for nm, which in ((upto, "upto"), (start, "start")):
            if nm is not None and nm not in self._node_by_name:
                raise ValueError(
                    f"unknown layer {nm!r} for {which}= "
                    f"(layers: {self.layer_names()})")
        blobs, _, _ = self._run(params, inputs, train, rng, upto=upto,
                                eps=eps, start=start, introspect=True)
        return blobs

    def _cast(self, arrs, dtype):
        """Cast floating arrays for mixed-precision compute; ints (labels,
        indices) pass through."""
        return [a.astype(dtype)
                if jnp.issubdtype(jnp.asarray(a).dtype, jnp.floating) else a
                for a in arrs]

    def _run(self, params, inputs, train, rng, upto: str | None = None,
             eps: Mapping[str, jax.Array] | None = None,
             start: str | None = None, introspect: bool = False):
        """The layer-by-layer forward shared by apply/apply_all.

        With ``compute_dtype`` set (bf16 on TPU), params and activations
        are cast per layer for MXU-rate matmuls while master params, BN
        state updates, loss layers, and the loss sum stay float32 — the
        standard mixed-precision recipe (params stay f32; casts are
        differentiable, so grads flow back in f32)."""
        if train is None:
            train = self.state.phase == Phase.TRAIN
        start_i = 0
        if start is not None:
            start_i = next(i for i, n in enumerate(self.nodes)
                           if n.lp.name == start)
        stop_i = len(self.nodes) - 1
        if upto is not None:
            ui = next((i for i, n in enumerate(self.nodes)
                       if n.lp.name == upto), None)
            if ui is not None:
                if ui < start_i:
                    raise ValueError(
                        f"start={start!r} comes after upto={upto!r}")
                stop_i = ui
        # the nodes this run actually executes — rng validation and eps
        # placement must see the RANGE, not the whole net
        active = self.nodes[start_i:stop_i + 1]
        if rng is None and any(n.impl.needs_rng(n.lp, train) for n in active):
            raise ValueError(
                f"net {self.name!r} needs an rng in this mode "
                f"(stochastic layer present)")
        if start is None:
            for name in self.input_blobs:
                if name not in inputs:
                    raise ValueError(f"missing input blob {name!r}")
        blobs: dict[str, jax.Array] = dict(inputs)
        new_params = dict(params)
        cd = self.compute_dtype
        loss = jnp.zeros((), jnp.float32)
        # eps injection point: a blob's FINAL assignment WITHIN the
        # executed range (in-place chains reassign; Caffe's per-blob diff
        # is the diff at the final value the run actually produced — a
        # producer outside [start, upto] never runs and must not claim
        # the injection)
        last_producer: dict[str, str] = {}
        if eps:
            for n in active:
                for t in n.tops:
                    if t in eps:
                        last_producer[t] = n.lp.name
        started = start is None
        # fusion runs on full-net, non-introspected runs only (ranged
        # runs and eps injection keep the plain per-layer path, and
        # apply_all must surface REAL intermediate blobs).  Horizontal
        # 1x1-sibling fusion: on by default (exact transform, measured
        # -5.6% GoogLeNet step), SPARKNET_NO_HFUSE=1 restores per-layer
        # execution.  Vertical chains: planned per SPARKNET_FUSE
        # (graph/fusion.py).  Both latched at Net construction.
        full_run = start is None and upto is None and not eps \
            and not introspect
        hfuse_on = (bool(self._hfuse_first) and full_run
                    and self._hfuse_enabled)
        vfuse_on = bool(self._vfuse_head) and full_run
        if not full_run and (
                (self._hfuse_first and self._hfuse_enabled)
                or self._vfuse_head):
            # a fusable net running unfused must not be silent — a
            # profile captured from this run is NOT the fused baseline
            self._note_unfused_run(
                "ranged" if (start is not None or upto is not None)
                else "eps" if eps else "introspect")
        hstash: dict[str, jax.Array] = {}
        for ni, node in enumerate(self.nodes):
            if not started:
                if node.lp.name != start:
                    continue
                started = True
            if getattr(node.impl, "is_input", lambda: False)():
                # Input-type layers still honor upto= (their tops are the
                # bound inputs; nothing to execute)
                if upto is not None and node.lp.name == upto:
                    break
                continue
            if vfuse_on and node.lp.name in self._vfuse_member:
                # executed inside its chain head's fused block; its
                # intermediate blob is single-consumer by legality
                # (graph/fusion.py), so nothing downstream misses it
                continue
            missing = [b for b in node.bottoms if b not in blobs]
            if missing:
                raise ValueError(
                    f"layer {node.lp.name!r} needs blobs {missing}; with "
                    f"start={start!r} every bottom produced before the "
                    f"start layer must be fed in inputs")
            layer_rng = None
            if rng is not None and node.impl.needs_rng(node.lp, train):
                # per-node identity fold, NOT sequential splits: a ranged
                # run (start=/upto=) must give each layer the same stream
                # the full forward gave it, so ranged backward replays the
                # masks its forward actually used
                layer_rng = jax.random.fold_in(rng, ni)
            stateful = getattr(node.impl, "has_state", False)
            if vfuse_on and node.lp.name in self._vfuse_head:
                ch = self._vfuse_head[node.lp.name]
                members = [self._node_by_name[m] for m in ch.members]
                assert not any(
                    getattr(m.impl, "has_state", False)
                    or m.impl.needs_rng(m.lp, train)
                    or any(w for w in m.loss_weights())
                    for m in members), (
                    f"vfuse chain {ch.scope()!r} admitted a stateful/"
                    f"rng/loss member; fix graph/fusion.py legality")
                final = self._apply_fused_chain(ch, members, new_params,
                                                blobs, cd, train)
                blobs[members[-1].tops[0]] = final
                continue
            if hfuse_on and node.lp.name in self._hfuse_member:
                # sibling 1x1 conv: its slice of the fused conv was
                # stashed when the group's first member ran
                tops = [hstash.pop(node.lp.name)]
            elif hfuse_on and node.lp.name in self._hfuse_first:
                members = self._hfuse_first[node.lp.name]
                # the fused path passes rng=None and skips stateful/
                # is_loss handling for EVERY member (non-first members
                # are served from hstash) — sound only while detection
                # admits nothing but stateless, rng-free Convolutions
                assert not any(
                    getattr(m.impl, "has_state", False)
                    or m.impl.needs_rng(m.lp, train)
                    for m in members), (
                    f"hfuse group of {node.lp.name!r} admitted a "
                    f"stateful/rng layer; fix _detect_hfuse_groups")
                mp = [self.node_params(new_params, m) for m in members]
                sizes = [p0[0].shape[0] for p0 in mp]
                fused = [jnp.concatenate([p0[0] for p0 in mp], axis=0)]
                if len(mp[0]) > 1:  # bias_term (uniform within a group)
                    fused.append(jnp.concatenate([p0[1] for p0 in mp],
                                                 axis=0))
                bots = [blobs[node.bottoms[0]]]
                if cd is not None:
                    bots = self._cast(bots, cd)
                    fused = self._cast(fused, cd)
                cuts, acc = [], 0
                for s in sizes[:-1]:
                    acc += s
                    cuts.append(acc)
                scope = "+".join(m.lp.name for m in members)
                with jax.named_scope(f"L[{scope}]"):
                    (y,) = node.impl.apply(node.lp, fused, bots, train,
                                           None)
                    parts = jnp.split(y, cuts, axis=1)
                for m, part in zip(members[1:], parts[1:]):
                    hstash[m.lp.name] = part
                tops = [parts[0]]
            else:
                p = self.node_params(new_params, node)
                bots = [blobs[b] for b in node.bottoms]
                if cd is not None:
                    if (node.impl.is_loss() or node.lp.type == "Accuracy"
                            or stateful):
                        # numerics-critical: losses, accuracy, BN batch
                        # stats
                        bots = self._cast(bots, jnp.float32)
                    else:
                        bots = self._cast(bots, cd)
                        p = self._cast(p, cd)
                # named scope: XLA op metadata carries "L[<layer>]"
                # through fwd AND the AD transpose, so profiler traces
                # attribute device time per layer (tools/profile_step.py
                # --by-layer — the `caffe time` per-layer view, reference:
                # caffe/tools/caffe.cpp:290-376, but post-fusion
                # on-device)
                with jax.named_scope(f"L[{node.lp.name}]"):
                    result = node.impl.apply(node.lp, p, bots, train,
                                             layer_rng)
                if stateful:
                    tops, updated = result
                    self._scatter_node_params(new_params, node, updated)
                else:
                    tops = result
            if eps:
                tops = [v + eps[t]
                        if last_producer.get(t) == node.lp.name else v
                        for t, v in zip(node.tops, tops)]
            for t, v in zip(node.tops, tops):
                blobs[t] = v
            # loss accumulation (reference: Layer::SetLossWeights +
            # Net::Forward summing weighted tops)
            for w, v in zip(node.loss_weights(), tops):
                if w:
                    # f32 accumulation even when the top was computed in a
                    # reduced compute_dtype (loss_weight on non-loss layers)
                    loss = loss + w * jnp.sum(v.astype(jnp.float32))
            if upto is not None and node.lp.name == upto:
                break
        return blobs, loss, new_params

    def _apply_fused_chain(self, ch, members, params, blobs, cd, train):
        """Execute one planned vertical chain as a single block.

        The head conv runs through its own impl (XLA's MXU tiling is
        already optimal; on eligible stems that includes the
        space-to-depth rewrite).  An LRN tail with a fused epilogue
        collapses [ReLU+]LRN into ``ops.vision.lrn_chain_epilogue`` —
        the Pallas one-VMEM-trip kernel on TPU, the scale-residual
        custom-VJP reference elsewhere.  Every other member applies its
        own impl inside the shared ``L[a+b+...]`` scope, so the whole
        chain profiles as ONE row (the post-fusion view perfwatch's
        worklist consumes) and those segments stay bit-identical to
        per-layer execution."""
        from ..ops.vision import lrn_chain_epilogue, lrn_geometry
        head = members[0]
        x = blobs[head.bottoms[0]]
        p = self.node_params(params, head)
        if cd is not None:
            x = self._cast([x], cd)[0]
            p = self._cast(p, cd)
        with jax.named_scope(f"L[{ch.scope()}]"):
            (y,) = head.impl.apply(head.lp, p, [x], train, None)
            i = 1
            while i < len(members):
                m = members[i]
                nxt = members[i + 1] if i + 1 < len(members) else None
                if (ch.epilogue == "relu+lrn" and m.lp.type == "ReLU"
                        and nxt is not None and nxt.lp.type == "LRN"):
                    size, alpha, beta, k, _ = lrn_geometry(nxt.lp)
                    y = lrn_chain_epilogue(y, size, alpha, beta, k,
                                           relu=True)
                    i += 2
                    continue
                if (ch.epilogue in ("lrn", "relu+lrn")
                        and m.lp.type == "LRN" and nxt is None):
                    size, alpha, beta, k, _ = lrn_geometry(m.lp)
                    y = lrn_chain_epilogue(y, size, alpha, beta, k,
                                           relu=False)
                    i += 1
                    continue
                mp = self.node_params(params, m)
                if cd is not None:
                    mp = self._cast(mp, cd)
                (y,) = m.impl.apply(m.lp, mp, [y], train, None)
                i += 1
        return y

    # -- introspection (FFI-parity helpers; reference: ccaffe.cpp:86-139,
    #    Net.scala:64-66) --------------------------------------------------
    @property
    def num_layers(self) -> int:
        return len(self.nodes)

    def layer_names(self) -> list[str]:
        return [n.lp.name for n in self.nodes]

    def layer_num_weights(self, params: WeightCollection) -> dict[str, int]:
        return {k: len(v) for k, v in params.items()}


# -- WeightCollection math (reference: Net.scala:17-46) ---------------------

def weights_add(a: WeightCollection, b: WeightCollection) -> WeightCollection:
    """Elementwise sum — WeightCollection.add (reference: Net.scala:27-46)."""
    return jax.tree_util.tree_map(jnp.add, a, b)


def weights_scalar_divide(w: WeightCollection, v: float) -> WeightCollection:
    """In the reference this is in-place (Net.scala:17-23); pure here."""
    return jax.tree_util.tree_map(lambda x: x / v, w)
