"""Minimal ``caffe`` module shim so pycaffe-style user layers import
unmodified (reference: caffe/python/caffe/__init__.py surface that
Python-layer modules actually touch — ``caffe.Layer`` plus the phase
constants; e.g. examples/pycaffe/layers/pyloss.py does ``import caffe``
and subclasses ``caffe.Layer``).

Usage::

    from sparknet_tpu import pycaffe_compat
    pycaffe_compat.install()          # sys.modules.setdefault("caffe", ...)

after which ``import caffe`` resolves to this shim unless a real pycaffe
is already importable (the real one always wins).
"""

from __future__ import annotations

import sys

TRAIN = 0
TEST = 1


class Layer:
    """Base class for user Python layers (python_layer.hpp analog).

    Subclasses override ``setup/reshape/forward/backward`` operating on
    blob lists whose elements expose ``.data``/``.diff`` numpy buffers
    (see ops/python_layer.PyBlob).  ``self.param_str`` carries
    ``python_param.param_str``; ``self.blobs`` is a plain list a layer
    may fill in ``setup`` (ParameterLayer-style state is better expressed
    through the functional protocol's ``init_params``)."""

    param_str: str = ""

    def __init__(self):
        self.blobs: list = []

    def setup(self, bottom, top):
        pass

    def reshape(self, bottom, top):
        pass

    def forward(self, bottom, top):
        raise NotImplementedError

    def backward(self, top, propagate_down, bottom):
        pass


def install() -> None:
    """Make ``import caffe`` resolve to this shim if no real pycaffe is
    installed.  Idempotent; never shadows an importable real caffe."""
    if "caffe" in sys.modules:
        return
    try:
        import importlib.util
        if importlib.util.find_spec("caffe") is not None:
            return
    except (ImportError, ValueError):
        pass
    sys.modules["caffe"] = sys.modules[__name__]
