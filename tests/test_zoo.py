"""Model-zoo compatibility pins.

Every net/solver prototxt shipped with the reference (caffe/models + the
caffe/examples tutorials) must keep loading through the prototxt front end
and — for the net files — building and forward-running through the graph
compiler.  This freezes the compatibility the reference gets for free from
its protobuf schema (reference: caffe/src/caffe/proto/caffe.proto) so a
parser or shape-inference regression fails loudly.

The data-layer swap mirrors the reference apps' ProtoLoader.replaceDataLayers
(reference: src/main/scala/libs/ProtoLoader.scala:50-57); deploy files run
from their own net-level input declarations.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sparknet_tpu.graph import Net
from sparknet_tpu.proto import (
    NetState,
    Phase,
    load_net_prototxt,
    load_solver_prototxt,
    replace_data_layers,
)

REF = "/root/reference/caffe"

# train/test net prototxts: path -> (channels, height, width) fed after the
# data-layer swap.  Geometry is what the reference apps feed each model
# (crop_size from transform_param where present).
TRAIN_NETS = {
    "examples/cifar10/cifar10_quick_train_test.prototxt": (3, 32, 32),
    "examples/cifar10/cifar10_full_train_test.prototxt": (3, 32, 32),
    "examples/cifar10/cifar10_full_java_train_test.prototxt": (3, 32, 32),
    "examples/cifar10/cifar10_full_sigmoid_train_test.prototxt": (3, 32, 32),
    "examples/cifar10/cifar10_full_sigmoid_train_test_bn.prototxt": (3, 32, 32),
    "examples/mnist/lenet_train_test.prototxt": (1, 28, 28),
    "examples/mnist/mnist_autoencoder.prototxt": (1, 28, 28),
    "examples/siamese/mnist_siamese_train_test.prototxt": (2, 28, 28),
    "examples/hdf5_classification/train_val.prototxt": (4, 1, 1),
    "examples/hdf5_classification/nonlinear_train_val.prototxt": (4, 1, 1),
    "examples/hdf5_classification/nonlinear_auto_train.prototxt": (4, 1, 1),
    "examples/hdf5_classification/nonlinear_auto_test.prototxt": (4, 1, 1),
    "models/bvlc_alexnet/train_val.prototxt": (3, 227, 227),
    "models/bvlc_reference_caffenet/train_val.prototxt": (3, 227, 227),
    "models/bvlc_googlenet/train_val.prototxt": (3, 224, 224),
    "models/finetune_flickr_style/train_val.prototxt": (3, 227, 227),
    "examples/finetune_pascal_detection/pascal_finetune_trainval_test.prototxt":
        (3, 227, 227),
    "examples/feature_extraction/imagenet_val.prototxt": (3, 227, 227),
}

# deploy-style nets: run straight from their input declarations.
DEPLOY_NETS = [
    "examples/mnist/lenet.prototxt",
    "examples/cifar10/cifar10_quick.prototxt",
    "examples/cifar10/cifar10_full.prototxt",
    "examples/net_surgery/conv.prototxt",
    "examples/siamese/mnist_siamese.prototxt",
    "models/bvlc_alexnet/deploy.prototxt",
    "models/bvlc_reference_caffenet/deploy.prototxt",
    "models/bvlc_googlenet/deploy.prototxt",
    "models/bvlc_reference_rcnn_ilsvrc13/deploy.prototxt",
    "models/finetune_flickr_style/deploy.prototxt",
    "examples/net_surgery/bvlc_caffenet_full_conv.prototxt",
]

# nets whose user-defined Python layers resolve through the pycaffe-compat
# adapter: built raw (DummyData feeds itself; python_param.module imports
# from the reference's examples/pycaffe/layers on sys.path).
PYLAYER_NETS = [
    "examples/pycaffe/linreg.prototxt",
]

SOLVERS = [
    "examples/cifar10/cifar10_quick_solver.prototxt",
    "examples/cifar10/cifar10_quick_solver_lr1.prototxt",
    "examples/cifar10/cifar10_full_solver.prototxt",
    "examples/cifar10/cifar10_full_solver_lr1.prototxt",
    "examples/cifar10/cifar10_full_solver_lr2.prototxt",
    "examples/cifar10/cifar10_full_java_solver.prototxt",
    "examples/cifar10/cifar10_full_sigmoid_solver.prototxt",
    "examples/cifar10/cifar10_full_sigmoid_solver_bn.prototxt",
    "examples/mnist/lenet_solver.prototxt",
    "examples/mnist/lenet_solver_adam.prototxt",
    "examples/mnist/lenet_solver_rmsprop.prototxt",
    "examples/mnist/lenet_adadelta_solver.prototxt",
    "examples/mnist/lenet_auto_solver.prototxt",
    "examples/mnist/lenet_multistep_solver.prototxt",
    "examples/mnist/lenet_stepearly_solver.prototxt",
    "examples/mnist/lenet_consolidated_solver.prototxt",  # V1 `layers` net
    "examples/mnist/mnist_autoencoder_solver.prototxt",
    "examples/mnist/mnist_autoencoder_solver_adadelta.prototxt",
    "examples/mnist/mnist_autoencoder_solver_adagrad.prototxt",
    "examples/mnist/mnist_autoencoder_solver_nesterov.prototxt",
    "examples/siamese/mnist_siamese_solver.prototxt",
    "examples/hdf5_classification/solver.prototxt",
    "examples/hdf5_classification/nonlinear_solver.prototxt",
    "examples/finetune_pascal_detection/pascal_finetune_solver.prototxt",
    "models/bvlc_alexnet/solver.prototxt",
    "models/bvlc_reference_caffenet/solver.prototxt",
    "models/bvlc_googlenet/solver.prototxt",
    "models/bvlc_googlenet/quick_solver.prototxt",
    "models/finetune_flickr_style/solver.prototxt",
]

# nets too large to forward on the CPU test rig every run — build/init only.
BUILD_ONLY = {
    "models/bvlc_alexnet/train_val.prototxt",
    "models/bvlc_reference_caffenet/train_val.prototxt",
    "models/bvlc_googlenet/train_val.prototxt",
    "models/finetune_flickr_style/train_val.prototxt",
    "examples/finetune_pascal_detection/pascal_finetune_trainval_test.prototxt",
    "examples/feature_extraction/imagenet_val.prototxt",
    "models/bvlc_alexnet/deploy.prototxt",
    "models/bvlc_reference_caffenet/deploy.prototxt",
    "models/bvlc_googlenet/deploy.prototxt",
    "models/bvlc_reference_rcnn_ilsvrc13/deploy.prototxt",
    "models/finetune_flickr_style/deploy.prototxt",
    "examples/net_surgery/bvlc_caffenet_full_conv.prototxt",
}


def _read(rel):
    with open(os.path.join(REF, rel)) as f:
        return f.read()


def test_zoo_inventory_complete():
    """Every .prototxt in the reference tree is classified above."""
    import glob
    known = (set(TRAIN_NETS) | set(DEPLOY_NETS) | set(PYLAYER_NETS)
             | set(SOLVERS))
    found = set()
    for root in ("models", "examples"):
        for p in glob.glob(os.path.join(REF, root, "**", "*.prototxt"),
                           recursive=True):
            found.add(os.path.relpath(p, REF))
    missing = found - known
    assert not missing, f"unclassified zoo prototxts: {sorted(missing)}"


@pytest.mark.parametrize("rel", sorted(TRAIN_NETS), ids=lambda r: r)
def test_train_net_builds(rel):
    c, h, w = TRAIN_NETS[rel]
    netp = load_net_prototxt(_read(rel))
    netp = replace_data_layers(netp, train_batch_size=2, test_batch_size=2,
                               channels=c, height=h, width=w)
    net = Net(netp, NetState(Phase.TRAIN))
    params = net.init(jax.random.PRNGKey(0))
    if rel in BUILD_ONLY:
        assert net.blob_shapes  # shape inference completed
        return
    inputs = {}
    for name, shape in net.input_blobs.items():
        if name == "label" or name.startswith("sim"):
            inputs[name] = jnp.zeros(shape)
        else:
            inputs[name] = jnp.asarray(
                np.random.default_rng(0).normal(size=shape).astype(np.float32))
    out = net.apply(params, inputs, rng=jax.random.PRNGKey(1))
    assert np.isfinite(float(out.loss))


@pytest.mark.parametrize("rel", sorted(DEPLOY_NETS), ids=lambda r: r)
def test_deploy_net_builds(rel):
    netp = load_net_prototxt(_read(rel))
    # shrink declared batch to 1 to keep the CPU rig fast
    for s in netp.input_shape:
        if len(s.dim) >= 1:
            s.dim[0] = 1
    net = Net(netp, NetState(Phase.TEST))
    params = net.init(jax.random.PRNGKey(0))
    if rel in BUILD_ONLY:
        assert net.blob_shapes
        return
    inputs = {
        name: jnp.zeros(shape) for name, shape in net.input_blobs.items()
    }
    blobs = net.apply_all(params, inputs)
    assert all(np.all(np.isfinite(np.asarray(v))) for v in blobs.values())


@pytest.mark.parametrize("rel", sorted(PYLAYER_NETS), ids=lambda r: r)
def test_python_layer_net_runs(rel):
    """Nets with ``Python`` layers build and train-step end-to-end: the
    adapter resolves python_param {module, layer} against the reference's
    own pycaffe example layers (reference: layer_factory.cpp Python
    registration; examples/pycaffe/linreg.prototxt)."""
    import sys

    from sparknet_tpu import pycaffe_compat
    pycaffe_compat.install()
    layers_dir = os.path.join(REF, "examples/pycaffe/layers")
    if layers_dir not in sys.path:
        sys.path.insert(0, layers_dir)
    netp = load_net_prototxt(_read(rel))
    net = Net(netp, NetState(Phase.TRAIN))
    params = net.init(jax.random.PRNGKey(0))
    out = net.apply(params, {}, rng=jax.random.PRNGKey(1))
    assert np.isfinite(float(out.loss))
    # and the Python loss is differentiable end-to-end (autodiff through
    # the pure_callback custom_vjp)
    def loss_fn(p):
        return net.apply(p, {}, rng=jax.random.PRNGKey(1)).loss
    grads = jax.grad(loss_fn)(params)
    flat = jax.tree_util.tree_leaves(grads)
    assert any(float(np.max(np.abs(np.asarray(g)))) > 0 for g in flat)


@pytest.mark.parametrize("rel", sorted(SOLVERS), ids=lambda r: r)
def test_solver_parses(rel):
    sp = load_solver_prototxt(_read(rel))
    assert sp.base_lr > 0
    assert sp.lr_policy in {"fixed", "step", "exp", "inv", "multistep",
                            "poly", "sigmoid", "stepearly"}


@pytest.mark.parametrize("rel", sorted(list(TRAIN_NETS) + DEPLOY_NETS
                                       + PYLAYER_NETS))
def test_zoo_serialize_roundtrip(rel):
    """Every zoo prototxt survives load -> to_pmsg -> serialize -> reload
    with the same layer structure — the write half (save_net_prototxt /
    upgrade tools) exercised over every real prototxt construct,
    including V0/V1-format files which round-trip as upgraded V2."""
    from sparknet_tpu.proto import save_net_prototxt

    net = load_net_prototxt(os.path.join(REF, rel))
    back = load_net_prototxt(save_net_prototxt(net))
    assert [l.name for l in back.layer] == [l.name for l in net.layer]
    assert [l.type for l in back.layer] == [l.type for l in net.layer]
    assert [l.bottom for l in back.layer] == [l.bottom for l in net.layer]
    assert [l.top for l in back.layer] == [l.top for l in net.layer]
    for a, b in zip(net.layer, back.layer):
        assert a.params == b.params, a.name
        assert [(r.phase, r.stage) for r in a.include] == \
            [(r.phase, r.stage) for r in b.include], a.name
        assert [(r.phase, r.stage) for r in a.exclude] == \
            [(r.phase, r.stage) for r in b.exclude], a.name
        assert [(p.name, p.raw_lr_mult, p.raw_decay_mult)
                for p in a.param] == \
            [(p.name, p.raw_lr_mult, p.raw_decay_mult)
             for p in b.param], a.name
        assert a.loss_weight == b.loss_weight and a.phase == b.phase
    assert back.input == net.input
    assert [s.dim for s in back.input_shape] == \
        [s.dim for s in net.input_shape]
