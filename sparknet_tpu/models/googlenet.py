"""GoogLeNet (Inception v1) — the deep fan-out stress model.

Architecture per the reference zoo (reference:
caffe/models/bvlc_googlenet/train_val.prototxt; published top-1 68.7%,
readme.md:19-20; fwd/bwd baseline 562.8/1123.8 ms @ batch 128 on K40+cuDNN,
readme.md:24-27).  Inception fan-out exercises what the reference needed
``InsertSplits`` for (caffe/src/caffe/util/insert_splits.cpp) — here value
reuse in the functional graph handles it.

Includes the two auxiliary classifiers (loss1/loss2, weight 0.3) attached
after inception_4a and 4d, train-phase only.
"""

from __future__ import annotations

from ..proto.caffe_pb import LayerParameter, NetParameter, Phase
from .dsl import (
    accuracy_layer, concat_layer, convolution_layer, dropout_layer,
    inner_product_layer, java_data_layer, layer, lrn_layer, net_param,
    pooling_layer, relu_layer, softmax_with_loss_layer,
)

_LRB = [{"lr_mult": 1.0, "decay_mult": 1.0}, {"lr_mult": 2.0, "decay_mult": 0.0}]
_XAVIER = {"type": "xavier"}
_B02 = {"type": "constant", "value": 0.2}


def _conv_relu(name: str, bottom: str, num_output: int, kernel: int,
               pad: int = 0, stride: int = 1) -> list[LayerParameter]:
    return [
        convolution_layer(name, bottom, name, num_output=num_output,
                          kernel=kernel, pad=pad, stride=stride,
                          weight_filler=_XAVIER, bias_filler=_B02, param=_LRB),
        relu_layer(f"{name}/relu", name),
    ]


def _inception(name: str, bottom: str, n1x1: int, n3x3r: int, n3x3: int,
               n5x5r: int, n5x5: int, npool: int) -> list[LayerParameter]:
    p = f"inception_{name}"
    layers: list[LayerParameter] = []
    layers += _conv_relu(f"{p}/1x1", bottom, n1x1, 1)
    layers += _conv_relu(f"{p}/3x3_reduce", bottom, n3x3r, 1)
    layers += _conv_relu(f"{p}/3x3", f"{p}/3x3_reduce", n3x3, 3, pad=1)
    layers += _conv_relu(f"{p}/5x5_reduce", bottom, n5x5r, 1)
    layers += _conv_relu(f"{p}/5x5", f"{p}/5x5_reduce", n5x5, 5, pad=2)
    layers.append(pooling_layer(f"{p}/pool", bottom, f"{p}/pool", pool="MAX",
                                kernel=3, stride=1, pad=1))
    layers += _conv_relu(f"{p}/pool_proj", f"{p}/pool", npool, 1)
    layers.append(concat_layer(f"{p}/output",
                               [f"{p}/1x1", f"{p}/3x3", f"{p}/5x5", f"{p}/pool_proj"],
                               f"{p}/output"))
    return layers


def _aux_classifier(tag: str, bottom: str) -> list[LayerParameter]:
    """Train-only auxiliary head, loss_weight 0.3."""
    p = f"loss{tag}"
    head = [
        pooling_layer(f"{p}/ave_pool", bottom, f"{p}/ave_pool", pool="AVE",
                      kernel=5, stride=3),
        *_conv_relu(f"{p}/conv", f"{p}/ave_pool", 128, 1),
        inner_product_layer(f"{p}/fc", f"{p}/conv", f"{p}/fc", num_output=1024,
                            weight_filler=_XAVIER, bias_filler=_B02, param=_LRB),
        relu_layer(f"{p}/relu_fc", f"{p}/fc"),
        dropout_layer(f"{p}/drop_fc", f"{p}/fc", ratio=0.7),
        inner_product_layer(f"{p}/classifier", f"{p}/fc", f"{p}/classifier",
                            num_output=1000, weight_filler=_XAVIER,
                            bias_filler={"type": "constant"}, param=_LRB),
    ]
    loss = layer(f"{p}/loss", "SoftmaxWithLoss",
                 [f"{p}/classifier", "label"], [f"{p}/loss1"],
                 phase=Phase.TRAIN)
    loss.loss_weight = [0.3]
    for l in head:
        l.phase = Phase.TRAIN
    return head + [loss]


def googlenet(train_batch: int = 32, test_batch: int = 50,
              crop: int = 224) -> NetParameter:
    layers: list[LayerParameter] = [
        java_data_layer("data_train", ["data", "label"], Phase.TRAIN,
                        (train_batch, 3, crop, crop), (train_batch,)),
        java_data_layer("data_test", ["data", "label"], Phase.TEST,
                        (test_batch, 3, crop, crop), (test_batch,)),
        *_conv_relu("conv1/7x7_s2", "data", 64, 7, pad=3, stride=2),
        pooling_layer("pool1/3x3_s2", "conv1/7x7_s2", "pool1/3x3_s2",
                      pool="MAX", kernel=3, stride=2),
        lrn_layer("pool1/norm1", "pool1/3x3_s2", "pool1/norm1",
                  local_size=5, alpha=1e-4, beta=0.75),
        *_conv_relu("conv2/3x3_reduce", "pool1/norm1", 64, 1),
        *_conv_relu("conv2/3x3", "conv2/3x3_reduce", 192, 3, pad=1),
        lrn_layer("conv2/norm2", "conv2/3x3", "conv2/norm2",
                  local_size=5, alpha=1e-4, beta=0.75),
        pooling_layer("pool2/3x3_s2", "conv2/norm2", "pool2/3x3_s2",
                      pool="MAX", kernel=3, stride=2),
        *_inception("3a", "pool2/3x3_s2", 64, 96, 128, 16, 32, 32),
        *_inception("3b", "inception_3a/output", 128, 128, 192, 32, 96, 64),
        pooling_layer("pool3/3x3_s2", "inception_3b/output", "pool3/3x3_s2",
                      pool="MAX", kernel=3, stride=2),
        *_inception("4a", "pool3/3x3_s2", 192, 96, 208, 16, 48, 64),
        *_aux_classifier("1", "inception_4a/output"),
        *_inception("4b", "inception_4a/output", 160, 112, 224, 24, 64, 64),
        *_inception("4c", "inception_4b/output", 128, 128, 256, 24, 64, 64),
        *_inception("4d", "inception_4c/output", 112, 144, 288, 32, 64, 64),
        *_aux_classifier("2", "inception_4d/output"),
        *_inception("4e", "inception_4d/output", 256, 160, 320, 32, 128, 128),
        pooling_layer("pool4/3x3_s2", "inception_4e/output", "pool4/3x3_s2",
                      pool="MAX", kernel=3, stride=2),
        *_inception("5a", "pool4/3x3_s2", 256, 160, 320, 32, 128, 128),
        *_inception("5b", "inception_5a/output", 384, 192, 384, 48, 128, 128),
        pooling_layer("pool5/7x7_s1", "inception_5b/output", "pool5/7x7_s1",
                      pool="AVE", kernel=7, stride=1),
        dropout_layer("pool5/drop_7x7_s1", "pool5/7x7_s1", ratio=0.4),
        inner_product_layer("loss3/classifier", "pool5/7x7_s1",
                            "loss3/classifier", num_output=1000,
                            weight_filler=_XAVIER,
                            bias_filler={"type": "constant"}, param=_LRB),
        softmax_with_loss_layer("loss3/loss3", ["loss3/classifier", "label"],
                                top="loss3/loss3"),
        accuracy_layer("loss3/top-1", ["loss3/classifier", "label"],
                       top="loss3/top-1", phase=Phase.TEST),
        accuracy_layer("loss3/top-5", ["loss3/classifier", "label"],
                       top="loss3/top-5", top_k=5, phase=Phase.TEST),
    ]
    return net_param("GoogleNet", layers)
