"""The committed example scripts must stay runnable (they are the
switching-user's orientation, mirroring the reference's pycaffe
example notebooks)."""

import os
import runpy

import pytest


def test_pycaffe_workflow_example(capsys):
    cwd = os.getcwd()
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    try:
        runpy.run_path(os.path.join(repo, "examples", "pycaffe_workflow.py"),
                       run_name="__main__")
    finally:
        os.chdir(cwd)
    out = capsys.readouterr().out
    assert "OK" in out and "class probabilities" in out


def test_distributed_workflow_example(capsys):
    cwd = os.getcwd()
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    try:
        runpy.run_path(
            os.path.join(repo, "examples", "distributed_workflow.py"),
            run_name="__main__")
    finally:
        os.chdir(cwd)
    out = capsys.readouterr().out
    assert "OK: distributed workflow complete" in out
    assert "hierarchical 2x4" in out
