"""detect — windowed R-CNN-style detection from the command line
(reference: caffe/python/detect.py, crop_mode='list').

Input is a CSV with columns ``filename,ymin,xmin,ymax,xmax`` (the
reference's window-list format); output is a CSV with the window
coordinates and per-class scores.  Selective-search proposal mode is not
bundled (the reference shells out to a MATLAB module for it) — pass
explicit windows.

Usage:
  python -m sparknet_tpu.tools.detect_cli WINDOWS.csv OUT.csv \
      --model_def deploy.prototxt [--pretrained_model weights.caffemodel]
      [--mean_file mean.npy] [--input_scale S] [--raw_scale 255]
      [--channel_swap 2,1,0] [--context_pad 16]
"""

from __future__ import annotations

import argparse
import csv
import time

COORD_COLS = ["ymin", "xmin", "ymax", "xmax"]


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("input_file", help="CSV of filename," +
                        ",".join(COORD_COLS))
    parser.add_argument("output_file", help="Output CSV.")
    parser.add_argument("--model_def", required=True)
    parser.add_argument("--pretrained_model", default=None)
    parser.add_argument("--gpu", action="store_true",
                        help="Accepted for compatibility; device "
                             "placement belongs to JAX.")
    parser.add_argument("--crop_mode", default="list",
                        choices=["list"],
                        help="Only explicit window lists are bundled "
                             "(detect.py's selective_search mode shells "
                             "out to MATLAB).")
    parser.add_argument("--mean_file", default="")
    parser.add_argument("--input_scale", type=float, default=None)
    parser.add_argument("--raw_scale", type=float, default=255.0)
    parser.add_argument("--channel_swap", default="2,1,0")
    parser.add_argument("--context_pad", type=int, default=16)
    args = parser.parse_args(argv)

    import numpy as np

    from ..classify import Detector
    from ..pycaffe_io import load_image

    mean = None
    if args.mean_file:
        mean = np.load(args.mean_file)
        if mean.ndim == 3 and mean.shape[1:] != (1, 1):
            mean = mean.mean(1).mean(1)  # detect.py collapses to channels
        if mean.ndim == 1:
            # broadcast against (N, C, H, W) crops on the CHANNEL axis
            mean = mean.reshape(-1, 1, 1)
    channel_swap = ([int(s) for s in args.channel_swap.split(",")]
                    if args.channel_swap else None)

    detector = Detector(
        args.model_def, args.pretrained_model, mean=mean,
        input_scale=args.input_scale, raw_scale=args.raw_scale,
        channel_swap=channel_swap, context_pad=args.context_pad)

    # group windows per image, preserving file order
    windows_by_file: dict[str, list] = {}
    with open(args.input_file) as f:
        reader = csv.DictReader(f)
        for row in reader:
            windows_by_file.setdefault(row["filename"], []).append(
                tuple(int(float(row[c])) for c in COORD_COLS))
    if not windows_by_file:
        raise SystemExit(f"no windows in {args.input_file!r}")

    t = time.time()
    results = []
    for fname, windows in windows_by_file.items():
        img = load_image(fname)
        dets = detector.detect_windows([(np.asarray(img).transpose(2, 0, 1),
                                         windows)])
        for d in dets:
            results.append((fname, d["window"], np.asarray(d["prediction"])))
    print(f"Processed {len(results)} windows in {time.time() - t:.3f} s.")

    n_classes = len(results[0][2])
    with open(args.output_file, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(["filename"] + COORD_COLS
                   + [f"class{i}" for i in range(n_classes)])
        for fname, window, pred in results:
            w.writerow([fname] + [int(v) for v in window]
                       + [float(p) for p in pred])
    print(f"Saved to {args.output_file}.")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
