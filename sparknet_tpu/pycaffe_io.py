"""``caffe.io`` shim — the image-IO/preprocessing helpers pycaffe
scripts universally use (reference: caffe/python/caffe/io.py):
``load_image``, ``resize_image``, ``oversample``, and ``Transformer``
(set_transpose / set_channel_swap / set_raw_scale / set_mean /
set_input_scale → ``preprocess``/``deprocess``).

Semantics follow the reference order exactly (io.py Transformer.preprocess):
resize → transpose → channel_swap → raw_scale → mean subtract →
input_scale; deprocess inverts in reverse.  Images are float arrays in
[0, 1] HxWxC (skimage convention), like ``caffe.io.load_image`` returns.
"""

from __future__ import annotations

import numpy as np

__all__ = ["load_image", "resize_image", "oversample", "Transformer",
           "blobproto_to_array", "array_to_blobproto",
           "arraylist_to_blobprotovecor_str",
           "blobprotovector_str_to_arraylist",
           "array_to_datum", "datum_to_array"]


# -- proto <-> array converters (reference: io.py:18-95) ---------------------

def _pmsg_of(msg):
    """Accept a caffe_pb2-shim Message or a raw PMessage."""
    return getattr(msg, "_p", msg)


def blobproto_to_array(blob, return_diff: bool = False) -> np.ndarray:
    """BlobProto -> ndarray shaped by ``shape`` or the legacy
    num/channels/height/width dims; ``return_diff`` reads the diff
    channel (io.py blobproto_to_array — the mean-file loading idiom)."""
    from .proto.caffe_pb import blob_to_array
    from .proto.textformat import PMessage
    pm = _pmsg_of(blob)
    if not return_diff:
        arr = blob_to_array(pm)
        # wire-decoded chunks can be read-only frombuffer views; pycaffe
        # scripts mutate the result in place
        return arr if arr.flags.writeable else arr.copy()
    m = PMessage()  # same shape fields, diff presented as data
    for k, v in pm.items():
        if k in ("data", "double_data"):
            continue
        key = {"diff": "data", "double_diff": "double_data"}.get(k, k)
        m.add(key, v)
    return blob_to_array(m)


def array_to_blobproto(arr, diff=None):
    """ndarray -> BlobProto message (new-style shape + packed data;
    io.py array_to_blobproto)."""
    from .proto.caffemodel import array_to_blob
    from .pycaffe_pb2 import _class_for
    pm = array_to_blob(np.asarray(arr, np.float32))
    if diff is not None:
        pm.set("diff", np.asarray(diff, np.float32).reshape(-1))
    return _class_for("BlobProto")(pm)


def arraylist_to_blobprotovecor_str(arraylist) -> bytes:
    """[arrays] -> serialized BlobProtoVector (io.py's name, typo and
    all — the compatibility contract)."""
    from .proto.caffemodel import array_to_blob
    from .proto.textformat import PMessage
    from .proto.wireformat import encode
    vec = PMessage()
    for arr in arraylist:
        vec.add("blobs", array_to_blob(np.asarray(arr, np.float32)))
    return encode(vec, "BlobProtoVector")


def blobprotovector_str_to_arraylist(s: bytes) -> list:
    """Serialized BlobProtoVector -> [arrays] (io.py)."""
    from .proto.caffe_pb import blob_to_array
    from .proto.wireformat import decode
    vec = decode(s, "BlobProtoVector")
    return [blob_to_array(b) for b in vec.get_all("blobs")]


def array_to_datum(arr: np.ndarray, label=None):
    """(C, H, W) array -> Datum message: uint8 data goes in the byte
    string, anything else in float_data (io.py array_to_datum; LMDB
    builders write datum.SerializeToString())."""
    from .proto.textformat import PMessage
    from .pycaffe_pb2 import _class_for
    arr = np.asarray(arr)
    if arr.ndim != 3:
        raise ValueError("Incorrect array shape.")
    m = PMessage()
    c, h, w = arr.shape
    m.set("channels", int(c))
    m.set("height", int(h))
    m.set("width", int(w))
    if arr.dtype == np.uint8:
        m.set("data", arr.tobytes())
    else:
        for v in arr.astype(float).flat:
            m.add("float_data", float(v))
    if label is not None:
        m.set("label", int(label))
    return _class_for("Datum")(m)


def datum_to_array(datum) -> np.ndarray:
    """Datum message -> (C, H, W) array: byte data as uint8, else
    float_data (io.py datum_to_array)."""
    pm = _pmsg_of(datum)
    shape = (int(pm.get("channels", 1)), int(pm.get("height", 1)),
             int(pm.get("width", 1)))
    data = pm.get("data")
    if data:
        # copy: frombuffer over bytes is read-only, but scripts mutate
        # the decoded image in place (reference fromstring copies)
        return np.frombuffer(bytes(data),
                             np.uint8).reshape(shape).copy()
    return np.asarray(pm.get_all("float_data"),
                      np.float32).reshape(shape)


def oversample(images, crop_dims) -> np.ndarray:
    """io.py oversample: for each HxWxC image, the 4 corners + center
    crops and their mirrors — returns (10·N, crop_h, crop_w, C).
    (classify.oversample is the NCHW Classifier-internal variant; this
    one matches the reference caffe.io signature and layout.)"""
    ch, cw = int(crop_dims[0]), int(crop_dims[1])
    out = []
    for im in images:
        im = np.asarray(im)
        h, w = im.shape[:2]
        if h < ch or w < cw:
            raise ValueError(f"image {im.shape} smaller than crop "
                             f"{(ch, cw)}")
        starts = [(0, 0), (0, w - cw), (h - ch, 0), (h - ch, w - cw),
                  ((h - ch) // 2, (w - cw) // 2)]
        # Reference ordering (io.py oversample): the 5 crops first, then
        # the same 5 mirrored as a block — scripts index positions
        # (e.g. first 5 = unmirrored).
        crops = [im[y:y + ch, x:x + cw] for y, x in starts]
        out.extend(crops)
        out.extend(c[:, ::-1] for c in crops)
    return np.stack(out)


def load_image(filename: str, color: bool = True) -> np.ndarray:
    """Load an image as float32 [0, 1] HxWxC (RGB) — io.py load_image
    (skimage.img_as_float), via PIL here."""
    from PIL import Image
    img = Image.open(filename)
    img = img.convert("RGB" if color else "L")
    arr = np.asarray(img, np.float32) / 255.0
    if not color:
        arr = arr[:, :, None]
    return arr


def resize_image(im: np.ndarray, new_dims, interp_order: int = 1) -> np.ndarray:
    """Resize HxWxC float image to ``new_dims`` (H, W) — io.py
    resize_image.  ``interp_order`` follows the reference's skimage
    spline orders: 0 nearest, 1 bilinear (default), >=2 bicubic."""
    from PIL import Image
    h, w = int(new_dims[0]), int(new_dims[1])
    resample = (Image.NEAREST if interp_order == 0
                else Image.BILINEAR if interp_order == 1
                else Image.BICUBIC)
    chans = []
    for c in range(im.shape[2]):
        ch = Image.fromarray(im[:, :, c].astype(np.float32), mode="F")
        chans.append(np.asarray(ch.resize((w, h), resample)))
    return np.stack(chans, axis=2).astype(im.dtype)


class Transformer:
    """io.py Transformer: per-input preprocessing configuration.

    ``inputs`` maps input blob name -> blob shape (N, C, H, W), exactly
    the pycaffe idiom::

        t = caffe.io.Transformer({'data': net.blobs['data'].shape})
        t.set_transpose('data', (2, 0, 1))
        t.set_mean('data', mu)
        t.set_raw_scale('data', 255)
        t.set_channel_swap('data', (2, 1, 0))
        net.blobs['data'].data[...] = t.preprocess('data', img)
    """

    def __init__(self, inputs: dict):
        self.inputs = {k: tuple(v) for k, v in inputs.items()}
        self.transpose: dict = {}
        self.channel_swap: dict = {}
        self.raw_scale: dict = {}
        self.mean: dict = {}
        self.input_scale: dict = {}

    def _check(self, in_: str) -> None:
        if in_ not in self.inputs:
            raise ValueError(
                f"{in_!r} is not one of the net inputs: "
                f"{sorted(self.inputs)}")

    def set_transpose(self, in_: str, order) -> None:
        self._check(in_)
        if len(order) != len(self.inputs[in_]) - 1:
            raise ValueError(
                "Transpose order needs to have the same number of "
                "dimensions as the input.")
        self.transpose[in_] = tuple(order)

    def set_channel_swap(self, in_: str, order) -> None:
        self._check(in_)
        if len(order) != self.inputs[in_][1]:
            raise ValueError(
                "Channel swap needs to have the same number of "
                "dimensions as the input channels.")
        self.channel_swap[in_] = tuple(order)

    def set_raw_scale(self, in_: str, scale: float) -> None:
        self._check(in_)
        self.raw_scale[in_] = float(scale)

    def set_input_scale(self, in_: str, scale: float) -> None:
        self._check(in_)
        self.input_scale[in_] = float(scale)

    def set_mean(self, in_: str, mean: np.ndarray) -> None:
        """Mean can be a scalar-per-channel vector (C,) or an image
        (C, H, W) matching the input's spatial dims (io.py set_mean,
        incl. its shape checks)."""
        self._check(in_)
        mean = np.asarray(mean, np.float32)
        if mean.ndim == 1:
            if mean.shape[0] != self.inputs[in_][1]:
                raise ValueError("Mean channels incompatible with input.")
            mean = mean[:, None, None]
        else:
            if mean.shape[0] != self.inputs[in_][1]:
                raise ValueError("Mean channels incompatible with input.")
            if mean.shape[1:] != tuple(self.inputs[in_][2:]):
                raise ValueError(
                    "Mean shape incompatible with input shape.")
        self.mean[in_] = mean

    def preprocess(self, in_: str, data: np.ndarray) -> np.ndarray:
        """io.py Transformer.preprocess order: resize → transpose →
        channel_swap → raw_scale → mean → input_scale."""
        self._check(in_)
        data = np.asarray(data, np.float32)
        in_dims = self.inputs[in_][2:]
        if data.ndim == 3 and data.shape[:2] != tuple(in_dims):
            data = resize_image(data, in_dims)
        if in_ in self.transpose:
            data = data.transpose(self.transpose[in_])
        if in_ in self.channel_swap:
            data = data[list(self.channel_swap[in_]), :, :]
        if in_ in self.raw_scale:
            data = data * self.raw_scale[in_]
        if in_ in self.mean:
            data = data - self.mean[in_]
        if in_ in self.input_scale:
            data = data * self.input_scale[in_]
        return data

    def deprocess(self, in_: str, data: np.ndarray) -> np.ndarray:
        """Invert preprocess (io.py deprocess order)."""
        self._check(in_)
        data = np.array(np.squeeze(data), np.float32)
        if in_ in self.input_scale:
            data = data / self.input_scale[in_]
        if in_ in self.mean:
            data = data + self.mean[in_]
        if in_ in self.raw_scale:
            data = data / self.raw_scale[in_]
        if in_ in self.channel_swap:
            inv = np.argsort(self.channel_swap[in_])
            data = data[list(inv), :, :]
        if in_ in self.transpose:
            data = data.transpose(np.argsort(self.transpose[in_]))
        return data
