"""Resilient job supervision — the recovery half of Spark's fault tolerance.

The reference inherited two things from Spark: fail-fast (a dead executor
fails the stage — ``spark.task.maxFailures`` is pinned to 1 at
CifarApp.scala:36) and *reschedule* (the driver relaunches the failed
work).  The launcher (``tools.launch``) reproduces fail-fast: the first
worker death tears the whole round down.  This module is the reschedule
half: ``ResilientRunner`` wraps ``launch_local``/``launch_ssh``, watches
the worker set, and on any nonzero exit relaunches the WHOLE job with
exponential backoff under a bounded restart budget.

Recovery is round-granular, not step-granular: the relaunched job finds
the newest valid checkpoint manifest on disk (``DistributedTrainer``'s
``checkpoint_dir`` auto-resume) and replays from that round boundary — a
preempted host costs at most ``checkpoint_every`` rounds of work, exactly
the granularity SparkNet's driver loop could recover at (a round was one
Spark stage).

Every (re)launch is stamped with SPARKNET_FAULT_ATTEMPT /
SPARKNET_RESTART_COUNT in the child env; the fault-injection harness
(``utils.faults``) keys one-shot faults off it, and training code can log
it.  A fresh coordinator port is chosen per attempt so a relaunch never
races the dying coordinator's socket in TIME_WAIT.
"""

from __future__ import annotations

import dataclasses
import sys
import time
from typing import Callable

from ..tools.launch import free_port, launch_local, launch_ssh


@dataclasses.dataclass(frozen=True)
class RestartPolicy:
    """Bounded restarts with exponential backoff — the
    ``spark.task.maxFailures`` contract plus the backoff Spark's DAG
    scheduler applies between stage reattempts."""

    max_restarts: int = 3          # total attempts = max_restarts + 1
    backoff_base: float = 1.0      # seconds before the first restart
    backoff_factor: float = 2.0
    backoff_max: float = 60.0

    def delay(self, restart_idx: int) -> float:
        """Sleep before restart #``restart_idx`` (0-based)."""
        return min(self.backoff_base * self.backoff_factor ** restart_idx,
                   self.backoff_max)


@dataclasses.dataclass(frozen=True)
class Attempt:
    index: int
    returncode: int
    duration_s: float


class ResilientRunner:
    """Launch a multi-process training job and keep it alive.

    Exactly one of ``nprocs`` (local mode) or ``hosts`` (ssh mode) must be
    given — the same split as ``tools.launch``.  ``run()`` returns the
    final exit code: 0 once any attempt completes, else the last failing
    code after the restart budget is spent.  ``attempts`` records every
    try for post-mortems.
    """

    def __init__(self, cmd: list[str], *,
                 nprocs: int | None = None,
                 hosts: list[str] | None = None,
                 platform: str | None = None,
                 devices_per_proc: int | None = None,
                 cwd: str | None = None,
                 timeout: float | None = None,
                 policy: RestartPolicy | None = None,
                 extra_env: dict | None = None,
                 sleep: Callable[[float], None] = time.sleep):
        if (nprocs is None) == (hosts is None):
            raise ValueError("exactly one of nprocs / hosts is required")
        self.cmd = list(cmd)
        self.nprocs = nprocs
        self.hosts = list(hosts) if hosts else None
        self.platform = platform
        self.devices_per_proc = devices_per_proc
        self.cwd = cwd
        self.timeout = timeout
        self.policy = policy or RestartPolicy()
        self.extra_env = dict(extra_env or {})
        self._sleep = sleep
        self.attempts: list[Attempt] = []

    def _launch_once(self, attempt: int) -> int:
        env = dict(self.extra_env)
        env["SPARKNET_FAULT_ATTEMPT"] = str(attempt)
        env["SPARKNET_RESTART_COUNT"] = str(attempt)
        if self.hosts is not None:
            return launch_ssh(self.cmd, self.hosts,
                              coordinator_port=free_port(),
                              cwd=self.cwd, timeout=self.timeout,
                              extra_env=env)
        return launch_local(self.cmd, self.nprocs, platform=self.platform,
                            devices_per_proc=self.devices_per_proc,
                            coordinator=f"127.0.0.1:{free_port()}",
                            timeout=self.timeout, extra_env=env)

    def run(self) -> int:
        rc = 0
        for attempt in range(self.policy.max_restarts + 1):
            t0 = time.monotonic()
            rc = self._launch_once(attempt)
            self.attempts.append(
                Attempt(attempt, rc, time.monotonic() - t0))
            if rc == 0:
                if attempt:
                    print(f"resilience: job recovered on attempt "
                          f"{attempt + 1}", file=sys.stderr, flush=True)
                return 0
            if attempt < self.policy.max_restarts:
                delay = self.policy.delay(attempt)
                print(f"resilience: attempt {attempt + 1} failed rc={rc}; "
                      f"restarting from latest checkpoint in {delay:.2g}s "
                      f"({self.policy.max_restarts - attempt} restarts "
                      f"left)", file=sys.stderr, flush=True)
                self._sleep(delay)
        print(f"resilience: restart budget exhausted after "
              f"{len(self.attempts)} attempts; giving up rc={rc}",
              file=sys.stderr, flush=True)
        return rc
