"""Signal-driven snapshot/stop — the SignalHandler analog.

The reference maps SIGINT/SIGHUP to solver actions (snapshot / stop /
none) checked between iterations (reference:
caffe/src/caffe/util/signal_handler.cpp:12-115; acted on inside
``Solver::Step`` at caffe/src/caffe/solver.cpp:270-281).  Same contract
here: handlers only set flags; the training loop polls between rounds, so
a snapshot is always taken at a consistent round boundary.

Preemption extension (beyond the reference): cloud schedulers deliver
SIGTERM with a grace window before the kill — a preempted TPU-VM that
dies dirty loses up to ``checkpoint_every`` rounds for no reason.
``SNAPSHOT_STOP`` (the default SIGTERM action) tells the training loop
to write one final round checkpoint and exit cleanly; use
``preemption_guard()`` for the standard SIGTERM/SIGINT wiring.
"""

from __future__ import annotations

import signal
from typing import Callable


class SolverAction:
    NONE = "none"
    STOP = "stop"
    SNAPSHOT = "snapshot"
    SNAPSHOT_STOP = "snapshot_stop"   # preemption: checkpoint, then stop


class SignalGuard:
    """Install SIGINT→stop, SIGHUP→snapshot, and SIGTERM→snapshot+stop
    (all configurable); restore the previous handlers on exit."""

    def __init__(self, sigint_action: str = SolverAction.STOP,
                 sighup_action: str = SolverAction.SNAPSHOT,
                 sigterm_action: str = SolverAction.SNAPSHOT_STOP):
        self._actions = {signal.SIGINT: sigint_action,
                         signal.SIGHUP: sighup_action,
                         signal.SIGTERM: sigterm_action}
        self._pending: list[str] = []
        self._previous: dict[int, object] = {}

    def __enter__(self) -> "SignalGuard":
        for sig, action in self._actions.items():
            if action == SolverAction.NONE:
                continue
            self._previous[sig] = signal.signal(sig, self._on_signal)
        return self

    def _on_signal(self, signum, frame) -> None:
        self._pending.append(self._actions[signum])
        if signum == signal.SIGTERM:
            # the preemption notice is a flight-recorder moment: dump
            # the recent-event ring NOW — if the grace window is blown
            # and the kill lands, the black box is already on disk
            from . import telemetry
            rec = telemetry.get_recorder()
            rec.record("sigterm", action=self._actions[signum])
            rec.dump("sigterm")

    def __exit__(self, *exc) -> None:
        for sig, prev in self._previous.items():
            signal.signal(sig, prev)

    def check(self) -> str:
        """The action requested since last check (Solver::GetRequestedAction
        analog); consumes one pending request."""
        if self._pending:
            return self._pending.pop(0)
        return SolverAction.NONE


def preemption_guard() -> SignalGuard:
    """The standard production wiring: SIGTERM (the preemption notice) →
    final checkpoint + clean exit; SIGINT (a human ^C) → the same, so an
    interrupted run is always resumable; SIGHUP → checkpoint and keep
    going."""
    return SignalGuard(sigint_action=SolverAction.SNAPSHOT_STOP,
                       sighup_action=SolverAction.SNAPSHOT,
                       sigterm_action=SolverAction.SNAPSHOT_STOP)
