"""TP rules — trace purity.

Functions reachable from a trace root must be pure with respect to the
host: an ``os.environ`` read inside a jitted function evaluates once at
trace time and bakes a constant into the executable (breaking the
``SPARKNET_TUNE=off``-equals-``auto`` structural guarantee and making
jit cache keys lie); clocks, host RNG, file IO and ``print`` similarly
run at trace time, not step time.

Trace roots recognised (project conventions included):

- ``@jax.jit`` / ``@partial(jax.jit, ...)`` / ``@jax.custom_vjp`` /
  ``@jax.custom_jvp`` / ``@jax.remat`` decorated functions
- functions passed to ``jit`` / ``grad`` / ``value_and_grad`` /
  ``vmap`` / ``pmap`` / ``pallas_call`` / ``checkpoint`` call sites
- both arguments of ``f.defvjp(fwd, bwd)``
- ``apply`` methods of ``@register_layer`` classes (the layer registry
  dispatches through a dict, which a name-based call graph cannot see,
  but every ``apply`` runs under the jitted step)

Reachability is a name-based intra-project call graph: calls through
locals, ``self``, imported modules and ``from``-imported functions are
followed; dynamic dispatch stops the walk (sound-enough in practice —
the registry ``apply`` convention above plugs the one big hole).

Rules:
  TP001  env read under trace (os.environ / os.getenv / knobs.*)
  TP002  clock read under trace (time.time/perf_counter/...)
  TP003  host RNG under trace (random.* / np.random.* / os.urandom)
  TP004  file IO under trace (open / io.open / Path.read_text...)
  TP005  print under trace
  TP006  np.asarray/np.array of a function parameter (forces a tracer
         to host — ConcretizationError at best, silent const at worst)
"""

from __future__ import annotations

import ast

from .core import Finding, Project, SourceFile, dotted

SEVERITY = "error"

_ROOT_DECOS = ("jit", "custom_vjp", "custom_jvp", "remat")
_ROOT_CALLS = {"jit", "grad", "value_and_grad", "vmap", "pmap",
               "pallas_call", "checkpoint", "remat"}
_CLOCK_CALLS = {"time", "perf_counter", "perf_counter_ns", "monotonic",
                "monotonic_ns", "process_time", "sleep", "time_ns"}
_FILE_CALLS = {"open"}
_PATH_IO_ATTRS = {"read_text", "read_bytes", "write_text", "write_bytes"}
_KNOB_ACCESSORS = {"raw", "is_set", "get_str", "get_int", "get_float",
                   "get_bool"}


class _Module:
    """Per-file indexes: functions by qualname, classes/methods, and
    the import alias maps used for cross-module call resolution."""

    def __init__(self, sf: SourceFile) -> None:
        self.sf = sf
        self.funcs: dict[str, ast.AST] = {}          # top-level name -> node
        self.methods: dict[tuple[str, str], ast.AST] = {}  # (cls, m) -> node
        self.layer_classes: list[str] = []           # @register_layer classes
        self.mod_alias: dict[str, str] = {}          # name -> dotted module
        self.sym_import: dict[str, tuple[str, str]] = {}  # name -> (mod, sym)
        self._index()

    def _index(self) -> None:
        sf = self.sf
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    local = alias.asname or alias.name.split(".")[0]
                    self.mod_alias[local] = (alias.name if alias.asname
                                             else alias.name.split(".")[0])
            elif isinstance(node, ast.ImportFrom):
                base = self._resolve_from(node)
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    local = alias.asname or alias.name
                    self.sym_import[local] = (base, alias.name)
        for child in ast.iter_child_nodes(sf.tree):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.funcs[child.name] = child
            elif isinstance(child, ast.ClassDef):
                is_layer = any(
                    dotted(d.func if isinstance(d, ast.Call) else d)
                    .endswith("register_layer") for d in child.decorator_list)
                if is_layer:
                    self.layer_classes.append(child.name)
                for item in child.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        self.methods[(child.name, item.name)] = item

    def _resolve_from(self, node: ast.ImportFrom) -> str:
        if node.level == 0:
            return node.module or ""
        parts = self.sf.module.split(".")
        # level 1 strips the module name itself; for package __init__
        # files sf.module IS the package, so one less to strip
        drop = node.level - (1 if self.sf.rel.endswith("__init__.py")
                             else 0)
        base_parts = parts[:len(parts) - drop] if drop else parts
        if node.module:
            base_parts = base_parts + node.module.split(".")
        return ".".join(base_parts)


class _CallGraph:
    def __init__(self, project: Project) -> None:
        self.project = project
        self.mods = {sf.module: _Module(sf) for sf in project.files}
        # node identity: (module, qualname)
        self.nodes: dict[tuple[str, str], ast.AST] = {}
        for mname, m in self.mods.items():
            for fname, fnode in m.funcs.items():
                self.nodes[(mname, fname)] = fnode
            for (cls, meth), fnode in m.methods.items():
                self.nodes[(mname, f"{cls}.{meth}")] = fnode

    # -- root discovery -----------------------------------------------------

    def roots(self) -> set[tuple[str, str]]:
        out: set[tuple[str, str]] = set()
        for mname, m in self.mods.items():
            for key, fnode in self._iter_defs(m):
                if self._has_root_deco(fnode):
                    out.add((mname, key))
            for cls in m.layer_classes:
                for meth in ("apply",):
                    if (cls, meth) in m.methods:
                        out.add((mname, f"{cls}.{meth}"))
            # call-site roots: jit(f), grad(f), f.defvjp(fwd, bwd), ...
            for node in ast.walk(m.sf.tree):
                if not isinstance(node, ast.Call):
                    continue
                name = dotted(node.func)
                leaf = name.rpartition(".")[2]
                args: list[ast.AST] = []
                if leaf in _ROOT_CALLS:
                    args = list(node.args[:1])
                elif leaf == "defvjp":
                    args = list(node.args[:2])
                for a in args:
                    tgt = self._resolve_ref(m, a, enclosing_cls=None)
                    if tgt:
                        out.add(tgt)
        return out

    @staticmethod
    def _iter_defs(m: "_Module"):
        for fname, fnode in m.funcs.items():
            yield fname, fnode
        for (cls, meth), fnode in m.methods.items():
            yield f"{cls}.{meth}", fnode

    @staticmethod
    def _has_root_deco(fnode: ast.AST) -> bool:
        for d in getattr(fnode, "decorator_list", ()):
            target = d.func if isinstance(d, ast.Call) else d
            name = dotted(target)
            leaf = name.rpartition(".")[2]
            if leaf in _ROOT_DECOS:
                return True
            # @partial(jax.jit, ...): the root marker is the first arg
            if leaf == "partial" and isinstance(d, ast.Call) and d.args:
                if dotted(d.args[0]).rpartition(".")[2] in _ROOT_DECOS:
                    return True
        return False

    # -- edge resolution ----------------------------------------------------

    def _module_for_alias(self, m: _Module, name: str) -> str | None:
        if name in m.mod_alias:
            cand = m.mod_alias[name]
            if cand in self.mods:
                return cand
        if name in m.sym_import:
            mod, sym = m.sym_import[name]
            if f"{mod}.{sym}" in self.mods:
                return f"{mod}.{sym}"
        return None

    def _resolve_ref(self, m: _Module, node: ast.AST,
                     enclosing_cls: str | None) -> tuple[str, str] | None:
        """A function reference (not a call) -> call-graph node."""
        if isinstance(node, ast.Name):
            if node.id in m.funcs:
                return (m.sf.module, node.id)
            if node.id in m.sym_import:
                mod, sym = m.sym_import[node.id]
                if (mod, sym) in self.nodes:
                    return (mod, sym)
        elif isinstance(node, ast.Attribute) and \
                isinstance(node.value, ast.Name):
            base = node.value.id
            if base == "self" and enclosing_cls:
                key = (m.sf.module, f"{enclosing_cls}.{node.attr}")
                if key in self.nodes:
                    return key
            tmod = self._module_for_alias(m, base)
            if tmod and (tmod, node.attr) in self.nodes:
                return (tmod, node.attr)
        return None

    def edges(self, mname: str, qual: str) -> set[tuple[str, str]]:
        m = self.mods[mname]
        fnode = self.nodes[(mname, qual)]
        cls = qual.split(".")[0] if "." in qual else None
        out: set[tuple[str, str]] = set()
        for node in ast.walk(fnode):
            if isinstance(node, ast.Call):
                tgt = self._resolve_ref(m, node.func, enclosing_cls=cls)
                if tgt:
                    out.add(tgt)
        return out

    def reachable(self) -> set[tuple[str, str]]:
        seen = set()
        work = list(self.roots())
        while work:
            key = work.pop()
            if key in seen or key not in self.nodes:
                continue
            seen.add(key)
            work.extend(self.edges(*key))
        return seen


def _param_names(fnode: ast.AST) -> set[str]:
    a = fnode.args
    names = {p.arg for p in (*a.posonlyargs, *a.args, *a.kwonlyargs)}
    names.discard("self")
    names.discard("cls")
    return names


def _check_function(project: Project, m: _Module, qual: str,
                    fnode: ast.AST) -> list[Finding]:
    sf = m.sf
    params = _param_names(fnode)
    findings: list[Finding] = []

    def hit(rule: str, node: ast.AST, msg: str, fix: str) -> None:
        f = project.finding(sf, rule, SEVERITY, node.lineno,
                            f"{msg} (trace-reachable via {qual})", fix)
        if f:
            findings.append(f)

    for node in ast.walk(fnode):
        if isinstance(node, ast.Attribute) and node.attr == "environ" and \
                isinstance(node.value, ast.Name) and node.value.id == "os":
            hit("TP001", node, "os.environ access under trace",
                "read the knob before the traced function and pass the "
                "value in (latch at construction), or baseline a "
                "deliberate trace-time knob")
            continue
        if not isinstance(node, ast.Call):
            continue
        name = dotted(node.func)
        leaf = name.rpartition(".")[2]
        head = name.partition(".")[0]
        if name == "os.getenv":
            hit("TP001", node, "os.getenv under trace",
                "latch the value outside the traced function")
        elif head == "knobs" and leaf in _KNOB_ACCESSORS:
            hit("TP001", node, f"knob read {name}() under trace",
                "latch the knob outside the traced function, or baseline "
                "a deliberate trace-time knob")
        elif head == "time" and leaf in _CLOCK_CALLS:
            hit("TP002", node, f"clock call {name}() under trace",
                "time outside the traced function; a traced clock reads "
                "once at trace time")
        elif (head == "random" or name.startswith("np.random.") or
              name.startswith("numpy.random.") or name == "os.urandom"):
            hit("TP003", node, f"host RNG {name}() under trace",
                "thread a jax.random key through instead")
        elif name in _FILE_CALLS or name == "io.open":
            hit("TP004", node, f"file IO {name}() under trace",
                "load the data before tracing and close over the array")
        elif leaf in _PATH_IO_ATTRS and isinstance(node.func, ast.Attribute):
            hit("TP004", node, f".{leaf}() file IO under trace",
                "load the data before tracing")
        elif name == "print":
            hit("TP005", node, "print under trace",
                "use jax.debug.print, or log outside the traced function")
        elif leaf in ("asarray", "array", "copy") and \
                head in ("np", "numpy") and node.args and \
                isinstance(node.args[0], ast.Name) and \
                node.args[0].id in params:
            hit("TP006", node,
                f"{name}() of parameter {node.args[0].id!r} forces a "
                f"tracer to host",
                "use jnp equivalents on traced values")
    return findings


def check(project: Project) -> list[Finding]:
    graph = _CallGraph(project)
    findings: list[Finding] = []
    seen_sites: set[tuple[str, str, int]] = set()
    for mname, qual in sorted(graph.reachable()):
        m = graph.mods[mname]
        if m.sf.rel == "sparknet_tpu/utils/knobs.py":
            # the sanctioned accessor: every registry read bottoms out in
            # knobs.raw()'s os.environ.get — callers are flagged, not it
            continue
        fnode = graph.nodes[(mname, qual)]
        for f in _check_function(project, m, qual, fnode):
            site = (f.rule, f.path, f.line)
            if site not in seen_sites:  # nested defs overlap parents
                seen_sites.add(site)
                findings.append(f)
    return findings
