"""Corrupt-input robustness for the binary format parsers: every mangled
buffer must produce a clean Python exception (or a documented fallback),
never a crash or a silent garbage parse — the native Datum parser's
overflow-safe bounds are exercised the same way."""

import numpy as np
import pytest

from sparknet_tpu.data.db import array_to_datum
from sparknet_tpu.data.leveldb_io import LeveldbError, LeveldbReader, write_leveldb
from sparknet_tpu.data.lmdb_io import LmdbError, LmdbReader, write_lmdb
from sparknet_tpu.proto.wireformat import WireError, decode, encode
from sparknet_tpu.proto.textformat import PMessage


def _mutations(data: bytes, rng, n=40):
    out = []
    for _ in range(n):
        b = bytearray(data)
        kind = rng.integers(0, 3)
        if kind == 0 and len(b) > 1:          # truncate
            del b[rng.integers(1, len(b)):]
        elif kind == 1:                        # flip bytes
            for _ in range(rng.integers(1, 4)):
                b[rng.integers(0, len(b))] = rng.integers(0, 256)
        else:                                  # insert garbage
            pos = rng.integers(0, len(b))
            b[pos:pos] = bytes(rng.integers(0, 256, size=5))
        out.append(bytes(b))
    return out


def test_wireformat_decode_survives_mutations():
    m = PMessage()
    m.add("name", "net")
    sub = PMessage()
    sub.add("name", "l1")
    sub.add("type", "ReLU")
    m.add("layer", sub)
    data = encode(m, "NetParameter")
    rng = np.random.default_rng(0)
    for mut in _mutations(data, rng):
        try:
            decode(mut, "NetParameter")
        except (WireError, ValueError, KeyError):
            pass  # clean rejection


def test_native_datum_parse_survives_mutations():
    from sparknet_tpu import native
    if not native.available():
        pytest.skip("no native toolchain")
    rng = np.random.default_rng(1)
    img = rng.integers(0, 256, size=(3, 6, 6)).astype(np.uint8)
    rec = array_to_datum(img, 3)
    for mut in _mutations(rec, rng, n=80):
        # must return a batch, or None (fallback) — never crash
        res = native.parse_datum_batch([mut], 3, 6, 6)
        if res is not None:
            out, labels = res
            assert out.shape == (1, 3, 6, 6)
    # pathological: huge length varint that would overflow pos+ln
    evil = bytes([0x22, 0xFF, 0xFF, 0xFF, 0xFF, 0xFF, 0xFF, 0xFF, 0xFF,
                  0x7F]) + b"x"
    assert native.parse_datum_batch([evil], 3, 6, 6) is None


def test_lmdb_reader_survives_mutations(tmp_path):
    import os
    items = [(b"%04d" % i, b"v" * 50) for i in range(20)]
    path = str(tmp_path / "db")
    write_lmdb(path, items)
    data = open(os.path.join(path, "data.mdb"), "rb").read()
    rng = np.random.default_rng(2)
    for i, mut in enumerate(_mutations(data, rng, n=25)):
        mpath = str(tmp_path / f"m{i}")
        os.makedirs(mpath, exist_ok=True)
        with open(os.path.join(mpath, "data.mdb"), "wb") as f:
            f.write(mut)
        try:
            with LmdbReader(mpath) as r:
                for _ in r.items():
                    pass
        except Exception:
            # any Python-level exception is a clean rejection; the fuzz
            # assertion is no hang / no native crash / bounded recursion
            pass


def test_leveldb_reader_survives_mutations(tmp_path):
    import os
    items = [(b"%04d" % i, b"v" * 50) for i in range(20)]
    path = str(tmp_path / "db")
    write_leveldb(path, items)
    log = os.path.join(path, "000003.log")
    data = open(log, "rb").read()
    rng = np.random.default_rng(3)
    for mut in _mutations(data, rng, n=25):
        with open(log, "wb") as f:
            f.write(mut)
        try:
            with LeveldbReader(path) as r:
                list(r.items())
        except Exception:
            pass  # clean Python-level rejection
