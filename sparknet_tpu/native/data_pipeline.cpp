// Native host-side data pipeline for sparknet_tpu.
//
// The TPU-native equivalent of the reference's native data path: the
// per-image crop-into-float-buffer hot loop (reference:
// src/main/java/libs/ByteImage.java:77-95 cropInto), CIFAR record parsing
// (reference: src/main/scala/loaders/CifarLoader.scala:65 readBatch), JPEG
// decode + force-resize (reference:
// src/main/scala/preprocessing/ScaleAndConvert.scala:16-27, done there via
// javax.imageio/thumbnailator), and mean-image accumulation (reference:
// src/main/scala/preprocessing/ComputeMean.scala:8-44).
//
// Exposed as a plain C ABI consumed over ctypes — no FFI framework, no
// Python objects held in native code, all buffers caller-owned numpy
// arrays.  Unlike the reference's JNA path (per-element Pointer.setFloat,
// the measured bottleneck in CallbackBenchmarkSpec), every call here is one
// batch-granular memcpy-class pass.

#include <cstdint>
#include <cstring>
#include <cstdio>
#include <csetjmp>

#include <jpeglib.h>

extern "C" {

// ---------------------------------------------------------------------------
// CIFAR-10 binary records: [label u8][3072 u8 CHW pixels] repeated.
// Splits into planar float images (0..255) and int32 labels.
// ---------------------------------------------------------------------------
int sn_decode_cifar(const uint8_t* records, int64_t n_records,
                    float* images_out, int32_t* labels_out) {
    const int64_t rec = 1 + 3 * 32 * 32;
    for (int64_t i = 0; i < n_records; ++i) {
        const uint8_t* r = records + i * rec;
        labels_out[i] = r[0];
        float* dst = images_out + i * 3072;
        for (int64_t j = 0; j < 3072; ++j) dst[j] = (float)r[1 + j];
    }
    return 0;
}

// ---------------------------------------------------------------------------
// Batched crop + mirror + mean-subtract, u8/f32 NCHW in -> f32 NCHW out.
// ys/xs/flips are per-image; mean may be null (skip), scalar (len 1), or a
// full C*crop*crop plane.  This is ByteImage.cropInto vectorized over the
// batch with the mean fused in.
// ---------------------------------------------------------------------------
static inline void crop_one(const float* src, int C, int H, int W,
                            float* dst, int crop, int y0, int x0, int flip,
                            const float* mean, int mean_len) {
    for (int c = 0; c < C; ++c) {
        const float* plane = src + (int64_t)c * H * W;
        float* dplane = dst + (int64_t)c * crop * crop;
        for (int y = 0; y < crop; ++y) {
            const float* srow = plane + (int64_t)(y0 + y) * W + x0;
            float* drow = dplane + (int64_t)y * crop;
            if (flip) {
                for (int x = 0; x < crop; ++x) drow[x] = srow[crop - 1 - x];
            } else {
                memcpy(drow, srow, sizeof(float) * crop);
            }
        }
    }
    if (mean) {
        int64_t plane = (int64_t)C * crop * crop;
        if (mean_len == 1) {
            for (int64_t j = 0; j < plane; ++j) dst[j] -= mean[0];
        } else {
            for (int64_t j = 0; j < plane; ++j) dst[j] -= mean[j];
        }
    }
}

int sn_crop_batch_f32(const float* src, int64_t n, int C, int H, int W,
                      float* dst, int crop,
                      const int32_t* ys, const int32_t* xs,
                      const int32_t* flips,
                      const float* mean, int64_t mean_len) {
    if (crop > H || crop > W) return -1;
    for (int64_t i = 0; i < n; ++i) {
        if (ys[i] < 0 || xs[i] < 0 || ys[i] + crop > H || xs[i] + crop > W)
            return -2;
        crop_one(src + i * (int64_t)C * H * W, C, H, W,
                 dst + i * (int64_t)C * crop * crop, crop,
                 ys[i], xs[i], flips[i], mean, (int)mean_len);
    }
    return 0;
}

// ---------------------------------------------------------------------------
// Batched Datum protobuf parse: n serialized Datum messages (wire format,
// caffe.proto fields: 1 channels, 2 height, 3 width, 4 data(bytes),
// 5 label, 6 float_data, 7 encoded) -> one f32 [n, c, h, w] batch +
// labels.  The native half of the reference's data_reader + C++ protobuf
// path; returns
//   0 ok; -1 malformed wire data; -2 shape mismatch vs (c,h,w);
//   -3 encoded/unsupported payload (caller falls back per-record).
// ---------------------------------------------------------------------------
static inline int dat_varint(const uint8_t* p, int64_t len, int64_t* pos,
                             uint64_t* out) {
    uint64_t v = 0;
    int shift = 0;
    while (*pos < len && shift < 64) {
        uint8_t b = p[(*pos)++];
        v |= (uint64_t)(b & 0x7F) << shift;
        if (!(b & 0x80)) { *out = v; return 0; }
        shift += 7;
    }
    return -1;
}

int sn_parse_datum_batch(const uint8_t* buf, const int64_t* offsets,
                         const int64_t* sizes, int64_t n,
                         int c, int h, int w,
                         float* out, int32_t* labels) {
    const int64_t plane = (int64_t)c * h * w;
    for (int64_t i = 0; i < n; ++i) {
        const uint8_t* p = buf + offsets[i];
        const int64_t len = sizes[i];
        int64_t pos = 0;
        int64_t ch = -1, hh = -1, ww = -1;
        const uint8_t* data = nullptr;
        int64_t dlen = 0;
        int64_t fcount = 0;
        bool encoded = false;
        float* dst = out + i * plane;
        labels[i] = 0;
        while (pos < len) {
            uint64_t key;
            if (dat_varint(p, len, &pos, &key)) return -1;
            const int field = (int)(key >> 3);
            const int wire = (int)(key & 7);
            if (wire == 0) {
                uint64_t v;
                if (dat_varint(p, len, &pos, &v)) return -1;
                switch (field) {
                    case 1: ch = (int64_t)v; break;
                    case 2: hh = (int64_t)v; break;
                    case 3: ww = (int64_t)v; break;
                    case 5: labels[i] = (int32_t)v; break;
                    case 7: encoded = v != 0; break;
                    default: break;
                }
            } else if (wire == 2) {
                uint64_t ln;
                if (dat_varint(p, len, &pos, &ln)) return -1;
                // overflow-safe bound: a huge ln must not wrap pos+ln
                if ((int64_t)ln < 0 || (int64_t)ln > len - pos) return -1;
                if (field == 4) {
                    data = p + pos;
                    dlen = (int64_t)ln;
                } else if (field == 6) {  // packed float_data
                    if (ln % 4) return -1;
                    int64_t cnt = (int64_t)ln / 4;
                    if (fcount + cnt > plane) return -2;
                    memcpy(dst + fcount, p + pos, ln);
                    fcount += cnt;
                }
                pos += (int64_t)ln;
            } else if (wire == 5) {
                if (pos + 4 > len) return -1;
                if (field == 6) {  // unpacked float_data element
                    if (fcount >= plane) return -2;
                    memcpy(dst + fcount, p + pos, 4);
                    ++fcount;
                }
                pos += 4;
            } else if (wire == 1) {
                if (pos + 8 > len) return -1;
                pos += 8;
            } else {
                return -1;  // groups/unknown wire types unsupported
            }
        }
        if (encoded) return -3;
        if (ch != c || hh != h || ww != w) return -2;
        if (data != nullptr) {
            if (dlen != plane) return -2;
            for (int64_t j = 0; j < plane; ++j) dst[j] = (float)data[j];
        } else if (fcount != plane) {
            return -2;
        }
    }
    return 0;
}

// ---------------------------------------------------------------------------
// Mean-image accumulation: sum a u8/f32 batch into float64 accumulators
// (ComputeMean's per-partition pixel sums).
// ---------------------------------------------------------------------------
int sn_accumulate_mean(const float* images, int64_t n, int64_t plane,
                       double* acc) {
    for (int64_t i = 0; i < n; ++i) {
        const float* img = images + i * plane;
        for (int64_t j = 0; j < plane; ++j) acc[j] += img[j];
    }
    return 0;
}

// ---------------------------------------------------------------------------
// JPEG decode + force-resize to out_h x out_w, planar RGB float output
// (ScaleAndConvert.convertImage semantics: ignore aspect ratio; failed
// decodes are reported, caller drops them like ScaleAndConvert:23-25).
// Bilinear sampling over the decoded image.
// ---------------------------------------------------------------------------
struct sn_jpeg_err {
    struct jpeg_error_mgr mgr;
    jmp_buf jump;
};

static void sn_jpeg_error_exit(j_common_ptr cinfo) {
    sn_jpeg_err* err = (sn_jpeg_err*)cinfo->err;
    longjmp(err->jump, 1);
}

int sn_decode_jpeg_resize(const uint8_t* buf, int64_t len,
                          int out_h, int out_w, float* out /*3*H*W*/) {
    jpeg_decompress_struct cinfo;
    sn_jpeg_err jerr;
    cinfo.err = jpeg_std_error(&jerr.mgr);
    jerr.mgr.error_exit = sn_jpeg_error_exit;
    // volatile: must survive longjmp intact (cf. libjpeg example.c)
    uint8_t* volatile pixels = nullptr;
    if (setjmp(jerr.jump)) {
        jpeg_destroy_decompress(&cinfo);
        delete[] pixels;
        return -1;  // undecodable -> caller drops the image
    }
    jpeg_create_decompress(&cinfo);
    jpeg_mem_src(&cinfo, buf, (unsigned long)len);
    if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
        jpeg_destroy_decompress(&cinfo);
        return -1;
    }
    cinfo.out_color_space = JCS_RGB;
    jpeg_start_decompress(&cinfo);
    const int W = cinfo.output_width, H = cinfo.output_height;
    const int comps = cinfo.output_components;  // 3 after JCS_RGB
    pixels = new uint8_t[(int64_t)W * H * comps];
    while (cinfo.output_scanline < cinfo.output_height) {
        uint8_t* row = pixels + (int64_t)cinfo.output_scanline * W * comps;
        jpeg_read_scanlines(&cinfo, &row, 1);
    }
    jpeg_finish_decompress(&cinfo);
    jpeg_destroy_decompress(&cinfo);

    // bilinear force-resize to (out_h, out_w), interleaved -> planar
    const float sy = (H > 1 && out_h > 1) ? (float)(H - 1) / (out_h - 1) : 0.f;
    const float sx = (W > 1 && out_w > 1) ? (float)(W - 1) / (out_w - 1) : 0.f;
    for (int y = 0; y < out_h; ++y) {
        float fy = y * sy;
        int y0 = (int)fy;
        int y1 = y0 + 1 < H ? y0 + 1 : y0;
        float wy = fy - y0;
        for (int x = 0; x < out_w; ++x) {
            float fx = x * sx;
            int x0 = (int)fx;
            int x1 = x0 + 1 < W ? x0 + 1 : x0;
            float wx = fx - x0;
            for (int c = 0; c < 3; ++c) {
                float p00 = pixels[((int64_t)y0 * W + x0) * comps + c];
                float p01 = pixels[((int64_t)y0 * W + x1) * comps + c];
                float p10 = pixels[((int64_t)y1 * W + x0) * comps + c];
                float p11 = pixels[((int64_t)y1 * W + x1) * comps + c];
                float v = (1 - wy) * ((1 - wx) * p00 + wx * p01) +
                          wy * ((1 - wx) * p10 + wx * p11);
                out[(int64_t)c * out_h * out_w + (int64_t)y * out_w + x] = v;
            }
        }
    }
    delete[] pixels;
    return 0;
}

}  // extern "C"
