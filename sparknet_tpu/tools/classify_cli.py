"""classify — out-of-the-box image classification from the command line
(reference: caffe/python/classify.py).

Input is an image file, a directory of images (--ext picks which), or a
.npy batch; output is a .npy of class probabilities.  Flags mirror the
reference script; --gpu is accepted and ignored (JAX owns device
placement, see pycaffe_compat.set_mode_gpu).

Usage:
  python -m sparknet_tpu.tools.classify_cli INPUT OUT.npy \
      --model_def deploy.prototxt [--pretrained_model weights.caffemodel]
      [--center_only] [--images_dim 256,256] [--mean_file mean.npy]
      [--input_scale S] [--raw_scale 255] [--channel_swap 2,1,0]
      [--ext jpg]
"""

from __future__ import annotations

import argparse
import glob
import os
import time


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("input_file",
                        help="Input image, directory, or npy.")
    parser.add_argument("output_file", help="Output npy filename.")
    parser.add_argument("--model_def", default=None,
                        help="Model definition file (required unless "
                             "--server).")
    parser.add_argument("--pretrained_model", default=None,
                        help="Trained model weights file.")
    parser.add_argument("--server", default=None, metavar="URL",
                        help="Submit to a running tools/serve.py instead "
                             "of compiling locally (e.g. "
                             "http://127.0.0.1:8100); preprocessing is "
                             "the same shared pipeline either way.")
    parser.add_argument("--model", default=None,
                        help="Served model name for --server (e.g. "
                             "lenet, caffenet).")
    parser.add_argument("--gpu", action="store_true",
                        help="Accepted for compatibility; device "
                             "placement belongs to JAX.")
    parser.add_argument("--center_only", action="store_true",
                        help="Predict from the center crop alone instead "
                             "of averaging the 10-crop oversample.")
    parser.add_argument("--images_dim", default="256,256",
                        help="Canonical 'height,width' of input images.")
    parser.add_argument("--mean_file", default="",
                        help="npy mean image (C,H,W) or per-channel "
                             "vector; '' for no mean subtraction.")
    parser.add_argument("--input_scale", type=float, default=None)
    parser.add_argument("--raw_scale", type=float, default=255.0)
    parser.add_argument("--channel_swap", default="2,1,0",
                        help="Channel permutation (RGB -> BGR default).")
    parser.add_argument("--ext", default="jpg",
                        help="Image extension for directory inputs.")
    args = parser.parse_args(argv)

    import numpy as np

    from ..classify import Classifier, RemoteClassifier
    from ..pycaffe_io import load_image

    image_dims = [int(s) for s in args.images_dim.split(",")]
    mean = np.load(args.mean_file) if args.mean_file else None
    if mean is not None and mean.ndim == 1:
        # per-channel vector: broadcast on the channel axis of NCHW crops
        mean = mean.reshape(-1, 1, 1)
    channel_swap = ([int(s) for s in args.channel_swap.split(",")]
                    if args.channel_swap else None)

    if args.server:
        if not args.model:
            parser.error("--server requires --model (the served name)")
        classifier = RemoteClassifier(
            args.server, args.model, image_dims=image_dims,
            mean=mean, input_scale=args.input_scale,
            raw_scale=args.raw_scale, channel_swap=channel_swap)
    else:
        if not args.model_def:
            parser.error("--model_def is required (or use --server)")
        classifier = Classifier(
            args.model_def, args.pretrained_model, image_dims=image_dims,
            mean=mean, input_scale=args.input_scale,
            raw_scale=args.raw_scale, channel_swap=channel_swap)

    t = time.time()
    if args.input_file.endswith("npy"):
        inputs = list(np.load(args.input_file).astype(np.float32))
    elif os.path.isdir(args.input_file):
        inputs = [load_image(f) for f in sorted(glob.glob(
            os.path.join(args.input_file, "*." + args.ext)))]
    else:
        inputs = [load_image(args.input_file)]
    if not inputs:
        raise SystemExit(f"no inputs found in {args.input_file!r}")
    print(f"Classifying {len(inputs)} inputs.")

    predictions = classifier.predict(
        inputs, oversample_crops=not args.center_only)
    print(f"Done in {time.time() - t:.2f} s.")
    np.save(args.output_file, predictions)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
