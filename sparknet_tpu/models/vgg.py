"""VGG-16 — the ICI-allreduce stress model (138M params).

Architecture per the public VGG ILSVRC 16-layer config referenced by
BASELINE.json config 5 ("VGG-16 on ILSVRC2012, stress ICI allreduce
bandwidth"); the reference zoo carries the same family for its multi-GPU
scaling docs (reference: caffe/docs/multigpu.md)."""

from __future__ import annotations

from ..proto.caffe_pb import LayerParameter, NetParameter, Phase
from .dsl import (
    accuracy_layer, convolution_layer, dropout_layer, inner_product_layer,
    java_data_layer, net_param, pooling_layer, relu_layer,
    softmax_with_loss_layer,
)

_LRB = [{"lr_mult": 1.0, "decay_mult": 1.0}, {"lr_mult": 2.0, "decay_mult": 0.0}]
_W = {"type": "gaussian", "std": 0.01}
_B = {"type": "constant"}

_STAGES = [(64, 2), (128, 2), (256, 3), (512, 3), (512, 3)]


def vgg16(train_batch: int = 64, test_batch: int = 50,
          crop: int = 224) -> NetParameter:
    layers: list[LayerParameter] = [
        java_data_layer("data_train", ["data", "label"], Phase.TRAIN,
                        (train_batch, 3, crop, crop), (train_batch,)),
        java_data_layer("data_test", ["data", "label"], Phase.TEST,
                        (test_batch, 3, crop, crop), (test_batch,)),
    ]
    bottom = "data"
    for si, (width, reps) in enumerate(_STAGES, start=1):
        for ri in range(1, reps + 1):
            name = f"conv{si}_{ri}"
            layers.append(convolution_layer(
                name, bottom, name, num_output=width, kernel=3, pad=1,
                weight_filler=_W, bias_filler=_B, param=_LRB))
            layers.append(relu_layer(f"relu{si}_{ri}", name))
            bottom = name
        layers.append(pooling_layer(f"pool{si}", bottom, f"pool{si}",
                                    pool="MAX", kernel=2, stride=2))
        bottom = f"pool{si}"
    for i, width in ((6, 4096), (7, 4096)):
        layers += [
            inner_product_layer(f"fc{i}", bottom, f"fc{i}", num_output=width,
                                weight_filler={"type": "gaussian", "std": 0.005},
                                bias_filler={"type": "constant", "value": 0.1},
                                param=_LRB),
            relu_layer(f"relu{i}", f"fc{i}"),
            dropout_layer(f"drop{i}", f"fc{i}", ratio=0.5),
        ]
        bottom = f"fc{i}"
    layers += [
        inner_product_layer("fc8", bottom, "fc8", num_output=1000,
                            weight_filler=_W, bias_filler=_B, param=_LRB),
        softmax_with_loss_layer("loss", ["fc8", "label"]),
        accuracy_layer("accuracy", ["fc8", "label"], phase=Phase.TEST),
        accuracy_layer("accuracy_top5", ["fc8", "label"], top="accuracy_top5",
                       top_k=5, phase=Phase.TEST),
    ]
    return net_param("VGG_ILSVRC_16", layers)
