"""Bounded retry-with-backoff for one-shot control-plane edges.

The reference inherits retry semantics from Spark — a failed task is
rescheduled up to ``spark.task.maxFailures`` times (reference:
CifarApp.scala:36 pins it to 1, i.e. fail-fast) — but its one-shot
control-plane calls (driver connect, LMDB open) have no such cover and a
transient NFS blip or a coordinator that is still binding its port kills
the job.  This module is the missing half: a small deterministic
exponential-backoff loop used by ``parallel.cluster.init_cluster`` and the
DB/file opens in ``data.lmdb_io`` / ``data.hdf5``.

Knobs (also via env, read per call so launchers can tune children):
  SPARKNET_IO_RETRIES   — attempts for data-plane file/DB opens (default 3)
  SPARKNET_IO_BACKOFF   — base delay in seconds (default 0.05)
"""

from __future__ import annotations

import os
import random
import sys
import time
from typing import Any, Callable, Iterable

from . import knobs


def backoff_delays(attempts: int, base: float, factor: float = 2.0,
                   max_delay: float = 30.0, jitter: float = 0.0,
                   rng: random.Random | None = None) -> Iterable[float]:
    """The sleep schedule between ``attempts`` tries: base, base·factor,
    base·factor², ... capped at ``max_delay`` (len == attempts - 1).

    ``jitter`` > 0 spreads each delay uniformly over
    ``[delay·(1-jitter), delay·(1+jitter)]`` so N simultaneously-failed
    ranks don't retry in lockstep and thundering-herd the coordinator
    (every rank of a torn-down job restarts at the same instant — without
    jitter they all re-connect in the same millisecond too).  ``rng`` is
    injectable for deterministic tests; the default is seeded per-process
    by the OS, which is exactly the decorrelation the herd needs."""
    if not 0.0 <= jitter < 1.0:
        raise ValueError(f"jitter must be in [0, 1), got {jitter}")
    rng = rng or random
    for i in range(max(attempts - 1, 0)):
        delay = min(base * factor ** i, max_delay)
        if jitter:
            delay *= 1.0 + jitter * (2.0 * rng.random() - 1.0)
        yield delay


def retry_call(fn: Callable[..., Any], *args: Any,
               attempts: int = 3, base_delay: float = 0.1,
               factor: float = 2.0, max_delay: float = 30.0,
               jitter: float = 0.0,
               retry_on: tuple[type[BaseException], ...] = (OSError,),
               sleep: Callable[[float], None] = time.sleep,
               describe: str | None = None, **kwargs: Any) -> Any:
    """Call ``fn(*args, **kwargs)``; on an exception in ``retry_on`` retry
    up to ``attempts`` total tries with exponential backoff.  The final
    failure re-raises the last exception unchanged (bounded budget — this
    is Spark's maxFailures contract, not an infinite supervisor)."""
    if attempts < 1:
        raise ValueError(f"attempts must be >= 1, got {attempts}")
    delays = list(backoff_delays(attempts, base_delay, factor, max_delay,
                                 jitter))
    for i in range(attempts):
        try:
            return fn(*args, **kwargs)
        except retry_on as e:
            if i == attempts - 1:
                raise
            what = describe or getattr(fn, "__name__", "call")
            print(f"retry: {what} failed ({type(e).__name__}: {e}); "
                  f"attempt {i + 1}/{attempts}, backing off {delays[i]:.2g}s",
                  file=sys.stderr)
            sleep(delays[i])
    raise AssertionError("unreachable")  # pragma: no cover


def io_retry(fn: Callable[..., Any], *args: Any,
             describe: str | None = None, **kwargs: Any) -> Any:
    """``retry_call`` tuned from the SPARKNET_IO_* env knobs — the wrapper
    the data-plane opens (LMDB mmap, HDF5, source lists) go through."""
    attempts = int(knobs.raw("SPARKNET_IO_RETRIES", "3") or 3)
    base = float(knobs.raw("SPARKNET_IO_BACKOFF", "0.05") or 0.05)
    return retry_call(fn, *args, attempts=attempts, base_delay=base,
                      retry_on=(OSError,), describe=describe, **kwargs)
