"""Drive: forward(start=) mid-net idiom + feed tier at overridden batch."""
import jax; jax.config.update("jax_platforms", "cpu")
import numpy as np
from sparknet_tpu import pycaffe_compat as caffe

NET = """
name: "d"
input: "data"
input_shape { dim: 2 dim: 3 dim: 12 dim: 12 }
layer { name: "conv1" type: "Convolution" bottom: "data" top: "conv1"
  convolution_param { num_output: 4 kernel_size: 3 pad: 1
    weight_filler { type: "xavier" } } }
layer { name: "relu1" type: "ReLU" bottom: "conv1" top: "conv1" }
layer { name: "ip" type: "InnerProduct" bottom: "conv1" top: "ip"
  inner_product_param { num_output: 3 weight_filler { type: "xavier" } } }
layer { name: "prob" type: "Softmax" bottom: "ip" top: "prob" }
"""
net = caffe.Net(NET, phase=caffe.TEST)
x = np.random.default_rng(0).normal(size=(2, 3, 12, 12)).astype(np.float32)
p0 = net.forward(data=x)["prob"].copy()
# the net-surgery idiom: zero the conv activations, re-run from relu1
net.blobs["conv1"].data[...] = 0.0
p1 = net.forward(start="relu1")["prob"]
assert np.allclose(p1, 1.0 / 3, atol=1e-5), p1  # uniform softmax of zeros... 
print("forward(start=) drive OK:", p0[0].round(3), "->", p1[0].round(3))
