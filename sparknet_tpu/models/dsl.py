"""Programmatic model DSL — the ``Layers.scala`` analog.

The reference builds ``LayerParameter``/``NetParameter`` protobufs inline
from Scala (reference: src/main/scala/libs/Layers.scala:18-137 — RDDLayer,
ConvolutionLayer, PoolingLayer, InnerProductLayer, ReLULayer,
SoftmaxWithLoss, NetParam).  Here the builders produce the same typed config
objects the prototxt parser does, so DSL-built and prototxt-loaded nets are
indistinguishable downstream.
"""

from __future__ import annotations

from typing import Any, Sequence

from ..proto.caffe_pb import LayerParameter, NetParameter, Phase
from ..proto.textformat import PMessage


def msg(**kwargs: Any) -> PMessage:
    """Build a PMessage from kwargs; dicts nest, lists/tuples repeat."""
    m = PMessage()
    for k, v in kwargs.items():
        if isinstance(v, dict):
            m.add(k, msg(**v))
        elif isinstance(v, PMessage):
            m.add(k, v)
        elif isinstance(v, (list, tuple)):
            for item in v:
                m.add(k, msg(**item) if isinstance(item, dict) else item)
        else:
            m.add(k, v)
    return m


def layer(name: str, type: str, bottoms: Sequence[str] = (),
          tops: Sequence[str] = (), phase: Phase | None = None,
          param: Sequence[dict] | None = None,
          **type_params: dict | PMessage) -> LayerParameter:
    """Generic layer builder; ``type_params`` maps sub-config names
    (e.g. convolution_param) to dicts."""
    lp = LayerParameter(
        name=name, type=type, bottom=list(bottoms), top=list(tops), phase=phase)
    if param:
        from ..proto.caffe_pb import ParamSpec
        lp.param = [
            ParamSpec(**p,
                      raw_lr_mult=p.get("lr_mult"),
                      raw_decay_mult=p.get("decay_mult"))
            for p in param]
    for key, sub in type_params.items():
        lp.params[key] = sub if isinstance(sub, PMessage) else msg(**sub)
    return lp


def net_param(name: str, layers: Sequence[LayerParameter]) -> NetParameter:
    """NetParam (reference: Layers.scala:130-137)."""
    return NetParameter(name=name, layer=list(layers))


def java_data_layer(name: str, tops: Sequence[str], phase: Phase,
                    data_shape: Sequence[int],
                    label_shape: Sequence[int] | None = None) -> LayerParameter:
    """Host-fed data layer (RDDLayer analog; reference: Layers.scala:18-40)."""
    p: dict[str, Any] = {"shape": {"dim": list(data_shape)}}
    if label_shape is not None:
        p["label_shape"] = {"dim": list(label_shape)}
    return layer(name, "JavaData", tops=tops, phase=phase, java_data_param=p)


def memory_data_layer(name: str, tops: Sequence[str], batch: int, channels: int,
                      height: int, width: int) -> LayerParameter:
    return layer(name, "MemoryData", tops=tops, memory_data_param={
        "batch_size": batch, "channels": channels,
        "height": height, "width": width})


def convolution_layer(name: str, bottom: str, top: str, *, num_output: int,
                      kernel: int | tuple[int, int], stride: int = 1,
                      pad: int = 0, group: int = 1,
                      weight_filler: dict | None = None,
                      bias_filler: dict | None = None,
                      param: Sequence[dict] | None = None) -> LayerParameter:
    """ConvolutionLayer (reference: Layers.scala:42-63)."""
    kh, kw = (kernel, kernel) if isinstance(kernel, int) else kernel
    cp: dict[str, Any] = {
        "num_output": num_output, "kernel_h": kh, "kernel_w": kw,
        "stride": stride, "pad": pad, "group": group,
    }
    if weight_filler:
        cp["weight_filler"] = weight_filler
    if bias_filler:
        cp["bias_filler"] = bias_filler
    return layer(name, "Convolution", [bottom], [top], param=param,
                 convolution_param=cp)


def pooling_layer(name: str, bottom: str, top: str, *, pool: str = "MAX",
                  kernel: int = 2, stride: int = 1, pad: int = 0,
                  global_pooling: bool = False) -> LayerParameter:
    """PoolingLayer (reference: Layers.scala:65-86)."""
    pp: dict[str, Any] = {"pool": pool, "stride": stride, "pad": pad}
    if global_pooling:
        pp["global_pooling"] = True
    else:
        pp["kernel_size"] = kernel
    return layer(name, "Pooling", [bottom], [top], pooling_param=pp)


def inner_product_layer(name: str, bottom: str, top: str, *, num_output: int,
                        weight_filler: dict | None = None,
                        bias_filler: dict | None = None,
                        param: Sequence[dict] | None = None) -> LayerParameter:
    """InnerProductLayer (reference: Layers.scala:88-100)."""
    ip: dict[str, Any] = {"num_output": num_output}
    if weight_filler:
        ip["weight_filler"] = weight_filler
    if bias_filler:
        ip["bias_filler"] = bias_filler
    return layer(name, "InnerProduct", [bottom], [top], param=param,
                 inner_product_param=ip)


def relu_layer(name: str, bottom: str, top: str | None = None) -> LayerParameter:
    """ReLULayer, in-place by default (reference: Layers.scala:102-113)."""
    return layer(name, "ReLU", [bottom], [top or bottom])


def lrn_layer(name: str, bottom: str, top: str, *, local_size: int = 5,
              alpha: float = 1.0, beta: float = 0.75) -> LayerParameter:
    return layer(name, "LRN", [bottom], [top], lrn_param={
        "local_size": local_size, "alpha": alpha, "beta": beta})


def dropout_layer(name: str, bottom: str, top: str | None = None,
                  ratio: float = 0.5) -> LayerParameter:
    return layer(name, "Dropout", [bottom], [top or bottom],
                 dropout_param={"dropout_ratio": ratio})


def concat_layer(name: str, bottoms: Sequence[str], top: str,
                 axis: int = 1) -> LayerParameter:
    return layer(name, "Concat", bottoms, [top], concat_param={"axis": axis})


def softmax_layer(name: str, bottom: str, top: str) -> LayerParameter:
    return layer(name, "Softmax", [bottom], [top])


def softmax_with_loss_layer(name: str, bottoms: Sequence[str],
                            top: str = "loss") -> LayerParameter:
    """SoftmaxWithLoss (reference: Layers.scala:115-128)."""
    return layer(name, "SoftmaxWithLoss", bottoms, [top])


def accuracy_layer(name: str, bottoms: Sequence[str], top: str = "accuracy",
                   top_k: int = 1, phase: Phase | None = Phase.TEST) -> LayerParameter:
    ap = {"top_k": top_k} if top_k != 1 else {}
    return layer(name, "Accuracy", bottoms, [top], phase=phase,
                 accuracy_param=ap)
