"""Elastic degraded-mode training + health-plane coverage: heartbeat
beacons, straggler deadlines (monitor unit + launcher kill + real-driver
end-to-end), elastic re-form in ResilientRunner (fake launches, real
mesh-free subprocess workers, rejoin probes), rich failure post-mortems
(log tail + heartbeat age), and preemption-aware SIGTERM shutdown —
the membership/health tier SparkNet never had (its supervision was
whole-stage Spark timeouts; SURVEY.md §2.5).
"""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from sparknet_tpu.parallel import health
from sparknet_tpu.parallel.resilience import (
    Attempt, ElasticPolicy, ResilienceError, ResilientRunner, RestartPolicy,
)
from sparknet_tpu.tools.launch import EXIT_STRAGGLER, launch_local

DRIVER = os.path.join(os.path.dirname(__file__), "multihost_driver.py")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# heartbeat beacons
# ---------------------------------------------------------------------------

def test_heartbeat_write_read_roundtrip(tmp_path):
    d = str(tmp_path)
    health.write_beat(d, rank=2, round_idx=5, phase="round_start", attempt=1)
    beat = health.read_beat(d, 2)
    assert beat.rank == 2 and beat.round == 5
    assert beat.phase == "round_start" and beat.attempt == 1
    assert beat.pid == os.getpid()
    assert 0 <= beat.age() < 5
    assert health.read_beat(d, 0) is None          # absent rank: no data
    health.write_beat(d, rank=0, round_idx=1, phase="init")
    assert set(health.read_all(d)) == {0, 2}


def test_heartbeat_read_tolerates_garbage(tmp_path):
    d = str(tmp_path)
    with open(health.beat_path(d, 1), "w") as f:
        f.write("{not json")
    assert health.read_beat(d, 1) is None
    (tmp_path / "hb_rank_zz.json").write_text("{}")   # unparsable rank
    assert health.read_all(d) == {}
    assert health.read_all(str(tmp_path / "absent")) == {}


def test_maybe_beat_is_env_gated(tmp_path, monkeypatch):
    monkeypatch.delenv("SPARKNET_HEARTBEAT_DIR", raising=False)
    health.maybe_beat(0)                               # no dir: no-op
    d = str(tmp_path / "hb")
    monkeypatch.setenv("SPARKNET_HEARTBEAT_DIR", d)
    monkeypatch.setenv("SPARKNET_PROC_ID", "3")
    monkeypatch.setenv("SPARKNET_FAULT_ATTEMPT", "2")
    health.maybe_beat(7, "round_end")
    beat = health.read_beat(d, 3)
    assert beat.round == 7 and beat.attempt == 2 and beat.phase == "round_end"


# ---------------------------------------------------------------------------
# straggler monitor (unit, fake clock)
# ---------------------------------------------------------------------------

def test_straggler_monitor_deadline_and_grace(tmp_path):
    d = str(tmp_path)
    now = [1000.0]
    mon = health.StragglerMonitor(d, deadline_s=10.0, clock=lambda: now[0])
    # nobody has beaten: startup grace, never flagged
    assert mon.check([0, 1]) == []
    health.write_beat(d, 0, 0, "round_start", clock=lambda: 1000.0)
    now[0] = 1009.0
    assert mon.check([0, 1]) == []                 # inside deadline
    now[0] = 1011.0
    assert mon.check([0, 1]) == [0]                # past it: flagged
    assert mon.check([0, 1]) == []                 # flagged at most once
    assert mon.last_age(0) == pytest.approx(11.0)
    assert mon.last_age(1) is None
    with pytest.raises(ValueError, match="deadline_s"):
        health.StragglerMonitor(d, deadline_s=0)


def test_straggler_monitor_fresh_beats_reset_age(tmp_path):
    d = str(tmp_path)
    now = [0.0]
    mon = health.StragglerMonitor(d, deadline_s=5.0, clock=lambda: now[0])
    for t in (0.0, 4.0, 8.0):                      # beats every 4s
        health.write_beat(d, 0, int(t), "round_start", clock=lambda t=t: t)
        now[0] = t + 3.0
        assert mon.check([0]) == []                # always within deadline


# ---------------------------------------------------------------------------
# launcher: straggler kill, log tee, per-rank report
# ---------------------------------------------------------------------------

def _clean_launch_env():
    saved = dict(os.environ)
    os.environ.pop("XLA_FLAGS", None)  # conftest's 8-device flag
    for k in list(os.environ):
        if k.startswith("SPARKNET_"):
            os.environ.pop(k)
    return saved


# mesh-free worker: beats per "round" via the real health/fault modules,
# so launcher/runner supervision is exercised without multiprocess XLA
# (which this rig's CPU backend lacks — the real-mesh analogs gate on the
# multiprocess_cpu fixture)
_FAKE_WORKER = """\
import os, sys, time
sys.path.insert(0, {repo!r})
from sparknet_tpu.parallel import health
from sparknet_tpu.utils import faults
rank = int(os.environ.get("SPARKNET_PROC_ID", "0"))
world = int(os.environ.get("SPARKNET_NUM_PROCS", "1"))
inj = faults.FaultInjector.from_env()
for r in range(3):
    health.maybe_beat(r, "round_start")
    inj.on_round(r, rank=rank)
    time.sleep(0.05)
print(f"worker rank={{rank}}/{{world}} "
      f"incarnation={{os.environ.get('SPARKNET_INCARNATION')}} ok",
      flush=True)
{extra}
"""


def _worker_script(tmp_path, extra=""):
    p = tmp_path / "worker.py"
    p.write_text(_FAKE_WORKER.format(repo=REPO, extra=extra))
    return str(p)


@pytest.mark.chaos
def test_launch_kills_straggler_at_round_deadline(tmp_path):
    """One rank beats then sleeps 60s; the supervisor must kill it after
    ~deadline seconds (not the 60s sleep, not the global timeout) and
    report it as the straggler."""
    worker = _worker_script(
        tmp_path, extra="""
if rank == 1:
    health.maybe_beat(99, "round_start")
    time.sleep(60)
""")
    saved = _clean_launch_env()
    try:
        report = {}
        t0 = time.monotonic()
        rc = launch_local([sys.executable, worker], nprocs=3, timeout=120,
                          heartbeat_dir=str(tmp_path / "hb"),
                          round_deadline=3.0,
                          log_dir=str(tmp_path / "logs"), report=report)
        elapsed = time.monotonic() - t0
    finally:
        os.environ.clear()
        os.environ.update(saved)
    assert rc == EXIT_STRAGGLER
    assert elapsed < 40, f"straggler not killed by deadline ({elapsed:.1f}s)"
    assert report["cause"] == "straggler"
    assert report["stragglers"] == [1] and report["first_failure"] == 1


def test_launch_log_dir_and_report(tmp_path):
    worker = _worker_script(tmp_path, extra="""
if rank == 2:
    print("XYZZY-DIAGNOSTIC", flush=True)
    sys.exit(7)
""")
    saved = _clean_launch_env()
    try:
        report = {}
        rc = launch_local([sys.executable, worker], nprocs=3, timeout=120,
                          heartbeat_dir=str(tmp_path / "hb"),
                          log_dir=str(tmp_path / "logs"), report=report)
    finally:
        os.environ.clear()
        os.environ.update(saved)
    assert rc == 7
    assert report["cause"] == "exit" and report["first_failure"] == 2
    assert report["rcs"][2] == 7
    log = (tmp_path / "logs" / "rank_2.log").read_text()
    assert "XYZZY-DIAGNOSTIC" in log
    # the dead rank's last beat is on disk for the post-mortem
    assert health.read_beat(str(tmp_path / "hb"), 2) is not None


# ---------------------------------------------------------------------------
# elastic re-form: scripted launches (unit-level)
# ---------------------------------------------------------------------------

def _scripted_runner(monkeypatch, script, *, nprocs=4, elastic=None,
                     policy=None, **kwargs):
    """ResilientRunner whose launches replay ``script``: a list of
    (rc, first_failure) tuples consumed in order; records world sizes."""
    import sparknet_tpu.parallel.resilience as R
    seen = {"worlds": [], "envs": []}
    it = iter(script)

    def fake_local(cmd, nprocs, **kw):
        rc, culprit = next(it)
        seen["worlds"].append(nprocs)
        seen["envs"].append(dict(kw["extra_env"]))
        if kw.get("report") is not None:
            kw["report"].update(
                first_failure=culprit,
                cause="clean" if rc == 0 else "exit",
                rcs={}, stragglers=[])
        return rc

    monkeypatch.setattr(R, "launch_local", fake_local)
    runner = ResilientRunner(
        ["job"], nprocs=nprocs,
        policy=policy or RestartPolicy(max_restarts=1, backoff_base=0.01,
                                       jitter=0.0),
        elastic=elastic, sleep=lambda s: None,
        workdir=kwargs.pop("workdir", None), **kwargs)
    return runner, seen


def test_elastic_reform_drops_culprit_and_recovers(monkeypatch, tmp_path):
    """Rank 3 fails every attempt of incarnation 0; the budget exhausts
    and the runner re-forms with 3 survivors instead of dying."""
    runner, seen = _scripted_runner(
        monkeypatch,
        [(43, 3), (43, 3),          # incarnation 0: budget spent on rank 3
         (0, None)],                # incarnation 1: survivors run clean
        elastic=ElasticPolicy(enabled=True, min_workers=2),
        workdir=str(tmp_path))
    assert runner.run() == 0
    assert seen["worlds"] == [4, 4, 3]
    assert runner.incarnation == 1 and runner.nprocs == 3
    assert [a.incarnation for a in runner.attempts] == [0, 0, 1]
    assert [a.world for a in runner.attempts] == [4, 4, 3]
    # one-shot fault stamps stay GLOBAL across re-forms
    assert [e["SPARKNET_FAULT_ATTEMPT"] for e in seen["envs"]] == \
        ["0", "1", "2"]
    assert [e["SPARKNET_INCARNATION"] for e in seen["envs"]] == \
        ["0", "0", "1"]


def test_elastic_respects_min_workers_floor(monkeypatch, tmp_path):
    """Shrinking stops at min_workers — the job then fails for good."""
    runner, seen = _scripted_runner(
        monkeypatch,
        [(43, 2), (43, 2),          # incarnation 0 (world 3)
         (43, 1), (43, 1)],         # incarnation 1 (world 2): floor hit
        nprocs=3,
        elastic=ElasticPolicy(enabled=True, min_workers=2),
        workdir=str(tmp_path))
    assert runner.run() == 43
    assert seen["worlds"] == [3, 3, 2, 2]
    assert runner.failure is not None
    assert runner.failure.rank == 1
    assert "2 incarnation(s)" in str(runner.failure)


def test_elastic_disabled_reproduces_bounded_budget(monkeypatch, tmp_path):
    runner, seen = _scripted_runner(
        monkeypatch, [(7, 0), (7, 0)], workdir=str(tmp_path))
    assert runner.run() == 7
    assert seen["worlds"] == [4, 4]            # never shrank
    assert runner.incarnation == 0
    assert isinstance(runner.failure, ResilienceError)
    assert runner.failure.returncode == 7


def test_elastic_needs_rank_attribution(monkeypatch, tmp_path):
    """A failure the launcher can't attribute (e.g. global timeout) must
    not drop an arbitrary innocent rank."""
    runner, seen = _scripted_runner(
        monkeypatch, [(124, None), (124, None)],
        elastic=ElasticPolicy(enabled=True), workdir=str(tmp_path))
    assert runner.run() == 124
    assert seen["worlds"] == [4, 4]
    assert "no rank attribution" in str(runner.failure)


def test_rejoin_probe_readmits_recovered_slot(monkeypatch, tmp_path):
    """A dropped slot whose probe passes rejoins at the next relaunch
    boundary; a twice-dropped slot is never probed again (livelock
    guard)."""
    probes = []

    def probe(slot):
        probes.append(slot)
        return True

    runner, seen = _scripted_runner(
        monkeypatch,
        [(43, 3), (43, 3),       # incarnation 0 (world 4): drop slot 3
         (43, 3), (43, 3),       # incarnation 1: slot rejoined (world 4
                                 # again), fails again -> dropped for good
         (0, None)],             # incarnation 2: world 3, clean
        elastic=ElasticPolicy(enabled=True, min_workers=2),
        rejoin_probe=probe, workdir=str(tmp_path))
    assert runner.run() == 0
    assert seen["worlds"] == [4, 4, 4, 4, 3]
    assert probes == [3]                       # second drop: not re-probed
    assert runner.dropped == [3]


def test_attempt_records_are_backwards_compatible():
    a = Attempt(0, 43, 1.5)
    assert a.returncode == 43 and a.incarnation == 0 and a.world == 0


# ---------------------------------------------------------------------------
# elastic re-form: REAL subprocess workers (mesh-free), real fault paths
# ---------------------------------------------------------------------------

@pytest.mark.chaos
def test_elastic_reform_end_to_end_with_perma_crash(tmp_path):
    """THE process-level re-form path: 4 workers, perma_crash@rank:3 (the
    'broken host' — dies on EVERY attempt), restart budget 1.  The runner
    must spend the budget, drop the rank, and complete on 3 survivors."""
    worker = _worker_script(tmp_path)
    saved = _clean_launch_env()
    try:
        runner = ResilientRunner(
            [sys.executable, worker], nprocs=4, timeout=120,
            policy=RestartPolicy(max_restarts=1, backoff_base=0.05,
                                 jitter=0.0),
            elastic=ElasticPolicy(enabled=True, min_workers=2),
            workdir=str(tmp_path / "job"),
            extra_env={"SPARKNET_FAULT": "perma_crash@rank:3"})
        rc = runner.run()
    finally:
        os.environ.clear()
        os.environ.update(saved)
    assert rc == 0
    assert [a.returncode for a in runner.attempts] == [43, 43, 0]
    assert [a.world for a in runner.attempts] == [4, 4, 3]
    assert runner.incarnation == 1 and runner.nprocs == 3
    # the re-formed world really ran with 3 procs and the incarnation env
    log = (tmp_path / "job" / "attempt_002" / "logs" /
           "rank_0.log").read_text()
    assert "rank=0/3" in log and "incarnation=1" in log


@pytest.mark.chaos
def test_failure_postmortem_has_log_tail_and_heartbeat_age(tmp_path):
    """Satellite: the final failure must carry the dead worker's log tail
    and last-heartbeat age, not just an exit code."""
    worker = _worker_script(tmp_path, extra="""
if rank == 1:
    print("PLUGH the flux capacitor burned out", flush=True)
    sys.exit(9)
""")
    saved = _clean_launch_env()
    try:
        runner = ResilientRunner(
            [sys.executable, worker], nprocs=2, timeout=120,
            policy=RestartPolicy(max_restarts=0),
            workdir=str(tmp_path / "job"))
        with pytest.raises(ResilienceError) as ei:
            runner.run_or_raise()
    finally:
        os.environ.clear()
        os.environ.update(saved)
    err = ei.value
    assert err.returncode == 9 and err.rank == 1 and err.cause == "exit"
    assert "PLUGH the flux capacitor" in err.log_tail
    assert "PLUGH" in str(err)                  # tail quoted in the message
    assert err.heartbeat_age is not None and err.heartbeat_age >= 0
    assert "last heartbeat" in str(err)


# ---------------------------------------------------------------------------
# straggler deadline, REAL training driver (single-proc, 4 virtual devices)
# ---------------------------------------------------------------------------

@pytest.mark.chaos
def test_straggler_driver_detected_and_relaunched(tmp_path):
    """Acceptance: a rank running ``straggle:<dur>`` past the round
    deadline is detected and relaunched WITHOUT waiting out the global
    timeout: the 60s straggle is cut short at the ~8s deadline, the
    relaunch resumes from checkpoint, and the run completes."""
    out = str(tmp_path / "strag.npz")
    ck = str(tmp_path / "ck")
    saved = _clean_launch_env()
    try:
        runner = ResilientRunner(
            [sys.executable, DRIVER, "--strategy", "sync", "--out", out,
             "--local-devices", "4", "--rounds", "3", "--ckpt-dir", ck],
            nprocs=1, platform="cpu", timeout=300, round_deadline=8.0,
            policy=RestartPolicy(max_restarts=1, backoff_base=0.2,
                                 jitter=0.0),
            workdir=str(tmp_path / "job"),
            extra_env={"SPARKNET_FAULT": "straggle:60s@round:1"})
        t0 = time.monotonic()
        rc = runner.run()
        elapsed = time.monotonic() - t0
    finally:
        os.environ.clear()
        os.environ.update(saved)
    assert rc == 0, f"straggling job did not recover, rc={rc}"
    assert [a.returncode for a in runner.attempts] == [EXIT_STRAGGLER, 0]
    assert runner.attempts[0].cause == "straggler"
    assert elapsed < 60, (f"waited out the straggle instead of the "
                          f"deadline ({elapsed:.0f}s)")
    assert os.path.exists(out)


# ---------------------------------------------------------------------------
# preemption: SIGTERM → final checkpoint → clean exit
# ---------------------------------------------------------------------------

def test_signal_guard_sigterm_maps_to_snapshot_stop():
    from sparknet_tpu.utils.signals import SignalGuard, SolverAction
    with SignalGuard() as guard:
        os.kill(os.getpid(), signal.SIGTERM)
        assert guard.check() == SolverAction.SNAPSHOT_STOP
        assert guard.check() == SolverAction.NONE


def test_preemption_guard_wiring():
    from sparknet_tpu.utils.signals import SolverAction, preemption_guard
    with preemption_guard() as guard:
        os.kill(os.getpid(), signal.SIGINT)
        assert guard.check() == SolverAction.SNAPSHOT_STOP
        os.kill(os.getpid(), signal.SIGHUP)
        assert guard.check() == SolverAction.SNAPSHOT


@pytest.mark.chaos
def test_sigterm_driver_checkpoints_before_exit(tmp_path):
    """Preemption contract end-to-end: SIGTERM mid-run makes the driver
    write one final round checkpoint and exit 0 — never a dirty death.
    ``--ckpt-every 1000`` guarantees the only manifest on disk is the
    signal-triggered one."""
    ck = tmp_path / "ck"
    saved = _clean_launch_env()
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    try:
        p = subprocess.Popen(
            [sys.executable, DRIVER, "--strategy", "sync",
             "--out", str(tmp_path / "pre.npz"), "--local-devices", "4",
             "--rounds", "100000", "--ckpt-dir", str(ck),
             "--ckpt-every", "1000"],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        # wait until training is demonstrably past round 2, then preempt
        deadline = time.monotonic() + 120
        for line in iter(p.stdout.readline, b""):
            if b"round 2 done" in line:
                break
            assert time.monotonic() < deadline, "driver never reached round 2"
        p.send_signal(signal.SIGTERM)
        out, _ = p.communicate(timeout=120)
        rc = p.returncode
    finally:
        os.environ.clear()
        os.environ.update(saved)
    assert rc == 0, f"preempted driver died dirty rc={rc}:\n{out.decode()}"
    assert b"preempted; stopped cleanly" in out
    manifests = sorted(f for f in os.listdir(ck)
                       if f.startswith("manifest_"))
    assert manifests, "no preemption checkpoint written"
    m = json.loads((ck / manifests[-1]).read_text())
    assert m["round"] >= 3
    # and the snapshot it points at is loadable
    from sparknet_tpu.utils.checkpoint import load_checkpoint
    blob = load_checkpoint(str(ck / m["file"]))
    assert int(blob["round"]) == m["round"]
