"""Round-4 verify drive: train/test/snapshot on TPU through the public
API with BOTH maxpool layers (strided + stride-1 padded) so the
VMEM-resident Pallas maxpool backward is exercised inside a real solver
step when SPARKNET_PALLAS_MAXPOOL=1.  Run twice:

    python .drive.py                                # select-and-scatter
    SPARKNET_PALLAS_MAXPOOL=1 python .drive.py      # Pallas backward

and compare the printed losses (should match to bf16-level noise; both
asserted to converge)."""
import itertools
import os
import numpy as np

from sparknet_tpu.proto import (load_net_prototxt,
                                load_solver_prototxt_with_net,
                                replace_data_layers)
from sparknet_tpu.solvers import Solver
from sparknet_tpu.data import device_feed
from sparknet_tpu.data.minibatch import batch_feed

MODE = os.environ.get("SPARKNET_PALLAS_MAXPOOL", "0")

NET = """
name: "drivenet"
layer { name: "data" type: "Input" top: "data" top: "label"
  input_param { shape { dim: 32 dim: 3 dim: 24 dim: 24 }
                shape { dim: 32 } } }
layer { name: "conv1" type: "Convolution" bottom: "data" top: "conv1"
  convolution_param { num_output: 16 kernel_size: 5 stride: 2
    weight_filler { type: "xavier" } } }
layer { name: "relu1" type: "ReLU" bottom: "conv1" top: "conv1" }
layer { name: "pool1" type: "Pooling" bottom: "conv1" top: "pool1"
  pooling_param { pool: MAX kernel_size: 3 stride: 2 } }
layer { name: "pool2" type: "Pooling" bottom: "pool1" top: "pool2"
  pooling_param { pool: MAX kernel_size: 3 stride: 1 pad: 1 } }
layer { name: "ip" type: "InnerProduct" bottom: "pool2" top: "ip"
  inner_product_param { num_output: 10 weight_filler { type: "xavier" } } }
layer { name: "loss" type: "SoftmaxWithLoss" bottom: "ip" bottom: "label" top: "loss" }
layer { name: "acc" type: "Accuracy" bottom: "ip" bottom: "label" top: "acc"
  include { phase: TEST } }
"""

net = load_net_prototxt(NET)
solver = Solver(load_solver_prototxt_with_net(
    'base_lr: 0.02\nmomentum: 0.9\n', net), seed=0)

# separable synthetic data: class k has mean pattern k
rng = np.random.default_rng(0)
protos = rng.normal(size=(10, 3, 24, 24)).astype(np.float32)
batches = []
for _ in range(8):
    lab = rng.integers(0, 10, size=32)
    img = protos[lab] * 2.0 + rng.normal(size=(32, 3, 24, 24)).astype(np.float32) * 0.3
    batches.append((img.astype(np.float32), lab.astype(np.float32)))

solver.set_train_data(device_feed(batch_feed(itertools.cycle(batches), None)))
l0 = solver.step(1)
solver.step(60)
l1 = float(solver.smoothed_loss())
print(f"PALLAS_MAXPOOL={MODE} loss {l0:.4f} -> {l1:.4f}")
assert l1 < 0.5 and l1 < l0, (l0, l1)

solver.set_test_data(lambda: batch_feed(iter(batches), None))
scores = solver.test(8)
print("test outputs:", scores)
acc = scores.get("acc", scores.get("accuracy"))
assert acc is not None and acc > 0.9, scores

solver.snapshot("/tmp/drive_s.npz")
s2 = Solver(load_solver_prototxt_with_net('base_lr: 0.02\nmomentum: 0.9\n', net), seed=1)
s2.restore("/tmp/drive_s.npz")
s2.set_test_data(lambda: batch_feed(iter(batches), None))
scores2 = s2.test(8)
assert abs(scores2["acc"] - acc) < 1e-5, (scores, scores2)
print("snapshot/restore roundtrip OK:", scores2)

# error probes
for desc, fn in [
    ("unknown bottom", lambda: Solver(
        load_solver_prototxt_with_net('base_lr: 0.1\n',
        load_net_prototxt(NET.replace('bottom: "conv1" top: "pool1"',
                                      'bottom: "nope" top: "pool1"'))), seed=0)),
    ("conv w/o kernel_size", lambda: Solver(load_solver_prototxt_with_net(
        'base_lr: 0.1\n', load_net_prototxt(
            NET.replace("kernel_size: 5 stride: 2", ""))), seed=0)),
]:
    try:
        fn()
        print(f"ERROR-PROBE FAIL: {desc} did not raise")
        raise SystemExit(1)
    except (ValueError, KeyError) as e:
        print(f"error probe OK ({desc}): {str(e)[:80]}")
print(f"DRIVE PASSED (PALLAS_MAXPOOL={MODE}, final loss {l1:.4f}, acc {acc:.3f})")
