"""Read LevelDB databases without libleveldb.

The reference's ``Data`` layer supports ``backend: LEVELDB`` (reference:
caffe/src/caffe/util/db_leveldb.cpp; format default in caffe.proto
DataParameter).  No libleveldb/plyvel/snappy exists on this rig, so this
module parses the on-disk format directly:

- SSTable files (``*.ldb``/``*.sst``): footer -> index block -> data
  blocks, block entries with shared-prefix encoding, snappy or raw blocks.
- Write-ahead logs (``*.log``): 32 KiB blocks of FULL/FIRST/MIDDLE/LAST
  fragments carrying write batches (Caffe's final records usually live
  here — db_leveldb just Put()s and closes, so the memtable is only in
  the log).
- A raw-snappy decompressor (literal + copy tags) for compressed blocks.

Simplification vs real leveldb: instead of replaying MANIFEST version
edits, ``LeveldbReader`` scans *all* table + log files and keeps the
highest-sequence entry per key.  For Caffe-written datasets (write-once,
no overwrites) this is exact; CRCs are not verified on read (the writer
below does compute real crc32c so real leveldb can verify them).
"""

from __future__ import annotations

import glob
import os
import struct
from typing import Iterator

TABLE_MAGIC = 0xDB4775248B80FB57
TYPE_DELETION, TYPE_VALUE = 0, 1


class LeveldbError(Exception):
    pass


def _varint(buf, pos: int) -> tuple[int, int]:
    shift = result = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def snappy_decompress(data) -> bytes:
    """Raw (non-framed) snappy, as used for LevelDB blocks."""
    ulen, pos = _varint(data, 0)
    out = bytearray()
    n = len(data)
    while pos < n:
        tag = data[pos]
        pos += 1
        kind = tag & 3
        if kind == 0:  # literal
            ln = (tag >> 2) + 1
            if ln > 60:
                nb = ln - 60
                ln = int.from_bytes(data[pos:pos + nb], "little") + 1
                pos += nb
            out += data[pos:pos + ln]
            pos += ln
        else:
            if kind == 1:
                ln = ((tag >> 2) & 7) + 4
                off = ((tag >> 5) << 8) | data[pos]
                pos += 1
            elif kind == 2:
                ln = (tag >> 2) + 1
                off = int.from_bytes(data[pos:pos + 2], "little")
                pos += 2
            else:
                ln = (tag >> 2) + 1
                off = int.from_bytes(data[pos:pos + 4], "little")
                pos += 4
            if off == 0 or off > len(out):
                raise LeveldbError("corrupt snappy copy")
            while ln > 0:  # copies may overlap (run-length style)
                chunk = min(ln, off)
                start = len(out) - off
                out += out[start:start + chunk]
                ln -= chunk
    if len(out) != ulen:
        raise LeveldbError(
            f"snappy length mismatch: {len(out)} != {ulen}")
    return bytes(out)


def _read_block(data: bytes, offset: int, size: int) -> bytes:
    """Block contents + 1-byte type + 4-byte crc (crc unverified)."""
    raw = data[offset:offset + size]
    ctype = data[offset + size]
    if ctype == 0:
        return raw
    if ctype == 1:
        return snappy_decompress(raw)
    raise LeveldbError(f"unknown block compression {ctype}")


def _block_entries(block: bytes) -> Iterator[tuple[bytes, bytes]]:
    """Decode shared-prefix entries; the restart array sits at the tail."""
    if len(block) < 4:
        return
    n_restarts, = struct.unpack_from("<I", block, len(block) - 4)
    end = len(block) - 4 - 4 * n_restarts
    pos = 0
    key = b""
    while pos < end:
        shared, pos = _varint(block, pos)
        non_shared, pos = _varint(block, pos)
        vlen, pos = _varint(block, pos)
        key = key[:shared] + block[pos:pos + non_shared]
        pos += non_shared
        yield key, block[pos:pos + vlen]
        pos += vlen


def _read_sstable(path: str) -> Iterator[tuple[bytes, int, int, bytes]]:
    """Yield (user_key, sequence, type, value) from one table file."""
    with open(path, "rb") as f:
        data = f.read()
    if len(data) < 48:
        raise LeveldbError(f"{path}: truncated table")
    footer = data[-48:]
    magic, = struct.unpack_from("<Q", footer, 40)
    if magic != TABLE_MAGIC:
        raise LeveldbError(f"{path}: bad table magic {magic:#x}")
    pos = 0
    _mi_off, pos = _varint(footer, pos)
    _mi_size, pos = _varint(footer, pos)
    idx_off, pos = _varint(footer, pos)
    idx_size, pos = _varint(footer, pos)
    index = _read_block(data, idx_off, idx_size)
    for _last_key, handle in _block_entries(index):
        hpos = 0
        b_off, hpos = _varint(handle, hpos)
        b_size, hpos = _varint(handle, hpos)
        block = _read_block(data, b_off, b_size)
        for ikey, value in _block_entries(block):
            if len(ikey) < 8:
                raise LeveldbError(f"{path}: internal key too short")
            trailer, = struct.unpack_from("<Q", ikey, len(ikey) - 8)
            yield ikey[:-8], trailer >> 8, trailer & 0xFF, value


def _read_log(path: str) -> Iterator[tuple[bytes, int, int, bytes]]:
    """Yield (user_key, sequence, type, value) from a write-ahead log."""
    BLOCK = 32768
    with open(path, "rb") as f:
        data = f.read()
    record = bytearray()
    pos = 0
    while pos + 7 <= len(data):
        block_left = BLOCK - (pos % BLOCK)
        if block_left < 7:
            pos += block_left  # trailer padding
            continue
        _crc, length, rtype = struct.unpack_from("<IHB", data, pos)
        pos += 7
        if rtype == 0 and length == 0:
            break  # zeroed tail
        frag = data[pos:pos + length]
        pos += length
        if rtype == 1:        # FULL
            record = bytearray(frag)
        elif rtype == 2:      # FIRST
            record = bytearray(frag)
            continue
        elif rtype == 3:      # MIDDLE
            record += frag
            continue
        elif rtype == 4:      # LAST
            record += frag
        else:
            raise LeveldbError(f"{path}: bad log record type {rtype}")
        yield from _decode_batch(bytes(record))
        record = bytearray()


def _decode_batch(batch: bytes) -> Iterator[tuple[bytes, int, int, bytes]]:
    if len(batch) < 12:
        return
    seq, count = struct.unpack_from("<QI", batch, 0)
    pos = 12
    for i in range(count):
        t = batch[pos]
        pos += 1
        klen, pos = _varint(batch, pos)
        key = batch[pos:pos + klen]
        pos += klen
        if t == TYPE_VALUE:
            vlen, pos = _varint(batch, pos)
            value = batch[pos:pos + vlen]
            pos += vlen
        else:
            value = b""
        yield key, seq + i, t, value


class LeveldbReader:
    """Key-ordered reader over a LevelDB directory: a lazy heap-merge of
    the (sorted) sstables with the logs' memtable contents, newest sequence
    per key winning.  Only the logs are materialized up front — they hold
    at most a memtable's worth of recent writes; table blocks stream on
    demand, so ``first()`` (shape peeking) never scans the whole DB."""

    def __init__(self, path: str):
        if not os.path.isdir(path):
            raise LeveldbError(f"{path}: not a LevelDB directory")
        self.path = path
        self._tables = sorted(glob.glob(os.path.join(path, "*.ldb"))
                              + glob.glob(os.path.join(path, "*.sst")))
        log_entries: list[tuple[bytes, int, int, bytes]] = []
        for p in sorted(glob.glob(os.path.join(path, "*.log"))):
            log_entries.extend(_read_log(p))
        log_entries.sort(key=lambda e: (e[0], -e[1]))
        self._log_entries = log_entries
        self._len: int | None = None

    def items(self) -> Iterator[tuple[bytes, bytes]]:
        import heapq
        sources = [_read_sstable(p) for p in self._tables]
        sources.append(iter(self._log_entries))
        # order by (key, -seq): the first entry of each key group wins
        merged = heapq.merge(*sources, key=lambda e: (e[0], -e[1]))
        current: bytes | None = None
        for key, _seq, t, value in merged:
            if key == current:
                continue  # older version of the same key
            current = key
            if t == TYPE_VALUE:
                yield key, value

    def __len__(self) -> int:
        if self._len is None:
            self._len = sum(1 for _ in self.items())
        return self._len

    def first(self) -> tuple[bytes, bytes]:
        for kv in self.items():
            return kv
        raise LeveldbError("empty database")

    def close(self) -> None:
        self._tables = []
        self._log_entries = []

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# ---------------------------------------------------------------------------
# Minimal writer (log-only): enough for tests and small dataset creation.
# A log-only DB is exactly what leveldb leaves behind after Put()s with no
# compaction — any real leveldb (and this reader) recovers it.
# ---------------------------------------------------------------------------

_CRC32C_TABLE: list[int] | None = None

try:  # hardware/SIMD implementation when present (~GB/s vs ~8 MB/s pure)
    import google_crc32c as _gcrc
except ImportError:  # pragma: no cover - rig has the wheel
    _gcrc = None


def _crc32c(data: bytes, crc: int = 0) -> int:
    """CRC-32C (Castagnoli, reflected poly 0x82F63B78) — the checksum real
    leveldb verifies during log recovery."""
    if _gcrc is not None:
        return _gcrc.extend(crc, bytes(data))
    global _CRC32C_TABLE
    if _CRC32C_TABLE is None:
        table = []
        for i in range(256):
            c = i
            for _ in range(8):
                c = (c >> 1) ^ 0x82F63B78 if c & 1 else c >> 1
            table.append(c)
        _CRC32C_TABLE = table
    crc ^= 0xFFFFFFFF
    for b in data:
        crc = (crc >> 8) ^ _CRC32C_TABLE[(crc ^ b) & 0xFF]
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    """leveldb's crc mask (util/crc32c.h Mask)."""
    c = _crc32c(data)
    return ((((c >> 15) | (c << 17)) & 0xFFFFFFFF) + 0xA282EAD8) & 0xFFFFFFFF


def _write_log(path: str, records) -> None:
    """leveldb log_format: 32 KiB blocks of crc-checked
    FULL/FIRST/MIDDLE/LAST fragments (db/log_writer.cc)."""
    BLOCK = 32768
    with open(path, "wb") as f:
        written = 0
        for record in records:
            pos = 0
            first = True
            while True:
                left = BLOCK - (written % BLOCK)
                if left < 7:
                    f.write(b"\0" * left)
                    written += left
                    left = BLOCK
                frag = record[pos:pos + left - 7]
                pos += len(frag)
                last = pos >= len(record)
                rtype = 1 if (first and last) else (
                    2 if first else (4 if last else 3))
                crc = _masked_crc(bytes([rtype]) + frag)
                f.write(struct.pack("<IHB", crc, len(frag), rtype) + frag)
                written += 7 + len(frag)
                first = False
                if last:
                    break


def _varint_bytes(v: int) -> bytes:
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        out.append(b | (0x80 if v else 0))
        if not v:
            return bytes(out)


def write_leveldb(path: str, items) -> int:
    """Write items as a log-only LevelDB: CURRENT, a MANIFEST holding one
    valid VersionEdit (comparator + log/file numbers + last sequence;
    db/version_edit.cc tags), and one write-ahead .log with real
    crc32c-checked records — a log-only DB is exactly what leveldb leaves
    behind after Put()s with no compaction, so recovery replays the log.
    Format-correct per leveldb's log_format.md/version_edit.cc (this
    module's reader round-trips it; no real leveldb exists on this rig to
    countersign)."""
    os.makedirs(path, exist_ok=True)
    n = 0

    def batches():
        nonlocal n
        seq = 1
        for key, value in items:
            body = (struct.pack("<QI", seq, 1) + bytes([TYPE_VALUE])
                    + _varint_bytes(len(key)) + key
                    + _varint_bytes(len(value)) + value)
            yield body
            seq += 1
            n += 1

    _write_log(os.path.join(path, "000003.log"), batches())
    comparator = b"leveldb.BytewiseComparator"
    edit = (_varint_bytes(1) + _varint_bytes(len(comparator)) + comparator
            + _varint_bytes(2) + _varint_bytes(3)    # kLogNumber = 3
            + _varint_bytes(3) + _varint_bytes(4)    # kNextFileNumber = 4
            + _varint_bytes(4) + _varint_bytes(n))   # kLastSequence
    _write_log(os.path.join(path, "MANIFEST-000002"), [edit])
    with open(os.path.join(path, "CURRENT"), "w") as f:
        f.write("MANIFEST-000002\n")
    return n
