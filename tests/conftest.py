"""Test rig: force the host-CPU backend with 8 virtual devices.

This is the analog of the reference's CPU_ONLY cmake fallback
(reference: libccaffe/CMakeLists.txt:44-47) — it lets every test, including
the multi-chip collective paths, run with no TPU attached (SURVEY.md §4.3).
Must run before jax initializes its backends, hence the env mutation at
import time of conftest.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["JAX_PLATFORM_NAME"] = "cpu"  # the axon plugin ignores JAX_PLATFORMS
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest


@pytest.fixture
def rng():
    import jax
    return jax.random.PRNGKey(0)


@pytest.fixture
def np_rng():
    return np.random.default_rng(0)


_MP_PROBE: dict = {"result": None}


@pytest.fixture(scope="session")
def multiprocess_cpu() -> bool:
    """Whether this rig's CPU backend supports multiprocess XLA
    computations.  Some jax builds reject them outright ('Multiprocess
    computations aren't implemented on the CPU backend'); the multi-host
    and multi-process chaos tests skip there instead of failing on an
    environment limitation.  Probed once per session with a minimal
    2-process driver run."""
    if _MP_PROBE["result"] is None:
        import subprocess
        import sys
        import tempfile

        from sparknet_tpu.tools.launch import launch_local

        driver = os.path.join(os.path.dirname(__file__),
                              "multihost_driver.py")
        saved = dict(os.environ)
        os.environ.pop("XLA_FLAGS", None)   # this conftest's 8-device flag
        for k in list(os.environ):
            if k.startswith("SPARKNET_"):
                os.environ.pop(k)
        try:
            with tempfile.TemporaryDirectory() as td:
                rc = launch_local(
                    [sys.executable, driver, "--strategy", "sync",
                     "--out", os.path.join(td, "probe.npz"),
                     "--rounds", "1"],
                    nprocs=2, platform="cpu", devices_per_proc=2,
                    timeout=240)
        finally:
            os.environ.clear()
            os.environ.update(saved)
        _MP_PROBE["result"] = rc == 0
    return _MP_PROBE["result"]
