"""Classifier / Detector — prediction wrappers over a trained net (the
pycaffe ``Classifier``/``Detector`` analogs; reference:
caffe/python/caffe/classifier.py, detector.py, and the oversample helper
in caffe/python/caffe/io.py:340-384).

The reference exposes pycaffe as an alternative binding to the C++ core;
this framework's core *is* Python, so these are thin layers: load
prototxt + weights, preprocess (resize → mean subtract → center crop /
10-crop oversample / R-CNN context-padded window warp), jitted batched
forward.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np


def preprocess_image(img: np.ndarray, image_dims: tuple[int, int], *,
                     channel_swap: tuple[int, ...] | None = None,
                     raw_scale: float | None = None) -> np.ndarray:
    """(C,H,W) or (H,W,C)/(H,W) float image -> (C, *image_dims), with
    channel permutation and raw_scale applied — the ONE preprocessing
    implementation shared by the local :class:`Classifier` and the
    serving plane's :class:`RemoteClassifier`, so a prediction means the
    same thing whichever side of the wire ran it.  mean/input_scale
    happen per-crop at net-input size (:func:`transform_crops`)."""
    arr = np.asarray(img, np.float32)
    if arr.ndim == 2:
        arr = arr[None]
    elif arr.ndim == 3 and arr.shape[0] not in (1, 3):
        arr = arr.transpose(2, 0, 1)  # HWC -> CHW
    if channel_swap is not None and arr.shape[0] == len(channel_swap):
        arr = arr[list(channel_swap)]
    if raw_scale is not None:
        arr = arr * raw_scale
    h, w = image_dims
    if arr.shape[-2:] != (h, w):
        from .data.db import _warp
        arr = _warp(arr, h, w)
    return arr


def transform_crops(crops: np.ndarray,
                    mean: np.ndarray | float | None = None,
                    input_scale: float | None = None) -> np.ndarray:
    """Per-crop transform at net-input size (crop-sized / per-channel /
    scalar mean, then input_scale) — shared local/remote, like
    :func:`preprocess_image`."""
    if mean is not None:
        crops = crops - mean
    if input_scale is not None:
        crops = crops * input_scale
    return crops


def oversample(images: np.ndarray, crop: int) -> np.ndarray:
    """(N, C, H, W) -> (10N, C, crop, crop): four corners + center, and
    their mirrors (reference: caffe/python/caffe/io.py:340-384, in NCHW)."""
    n, c, h, w = images.shape
    ys = (0, h - crop)
    xs = (0, w - crop)
    cy, cx = (h - crop) // 2, (w - crop) // 2
    wins = [(y, x) for y in ys for x in xs] + [(cy, cx)]
    crops = np.empty((10 * n, c, crop, crop), images.dtype)
    for i, (y, x) in enumerate(wins):
        view = images[:, :, y:y + crop, x:x + crop]
        crops[i * n:(i + 1) * n] = view
        crops[(5 + i) * n:(6 + i) * n] = view[:, :, :, ::-1]
    return crops


class Classifier:
    """Load a deploy prototxt + weights and predict class probabilities.

    ``predict(inputs, oversample=True)`` matches Classifier.predict
    semantics: inputs are resized to ``image_dims``, then either
    center-cropped or 10-crop oversampled to the net's input size; crop
    predictions are averaged per input."""

    def __init__(self, model_file: str, pretrained_file: str | None = None,
                 image_dims: tuple[int, int] | None = None,
                 mean: np.ndarray | float | None = None,
                 input_scale: float | None = None,
                 raw_scale: float | None = None,
                 channel_swap=None):
        import jax

        from .graph import Net
        from .proto import NetState, Phase, load_net_prototxt

        net_param = load_net_prototxt(model_file)
        self.net = Net(net_param, NetState(Phase.TEST))
        params = self.net.init(jax.random.PRNGKey(0))
        if pretrained_file:
            from .solvers.solver import load_weights_into
            params = load_weights_into(self.net, params, pretrained_file)
        self.params = params
        self.input_name = next(iter(self.net.input_blobs))
        in_shape = self.net.input_blobs[self.input_name]
        self.crop = in_shape[-1]
        self.channels = in_shape[1]
        self.image_dims = tuple(image_dims or (self.crop, self.crop))
        self.mean = mean
        self.input_scale = input_scale
        self.raw_scale = raw_scale
        # channel permutation applied after HWC->CHW, before raw_scale —
        # classifier.py's RGB->BGR default path (Transformer
        # set_channel_swap ordering)
        self.channel_swap = tuple(channel_swap) if channel_swap else None
        self._fwd = jax.jit(
            lambda p, x: self.net.apply(p, {self.input_name: x},
                                        train=False).blobs)

    def _preprocess(self, img: np.ndarray) -> np.ndarray:
        """Delegates to the shared :func:`preprocess_image` (the
        Transformer is configured with the net blob shape, so a
        pycaffe-style mean array is crop-sized)."""
        return preprocess_image(img, self.image_dims,
                                channel_swap=self.channel_swap,
                                raw_scale=self.raw_scale)

    def _transform_crops(self, crops: np.ndarray) -> np.ndarray:
        return transform_crops(crops, self.mean, self.input_scale)

    def predict(self, inputs: Sequence[np.ndarray],
                oversample_crops: bool = True) -> np.ndarray:
        """Class probabilities, (N, classes); oversampled crops averaged
        per input (classifier.py predict)."""
        batch = np.stack([self._preprocess(im) for im in inputs])
        n = len(batch)
        if oversample_crops:
            crops = oversample(batch, self.crop)
        else:
            y = (batch.shape[2] - self.crop) // 2
            x = (batch.shape[3] - self.crop) // 2
            crops = batch[:, :, y:y + self.crop, x:x + self.crop]
        blobs = self._fwd(self.params, self._transform_crops(crops))
        # the prediction top: last single output (deploy nets end in prob)
        out = np.asarray(blobs[self.net.output_blobs[-1]])
        out = out.reshape(out.shape[0], -1)
        if oversample_crops:
            out = out.reshape(10, n, -1).mean(axis=0)
        return out


class Detector(Classifier):
    """Windowed (R-CNN style) detection: classify a list of image crops,
    each extracted with ``context_pad`` surrounding context and warped to
    the net input (reference: caffe/python/caffe/detector.py
    detect_windows + the window crop of window_data_layer.cpp)."""

    def __init__(self, model_file: str, pretrained_file: str | None = None,
                 mean: np.ndarray | float | None = None,
                 input_scale: float | None = None,
                 raw_scale: float | None = None,
                 channel_swap=None,
                 context_pad: int = 0):
        super().__init__(model_file, pretrained_file, mean=mean,
                         input_scale=input_scale, raw_scale=raw_scale,
                         channel_swap=channel_swap)
        self.context_pad = context_pad

    def detect_windows(self, images_windows: Sequence[tuple[np.ndarray,
                                                            Sequence]]):
        """``images_windows``: (image, [(y1, x1, y2, x2), ...]) pairs.
        Returns a flat list of {'window', 'prediction'} dicts, matching
        detect_windows' output shape."""
        from .data.db import _crop_warp_window
        crops, metas = [], []
        for image, windows in images_windows:
            arr = np.asarray(image, np.float32)
            if arr.ndim == 2:
                arr = arr[None]
            elif arr.ndim == 3 and arr.shape[0] not in (1, 3):
                arr = arr.transpose(2, 0, 1)
            if self.channel_swap is not None and \
                    arr.shape[0] == len(self.channel_swap):
                arr = arr[list(self.channel_swap)]
            if self.raw_scale is not None:
                arr = arr * self.raw_scale
            for (y1, x1, y2, x2) in windows:
                # mean/input_scale applied to the full crop buffer after
                # warp+paste — a crop-sized mean stays broadcastable even
                # for border-clipped windows
                win = _crop_warp_window(
                    arr, int(x1), int(y1), int(x2), int(y2), self.crop,
                    self.context_pad, use_square=False, do_mirror=False,
                    mean=None, scale=1.0)
                crops.append(win)
                metas.append((y1, x1, y2, x2))
        blobs = self._fwd(self.params,
                          self._transform_crops(np.stack(crops)))
        out = np.asarray(blobs[self.net.output_blobs[-1]])
        out = out.reshape(out.shape[0], -1)
        return [{"window": w, "prediction": out[i]}
                for i, w in enumerate(metas)]


# ---------------------------------------------------------------------------
# Remote (served) classification — the --server path of classify_cli
# ---------------------------------------------------------------------------

def http_json(url: str, payload: dict | None = None,
              timeout: float = 30.0) -> dict:
    """One JSON request against the serving plane (stdlib urllib — the
    client must not need more than the server ships).  HTTP errors with
    a JSON body surface as RuntimeError carrying the server's typed
    ``error``/``reason`` fields."""
    import json
    import urllib.error
    import urllib.request
    data = None if payload is None else json.dumps(payload).encode()
    req = urllib.request.Request(
        url, data=data,
        headers={"Content-Type": "application/json"} if data else {})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return json.loads(resp.read().decode())
    except urllib.error.HTTPError as e:
        try:
            body = json.loads(e.read().decode())
        except Exception:
            body = {}
        raise RuntimeError(
            f"{url}: HTTP {e.code} "
            f"({body.get('reason') or ''} {body.get('error') or e.reason})"
        ) from None


def remote_classify(url: str, model: str, arr: np.ndarray,
                    tenant: str = "classify",
                    timeout: float = 30.0) -> dict:
    """Submit ONE (C,H,W) float32 example to a running ``tools/serve.py``
    and return the server's JSON (probs + latency stamps)."""
    import base64
    arr = np.ascontiguousarray(arr, np.float32)
    return http_json(f"{url}/v1/classify", {
        "model": model, "tenant": tenant,
        "shape": list(arr.shape), "dtype": "float32",
        "data_b64": base64.b64encode(arr.tobytes()).decode(),
        "timeout_s": timeout,
    }, timeout=timeout + 10.0)


class RemoteClassifier:
    """Classifier.predict against a running inference server instead of a
    local compile: the SAME preprocessing (:func:`preprocess_image` /
    :func:`transform_crops` / :func:`oversample`) runs client-side, then
    each crop is submitted as its own request — the server's dynamic
    micro-batching coalesces the 10-crop fan-out back into one padded
    forward.  Net geometry (crop size, channels) comes from the server's
    ``/v1/models``, so client and server can never disagree about it."""

    def __init__(self, url: str, model: str,
                 image_dims: tuple[int, int] | None = None,
                 mean: np.ndarray | float | None = None,
                 input_scale: float | None = None,
                 raw_scale: float | None = None,
                 channel_swap=None, tenant: str = "classify",
                 timeout: float = 30.0):
        self.url = url.rstrip("/")
        self.model = model
        self.tenant = tenant
        self.timeout = timeout
        models = http_json(f"{self.url}/v1/models",
                           timeout=timeout).get("models", {})
        if model not in models:
            raise ValueError(
                f"server {url} has no model {model!r} "
                f"(loaded: {sorted(models)})")
        in_shape = models[model]["in_shape"]
        self.channels, self.crop = int(in_shape[0]), int(in_shape[-1])
        self.image_dims = tuple(image_dims or (self.crop, self.crop))
        self.mean = mean
        self.input_scale = input_scale
        self.raw_scale = raw_scale
        self.channel_swap = tuple(channel_swap) if channel_swap else None

    def predict(self, inputs: Sequence[np.ndarray],
                oversample_crops: bool = True) -> np.ndarray:
        """Class probabilities, (N, classes) — Classifier.predict
        semantics over the wire; crop requests are posted concurrently so
        the server micro-batches them."""
        from concurrent.futures import ThreadPoolExecutor
        batch = np.stack([
            preprocess_image(im, self.image_dims,
                             channel_swap=self.channel_swap,
                             raw_scale=self.raw_scale) for im in inputs])
        n = len(batch)
        if oversample_crops:
            crops = oversample(batch, self.crop)
        else:
            y = (batch.shape[2] - self.crop) // 2
            x = (batch.shape[3] - self.crop) // 2
            crops = batch[:, :, y:y + self.crop, x:x + self.crop]
        crops = transform_crops(crops, self.mean, self.input_scale)
        with ThreadPoolExecutor(max_workers=min(32, len(crops))) as ex:
            rows = list(ex.map(
                lambda c: remote_classify(self.url, self.model, c,
                                          tenant=self.tenant,
                                          timeout=self.timeout)["probs"],
                crops))
        out = np.asarray(rows, np.float32)
        if oversample_crops:
            out = out.reshape(10, n, -1).mean(axis=0)
        return out
