"""The single-step update pipeline shared by the host Solver and the
distributed trainers.

One authoritative implementation of: forward+backward (with BatchNorm
forward-state aux) → ClipGradients → Normalize → Regularize → rule update —
the ``Solver::Step`` inner body + ``ApplyUpdate`` sequence (reference:
caffe/src/caffe/solver.cpp:221-262, solvers/sgd_solver.cpp:102-143).
"""

from __future__ import annotations

import jax

from ..graph.net import Net
from ..proto.caffe_pb import SolverParameter
from .lr_policies import learning_rate
from .update_rules import SolverUpdate, preprocess_grads


def make_step_fns(sp: SolverParameter, net: Net, rule: SolverUpdate,
                  lr_mults, decay_mults):
    """Returns (loss_and_grads, local_update):

    - ``loss_and_grads(params, batch, rng) -> (loss, params_with_bn, grads)``
    - ``local_update(params, state, it, batch, rng) -> (params, state, loss)``
    """

    def loss_and_grads(params, batch, rng):
        def loss_fn(p):
            out = net.apply(p, batch, train=True, rng=rng)
            return out.loss, out.params
        (loss, new_params), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        return loss, new_params, grads

    def local_update(params, state, it, batch, rng):
        loss, params, grads = loss_and_grads(params, batch, rng)
        grads = preprocess_grads(sp, params, grads, lr_mults, decay_mults)
        rate = learning_rate(sp, it)
        params, state = rule.apply(params, grads, state, rate, it,
                                   lr_mults=lr_mults)
        return params, state, loss

    return loss_and_grads, local_update
