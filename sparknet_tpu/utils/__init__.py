from .checkpoint import CheckpointError, save_checkpoint, load_checkpoint
from .retry import retry_call
from .timing import Timer
