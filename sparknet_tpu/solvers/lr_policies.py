"""Learning-rate policies — Caffe-exact.

Mirrors ``SGDSolver::GetLearningRate`` (reference:
caffe/src/caffe/solvers/sgd_solver.cpp:27-79): fixed, step, exp, inv,
multistep, poly, sigmoid.  Implemented in jnp on a traced iteration scalar so
the whole schedule lives inside the compiled train step — no host round-trip
per step.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..proto.caffe_pb import SolverParameter


def learning_rate(sp: SolverParameter, it) -> jnp.ndarray:
    """Rate at iteration ``it`` (python int or traced int array)."""
    it = jnp.asarray(it, jnp.float32)
    base = sp.base_lr
    policy = sp.lr_policy
    if policy == "fixed":
        return jnp.full((), base, jnp.float32)
    if policy == "step":
        current = jnp.floor(it / sp.stepsize)
        return base * jnp.power(sp.gamma, current)
    if policy == "exp":
        return base * jnp.power(sp.gamma, it)
    if policy == "inv":
        return base * jnp.power(1.0 + sp.gamma * it, -sp.power)
    if policy == "multistep":
        boundaries = jnp.asarray(sp.stepvalue, jnp.float32)
        current = jnp.sum(it >= boundaries) if sp.stepvalue else 0
        return base * jnp.power(sp.gamma, current.astype(jnp.float32)
                                if sp.stepvalue else 0.0)
    if policy == "poly":
        return base * jnp.power(1.0 - it / max(sp.max_iter, 1), sp.power)
    if policy == "sigmoid":
        return base * (1.0 / (1.0 + jnp.exp(-sp.gamma * (it - sp.stepsize))))
    raise ValueError(f"unknown lr_policy {policy!r}")
