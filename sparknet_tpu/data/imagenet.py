"""ImageNet-style loader: tar archives of JPEGs + a filename→label map.

The analog of the reference's S3 loader chain (reference:
src/main/scala/loaders/ImageNetLoader.scala — list tar objects :25-38, read
the ``train.txt`` label map :41-54, workers stream-untar JPEG bytes :56-86,
``apply`` :91 yielding (bytes, label) pairs) followed by decode/force-resize
(reference: src/main/scala/preprocessing/ScaleAndConvert.scala:16-27, with
undecodable images silently dropped :23-25).

Sources are local paths or directories (the cluster data plane ships bytes
to hosts; S3/GCS staging is the launcher's job, as EC2 scripts were for the
reference).  Decode runs through the native C++ pipeline
(sparknet_tpu.native.decode_jpeg_resize) with a PIL fallback.
"""

from __future__ import annotations

import os
import tarfile
from typing import Iterator

import numpy as np

from .. import native
from .partition import PartitionedDataset


def read_label_map(path: str) -> dict[str, int]:
    """Parse a ``train.txt``-style "filename label" map
    (ImageNetLoader.getLabels, reference: ImageNetLoader.scala:41-54)."""
    labels: dict[str, int] = {}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            name, lab = line.rsplit(None, 1)
            labels[os.path.basename(name)] = int(lab)
    return labels


def list_tars(root: str, prefix: str = "") -> list[str]:
    """All .tar files under ``root`` matching the key prefix
    (ImageNetLoader.getFilePathsRDD, reference: ImageNetLoader.scala:25-38)."""
    out = []
    for dirpath, _, files in os.walk(root):
        for f in sorted(files):
            if f.endswith(".tar"):
                rel = os.path.relpath(os.path.join(dirpath, f), root)
                if rel.startswith(prefix):
                    out.append(os.path.join(dirpath, f))
    return sorted(out)


def stream_tar_images(tar_path: str, labels: dict[str, int],
                      ) -> Iterator[tuple[bytes, int]]:
    """Stream (jpeg bytes, label) from one tar
    (ImageNetLoader.loadImagesFromTar, reference: ImageNetLoader.scala:56-86).
    Entries missing from the label map are skipped."""
    with tarfile.open(tar_path) as tf:
        for member in tf:
            if not member.isfile():
                continue
            name = os.path.basename(member.name)
            if name not in labels:
                continue
            f = tf.extractfile(member)
            if f is None:
                continue
            yield f.read(), labels[name]


def decode_and_resize(pairs: Iterator[tuple[bytes, int]], size: int = 256,
                      ) -> Iterator[tuple[np.ndarray, int]]:
    """JPEG → planar f32 (3, size, size), force-resize; undecodable images
    dropped (ScaleAndConvert semantics)."""
    for data, label in pairs:
        img = native.decode_jpeg_resize(data, size, size)
        if img is not None:
            yield img, label


class LazyTarPartition:
    """A partition of (image, label) records decoded on access.

    Holds only an *index* — (tar key, byte offset, byte size, label) per
    record — so resident memory is O(records · ~100 bytes), not
    O(records · decoded image).  Slicing decodes just the touched window,
    which is exactly RoundFeed's contiguous-run access pattern; undecodable
    entries get drop-accounted per ScaleAndConvert semantics (replaced by
    the partition's first decodable image so batch shapes stay static,
    with the drop counted in ``dropped``)."""

    def __init__(self, entries: list[tuple[str, int, int, int]],
                 store, size: int):
        self.entries = entries
        self.store = store
        self.size = size
        self.decoded_count = 0     # observability + laziness tests
        self.dropped = 0
        self._fallback: tuple[np.ndarray, int] | None = None

    def __len__(self) -> int:
        return len(self.entries)

    def _get_fallback(self) -> tuple[np.ndarray, int]:
        """First decodable record of the partition (image AND its label —
        substituting pixels under a corrupt record's label would inject
        label noise)."""
        if self._fallback is None:
            for key, off, nbytes, label in self.entries:
                raw = self.store.open_range(key, off, nbytes)
                img = native.decode_jpeg_resize(raw, self.size, self.size)
                if img is not None:
                    self._fallback = (img, label)
                    break
            else:
                raise RuntimeError(
                    "no image in this partition decodes — the JPEG decode "
                    "layer (native libjpeg / PIL fallback) is unavailable "
                    "or broken, not the data")
        return self._fallback

    def _decode(self, entry) -> tuple[np.ndarray, int]:
        key, off, nbytes, label = entry
        raw = self.store.open_range(key, off, nbytes)
        self.decoded_count += 1
        img = native.decode_jpeg_resize(raw, self.size, self.size)
        if img is None:
            self.dropped += 1
            return self._get_fallback()
        if self._fallback is None:
            self._fallback = (img, label)
        return img, label

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return [self._decode(e) for e in self.entries[idx]]
        return self._decode(self.entries[idx])

    def __iter__(self):
        for e in self.entries:
            yield self._decode(e)


def index_tars(source: str, label_file: str, prefix: str = "",
               store=None) -> list[tuple[str, int, int, int]]:
    """One sequential pass over the tar headers building the lazy record
    index (no image bytes are read).  ``source`` may be a local dir,
    file:// URL, or s3://, gs:// (reference: ImageNetLoader.scala:25-54).
    Pass ``store`` to reuse an already-constructed client."""
    if store is None:
        from .objectstore import get_store
        store, key_prefix = get_store(source)
    else:
        key_prefix = ""
    labels = read_label_map(label_file)
    entries: list[tuple[str, int, int, int]] = []
    for key in store.list_keys(key_prefix or prefix):
        if not key.endswith(".tar"):
            continue
        with store.open(key) as f:
            with tarfile.open(fileobj=f, mode="r|") as tf:  # streaming
                for member in tf:
                    if not member.isfile():
                        continue
                    name = os.path.basename(member.name)
                    if name not in labels:
                        continue
                    entries.append((key, member.offset_data, member.size,
                                    labels[name]))
    if not entries:
        raise FileNotFoundError(
            f"no labeled images found under {source!r} "
            f"(labels: {len(labels)} entries)")
    return entries


def load_imagenet(tar_root: str, label_file: str, num_partitions: int,
                  size: int = 256, prefix: str = "", seed: int = 0,
                  ) -> PartitionedDataset:
    """Full chain: tars → record index → lazily-decoded partitions
    (ImageNetLoader.apply, reference: ImageNetLoader.scala:91; decode on
    access replaces the up-front ScaleAndConvert map, bounding RSS to the
    touched slices instead of the whole dataset)."""
    from .objectstore import get_store
    store, key_prefix = get_store(tar_root)
    entries = index_tars(tar_root, label_file, key_prefix or prefix,
                         store=store)
    rng = np.random.default_rng(seed)
    rng.shuffle(entries)
    parts = []
    n = max(1, num_partitions)
    per = len(entries) // n
    for w in range(n):
        lo = w * per
        hi = lo + per if w < n - 1 else len(entries)
        parts.append(LazyTarPartition(entries[lo:hi], store, size))
    return PartitionedDataset(parts)
