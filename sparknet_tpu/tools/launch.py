"""Multi-process launcher — the spark-submit analog.

The reference launches one driver + N executor JVMs via spark-submit
(reference: SETUP.md:45, README.md:60; worker-handle RDD at
ImageNetApp.scala:97).  Here the launcher only does *process placement* —
it carries no tensor traffic (that rides ICI/DCN via the JAX distributed
runtime).  Every spawned process gets the SPARKNET_COORDINATOR /
SPARKNET_NUM_PROCS / SPARKNET_PROC_ID env contract consumed by
``parallel.cluster.init_cluster_from_env``.

Modes:
  local  — spawn N processes on this machine (the CPU multi-process test
           rig; the analog of Spark local mode).  ``--devices-per-proc``
           carves virtual CPU devices per process.
  ssh    — run the command on each host of ``--hosts`` via ssh, process i
           on host i (plain SSH pod bring-up for TPU-VM workers, where
           each host sees its local chips natively).

Usage:
  python -m sparknet_tpu.tools.launch --nprocs 2 --devices-per-proc 2 \
      --platform cpu -- python -m sparknet_tpu.apps.cifar_app --synthetic ...
  python -m sparknet_tpu.tools.launch --hosts tpu-w0,tpu-w1 -- \
      python -m sparknet_tpu.apps.imagenet_app ...
"""

from __future__ import annotations

import argparse
import os
import socket
import subprocess
import sys
import threading
import time


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _proc_env(base: dict, coordinator: str, nprocs: int, pid: int,
              platform: str | None, devices_per_proc: int | None,
              extra_env: dict | None = None) -> dict:
    env = dict(base)
    env["SPARKNET_COORDINATOR"] = coordinator
    env["SPARKNET_NUM_PROCS"] = str(nprocs)
    env["SPARKNET_PROC_ID"] = str(pid)
    if platform:
        env["JAX_PLATFORMS"] = platform
        env["JAX_PLATFORM_NAME"] = platform
    if devices_per_proc:
        flags = env.get("XLA_FLAGS", "")
        env["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count="
            f"{devices_per_proc}").strip()
    if extra_env:
        env.update({k: str(v) for k, v in extra_env.items()})
    return env


def _wait_all(procs: list, timeout: float | None,
              poll_interval: float = 0.05) -> int:
    """Supervise the worker set: returns 0 when every process exits clean.
    The FIRST nonzero exit tears the whole round down — remaining workers
    are killed immediately rather than left hanging on a dead collective
    until the timeout (the stage-abort half of Spark's task supervision;
    the reschedule half lives in ``parallel.resilience``).  A timeout
    kills everything and returns 124."""
    deadline = time.monotonic() + timeout if timeout else None
    rc = 0
    pending = list(procs)
    while pending and rc == 0:
        for p in list(pending):
            r = p.poll()
            if r is None:
                continue
            pending.remove(p)
            if r != 0:
                rc = r
                break
        if rc == 0 and pending:
            if deadline is not None and time.monotonic() > deadline:
                rc = 124
                break
            time.sleep(poll_interval)
    for p in procs:
        if p.poll() is None:
            p.kill()
    for p in procs:
        try:
            p.wait(timeout=10)
        except subprocess.TimeoutExpired:  # pragma: no cover
            pass
    return rc


def _stream(prefix: str, pipe) -> None:
    for line in iter(pipe.readline, b""):
        sys.stderr.write(f"[{prefix}] {line.decode(errors='replace')}")
        sys.stderr.flush()


def launch_local(cmd: list[str], nprocs: int, *, platform: str | None = None,
                 devices_per_proc: int | None = None,
                 coordinator: str | None = None,
                 timeout: float | None = None,
                 extra_env: dict | None = None) -> int:
    """Spawn ``nprocs`` copies of ``cmd`` locally; returns the first
    non-zero exit code, else 0.  Output is streamed with [p<i>] prefixes.
    The first worker death kills the remaining workers immediately
    (see ``_wait_all``).  ``extra_env`` adds per-job vars to every child
    (the ResilientRunner's attempt-stamping channel)."""
    coordinator = coordinator or f"127.0.0.1:{free_port()}"
    procs = []
    threads = []
    for pid in range(nprocs):
        env = _proc_env(os.environ, coordinator, nprocs, pid, platform,
                        devices_per_proc, extra_env)
        p = subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                             stderr=subprocess.STDOUT)
        t = threading.Thread(target=_stream, args=(f"p{pid}", p.stdout),
                             daemon=True)
        t.start()
        procs.append(p)
        threads.append(t)
    rc = _wait_all(procs, timeout)
    for t in threads:
        t.join(timeout=5)
    return rc


def launch_ssh(cmd: list[str], hosts: list[str], *,
               coordinator_port: int | None = None,
               cwd: str | None = None,
               timeout: float | None = None,
               extra_env: dict | None = None) -> int:
    """Run ``cmd`` on every host via ssh; host 0 doubles as coordinator."""
    port = coordinator_port or 9876
    coordinator = f"{hosts[0]}:{port}"
    cwd = cwd or os.getcwd()
    procs = []
    threads = []
    for pid, host in enumerate(hosts):
        pairs = [
            ("SPARKNET_COORDINATOR", coordinator),
            ("SPARKNET_NUM_PROCS", str(len(hosts))),
            ("SPARKNET_PROC_ID", str(pid)),
        ]
        if extra_env:
            pairs.extend((k, str(v)) for k, v in extra_env.items())
        envs = " ".join(f"{k}={v!r}" for k, v in pairs)
        remote = f"cd {cwd} && env {envs} " + " ".join(cmd)
        p = subprocess.Popen(["ssh", "-o", "BatchMode=yes", host, remote],
                             stdout=subprocess.PIPE,
                             stderr=subprocess.STDOUT)
        t = threading.Thread(target=_stream, args=(host, p.stdout),
                             daemon=True)
        t.start()
        procs.append(p)
        threads.append(t)
    rc = _wait_all(procs, timeout)
    for t in threads:
        t.join(timeout=5)
    return rc


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="spark-submit analog: place N framework processes")
    ap.add_argument("--nprocs", type=int, default=None,
                    help="local mode: number of processes")
    ap.add_argument("--hosts", default=None,
                    help="ssh mode: comma-separated host list")
    ap.add_argument("--platform", default=None,
                    help="force JAX platform in children (e.g. cpu)")
    ap.add_argument("--devices-per-proc", type=int, default=None,
                    help="virtual CPU devices per process (test rigs)")
    ap.add_argument("--timeout", type=float, default=None)
    ap.add_argument("cmd", nargs=argparse.REMAINDER,
                    help="command to run (prefix with --)")
    args = ap.parse_args(argv)
    cmd = args.cmd[1:] if args.cmd and args.cmd[0] == "--" else args.cmd
    if not cmd:
        ap.error("no command given")
    if args.hosts:
        return launch_ssh(cmd, args.hosts.split(","), timeout=args.timeout)
    if not args.nprocs:
        ap.error("--nprocs or --hosts required")
    return launch_local(cmd, args.nprocs, platform=args.platform,
                        devices_per_proc=args.devices_per_proc,
                        timeout=args.timeout)


if __name__ == "__main__":
    sys.exit(main())
