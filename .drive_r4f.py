"""Drive: ranged fwd/bwd on a TRAIN net with dropout — mask replay."""
import jax; jax.config.update("jax_platforms", "cpu")
import numpy as np
from sparknet_tpu import pycaffe_compat as caffe

NET = """
name: "t"
input: "data"
input_shape { dim: 8 dim: 10 }
layer { name: "drop1" type: "Dropout" bottom: "data" top: "d1"
  dropout_param { dropout_ratio: 0.5 } }
layer { name: "ip1" type: "InnerProduct" bottom: "d1" top: "h"
  inner_product_param { num_output: 6 weight_filler { type: "xavier" } } }
layer { name: "drop2" type: "Dropout" bottom: "h" top: "d2"
  dropout_param { dropout_ratio: 0.5 } }
layer { name: "ip2" type: "InnerProduct" bottom: "d2" top: "out"
  inner_product_param { num_output: 3 weight_filler { type: "xavier" } } }
"""
net = caffe.Net(NET, phase=caffe.TRAIN)
rng = np.random.default_rng(1)
x = rng.normal(size=(8, 10)).astype(np.float32)
net.forward(data=x)
d2_after_fwd = net.blobs["d2"].data.copy()
dy = rng.normal(size=(8, 3)).astype(np.float32)
full = net.backward(diffs=["d1"], out=dy)
# ranged forward from ip2 (no stochastic layer in range) must not
# perturb the stream...
net.forward(start="ip2")
# ...so the ranged backward still replays the original masks: its
# range-input diff equals the full backward's
g = net.backward(start="ip2", end="ip1", out=dy)
assert np.allclose(g["d1"], full["d1"], atol=1e-6), "mask replay broken"
# and a NEW forward over a stochastic range resamples (Caffe resamples
# every Forward) — d2 legitimately changes
net.forward(start="ip1", end="drop2")
assert not np.array_equal(net.blobs["d2"].data, d2_after_fwd)
print("ranged stochastic drive OK; d1-grad norm",
      round(float(np.abs(g["d1"]).sum()), 4))
