"""Typed views over parsed prototxt for the Caffe config schema.

The reference's single source of truth is ``caffe.proto`` (reference:
caffe/src/caffe/proto/caffe.proto:64 NetParameter, :102 SolverParameter,
:310 LayerParameter); the JVM side uses 85k lines of protoc-generated Java
(src/main/java/caffe/Caffe.java).  Here we keep the parsed ``PMessage`` as
the backing store and expose typed dataclass views for the messages the
framework logic touches; per-layer parameter sub-messages stay as PMessage
and are read with defaulting accessors by the op implementations — the same
division of labor protobuf's descriptor layer provides, in ~2 orders of
magnitude less code.
"""

from __future__ import annotations

import dataclasses
import enum
import math
from typing import Any, Sequence

import numpy as np

from .textformat import EnumToken, PMessage, parse, serialize


class Phase(enum.IntEnum):
    TRAIN = 0
    TEST = 1


def blob_to_array(m: PMessage) -> "np.ndarray":
    """BlobProto -> ndarray (Blob::FromProto shape rules, reference:
    caffe/src/caffe/blob.cpp — ``shape`` if present, else legacy
    num/channels/height/width).  Data may arrive as packed numpy chunks
    (binary wire decode) or scalar floats (text parse)."""
    def flat_of(key: str):
        chunks = [np.atleast_1d(np.asarray(c)) for c in m.get_all(key)]
        if not chunks:
            return None
        flat = chunks[0] if len(chunks) == 1 else np.concatenate(chunks)
        return flat.astype(np.float32, copy=False)

    flat = flat_of("data")
    if flat is None:
        flat = flat_of("double_data")
    if flat is None:
        flat = np.zeros((0,), np.float32)
    shape_msg = m.get("shape")
    if isinstance(shape_msg, PMessage):
        shape = tuple(BlobShape.from_pmsg(shape_msg).dim)
    else:
        legacy = [int(m.get(k, 0)) for k in ("num", "channels", "height", "width")]
        shape = tuple(legacy) if any(legacy) else (flat.size,)
    if math.prod(shape) != flat.size:
        raise ValueError(f"BlobProto count {flat.size} != shape {shape} product")
    return flat.reshape(shape)


def _phase_of(v: Any) -> Phase | None:
    if v is None:
        return None
    if isinstance(v, Phase):
        return v
    if isinstance(v, str):
        return Phase[v]
    return Phase(int(v))


@dataclasses.dataclass
class BlobShape:
    dim: list[int]

    @classmethod
    def from_pmsg(cls, m: PMessage) -> "BlobShape":
        dims: list[int] = []
        for d in m.get_all("dim"):
            # binary decode yields packed numpy vectors; text yields scalars
            dims.extend(int(x) for x in np.atleast_1d(np.asarray(d)))
        return cls(dim=dims)

    def to_pmsg(self) -> PMessage:
        m = PMessage()
        for d in self.dim:
            m.add("dim", int(d))
        return m


@dataclasses.dataclass
class FillerParameter:
    """Weight-init config (reference: caffe/include/caffe/filler.hpp:31-146)."""

    type: str = "constant"
    value: float = 0.0
    min: float = 0.0
    max: float = 1.0
    mean: float = 0.0
    std: float = 1.0
    sparse: int = -1
    variance_norm: str = "FAN_IN"  # FAN_IN | FAN_OUT | AVERAGE

    @classmethod
    def from_pmsg(cls, m: PMessage | None) -> "FillerParameter":
        if m is None:
            return cls()
        return cls(
            type=str(m.get("type", "constant")),
            value=float(m.get("value", 0.0)),
            min=float(m.get("min", 0.0)),
            max=float(m.get("max", 1.0)),
            mean=float(m.get("mean", 0.0)),
            std=float(m.get("std", 1.0)),
            sparse=int(m.get("sparse", -1)),
            variance_norm=str(m.get("variance_norm", "FAN_IN")),
        )


@dataclasses.dataclass
class NetStateRule:
    """Phase/level/stage inclusion rule (reference: caffe.proto:263)."""

    phase: Phase | None = None
    min_level: int | None = None
    max_level: int | None = None
    stage: list[str] = dataclasses.field(default_factory=list)
    not_stage: list[str] = dataclasses.field(default_factory=list)

    @classmethod
    def from_pmsg(cls, m: PMessage) -> "NetStateRule":
        return cls(
            phase=_phase_of(m.get("phase")),
            min_level=m.get("min_level"),
            max_level=m.get("max_level"),
            stage=[str(s) for s in m.get_all("stage")],
            not_stage=[str(s) for s in m.get_all("not_stage")],
        )

    def to_pmsg(self) -> PMessage:
        m = PMessage()
        if self.phase is not None:
            m.add("phase", EnumToken(self.phase.name))
        if self.min_level is not None:
            m.add("min_level", int(self.min_level))
        if self.max_level is not None:
            m.add("max_level", int(self.max_level))
        for s in self.stage:
            m.add("stage", s)
        for s in self.not_stage:
            m.add("not_stage", s)
        return m

    def matches(self, state: "NetState") -> bool:
        """Mirror of Net::StateMeetsRule (reference: caffe/src/caffe/net.cpp:287-329)."""
        if self.phase is not None and self.phase != state.phase:
            return False
        if self.min_level is not None and state.level < int(self.min_level):
            return False
        if self.max_level is not None and state.level > int(self.max_level):
            return False
        for s in self.stage:
            if s not in state.stage:
                return False
        for s in self.not_stage:
            if s in state.stage:
                return False
        return True


@dataclasses.dataclass
class NetState:
    phase: Phase = Phase.TEST
    level: int = 0
    stage: list[str] = dataclasses.field(default_factory=list)

    @classmethod
    def from_pmsg(cls, m: PMessage | None) -> "NetState":
        if m is None:
            return cls()
        return cls(
            phase=_phase_of(m.get("phase")) or Phase.TEST,
            level=int(m.get("level", 0)),
            stage=[str(s) for s in m.get_all("stage")],
        )

    def to_pmsg(self) -> PMessage:
        m = PMessage()
        m.add("phase", EnumToken(self.phase.name))
        if self.level:
            m.add("level", int(self.level))
        for s in self.stage:
            m.add("stage", s)
        return m


@dataclasses.dataclass
class ParamSpec:
    """Per-learnable-blob training config (lr_mult/decay_mult).  The raw_*
    fields preserve proto2 presence (has_lr_mult) — param sharing needs to
    distinguish "explicitly 1.0" from "unset" (net.cpp AppendParam)."""

    name: str | None = None
    lr_mult: float = 1.0
    decay_mult: float = 1.0
    raw_lr_mult: float | None = None
    raw_decay_mult: float | None = None

    @classmethod
    def from_pmsg(cls, m: PMessage) -> "ParamSpec":
        raw_lr = m.get("lr_mult")
        raw_decay = m.get("decay_mult")
        return cls(
            name=m.get("name"),
            lr_mult=float(raw_lr) if raw_lr is not None else 1.0,
            decay_mult=float(raw_decay) if raw_decay is not None else 1.0,
            raw_lr_mult=float(raw_lr) if raw_lr is not None else None,
            raw_decay_mult=float(raw_decay) if raw_decay is not None else None,
        )


# V0 lowercase type names -> V1 enum names
# (reference: caffe/src/caffe/util/upgrade_proto.cpp UpgradeV0LayerType)
_V0_TYPE_MAP = {
    "accuracy": "ACCURACY", "bnll": "BNLL", "concat": "CONCAT",
    "conv": "CONVOLUTION", "data": "DATA", "dropout": "DROPOUT",
    "euclidean_loss": "EUCLIDEAN_LOSS", "flatten": "FLATTEN",
    "hdf5_data": "HDF5_DATA", "hdf5_output": "HDF5_OUTPUT",
    "im2col": "IM2COL", "images": "IMAGE_DATA",
    "infogain_loss": "INFOGAIN_LOSS", "innerproduct": "INNER_PRODUCT",
    "lrn": "LRN", "multinomial_logistic_loss": "MULTINOMIAL_LOGISTIC_LOSS",
    "pool": "POOLING", "relu": "RELU", "sigmoid": "SIGMOID",
    "softmax": "SOFTMAX", "softmax_loss": "SOFTMAX_LOSS", "split": "SPLIT",
    "tanh": "TANH", "window_data": "WINDOW_DATA", "padding": "PADDING",
}


def _net_needs_v0_upgrade(m: PMessage) -> bool:
    """V0 nets nest a V0LayerParameter inside each layers entry
    (upgrade_proto.cpp NetNeedsV0ToV1Upgrade)."""
    return any(isinstance(l, PMessage) and l.has("layer")
               for l in m.get_all("layers"))


def _upgrade_v0_padding(entries: list[PMessage]) -> list[PMessage]:
    """Fold explicit ``padding`` layers into the conv/pool layer that
    consumes them (upgrade_proto.cpp UpgradeV0PaddingLayers)."""
    top_src: dict[str, PMessage] = {}
    out: list[PMessage] = []
    for entry in entries:
        v0 = entry.get("layer")
        if v0 is not None and str(v0.get("type", "")) == "padding":
            for t in entry.get_all("top"):
                top_src[str(t)] = entry
            continue
        for i, b in enumerate(entry.get_all("bottom")):
            pad_entry = top_src.get(str(b))
            if pad_entry is None:
                continue
            pv0 = pad_entry.get("layer")
            if v0 is None or str(v0.get("type", "")) not in ("conv", "pool"):
                who = str(v0.get("name", "?")) if v0 is not None else "?"
                raise ValueError(
                    f"padding layer feeds non-conv/pool layer {who!r} "
                    "(undefined in Caffe; upgrade_proto.cpp CHECK)")
            v0.set("pad", pv0.get("pad", 0))
            bots = entry.get_all("bottom")
            bots[i] = pad_entry.get("bottom")
            entry.clear("bottom")
            for b2 in bots:
                entry.add("bottom", b2)
        for t in entry.get_all("top"):
            top_src.pop(str(t), None)
        out.append(entry)
    return out


def _upgrade_v0_layer(entry: PMessage) -> PMessage:
    """One V0 layers entry -> V1-style flat PMessage
    (upgrade_proto.cpp UpgradeV0LayerParameter)."""
    v0 = entry.get("layer")
    out = PMessage()
    for b in entry.get_all("bottom"):
        out.add("bottom", b)
    for t in entry.get_all("top"):
        out.add("top", t)
    if v0 is None:
        return out
    type_ = str(v0.get("type", ""))
    if v0.has("name"):
        out.add("name", v0.get("name"))
    out.add("type", _V0_TYPE_MAP.get(type_, type_))
    for key in ("blobs", "blobs_lr", "weight_decay"):
        for val in v0.get_all(key):
            out.add(key, val)

    subs: dict[str, PMessage] = {}

    def sub(name: str) -> PMessage:
        if name not in subs:
            subs[name] = PMessage()
        return subs[name]

    def move(v0_key: str, sub_name: str, new_key: str | None = None) -> None:
        if v0.has(v0_key):
            sub(sub_name).add(new_key or v0_key, v0.get(v0_key))

    if type_ == "conv":
        move("num_output", "convolution_param")
        move("biasterm", "convolution_param", "bias_term")
        move("weight_filler", "convolution_param")
        move("bias_filler", "convolution_param")
        move("pad", "convolution_param")
        move("kernelsize", "convolution_param", "kernel_size")
        move("group", "convolution_param")
        move("stride", "convolution_param")
    elif type_ == "innerproduct":
        move("num_output", "inner_product_param")
        move("biasterm", "inner_product_param", "bias_term")
        move("weight_filler", "inner_product_param")
        move("bias_filler", "inner_product_param")
    elif type_ == "pool":
        move("pad", "pooling_param")
        move("kernelsize", "pooling_param", "kernel_size")
        move("stride", "pooling_param")
        move("pool", "pooling_param")
    elif type_ == "dropout":
        move("dropout_ratio", "dropout_param")
    elif type_ == "lrn":
        move("local_size", "lrn_param")
        move("alpha", "lrn_param")
        move("beta", "lrn_param")
        move("k", "lrn_param")
    elif type_ == "data":
        move("source", "data_param")
        move("batchsize", "data_param", "batch_size")
        move("rand_skip", "data_param")
    elif type_ == "hdf5_data":
        move("source", "hdf5_data_param")
        move("batchsize", "hdf5_data_param", "batch_size")
    elif type_ == "images":
        move("source", "image_data_param")
        move("batchsize", "image_data_param", "batch_size")
        move("rand_skip", "image_data_param")
        move("shuffle_images", "image_data_param", "shuffle")
        move("new_height", "image_data_param")
        move("new_width", "image_data_param")
    elif type_ == "window_data":
        move("source", "window_data_param")
        move("batchsize", "window_data_param", "batch_size")
        move("det_fg_threshold", "window_data_param", "fg_threshold")
        move("det_bg_threshold", "window_data_param", "bg_threshold")
        move("det_fg_fraction", "window_data_param", "fg_fraction")
        move("det_context_pad", "window_data_param", "context_pad")
        move("det_crop_mode", "window_data_param", "crop_mode")
    elif type_ == "infogain_loss":
        move("source", "infogain_loss_param")
    elif type_ == "concat":
        move("concat_dim", "concat_param")
    # old-style transformation fields -> transform_param
    # (UpgradeNetDataTransformation)
    if type_ in ("data", "images", "window_data"):
        move("scale", "transform_param")
        move("meanfile", "transform_param", "mean_file")
        move("cropsize", "transform_param", "crop_size")
        move("mirror", "transform_param")
    for name, msg_ in subs.items():
        out.add(name, msg_)
    return out


_DATA_PARAM_OF = {"Data": "data_param", "ImageData": "image_data_param",
                  "WindowData": "window_data_param"}


def _upgrade_data_transform(lp: "LayerParameter") -> None:
    """Move old-style scale/mean_file/crop_size/mirror fields out of
    data_param and friends into transform_param (upgrade_proto.cpp
    UpgradeNetDataTransformation)."""
    pkey = _DATA_PARAM_OF.get(lp.type)
    if pkey is None or pkey not in lp.params:
        return
    p = lp.params[pkey]
    moved = {k: p.get(k) for k in ("scale", "mean_file", "crop_size",
                                   "mirror") if p.has(k)}
    if not moved:
        return
    tp = lp.params.setdefault("transform_param", PMessage())
    for k, v in moved.items():
        if not tp.has(k):
            tp.add(k, v)
        p.clear(k)


# V1LayerParameter enum type names -> V2 string type names
# (reference: caffe/src/caffe/util/upgrade_proto.cpp UpgradeV1LayerType)
_V1_TYPE_MAP = {
    "ABSVAL": "AbsVal", "ACCURACY": "Accuracy", "ARGMAX": "ArgMax",
    "BNLL": "BNLL", "CONCAT": "Concat", "CONTRASTIVE_LOSS": "ContrastiveLoss",
    "CONVOLUTION": "Convolution", "DECONVOLUTION": "Deconvolution",
    "DATA": "Data", "DROPOUT": "Dropout", "DUMMY_DATA": "DummyData",
    "EUCLIDEAN_LOSS": "EuclideanLoss", "ELTWISE": "Eltwise", "EXP": "Exp",
    "FLATTEN": "Flatten", "HDF5_DATA": "HDF5Data", "HDF5_OUTPUT": "HDF5Output",
    "HINGE_LOSS": "HingeLoss", "IM2COL": "Im2col", "IMAGE_DATA": "ImageData",
    "INFOGAIN_LOSS": "InfogainLoss", "INNER_PRODUCT": "InnerProduct",
    "LRN": "LRN", "MEMORY_DATA": "MemoryData",
    "MULTINOMIAL_LOGISTIC_LOSS": "MultinomialLogisticLoss", "MVN": "MVN",
    "POOLING": "Pooling", "POWER": "Power", "RELU": "ReLU",
    "SIGMOID": "Sigmoid", "SIGMOID_CROSS_ENTROPY_LOSS": "SigmoidCrossEntropyLoss",
    "SILENCE": "Silence", "SOFTMAX": "Softmax", "SOFTMAX_LOSS": "SoftmaxWithLoss",
    "SPLIT": "Split", "SLICE": "Slice", "TANH": "TanH",
    "WINDOW_DATA": "WindowData", "THRESHOLD": "Threshold",
}

# V1 nested blobs_lr/weight_decay -> ParamSpec
_PARAM_SUBMSG_KEYS = (
    "transform_param", "loss_param", "accuracy_param", "argmax_param",
    "batch_norm_param", "bias_param", "concat_param", "contrastive_loss_param",
    "convolution_param", "data_param", "dropout_param", "dummy_data_param",
    "eltwise_param", "embed_param", "exp_param", "flatten_param",
    "hdf5_data_param", "hdf5_output_param", "hinge_loss_param",
    "image_data_param", "infogain_loss_param", "inner_product_param",
    "input_param", "log_param", "lrn_param", "memory_data_param", "mvn_param",
    "pooling_param", "power_param", "prelu_param", "python_param",
    "reduction_param", "relu_param", "reshape_param", "scale_param",
    "sigmoid_param", "softmax_param", "spp_param", "slice_param",
    "tanh_param", "threshold_param", "tile_param", "window_data_param",
    "java_data_param",
)


@dataclasses.dataclass
class LayerParameter:
    """One layer of the net graph (reference: caffe.proto:310)."""

    name: str = ""
    type: str = ""
    bottom: list[str] = dataclasses.field(default_factory=list)
    top: list[str] = dataclasses.field(default_factory=list)
    phase: Phase | None = None
    loss_weight: list[float] = dataclasses.field(default_factory=list)
    param: list[ParamSpec] = dataclasses.field(default_factory=list)
    include: list[NetStateRule] = dataclasses.field(default_factory=list)
    exclude: list[NetStateRule] = dataclasses.field(default_factory=list)
    propagate_down: list[bool] = dataclasses.field(default_factory=list)
    # type-specific sub-configs, kept schema-free:
    params: dict[str, PMessage] = dataclasses.field(default_factory=dict)
    # trained weight blobs, present when loaded from a .caffemodel
    # (reference: caffe.proto LayerParameter.blobs=7, V1LayerParameter.blobs=6)
    blobs: list[Any] = dataclasses.field(default_factory=list)

    @classmethod
    def from_pmsg(cls, m: PMessage, v1: bool = False) -> "LayerParameter":
        type_ = m.get("type", "")
        if v1 and isinstance(type_, str) and type_ in _V1_TYPE_MAP:
            type_ = _V1_TYPE_MAP[type_]
        lp = cls(
            name=str(m.get("name", "")),
            type=str(type_),
            bottom=[str(b) for b in m.get_all("bottom")],
            top=[str(t) for t in m.get_all("top")],
            phase=_phase_of(m.get("phase")),
            loss_weight=[float(w) for w in m.get_all("loss_weight")],
            include=[NetStateRule.from_pmsg(r) for r in m.get_all("include")],
            exclude=[NetStateRule.from_pmsg(r) for r in m.get_all("exclude")],
            propagate_down=[bool(p) for p in m.get_all("propagate_down")],
        )
        # params: new-style `param { lr_mult ... }`; V1-style scalar
        # blobs_lr / weight_decay lists (upgrade_proto.cpp semantics).
        pmsgs = [p for p in m.get_all("param") if isinstance(p, PMessage)]
        shared_names = [p for p in m.get_all("param") if isinstance(p, str)]
        if pmsgs:
            lp.param = [ParamSpec.from_pmsg(p) for p in pmsgs]
        elif v1 and (m.has("blobs_lr") or m.has("weight_decay") or shared_names):
            lrs = [float(x) for x in m.get_all("blobs_lr")]
            wds = [float(x) for x in m.get_all("weight_decay")]
            n = max(len(lrs), len(wds), len(shared_names))
            for i in range(n):
                lp.param.append(ParamSpec(
                    name=shared_names[i] if i < len(shared_names) else None,
                    lr_mult=lrs[i] if i < len(lrs) else 1.0,
                    decay_mult=wds[i] if i < len(wds) else 1.0,
                    # V1 blobs_lr/weight_decay are explicit settings — keep
                    # presence so shared-param merge semantics see them
                    raw_lr_mult=lrs[i] if i < len(lrs) else None,
                    raw_decay_mult=wds[i] if i < len(wds) else None,
                ))
        for key in _PARAM_SUBMSG_KEYS:
            sub = m.get(key)
            if isinstance(sub, PMessage):
                lp.params[key] = sub
        lp.blobs = [blob_to_array(b) for b in m.get_all("blobs")
                    if isinstance(b, PMessage)]
        return lp

    def sub(self, key: str) -> PMessage:
        """Type-specific sub-config, empty message if absent."""
        return self.params.get(key) or PMessage()

    def to_pmsg(self, include_blobs: bool = False) -> PMessage:
        """Serialize back to a (new-style) layer message — the write half
        of the prototxt round-trip (upgrade tools, DSL-to-prototxt)."""
        m = PMessage()
        if self.name:
            m.add("name", self.name)
        if self.type:
            m.add("type", self.type)
        for b in self.bottom:
            m.add("bottom", b)
        for t in self.top:
            m.add("top", t)
        if self.phase is not None:
            m.add("phase", EnumToken(self.phase.name))
        for w in self.loss_weight:
            m.add("loss_weight", float(w))
        for ps in self.param:
            pm = PMessage()
            if ps.name:
                pm.add("name", ps.name)
            if ps.raw_lr_mult is not None:
                pm.add("lr_mult", ps.raw_lr_mult)
            if ps.raw_decay_mult is not None:
                pm.add("decay_mult", ps.raw_decay_mult)
            m.add("param", pm)
        for r in self.include:
            m.add("include", r.to_pmsg())
        for r in self.exclude:
            m.add("exclude", r.to_pmsg())
        for p in self.propagate_down:
            m.add("propagate_down", bool(p))
        for key, sub in self.params.items():
            m.add(key, sub)
        if include_blobs and self.blobs:
            from .caffemodel import array_to_blob
            for b in self.blobs:
                m.add("blobs", array_to_blob(np.asarray(b)))
        return m

    def included_in(self, state: NetState) -> bool:
        """Mirror of Net::FilterNet layer inclusion (reference: net.cpp:256-286):
        no rules -> included; include rules -> any match; exclude -> none match;
        plus the direct `phase` field used by ProtoLoader.replaceDataLayers."""
        if self.phase is not None and self.phase != state.phase:
            return False
        if self.include:
            return any(r.matches(state) for r in self.include)
        return not any(r.matches(state) for r in self.exclude)


@dataclasses.dataclass
class NetParameter:
    """The model graph config (reference: caffe.proto:64)."""

    name: str = ""
    layer: list[LayerParameter] = dataclasses.field(default_factory=list)
    input: list[str] = dataclasses.field(default_factory=list)
    input_shape: list[BlobShape] = dataclasses.field(default_factory=list)
    state: NetState = dataclasses.field(default_factory=NetState)
    force_backward: bool = False

    @classmethod
    def from_pmsg(cls, m: PMessage) -> "NetParameter":
        layers_new = m.get_all("layer")
        layers_v1 = m.get_all("layers")
        if _net_needs_v0_upgrade(m):
            # V0 -> V1 at the message level (padding folding + nested
            # V0LayerParameter flattening), then the V1 path below
            layers_v1 = [_upgrade_v0_layer(e)
                         for e in _upgrade_v0_padding(list(layers_v1))]
        layer = [LayerParameter.from_pmsg(l) for l in layers_new]
        layer += [LayerParameter.from_pmsg(l, v1=True) for l in layers_v1]
        for lp in layer:
            _upgrade_data_transform(lp)
        input_shape = [BlobShape.from_pmsg(s) for s in m.get_all("input_shape")]
        input_dims = [int(d) for d in m.get_all("input_dim")]
        if input_dims and not input_shape:
            # legacy input_dim: 4 ints per input blob
            for i in range(0, len(input_dims), 4):
                input_shape.append(BlobShape(dim=input_dims[i:i + 4]))
        return cls(
            name=str(m.get("name", "")),
            layer=layer,
            input=[str(i) for i in m.get_all("input")],
            input_shape=input_shape,
            state=NetState.from_pmsg(m.get("state")),
            force_backward=bool(m.get("force_backward", False)),
        )

    def to_pmsg(self, include_blobs: bool = False) -> PMessage:
        """Serialize to a new-style (V2) net message — always upgraded,
        exactly like the reference's upgrade_net_proto_* tools emit."""
        m = PMessage()
        if self.name:
            m.add("name", self.name)
        for i, name in enumerate(self.input):
            m.add("input", name)
        for s in self.input_shape:
            m.add("input_shape", s.to_pmsg())
        if self.force_backward:
            m.add("force_backward", True)
        if self.state != NetState():
            m.add("state", self.state.to_pmsg())
        for lp in self.layer:
            m.add("layer", lp.to_pmsg(include_blobs=include_blobs))
        return m

    def filtered(self, state: NetState) -> "NetParameter":
        """Phase-filtered copy — Net::FilterNet (reference: net.cpp:256)."""
        out = dataclasses.replace(
            self, layer=[l for l in self.layer if l.included_in(state)], state=state
        )
        return out


@dataclasses.dataclass
class SolverParameter:
    """Training config (reference: caffe.proto:102).  Field defaults follow
    the proto defaults used by SGDSolver (reference:
    caffe/src/caffe/solvers/sgd_solver.cpp, caffe/src/caffe/solver.cpp)."""

    net: str | None = None
    net_param: NetParameter | None = None
    train_net: str | None = None
    test_net: list[str] = dataclasses.field(default_factory=list)
    train_net_param: NetParameter | None = None
    test_net_param: list[NetParameter] = dataclasses.field(default_factory=list)
    train_state: NetState = dataclasses.field(default_factory=lambda: NetState(Phase.TRAIN))
    test_state: list[NetState] = dataclasses.field(default_factory=list)

    test_iter: list[int] = dataclasses.field(default_factory=list)
    test_interval: int = 0
    test_initialization: bool = True
    base_lr: float = 0.01
    display: int = 0
    average_loss: int = 1
    max_iter: int = 0
    iter_size: int = 1
    lr_policy: str = "fixed"
    gamma: float = 0.0
    power: float = 0.0
    momentum: float = 0.0
    weight_decay: float = 0.0
    regularization_type: str = "L2"
    stepsize: int = 0
    stepvalue: list[int] = dataclasses.field(default_factory=list)
    clip_gradients: float = -1.0
    snapshot: int = 0
    snapshot_prefix: str = ""
    random_seed: int = -1
    solver_type: str = "SGD"  # SGD|NESTEROV|ADAGRAD|RMSPROP|ADADELTA|ADAM
    delta: float = 1e-8
    momentum2: float = 0.999
    rms_decay: float = 0.99
    debug_info: bool = False
    snapshot_format: str = "BINARYPROTO"  # or HDF5 (caffe.proto:240-244)

    @classmethod
    def from_pmsg(cls, m: PMessage) -> "SolverParameter":
        def net_of(key: str) -> NetParameter | None:
            sub = m.get(key)
            return NetParameter.from_pmsg(sub) if isinstance(sub, PMessage) else None

        solver_type = m.get("type", m.get("solver_type", "SGD"))
        sp = cls(
            net=m.get("net"),
            net_param=net_of("net_param"),
            train_net=m.get("train_net"),
            test_net=[str(t) for t in m.get_all("test_net")],
            train_net_param=net_of("train_net_param"),
            test_net_param=[NetParameter.from_pmsg(t) for t in m.get_all("test_net_param")],
            test_iter=[int(t) for t in m.get_all("test_iter")],
            test_interval=int(m.get("test_interval", 0)),
            test_initialization=bool(m.get("test_initialization", True)),
            base_lr=float(m.get("base_lr", 0.01)),
            display=int(m.get("display", 0)),
            average_loss=int(m.get("average_loss", 1)),
            max_iter=int(m.get("max_iter", 0)),
            iter_size=int(m.get("iter_size", 1)),
            lr_policy=str(m.get("lr_policy", "fixed")),
            gamma=float(m.get("gamma", 0.0)),
            power=float(m.get("power", 0.0)),
            momentum=float(m.get("momentum", 0.0)),
            weight_decay=float(m.get("weight_decay", 0.0)),
            regularization_type=str(m.get("regularization_type", "L2")),
            stepsize=int(m.get("stepsize", 0)),
            stepvalue=[int(v) for v in m.get_all("stepvalue")],
            clip_gradients=float(m.get("clip_gradients", -1.0)),
            snapshot=int(m.get("snapshot", 0)),
            snapshot_prefix=str(m.get("snapshot_prefix", "")),
            random_seed=int(m.get("random_seed", -1)),
            solver_type=str(solver_type).upper(),
            delta=float(m.get("delta", 1e-8)),
            momentum2=float(m.get("momentum2", 0.999)),
            rms_decay=float(m.get("rms_decay", 0.99)),
            debug_info=bool(m.get("debug_info", False)),
            snapshot_format=str(m.get("snapshot_format",
                                      "BINARYPROTO")).upper(),
        )
        if m.has("train_state"):
            sp.train_state = NetState.from_pmsg(m.get("train_state"))
            sp.train_state.phase = Phase.TRAIN
        for ts in m.get_all("test_state"):
            st = NetState.from_pmsg(ts)
            st.phase = Phase.TEST
            sp.test_state.append(st)
        return sp


# ---------------------------------------------------------------------------
# Loading helpers — the ProtoLoader analog
# (reference: src/main/scala/libs/ProtoLoader.scala:9-57)
# ---------------------------------------------------------------------------

def load_net_prototxt(path_or_text: str) -> NetParameter:
    """Parse a net prototxt from a file path or literal text
    (ProtoLoader.loadNetPrototxt, reference: ProtoLoader.scala:20)."""
    text = _read(path_or_text)
    return NetParameter.from_pmsg(parse(text))


def load_solver_prototxt(path_or_text: str) -> SolverParameter:
    """ProtoLoader.loadSolverPrototxt (reference: ProtoLoader.scala:9)."""
    text = _read(path_or_text)
    return SolverParameter.from_pmsg(parse(text))


def load_solver_prototxt_with_net(
    solver_path_or_text: str,
    net: NetParameter,
    snapshot_prefix: str | None = None,
) -> SolverParameter:
    """Embed a net into a solver config, clearing snapshotting unless a
    prefix is given (ProtoLoader.loadSolverPrototxtWithNet, reference:
    ProtoLoader.scala:31-43)."""
    sp = load_solver_prototxt(solver_path_or_text)
    sp.net = None
    sp.train_net = None
    sp.test_net = []
    sp.net_param = net
    if snapshot_prefix is None:
        sp.snapshot = 0
        sp.snapshot_prefix = ""
    else:
        sp.snapshot_prefix = snapshot_prefix
    return sp


def save_net_prototxt(net: NetParameter, path_or_none: str | None = None
                      ) -> str:
    """Serialize a NetParameter (e.g. a DSL-built model) to prototxt text,
    optionally writing it to a file — the write half of the ProtoLoader
    round-trip (net_spec.py's to_proto role)."""
    text = serialize(net.to_pmsg())
    if path_or_none:
        with open(path_or_none, "w") as f:
            f.write(text)
    return text


def _resolve_ref_path(net_ref: str, solver_path: str,
                      extra_bases: Sequence[str] = ()) -> str:
    """Resolve one net file reference: cwd first (Caffe resolves
    relative to the process cwd — zoo solvers use paths like
    examples/cifar10/...), then the solver's own directory, its basename
    there, and any ``extra_bases``."""
    import os
    bases = ["", os.path.dirname(os.path.abspath(solver_path)) or "."]
    bases.extend(extra_bases)
    for base in bases:
        for cand in (os.path.join(base, net_ref) if base else net_ref,
                     os.path.join(base, os.path.basename(net_ref))
                     if base else net_ref):
            if os.path.exists(cand):
                return cand
    raise FileNotFoundError(f"cannot resolve net path {net_ref!r} "
                            f"(searched {bases})")


def resolve_net_path(sp: "SolverParameter", solver_path: str,
                     extra_bases: Sequence[str] = ()) -> str:
    """Resolve a solver's ``net:``/``train_net:`` file reference."""
    net_ref = sp.net or sp.train_net
    if net_ref is None:
        raise FileNotFoundError("solver has no net:/train_net: reference")
    return _resolve_ref_path(net_ref, solver_path, extra_bases)


def resolve_solver_nets(sp: "SolverParameter", solver_path: str) -> None:
    """Load every net file reference of a solver into its *_net_param
    fields (Solver::InitTrainNet/InitTestNets path resolution): ``net:``/
    ``train_net:`` into ``net_param`` and each ``test_net:`` entry into
    ``test_net_param``.  Embedded definitions win over file references."""
    if not (sp.net_param or sp.train_net_param):
        sp.net_param = load_net_prototxt(resolve_net_path(sp, solver_path))
    if sp.test_net and not sp.test_net_param:
        sp.test_net_param = [
            load_net_prototxt(_resolve_ref_path(p, solver_path))
            for p in sp.test_net]


def replace_data_layers(
    net: NetParameter,
    train_batch_size: int,
    test_batch_size: int,
    channels: int,
    height: int,
    width: int,
) -> NetParameter:
    """Swap the first data layer(s) for host-fed input layers, one per phase
    (ProtoLoader.replaceDataLayers, reference: ProtoLoader.scala:50-57).

    In the reference this installs ``JavaData`` layers whose forward calls
    back into the JVM; here the layer type marks a graph input fed by the
    host pipeline via ``device_put`` — the graph sees a plain input blob.
    """
    data_types = {
        "Data", "ImageData", "WindowData", "MemoryData", "HDF5Data",
        "DummyData", "JavaData", "Input",
    }
    kept = [l for l in net.layer if l.type not in data_types]
    # Collect tops across ALL stripped data layers — the reference's
    # JavaData nets use two single-top layers (data + label), e.g.
    # examples/cifar10/cifar10_full_java_train_test.prototxt.
    tops: list[str] = []
    for l in net.layer:
        if l.type in data_types:
            for t in l.top:
                if t not in tops:
                    tops.append(t)
    if not tops:
        tops = ["data", "label"]

    def make(phase: Phase, batch: int) -> LayerParameter:
        lp = LayerParameter(
            name=f"{tops[0]}_{phase.name.lower()}",
            type="JavaData",
            top=list(tops),
            phase=phase,
        )
        shape = PMessage()
        for d in (batch, channels, height, width):
            shape.add("dim", d)
        jd = PMessage()
        jd.add("shape", shape)
        if len(tops) > 1:
            lshape = PMessage()
            lshape.add("dim", batch)
            jd.add("label_shape", lshape)
        lp.params["java_data_param"] = jd
        return lp

    out = dataclasses.replace(net)
    out.layer = [make(Phase.TRAIN, train_batch_size), make(Phase.TEST, test_batch_size)] + kept
    return out


def _read(path_or_text: str) -> str:
    if "\n" in path_or_text or "{" in path_or_text:
        return path_or_text
    with open(path_or_text) as f:
        return f.read()
