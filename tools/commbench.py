"""Comm-codec parity gate (the compressed-exchange analog of roundbench).

Five verdicts on a small CPU mesh (~seconds), any failure = rc 1:

1. **codec-none bit-identity** — a trainer with ``comm_codec="none"``
   (overlap on OR off) must produce bit-identical losses and parameters
   to a trainer built with a pre-codec TrainerConfig: the codec machinery
   may not perturb the default path by even one ulp.
2. **error-feedback invariant** — for every real codec,
   ``decode(encode(delta)) + residual == delta`` exactly in f32 (the
   residual IS the deferred compression error).  A planted codec that
   drops residuals (``keep_residual=False``) MUST fail this gate — that
   failure is asserted, so the gate is proven able to catch the bug class
   it exists for.
3. **loss-band convergence** — int8/bf16 delta exchange with error
   feedback must land within a declared band of the full-precision
   trainer's loss after the same rounds (compression defers error, it
   must not change where training goes).
4. **overlap parity + stall** — ``comm_overlap=True`` must be
   bit-identical to False under a lossy codec, with strictly less
   steady-state host stall charged to the comm components (measured
   after a warm-up round so compile time is not the story).
5. **wire-byte shrink** — the int8 codec's per-round exchanged bytes
   must be ≥ 3× smaller than full precision (analytic, from the real
   encode via ``comms.exchange_bytes``).

Wired into tools/run_tier1.sh behind SPARKNET_COMMBENCH=1 (or
``--commbench``); the JSON doc ingests into the perf ledger via
``perfwatch regress --ingest`` (entries_from_commbench).

Usage:
    python tools/commbench.py [--rounds 8] [--devices 4] [--out FILE]

Prints one JSON line on stdout; rc 0 = all gates hold.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

LOSS_BAND = 0.05   # |final_loss_codec - final_loss_none| tolerance
REAL_CODECS = ("bf16", "int8", "int8_channel")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rounds", type=int, default=8)
    ap.add_argument("--devices", type=int, default=4,
                    help="CPU mesh width (virtual devices)")
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--tau", type=int, default=2)
    ap.add_argument("--out", default=None)
    args = ap.parse_args(argv)

    import jax
    import jax.numpy as jnp
    import numpy as np

    from sparknet_tpu.models import lenet
    from sparknet_tpu.parallel import (
        DistributedTrainer, TrainerConfig, comms, make_mesh,
    )
    from sparknet_tpu.proto import load_solver_prototxt_with_net

    tau = args.tau
    sp = load_solver_prototxt_with_net(
        'base_lr: 0.005\nmomentum: 0.9\nlr_policy: "fixed"\n',
        lenet(args.batch, args.batch))
    mesh = make_mesh(args.devices)

    def batch(r):
        rng = np.random.default_rng(4200 + r)
        return {"data": rng.normal(size=(tau, args.batch, 1, 28, 28)
                                   ).astype(np.float32),
                "label": rng.integers(0, 10, size=(tau, args.batch)
                                      ).astype(np.float32)}

    def run(cfg: TrainerConfig, measure_stall: bool = False) -> dict:
        tr = DistributedTrainer(sp, mesh, cfg, seed=0)
        t0 = time.perf_counter()
        warm = 1 if measure_stall else 0
        losses = []
        for r in range(args.rounds):
            loss = tr.train_round(batch(r))
            if r + 1 == warm:
                # compile + first dispatch settled: zero the comm
                # components so the reported stall is steady-state
                jax.block_until_ready(tr.params)
                for k in ("comm_encode", "comm_allreduce", "comm_decode"):
                    tr.stall_s[k] = 0.0
                t0 = time.perf_counter()
            losses.append(loss)
        tr.drain()
        jax.block_until_ready(tr.params)
        dt = time.perf_counter() - t0
        return {
            "losses": losses,
            "params": {k: [np.asarray(b) for b in v]
                       for k, v in tr.params.items()},
            "wall_s": round(dt, 3),
            "stall_s": {k: round(v, 4) for k, v in tr.stall_s.items()},
            "comm_stall_s": round(sum(
                v for k, v in tr.stall_s.items()
                if k.startswith("comm_")), 4),
        }

    def bit_identical(a: dict, b: dict) -> list[str]:
        out = []
        if a["losses"] != b["losses"]:
            out.append(f"losses diverge: {a['losses']} vs {b['losses']}")
        for name, blobs in a["params"].items():
            for i, x in enumerate(blobs):
                if not np.array_equal(x, b["params"][name][i]):
                    out.append(f"param {name}[{i}] not bit-identical")
        return out

    failures: list[str] = []

    # -- 1. codec none == the pre-codec trainer, overlap inert ------------
    base = run(TrainerConfig(strategy="local_sgd", tau=tau))
    none_off = run(TrainerConfig(strategy="local_sgd", tau=tau,
                                 comm_codec="none", comm_overlap=False))
    none_on = run(TrainerConfig(strategy="local_sgd", tau=tau,
                                comm_codec="none", comm_overlap=True))
    failures += [f"[none-vs-base] {m}" for m in bit_identical(base, none_off)]
    failures += [f"[none-overlap] {m}" for m in bit_identical(base, none_on)]

    # -- 2. error-feedback invariant; the planted residual-dropper FAILS --
    dropres = comms.Codec("int8_dropres",
                          encode=comms.get_codec("int8").encode,
                          decode=comms.get_codec("int8").decode,
                          keep_residual=False)
    rng = np.random.default_rng(7)
    delta = {
        "conv": [jnp.asarray(rng.normal(scale=1e-3, size=(4, 8, 1, 5, 5)),
                             jnp.float32)],
        "bias": [jnp.asarray(rng.normal(scale=1e-4, size=(4, 8)),
                             jnp.float32)],
    }

    def ef_invariant_holds(codec) -> bool:
        _, decoded, residual = comms.roundtrip_tree(codec, delta)
        recon = jax.tree_util.tree_map(lambda d, r: d + r, decoded, residual)
        return all(np.array_equal(np.asarray(a), np.asarray(b))
                   for a, b in zip(jax.tree_util.tree_leaves(recon),
                                   jax.tree_util.tree_leaves(delta)))

    ef = {name: ef_invariant_holds(comms.get_codec(name))
          for name in REAL_CODECS}
    ef["int8_dropres"] = ef_invariant_holds(dropres)
    for name in REAL_CODECS:
        if not ef[name]:
            failures.append(f"[ef] codec {name} violates the "
                            f"error-feedback invariant")
    if ef["int8_dropres"]:
        failures.append("[ef] planted residual-dropping codec PASSED the "
                        "invariant gate — the gate is broken, not the codec")

    # -- 3 + 4. lossy codecs: loss band, overlap parity, stall ------------
    codec_runs: dict[str, dict] = {}
    for name in REAL_CODECS:
        r = run(TrainerConfig(strategy="local_sgd", tau=tau,
                              comm_codec=name), measure_stall=True)
        codec_runs[name] = r
        drift = abs(r["losses"][-1] - base["losses"][-1])
        if not np.isfinite(r["losses"][-1]) or drift > LOSS_BAND:
            failures.append(
                f"[band] codec {name} final loss {r['losses'][-1]:.4f} "
                f"vs none {base['losses'][-1]:.4f} (|Δ|={drift:.4f} > "
                f"{LOSS_BAND})")
    int8_overlap = run(TrainerConfig(strategy="local_sgd", tau=tau,
                                     comm_codec="int8", comm_overlap=True),
                       measure_stall=True)
    failures += [f"[overlap-int8] {m}"
                 for m in bit_identical(codec_runs["int8"], int8_overlap)]
    stall_sync = codec_runs["int8"]["comm_stall_s"]
    stall_overlap = int8_overlap["comm_stall_s"]
    if not stall_overlap < stall_sync:
        failures.append(
            f"[stall] overlap did not reduce comm stall: "
            f"{stall_overlap}s overlapped vs {stall_sync}s synchronous")

    # -- 5. wire bytes ----------------------------------------------------
    tr_probe = DistributedTrainer(
        sp, mesh, TrainerConfig(strategy="local_sgd", tau=tau), seed=0)
    n_tier = args.devices
    bytes_none = comms.exchange_bytes(comms.get_codec("none"),
                                      tr_probe.params, n_tier)
    bytes_by_codec = {
        name: comms.exchange_bytes(comms.get_codec(name), tr_probe.params,
                                   n_tier)
        for name in REAL_CODECS}
    shrink = round(bytes_none / bytes_by_codec["int8"], 3)
    if shrink < 3.0:
        failures.append(f"[bytes] int8 shrink {shrink}x < 3x")

    result = {
        "commbench": True,   # ingest sniff key (perfledger.entries_from_any)
        "ok": not failures,
        "failures": failures,
        "rounds": args.rounds,
        "tau": tau,
        "batch": args.batch,
        "devices": args.devices,
        "ef_invariant": ef,
        "final_loss_none": base["losses"][-1],
        "none": {k: base[k] for k in ("wall_s", "stall_s")},
        "codecs": {
            name: {"wall_s": r["wall_s"], "stall_s": r["stall_s"],
                   "comm_stall_s": r["comm_stall_s"],
                   "final_loss": r["losses"][-1],
                   "exchange_bytes": bytes_by_codec[name]}
            for name, r in codec_runs.items()},
        "overlap_int8": {"wall_s": int8_overlap["wall_s"],
                         "stall_s": int8_overlap["stall_s"],
                         "comm_stall_s": stall_overlap},
        "exchange_bytes_none": bytes_none,
        "comm_stall_sync_s": stall_sync,
        "comm_stall_overlap_s": stall_overlap,
        "comm_bytes_shrink_x": shrink,
    }
    line = json.dumps(result)
    print(line, flush=True)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    if failures:
        print(f"[commbench] GATE FAILURE: {failures}", file=sys.stderr,
              flush=True)
        return 1
    print(f"[commbench] all gates hold: codec none bit-identical, EF "
          f"invariant green (planted dropper caught), int8 shrink "
          f"{shrink}x, comm stall {stall_sync}s sync -> {stall_overlap}s "
          f"overlapped", file=sys.stderr, flush=True)
    return 0


if __name__ == "__main__":
    # standalone: force the CPU backend with a virtual mesh BEFORE jax
    # initializes (the same rig contract as tests/conftest.py)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.setdefault("JAX_PLATFORM_NAME", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=4").strip()
    raise SystemExit(main())
