"""Profile the compiled train step and print a per-op time table.

The "where the time goes" tool the round-2 verdict demanded: capture a
``jax.profiler`` trace of N steps of the *same scanned train block the
benchmark times* (bench.py), parse the xplane.pb headlessly
(sparknet_tpu/utils/xplane.py), and print the device-plane op table plus
step-time and MFU so layout/precision experiments have a measured target.

Usage:
    python tools/profile_step.py [--model caffenet] [--batch 256]
        [--iters 20] [--dtype bf16] [--out profiles/caffenet] [--eval]

``--eval`` profiles the forward-only eval pass instead (the `caffe
time` forward leg): the scanned test-net forward with eval MFU in the
summary, written to profiles/<model>[_bf16]_eval by default.

The reference's closest analog is `caffe time` (per-layer fwd/bwd timing,
caffe/tools/caffe.cpp:290-376); this is per-XLA-op, post-fusion — the
view that actually explains TPU step time.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="caffenet",
                    choices=["caffenet", "googlenet", "vgg16", "lenet"])
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--dtype", default="f32", choices=["f32", "bf16"])
    ap.add_argument("--out", default=None,
                    help="trace dir (default profiles/<model>)")
    ap.add_argument("--top", type=int, default=25)
    ap.add_argument("--platform", default=None)
    ap.add_argument("--eval", action="store_true",
                    help="profile the forward-only eval pass (the "
                         "test-net `caffe time` forward leg) instead of "
                         "the train step — eval MFU in the summary")
    args = ap.parse_args()

    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                          os.path.join(os.path.dirname(os.path.dirname(
                              os.path.abspath(__file__))), ".jax_cache"))
    import jax
    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    import jax.numpy as jnp
    import numpy as np

    from sparknet_tpu.proto import load_solver_prototxt_with_net
    from sparknet_tpu.solvers import Solver
    from sparknet_tpu.utils import xplane
    from sparknet_tpu.utils.profiling import (
        BENCH_SOLVER_PROTOTXT,
        build_bench_model,
        eval_cost_flops,
        peak_flops,
        record_fusion_plan,
        record_tuning,
        scanned_eval_block,
        scanned_train_block,
        step_cost_flops,
    )

    net, in_shape, classes = build_bench_model(args.model, args.batch)
    sp = load_solver_prototxt_with_net(BENCH_SOLVER_PROTOTXT, net)
    solver = Solver(sp, seed=0,
                    compute_dtype=jnp.bfloat16 if args.dtype == "bf16" else None)

    rng = np.random.default_rng(0)
    data = jnp.asarray(rng.normal(size=(args.batch,) + in_shape).astype(np.float32))
    label = jnp.asarray(rng.integers(0, classes, size=(args.batch,)).astype(np.float32))
    batch = {"data": data[None], "label": label[None]}

    params, state = solver.params, solver.state
    step_rng = jax.random.PRNGKey(0)

    # cost_analysis of the fori_loop block would undercount (the while body
    # is costed once); cost the single step, exactly as bench.py does
    if args.eval:
        eval_batch = {"data": data, "label": label}
        block = scanned_eval_block(solver, args.iters)
        flops_per_step = eval_cost_flops(solver, eval_batch)

        def run_block(s):
            return block(params, eval_batch, s)
    else:
        block = scanned_train_block(solver, args.iters)
        flops_per_step = step_cost_flops(solver, batch)

    t0 = time.perf_counter()
    if args.eval:
        tap = run_block(jnp.zeros(()))
        jax.block_until_ready(tap)
    else:
        params, state, step_rng, loss = block(params, state, 0, batch,
                                              step_rng)
        jax.block_until_ready(loss)
    print(f"[profile] compile+warmup {time.perf_counter() - t0:.1f}s",
          file=sys.stderr)

    out_dir = args.out or os.path.join(
        "profiles",
        args.model + ("_bf16" if args.dtype == "bf16" else "")
        + ("_eval" if args.eval else ""))
    os.makedirs(out_dir, exist_ok=True)
    t0 = time.perf_counter()
    jax.profiler.start_trace(out_dir)
    if args.eval:
        tap = run_block(jnp.ones(()))
        jax.block_until_ready(tap)
    else:
        params, state, step_rng, loss = block(params, state, args.iters,
                                              batch, step_rng)
        jax.block_until_ready(loss)
    jax.profiler.stop_trace()
    dt = time.perf_counter() - t0
    step_s = dt / args.iters

    dev = jax.devices()[0]
    peak = peak_flops(dev.device_kind)
    mfu = (flops_per_step / step_s / peak) if (flops_per_step and peak) else None

    # CPU-runtime traces carry instruction names but no scope stats; the
    # optimized HLO of the SAME compiled block supplies the
    # name -> op_name join that recovers L[...] layer attribution
    # (xplane.hlo_layer_map).  Cheap on re-compile: the persistent
    # compilation cache already holds this executable.
    layer_map = None
    try:
        if args.eval:
            lowered = block.lower(params, eval_batch, jnp.zeros(()))
        else:
            lowered = block.lower(params, state, 0, batch, step_rng)
        layer_map = xplane.hlo_layer_map(lowered.compile().as_text())
    except Exception as e:
        print(f"[profile] no HLO layer map: {e}", file=sys.stderr)

    tables = xplane.op_tables(out_dir, top=args.top, layer_map=layer_map)
    print(xplane.format_tables(tables))
    # the profiled net's vertical-fusion plan: stamped into the summary
    # (the perf-ledger fingerprint field) and recorded next to the
    # op_table as fusion_plan.json so a capture is reproducible —
    # SPARKNET_FUSE=profiles/<model>/fusion_plan.json replays it exactly
    prof_net = solver.test_net if args.eval else solver.train_net
    summary = {
        "model": args.model, "batch": args.batch, "dtype": args.dtype,
        "mode": "eval_forward" if args.eval else "train_step",
        "fuse_plan": record_fusion_plan(prof_net, out_dir),
        "tune_plan": record_tuning(prof_net, out_dir),
        "device": f"{dev.platform}/{dev.device_kind}",
        "step_ms": round(step_s * 1e3, 2),
        "img_s": round(args.batch / step_s, 1),
        "mfu": round(mfu, 4) if mfu else None,
        "flops_per_step": flops_per_step,
        "trace_dir": out_dir,
    }
    busy_s = tables["total_ms"] / args.iters / 1e3
    summary["device_busy_ms_per_step"] = round(busy_s * 1e3, 2)
    if flops_per_step and peak and busy_s:
        # wall over the tunneled rig includes ~100ms RPC latency; the
        # device-busy MFU is the number that reflects the compiled step
        summary["mfu_device_busy"] = round(flops_per_step / busy_s / peak, 4)
    print(json.dumps(summary))
    with open(os.path.join(out_dir, "op_table.json"), "w") as f:
        json.dump({"summary": summary, **tables}, f, indent=1)


if __name__ == "__main__":
    main()
