"""The single-step update pipeline shared by the host Solver and the
distributed trainers.

One authoritative implementation of: forward+backward (with BatchNorm
forward-state aux) → ClipGradients → Normalize → Regularize → rule update —
the ``Solver::Step`` inner body + ``ApplyUpdate`` sequence (reference:
caffe/src/caffe/solver.cpp:221-262, solvers/sgd_solver.cpp:102-143).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..graph.net import Net
from ..proto.caffe_pb import SolverParameter
from .lr_policies import learning_rate
from .update_rules import SolverUpdate, preprocess_grads


def make_step_fns(sp: SolverParameter, net: Net, rule: SolverUpdate,
                  lr_mults, decay_mults):
    """Returns (loss_and_grads, local_update, accum_loss_and_grads):

    - ``loss_and_grads(params, batch, rng) -> (loss, params_with_bn, grads)``
    - ``local_update(params, state, it, batches, rng) -> (params, state,
      loss)`` — one full solver step over [iter_size, batch, ...] feeds
    - ``accum_loss_and_grads(params, batches, rng) -> (loss, params, grads)``
      — the ``iter_size`` micro-batch accumulation of ``Solver::Step``
      (reference: solver.cpp:221-224), raw summed grads (normalization by
      iter_size happens in ``preprocess_grads``)
    """

    def loss_and_grads(params, batch, rng):
        def loss_fn(p):
            out = net.apply(p, batch, train=True, rng=rng)
            return out.loss, out.params
        (loss, new_params), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        return loss, new_params, grads

    def accum_loss_and_grads(params, batches, rng):
        """``batches`` leaves carry a leading iter_size axis."""
        if sp.iter_size == 1:
            batch = jax.tree_util.tree_map(lambda x: x[0], batches)
            return loss_and_grads(params, batch, rng)

        def body(carry, batch):
            params, acc, rng = carry
            rng, sub = jax.random.split(rng)
            loss, params, g = loss_and_grads(params, batch, sub)
            acc = jax.tree_util.tree_map(jnp.add, acc, g)
            return (params, acc, rng), loss

        zero = jax.tree_util.tree_map(jnp.zeros_like, params)
        (params, grads, _), losses = jax.lax.scan(
            body, (params, zero, rng), batches)
        return jnp.mean(losses), params, grads

    def local_update(params, state, it, batches, rng):
        loss, params, grads = accum_loss_and_grads(params, batches, rng)
        grads = preprocess_grads(sp, params, grads, lr_mults, decay_mults)
        rate = learning_rate(sp, it)
        params, state = rule.apply(params, grads, state, rate, it,
                                   lr_mults=lr_mults)
        return params, state, loss

    return loss_and_grads, local_update, accum_loss_and_grads
