"""Multi-process launcher — the spark-submit analog.

The reference launches one driver + N executor JVMs via spark-submit
(reference: SETUP.md:45, README.md:60; worker-handle RDD at
ImageNetApp.scala:97).  Here the launcher only does *process placement* —
it carries no tensor traffic (that rides ICI/DCN via the JAX distributed
runtime).  Every spawned process gets the SPARKNET_COORDINATOR /
SPARKNET_NUM_PROCS / SPARKNET_PROC_ID env contract consumed by
``parallel.cluster.init_cluster_from_env``.

Modes:
  local  — spawn N processes on this machine (the CPU multi-process test
           rig; the analog of Spark local mode).  ``--devices-per-proc``
           carves virtual CPU devices per process.
  ssh    — run the command on each host of ``--hosts`` via ssh, process i
           on host i (plain SSH pod bring-up for TPU-VM workers, where
           each host sees its local chips natively).

Health plane: with ``heartbeat_dir`` set the children get
SPARKNET_HEARTBEAT_DIR (workers publish per-round beats via
``parallel.health.maybe_beat``), and with ``round_deadline`` the
supervisor additionally runs a ``StragglerMonitor`` over those beats — a
rank that beat once and then went silent past the deadline is declared
hung, killed, and the job torn down with exit code ``EXIT_STRAGGLER``
(125) so the resilience layer relaunches from checkpoint instead of
stalling until the global timeout.  ``log_dir`` tees every rank's output
to ``rank_<i>.log`` (the post-mortem ResilientRunner quotes), and a
caller-provided ``report`` dict receives per-rank exit codes, the first
failing rank, and any straggler kills.

Usage:
  python -m sparknet_tpu.tools.launch --nprocs 2 --devices-per-proc 2 \
      --platform cpu -- python -m sparknet_tpu.apps.cifar_app --synthetic ...
  python -m sparknet_tpu.tools.launch --hosts tpu-w0,tpu-w1 -- \
      python -m sparknet_tpu.apps.imagenet_app ...
"""

from __future__ import annotations

import argparse
import os
import socket
import subprocess
import sys
import threading
import time


EXIT_STRAGGLER = 125   # a rank was killed for missing the round deadline

# ssh-mode addresses that mean "spawn here, not over ssh" — the
# simulated N-host pod rig runs every 'host' on one CPU box with these
LOCAL_ADDRS = ("local", "localhost", "127.0.0.1")


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _proc_env(base: dict, coordinator: str, nprocs: int, pid: int,
              platform: str | None, devices_per_proc: int | None,
              extra_env: dict | None = None) -> dict:
    env = dict(base)
    env["SPARKNET_COORDINATOR"] = coordinator
    env["SPARKNET_NUM_PROCS"] = str(nprocs)
    env["SPARKNET_PROC_ID"] = str(pid)
    if platform:
        env["JAX_PLATFORMS"] = platform
        env["JAX_PLATFORM_NAME"] = platform
    if devices_per_proc:
        flags = env.get("XLA_FLAGS", "")
        env["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count="
            f"{devices_per_proc}").strip()
    if extra_env:
        env.update({k: str(v) for k, v in extra_env.items()})
    return env


def _wait_all(procs: list, timeout: float | None,
              poll_interval: float = 0.05, monitor=None,
              report: dict | None = None) -> int:
    """Supervise the worker set: returns 0 when every process exits clean.
    The FIRST nonzero exit tears the whole round down — remaining workers
    are killed immediately rather than left hanging on a dead collective
    until the timeout (the stage-abort half of Spark's task supervision;
    the reschedule half lives in ``parallel.resilience``).  A timeout
    kills everything and returns 124.

    ``monitor`` (a ``parallel.health.StragglerMonitor``) is polled with
    the still-live rank set; any rank it flags is killed and the job
    torn down with EXIT_STRAGGLER — a hung rank costs one round-deadline,
    not the whole timeout.  ``report`` (if given) is filled with the
    post-mortem: per-rank exit codes, the first failing rank, straggler
    kills, and the failure cause."""
    deadline = time.monotonic() + timeout if timeout else None
    rc = 0
    rcs: dict[int, int | None] = {i: None for i in range(len(procs))}
    first_failure: int | None = None
    stragglers: list[int] = []
    cause = ""
    pending = dict(enumerate(procs))
    while pending and rc == 0:
        for rank, p in list(pending.items()):
            r = p.poll()
            if r is None:
                continue
            del pending[rank]
            rcs[rank] = r
            if r != 0:
                rc, first_failure, cause = r, rank, "exit"
                break
        if rc == 0 and pending:
            if monitor is not None:
                hung = monitor.check(sorted(pending))
                if hung:
                    rc, first_failure, cause = (
                        EXIT_STRAGGLER, hung[0], "straggler")
                    stragglers = hung
                    for rank in hung:
                        print(f"launch: rank {rank} missed the round "
                              f"deadline ({monitor.deadline_s:.3g}s); "
                              f"killing as hung", file=sys.stderr,
                              flush=True)
                        pending[rank].kill()
                    break
            if deadline is not None and time.monotonic() > deadline:
                rc, cause = 124, "timeout"
                break
            time.sleep(poll_interval)
    for p in procs:
        if p.poll() is None:
            p.kill()
    for rank, p in enumerate(procs):
        try:
            p.wait(timeout=10)
        except subprocess.TimeoutExpired:  # pragma: no cover
            pass
        if rcs.get(rank) is None:
            rcs[rank] = p.poll()
    if rc == 0:
        cause = "clean"
    if report is not None:
        report.update(rcs=rcs, first_failure=first_failure,
                      stragglers=stragglers, cause=cause)
    return rc


def _stream(prefix: str, pipe, log_path: str | None = None) -> None:
    log = open(log_path, "ab") if log_path else None
    try:
        for line in iter(pipe.readline, b""):
            sys.stderr.write(f"[{prefix}] {line.decode(errors='replace')}")
            sys.stderr.flush()
            if log is not None:
                log.write(line)
                log.flush()
    finally:
        if log is not None:
            log.close()


def _make_monitor(heartbeat_dir: str | None, round_deadline: float | None,
                  *, host_map: list | None = None, transport=None,
                  host_suspect_probe=None, host_down_probe=None):
    if not (heartbeat_dir and round_deadline):
        return None
    # lazy import: the health plane is optional and the launcher should
    # stay importable without it on minimal rigs
    from ..parallel.health import GangHealth, StragglerMonitor
    os.makedirs(heartbeat_dir, exist_ok=True)
    # lease-aware gang monitor when beats ride a remote transport (the
    # relay is part of the tick) or the fleet can mark hosts suspect —
    # either way partition-vs-death discipline applies
    if host_map is not None and (
            (transport is not None and not transport.local)
            or host_suspect_probe is not None):
        return GangHealth(heartbeat_dir, round_deadline, host_map=host_map,
                          transport=transport,
                          suspect_probe=host_suspect_probe,
                          down_probe=host_down_probe)
    return StragglerMonitor(heartbeat_dir, round_deadline)


def _rank_hb_dir(heartbeat_dir: str | None,
                 host_map: list | None, rank: int) -> str | None:
    """Rank ``rank``'s beacon dir: the per-host ``host_<name>/`` subdir
    when a host placement is given (so supervisors can roll liveness up
    per host — health.read_hosts), else the flat root."""
    if not heartbeat_dir:
        return None
    if not host_map:
        return heartbeat_dir
    from ..parallel.health import host_dir
    return host_dir(heartbeat_dir, str(host_map[rank]))


def _check_host_map(host_map: list | None, n: int) -> None:
    if host_map is not None and len(host_map) != n:
        raise ValueError(f"host_map has {len(host_map)} entries for "
                         f"{n} ranks — one host label per rank required")


def launch_local(cmd: list[str], nprocs: int, *, platform: str | None = None,
                 devices_per_proc: int | None = None,
                 coordinator: str | None = None,
                 timeout: float | None = None,
                 extra_env: dict | None = None,
                 heartbeat_dir: str | None = None,
                 round_deadline: float | None = None,
                 log_dir: str | None = None,
                 report: dict | None = None,
                 host_map: list | None = None,
                 on_spawn=None) -> int:
    """Spawn ``nprocs`` copies of ``cmd`` locally; returns the first
    non-zero exit code, else 0.  Output is streamed with [p<i>] prefixes.
    The first worker death kills the remaining workers immediately
    (see ``_wait_all``).  ``extra_env`` adds per-job vars to every child
    (the ResilientRunner's attempt-stamping channel); ``heartbeat_dir`` /
    ``round_deadline`` / ``log_dir`` / ``report`` are the health plane
    (module docstring).  ``host_map`` (one host label per rank) stamps
    SPARKNET_FLEET_HOST on each child and routes its beacons into the
    per-host ``host_<name>/`` subdir — the simulated-pod rig's placement
    channel.  ``on_spawn`` (if given) receives the list of
    ``subprocess.Popen`` handles once the full gang is up — an external
    supervisor's only safe channel to the worker pids (for preemption
    signals and orphan accounting; see ``parallel.fleet``)."""
    _check_host_map(host_map, nprocs)
    coordinator = coordinator or f"127.0.0.1:{free_port()}"
    monitor = _make_monitor(heartbeat_dir, round_deadline)
    if log_dir:
        os.makedirs(log_dir, exist_ok=True)
    procs = []
    threads = []
    for pid in range(nprocs):
        env = _proc_env(os.environ, coordinator, nprocs, pid, platform,
                        devices_per_proc, extra_env)
        hb = _rank_hb_dir(heartbeat_dir, host_map, pid)
        if hb:
            env["SPARKNET_HEARTBEAT_DIR"] = hb
        if host_map:
            env["SPARKNET_FLEET_HOST"] = str(host_map[pid])
        p = subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                             stderr=subprocess.STDOUT)
        log = os.path.join(log_dir, f"rank_{pid}.log") if log_dir else None
        t = threading.Thread(target=_stream, args=(f"p{pid}", p.stdout, log),
                             daemon=True)
        t.start()
        procs.append(p)
        threads.append(t)
    if on_spawn is not None:
        on_spawn(list(procs))
    rc = _wait_all(procs, timeout, monitor=monitor, report=report)
    for t in threads:
        t.join(timeout=5)
    return rc


def launch_ssh(cmd: list[str], hosts: list[str], *,
               coordinator_port: int | None = None,
               cwd: str | None = None,
               timeout: float | None = None,
               extra_env: dict | None = None,
               heartbeat_dir: str | None = None,
               round_deadline: float | None = None,
               log_dir: str | None = None,
               report: dict | None = None,
               platform: str | None = None,
               devices_per_proc: int | None = None,
               host_map: list | None = None,
               on_spawn=None,
               transport=None,
               host_suspect_probe=None,
               host_down_probe=None) -> int:
    """Run ``cmd`` on every host via the host transport; host 0 doubles
    as coordinator.  ``transport`` (a ``parallel.transport.HostTransport``)
    is the exec/ship/beat seam; when omitted it is chosen from the env —
    ssh when ``SPARKNET_SSH_CMD`` is set or any address is remote, local
    otherwise, chaos-wrapped when network faults are active.  Addresses
    in ``LOCAL_ADDRS`` are spawned directly ONLY under a local transport;
    with SPARKNET_SSH_CMD set even ``localhost`` rides the ssh wire
    format (that is the CI fake-ssh rig — the argv/env/stdio plumbing is
    the production path, no sshd required).

    Health plane: under a local transport ranks beat straight into the
    shared ``heartbeat_dir``; under a remote one each rank beats into a
    host-local staging dir and the supervisor's monitor relays beats
    back over the transport each tick, with LEASE discipline on top —
    a whole-host beacon silence marks the host SUSPECT and *suspends*
    its ranks (a network partition must not kill a healthy gang or burn
    restart budget) unless ``host_down_probe`` confirms real death, in
    which case the straggler kill proceeds and the resilience layer
    takes the lost-host path.  ``host_suspect_probe`` lets the fleet
    feed externally-known suspicion into the same suspension.

    ``platform``/``devices_per_proc`` apply to direct local spawns
    (remote hosts see their chips natively).  ``host_map`` gives each
    rank its host *label* (defaults to its address) for beacon routing
    and the SPARKNET_FLEET_HOST tag.  ``on_spawn`` receives the local
    ``Popen`` handles (signalling an ssh one ends its remote command via
    the ssh session, so preemption still works, host by host)."""
    _check_host_map(host_map, len(hosts))
    if host_map is None:
        host_map = [str(h) for h in hosts]
    if transport is None:
        from ..parallel.transport import default_transport
        transport = default_transport(hosts)
    all_local = all(h in LOCAL_ADDRS for h in hosts)
    port = coordinator_port or (free_port() if all_local else 9876)
    addr0 = "127.0.0.1" if hosts[0] in LOCAL_ADDRS else hosts[0]
    coordinator = f"{addr0}:{port}"
    cwd = cwd or os.getcwd()
    monitor = _make_monitor(heartbeat_dir, round_deadline,
                            host_map=host_map, transport=transport,
                            host_suspect_probe=host_suspect_probe,
                            host_down_probe=host_down_probe)
    if log_dir:
        os.makedirs(log_dir, exist_ok=True)
    procs = []
    threads = []
    for pid, host in enumerate(hosts):
        direct = host in LOCAL_ADDRS and transport.local
        if direct:
            hb = _rank_hb_dir(heartbeat_dir, host_map, pid)
            env = _proc_env(os.environ, coordinator, len(hosts), pid,
                            platform, devices_per_proc, extra_env)
            if hb:
                os.makedirs(hb, exist_ok=True)
                env["SPARKNET_HEARTBEAT_DIR"] = hb
            env["SPARKNET_FLEET_HOST"] = str(host_map[pid])
            p = subprocess.Popen(cmd, env=env, cwd=cwd,
                                 stdout=subprocess.PIPE,
                                 stderr=subprocess.STDOUT)
        else:
            pairs = [
                ("SPARKNET_COORDINATOR", coordinator),
                ("SPARKNET_NUM_PROCS", str(len(hosts))),
                ("SPARKNET_PROC_ID", str(pid)),
                ("SPARKNET_FLEET_HOST", str(host_map[pid])),
            ]
            if heartbeat_dir:
                # remote ranks beat into host-local staging; the
                # monitor's relay moves beats into host_<name>/ — the
                # shared-filesystem assumption stops at the supervisor
                from ..parallel.health import stage_dir
                pairs.append(("SPARKNET_HEARTBEAT_DIR",
                              stage_dir(heartbeat_dir,
                                        str(host_map[pid]))))
            if extra_env:
                pairs.extend((k, str(v)) for k, v in extra_env.items())
            p = transport.popen(host, cmd, env_pairs=pairs, cwd=cwd)
        log = os.path.join(log_dir, f"rank_{pid}.log") if log_dir else None
        tag = host_map[pid] if direct else host
        t = threading.Thread(target=_stream, args=(tag, p.stdout, log),
                             daemon=True)
        t.start()
        procs.append(p)
        threads.append(t)
    if on_spawn is not None:
        on_spawn(list(procs))
    rc = _wait_all(procs, timeout, monitor=monitor, report=report)
    for t in threads:
        t.join(timeout=5)
    if report is not None:
        report["transport"] = transport.kind
        if monitor is not None and hasattr(monitor, "ever_suspect"):
            report["suspect_hosts"] = sorted(monitor.ever_suspect)
            report["confirmed_down"] = sorted(monitor.confirmed_down)
    return rc


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="spark-submit analog: place N framework processes")
    ap.add_argument("--nprocs", type=int, default=None,
                    help="local mode: number of processes")
    ap.add_argument("--hosts", default=None,
                    help="ssh mode: comma-separated host list")
    ap.add_argument("--platform", default=None,
                    help="force JAX platform in children (e.g. cpu)")
    ap.add_argument("--devices-per-proc", type=int, default=None,
                    help="virtual CPU devices per process (test rigs)")
    ap.add_argument("--timeout", type=float, default=None)
    ap.add_argument("--heartbeat-dir", default=None,
                    help="shared dir for worker liveness beacons")
    ap.add_argument("--round-deadline", type=float, default=None,
                    help="seconds of beacon silence before a rank is "
                         "declared hung and killed (needs --heartbeat-dir)")
    ap.add_argument("--log-dir", default=None,
                    help="tee each rank's output to rank_<i>.log here")
    ap.add_argument("--feed-workers", type=int, default=None,
                    help="decode-pool width per worker (exported as "
                         "SPARKNET_FEED_WORKERS; 0 = serial feed path)")
    ap.add_argument("--feed-depth", type=int, default=None,
                    help="prefetch depth per worker (exported as "
                         "SPARKNET_FEED_DEPTH)")
    ap.add_argument("cmd", nargs=argparse.REMAINDER,
                    help="command to run (prefix with --)")
    args = ap.parse_args(argv)
    cmd = args.cmd[1:] if args.cmd and args.cmd[0] == "--" else args.cmd
    if not cmd:
        ap.error("no command given")
    if args.round_deadline and not args.heartbeat_dir:
        ap.error("--round-deadline requires --heartbeat-dir")
    if args.feed_workers is not None and args.feed_workers < 0:
        ap.error("--feed-workers must be >= 0")
    if args.feed_depth is not None and args.feed_depth < 1:
        ap.error("--feed-depth must be >= 1")
    # feed-pipeline knobs ride the same env contract every other
    # per-process setting uses (consumed by data.pipeline at feed build)
    feed_env = {}
    if args.feed_workers is not None:
        feed_env["SPARKNET_FEED_WORKERS"] = args.feed_workers
    if args.feed_depth is not None:
        feed_env["SPARKNET_FEED_DEPTH"] = args.feed_depth
    health = dict(heartbeat_dir=args.heartbeat_dir,
                  round_deadline=args.round_deadline, log_dir=args.log_dir,
                  extra_env=feed_env or None)
    if args.hosts:
        return launch_ssh(cmd, args.hosts.split(","), timeout=args.timeout,
                          **health)
    if not args.nprocs:
        ap.error("--nprocs or --hosts required")
    return launch_local(cmd, args.nprocs, platform=args.platform,
                        devices_per_proc=args.devices_per_proc,
                        timeout=args.timeout, **health)


if __name__ == "__main__":
    sys.exit(main())
