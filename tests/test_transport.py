"""The host-transport seam (parallel/transport.py) and the network
survival discipline built on it: the ssh wire format under a fake-ssh
shim (the CI rig for the production launch path), lease-based
partition-vs-death classification (LeaseMonitor / GangHealth),
crc-verified resumable checkpoint shipping, and incarnation fencing.

None of these tests need the multiprocess-XLA fixture: workers are
plain python subprocesses, so the ssh tier's argv/env/stdio contract is
pinned on every tier-1 run, not only on rigs whose CPU backend supports
multiprocess collectives."""

import hashlib
import json
import os
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _make_shim(tmp_path):
    """The fake-ssh shim: logs the exact wire argv, then executes the
    remote command string locally (argv[4] = the `cd .. && env .. cmd`
    string, exactly what sshd would hand the remote shell)."""
    log = tmp_path / "ssh.log"
    shim = tmp_path / "fake-ssh"
    shim.write_text("#!/bin/bash\n"
                    f"echo \"ARGS:$*\" >> {log}\n"
                    "exec bash -c \"$4\"\n")
    shim.chmod(0o755)
    return shim, log


# -- ssh wire format / env contract ---------------------------------------

def test_launch_ssh_wire_format_and_env_contract(tmp_path, monkeypatch):
    """launch_ssh over the fake-ssh shim: every rank rides the exact
    production wire (`<ssh> -o BatchMode=yes <host> "cd <cwd> && env
    K='v' ... cmd"`), and the remote process sees the full env contract
    (coordinator, world size, proc id, host tag, extra env)."""
    from sparknet_tpu.tools.launch import launch_ssh

    shim, log = _make_shim(tmp_path)
    monkeypatch.setenv("SPARKNET_SSH_CMD", str(shim))
    out = tmp_path / "out"
    out.mkdir()
    worker = tmp_path / "worker.py"
    worker.write_text(
        "import json, os\n"
        "keys = ['SPARKNET_COORDINATOR', 'SPARKNET_NUM_PROCS',\n"
        "        'SPARKNET_PROC_ID', 'SPARKNET_FLEET_HOST', 'WIRE_EXTRA']\n"
        "rec = {k: os.environ.get(k) for k in keys}\n"
        "dst = os.path.join(os.environ['WIRE_OUT'],\n"
        "                   os.environ['SPARKNET_PROC_ID'] + '.json')\n"
        "with open(dst, 'w') as f:\n"
        "    json.dump(rec, f)\n")

    report = {}
    rc = launch_ssh(
        [sys.executable, str(worker)], hosts=["hosta", "hostb"],
        cwd=str(tmp_path), timeout=120, report=report,
        extra_env={"WIRE_OUT": str(out), "WIRE_EXTRA": "rode-the-wire"})
    assert rc == 0, f"fake-ssh launch failed rc={rc}"
    assert report["transport"] == "ssh"

    # wire argv: one line per rank, exact ssh shape
    args = [l for l in log.read_text().strip().splitlines()
            if l.startswith("ARGS:")]
    assert len(args) == 2
    assert any(" hosta " in a for a in args)
    assert any(" hostb " in a for a in args)
    for a in args:
        assert "-o BatchMode=yes" in a
        assert f"cd {tmp_path}" in a
        assert "SPARKNET_COORDINATOR=" in a
        assert "SPARKNET_NUM_PROCS='2'" in a

    # env contract as the remote process actually saw it
    for pid, host in ((0, "hosta"), (1, "hostb")):
        with open(out / f"{pid}.json") as f:
            rec = json.load(f)
        assert rec["SPARKNET_PROC_ID"] == str(pid)
        assert rec["SPARKNET_NUM_PROCS"] == "2"
        assert rec["SPARKNET_FLEET_HOST"] == host
        assert rec["SPARKNET_COORDINATOR"].startswith("hosta:")
        assert rec["WIRE_EXTRA"] == "rode-the-wire"


def test_launch_ssh_teardown_on_first_death(tmp_path, monkeypatch):
    """The first nonzero remote exit tears the whole gang down — the
    surviving rank (asleep for 300s) must be killed well before both its
    sleep and the launcher timeout."""
    from sparknet_tpu.tools.launch import launch_ssh

    shim, _ = _make_shim(tmp_path)
    monkeypatch.setenv("SPARKNET_SSH_CMD", str(shim))
    worker = tmp_path / "worker.py"
    worker.write_text(
        "import os, sys, time\n"
        "if os.environ.get('SPARKNET_PROC_ID') == '1':\n"
        "    sys.exit(3)\n"
        "time.sleep(300)\n")

    report = {}
    t0 = time.monotonic()
    rc = launch_ssh([sys.executable, str(worker)],
                    hosts=["hosta", "hostb"], cwd=str(tmp_path),
                    timeout=120, report=report)
    elapsed = time.monotonic() - t0
    assert rc == 3, f"remote exit code must surface verbatim, got {rc}"
    assert elapsed < 60, f"teardown took {elapsed:.0f}s — gang not torn " \
                         f"down on first death"
    assert report["cause"] == "exit"
    assert report["first_failure"] == 1


# -- lease-based liveness -------------------------------------------------

def test_lease_monitor_states(tmp_path):
    from sparknet_tpu.parallel import health

    root = str(tmp_path / "hb")
    t = {"now": 1000.0}
    health.write_beat(health.host_dir(root, "a"), 0, 5, "round_start",
                      clock=lambda: t["now"])
    mon = health.LeaseMonitor(root, lease_s=1.0, misses=2,
                              clock=lambda: t["now"])
    assert mon.state("a") == health.LEASE_LIVE
    assert mon.state("never-beat") == health.LEASE_NO_BEATS
    t["now"] = 1003.0   # 3s silence > 1.0 x 2 window
    assert mon.state("a") == health.LEASE_SUSPECT
    assert mon.states(["a", "b"]) == {"a": health.LEASE_SUSPECT,
                                      "b": health.LEASE_NO_BEATS}


class _StubInjector:
    """The three hooks ChaosTransport consumes, programmable."""

    def __init__(self, drop_seqs=(), torn_count=0):
        self.drop_seqs = set(drop_seqs)
        self.torn_left = torn_count
        self.specs = []

    def net_specs(self):
        return []

    def drop_ship(self, seq):
        return seq in self.drop_seqs

    def torn_ship(self):
        if self.torn_left > 0:
            self.torn_left -= 1
            return True
        return False


def test_gang_health_partition_suspends_then_heals(tmp_path):
    """The partition-vs-death state machine: whole-host beat silence on
    a non-local transport marks the host SUSPECT and suspends (not
    kills) its ranks; when the link heals and beats flow again the host
    returns to straggler discipline — no rank was ever flagged."""
    from sparknet_tpu.parallel import health
    from sparknet_tpu.parallel.transport import ChaosTransport, SshTransport

    root = str(tmp_path / "hb")
    t = {"now": 1000.0}
    clk = lambda: t["now"]
    chaos = ChaosTransport(SshTransport(), injector=_StubInjector())
    lease = health.LeaseMonitor(root, lease_s=1.0, misses=2, clock=clk)
    gh = health.GangHealth(root, 5.0, host_map=["a", "b"],
                           transport=chaos, lease=lease, clock=clk)

    def beat(rank, host):
        health.write_beat(health.stage_dir(root, host), rank, 1,
                          "round_start", clock=clk)

    beat(0, "a")
    beat(1, "b")
    assert gh.check([0, 1]) == []
    assert gh.suspect_hosts == set()

    # sever the link to b: beats stop relaying, lease expires -> SUSPECT
    chaos.partition("b")
    t["now"] = 1003.0
    beat(0, "a")
    assert gh.check([0, 1]) == []
    assert gh.suspect_hosts == {"b"}

    # deep into straggler territory (7s > 5s deadline): rank 1 is
    # shielded by the suspension — a partition must not kill the gang
    t["now"] = 1007.0
    beat(0, "a")
    assert gh.check([0, 1]) == []

    # heal: fresh beats relay, suspect clears, nobody was flagged
    chaos.heal("b")
    beat(1, "b")
    beat(0, "a")
    assert gh.check([0, 1]) == []
    assert gh.suspect_hosts == set()
    assert gh.ever_suspect == {"b"}


def test_gang_health_down_probe_escalates_to_kill(tmp_path):
    """Same silence signature, but the down-probe confirms real death:
    suspension is bypassed and straggler discipline kills the rank (the
    resilience layer then takes the PR 16 lost-host path)."""
    from sparknet_tpu.parallel import health
    from sparknet_tpu.parallel.transport import ChaosTransport, SshTransport

    root = str(tmp_path / "hb")
    t = {"now": 1000.0}
    clk = lambda: t["now"]
    chaos = ChaosTransport(SshTransport(), injector=_StubInjector())
    lease = health.LeaseMonitor(root, lease_s=1.0, misses=2, clock=clk)
    gh = health.GangHealth(root, 5.0, host_map=["a", "b"],
                           transport=chaos, lease=lease, clock=clk,
                           down_probe=lambda h: h == "b")

    health.write_beat(health.stage_dir(root, "a"), 0, 1, "round_start",
                      clock=clk)
    health.write_beat(health.stage_dir(root, "b"), 1, 1, "round_start",
                      clock=clk)
    assert gh.check([0, 1]) == []
    chaos.partition("b")
    t["now"] = 1007.0   # past the lease window AND the round deadline
    health.write_beat(health.stage_dir(root, "a"), 0, 1, "round_start",
                      clock=clk)
    assert gh.check([0, 1]) == [1]
    assert gh.confirmed_down == {"b"}
    assert gh.suspect_hosts == set()


# -- verified, resumable shipping -----------------------------------------

def test_verified_copy_resumes_torn_prefix(tmp_path, monkeypatch):
    from sparknet_tpu.parallel.transport import _verified_copy

    src = tmp_path / "blob.bin"
    data = bytes(range(256)) * 20   # 5120 bytes = 5 x 1024-byte chunks
    src.write_bytes(data)
    dst = tmp_path / "landed" / "blob.bin"
    # a torn previous transfer: two good chunks + a corrupt partial tail
    os.makedirs(dst.parent)
    (dst.parent / "blob.bin.tmp.ship").write_bytes(
        data[:2048] + b"\xff" * 500)
    rec = _verified_copy(str(src), str(dst), chunk=1024)
    assert rec["resumed_bytes"] == 2048
    assert rec["bytes"] == len(data)
    assert dst.read_bytes() == data


def test_chaos_ship_drop_retries_then_lands(tmp_path, monkeypatch):
    from sparknet_tpu.parallel.transport import ChaosTransport, \
        LocalTransport

    monkeypatch.setenv("SPARKNET_SHIP_RETRIES", "3")
    src = tmp_path / "a.bin"
    src.write_bytes(b"payload" * 512)
    dst = tmp_path / "remote" / "a.bin"
    chaos = ChaosTransport(LocalTransport(),
                           injector=_StubInjector(drop_seqs={0}))
    rec = chaos.ship(str(src), "hostb", str(dst))
    assert rec["bytes"] == len(b"payload" * 512)
    assert dst.read_bytes() == src.read_bytes()


def test_chaos_torn_ship_resumes_on_retry(tmp_path, monkeypatch):
    """A torn transfer leaves half the bytes in the temp; the retry must
    resume past the intact prefix and the landed file must be whole."""
    from sparknet_tpu.parallel.transport import ChaosTransport, \
        LocalTransport

    monkeypatch.setenv("SPARKNET_SHIP_RETRIES", "3")
    monkeypatch.setenv("SPARKNET_SHIP_CHUNK_MB", "0.0009765625")  # 1 KiB
    src = tmp_path / "a.bin"
    data = bytes(range(256)) * 20
    src.write_bytes(data)
    dst = tmp_path / "remote" / "a.bin"
    chaos = ChaosTransport(LocalTransport(),
                           injector=_StubInjector(torn_count=1))
    rec = chaos.ship(str(src), "hostb", str(dst))
    assert rec["resumed_bytes"] == 2048   # the torn half, whole chunks
    assert dst.read_bytes() == data


def test_chaos_partitioned_ship_and_exec_refuse(tmp_path, monkeypatch):
    from sparknet_tpu.parallel.transport import ChaosTransport, \
        LocalTransport, PartitionedError

    monkeypatch.setenv("SPARKNET_SHIP_RETRIES", "2")
    src = tmp_path / "a.bin"
    src.write_bytes(b"x" * 100)
    chaos = ChaosTransport(LocalTransport(), injector=_StubInjector())
    chaos.partition("hostb")
    with pytest.raises(PartitionedError):
        chaos.ship(str(src), "hostb", str(tmp_path / "dst" / "a.bin"))
    with pytest.raises(PartitionedError):
        chaos.popen("hostb", ["true"], env_pairs=[])
    assert chaos.beat_sync("hostb", str(tmp_path), str(tmp_path)) == 0
    chaos.heal("hostb")
    chaos.popen("hostb", [sys.executable, "-c", "pass"],
                env_pairs=[]).wait(timeout=30)


# -- checkpoint shipping --------------------------------------------------

def _fake_ckpt(directory, round_idx, payload):
    os.makedirs(directory, exist_ok=True)
    name = f"ckpt_round_{round_idx:08d}.npz"
    path = os.path.join(directory, name)
    with open(path, "wb") as f:
        f.write(payload)
    man = {"file": name, "round": round_idx,
           "sha256": hashlib.sha256(payload).hexdigest()}
    with open(os.path.join(directory, f"manifest_{round_idx:08d}.json"),
              "w") as f:
        json.dump(man, f)
    return path


def test_ship_latest_checkpoint_picks_newest_valid(tmp_path):
    from sparknet_tpu.parallel.transport import LocalTransport, \
        newest_valid_round, ship_latest_checkpoint

    src = str(tmp_path / "src")
    dst = str(tmp_path / "dst")
    _fake_ckpt(src, 3, b"round-three" * 100)
    # round 7 is torn on the source: manifest sha no longer matches
    p7 = _fake_ckpt(src, 7, b"round-seven" * 100)
    with open(p7, "ab") as f:
        f.write(b"corruption")
    assert newest_valid_round(src) == 3

    rec = ship_latest_checkpoint(LocalTransport(), "hostb", src, dst)
    assert rec["round"] == 3
    assert newest_valid_round(dst) == 3
    again = ship_latest_checkpoint(LocalTransport(), "hostb", src, dst)
    assert again["skipped"] == "up to date"


def test_ship_latest_checkpoint_empty_source(tmp_path):
    from sparknet_tpu.parallel.transport import LocalTransport, \
        ship_latest_checkpoint

    assert ship_latest_checkpoint(
        LocalTransport(), "hostb", str(tmp_path / "nothing"),
        str(tmp_path / "dst")) is None


# -- incarnation fencing --------------------------------------------------

def test_fence_monotonic_advance_and_typed_refusal(tmp_path):
    from sparknet_tpu.utils.checkpoint import (
        CheckpointError, CheckpointFencedError, advance_fence,
        check_fence, read_fence)

    d = str(tmp_path / "ckpt")
    assert read_fence(d) == 0
    assert advance_fence(d, 100001) == 100001
    check_fence(d, 100001)          # current holder passes
    assert advance_fence(d, 200001) == 200001   # new incarnation claims
    # the zombie (older token) is refused, with a typed error carrying
    # both sides of the comparison
    with pytest.raises(CheckpointFencedError) as ei:
        check_fence(d, 100001)
    assert ei.value.token == 100001
    assert ei.value.fence == 200001
    assert isinstance(ei.value, CheckpointError)
    # a stale claimant cannot LOWER the fence either
    with pytest.raises(CheckpointFencedError):
        advance_fence(d, 100001)
    assert read_fence(d) == 200001
    assert read_fence(str(tmp_path / "absent")) == 0


def test_zombie_writer_refused_at_manifest_rename(tmp_path, monkeypatch):
    """The zombie-writer window, end to end through the trainer: a save
    whose incarnation is fenced off WHILE its npz is in flight must be
    refused at the manifest rename — the last gate before visibility —
    with a typed error and zero new artifacts (torn or visible).  The
    successor then resumes from the last checkpoint the zombie landed
    legitimately."""
    import numpy as np

    import sparknet_tpu.utils.checkpoint as ckpt_mod
    from test_resilience import _batch, _make_trainer

    d = tmp_path / "ck"
    monkeypatch.setenv("SPARKNET_FENCE_TOKEN", "100001")
    tr = _make_trainer(d, async_checkpoint=False)
    tr.train_round(_batch(0))          # round 1 lands under token 100001
    w1 = np.asarray(tr.params["conv1"][0]).copy()

    # a successor incarnation claims the dir exactly when the zombie's
    # npz has landed but its manifest has not (what the successor's
    # resume_latest does on the shipped copy)
    real_save = ckpt_mod.save_checkpoint

    def racing_save(path, tree):
        real_save(path, tree)
        ckpt_mod.advance_fence(str(d), 200002)

    monkeypatch.setattr(ckpt_mod, "save_checkpoint", racing_save)
    with pytest.raises(ckpt_mod.CheckpointFencedError) as ei:
        tr.train_round(_batch(1))
    assert ei.value.token == 100001
    assert ei.value.fence == 200002
    monkeypatch.setattr(ckpt_mod, "save_checkpoint", real_save)

    # the refused round left nothing behind: no manifest, no npz, no temp
    leftovers = [n for n in os.listdir(d)
                 if "00000002" in n or ".tmp." in n]
    assert leftovers == []
    assert ckpt_mod.read_fence(str(d)) == 200002

    # the successor resumes cleanly from the zombie's last GOOD round
    monkeypatch.setenv("SPARKNET_FENCE_TOKEN", "200002")
    tr2 = _make_trainer(d, seed=99, async_checkpoint=False)
    assert tr2.resumed is not None
    assert tr2.round == 1
    np.testing.assert_array_equal(np.asarray(tr2.params["conv1"][0]), w1)


# -- status view columns --------------------------------------------------

def test_hosts_view_lease_and_transport_columns():
    from sparknet_tpu.parallel.fleet import (
        HOST_DRAINING, RUNNING, HostPool, hosts_view)

    pool = HostPool.from_spec("a=2,b=2,c=2")
    pool.mark("c", HOST_DRAINING)
    jobs = [{"job": "j1", "state": RUNNING, "slots": [0, 1],
             "hosts": ["a"]},
            {"job": "j2", "state": RUNNING, "slots": [2, 3],
             "hosts": ["b"]}]
    view = hosts_view(pool, jobs,
                      beat_ages={"a": 99.0, "b": 0.2},
                      transports={"a": "ssh", "b": "ssh"})
    assert view["a"]["lease"] == "suspect"     # 99s > default 6s window
    assert view["a"]["beat_age_s"] == 99.0
    assert view["a"]["transport"] == "ssh"
    assert view["b"]["lease"] == "live"
    assert view["c"]["lease"] == HOST_DRAINING  # operator state verbatim
    assert view["c"]["transport"] == "local"


def test_mark_host_suspect_accepted(tmp_path):
    from sparknet_tpu.parallel.fleet import FleetError, request_mark_host

    request_mark_host(str(tmp_path), "b", "suspect", by="test")
    with open(tmp_path / "host_control.jsonl") as f:
        rec = json.loads(f.read().strip())
    assert rec["state"] == "suspect"
    with pytest.raises(FleetError):
        request_mark_host(str(tmp_path), "b", "wedged")
