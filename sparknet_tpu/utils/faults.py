"""Deterministic fault injection — chaos testing for the resilience layer.

SparkNet's recovery story was only ever exercised by luck (a preempted
EC2 spot node during a paper run); here every failure mode is a
first-class, deterministic test input.  Faults are described by the
``SPARKNET_FAULT`` env var and fire at well-defined hook points:

    SPARKNET_FAULT=<spec>[,<spec>...]
    spec     := kind[:arg][@round:<N>][@rank:<R>][@attempt:<A>]
    kind     := crash         — os._exit(43) at the start of round N
              | perma_crash   — os._exit(43) on EVERY attempt (a broken
                                host, not a transient death; needs @rank —
                                the elastic layer drops the rank once its
                                restart budget is spent)
              | hang          — block forever at the start of round N
              | straggle      — arg = duration: sleep that long at the
                                start of round N (a stuck-but-alive rank;
                                the straggler deadline must catch it)
              | slow_feed     — arg = per-batch delay ("200ms", "0.5s", "2")
              | nan_inject    — poison the round-N feed with NaNs (the
                                numerical-integrity guard must roll back)
              | corrupt_ckpt  — scribble over the checkpoint written at
                                round N, after its manifest exists
              | crash_in_ckpt — os._exit(43) mid-checkpoint-write at round
                                N: after the npz is durable but BEFORE the
                                manifest (the worst torn-write window —
                                resume must skip the orphan)
              | corrupt_record — arg = probability p in (0, 1]: flip bytes
                                in (and truncate) p·100% of the records a
                                DB feed decodes — rotting storage; the
                                quarantine layer must skip-and-count
              | feeder_die    — the prefetch feeder thread dies silently
                                (no error, no sentinel) before producing
                                batch N — the watchdog must detect the
                                dead thread and restart it once
              | feeder_hang   — arg = duration: the feeder blocks that
                                long before producing batch N (a stuck
                                read; the watchdog's stall timeout must
                                fire, not the job timeout)
              | bitflip_params — flip one mantissa bit in REPLICA R's
                                resident copy of the params at the start
                                of round N (@rank names the replica, not
                                the process — a flaky-HBM event; the
                                cross-replica audit must catch it before
                                the next averaging folds it in)
              | preempt       — deliver SIGTERM to THIS process at the
                                start of round N: the deterministic
                                replacement for a cloud scheduler's
                                preemption notice.  The preemption guard
                                (utils/signals.py SNAPSHOT_STOP) must
                                turn it into one final round checkpoint
                                + a clean rc-0 exit, and the fleet layer
                                must requeue-and-resume the job — NOT
                                count it complete
              | partition     — needs @host:NAME: sever the network link
                                to that host at the transport layer
                                (parallel/transport.ChaosTransport) —
                                its processes stay ALIVE but beats stop
                                arriving and new exec/ship calls fail.
                                The lease layer must mark the host
                                SUSPECT (never LOST) and suspend its
                                gang without burning restart budget
              | heal          — needs @host:NAME: undo a partition — the
                                link comes back, relayed beats flow
                                again, the suspended gang resumes
              | slow_link     — arg = per-operation delay ("50ms"),
                                needs @host:NAME: a degraded link — every
                                transport op to that host pays the delay
                                (the straggler-ATTRIBUTION case: slow,
                                not dead, and the blame must land on the
                                link, not the chip)
              | drop_ship     — arg = probability p in (0, 1]: each
                                artifact-shipping call fails with that
                                deterministic per-call probability — the
                                retry/backoff path must absorb it
              | torn_ship     — the next shipping call writes a partial
                                destination file then fails (a torn
                                transfer); the crc-verified resume must
                                detect and finish it, never serve the
                                torn prefix.  Fires once per process
              | bad_canary    — arg = model or version id: that serving
                                model's head produces NaN rows on EVERY
                                batch (a bad deployment, not a blip).
                                The engine's non-finite guard turns the
                                rows into typed failures, the per-version
                                SLO judge burns, and the rollout
                                controller must auto-roll back.  NOTE the
                                arg uses ':' (``bad_canary:mv-abc``), as
                                '@' is the modifier separator — it
                                matches the full versioned serving name,
                                its base model, or its version id

Scoping:
  @round:N   — fire at round N (required for crash/hang/straggle/
               nan_inject/crash_in_ckpt/bitflip_params; for feeder_die/
               feeder_hang N is the prefetch BATCH index; for
               corrupt_ckpt it names the checkpointed round; optional
               for perma_crash — default every round; slow_feed and
               corrupt_record ignore it)
  @rank:R    — only on process R (default: every rank; REQUIRED for
               perma_crash; for bitflip_params R names the target
               REPLICA on the mesh, not the process)
  @attempt:A — only on job attempt A.  The ResilientRunner stamps every
               (re)launch with SPARKNET_FAULT_ATTEMPT; crash / hang /
               straggle / corrupt_ckpt / crash_in_ckpt / nan_inject
               default to attempt 0 ONLY, so an injected fault fires once
               and the automatic restart then runs clean — the
               deterministic replacement for "the spot instance came
               back".  slow_feed and perma_crash default to every attempt
               (they model degradation and permanent loss, not a
               transient death).

nan_inject, bitflip_params, preempt, feeder_die, and feeder_hang
additionally fire at most once per process even without a restart: the guard/audit rollback
replays the same round index (and the restarted feeder replays the same
batch index), and the replay must run clean (the deterministic
replacement for "the cosmic ray does not strike twice").

Hook points: ``FaultInjector.on_round`` in training drivers,
``feed_delay`` / ``feeder_event`` in ``data.prefetch.PrefetchIterator``,
``corrupt_record`` in ``data.db.db_feed``, and ``nan_inject`` /
``bitflip_rank`` / ``corrupt_checkpoint`` / ``on_checkpoint_write`` in
``parallel.trainer.DistributedTrainer``.
"""

from __future__ import annotations

import dataclasses
import os
import signal
import sys
import time
import zlib
from typing import Callable, Mapping

KINDS = ("crash", "perma_crash", "hang", "straggle", "slow_feed",
         "nan_inject", "corrupt_ckpt", "crash_in_ckpt", "corrupt_record",
         "feeder_die", "feeder_hang", "bitflip_params", "preempt",
         "partition", "heal", "slow_link", "drop_ship", "torn_ship",
         "bad_canary")

# the network kinds: consumed by parallel/transport.ChaosTransport, not
# by the in-process hook points
NET_KINDS = ("partition", "heal", "slow_link", "drop_ship", "torn_ship")
# network kinds that must name the host whose link they describe
_NEED_HOST = ("partition", "heal", "slow_link")

# kinds that keep firing on every job attempt unless @attempt pins one
# (network state belongs to the link, not to any one attempt)
_EVERY_ATTEMPT = ("slow_feed", "perma_crash", "corrupt_record",
                  "bad_canary") + NET_KINDS
# kinds whose ':' arg is a duration
_DURATION_ARG = ("slow_feed", "straggle", "feeder_hang", "slow_link")
# kinds whose ':' arg is a probability in (0, 1]
_PROB_ARG = ("corrupt_record", "drop_ship")
# kinds whose ':' arg names a serving model / version ('@' is taken by
# the modifier grammar, so the name rides the ':' arg)
_NAME_ARG = ("bad_canary",)
# kinds that must name a round (for feeder_* the "round" is the batch
# sequence index the prefetch feeder is about to produce)
_NEED_ROUND = ("crash", "hang", "straggle", "nan_inject", "crash_in_ckpt",
               "feeder_die", "feeder_hang", "bitflip_params", "preempt")


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    kind: str
    round: int | None = None
    rank: int | None = None
    attempt: int | None = None     # None => kind-specific default (see doc)
    delay_s: float = 0.0           # slow_feed/straggle/feeder_hang/slow_link
    prob: float = 0.0              # corrupt_record / drop_ship only
    host: str | None = None        # partition / heal / slow_link only
    model: str | None = None       # bad_canary only


def _parse_duration(text: str) -> float:
    t = text.strip()
    try:
        if t.endswith("ms"):
            return float(t[:-2]) / 1000.0
        if t.endswith("s"):
            return float(t[:-1])
        return float(t)
    except ValueError:
        raise ValueError(f"bad duration {text!r} (want e.g. '200ms', "
                         f"'1.5s', or plain seconds)") from None


def parse_faults(text: str) -> tuple[FaultSpec, ...]:
    """Parse a SPARKNET_FAULT value; raises ValueError with the offending
    spec named (config errors must be loud, not silently inert)."""
    specs = []
    for raw in text.split(","):
        raw = raw.strip()
        if not raw:
            continue
        head, *mods = raw.split("@")
        kind, _, arg = head.partition(":")
        kind = kind.strip()
        if kind not in KINDS:
            raise ValueError(f"unknown fault kind {kind!r} in {raw!r} "
                             f"(known: {', '.join(KINDS)})")
        delay = 0.0
        prob = 0.0
        model: str | None = None
        if kind in _NAME_ARG:
            if not arg:
                raise ValueError(
                    f"{kind} needs a model-or-version arg in {raw!r} "
                    f"(e.g. 'bad_canary:mv-abc123' — ':' not '@', the "
                    f"'@' is the modifier separator)")
            model = arg.strip()
        elif kind in _DURATION_ARG:
            if not arg:
                raise ValueError(f"{kind} needs a duration arg in {raw!r}")
            delay = _parse_duration(arg)
        elif kind in _PROB_ARG:
            if not arg:
                raise ValueError(
                    f"{kind} needs a probability arg in {raw!r}")
            try:
                prob = float(arg)
            except ValueError:
                raise ValueError(
                    f"bad probability {arg!r} in {raw!r}") from None
            if not 0.0 < prob <= 1.0:
                raise ValueError(
                    f"{kind} probability must be in (0, 1], got {prob} "
                    f"({raw!r})")
        elif arg:
            raise ValueError(f"{kind} takes no ':' arg (got {raw!r})")
        fields: dict[str, int] = {}
        host: str | None = None
        for mod in mods:
            key, _, val = mod.partition(":")
            key = key.strip()
            if key not in ("round", "rank", "attempt", "host") or not val:
                raise ValueError(f"bad modifier {mod!r} in {raw!r} "
                                 f"(want @round:N / @rank:R / @attempt:A "
                                 f"/ @host:NAME)")
            if key == "host":
                host = val.strip()
                continue
            try:
                fields[key] = int(val)
            except ValueError:
                raise ValueError(
                    f"modifier {mod!r} in {raw!r}: not an integer") from None
        if kind in _NEED_ROUND and "round" not in fields:
            raise ValueError(f"{kind} needs @round:N ({raw!r})")
        if kind in _NEED_HOST and host is None:
            raise ValueError(f"{kind} needs @host:NAME ({raw!r}) — a "
                             f"link fault must name whose link")
        if host is not None and kind not in NET_KINDS:
            raise ValueError(f"{kind} takes no @host modifier ({raw!r})")
        if kind == "perma_crash" and "rank" not in fields:
            raise ValueError(
                f"perma_crash needs @rank:R ({raw!r}) — a rankless "
                f"permanent crash means no survivor set to re-form with")
        if kind == "bitflip_params" and "rank" not in fields:
            raise ValueError(
                f"bitflip_params needs @rank:R ({raw!r}) — it must name "
                f"WHICH replica's resident copy rots, or the audit has "
                f"nothing to disagree about")
        specs.append(FaultSpec(kind=kind, round=fields.get("round"),
                               rank=fields.get("rank"),
                               attempt=fields.get("attempt"),
                               delay_s=delay, prob=prob, host=host,
                               model=model))
    return tuple(specs)


class FaultInjector:
    """Evaluates parsed fault specs at the hook points.  ``_exit`` and
    ``_sleep`` are injectable for unit tests; production uses the real
    ones (crash must be un-catchable, like a SIGKILLed worker)."""

    def __init__(self, specs: tuple[FaultSpec, ...], *, attempt: int = 0,
                 rank: int = 0,
                 _exit: Callable[[int], None] = os._exit,
                 _sleep: Callable[[float], None] = time.sleep,
                 _kill: Callable[[int, int], None] = os.kill):
        self.specs = specs
        self.attempt = attempt
        self.rank = rank
        self._exit = _exit
        self._sleep = _sleep
        self._kill = _kill
        self._fired: set[FaultSpec] = set()   # once-per-process kinds

    @classmethod
    def from_env(cls, env: Mapping[str, str] | None = None,
                 **kwargs) -> "FaultInjector":
        env = os.environ if env is None else env
        text = env.get("SPARKNET_FAULT", "")
        return cls(parse_faults(text) if text else (),
                   attempt=int(env.get("SPARKNET_FAULT_ATTEMPT", "0") or 0),
                   rank=int(env.get("SPARKNET_PROC_ID", "0") or 0),
                   **kwargs)

    def _active(self, spec: FaultSpec, rank: int | None) -> bool:
        r = self.rank if rank is None else rank
        if spec.rank is not None and spec.rank != r:
            return False
        want = spec.attempt
        if want is None:
            # one-shot faults fire on the first attempt only; degradation
            # and permanent-loss kinds fire on every attempt
            want = None if spec.kind in _EVERY_ATTEMPT else 0
        return want is None or want == self.attempt

    def on_round(self, round_idx: int, rank: int | None = None) -> None:
        """Call at the start of every training round."""
        for spec in self.specs:
            if spec.kind not in ("crash", "perma_crash", "hang", "straggle",
                                 "preempt"):
                continue
            if spec.kind == "perma_crash":
                if spec.round is not None and spec.round != round_idx:
                    continue
            elif spec.round != round_idx:
                continue
            if not self._active(spec, rank):
                continue
            if spec.kind == "preempt" and spec in self._fired:
                continue
            who = self.rank if rank is None else rank
            print(f"FAULT: {spec.kind} at round {round_idx} on rank {who} "
                  f"(attempt {self.attempt})", file=sys.stderr, flush=True)
            if spec.kind in ("crash", "perma_crash"):
                self._exit(43)
                return  # only reached with a test-injected _exit
            if spec.kind == "straggle":
                self._sleep(spec.delay_s)
                continue  # a straggler resumes (if it survives that long)
            if spec.kind == "preempt":
                # the preemption notice: SIGTERM to ourselves, exactly as
                # a cloud scheduler's grace window starts.  Once per
                # process — the round that observes the flag checkpoints
                # and exits, and the resumed process is PAST this round
                self._fired.add(spec)
                self._kill(os.getpid(), signal.SIGTERM)
                continue  # training continues until the guard polls
            while True:  # hang: a stuck worker, killable only from outside
                self._sleep(3600)

    def feed_delay(self, rank: int | None = None) -> float:
        """Seconds each prefetched batch should be delayed by."""
        return sum(s.delay_s for s in self.specs
                   if s.kind == "slow_feed" and self._active(s, rank))

    def nan_inject(self, round_idx: int, rank: int | None = None) -> bool:
        """True when the round-``round_idx`` feed should be poisoned with
        NaNs on this rank.  Fires at most ONCE per process per spec: the
        guard's rollback replays the same round index and the replay must
        run clean (see module docstring)."""
        for spec in self.specs:
            if (spec.kind != "nan_inject" or spec.round != round_idx
                    or spec in self._fired
                    or not self._active(spec, rank)):
                continue
            self._fired.add(spec)
            return True
        return False

    def corrupt_checkpoint(self, round_idx: int,
                           rank: int | None = None) -> bool:
        """True when the checkpoint just written for ``round_idx`` should
        be scribbled over (exercises manifest-fallback on resume)."""
        return any(
            s.kind == "corrupt_ckpt"
            and (s.round is None or s.round == round_idx)
            and self._active(s, rank)
            for s in self.specs)

    def on_checkpoint_write(self, round_idx: int,
                            rank: int | None = None) -> None:
        """Call between a round-checkpoint's npz write and its manifest
        write — the torn-write window ``crash_in_ckpt`` kills in (the
        orphan npz without a manifest must be invisible to resume)."""
        for spec in self.specs:
            if (spec.kind != "crash_in_ckpt" or spec.round != round_idx
                    or not self._active(spec, rank)):
                continue
            who = self.rank if rank is None else rank
            print(f"FAULT: crash_in_ckpt at round {round_idx} on rank "
                  f"{who} (attempt {self.attempt})", file=sys.stderr,
                  flush=True)
            self._exit(43)
            return  # only reached with a test-injected _exit

    def corrupt_record(self, seq: int, rank: int | None = None) -> bool:
        """True when decoded record number ``seq`` (a feed-lifetime
        sequence counter) should be handed corrupted bytes.  The choice is
        a pure function of ``seq`` so a restarted feed re-corrupts the
        SAME records — corruption on disk does not move around."""
        for spec in self.specs:
            if spec.kind != "corrupt_record" or not self._active(spec, rank):
                continue
            # deterministic per-record coin flip at probability spec.prob
            h = zlib.crc32(f"corrupt_record:{seq}".encode()) & 0xFFFFFFFF
            if h < spec.prob * 2**32:
                return True
        return False

    def net_specs(self) -> tuple[FaultSpec, ...]:
        """The active network-fault specs (partition/heal/slow_link) —
        ``parallel.transport.ChaosTransport`` seeds its link state from
        these at construction time."""
        return tuple(s for s in self.specs
                     if s.kind in ("partition", "heal", "slow_link")
                     and self._active(s, None))

    def drop_ship(self, seq: int) -> bool:
        """True when shipping call number ``seq`` should fail — a pure
        function of ``seq`` (like ``corrupt_record``) so a retried ship
        sequence hits the SAME drops on replay."""
        for spec in self.specs:
            if spec.kind != "drop_ship" or not self._active(spec, None):
                continue
            h = zlib.crc32(f"drop_ship:{seq}".encode()) & 0xFFFFFFFF
            if h < spec.prob * 2**32:
                return True
        return False

    def torn_ship(self) -> bool:
        """True when the NEXT shipping call should tear mid-transfer
        (partial destination bytes, then failure).  At most once per
        process: the resumed transfer must run clean."""
        for spec in self.specs:
            if (spec.kind != "torn_ship" or spec in self._fired
                    or not self._active(spec, None)):
                continue
            self._fired.add(spec)
            return True
        return False

    def feeder_event(self, batch_idx: int,
                     rank: int | None = None) -> tuple[str, float] | None:
        """("die", 0) / ("hang", duration) when the prefetch feeder should
        fail before producing batch ``batch_idx``, else None.  Fires at
        most once per process per spec: the watchdog's one-shot feeder
        restart replays the same batch index and must run clean."""
        for spec in self.specs:
            if (spec.kind not in ("feeder_die", "feeder_hang")
                    or spec.round != batch_idx or spec in self._fired
                    or not self._active(spec, rank)):
                continue
            self._fired.add(spec)
            who = self.rank if rank is None else rank
            print(f"FAULT: {spec.kind} before batch {batch_idx} on rank "
                  f"{who} (attempt {self.attempt})", file=sys.stderr,
                  flush=True)
            if spec.kind == "feeder_die":
                return ("die", 0.0)
            return ("hang", spec.delay_s)

    def bad_canary(self, model: str, rank: int | None = None) -> bool:
        """True when serving model ``model`` should produce NaN rows on
        this batch.  Fires on EVERY batch (a bad deployment stays bad —
        the rollout judge needs a sustained burn, not a blip).  The spec
        arg matches the full versioned serving name, its base model, or
        bare version id, so a soak can plant the fault by version alone
        (``bad_canary:mv-abc123``)."""
        for spec in self.specs:
            if spec.kind != "bad_canary" or not self._active(spec, rank):
                continue
            want = spec.model or ""
            if (model == want
                    or model.rsplit("@", 1)[-1] == want
                    or model.split("@", 1)[0] == want):
                return True
        return False

    def bitflip_rank(self, round_idx: int) -> int | None:
        """The replica index whose resident params should get a bit
        flipped at the start of round ``round_idx``, or None.  NOTE:
        unlike every other kind, @rank names the target REPLICA (mesh
        position), not the calling process — a single-process mesh of N
        virtual devices still has N replicas to rot.  Fires at most once
        per process per spec (the audit's rollback replay runs clean)."""
        for spec in self.specs:
            if (spec.kind != "bitflip_params" or spec.round != round_idx
                    or spec in self._fired):
                continue
            want = spec.attempt if spec.attempt is not None else 0
            if want != self.attempt:
                continue
            self._fired.add(spec)
            return spec.rank
        return None


_CACHE: tuple[tuple[str, ...], FaultInjector] | None = None


def get_injector() -> FaultInjector:
    """Process-wide injector, re-parsed whenever the driving env vars
    change (so tests can monkeypatch the env between uses).  Note the
    once-per-process state (``nan_inject``) lives in the cached instance:
    tests that reuse an identical SPARKNET_FAULT value across cases must
    call :func:`reset_injector` to re-arm it."""
    global _CACHE
    from . import knobs
    key = tuple(knobs.raw(k, "") for k in
                ("SPARKNET_FAULT", "SPARKNET_FAULT_ATTEMPT",
                 "SPARKNET_PROC_ID"))
    if _CACHE is None or _CACHE[0] != key:
        _CACHE = (key, FaultInjector.from_env())
    return _CACHE[1]


def reset_injector() -> None:
    """Drop the process-wide injector (and its fired-once memory)."""
    global _CACHE
    _CACHE = None


def corrupt_bytes(raw: bytes, seq: int) -> bytes:
    """Deterministically rot one record: XOR-flip three bytes at
    seq-derived positions and drop the final byte (a torn read).  The
    truncation guarantees a length-delimited decoder notices — a flip
    that lands inside pixel payload alone would be silent corruption,
    which is the object-store checksum tier's job to catch, not the
    decoder's."""
    if not raw:
        return raw
    buf = bytearray(raw[:-1] if len(raw) > 1 else raw)
    for i in range(3):
        if not buf:
            break
        pos = zlib.crc32(f"corrupt_bytes:{seq}:{i}".encode()) % len(buf)
        buf[pos] ^= 0x5A
    return bytes(buf)


def scribble(path: str) -> None:
    """Corrupt a file in place: truncate to half and overwrite the tail —
    breaks both the zip directory of an .npz and any content checksum."""
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.truncate(max(size // 2, 1))
        f.seek(max(size // 2 - 64, 0))
        f.write(b"\xde\xad\xbe\xef" * 4)
