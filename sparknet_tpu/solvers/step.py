"""The single-step update pipeline shared by the host Solver and the
distributed trainers.

One authoritative implementation of: forward+backward (with BatchNorm
forward-state aux) → ClipGradients → Normalize → Regularize → rule update —
the ``Solver::Step`` inner body + ``ApplyUpdate`` sequence (reference:
caffe/src/caffe/solver.cpp:221-262, solvers/sgd_solver.cpp:102-143).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..graph.net import Net
from ..proto.caffe_pb import SolverParameter
from .lr_policies import learning_rate
from .update_rules import SolverUpdate, preprocess_grads


def make_step_fns(sp: SolverParameter, net: Net, rule: SolverUpdate,
                  lr_mults, decay_mults, remat: bool = False,
                  in_scan: bool = False):
    """Returns (loss_and_grads, local_update, accum_loss_and_grads):

    - ``loss_and_grads(params, batch, rng) -> (loss, params_with_bn, grads)``
    - ``local_update(params, state, it, batches, rng, lr_scale=1.0) ->
      (params, state, loss)`` — one full solver step over
      [iter_size, batch, ...] feeds; ``lr_scale`` multiplies the policy
      rate (the numerical-integrity guard's LR-backoff channel — a
      traced scalar, so changing it does not recompile)
    - ``accum_loss_and_grads(params, batches, rng) -> (loss, params, grads)``
      — the ``iter_size`` micro-batch accumulation of ``Solver::Step``
      (reference: solver.cpp:221-224), raw summed grads (normalization by
      iter_size happens in ``preprocess_grads``)

    ``remat=True`` wraps the forward in ``jax.checkpoint`` so the backward
    recomputes activations instead of storing them — trades FLOPs for HBM
    on memory-bound configs (big batches / VGG-class activation volumes).
    ``in_scan=True`` (the DistributedTrainer, whose round bodies live in
    ``lax.scan``) drops the CSE-prevention barriers — scan already keeps
    XLA from undoing the rematerialization, and the barriers only block
    fusion there (jax.checkpoint docs' prevent_cse guidance).
    """

    def raw_fwd(p, batch, rng):
        out = net.apply(p, batch, train=True, rng=rng)
        return out.loss, out.params

    if remat:
        fwd = jax.checkpoint(raw_fwd, prevent_cse=not in_scan)
        fwd_in_scan = jax.checkpoint(raw_fwd, prevent_cse=False)
    else:
        fwd = fwd_in_scan = raw_fwd

    def loss_and_grads(params, batch, rng):
        (loss, new_params), grads = jax.value_and_grad(
            fwd, has_aux=True)(params, batch, rng)
        return loss, new_params, grads

    def accum_loss_and_grads(params, batches, rng):
        """``batches`` leaves carry a leading iter_size axis."""
        if sp.iter_size == 1:
            batch = jax.tree_util.tree_map(lambda x: x[0], batches)
            return loss_and_grads(params, batch, rng)

        def body(carry, batch):
            params, acc, rng = carry
            rng, sub = jax.random.split(rng)
            (loss, params), g = jax.value_and_grad(
                fwd_in_scan, has_aux=True)(params, batch, sub)
            acc = jax.tree_util.tree_map(jnp.add, acc, g)
            return (params, acc, rng), loss

        zero = jax.tree_util.tree_map(jnp.zeros_like, params)
        (params, grads, _), losses = jax.lax.scan(
            body, (params, zero, rng), batches)
        return jnp.mean(losses), params, grads

    def local_update(params, state, it, batches, rng, lr_scale=1.0):
        loss, params, grads = accum_loss_and_grads(params, batches, rng)
        grads = preprocess_grads(sp, params, grads, lr_mults, decay_mults)
        rate = learning_rate(sp, it) * lr_scale
        params, state = rule.apply(params, grads, state, rate, it,
                                   lr_mults=lr_mults)
        return params, state, loss

    return loss_and_grads, local_update, accum_loss_and_grads
