"""Neuron (elementwise) layers.

Reference implementations: caffe/src/caffe/layers/{relu,prelu,sigmoid,tanh,
absval,bnll,dropout,exp,log,power,threshold}_layer.cpp (headers grouped in
caffe/include/caffe/neuron_layers.hpp).  Each is a one-liner under XLA, which
fuses them into adjacent matmul/conv HLOs — there is nothing to hand-schedule.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ..proto.caffe_pb import FillerParameter
from .fillers import fill
from .registry import LayerImpl, register_layer


@register_layer("ReLU")
class ReLULayer(LayerImpl):
    """max(x,0) + negative_slope·min(x,0) (relu_layer.cpp)."""

    def apply(self, lp, params, bottoms, train, rng):
        slope = float(lp.sub("relu_param").get("negative_slope", 0.0))
        x = bottoms[0]
        if slope == 0.0:
            return [jnp.maximum(x, 0.0)]
        return [jnp.maximum(x, 0.0) + slope * jnp.minimum(x, 0.0)]


@register_layer("PReLU")
class PReLULayer(LayerImpl):
    """Learnable per-channel slope (prelu_layer.cpp); blob shape (C,),
    channel_shared collapses it to (1,); default filler constant 0.25."""

    def init(self, rng, lp, bottom_shapes):
        p = lp.sub("prelu_param")
        shared = bool(p.get("channel_shared", False))
        c = 1 if shared else bottom_shapes[0][1]
        f = FillerParameter.from_pmsg(p.get("filler"))
        if not p.has("filler"):
            f = FillerParameter(type="constant", value=0.25)
        return [fill(rng, f, (c,))]

    def apply(self, lp, params, bottoms, train, rng):
        x = bottoms[0]
        slope = params[0].reshape(1, -1, *([1] * (x.ndim - 2)))
        return [jnp.maximum(x, 0.0) + slope * jnp.minimum(x, 0.0)]


@register_layer("Sigmoid")
class SigmoidLayer(LayerImpl):
    def apply(self, lp, params, bottoms, train, rng):
        return [jax.nn.sigmoid(bottoms[0])]


@register_layer("TanH")
class TanHLayer(LayerImpl):
    def apply(self, lp, params, bottoms, train, rng):
        return [jnp.tanh(bottoms[0])]


@register_layer("AbsVal")
class AbsValLayer(LayerImpl):
    def apply(self, lp, params, bottoms, train, rng):
        return [jnp.abs(bottoms[0])]


@register_layer("BNLL")
class BNLLLayer(LayerImpl):
    """log(1+exp(x)), computed stably as in bnll_layer.cpp."""

    def apply(self, lp, params, bottoms, train, rng):
        x = bottoms[0]
        return [jnp.maximum(x, 0.0) + jnp.log1p(jnp.exp(-jnp.abs(x)))]


@register_layer("Dropout")
class DropoutLayer(LayerImpl):
    """Train-time inverted dropout: zero with prob p, scale survivors by
    1/(1-p); identity at test (dropout_layer.cpp:20-45)."""

    def needs_rng(self, lp, train: bool = True) -> bool:
        return train and float(lp.sub("dropout_param").get("dropout_ratio", 0.5)) > 0

    def apply(self, lp, params, bottoms, train, rng):
        ratio = float(lp.sub("dropout_param").get("dropout_ratio", 0.5))
        x = bottoms[0]
        if not train or ratio == 0.0:
            return [x]
        keep = 1.0 - ratio
        mask = jax.random.bernoulli(rng, keep, x.shape)
        return [jnp.where(mask, x / keep, 0.0)]


@register_layer("Exp")
class ExpLayer(LayerImpl):
    """y = base^(shift + scale·x), natural base when base == -1
    (exp_layer.cpp)."""

    def apply(self, lp, params, bottoms, train, rng):
        p = lp.sub("exp_param")
        base = float(p.get("base", -1.0))
        scale = float(p.get("scale", 1.0))
        shift = float(p.get("shift", 0.0))
        inner = shift + scale * bottoms[0]
        if base == -1.0:
            return [jnp.exp(inner)]
        return [jnp.exp(inner * math.log(base))]


@register_layer("Log")
class LogLayer(LayerImpl):
    """y = log_base(shift + scale·x) (log_layer.cpp)."""

    def apply(self, lp, params, bottoms, train, rng):
        p = lp.sub("log_param")
        base = float(p.get("base", -1.0))
        scale = float(p.get("scale", 1.0))
        shift = float(p.get("shift", 0.0))
        y = jnp.log(shift + scale * bottoms[0])
        if base != -1.0:
            y = y / math.log(base)
        return [y]


@register_layer("Power")
class PowerLayer(LayerImpl):
    """y = (shift + scale·x)^power (power_layer.cpp)."""

    def apply(self, lp, params, bottoms, train, rng):
        p = lp.sub("power_param")
        power = float(p.get("power", 1.0))
        scale = float(p.get("scale", 1.0))
        shift = float(p.get("shift", 0.0))
        inner = shift + scale * bottoms[0]
        if power == 1.0:
            return [inner]
        return [inner ** power]


@register_layer("Threshold")
class ThresholdLayer(LayerImpl):
    """y = 1[x > threshold] (threshold_layer.cpp)."""

    def apply(self, lp, params, bottoms, train, rng):
        t = float(lp.sub("threshold_param").get("threshold", 0.0))
        return [(bottoms[0] > t).astype(bottoms[0].dtype)]
