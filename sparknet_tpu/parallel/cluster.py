"""Multi-host cluster bring-up.

The reference's control plane is one Spark driver plus N executor JVMs,
with the model replicated per-JVM via classloading side effects (reference:
src/main/scala/apps/CifarApp.scala:23-29 — SURVEY.md §7.3 calls this
"fragile magic") and all cross-machine traffic through Spark TCP.  Here
multi-host is the JAX distributed runtime: every host calls
``init_cluster``, gets the same global mesh over all chips (ICI within a
slice, DCN across), and runs the same SPMD program; per-host model
construction is explicit same-seed init, not classloader side effects.

On a TPU pod slice, coordinator/process discovery is automatic from the TPU
metadata environment; off-pod (CPU/GPU test rigs), pass the coordinator
address and process counts explicitly — the spark-submit launcher keeps
doing process placement, but carries no tensor traffic.
"""

from __future__ import annotations

import jax

from ..utils import knobs


def init_cluster(coordinator_address: str | None = None,
                 num_processes: int | None = None,
                 process_id: int | None = None) -> None:
    """Join (or bootstrap) the distributed runtime.  No-op for single-host.

    All arguments default to auto-discovery (TPU metadata / env vars), the
    normal mode on a TPU-VM pod.

    The coordinator connect is a one-shot control-plane edge: non-zero
    ranks race the coordinator's socket bind, and a restarted job can hit
    its predecessor's port in TIME_WAIT — so the connect is retried with
    bounded exponential backoff (SPARKNET_CONNECT_RETRIES /
    SPARKNET_CONNECT_BACKOFF, defaults 3 / 0.5s).  The backoff is
    JITTERED by default (SPARKNET_CONNECT_JITTER, default 0.25): a
    relaunched job restarts ALL its ranks at the same instant, and
    without jitter every rank re-dials the coordinator in lockstep — the
    textbook thundering herd."""
    from ..utils.retry import retry_call
    attempts = int(knobs.raw("SPARKNET_CONNECT_RETRIES", "3") or 3)
    base = float(knobs.raw("SPARKNET_CONNECT_BACKOFF", "0.5") or 0.5)
    jitter = float(knobs.raw("SPARKNET_CONNECT_JITTER", "0.25") or 0.25)
    retry_call(
        jax.distributed.initialize,
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        attempts=attempts, base_delay=base, jitter=jitter,
        retry_on=(RuntimeError, OSError, ConnectionError, TimeoutError),
        describe="jax.distributed.initialize")


def init_cluster_from_env() -> bool:
    """Join the cluster described by SPARKNET_COORDINATOR /
    SPARKNET_NUM_PROCS / SPARKNET_PROC_ID — the env contract the launcher
    (``sparknet_tpu.tools.launch``) sets on every spawned process, playing
    the role of spark-submit's executor placement (reference: SETUP.md,
    ImageNetApp.scala:97).  Returns False (and does nothing) when the env
    is absent, i.e. single-process runs.

    The three vars are validated together: a partial contract (coordinator
    set but counts missing, non-integer counts, or an out-of-range rank)
    raises a ValueError naming the offending variable instead of a bare
    KeyError deep in the launcher plumbing."""
    addr = knobs.raw("SPARKNET_COORDINATOR")
    if not addr:
        for var in ("SPARKNET_NUM_PROCS", "SPARKNET_PROC_ID"):
            if knobs.raw(var):
                raise ValueError(
                    f"{var} is set but SPARKNET_COORDINATOR is not — the "
                    f"launcher env contract requires all three of "
                    f"SPARKNET_COORDINATOR / SPARKNET_NUM_PROCS / "
                    f"SPARKNET_PROC_ID")
        return False
    values = {}
    for var in ("SPARKNET_NUM_PROCS", "SPARKNET_PROC_ID"):
        raw = knobs.raw(var)
        if raw is None or raw == "":
            raise ValueError(
                f"SPARKNET_COORDINATOR is set but {var} is missing — the "
                f"launcher must export SPARKNET_COORDINATOR, "
                f"SPARKNET_NUM_PROCS, and SPARKNET_PROC_ID together")
        try:
            values[var] = int(raw)
        except ValueError:
            raise ValueError(
                f"{var}={raw!r} is not an integer") from None
    nprocs, pid = values["SPARKNET_NUM_PROCS"], values["SPARKNET_PROC_ID"]
    if nprocs < 1:
        raise ValueError(f"SPARKNET_NUM_PROCS={nprocs} must be >= 1")
    if not 0 <= pid < nprocs:
        raise ValueError(
            f"SPARKNET_PROC_ID={pid} out of range for "
            f"SPARKNET_NUM_PROCS={nprocs} (want 0 <= id < num_procs)")
    init_cluster(addr, nprocs, pid)
    return True


def shutdown_cluster() -> None:
    jax.distributed.shutdown()


def is_multi_host() -> bool:
    return jax.process_count() > 1


def local_batch_slice(global_batch: int) -> slice:
    """The half-open row range of the global batch this host should feed —
    the partition-to-worker mapping the reference gets from Spark
    ``zipPartitions`` (reference: ImageNetApp.scala:145)."""
    n, i = jax.process_count(), jax.process_index()
    if global_batch % n:
        raise ValueError(f"global batch {global_batch} not divisible by "
                         f"{n} hosts")
    per = global_batch // n
    return slice(i * per, (i + 1) * per)


def global_max(value: int) -> int:
    """Max of a per-host integer across all processes (identity
    single-host).  Use for eval step counts: every `DistributedTrainer.
    test` step is a collective, so hosts with uneven partition sizes must
    agree on the lockstep step count (the largest) — exhausted hosts pad
    with invalid steps."""
    if jax.process_count() == 1:
        return int(value)
    import numpy as np
    from jax.experimental import multihost_utils
    arr = multihost_utils.process_allgather(np.asarray([int(value)]))
    return int(np.max(arr))
