"""Common layers: InnerProduct, BatchNorm, Scale, Bias, MVN, Embed, shape
ops (Flatten/Reshape/Concat/Slice/Split/Tile), Eltwise, Reduction, Filter,
BatchReindex, ArgMax, Softmax, Accuracy, Silence.

Reference implementations: caffe/src/caffe/layers/*.cpp grouped under
caffe/include/caffe/common_layers.hpp.
"""

from __future__ import annotations

import math
from typing import Sequence

import jax
import jax.numpy as jnp

from ..proto.caffe_pb import FillerParameter, LayerParameter
from .fillers import fill
from .registry import LayerImpl, Shape, register_layer


def _canon_axis(axis: int, ndim: int) -> int:
    return axis + ndim if axis < 0 else axis


@register_layer("InnerProduct")
class InnerProductLayer(LayerImpl):
    """Fully-connected layer (reference:
    caffe/src/caffe/layers/inner_product_layer.cpp): flattens from `axis`,
    weight (num_output, dim) — or (dim, num_output) with transpose — plus
    optional bias.  Lowers to a single MXU GEMM."""

    def _geom(self, lp: LayerParameter, bottom_shape: Shape):
        p = lp.sub("inner_product_param")
        num_output = int(p.get("num_output", 0))
        axis = _canon_axis(int(p.get("axis", 1)), len(bottom_shape))
        transpose = bool(p.get("transpose", False))
        bias_term = bool(p.get("bias_term", True))
        dim = math.prod(bottom_shape[axis:])
        return num_output, axis, dim, transpose, bias_term

    def out_shapes(self, lp, bottom_shapes):
        num_output, axis, _, _, _ = self._geom(lp, bottom_shapes[0])
        return [tuple(bottom_shapes[0][:axis]) + (num_output,)]

    def init(self, rng, lp, bottom_shapes):
        num_output, _, dim, transpose, bias_term = self._geom(lp, bottom_shapes[0])
        p = lp.sub("inner_product_param")
        wf = FillerParameter.from_pmsg(p.get("weight_filler"))
        r1, r2 = jax.random.split(rng)
        wshape = (dim, num_output) if transpose else (num_output, dim)
        blobs = [fill(r1, wf, wshape)]
        if bias_term:
            bf = FillerParameter.from_pmsg(p.get("bias_filler"))
            blobs.append(fill(r2, bf, (num_output,)))
        return blobs

    def apply(self, lp, params, bottoms, train, rng):
        num_output, axis, dim, transpose, bias_term = self._geom(lp, bottoms[0].shape)
        x = bottoms[0].reshape(bottoms[0].shape[:axis] + (dim,))
        w = params[0]
        y = x @ w if transpose else x @ w.T
        if bias_term:
            y = y + params[1]
        return [y]


@register_layer("BatchNorm")
class BatchNormLayer(LayerImpl):
    """Caffe BatchNorm (reference: caffe/src/caffe/layers/batch_norm_layer.cpp):
    three non-learnable blobs — running mean (C,), running variance (C,),
    scale factor (1,) — updated during training forward with
    moving_average_fraction; affine transform is a separate Scale layer.
    `use_global_stats` defaults to the phase (test → true)."""

    has_state = True

    def init(self, rng, lp, bottom_shapes):
        c = bottom_shapes[0][1]
        return [jnp.zeros((c,)), jnp.zeros((c,)), jnp.zeros((1,))]

    def apply(self, lp, params, bottoms, train, rng):
        p = lp.sub("batch_norm_param")
        use_global = bool(p.get("use_global_stats", not train))
        maf = float(p.get("moving_average_fraction", 0.999))
        eps = float(p.get("eps", 1e-5))
        x = bottoms[0]
        mean_b, var_b, scale_b = params
        bshape = (1, -1) + (1,) * (x.ndim - 2)
        if use_global:
            factor = jnp.where(scale_b[0] == 0, 0.0, 1.0 / jnp.where(scale_b[0] == 0, 1.0, scale_b[0]))
            mean = mean_b * factor
            var = var_b * factor
            y = (x - mean.reshape(bshape)) / jnp.sqrt(var.reshape(bshape) + eps)
            return [y], list(params)
        axes = (0,) + tuple(range(2, x.ndim))
        mean = jnp.mean(x, axis=axes)
        var = jnp.mean((x - mean.reshape(bshape)) ** 2, axis=axes)
        y = (x - mean.reshape(bshape)) / jnp.sqrt(var.reshape(bshape) + eps)
        # caffe applies an unbiased correction m/(m-1) to the stored variance
        m = x.size // x.shape[1]
        bias_corr = m / max(m - 1, 1)
        new_params = [
            mean_b * maf + jax.lax.stop_gradient(mean),
            var_b * maf + bias_corr * jax.lax.stop_gradient(var),
            scale_b * maf + 1.0,
        ]
        return [y], new_params


def _scale_shape(lp: LayerParameter, key: str, bottom_shape: Shape) -> tuple[int, Shape]:
    p = lp.sub(key)
    axis = _canon_axis(int(p.get("axis", 1)), len(bottom_shape))
    num_axes = int(p.get("num_axes", 1))
    if num_axes == -1:
        shape = tuple(bottom_shape[axis:])
    else:
        shape = tuple(bottom_shape[axis:axis + num_axes])
    return axis, shape


def _broadcastable(v: jax.Array, axis: int, x: jax.Array) -> jax.Array:
    shape = [1] * x.ndim
    for i, d in enumerate(v.shape):
        shape[axis + i] = d
    return v.reshape(shape)


@register_layer("Scale")
class ScaleLayer(LayerImpl):
    """y = x · γ (+ β), γ broadcast from `axis` (reference:
    caffe/src/caffe/layers/scale_layer.cpp).  Two-bottom form multiplies by
    the second bottom instead of a learned blob."""

    def init(self, rng, lp, bottom_shapes):
        if len(lp.bottom) > 1:
            blobs = []
            shape = tuple(bottom_shapes[1])
        else:
            _, shape = _scale_shape(lp, "scale_param", bottom_shapes[0])
            p = lp.sub("scale_param")
            f = FillerParameter.from_pmsg(p.get("filler"))
            if not p.has("filler"):
                f = FillerParameter(type="constant", value=1.0)
            blobs = [fill(rng, f, shape)]
        if bool(lp.sub("scale_param").get("bias_term", False)):
            bf = FillerParameter.from_pmsg(lp.sub("scale_param").get("bias_filler"))
            blobs.append(fill(jax.random.fold_in(rng, 1), bf, shape))
        return blobs

    def apply(self, lp, params, bottoms, train, rng):
        x = bottoms[0]
        axis, _ = _scale_shape(lp, "scale_param", x.shape)
        bias_term = bool(lp.sub("scale_param").get("bias_term", False))
        if len(bottoms) > 1:
            gamma = bottoms[1]
            beta = params[0] if bias_term and params else None
        else:
            gamma = params[0]
            beta = params[1] if bias_term and len(params) > 1 else None
        y = x * _broadcastable(gamma, axis, x)
        if beta is not None:
            y = y + _broadcastable(beta, axis, x)
        return [y]


@register_layer("Bias")
class BiasLayer(LayerImpl):
    """y = x + β, β broadcast from `axis` (reference:
    caffe/src/caffe/layers/bias_layer.cpp)."""

    def init(self, rng, lp, bottom_shapes):
        if len(lp.bottom) > 1:
            return []
        _, shape = _scale_shape(lp, "bias_param", bottom_shapes[0])
        f = FillerParameter.from_pmsg(lp.sub("bias_param").get("filler"))
        return [fill(rng, f, shape)]

    def apply(self, lp, params, bottoms, train, rng):
        x = bottoms[0]
        axis, _ = _scale_shape(lp, "bias_param", x.shape)
        beta = bottoms[1] if len(bottoms) > 1 else params[0]
        return [x + _broadcastable(beta, axis, x)]


@register_layer("MVN")
class MVNLayer(LayerImpl):
    """Mean-variance normalization per sample (reference:
    caffe/src/caffe/layers/mvn_layer.cpp); across_channels widens the
    normalization axes to include C."""

    def apply(self, lp, params, bottoms, train, rng):
        p = lp.sub("mvn_param")
        across = bool(p.get("across_channels", False))
        normalize_variance = bool(p.get("normalize_variance", True))
        eps = float(p.get("eps", 1e-9))
        x = bottoms[0]
        axes = tuple(range(1, x.ndim)) if across else tuple(range(2, x.ndim))
        mean = jnp.mean(x, axis=axes, keepdims=True)
        y = x - mean
        if normalize_variance:
            std = jnp.sqrt(jnp.mean(y * y, axis=axes, keepdims=True))
            y = y / (std + eps)
        return [y]


@register_layer("Embed")
class EmbedLayer(LayerImpl):
    """Index lookup into a (input_dim, num_output) table (reference:
    caffe/src/caffe/layers/embed_layer.cpp); equivalent to InnerProduct on
    one-hot input."""

    def _geom(self, lp):
        p = lp.sub("embed_param")
        return (int(p.get("num_output", 0)), int(p.get("input_dim", 0)),
                bool(p.get("bias_term", True)))

    def out_shapes(self, lp, bottom_shapes):
        num_output, _, _ = self._geom(lp)
        return [tuple(bottom_shapes[0]) + (num_output,)]

    def init(self, rng, lp, bottom_shapes):
        num_output, input_dim, bias_term = self._geom(lp)
        p = lp.sub("embed_param")
        r1, r2 = jax.random.split(rng)
        blobs = [fill(r1, FillerParameter.from_pmsg(p.get("weight_filler")),
                      (input_dim, num_output))]
        if bias_term:
            blobs.append(fill(r2, FillerParameter.from_pmsg(p.get("bias_filler")),
                              (num_output,)))
        return blobs

    def apply(self, lp, params, bottoms, train, rng):
        _, _, bias_term = self._geom(lp)
        idx = bottoms[0].astype(jnp.int32)
        y = params[0][idx]
        if bias_term:
            y = y + params[1]
        return [y]


@register_layer("Flatten")
class FlattenLayer(LayerImpl):
    """Flatten axes [axis, end_axis] (reference: flatten_layer.cpp)."""

    def _axes(self, lp, ndim):
        p = lp.sub("flatten_param")
        axis = _canon_axis(int(p.get("axis", 1)), ndim)
        end = _canon_axis(int(p.get("end_axis", -1)), ndim)
        return axis, end

    def out_shapes(self, lp, bottom_shapes):
        s = bottom_shapes[0]
        axis, end = self._axes(lp, len(s))
        mid = math.prod(s[axis:end + 1])
        return [tuple(s[:axis]) + (mid,) + tuple(s[end + 1:])]

    def apply(self, lp, params, bottoms, train, rng):
        return [bottoms[0].reshape(self.out_shapes(lp, [bottoms[0].shape])[0])]


@register_layer("Reshape")
class ReshapeLayer(LayerImpl):
    """Reshape with 0 (copy dim) and -1 (infer) entries (reference:
    reshape_layer.cpp), over the [axis, axis+num_axes) window."""

    def out_shapes(self, lp, bottom_shapes):
        s = list(bottom_shapes[0])
        p = lp.sub("reshape_param")
        spec = [int(d) for d in p.get("shape").get_all("dim")] if p.get("shape") else []
        axis = _canon_axis(int(p.get("axis", 0)), len(s))
        num_axes = int(p.get("num_axes", -1))
        window = s[axis:] if num_axes == -1 else s[axis:axis + num_axes]
        out_window: list[int] = []
        infer = -1
        for i, d in enumerate(spec):
            if d == 0:
                out_window.append(window[i])
            elif d == -1:
                infer = i
                out_window.append(1)
            else:
                out_window.append(d)
        total = math.prod(window)
        if infer >= 0:
            known = math.prod(out_window)
            out_window[infer] = total // known
        head = s[:axis]
        tail = [] if num_axes == -1 else s[axis + num_axes:]
        return [tuple(head) + tuple(out_window) + tuple(tail)]

    def apply(self, lp, params, bottoms, train, rng):
        return [bottoms[0].reshape(self.out_shapes(lp, [bottoms[0].shape])[0])]


@register_layer("Concat")
class ConcatLayer(LayerImpl):
    """Concatenate along `axis` (default 1; legacy concat_dim) —
    concat_layer.cpp."""

    def _axis(self, lp, ndim):
        p = lp.sub("concat_param")
        if p.has("concat_dim"):
            return int(p.get("concat_dim"))
        return _canon_axis(int(p.get("axis", 1)), ndim)

    def out_shapes(self, lp, bottom_shapes):
        axis = self._axis(lp, len(bottom_shapes[0]))
        s = list(bottom_shapes[0])
        s[axis] = sum(bs[axis] for bs in bottom_shapes)
        return [tuple(s)]

    def apply(self, lp, params, bottoms, train, rng):
        return [jnp.concatenate(bottoms, axis=self._axis(lp, bottoms[0].ndim))]


@register_layer("Slice")
class SliceLayer(LayerImpl):
    """Split along `axis` at slice_point (or evenly) — slice_layer.cpp."""

    def _geom(self, lp, shape, ntop):
        p = lp.sub("slice_param")
        if p.has("slice_dim"):
            axis = int(p.get("slice_dim"))
        else:
            axis = _canon_axis(int(p.get("axis", 1)), len(shape))
        points = [int(x) for x in p.get_all("slice_point")]
        if not points:
            if shape[axis] % ntop:
                raise ValueError(
                    f"layer {lp.name!r}: axis dim {shape[axis]} not divisible "
                    f"into {ntop} equal slices (give slice_point)")
            step = shape[axis] // ntop
            points = [step * i for i in range(1, ntop)]
        bounds = [0] + points + [shape[axis]]
        return axis, bounds

    def out_shapes(self, lp, bottom_shapes):
        ntop = max(len(lp.top), 1)
        axis, bounds = self._geom(lp, bottom_shapes[0], ntop)
        outs = []
        for i in range(len(bounds) - 1):
            s = list(bottom_shapes[0])
            s[axis] = bounds[i + 1] - bounds[i]
            outs.append(tuple(s))
        return outs

    def apply(self, lp, params, bottoms, train, rng):
        ntop = max(len(lp.top), 1)
        x = bottoms[0]
        axis, bounds = self._geom(lp, x.shape, ntop)
        idx = [slice(None)] * x.ndim
        outs = []
        for i in range(len(bounds) - 1):
            idx[axis] = slice(bounds[i], bounds[i + 1])
            outs.append(x[tuple(idx)])
        return outs


@register_layer("Split")
class SplitLayer(LayerImpl):
    """Fan-out copy: one bottom to N tops (split_layer.cpp).  The reference
    inserts these automatically (util/insert_splits.cpp); JAX's functional
    graphs make the automatic insertion unnecessary, but the explicit layer
    type is still supported."""

    def out_shapes(self, lp, bottom_shapes):
        return [tuple(bottom_shapes[0])] * max(len(lp.top), 1)

    def apply(self, lp, params, bottoms, train, rng):
        return [bottoms[0]] * max(len(lp.top), 1)


@register_layer("Eltwise")
class EltwiseLayer(LayerImpl):
    """PROD / SUM (with coeffs) / MAX over equal-shaped bottoms
    (eltwise_layer.cpp)."""

    def apply(self, lp, params, bottoms, train, rng):
        p = lp.sub("eltwise_param")
        op = str(p.get("operation", "SUM"))
        if op == "PROD":
            y = bottoms[0]
            for b in bottoms[1:]:
                y = y * b
        elif op == "SUM":
            coeffs = [float(c) for c in p.get_all("coeff")] or [1.0] * len(bottoms)
            if len(coeffs) != len(bottoms):
                raise ValueError(
                    f"layer {lp.name!r}: eltwise coeff count {len(coeffs)} "
                    f"!= bottom count {len(bottoms)}")
            y = coeffs[0] * bottoms[0]
            for c, b in zip(coeffs[1:], bottoms[1:]):
                y = y + c * b
        elif op == "MAX":
            y = bottoms[0]
            for b in bottoms[1:]:
                y = jnp.maximum(y, b)
        else:
            raise ValueError(f"unknown eltwise op {op!r}")
        return [y]


@register_layer("Reduction")
class ReductionLayer(LayerImpl):
    """Reduce trailing axes from `axis` with SUM/ASUM/SUMSQ/MEAN × coeff
    (reduction_layer.cpp)."""

    def out_shapes(self, lp, bottom_shapes):
        p = lp.sub("reduction_param")
        axis = _canon_axis(int(p.get("axis", 0)), len(bottom_shapes[0]))
        return [tuple(bottom_shapes[0][:axis])]

    def apply(self, lp, params, bottoms, train, rng):
        p = lp.sub("reduction_param")
        op = str(p.get("operation", "SUM"))
        axis = _canon_axis(int(p.get("axis", 0)), bottoms[0].ndim)
        coeff = float(p.get("coeff", 1.0))
        x = bottoms[0]
        axes = tuple(range(axis, x.ndim))
        if op == "SUM":
            y = jnp.sum(x, axis=axes)
        elif op == "ASUM":
            y = jnp.sum(jnp.abs(x), axis=axes)
        elif op == "SUMSQ":
            y = jnp.sum(x * x, axis=axes)
        elif op == "MEAN":
            y = jnp.mean(x, axis=axes)
        else:
            raise ValueError(f"unknown reduction op {op!r}")
        return [coeff * y]


@register_layer("Tile")
class TileLayer(LayerImpl):
    """Repeat along `axis` `tiles` times (tile_layer.cpp)."""

    def _geom(self, lp, ndim):
        p = lp.sub("tile_param")
        return _canon_axis(int(p.get("axis", 1)), ndim), int(p.get("tiles", 1))

    def out_shapes(self, lp, bottom_shapes):
        axis, tiles = self._geom(lp, len(bottom_shapes[0]))
        s = list(bottom_shapes[0])
        s[axis] *= tiles
        return [tuple(s)]

    def apply(self, lp, params, bottoms, train, rng):
        axis, tiles = self._geom(lp, bottoms[0].ndim)
        reps = [1] * bottoms[0].ndim
        reps[axis] = tiles
        return [jnp.tile(bottoms[0], reps)]


@register_layer("BatchReindex")
class BatchReindexLayer(LayerImpl):
    """Gather batch items by an index bottom (batch_reindex_layer.cpp)."""

    def out_shapes(self, lp, bottom_shapes):
        return [tuple(bottom_shapes[1][:1]) + tuple(bottom_shapes[0][1:])]

    def min_bottoms(self) -> int:
        return 2

    def apply(self, lp, params, bottoms, train, rng):
        return [bottoms[0][bottoms[1].astype(jnp.int32)]]


@register_layer("Filter")
class FilterLayer(LayerImpl):
    """Select batch items where the last bottom (selector) is nonzero
    (filter_layer.cpp).  The output batch size is data-dependent, which XLA
    cannot compile; this layer therefore only works outside `jit` (eager),
    matching its rarity — no zoo model uses it.  ``dynamic_batch`` marks the
    tops so the graph compiler rejects shape-sensitive consumers (their
    declared batch dim would be wrong)."""

    dynamic_batch = True

    def min_bottoms(self) -> int:
        return 2

    def out_shapes(self, lp, bottom_shapes):
        # batch dim unknown until runtime; report input shape — consumers
        # that build params from these shapes are rejected in Net.__init__
        return [tuple(s) for s in bottom_shapes[:-1]]

    def apply(self, lp, params, bottoms, train, rng):
        sel = bottoms[-1].reshape(-1)
        idx = jnp.nonzero(sel)[0]  # errors under jit by design
        return [b[idx] for b in bottoms[:-1]]


@register_layer("ArgMax")
class ArgMaxLayer(LayerImpl):
    """Top-k indices (and optionally values) (argmax_layer.cpp)."""

    def _geom(self, lp):
        p = lp.sub("argmax_param")
        return (bool(p.get("out_max_val", False)), int(p.get("top_k", 1)),
                p.get("axis"))

    def out_shapes(self, lp, bottom_shapes):
        out_max_val, top_k, axis = self._geom(lp)
        s = bottom_shapes[0]
        if axis is not None:
            axis = _canon_axis(int(axis), len(s))
            out = list(s)
            out[axis] = top_k
            return [tuple(out)]
        return [(s[0], 2 if out_max_val else 1, top_k)]

    def apply(self, lp, params, bottoms, train, rng):
        out_max_val, top_k, axis = self._geom(lp)
        x = bottoms[0]
        if axis is not None:
            axis = _canon_axis(int(axis), x.ndim)
            xt = jnp.moveaxis(x, axis, -1)
            vals, idxs = jax.lax.top_k(xt, top_k)
            pick = vals if out_max_val else idxs.astype(x.dtype)
            return [jnp.moveaxis(pick, -1, axis)]
        flat = x.reshape(x.shape[0], -1)
        vals, idxs = jax.lax.top_k(flat, top_k)
        idxs = idxs.astype(x.dtype)
        if out_max_val:
            return [jnp.stack([idxs, vals], axis=1)]
        return [idxs[:, None, :]]


@register_layer("Softmax")
class SoftmaxLayer(LayerImpl):
    """Numerically-stable softmax along `axis` (softmax_layer.cpp)."""

    def apply(self, lp, params, bottoms, train, rng):
        axis = _canon_axis(int(lp.sub("softmax_param").get("axis", 1)),
                           bottoms[0].ndim)
        return [jax.nn.softmax(bottoms[0], axis=axis)]


@register_layer("Accuracy")
class AccuracyLayer(LayerImpl):
    """Top-k classification accuracy with optional ignore_label (reference:
    caffe/src/caffe/layers/accuracy_layer.cpp).  bottom[0] scores
    (N, C, spatial...), bottom[1] integer labels."""

    def min_bottoms(self) -> int:
        return 2

    def out_shapes(self, lp, bottom_shapes):
        if len(lp.top) > 1:
            # second top: per-class accuracy (accuracy_layer.cpp Reshape)
            axis = _canon_axis(int(lp.sub("accuracy_param").get("axis", 1)),
                               len(bottom_shapes[0]))
            return [(), (bottom_shapes[0][axis],)]
        return [()]

    def top_has_batch_axis(self, lp, top_index: int) -> bool:
        return False  # scalar accuracy; per-class vector is class-indexed

    def apply(self, lp, params, bottoms, train, rng):
        p = lp.sub("accuracy_param")
        top_k = int(p.get("top_k", 1))
        axis = _canon_axis(int(p.get("axis", 1)), bottoms[0].ndim)
        ignore = p.get("ignore_label")
        scores, labels = bottoms[0], bottoms[1]
        labels = labels.reshape(labels.shape[0], -1) if labels.ndim > 1 else labels[:, None]
        sc = jnp.moveaxis(scores, axis, -1)
        sc = sc.reshape(sc.shape[0], -1, sc.shape[-1])  # (N, spatial, C)
        lab = labels.astype(jnp.int32).reshape(sc.shape[0], -1)
        true_score = jnp.take_along_axis(sc, lab[:, :, None], axis=-1)
        # rank of true label = #classes with strictly greater score; ties
        # resolved optimistically like caffe's (>=) partial sort
        rank = jnp.sum(sc > true_score, axis=-1)
        correct = (rank < top_k).astype(jnp.float32)
        mask = (lab != int(ignore)).astype(jnp.float32) if ignore is not None \
            else jnp.ones_like(correct)
        denom = jnp.maximum(jnp.sum(mask), 1.0)
        tops = [jnp.sum(correct * mask) / denom if ignore is not None
                else jnp.mean(correct)]
        if len(lp.top) > 1:
            # per-class: correct/count per label value, 0 where the class
            # never appears (accuracy_layer.cpp nums_buffer_ divide)
            classes = sc.shape[-1]
            onehot = (lab[:, :, None] == jnp.arange(classes)) * mask[:, :, None]
            per_count = jnp.sum(onehot, axis=(0, 1))
            per_correct = jnp.sum(onehot * correct[:, :, None], axis=(0, 1))
            tops.append(per_correct / jnp.maximum(per_count, 1.0))
        return tops


@register_layer("Silence")
class SilenceLayer(LayerImpl):
    """Consume bottoms, produce nothing (silence_layer.cpp)."""

    def out_shapes(self, lp, bottom_shapes):
        return []

    def apply(self, lp, params, bottoms, train, rng):
        return []
