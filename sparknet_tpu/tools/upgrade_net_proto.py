"""upgrade_net_proto_text / upgrade_net_proto_binary — read a net proto in
any legacy format (V0 nested layers + padding layers, V1 enum layers, old
data-transform fields) and write it back in the current (V2) format
(reference: caffe/tools/upgrade_net_proto_text.cpp,
upgrade_net_proto_binary.cpp; upgrade chain upgrade_proto.cpp:15-50).

Usage:
  python -m sparknet_tpu.tools.upgrade_net_proto IN OUT [--binary]

Input format (text prototxt vs binary protobuf) is sniffed; --binary
selects binary output (the upgrade_net_proto_binary analog, carrying
weight blobs through), otherwise text is written.
"""

from __future__ import annotations

import argparse


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("input")
    ap.add_argument("output")
    ap.add_argument("--binary", action="store_true",
                    help="write binary NetParameter (weights preserved)")
    args = ap.parse_args(argv)

    from ..proto import load_net_prototxt, save_net_prototxt
    from ..proto.wireformat import encode

    # sniff by parsing: a text prototxt is essentially never valid wire
    # format (ASCII letters decode as bogus field/wire-type pairs), while
    # binary files routinely contain 0x0a/printable runs — so try the
    # strict binary decoder first and fall back to text on WireError
    from ..proto.caffemodel import load_net_binaryproto
    from ..proto.wireformat import WireError
    try:
        net = load_net_binaryproto(args.input)
    except WireError:
        net = load_net_prototxt(args.input)  # upgrades run in from_pmsg

    if args.binary:
        with open(args.output, "wb") as f:
            f.write(encode(net.to_pmsg(include_blobs=True), "NetParameter"))
    else:
        save_net_prototxt(net, args.output)
    print(f"Wrote upgraded NetParameter to {args.output}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
