"""CD rules — thread and typed-error discipline.

The repo's thread contract (DecodePool / EngineDead, WALKTHROUGH §6.10
and §6.13): worker death surfaces as a typed error on every waiter,
never a hang and never a silent swallow; shared state mutated from
both sides of a thread boundary is guarded by a held lock unless the
class explicitly declares the attribute in an ``_unguarded_ok``
allowlist (the GIL makes single-word flag writes atomic — the
allowlist records that the author THOUGHT about it).

  CD001  a class that spawns threading.Thread mutates an attribute
         from both the spawning side and the worker side with at
         least one write outside any ``with self.<lock>:`` block, and
         the attribute is not in ``_unguarded_ok``
  CD002  a broad except inside a thread-worker method that swallows
         the error: no re-raise, no use of the caught exception, no
         parking it on self for a waiter to find
  CD003  broad ``except Exception`` / ``except BaseException`` / bare
         ``except`` anywhere — narrow it to the module's typed errors,
         or baseline it with a reason
"""

from __future__ import annotations

import ast

from .core import Finding, Project, SourceFile, dotted

_BROAD = {"Exception", "BaseException"}


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    types = t.elts if isinstance(t, ast.Tuple) else [t]
    for item in types:
        if dotted(item).rpartition(".")[2] in _BROAD:
            return True
    return False


def _self_attr(node: ast.AST) -> str | None:
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


class _ClassInfo:
    def __init__(self, sf: SourceFile, node: ast.ClassDef) -> None:
        self.sf = sf
        self.node = node
        self.methods: dict[str, ast.AST] = {
            it.name: it for it in node.body
            if isinstance(it, (ast.FunctionDef, ast.AsyncFunctionDef))}
        self.unguarded_ok = self._allowlist(node)
        self.worker_roots = self._thread_targets(node)
        self.worker_set = self._closure(self.worker_roots)

    @staticmethod
    def _allowlist(node: ast.ClassDef) -> set[str]:
        for it in node.body:
            if isinstance(it, ast.Assign):
                names = [t.id for t in it.targets
                         if isinstance(t, ast.Name)]
                if "_unguarded_ok" in names:
                    val = it.value
                    if isinstance(val, ast.Call):  # frozenset({...})
                        val = val.args[0] if val.args else val
                    if isinstance(val, (ast.Set, ast.Tuple, ast.List)):
                        return {e.value for e in val.elts
                                if isinstance(e, ast.Constant)}
        return set()

    def _thread_targets(self, node: ast.ClassDef) -> set[str]:
        roots: set[str] = set()
        for n in ast.walk(node):
            if isinstance(n, ast.Call) and \
                    dotted(n.func).rpartition(".")[2] == "Thread":
                for kw in n.keywords:
                    if kw.arg == "target":
                        attr = _self_attr(kw.value)
                        if attr and attr in self.methods:
                            roots.add(attr)
        return roots

    def _closure(self, roots: set[str]) -> set[str]:
        seen: set[str] = set()
        work = list(roots)
        while work:
            name = work.pop()
            if name in seen or name not in self.methods:
                continue
            seen.add(name)
            for n in ast.walk(self.methods[name]):
                if isinstance(n, ast.Call):
                    attr = _self_attr(n.func)
                    if attr and attr in self.methods:
                        work.append(attr)
        return seen

    def writes(self, method: str) -> list[tuple[str, int, bool]]:
        """(attr, line, guarded) for every ``self.x = ...`` in method,
        guarded = lexically inside ``with self.<attr>:``."""
        out: list[tuple[str, int, bool]] = []

        def targets_of(node: ast.AST) -> list[ast.AST]:
            if isinstance(node, ast.Assign):
                return list(node.targets)
            if isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                return [node.target]
            return []

        def flat(t: ast.AST) -> list[ast.AST]:
            if isinstance(t, (ast.Tuple, ast.List)):
                return [x for e in t.elts for x in flat(e)]
            return [t]

        def walk(node: ast.AST, depth: int) -> None:
            inc = 0
            if isinstance(node, (ast.With, ast.AsyncWith)):
                if any(_self_attr(item.context_expr)
                       for item in node.items):
                    inc = 1
            for t in targets_of(node):
                for leaf in flat(t):
                    attr = _self_attr(leaf)
                    if attr:
                        out.append((attr, leaf.lineno, depth > 0))
            for child in ast.iter_child_nodes(node):
                walk(child, depth + inc)

        walk(self.methods[method], 0)
        return out


def _check_class(project: Project, sf: SourceFile,
                 info: _ClassInfo) -> tuple[list[Finding],
                                            set[tuple[str, int]]]:
    findings: list[Finding] = []
    cd2_sites: set[tuple[str, int]] = set()
    if not info.worker_roots:
        return findings, cd2_sites

    # CD001 — cross-thread unguarded mutation
    side_writes: dict[str, dict[str, list[tuple[str, int, bool]]]] = \
        {"main": {}, "worker": {}}
    for name in info.methods:
        if name == "__init__":
            continue  # construction happens-before thread start
        side = "worker" if name in info.worker_set else "main"
        for attr, line, guarded in info.writes(name):
            side_writes[side].setdefault(attr, []).append(
                (name, line, guarded))
    for attr in sorted(set(side_writes["main"]) & set(side_writes["worker"])):
        if attr in info.unguarded_ok:
            continue
        all_writes = side_writes["main"][attr] + side_writes["worker"][attr]
        unguarded = [w for w in all_writes if not w[2]]
        if not unguarded:
            continue
        _, line, _ = min(unguarded, key=lambda w: w[1])
        f = project.finding(
            sf, "CD001", "error", line,
            f"{info.node.name}.{attr} is written from both the spawning "
            f"side and the thread side with an unguarded write",
            "hold the class lock for every write, or declare the attr in "
            "_unguarded_ok with a comment saying why a bare write is safe")
        if f:
            findings.append(f)

    # CD002 — swallow in worker loop
    for name in sorted(info.worker_set):
        for node in ast.walk(info.methods[name]):
            if not isinstance(node, ast.ExceptHandler) or \
                    not _is_broad(node):
                continue
            if _handler_surfaces(node):
                continue
            f = project.finding(
                sf, "CD002", "error", node.lineno,
                f"broad except in thread worker "
                f"{info.node.name}.{name} swallows the error",
                "re-raise as the module's typed error, or park it on "
                "self (self._err = e) for the waiter contract to surface")
            if f:
                findings.append(f)
            cd2_sites.add((sf.rel, node.lineno))
    return findings, cd2_sites


def _handler_surfaces(handler: ast.ExceptHandler) -> bool:
    """True when the handler re-raises, uses the caught error, parks
    state on self, or delegates to a method (assumed to surface)."""
    caught = handler.name
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if caught and isinstance(node, ast.Name) and node.id == caught:
            return True
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            if any(_self_attr(t) for t in targets):
                return True
        if isinstance(node, ast.Call) and _self_attr(node.func):
            return True
    return False


def check(project: Project) -> list[Finding]:
    findings: list[Finding] = []
    cd2_sites: set[tuple[str, int]] = set()
    for sf in project.files:
        for node in ast.iter_child_nodes(sf.tree):
            if isinstance(node, ast.ClassDef):
                fs, sites = _check_class(project, sf, _ClassInfo(sf, node))
                findings.extend(fs)
                cd2_sites.update(sites)
    # CD003 — broad except anywhere (CD002 sites already reported)
    for sf in project.files:
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.ExceptHandler) and _is_broad(node) \
                    and (sf.rel, node.lineno) not in cd2_sites:
                kind = "bare except" if node.type is None else \
                    f"except {ast.unparse(node.type)}"
                f = project.finding(
                    sf, "CD003", "error", node.lineno,
                    f"overbroad handler: {kind}",
                    "narrow to the module's typed errors, or baseline "
                    "with a one-line reason")
                if f:
                    findings.append(f)
    return findings
