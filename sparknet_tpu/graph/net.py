"""Graph compiler: NetParameter -> pure init/apply functions.

The TPU-native replacement for Caffe's ``Net`` (reference:
caffe/src/caffe/net.cpp:40 ``Init`` — phase filtering, topological wiring via
AppendTop/AppendBottom at net.cpp:385/444, per-layer SetUp with shape
inference) and its executor (``ForwardFromTo``/``BackwardFromTo``,
net.cpp:565/635).  Differences by design:

- The graph lowers to one pure function; ``jax.jit`` compiles forward, and
  backward is ``jax.grad`` of it — there are no per-layer Backward
  implementations and no topological scheduler to maintain.
- ``InsertSplits`` (reference: caffe/src/caffe/util/insert_splits.cpp:12) is
  unnecessary: fan-out in a functional graph is just reusing a value; XLA
  accumulates the cotangents.
- Blob memory management (``SyncedMemory`` CPU/GPU state machine, reference:
  caffe/src/caffe/syncedmem.hpp:62) is XLA's problem, not ours.

Parameter storage is a flat ``{key: [blobs...]}`` dict keyed by layer name,
with cross-layer sharing via ``ParamSpec.name`` (reference: net.cpp
AppendParam sharing semantics) resolved to owner keys.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Mapping, Sequence

import jax
import jax.numpy as jnp

from ..ops.registry import LayerImpl, Shape, get_layer_impl
from ..proto.caffe_pb import (
    LayerParameter,
    NetParameter,
    NetState,
    Phase,
)

# WeightCollection — the {layer name -> list of arrays} container the driver
# averages (reference: src/main/scala/libs/Net.scala:14-47).  Here it is just
# a pytree alias; elementwise add / scalarDivide are jax.tree_util one-liners.
WeightCollection = dict[str, list[jax.Array]]


@dataclasses.dataclass
class NetOutputs:
    """Result of one forward pass."""

    blobs: dict[str, jax.Array]      # net-output blobs (unconsumed tops)
    loss: jax.Array                  # Σ loss_weight · top
    params: WeightCollection         # params incl. forward-state updates (BN)


@dataclasses.dataclass
class _LayerNode:
    lp: LayerParameter
    impl: LayerImpl
    bottoms: list[str]
    tops: list[str]
    param_key: str            # owner layer name holding this layer's blobs
    lr_mults: list[float]
    decay_mults: list[float]


class Net:
    """A phase-filtered, shape-inferred, executable network."""

    def __init__(self, net_param: NetParameter, state: NetState | None = None,
                 *, compute_dtype=None):
        if state is None:
            state = net_param.state or NetState()
        self.state = state
        self.param = net_param.filtered(state)
        self.name = net_param.name
        self.compute_dtype = compute_dtype
        self.nodes: list[_LayerNode] = []
        self.blob_shapes: dict[str, Shape] = {}
        self.input_blobs: dict[str, Shape] = {}

        # net-level input declarations (legacy `input:` + `input_shape:`)
        for i, name in enumerate(self.param.input):
            shape = tuple(self.param.input_shape[i].dim)
            self.blob_shapes[name] = shape
            self.input_blobs[name] = shape

        shared_owner: dict[str, tuple[str, int]] = {}  # ParamSpec.name -> (layer, idx)
        consumed: set[str] = set()

        for lp in self.param.layer:
            impl = get_layer_impl(lp.type)
            tops = list(lp.top)
            bottoms = list(lp.bottom)
            for b in bottoms:
                if b not in self.blob_shapes:
                    raise ValueError(
                        f"layer {lp.name!r} bottom {b!r} unknown "
                        f"(known: {sorted(self.blob_shapes)})")
                consumed.add(b)
            bshapes = [self.blob_shapes[b] for b in bottoms]
            oshapes = impl.out_shapes(lp, bshapes)
            if not tops:
                tops = [lp.name] if oshapes else []
            while len(tops) < len(oshapes):
                tops.append(f"{lp.name}_top{len(tops)}")
            for t, s in zip(tops, oshapes):
                self.blob_shapes[t] = tuple(int(d) for d in s)
            if getattr(impl, "is_input", lambda: False)():
                for t, s in zip(tops, oshapes):
                    self.input_blobs[t] = tuple(int(d) for d in s)

            # param sharing resolution
            param_key = lp.name
            specs = lp.param
            lr_mults = [ps.lr_mult for ps in specs]
            decay_mults = [ps.decay_mult for ps in specs]
            if specs and specs[0].name:
                owner = shared_owner.get(specs[0].name)
                if owner is None:
                    shared_owner[specs[0].name] = (lp.name, 0)
                else:
                    param_key = owner[0]
            if lp.type == "BatchNorm":
                lr_mults = [0.0, 0.0, 0.0]
                decay_mults = [0.0, 0.0, 0.0]
            self.nodes.append(_LayerNode(
                lp=lp, impl=impl, bottoms=bottoms, tops=tops,
                param_key=param_key, lr_mults=lr_mults, decay_mults=decay_mults,
            ))

        produced = [t for n in self.nodes for t in n.tops]
        self.output_blobs = [t for t in dict.fromkeys(produced)
                             if t not in consumed and t not in self.input_blobs]

    # -- construction -----------------------------------------------------
    def init(self, rng: jax.Array) -> WeightCollection:
        """Create all learnable blobs with Caffe-filler init (the SetUp pass
        of reference net.cpp:73-133)."""
        params: WeightCollection = {}
        for node in self.nodes:
            if node.param_key != node.lp.name:
                continue  # shared; owner creates
            rng, sub = jax.random.split(rng)
            bshapes = [self.blob_shapes[b] for b in node.bottoms]
            blobs = node.impl.init(sub, node.lp, bshapes)
            if blobs:
                params[node.lp.name] = list(blobs)
        return params

    def lr_mult_tree(self, params: WeightCollection) -> WeightCollection:
        """Per-blob lr multipliers, same pytree structure as params
        (ParamSpec.lr_mult, reference: caffe.proto ParamSpec)."""
        return self._mult_tree(params, "lr_mults", 1.0)

    def decay_mult_tree(self, params: WeightCollection) -> WeightCollection:
        return self._mult_tree(params, "decay_mults", 1.0)

    def _mult_tree(self, params, attr, default):
        out: WeightCollection = {}
        by_name = {n.lp.name: n for n in self.nodes}
        for key, blobs in params.items():
            mults = getattr(by_name[key], attr, []) if key in by_name else []
            out[key] = [
                jnp.asarray(mults[i] if i < len(mults) else default)
                for i in range(len(blobs))
            ]
        return out

    # -- execution --------------------------------------------------------
    def apply(self, params: WeightCollection, inputs: Mapping[str, jax.Array],
              *, train: bool | None = None, rng: jax.Array | None = None,
              ) -> NetOutputs:
        """One forward pass.  ``inputs`` binds every input blob (data-layer
        top).  Returns net outputs, the weighted loss sum, and params with
        any forward-state updates (BatchNorm running stats) applied."""
        blobs, loss, new_params = self._run(params, inputs, train, rng)
        out = {t: blobs[t] for t in self.output_blobs}
        return NetOutputs(blobs=out, loss=loss, params=new_params)

    def apply_all(self, params, inputs, *, train=None, rng=None
                  ) -> dict[str, jax.Array]:
        """Forward returning every intermediate blob (debug; the analog of
        reading arbitrary blobs over the reference's FFI introspection,
        libccaffe/ccaffe.cpp:86-139)."""
        blobs, _, _ = self._run(params, inputs, train, rng)
        return blobs

    def _run(self, params, inputs, train, rng):
        """The layer-by-layer forward shared by apply/apply_all."""
        if train is None:
            train = self.state.phase == Phase.TRAIN
        if rng is None and any(n.impl.needs_rng(n.lp, train) for n in self.nodes):
            raise ValueError(
                f"net {self.name!r} needs an rng in this mode "
                f"(stochastic layer present)")
        for name in self.input_blobs:
            if name not in inputs:
                raise ValueError(f"missing input blob {name!r}")
        blobs: dict[str, jax.Array] = dict(inputs)
        new_params = dict(params)
        loss = jnp.zeros((), jnp.float32)
        for node in self.nodes:
            if getattr(node.impl, "is_input", lambda: False)():
                continue
            layer_rng = None
            if rng is not None and node.impl.needs_rng(node.lp, train):
                rng, layer_rng = jax.random.split(rng)
            p = new_params.get(node.param_key, [])
            bots = [blobs[b] for b in node.bottoms]
            result = node.impl.apply(node.lp, p, bots, train, layer_rng)
            if getattr(node.impl, "has_state", False):
                tops, updated = result
                new_params[node.param_key] = list(updated)
            else:
                tops = result
            for t, v in zip(node.tops, tops):
                blobs[t] = v
            # loss accumulation (reference: Layer::SetLossWeights +
            # Net::Forward summing weighted tops)
            weights = list(node.lp.loss_weight)
            if not weights and node.impl.is_loss():
                weights = [1.0] + [0.0] * (len(node.tops) - 1)
            for w, v in zip(weights, tops):
                if w:
                    loss = loss + w * jnp.sum(v)
        return blobs, loss, new_params

    # -- introspection (FFI-parity helpers; reference: ccaffe.cpp:86-139,
    #    Net.scala:64-66) --------------------------------------------------
    @property
    def num_layers(self) -> int:
        return len(self.nodes)

    def layer_names(self) -> list[str]:
        return [n.lp.name for n in self.nodes]

    def layer_num_weights(self, params: WeightCollection) -> dict[str, int]:
        return {k: len(v) for k, v in params.items()}


# -- WeightCollection math (reference: Net.scala:17-46) ---------------------

def weights_add(a: WeightCollection, b: WeightCollection) -> WeightCollection:
    """Elementwise sum — WeightCollection.add (reference: Net.scala:27-46)."""
    return jax.tree_util.tree_map(jnp.add, a, b)


def weights_scalar_divide(w: WeightCollection, v: float) -> WeightCollection:
    """In the reference this is in-place (Net.scala:17-23); pure here."""
    return jax.tree_util.tree_map(lambda x: x / v, w)
