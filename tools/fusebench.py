"""Fused-vs-reference parity gate for the vertical fusion pass.

The profile-driven conv+bias+relu(+pool/LRN) chain fusion
(``sparknet_tpu/graph/fusion.py`` planning, ``graph/net.py`` block
execution, ``ops/vision.py`` / ``ops/pallas_kernels.py`` LRN epilogues)
must be a pure THROUGHPUT optimization: fused execution has to
reproduce per-layer execution exactly.  This tool builds one synthetic
net containing every chain shape the planner emits —

    conv+bias+relu            (in-block, no epilogue op)
    conv+bias+relu+pool       (in-block)
    conv+bias+relu+LRN        (fused relu+lrn epilogue)
    conv+bias+relu+pool+LRN   (fused lrn epilogue)

— and FAILS unless, SPARKNET_FUSE=off vs =all, on this backend:

- the forward loss and every net-output blob are BIT-IDENTICAL in f32
  and under compute_dtype=bf16 (on CPU the fused primal forward lowers
  to the same op sequence as the per-layer path; on TPU the Pallas
  epilogue is held to the same equality — a failure there is a kernel
  bug, not tolerance);
- every parameter gradient matches within a documented ulp bound
  (rtol 1e-5 f32: the fused chains carry the closed-form custom VJP,
  which is the same arithmetic associated differently);
- the planner REFUSES a planted unfusable hotspot: a profile worklist
  naming a fan-out conv (two consumers) must come back in
  ``plan.refused`` with a reason, never silently fused or dropped;
- ``SPARKNET_FUSE=off`` really is the escape hatch: no chains planned,
  ``fuse_plan_id() == "off"``.

It also times the LRN-chain train step fused vs unfused (the worklist's
#1 chain class) and fails if fusion makes it >25% SLOWER — the win is
recorded, the gate only refuses a gross regression (CPU CI timers are
noisy; the committed BENCH/profile captures are the numbers of record).
``--iters 0`` skips the timing leg entirely (the in-tree smoke does:
at that size on a loaded box the timer measures the scheduler).

Wired into tools/run_tier1.sh behind SPARKNET_FUSEBENCH=1 (or
``--fusebench``); the same contracts run in-process in
tests/test_fusion.py.

Usage:
    python tools/fusebench.py [--batch 4] [--iters 6] [--out FILE]

Prints one JSON line on stdout; rc 0 = parity holds.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _build_layers(batch: int, channels: int = 32, side: int = 14):
    from sparknet_tpu.models.dsl import (
        convolution_layer,
        inner_product_layer,
        layer,
        lrn_layer,
        pooling_layer,
        relu_layer,
        softmax_with_loss_layer,
    )
    wf = {"type": "gaussian", "std": 0.05}
    bf = {"type": "constant", "value": 0.1}
    return [
        layer("data", "Input", tops=["data", "label"],
              input_param={"shape": [{"dim": [batch, 3, side, side]},
                                     {"dim": [batch]}]}),
        # conv+bias+relu (in-block)
        convolution_layer("c1", "data", "c1", num_output=channels, kernel=3,
                          pad=1, weight_filler=wf, bias_filler=bf),
        relu_layer("r1", "c1", "c1"),
        # conv+bias+relu+pool (in-block)
        convolution_layer("c2", "c1", "c2", num_output=channels, kernel=3,
                          pad=1, weight_filler=wf, bias_filler=bf),
        relu_layer("r2", "c2", "c2"),
        pooling_layer("p2", "c2", "p2", kernel=2, stride=2),
        # conv+bias+relu+LRN (fused relu+lrn epilogue)
        convolution_layer("c3", "p2", "c3", num_output=channels, kernel=3,
                          pad=1, weight_filler=wf, bias_filler=bf),
        relu_layer("r3", "c3", "c3"),
        lrn_layer("n3", "c3", "n3", local_size=5, alpha=1e-4, beta=0.75),
        # conv+bias+relu+pool+LRN (fused lrn epilogue after the pool)
        convolution_layer("c4", "n3", "c4", num_output=channels, kernel=3,
                          pad=1, weight_filler=wf, bias_filler=bf),
        relu_layer("r4", "c4", "c4"),
        pooling_layer("p4", "c4", "p4", kernel=2, stride=2),
        lrn_layer("n4", "p4", "n4", local_size=3, alpha=2e-4, beta=0.5),
        inner_product_layer("ip", "n4", "ip", num_output=10,
                            weight_filler={"type": "gaussian", "std": 0.01}),
        softmax_with_loss_layer("loss", ["ip", "label"]),
    ]


EXPECTED_CHAINS = {
    "c1+r1": "none",
    "c2+r2+p2": "none",
    "c3+r3+n3": "relu+lrn",
    "c4+r4+p4+n4": "lrn",
}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--iters", type=int, default=6,
                    help="timed iterations of the LRN-chain microbench")
    ap.add_argument("--out", default=None)
    args = ap.parse_args(argv)

    import jax
    import jax.numpy as jnp
    import numpy as np

    from sparknet_tpu.graph import fusion
    from sparknet_tpu.graph.net import Net
    from sparknet_tpu.models.dsl import net_param
    from sparknet_tpu.proto.caffe_pb import NetState, Phase

    failures: list[str] = []
    netp = net_param("fusebench", _build_layers(args.batch))

    def build(fuse: str, dtype=None) -> Net:
        os.environ["SPARKNET_FUSE"] = fuse
        try:
            return Net(netp, NetState(Phase.TRAIN), compute_dtype=dtype)
        finally:
            os.environ.pop("SPARKNET_FUSE", None)

    net_off = build("off")
    net_all = build("all")

    # -- plan shape: every chain family present, escape hatch clean ------
    planned = {c.scope(): c.epilogue for c in net_all._fuse_plan.chains}
    if planned != EXPECTED_CHAINS:
        failures.append(f"planned chains {planned} != {EXPECTED_CHAINS}")
    if net_off.fuse_plan_id() != "off" or getattr(
            net_off, "_vfuse_head", None):
        failures.append("SPARKNET_FUSE=off still planned chains")

    # -- forward/backward parity, f32 ------------------------------------
    rng = jax.random.PRNGKey(0)
    params = net_off.init(rng)
    r = np.random.default_rng(0)
    ins = {"data": jnp.asarray(
        r.normal(size=net_off.input_blobs["data"]), jnp.float32),
        "label": jnp.asarray(
            r.integers(0, 10, size=net_off.input_blobs["label"]),
            jnp.float32)}

    def loss_fn(net):
        return lambda p: net.apply(p, ins, rng=rng).loss

    l_off, g_off = jax.value_and_grad(loss_fn(net_off))(params)
    l_all, g_all = jax.value_and_grad(loss_fn(net_all))(params)
    if float(l_off) != float(l_all):
        failures.append(
            f"f32 forward loss not bit-identical: {float(l_off)!r} "
            f"(off) vs {float(l_all)!r} (all)")
    grad_rel = 0.0
    for k in g_off:
        for a, b in zip(g_off[k], g_all[k]):
            a64 = np.asarray(a, np.float64)
            b64 = np.asarray(b, np.float64)
            denom = float(np.max(np.abs(a64))) or 1.0
            grad_rel = max(grad_rel,
                           float(np.max(np.abs(a64 - b64))) / denom)
    if grad_rel > 1e-5:
        failures.append(f"f32 gradient divergence {grad_rel:.3e} exceeds "
                        f"the 1e-5 ulp bound")

    # -- forward parity, bf16 compute ------------------------------------
    lb_off = float(loss_fn(build("off", jnp.bfloat16))(params))
    lb_all = float(loss_fn(build("all", jnp.bfloat16))(params))
    if lb_off != lb_all:
        failures.append(f"bf16 forward loss not bit-identical: "
                        f"{lb_off!r} vs {lb_all!r}")

    # -- planted-unfusable refusal ---------------------------------------
    # a worklist hotspot whose conv has TWO consumers (fan-out) names no
    # legal chain; the planner must record the refusal, not fuse or drop
    from sparknet_tpu.models.dsl import (
        concat_layer, convolution_layer, layer, relu_layer,
    )
    fan = net_param("fanout", [
        layer("data", "Input", tops=["data"],
              input_param={"shape": [{"dim": [1, 3, 8, 8]}]}),
        convolution_layer("hot", "data", "hot", num_output=4, kernel=3,
                          pad=1, weight_filler={"type": "xavier"}),
        relu_layer("hotrelu", "hot", "hotr"),
        concat_layer("skip", ["hot", "hotr"], "out"),
    ])
    os.environ["SPARKNET_FUSE"] = "off"
    try:
        fan_net = Net(fan, NetState(Phase.TEST))
    finally:
        os.environ.pop("SPARKNET_FUSE", None)
    fake_profile = {"by_layer": [
        {"op": "hot", "total_ms": 50.0, "pct": 40.0, "gb_per_s": 300.0,
         "gflops_per_s": 100.0},
        {"op": "neighbor", "total_ms": 30.0, "pct": 30.0,
         "gb_per_s": 1000.0},
    ]}
    plan = fusion.plan_from_profile(fan_net, fake_profile, source="planted")
    if plan.chains:
        failures.append(f"planner fused a fan-out conv: "
                        f"{[c.scope() for c in plan.chains]}")
    if not any(rf.get("candidate") == "hot" and rf.get("reason")
               for rf in plan.refused):
        failures.append(f"fan-out hotspot not refused with a reason: "
                        f"{plan.refused}")

    # -- LRN-chain microbench (report the win, refuse a regression) ------
    # --iters 0 skips the timing leg: at in-tree-smoke sizes under a
    # loaded CI box the timer is pure noise; the opt-in gate runs it at
    # a size where a real slowdown is distinguishable from scheduling
    timing: dict = {}
    if args.iters > 0:
        def timed(net) -> float:
            f = jax.jit(jax.value_and_grad(loss_fn(net)))
            _, g = f(params)
            jax.block_until_ready(g)
            t0 = time.perf_counter()
            for _ in range(args.iters):
                _, g = f(params)
            jax.block_until_ready(g)
            return (time.perf_counter() - t0) / args.iters

        t_off = timed(net_off)
        t_all = timed(net_all)
        timing = {
            "unfused_step_ms": round(t_off * 1e3, 2),
            "fused_step_ms": round(t_all * 1e3, 2),
            "fused_speedup_x": round(t_off / t_all, 3) if t_all else None,
        }
        if t_all > 1.25 * t_off:
            failures.append(f"fused step {t_all * 1e3:.1f} ms is >25% "
                            f"slower than unfused {t_off * 1e3:.1f} ms")

    result = {
        "ok": not failures,
        "failures": failures,
        "backend": jax.default_backend(),
        "plan_id": net_all.fuse_plan_id(),
        "chains": planned,
        "grad_max_rel": grad_rel,
        "refused": plan.refused,
        **timing,
    }
    line = json.dumps(result)
    print(line, flush=True)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    if failures:
        print(f"[fusebench] PARITY FAILURE: {failures}", file=sys.stderr,
              flush=True)
        return 1
    t = (f"; LRN-chain step {timing['unfused_step_ms']} -> "
         f"{timing['fused_step_ms']} ms ({timing['fused_speedup_x']}x)"
         if timing else "")
    print(f"[fusebench] parity holds over {len(planned)} chain shapes "
          f"(grad ulp {grad_rel:.1e}){t}", file=sys.stderr, flush=True)
    return 0


if __name__ == "__main__":
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    raise SystemExit(main())
