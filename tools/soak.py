"""Chaos soak runner: N short supervised training runs under randomized —
but seeded — fault schedules, each checked for exact recovery, with a
JSON verdict.

The per-fault chaos tests (tests/test_resilience.py, marker ``chaos``)
pin one failure mode each; this runner is the composition check the
ROADMAP's production posture needs: pick a fault *schedule* at random
(crash, torn checkpoint write, NaN poison, replica bit flip, straggle ...
each with a random round/rank), run the standard 4-round driver workload
under ResilientRunner supervision, and assert the finished params are
bit-for-bit the fault-free baseline of the same configuration.  The
randomness is fully derived from ``--seed``, so any red verdict is
replayable with the same command line.

Fleet mode (``--fleet N``) is the FLEET-WIDE composition check: N
seeded jobs (each with its own injected crash/straggle/preempt/nan
schedule) run CONCURRENTLY under one ``FleetScheduler``, plus a
late-arriving high-priority job sized to the whole device budget that
forces a fleet-level preemption of everything running.  With
``--fleet-kill`` the scheduler itself is SIGKILLed mid-run and resumed
from its journal.  The verdict requires every job to reach its target
round with final params bit-identical to its fault-free baseline, the
resumed queue to never double-launch, and ZERO orphaned worker
processes at the end.

Pod mode (``--pod N``) is the POD-SCALE burn-in: a simulated N-host rig
(every host a local slice of the device budget, but placed over the
REAL ssh wire format — ``SshTransport`` through a fake-ssh shim, or a
caller-supplied ``SPARKNET_SSH_CMD`` for a live inventory) runs mixed
tenants — two training gangs plus a
replicated serving tenant behind the request router — under a seeded
production-shaped :class:`TrafficModel`: a diurnal paced-load curve, a
flash crowd, corrupt-upload bursts through the data quarantine plane,
and host-kill / host-drain chaos events injected through the
host-control channel mid-leg.  Every episode must end with the training
params bit-identical to the fault-free baseline, zero client-visible
serving errors (typed rejections allowed), the serving tier healed back
to N replicas, and ZERO orphans; any breach writes a postmortem.json +
flight-recorder dump and fails the run.  ``--forever`` keeps scheduling
episodes until one fails (the standing burn-in posture); ``--pod-slice``
is the ~60 s CI shape (one host-kill + one flash crowd).

Net mode (``--net``) is the NETWORK chaos burn-in — the partition-vs-
death legs the pod burn-in grows in PR 17, runnable standalone so CI
can gate on them.  Every leg drives the production ssh wire format
(``SshTransport`` through a local fake-ssh shim) wrapped in a
``ChaosTransport``: (1) *partition-suspend-heal* — sever the beat relay
to a mid-round gang; the lease must mark the host SUSPECT (not kill it,
not burn restart budget), the heal must lift the suspension, and the
finished params must be bit-identical to the fault-free baseline;
(2) *fenced-zombie-ship* — an incarnation checkpoints on one host, its
requeue lands on a checkpoint-less host that pulls the newest valid
round over a link that TEARS the first transfer (the retry resumes the
torn prefix, crc-verified), resumes bit-identically, and the fenced-off
zombie returning from behind the partition is refused at the fence with
a typed error and zero corruption; (3, full runs only) *slow-link
attribution* — a delayed relay is NOT silence: no suspect, no straggler
kill, bit-identical finish.  A full ``--pod`` episode set appends the
same legs, so the pod burn-in exercises them too; ``--net-slice`` keeps
the ~60 s CI shape (legs 1 + 2).

Rollout mode (``--rollout``) is the DEPLOYMENT-PLANE burn-in (PR 18):
three legs over a real registry + router + per-version engines in one
process.  (1) *canary-promote* — a healthy canary at 50 % traffic must
earn promotion through sustained green per-version SLO verdicts over
the request floor, with the old stable drained through the router
fences and pinned-canary answers bit-identical across the pointer
flip; (2) *bad-canary-rollback* — a canary poisoned with the planted
``bad_canary`` fault (its head emits NaNs; the engine fails those
requests TYPED, never serves them) must be auto-rolled back by the
judge within the breach window, with zero errors on stable-pinned
traffic, zero non-finite rows served, the channel pointer reverted,
the canary drained, and a flight dump on disk; (3) *controller-kill-
resume* — a controller killed after ``canary_live`` must resume to
fully-stable (an unjudged canary takes no traffic) and one killed
between ``promote_begin`` and its ``done`` must resume to
fully-promoted, both idempotently with no orphan replicas.

Usage:
  python tools/soak.py --runs 8 --seed 0 --out soak.json
  python tools/soak.py --fleet 4 --fleet-kill --seed 0   # fleet chaos
  python tools/soak.py --pod 3 --seed 0 --out SOAK_pod.json
  python tools/soak.py --pod 3 --forever   # standing burn-in
  python tools/soak.py --net --seed 0 --out SOAK_net.json
  python tools/soak.py --rollout --seed 0 --out SOAK_rollout.json
  SPARKNET_SOAK=1 tools/run_tier1.sh       # the 2-run CI smoke
  SPARKNET_FLEETSOAK=1 tools/run_tier1.sh  # the 2-job fleet smoke
  SPARKNET_PODSOAK=1 tools/run_tier1.sh    # the 3-host pod slice
  SPARKNET_NETSOAK=1 tools/run_tier1.sh    # the 2-leg net slice
  SPARKNET_ROLLSMOKE=1 tools/run_tier1.sh  # the 3-leg rollout smoke

Exit code 0 iff every run recovered exactly; the JSON verdict names each
run's schedule, exit code, attempt count, and whether the params matched.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
DRIVER = os.path.join(REPO, "tests", "multihost_driver.py")


def _schedules(rng):
    """One randomized-but-seeded fault schedule: (name, SPARKNET_FAULT
    value, extra driver flags).  Rounds land in [1, 3) so the 4-round
    workload always has a checkpoint before and rounds after the fault."""
    r = int(rng.integers(1, 3))
    return [
        ("crash", f"crash@round:{r}", []),
        ("crash_in_ckpt", f"crash_in_ckpt@round:{r}", []),
        ("corrupt_ckpt", f"corrupt_ckpt@round:{r}", []),
        ("nan_inject", f"nan_inject@round:{r}", ["--guard"]),
        ("bitflip_params",
         f"bitflip_params@rank:{int(rng.integers(0, 4))}@round:{r}",
         ["--audit-every", "1"]),
        ("straggle+crash",
         f"straggle:0.5s@round:{r},crash@round:{r}@attempt:0", []),
    ]


# telemetry env survives the scrub so a traced soak (SPARKNET_TRACE_DIR
# set, then `tools/obs.py merge` over the dir) yields the one-timeline
# chaos story: fault injection, restarts, rollbacks, recovered rounds,
# correlated across every rank and attempt
_KEEP_ENV = ("SPARKNET_SOAK", "SPARKNET_TELEMETRY", "SPARKNET_TRACE_DIR",
             "SPARKNET_METRICS_SNAP", "SPARKNET_METRICS_SNAP_S",
             "SPARKNET_RUN_ID", "SPARKNET_FLIGHT_EVENTS",
             # a caller-supplied ssh shim (or real ssh wrapper) survives
             # the scrub: the pod/net modes ride the wire it names
             "SPARKNET_SSH_CMD")


def _clean_env():
    os.environ.pop("XLA_FLAGS", None)
    for k in list(os.environ):
        if k.startswith("SPARKNET_") and k not in _KEEP_ENV:
            os.environ.pop(k)


def _run_driver(out, ckpt, flags, fault=None, max_restarts=2,
                local_devices=4, rounds=4):
    from sparknet_tpu.parallel.resilience import ResilientRunner, RestartPolicy
    cmd = [sys.executable, DRIVER, "--strategy", "sync", "--out", out,
           "--local-devices", str(local_devices),
           "--expect-devices", str(local_devices),
           "--rounds", str(rounds)] + flags
    if ckpt:
        cmd += ["--ckpt-dir", ckpt]
    runner = ResilientRunner(
        cmd, nprocs=1, platform="cpu", timeout=300,
        policy=RestartPolicy(max_restarts=max_restarts, backoff_base=0.2),
        extra_env={"SPARKNET_FAULT": fault} if fault else None)
    rc = runner.run()
    return rc, len(runner.attempts)


def _params_match(base_npz, out_npz):
    import numpy as np
    a, b = np.load(base_npz), np.load(out_npz)
    for k in a.files:
        if k.startswith("__"):
            continue
        if not np.array_equal(a[k], b[k]):
            return False, k
    return True, None


# ---------------------------------------------------------------------------
# Net chaos legs (--net; full --pod runs append the same set): partition
# vs death, fenced checkpoint shipping, and slow-link attribution over
# the REAL ssh wire format (SshTransport through a fake-ssh shim) with
# ChaosTransport injecting the network faults mid-episode
# ---------------------------------------------------------------------------

def _fake_ssh_shim(workdir: str) -> str:
    """Write the fake-ssh shim: executes the remote command string
    locally with the exact argv ssh receives (``$4`` is the remote
    string after ``-o BatchMode=yes <host>``), so the wire format, env
    contract, and stdio plumbing are the production path — no sshd.
    ``exec`` keeps the worker pid == the Popen pid (signalling and
    pid-identity checks work unchanged)."""
    path = os.path.join(workdir, "fake-ssh")
    with open(path, "w") as f:
        f.write('#!/bin/bash\nexec bash -c "$4"\n')
    os.chmod(path, 0o755)
    return path


class _TornOnceInjector:
    """Minimal injector for ChaosTransport: tear the first ``torn``
    ship attempts (each leaves a half-written temp the retry must
    resume past), then run clean.  Duck-typed to the faults-injector
    surface the transport consumes."""

    def __init__(self, torn: int = 1):
        self.torn = torn
        self.specs = ()

    def net_specs(self):
        return []

    def drop_ship(self, seq):
        return False

    def torn_ship(self):
        if self.torn > 0:
            self.torn -= 1
            return True
        return False


def _net_knobs(workdir: str) -> None:
    """The net-leg env: the fake-ssh wire (unless the caller supplied a
    real SPARKNET_SSH_CMD), a tight lease so a partition is suspected
    within ~1 s, and small ship chunks so torn-transfer resume moves a
    real whole-chunk prefix."""
    os.environ.setdefault("SPARKNET_SSH_CMD", _fake_ssh_shim(workdir))
    os.environ.setdefault("SPARKNET_LEASE_S", "0.5")
    os.environ.setdefault("SPARKNET_LEASE_MISSES", "2")
    os.environ.setdefault("SPARKNET_SHIP_CHUNK_MB", "0.0625")
    # the ssh-spawned workers inherit this process's env through the
    # shim (the remote branch applies no platform/device carving)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.setdefault("JAX_PLATFORM_NAME", "cpu")


def _wire_driver(out, rounds, *, host, ckpt=None, extra_env=None,
                 transport=None, heartbeat_dir=None, round_deadline=None,
                 report=None) -> int:
    """One driver run over the ssh wire: a single rank with 4 virtual
    devices on the fake 'remote' host (SPARKNET_NUM_PROCS=1 — the gang
    shape the pod fleet places).  ``host`` is the host LABEL
    (beat-staging + lease identity); the transport address stays
    127.0.0.1 so the coordinator resolves, exactly the name-vs-addr
    split a HostPool inventory makes."""
    from sparknet_tpu.tools.launch import free_port, launch_ssh
    cmd = [sys.executable, DRIVER, "--strategy", "sync", "--out", out,
           "--local-devices", "4", "--expect-devices", "4",
           "--rounds", str(rounds)]
    if ckpt:
        cmd += ["--ckpt-dir", ckpt]
    return launch_ssh(cmd, hosts=["127.0.0.1"], host_map=[host],
                      coordinator_port=free_port(),
                      cwd=REPO, timeout=300, extra_env=extra_env,
                      transport=transport, heartbeat_dir=heartbeat_dir,
                      round_deadline=round_deadline, report=report)


def _net_partition_episode(workdir, baseline, rounds, *,
                           slow_ms: float | None = None) -> dict:
    """Symmetric partition mid-round (or, with ``slow_ms``, a degraded
    link): sever the beat relay to a healthy mid-round gang.  The lease
    must mark the host SUSPECT and *suspend* its ranks — no straggler
    kill, no restart-budget burn — then lift the suspension on heal,
    and the finished params must be bit-identical to the fault-free
    baseline.  The slow-link variant asserts the opposite discipline:
    delay is NOT silence — beats arrive late but fresh, so no suspect,
    no kill (straggler attribution stays with the per-rank beats)."""
    import threading

    from sparknet_tpu.parallel import health
    from sparknet_tpu.parallel.transport import (ChaosTransport,
                                                 SshTransport)

    name = "slow_link_attribution" if slow_ms else "partition_suspend_heal"
    epdir = os.path.join(workdir, name)
    os.makedirs(epdir, exist_ok=True)
    out = os.path.join(epdir, "out.npz")
    hb = os.path.join(epdir, "hb")
    host = "hostb"
    chaos = ChaosTransport(SshTransport(), injector=_TornOnceInjector(0))
    flap: dict = {}

    def flapper():
        # wait until the first beat has been RELAYED (the monitor has
        # host liveness on file — a partition before any relayed beat
        # is startup grace, not a lease event), then flap the link
        hdir = health.host_dir(hb, host)
        deadline = time.monotonic() + 120.0
        while time.monotonic() < deadline:
            if health._read_flat(hdir):
                break
            time.sleep(0.05)
        else:
            flap["error"] = "no beat ever relayed"
            return
        if slow_ms:
            chaos.set_slow(host, slow_ms)
            flap["slow_ms"] = slow_ms
            time.sleep(3.0)
            chaos.set_slow(host, 0)
            flap["restored"] = True
        else:
            chaos.partition(host)
            flap["partitioned"] = True
            time.sleep(4.0)   # 4x the 1 s lease window, > round deadline
            chaos.heal(host)
            flap["healed"] = True

    th = threading.Thread(target=flapper, daemon=True)
    th.start()
    report: dict = {}
    t0 = time.monotonic()
    rc = _wire_driver(out, rounds, host=host, transport=chaos,
                      heartbeat_dir=hb, round_deadline=3.0, report=report)
    th.join(timeout=15.0)
    match, bad = False, None
    if rc == 0:
        match, bad = _params_match(baseline, out)
    row = {"episode": name, "rc": rc, "cause": report.get("cause"),
           "transport": report.get("transport"),
           "suspects": report.get("suspect_hosts"),
           "confirmed_down": report.get("confirmed_down"),
           "stragglers": report.get("stragglers"), "flap": flap,
           "match": match, "elapsed_s": round(time.monotonic() - t0, 1)}
    if bad:
        row["diverged_at"] = bad
    if slow_ms:
        row["ok"] = bool(rc == 0 and match
                         and report.get("cause") == "clean"
                         and not report.get("suspect_hosts")
                         and not report.get("stragglers")
                         and flap.get("restored"))
    else:
        row["ok"] = bool(rc == 0 and match
                         and report.get("cause") == "clean"
                         and report.get("suspect_hosts") == [host]
                         and not report.get("confirmed_down")
                         and not report.get("stragglers")
                         and flap.get("healed"))
    return row


def _net_fenced_ship_episode(workdir, baseline, rounds) -> dict:
    """Fenced, resumable checkpoint shipping end-to-end: incarnation 1
    (fence token 100001) trains the first half of the rounds
    checkpointing on hosta; its requeue lands on checkpoint-less hostb,
    which pulls the newest valid round over a link that TEARS the first
    transfer — the retry must resume the torn whole-chunk prefix and
    land crc-verified.  Incarnation 2 (token 200002) resumes from the
    shipped artifacts and must finish bit-identical to the
    uninterrupted baseline.  Then the fenced-off incarnation returns
    from behind the partition and tries to reclaim the dir: typed
    refusal at the fence, zero state touched."""
    import glob

    from sparknet_tpu.parallel.transport import (
        ChaosTransport, SshTransport, newest_valid_round,
        ship_latest_checkpoint,
    )
    from sparknet_tpu.utils.checkpoint import (
        CheckpointFencedError, advance_fence, read_fence,
    )

    epdir = os.path.join(workdir, "fenced_zombie_ship")
    os.makedirs(epdir, exist_ok=True)
    ck_a = os.path.join(epdir, "ckpt_host_hosta")
    ck_b = os.path.join(epdir, "ckpt_host_hostb")
    out = os.path.join(epdir, "out.npz")
    t0 = time.monotonic()
    row: dict = {"episode": "fenced_zombie_ship"}

    rc1 = _wire_driver(os.path.join(epdir, "half.npz"), rounds // 2,
                       host="hosta", ckpt=ck_a,
                       extra_env={"SPARKNET_FENCE_TOKEN": "100001"})
    row["rc_first"] = rc1

    chaos = ChaosTransport(SshTransport(), injector=_TornOnceInjector())
    try:
        rec = ship_latest_checkpoint(chaos, "hostb", ck_a, ck_b)
    except (OSError, RuntimeError, ValueError) as e:  # ShipError is OSError
        rec = None
        row["ship_error"] = f"{type(e).__name__}: {e}"
    row["ship"] = rec

    rc2 = _wire_driver(out, rounds, host="hostb", ckpt=ck_b,
                       extra_env={"SPARKNET_FENCE_TOKEN": "200002"})
    row["rc_resume"] = rc2
    match, bad = False, None
    if rc2 == 0:
        match, bad = _params_match(baseline, out)
    if bad:
        row["diverged_at"] = bad

    zombie: dict = {"refused": False}
    try:
        advance_fence(ck_b, 100002)
    except CheckpointFencedError as e:
        zombie = {"refused": True, "error": type(e).__name__,
                  "token": e.token, "fence": e.fence}
    torn_left = glob.glob(os.path.join(ck_b, "*.tmp*"))
    row.update(
        zombie=zombie, fence=read_fence(ck_b),
        newest_round=newest_valid_round(ck_b), match=match,
        elapsed_s=round(time.monotonic() - t0, 1),
        ok=bool(rc1 == 0 and rc2 == 0 and match and rec
                and rec.get("round") == rounds // 2
                and rec.get("resumed_bytes", 0) > 0
                and zombie.get("refused")
                and zombie.get("fence") == read_fence(ck_b)
                and not torn_left))
    if torn_left:
        row["torn_leftovers"] = torn_left
    return row


def _net_episodes(workdir, baseline, rounds, *, net_slice: bool) -> list:
    """The net chaos leg set (shared by --net and full --pod runs)."""
    episodes = [
        _net_partition_episode(workdir, baseline, rounds),
        _net_fenced_ship_episode(workdir, baseline, rounds),
    ]
    if not net_slice:
        episodes.append(_net_partition_episode(workdir, baseline, rounds,
                                               slow_ms=250.0))
    for e in episodes:
        print(f"net-soak: {e['episode']} -> "
              f"{'OK' if e['ok'] else 'FAIL'} ({e['elapsed_s']}s)",
              flush=True)
    return episodes


def net_soak(args) -> int:
    from sparknet_tpu.parallel.health import lease_window_s

    _clean_env()
    own_tmp = args.workdir is None
    workdir = args.workdir or tempfile.mkdtemp(prefix="sparknet_net_")
    os.makedirs(workdir, exist_ok=True)
    _net_knobs(workdir)
    t0 = time.monotonic()
    rounds = 8
    base = os.path.join(workdir, "base.npz")
    rc, _ = _run_driver(base, None, [], rounds=rounds)
    if rc != 0:
        raise RuntimeError(f"fault-free baseline failed rc={rc}")
    episodes = _net_episodes(workdir, base, rounds,
                             net_slice=args.net_slice)
    passed = sum(1 for e in episodes if e["ok"])
    report = {"mode": "net", "seed": args.seed,
              "slice": bool(args.net_slice), "rounds": rounds,
              "lease_window_s": lease_window_s(), "episodes": episodes,
              "passed": passed, "failed": len(episodes) - passed,
              "elapsed_s": round(time.monotonic() - t0, 1),
              "ok": bool(episodes) and passed == len(episodes)}
    text = json.dumps(report, indent=1)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
        print(f"net-soak: verdict written to {args.out} "
              f"({passed}/{len(episodes)} episode(s) passed)")
    else:
        print(text)
    if own_tmp and report["ok"]:
        import shutil
        shutil.rmtree(workdir, ignore_errors=True)
    elif not report["ok"]:
        print(f"net-soak: scratch kept at {workdir} for post-mortem",
              file=sys.stderr)
    return 0 if report["ok"] else 1


# ---------------------------------------------------------------------------
# Fleet chaos soak (--fleet N): concurrent jobs, one scheduler, injected
# crash/straggle/preempt/nan schedules + fleet-level priority preemption
# (+ optional scheduler kill/resume), verified bit-identical and orphan-free
# ---------------------------------------------------------------------------

def _fleet_schedules(rng, i):
    """Seeded fault schedule for fleet job ``i``.  The first FOUR jobs
    are pinned to the crash / preempt / nan / straggle families in that
    order, so the 2-job CI smoke (SPARKNET_FLEETSOAK=1) always covers
    the preempt/resume/crash triangle and any >= 4-job acceptance run
    covers all four; later jobs draw seeded from the full menu (the
    round numbers stay seeded for every job)."""
    r = int(rng.integers(1, 3))
    menu = [
        ("crash", f"crash@round:{r}", False),
        ("preempt", f"preempt@round:{r}", False),
        ("nan_inject", f"nan_inject@round:{r}", True),
        ("straggle+crash",
         f"straggle:0.5s@round:{r},crash@round:{r}@attempt:0", False),
        ("crash_in_ckpt", f"crash_in_ckpt@round:{r}", False),
        ("corrupt_ckpt", f"corrupt_ckpt@round:{r}", False),
    ]
    if i < 4:
        return menu[i]
    return menu[int(rng.integers(0, len(menu)))]


def _journal_pids(workdir):
    """Every worker pid the fleet journal ever recorded."""
    from sparknet_tpu.parallel.fleet import FleetJournal
    pids = {}
    path = os.path.join(workdir, "fleet_journal.jsonl")
    for ev in FleetJournal.read(path):
        if ev.get("ev") == "pids":
            pids.setdefault(ev["job"], set()).update(ev.get("pids", []))
    return pids


def fleet_soak(args) -> int:
    import numpy as np

    from sparknet_tpu.parallel.fleet import (
        FleetScheduler, JobSpec, _pid_is_fleet_job, format_status,
    )

    _clean_env()
    rng = np.random.default_rng(args.seed)
    own_tmp = args.workdir is None
    workdir = args.workdir or tempfile.mkdtemp(prefix="sparknet_fleet_")
    os.makedirs(workdir, exist_ok=True)
    fleet_dir = os.path.join(workdir, "fleet")
    devices = args.fleet_devices
    t0 = time.monotonic()

    # -- job set: N faulted jobs + the late high-priority preemptor ------
    specs, meta = [], {}
    for i in range(args.fleet):
        name, fault, guard = _fleet_schedules(rng, i)
        spec = JobSpec(
            name=f"job{i}", tenant=("acme", "beta")[i % 2],
            priority=i % 2, world=4, rounds=4, guard=guard, fault=fault,
            max_restarts=2, timeout_s=300.0)
        specs.append(spec)
        meta[spec.name] = {"schedule": name, "fault": fault}
    preemptor = JobSpec(
        name="preemptor", tenant="ops", priority=99, world=devices,
        rounds=3, not_before_s=args.fleet_preempt_after,
        preemptible=False, timeout_s=300.0)
    specs.append(preemptor)
    meta[preemptor.name] = {"schedule": "clean-high-priority", "fault": None}

    # -- fault-free baselines, one per distinct job shape ----------------
    baselines: dict[tuple, str] = {}

    def baseline_for(spec):
        key = (spec.world, spec.rounds, spec.guard)
        if key not in baselines:
            path = os.path.join(workdir, f"base_{len(baselines)}.npz")
            ck = os.path.join(workdir, f"base_ck_{len(baselines)}")
            flags = ["--guard"] if spec.guard else []
            rc, _ = _run_driver(path, ck if flags else None, flags,
                                local_devices=spec.world,
                                rounds=spec.rounds)
            if rc != 0:
                raise RuntimeError(f"fault-free baseline failed rc={rc} "
                                   f"(shape={key})")
            baselines[key] = path
        return baselines[key]

    for spec in specs:
        baseline_for(spec)

    # -- run the fleet (optionally killing the scheduler mid-run) --------
    killed = False
    if args.fleet_kill:
        jobs_json = os.path.join(workdir, "jobs.json")
        with open(jobs_json, "w") as f:
            json.dump([s.to_json() for s in specs], f)
        import signal
        import subprocess
        proc = subprocess.Popen(
            [sys.executable, os.path.join(REPO, "tools", "fleet.py"),
             "--workdir", fleet_dir, "--devices", str(devices),
             "--jobs", jobs_json, "--status-every", "0"],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
        time.sleep(args.fleet_kill_after)
        proc.send_signal(signal.SIGKILL)   # no grace: the worst case
        proc.wait()
        killed = True
        print(f"fleet-soak: scheduler SIGKILLed after "
              f"{args.fleet_kill_after}s; resuming from the journal",
              flush=True)
        fleet = FleetScheduler.resume(fleet_dir)
    else:
        fleet = FleetScheduler(fleet_dir, devices,
                               tenants={"acme": devices, "beta": devices})
        for spec in specs:
            fleet.submit(spec)
    rc = fleet.run(tick_s=0.1, timeout_s=args.fleet_timeout)

    # -- verdict ---------------------------------------------------------
    jobs = []
    for spec in specs:
        job = fleet.jobs[spec.name]
        verdict = dict(meta[spec.name], job=spec.name, state=job.state,
                       episodes=job.episodes, attempts=job.restarts_used,
                       preempts=job.preempt_count)
        if job.state == "COMPLETED":
            match, bad = _params_match(baseline_for(spec), job.out_path)
            verdict.update(match=match,
                           **({"diverged_at": bad} if not match else {}))
        else:
            verdict.update(match=False)
        verdict["ok"] = job.state == "COMPLETED" and verdict["match"]
        jobs.append(verdict)

    # zero-orphans: every pid the journal ever recorded must be dead (or
    # provably not ours anymore)
    orphans = {name: sorted(p for p in pids
                            if _pid_is_fleet_job(p, name))
               for name, pids in _journal_pids(fleet_dir).items()}
    orphans = {k: v for k, v in orphans.items() if v}
    preempt_seen = any(j["preempts"] > 0 for j in jobs)

    passed = sum(1 for j in jobs if j["ok"])
    report = {"mode": "fleet", "seed": args.seed, "devices": devices,
              "killed_scheduler": killed, "jobs": jobs,
              "passed": passed, "failed": len(jobs) - passed,
              "orphans": orphans, "preemption_exercised": preempt_seen,
              "elapsed_s": round(time.monotonic() - t0, 1),
              "ok": (rc == 0 and passed == len(jobs) and not orphans
                     and preempt_seen)}
    print(format_status(fleet.status()), flush=True)
    text = json.dumps(report, indent=1)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
        print(f"fleet-soak: verdict written to {args.out} "
              f"({passed}/{len(jobs)} passed"
              f"{', orphans!' if orphans else ''})")
    else:
        print(text)
    if own_tmp and report["ok"]:
        import shutil
        shutil.rmtree(workdir, ignore_errors=True)
    elif not report["ok"]:
        print(f"fleet-soak: scratch kept at {workdir} for post-mortem",
              file=sys.stderr)
    return 0 if report["ok"] else 1


# ---------------------------------------------------------------------------
# Pod burn-in (--pod N): simulated multi-host fleet under production-shaped
# traffic — diurnal paced load, a flash crowd, corrupt-upload bursts through
# the quarantine plane, host-kill / host-drain chaos — every recovery
# bit-identical, every leg error-free, zero orphans
# ---------------------------------------------------------------------------

class TrafficModel:
    """Seeded synthesized production traffic for the pod burn-in.

    One instance is one "day": ``next_qps()`` walks a diurnal sine curve
    (seeded phase, so two runs with the same ``--seed`` replay the same
    day), ``flash_qps()`` is the flash-crowd step over the base, and
    ``corrupt_burst(budget)`` sizes the corrupt-upload bursts the
    quarantine plane must absorb (one within budget) and reject (one
    past it).  All magnitudes come from the SPARKNET_SOAK_* knobs unless
    the CLI overrides them."""

    def __init__(self, rng, *, base_qps=None, flash_x=None, leg_s=None,
                 day_legs: int = 12):
        from sparknet_tpu.utils import knobs
        self.rng = rng
        self.base_qps = (base_qps if base_qps is not None
                         else knobs.get_float("SPARKNET_SOAK_QPS", 4.0))
        self.flash_x = (flash_x if flash_x is not None
                        else knobs.get_float("SPARKNET_SOAK_FLASH_X", 2.5))
        self.leg_s = (leg_s if leg_s is not None
                      else knobs.get_float("SPARKNET_SOAK_LEG_S", 4.0))
        self.day_legs = day_legs
        self.phase = float(rng.uniform(0.0, 1.0))
        self.step = 0

    def next_qps(self) -> float:
        import math
        f = self.step / self.day_legs + self.phase
        self.step += 1
        qps = self.base_qps * (0.7 + 0.3 * math.sin(2 * math.pi * f))
        return round(max(qps, 0.5), 3)

    def flash_qps(self) -> float:
        return round(max(self.base_qps * self.flash_x, 1.0), 3)

    def corrupt_burst(self, budget: int) -> tuple[int, int]:
        """(records in the within-budget burst, records attempted in the
        past-budget flood)."""
        within = int(self.rng.integers(2, max(budget, 3)))
        return min(within, budget), budget + 2


def _corrupt_upload_burst(tm: "TrafficModel") -> dict:
    """One corrupt-upload episode through the data quarantine plane: a
    within-budget burst must be absorbed as typed skip accounting
    (attributed per source), and the first record past the budget must
    raise QuarantineExceeded carrying the report — silent swallowing or
    an untyped crash are both red."""
    from sparknet_tpu.data.integrity import (
        DataCorruptionError, Quarantine, QuarantineExceeded,
        QuarantinePolicy,
    )
    epoch = 200
    q = Quarantine(QuarantinePolicy(max_fraction=0.05), epoch_size=epoch,
                   source="pod-upload")
    within, flood = tm.corrupt_burst(q.budget)
    for i in range(within):
        q.admit(DataCorruptionError(
            "synthetic upload corruption", source="pod-upload",
            key=f"upload/{i}", offset=int(tm.rng.integers(0, 1 << 20))))
    absorbed = q.report()
    typed_report = None
    try:
        for i in range(flood):
            q.admit(DataCorruptionError(
                "synthetic upload corruption", source="pod-upload-flood",
                key=f"flood/{i}"))
    except QuarantineExceeded as e:
        typed_report = e.report
    return {"budget": q.budget, "absorbed": within,
            "typed_overflow": typed_report is not None,
            "by_source": absorbed["by_source"],
            "ok": bool(typed_report is not None
                       and absorbed["epoch_bad"] == within)}


def _wait_for(cond, timeout_s: float, tick_s: float = 0.15) -> bool:
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(tick_s)
    return bool(cond())


def _pod_episode(args, rng, workdir, baseline, ep: int,
                 rounds: int) -> dict:
    """One burn-in episode on a fresh simulated pod: schedule the mixed
    tenants, replay the traffic model (serveload's paced closed loops),
    fire the chaos events mid-leg through the host-control channel, and
    return the verdict row.  ``--pod-slice`` keeps the CI shape (one
    host-kill + one flash crowd); the full episode adds a host drain
    mid-training and a serving-host loss."""
    import numpy as np

    from sparknet_tpu.parallel.autoscale import (
        Autoscaler, AutoscaleConfig, fleet_stats_fn,
    )
    from sparknet_tpu.parallel.fleet import (
        COMPLETED, TERMINAL, FleetScheduler, HostPool, JobSpec,
        _pid_is_fleet_job, format_status, request_mark_host,
    )
    from sparknet_tpu.parallel.router import RouterConfig, ServingFleet
    from sparknet_tpu.parallel.serving import (
        ModelHouse, ServeConfig, solo_references,
    )
    from sparknet_tpu.utils.telemetry import get_recorder

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import serveload

    t0 = time.monotonic()
    full = not args.pod_slice
    model, replicas, world = "lenet", 2, 3
    tm = TrafficModel(rng, base_qps=args.pod_qps,
                      flash_x=args.pod_flash_x, leg_s=args.pod_leg_s)
    rec = get_recorder()
    fleet_dir = os.path.join(workdir, f"ep{ep}")
    pool = HostPool.parse(",".join(f"h{i}={args.pod_devices}"
                                   for i in range(args.pod)))

    cfg = ServeConfig(batch_shapes=(1, 4, 8), seed=0)
    serve_env = {
        "SPARKNET_SERVE_SHAPES": ",".join(str(s)
                                          for s in cfg.batch_shapes),
        "SPARKNET_SERVE_MAX_DELAY_MS": str(cfg.max_delay_ms),
        "SPARKNET_SERVE_QUEUE": str(cfg.max_queue),
        "SPARKNET_SERVE_DTYPE": cfg.dtype,
    }
    sched = FleetScheduler(fleet_dir, None, hosts=pool,
                           preempt_grace_s=15.0)
    fleet = ServingFleet(fleet_dir, pool.total_devices, scheduler=sched,
                         serve_env=serve_env,
                         router_cfg=RouterConfig(spill_depth=8),
                         replica_timeout_s=20.0)
    scaler = Autoscaler(
        fleet_stats_fn(fleet), fleet.scale_up, fleet.scale_down,
        cfg=AutoscaleConfig(min_replicas=replicas,
                            max_replicas=replicas + 1, up_queue=64.0,
                            cooldown_s=2.0, down_idle_s=3600.0,
                            sample_every_s=0.25),
        state_path=os.path.join(fleet_dir, "autoscale.json"))

    trains = [JobSpec(name=f"train{i}", tenant=("acme", "beta")[i],
                      world=world, rounds=rounds, global_batch=4 * world,
                      max_restarts=3, timeout_s=300.0)
              for i in range(2)]
    if not full:
        # slice: trainings warm up alongside the replicas so the single
        # host-kill lands mid-round within the ~60s budget
        for spec in trains:
            sched.submit(spec)

    report: dict = {"episode": ep, "hosts": pool.to_json(),
                    "base_qps": tm.base_qps, "leg_s": tm.leg_s,
                    "slice": not full}
    legs: list[dict] = []
    chaos: dict = {}

    def mark(host, state):
        rec.record("pod_soak_chaos", host=host, state=state, episode=ep)
        request_mark_host(fleet_dir, host, state, by=f"pod-soak-ep{ep}")
        return {"host": host, "state": state}

    def serve_hosts():
        return {h for j in sched.jobs.values()
                if j.spec.kind == "serve" and j.state not in TERMINAL
                for h in j.hosts}

    def leg(name, qps, midpoint=None, clients=4):
        rep, mid = serveload._paced_with_midpoint(
            fleet.router, model, inputs, refs, clients=clients, window=1,
            seconds=tm.leg_s, qps=qps, midpoint=midpoint or (lambda: None),
            tenant="podsoak")
        row = {"leg": name, "offered_qps": qps,
               "achieved_qps": rep.get("achieved_qps"),
               "errors": rep.get("errors"),
               "mismatches": rep.get("exact_mismatches"),
               "rejected": rep.get("rejected"),
               "p99_ms": rep.get("p99_ms")}
        if mid.get("error"):
            row["chaos_error"] = mid["error"]
        elif mid.get("value") is not None:
            row["chaos"] = mid["value"]
        legs.append(row)
        print(f"pod-soak: ep{ep} leg {name}: offered {qps} qps -> "
              f"{row['achieved_qps']} qps, errors {row['errors']}, "
              f"mismatches {row['mismatches']}"
              + (f", chaos {row.get('chaos')}" if midpoint else ""),
              flush=True)
        return row

    healed = drained = True
    try:
        # in-process references: replicas share config + seed, so the
        # pod must answer bit-identically to this solo house
        lm = ModelHouse(cfg).load(model)
        inputs = [rng.normal(size=lm.in_shape).astype(np.float32)
                  for _ in range(12)]
        refs = solo_references(lm, inputs)

        fleet.ensure(model, replicas)
        fleet.attach_autoscaler(scaler)
        fleet.run_background()
        fleet.wait_ready(model, replicas, timeout_s=240.0)
        if full:
            # full episode: the trainings start only now, so the drain
            # leg below still catches a gang mid-round
            for spec in trains:
                sched.submit(spec)
        if not _wait_for(lambda: all(sched.jobs[s.name].hosts
                                     for s in trains), 60.0):
            raise RuntimeError("training gangs never placed: "
                               + format_status(sched.status()))

        # -- chaos 1: kill a training host mid-leg ---------------------
        sh = serve_hosts()
        kill_victim = next(
            (h for s in trains for h in sched.jobs[s.name].hosts
             if h not in sh),
            sched.jobs[trains[0].name].hosts[0])
        chaos["host_kill"] = kill_victim
        leg("diurnal_kill", tm.next_qps(),
            midpoint=lambda: mark(kill_victim, "lost"))

        # -- corrupt-upload burst through the quarantine plane ---------
        report["quarantine"] = _corrupt_upload_burst(tm)

        # -- flash crowd; the lost host recovers mid-crowd -------------
        leg("flash_crowd", tm.flash_qps(), clients=6,
            midpoint=lambda: mark(kill_victim, "live"))

        if full:
            # -- chaos 2: drain a host carrying a live training gang ---
            sh = serve_hosts()
            cands = [h for s in trains
                     if sched.jobs[s.name].state not in TERMINAL
                     for h in sched.jobs[s.name].hosts]
            cands = [h for h in cands if h not in sh or len(sh) > 1]
            if cands:
                drain_victim = cands[0]
                chaos["host_drain"] = drain_victim
                leg("diurnal_drain", tm.next_qps(),
                    midpoint=lambda: mark(drain_victim, "draining"))
                drained = _wait_for(
                    lambda: not sched.jobs_on_host(drain_victim), 120.0)
                mark(drain_victim, "live")
            else:
                # the full acceptance must exercise the drain path; a
                # missed window (trainings already done) is red
                chaos["host_drain"] = None
                drained = False

        # -- trainings must finish (kills/drains notwithstanding) ------
        if not _wait_for(lambda: all(sched.jobs[s.name].state in TERMINAL
                                     for s in trains), args.pod_timeout):
            raise RuntimeError("trainings not terminal within "
                               f"{args.pod_timeout}s: "
                               + format_status(sched.status()))

        if full:
            # -- chaos 3: serving host loss = bulk replica death -------
            sh = sorted(serve_hosts())
            if len(sh) >= 2:
                victim2 = sh[0]
                chaos["serve_host_loss"] = victim2
                leg("diurnal_serve_loss", tm.next_qps(),
                    midpoint=lambda: mark(victim2, "lost"))
                try:
                    fleet.wait_ready(model, replicas, timeout_s=180.0)
                except TimeoutError:
                    healed = False
                mark(victim2, "live")
            else:
                chaos["serve_host_loss"] = None
                healed = False   # replicas were never spread: red

        # -- final heal check ------------------------------------------
        try:
            fleet.wait_ready(model, replicas, timeout_s=120.0)
        except TimeoutError:
            healed = False
    finally:
        fleet.stop(grace_s=5.0)

    # -- verdict ---------------------------------------------------------
    tverd = []
    for s in trains:
        job = sched.jobs[s.name]
        v = {"job": s.name, "state": job.state, "episodes": job.episodes,
             "preempts": job.preempt_count}
        if job.state == COMPLETED:
            m, bad = _params_match(baseline, job.out_path)
            v.update(match=m, **({"diverged_at": bad} if not m else {}))
        else:
            v["match"] = False
        v["ok"] = job.state == COMPLETED and v["match"]
        tverd.append(v)

    orphans = {name: sorted(p for p in pids
                            if _pid_is_fleet_job(p, name))
               for name, pids in _journal_pids(fleet_dir).items()}
    orphans = {k: v for k, v in orphans.items() if v}
    slo_ok = all(l["errors"] == 0 and l["mismatches"] == 0 for l in legs)
    perf_ok = all((l["achieved_qps"] or 0) > 0 for l in legs)
    chaos_errs = [l["chaos_error"] for l in legs if "chaos_error" in l]

    report.update(
        chaos=chaos, legs=legs, trainings=tverd, healed=healed,
        drained=drained, slo_ok=slo_ok, perf_band_ok=perf_ok,
        orphans=orphans, elapsed_s=round(time.monotonic() - t0, 1),
        ok=(all(v["ok"] for v in tverd) and slo_ok and perf_ok
            and healed and drained and not orphans and not chaos_errs
            and report.get("quarantine", {}).get("ok", False)))
    if chaos_errs:
        report["chaos_errors"] = chaos_errs

    if not report["ok"]:
        # artifact-producing failure: black box + postmortem in the
        # episode dir (which pod_soak then keeps)
        rec.dump(f"pod-soak-ep{ep}", directory=fleet_dir)
        try:
            with open(os.path.join(fleet_dir, "postmortem.json"),
                      "w") as f:
                json.dump({"report": report,
                           "status": sched.status()}, f, indent=1,
                          default=str)
        except OSError:
            pass
    return report


def pod_soak(args) -> int:
    import numpy as np

    _clean_env()
    rng = np.random.default_rng(args.seed)
    own_tmp = args.workdir is None
    workdir = args.workdir or tempfile.mkdtemp(prefix="sparknet_pod_")
    os.makedirs(workdir, exist_ok=True)
    # the pod's host lifecycle rides the REAL ssh wire format: with
    # SPARKNET_SSH_CMD set, every placement/exec/ship goes through
    # SshTransport (the fake-ssh shim by default; a live inventory
    # supplies its own wrapper and keeps it through _KEEP_ENV)
    _net_knobs(workdir)
    t0 = time.monotonic()

    # one fault-free baseline for the training shape all tenants share
    # (world=3 gangs; batch 12 keeps the shard math exact; the full
    # episode trains longer so the drain leg catches a gang mid-round)
    rounds = 4 if args.pod_slice else 12
    base = os.path.join(workdir, "base.npz")
    rc, _ = _run_driver(base, None, ["--global-batch", "12"],
                        local_devices=3, rounds=rounds)
    if rc != 0:
        raise RuntimeError(f"fault-free baseline failed rc={rc}")

    episodes = []
    ok = True
    try:
        ep = 0
        while True:
            episodes.append(_pod_episode(args, rng, workdir, base, ep,
                                         rounds))
            ok = episodes[-1]["ok"]
            print(f"pod-soak: episode {ep} -> "
                  f"{'OK' if ok else 'FAIL'} "
                  f"({episodes[-1]['elapsed_s']}s)", flush=True)
            ep += 1
            if not ok or not args.forever:
                break
    except KeyboardInterrupt:
        print("pod-soak: interrupted — closing out the verdict",
              file=sys.stderr, flush=True)

    if not args.pod_slice and ok and not args.forever:
        # the full burn-in grows the network chaos legs (partition
        # suspend/heal, fenced zombie shipping, slow-link attribution)
        # on its own fault-free baseline shape
        net_base = os.path.join(workdir, "net_base.npz")
        rc, _ = _run_driver(net_base, None, [], rounds=8)
        if rc != 0:
            raise RuntimeError(f"net-leg baseline failed rc={rc}")
        episodes.extend(_net_episodes(os.path.join(workdir, "net"),
                                      net_base, 8, net_slice=False))

    passed = sum(1 for e in episodes if e["ok"])
    report = {"mode": "pod", "seed": args.seed, "pod_hosts": args.pod,
              "devices_per_host": args.pod_devices,
              "transport": "ssh",
              "slice": bool(args.pod_slice), "episodes": episodes,
              "passed": passed, "failed": len(episodes) - passed,
              "elapsed_s": round(time.monotonic() - t0, 1),
              "ok": bool(episodes) and passed == len(episodes)}
    text = json.dumps(report, indent=1)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
        print(f"pod-soak: verdict written to {args.out} "
              f"({passed}/{len(episodes)} episode(s) passed)")
    else:
        print(text)
    if own_tmp and report["ok"]:
        import shutil
        shutil.rmtree(workdir, ignore_errors=True)
    elif not report["ok"]:
        print(f"pod-soak: scratch kept at {workdir} for post-mortem "
              "(postmortem.json + flight dump in the failing episode "
              "dir)", file=sys.stderr)
    return 0 if report["ok"] else 1


# ---------------------------------------------------------------------------
# Rollout chaos legs (--rollout): the deployment plane end to end — a
# healthy canary must promote, a poisoned canary must auto-roll back
# with zero client-visible damage on stable traffic, and a controller
# killed mid-rollout must resume to a consistent fleet.


class _RolloutFleet:
    """In-process serving tier keyed by VERSIONED name: the rollout
    controller's ensure/retire/verdict wiring.  Every versioned name
    gets its own house + engine (whose built-in per-version SLOMonitor
    is the judge's verdict source) behind one real Router — the same
    shape ``tools/serve.py --fleet`` runs, minus the HTTP hop."""

    def __init__(self, registry, cfg, router):
        self.registry = registry
        self.cfg = cfg
        self.router = router
        self.live: dict = {}      # versioned name -> (rid, engine)

    def ensure(self, name: str) -> None:
        if name in self.live:
            return
        from sparknet_tpu.parallel.router import InProcessReplica
        from sparknet_tpu.parallel.serving import InferenceEngine, ModelHouse
        model, _, version = name.partition("@")
        house = ModelHouse(self.cfg)
        house.load_version(model, version, registry=self.registry)
        eng = InferenceEngine(house, self.cfg)
        rid = f"r-{version}"
        self.router.add_replica(rid, InProcessReplica(rid, eng))
        self.live[name] = (rid, eng)

    def retire(self, name: str) -> None:
        ent = self.live.pop(name, None)
        if ent is None:
            return
        rid, eng = ent
        self.router.drain(rid, timeout_s=30.0)
        eng.stop()

    def verdict(self, name: str):
        ent = self.live.get(name)
        if ent is None:
            return None
        return ent[1].slo.evaluate()

    def close(self) -> None:
        for name in list(self.live):
            self.retire(name)


def _rollout_promote_episode(ctl, fleet, reg, router, inputs, refs,
                             v1, v2) -> dict:
    """A HEALTHY canary must earn promotion: sustained green verdicts
    over the request floor, old stable drained, pinned-canary answers
    bit-identical across the pointer flip."""
    import numpy as np
    from sparknet_tpu.parallel.registry import versioned
    from sparknet_tpu.parallel.serving import ServingError

    t0 = time.monotonic()
    reg.set_channels("lenet", stable=v1)
    fleet.ensure(versioned("lenet", v1))
    ctl.start_canary("lenet", v2, weight=0.5)
    pins = inputs[:4]
    pre = [router.classify("lenet", x, version=v2, timeout=60).probs
           for x in pins]

    errors = mism = iters = 0
    decision = "canary"
    deadline = time.monotonic() + 120.0
    while decision == "canary" and time.monotonic() < deadline:
        for i, x in enumerate(inputs):
            try:
                res = router.classify("lenet", x, tenant="rollsoak",
                                      timeout=60)
            except ServingError:
                errors += 1      # untyped errors crash the episode: bug
            else:
                if not np.array_equal(res.probs, refs[res.padded_to][i]):
                    mism += 1
        iters += 1
        decision = ctl.judge("lenet")
        time.sleep(0.05)

    promoted = decision == "promote"
    if promoted:
        ctl.promote("lenet")
    post = [router.classify("lenet", x, version=v2, timeout=60).probs
            for x in pins]
    ch = reg.channels("lenet")
    pin_ok = all(np.array_equal(a, b) for a, b in zip(pre, post))
    old_gone = (versioned("lenet", v1) not in fleet.live
                and f"r-{v1}" not in router.replica_ids())
    return {"episode": "canary_promote", "stable": v1, "canary": v2,
            "promoted": promoted, "iters": iters,
            "stable_errors": errors, "mismatches": mism,
            "pin_identical": pin_ok, "old_stable_drained": old_gone,
            "channels": ch,
            "elapsed_s": round(time.monotonic() - t0, 1),
            "ok": bool(promoted and errors == 0 and mism == 0
                       and pin_ok and old_gone
                       and ch["stable"] == v2 and ch["canary"] is None)}


def _rollout_bad_canary_episode(ctl, fleet, reg, router, inputs, refs,
                                stable, trace_dir) -> dict:
    """A POISONED canary (planted ``bad_canary`` fault: the model head
    emits NaNs) must be caught by the judge and auto-rolled back: zero
    errors on stable-pinned traffic, zero non-finite rows ever served,
    channel reverted, canary drained, flight dump on disk, and the
    journal resuming as consistent with pinned answers bit-identical
    across the recovery."""
    import glob

    import numpy as np

    from sparknet_tpu.parallel.registry import versioned
    from sparknet_tpu.parallel.rollout import RolloutController
    from sparknet_tpu.parallel.serving import ServingError

    t0 = time.monotonic()
    v3 = reg.publish("lenet", notes="rollout soak v3 (to be poisoned)")
    pins = inputs[:4]
    pre = [router.classify("lenet", x, version=stable, timeout=60).probs
           for x in pins]
    dumps_before = len(glob.glob(os.path.join(
        trace_dir, "*rollout_rollback*")))

    # the canary is born bad: every batch of the poisoned version
    # produces NaNs (the engine must fail them TYPED, never serve them)
    os.environ["SPARKNET_FAULT"] = f"bad_canary:{v3}"
    stable_errors = typed = untyped = served_bad = mism = 0
    try:
        ctl.start_canary("lenet", v3, weight=0.5)
        t_live = time.monotonic()
        decision = "canary"
        deadline = time.monotonic() + 120.0
        while decision == "canary" and time.monotonic() < deadline:
            for i, x in enumerate(inputs):
                try:
                    res = router.classify("lenet", x, tenant="rollsoak",
                                          timeout=60)
                except ServingError:
                    typed += 1       # the canary failing loudly is fine
                # measuring untyped leakage IS this episode's job: the
                # soak asserts this counter stays zero
                except Exception:  # sparklint: disable=CD003
                    untyped += 1     # anything untyped is not
                else:
                    if not np.isfinite(res.probs).all():
                        served_bad += 1   # NaN reached a client: red
                    elif not np.array_equal(res.probs,
                                            refs[res.padded_to][i]):
                        mism += 1
            # stable-PINNED traffic must never feel the canary at all
            try:
                router.classify("lenet", inputs[0], version=stable,
                                timeout=60)
            except ServingError:
                stable_errors += 1   # untyped here crashes the episode
            decision = ctl.judge("lenet")
            time.sleep(0.05)
        rolled_back = decision == "rollback"
        detect_s = round(time.monotonic() - t_live, 2)
        if rolled_back:
            ctl.rollback("lenet", reason="sustained SLO breach "
                                         "(bad canary)")
    finally:
        os.environ.pop("SPARKNET_FAULT", None)

    ch = reg.channels("lenet")
    ro = router.rollout("lenet")
    drained = (versioned("lenet", v3) not in fleet.live
               and f"r-{v3}" not in router.replica_ids())
    dumped = len(glob.glob(os.path.join(
        trace_dir, "*rollout_rollback*"))) > dumps_before
    post = [router.classify("lenet", x, version=stable, timeout=60).probs
            for x in pins]
    pin_ok = all(np.array_equal(a, b) for a, b in zip(pre, post))
    # a fresh controller over the same journal must find nothing to fix
    resumed = RolloutController(
        reg, ctl.workdir, ensure=fleet.ensure, retire=fleet.retire,
        verdict=fleet.verdict, router=router, cfg=ctl.cfg).resume()
    post2 = [router.classify("lenet", x, version=stable,
                             timeout=60).probs for x in pins]
    pin_ok = pin_ok and all(np.array_equal(a, b)
                            for a, b in zip(pre, post2))
    return {"episode": "bad_canary_rollback", "stable": stable,
            "canary": v3, "rolled_back": rolled_back,
            "detect_s": detect_s, "stable_errors": stable_errors,
            "canary_typed_failures": typed, "untyped_errors": untyped,
            "served_bad": served_bad, "mismatches": mism,
            "drained": drained, "flight_dump": dumped,
            "pin_identical": pin_ok,
            "resume": resumed.get("lenet", "consistent"),
            "channels": ch,
            "elapsed_s": round(time.monotonic() - t0, 1),
            "ok": bool(rolled_back and stable_errors == 0 and typed > 0
                       and untyped == 0 and served_bad == 0
                       and mism == 0 and drained and dumped and pin_ok
                       and ch["stable"] == stable
                       and ch["canary"] is None and ch["weight"] == 0.0
                       and ro is not None and ro.canary is None
                       and resumed.get("lenet",
                                       "consistent") == "consistent")}


def _rollout_resume_episode(workdir) -> dict:
    """Kill the controller at BOTH dangerous points — after the canary
    went live (before any judgment) and between ``promote_begin`` and
    its ``done`` — and prove resume lands on exactly one of {fully
    stable, fully promoted}, idempotently, with no orphan replicas."""
    from sparknet_tpu.parallel.registry import ModelRegistry, versioned
    from sparknet_tpu.parallel.rollout import RolloutConfig, RolloutController

    t0 = time.monotonic()
    cfg = RolloutConfig(fraction=0.25, judge_s=0.5, poll_s=0.05,
                        min_requests=1, breach_polls=1)

    class _Killed(Exception):
        pass

    def rig(tag):
        d = os.path.join(workdir, tag)
        reg = ModelRegistry(os.path.join(d, "registry"))
        up: set = set()
        retired: list = []

        def retire(name):
            retired.append(name)
            up.discard(name)

        a = reg.publish("demo", notes="a")
        b = reg.publish("demo", notes="b")
        reg.set_channels("demo", stable=a)
        kw = dict(ensure=up.add, retire=retire,
                  verdict=lambda name: None, cfg=cfg)
        return d, reg, up, retired, a, b, kw

    # -- kill after canary_live: nobody is judging -> must roll back ---
    d, reg, up, retired, a, b, kw = rig("mid_canary")
    RolloutController(reg, d, **kw).start_canary("demo", b)
    res1 = RolloutController(reg, d, **kw).resume()
    ch = reg.channels("demo")
    mid_canary_ok = (res1 == {"demo": "rolled_back"}
                     and ch["stable"] == a and ch["canary"] is None
                     and versioned("demo", b) in retired
                     and up == {versioned("demo", a)})
    res1b = RolloutController(reg, d, **kw).resume()
    idem1 = res1b == {"demo": "consistent"}

    # -- kill between promote_begin and done: the decision is durable
    # -> resume must FINISH the promote, not un-decide it --------------
    class _DiesApplying(RolloutController):
        def _apply_promote(self, *args, **kwargs):
            raise _Killed()

    d, reg, up, retired, a, b, kw = rig("mid_promote")
    ctl = _DiesApplying(reg, d, **kw)
    ctl.start_canary("demo", b)
    try:
        ctl.promote("demo")
    except _Killed:
        pass
    res2 = RolloutController(reg, d, **kw).resume()
    ch = reg.channels("demo")
    mid_promote_ok = (res2 == {"demo": "promoted"}
                      and ch["stable"] == b and ch["canary"] is None
                      and versioned("demo", a) in retired
                      and up == {versioned("demo", b)})
    res2b = RolloutController(reg, d, **kw).resume()
    idem2 = res2b == {"demo": "consistent"}

    return {"episode": "controller_kill_resume",
            "mid_canary": res1.get("demo"),
            "mid_promote": res2.get("demo"),
            "idempotent": bool(idem1 and idem2),
            "elapsed_s": round(time.monotonic() - t0, 1),
            "ok": bool(mid_canary_ok and mid_promote_ok
                       and idem1 and idem2)}


def rollout_soak(args) -> int:
    import numpy as np

    from sparknet_tpu.parallel.registry import ModelRegistry, versioned
    from sparknet_tpu.parallel.rollout import RolloutConfig, RolloutController
    from sparknet_tpu.parallel.router import Router, RouterConfig
    from sparknet_tpu.parallel.serving import (
        ModelHouse, ServeConfig, solo_references,
    )

    _clean_env()
    rng = np.random.default_rng(args.seed)
    own_tmp = args.workdir is None
    workdir = args.workdir or tempfile.mkdtemp(prefix="sparknet_rollout_")
    os.makedirs(workdir, exist_ok=True)
    trace_dir = os.environ.setdefault(
        "SPARKNET_TRACE_DIR", os.path.join(workdir, "trace"))
    os.makedirs(trace_dir, exist_ok=True)
    regdir = os.path.join(workdir, "registry")
    os.environ["SPARKNET_REGISTRY_DIR"] = regdir
    t0 = time.monotonic()

    reg = ModelRegistry(regdir)
    # small fast SLO windows so a ~30 s leg sees real multi-window
    # burn-rate judgments, not just the defaults' opening blur
    cfg = ServeConfig(batch_shapes=(1, 4), seed=0,
                      slo_fast_window_s=1.5, slo_window_s=6.0,
                      slo_min_requests=4, slo_reject_budget=0.05,
                      slo_sample_every_s=0.1)
    router = Router(RouterConfig(spill_depth=8))
    fleet = _RolloutFleet(reg, cfg, router)
    ctl = RolloutController(
        reg, workdir, ensure=fleet.ensure, retire=fleet.retire,
        verdict=fleet.verdict, router=router,
        cfg=RolloutConfig(fraction=0.5, judge_s=1.5, poll_s=0.05,
                          min_requests=10, breach_polls=2))

    v1 = reg.publish("lenet", slo={"p99_ms": 2000.0},
                     notes="rollout soak v1")
    v2 = reg.publish("lenet", slo={"p99_ms": 2000.0},
                     notes="rollout soak v2")
    # zoo-init versions share seed 0, so one solo house is the
    # bit-identity oracle for BOTH sides of the split
    lm = ModelHouse(cfg).load("lenet")
    inputs = [rng.normal(size=lm.in_shape).astype(np.float32)
              for _ in range(16)]
    refs = solo_references(lm, inputs)

    episodes = []
    try:
        episodes.append(_rollout_promote_episode(
            ctl, fleet, reg, router, inputs, refs, v1, v2))
        if episodes[-1]["ok"]:
            episodes.append(_rollout_bad_canary_episode(
                ctl, fleet, reg, router, inputs, refs, v2, trace_dir))
        episodes.append(_rollout_resume_episode(
            os.path.join(workdir, "resume")))
    finally:
        fleet.close()

    for e in episodes:
        print(f"rollout-soak: {e['episode']} -> "
              f"{'OK' if e['ok'] else 'FAIL'} ({e['elapsed_s']}s)",
              flush=True)
    passed = sum(1 for e in episodes if e["ok"])
    report = {"mode": "rollout", "seed": args.seed,
              "episodes": episodes, "passed": passed,
              "failed": len(episodes) - passed,
              "elapsed_s": round(time.monotonic() - t0, 1),
              "ok": len(episodes) == 3 and passed == len(episodes)}
    text = json.dumps(report, indent=1)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
        print(f"rollout-soak: verdict written to {args.out} "
              f"({passed}/{len(episodes)} episode(s) passed)")
    else:
        print(text)
    if own_tmp and report["ok"]:
        import shutil
        shutil.rmtree(workdir, ignore_errors=True)
    elif not report["ok"]:
        print(f"rollout-soak: scratch kept at {workdir} for post-mortem "
              "(rollout.jsonl + flight dumps)", file=sys.stderr)
    return 0 if report["ok"] else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description="chaos soak runner")
    ap.add_argument("--runs", type=int, default=8)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--out", default=None,
                    help="write the JSON verdict here (default: stdout)")
    ap.add_argument("--workdir", default=None,
                    help="scratch dir (default: a TemporaryDirectory)")
    ap.add_argument("--fleet", type=int, default=0, metavar="N",
                    help="fleet mode: N concurrent seeded chaos jobs + a "
                         "late whole-budget preemptor under one "
                         "FleetScheduler (0 = classic per-run soak)")
    ap.add_argument("--fleet-devices", type=int, default=8)
    ap.add_argument("--fleet-kill", action="store_true",
                    help="SIGKILL the scheduler mid-run and resume it "
                         "from its journal")
    ap.add_argument("--fleet-kill-after", type=float, default=6.0)
    ap.add_argument("--fleet-preempt-after", type=float, default=5.0,
                    help="delay before the high-priority preemptor "
                         "arrives")
    ap.add_argument("--fleet-timeout", type=float, default=420.0)
    ap.add_argument("--pod", type=int, default=0, metavar="N",
                    help="pod mode: burn in a simulated N-host fleet "
                         "(mixed training + serving tenants) under the "
                         "seeded traffic model")
    ap.add_argument("--pod-devices", type=int, default=4,
                    help="device slices per simulated host")
    ap.add_argument("--pod-slice", action="store_true",
                    help="the ~60s CI shape: one host-kill + one flash "
                         "crowd (skips the drain and serving-host-loss "
                         "legs)")
    ap.add_argument("--forever", action="store_true",
                    help="standing burn-in: keep scheduling episodes "
                         "until one fails (or Ctrl-C)")
    ap.add_argument("--pod-timeout", type=float, default=420.0,
                    help="bound on the training tenants of one episode")
    ap.add_argument("--pod-qps", type=float, default=None,
                    help="base offered QPS (default SPARKNET_SOAK_QPS)")
    ap.add_argument("--pod-flash-x", type=float, default=None,
                    help="flash-crowd multiplier "
                         "(default SPARKNET_SOAK_FLASH_X)")
    ap.add_argument("--pod-leg-s", type=float, default=None,
                    help="seconds per traffic leg "
                         "(default SPARKNET_SOAK_LEG_S)")
    ap.add_argument("--net", action="store_true",
                    help="net mode: the partition/fenced-ship/slow-link "
                         "chaos legs over the fake-ssh ChaosTransport")
    ap.add_argument("--net-slice", action="store_true",
                    help="the ~60s CI shape: partition-suspend-heal + "
                         "fenced-zombie legs only (skips slow-link)")
    ap.add_argument("--rollout", action="store_true",
                    help="rollout mode: canary-promote, bad-canary "
                         "auto-rollback, and controller-kill-resume "
                         "legs over the registry + rollout controller")
    args = ap.parse_args(argv)

    if args.rollout:
        return rollout_soak(args)
    if args.net:
        return net_soak(args)
    if args.pod:
        return pod_soak(args)
    if args.fleet:
        return fleet_soak(args)

    import numpy as np
    _clean_env()
    rng = np.random.default_rng(args.seed)

    own_tmp = args.workdir is None
    workdir = args.workdir or tempfile.mkdtemp(prefix="sparknet_soak_")
    os.makedirs(workdir, exist_ok=True)

    baselines: dict[tuple[str, ...], str] = {}

    def baseline_for(flags):
        """Fault-free reference run per flag set (cached — the guard and
        audit change checkpoint traffic but not the training math, so
        matching flags keeps the comparison honest)."""
        key = tuple(flags)
        if key not in baselines:
            path = os.path.join(workdir, f"base_{len(baselines)}.npz")
            ck = os.path.join(workdir, f"base_ck_{len(baselines)}")
            rc, _ = _run_driver(path, ck if flags else None, list(flags))
            if rc != 0:
                raise RuntimeError(f"fault-free baseline failed rc={rc} "
                                   f"(flags={flags})")
            baselines[key] = path
        return baselines[key]

    runs = []
    t0 = time.monotonic()
    for i in range(args.runs):
        options = _schedules(rng)
        name, fault, flags = options[int(rng.integers(0, len(options)))]
        out = os.path.join(workdir, f"run_{i}.npz")
        ck = os.path.join(workdir, f"ck_{i}")
        verdict = {"run": i, "schedule": name, "fault": fault,
                   "flags": flags}
        try:
            base = baseline_for(flags)
            rc, attempts = _run_driver(out, ck, list(flags), fault=fault)
            verdict.update(rc=rc, attempts=attempts)
            if rc == 0:
                match, bad_key = _params_match(base, out)
                verdict.update(match=match,
                               **({"diverged_at": bad_key}
                                  if not match else {}))
            else:
                verdict.update(match=False)
        except Exception as e:   # a broken run is a red verdict, not a crash
            verdict.update(rc=-1, attempts=0, match=False, error=str(e))
        verdict["ok"] = bool(verdict.get("rc") == 0 and verdict["match"])
        runs.append(verdict)
        print(f"soak: run {i} [{fault}] -> "
              f"{'OK' if verdict['ok'] else 'FAIL'} "
              f"(rc={verdict.get('rc')}, attempts="
              f"{verdict.get('attempts')})", flush=True)

    passed = sum(1 for r in runs if r["ok"])
    report = {"seed": args.seed, "runs": runs, "passed": passed,
              "failed": len(runs) - passed,
              "elapsed_s": round(time.monotonic() - t0, 1),
              "ok": passed == len(runs)}
    text = json.dumps(report, indent=1)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
        print(f"soak: verdict written to {args.out} "
              f"({passed}/{len(runs)} passed)")
    else:
        print(text)
    if own_tmp and report["ok"]:
        import shutil
        shutil.rmtree(workdir, ignore_errors=True)
    elif not report["ok"]:
        print(f"soak: scratch kept at {workdir} for post-mortem",
              file=sys.stderr)
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
