"""Distributed training strategies as single compiled programs.

The two data-parallel forms of the reference (SURVEY.md §2.4), re-built as
XLA collectives inside one ``shard_map``-compiled round:

1. **"local_sgd"** — SparkNet's contribution: every worker runs τ local SGD
   steps on its own data partition, then weights are averaged.  The
   reference implements this as a Spark driver loop — broadcast weights →
   per-worker ``net.train(τ)`` → collect and average ≈249 MB of weights
   through one driver JVM (reference: src/main/scala/apps/ImageNetApp.scala:
   100-182, WeightCollection.add at src/main/scala/libs/Net.scala:27-46) —
   costing two cross-machine barriers and a driver bottleneck per round.
   Here the whole round is ONE jitted op: ``lax.scan`` over τ compute steps,
   then ``lax.pmean`` over the mesh — the averaging rides ICI at full
   bisection bandwidth and no weight ever visits a host.  Per-worker solver
   state (momentum history) stays device-resident between rounds, exactly
   like the reference's per-worker embedded solvers.

2. **"sync"** — Caffe's P2PSync semantics: per-step gradient reduction then
   a single update (reference: caffe/src/caffe/parallel.cpp:271-360
   tree-reduce over CUDA P2P; ``on_gradients_ready`` hook at solver.cpp:260).
   Here the tree is ``lax.pmean`` on the gradients inside the step.

3. **"hierarchical"** — the two tiers COMPOSED on a (host, chip) mesh,
   the way a real TPU pod would deploy SparkNet's semantics: per-step
   gradient pmean over the ``chip`` axis (ICI within a host — P2PSync's
   role) and τ-step weight averaging over the ``host`` axis (DCN across
   hosts — the Spark driver round's role).  The reference never composed
   its two tiers (SparkNet pinned one GPU per worker, Net.scala:95);
   this is the completion of that design.  Optimizer state is per-HOST
   (all chips of a host apply identical chip-mean updates, so the state
   is replicated within the host and distinct across hosts between
   averaging boundaries).  Collapses to flat "sync" at n_hosts=1 and to
   flat "local_sgd" at chips_per_host=1 (tested equivalences).

τ=1 local_sgd and sync differ exactly as in the reference: sync averages
gradients before the momentum update (one shared optimizer state), local_sgd
averages weights after it (per-worker optimizer states).
"""

from __future__ import annotations

import collections
import dataclasses
import glob
import hashlib
import inspect
import json
import os
import sys
import time
from typing import Any, Callable, Iterator, Mapping

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..graph.net import Net, WeightCollection
from ..proto.caffe_pb import NetState, Phase, SolverParameter
from ..utils import telemetry
from ..solvers.lr_policies import learning_rate
from ..solvers.step import make_step_fns
from ..solvers.update_rules import make_update_rule, preprocess_grads
from .mesh import (
    CHIP_AXIS, DATA_AXIS, HOST_AXIS, make_mesh, make_pod_mesh,
    put_global_tree, replicated, stage_local,
)

try:  # jax >= 0.6 exposes shard_map at top level
    shard_map = jax.shard_map
except AttributeError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map  # type: ignore

# the replication-check kwarg was renamed check_rep -> check_vma across
# jax versions; resolve whichever this jax spells, once at import
_sm_params = inspect.signature(shard_map).parameters
if "check_vma" in _sm_params:
    _SM_NOCHECK: dict[str, bool] = {"check_vma": False}
elif "check_rep" in _sm_params:
    _SM_NOCHECK = {"check_rep": False}
else:  # pragma: no cover
    _SM_NOCHECK = {}
del _sm_params


@dataclasses.dataclass(frozen=True)
class TrainerConfig:
    strategy: str = "local_sgd"   # "local_sgd" | "sync" | "hierarchical"
    tau: int = 1                  # steps per round (local steps for local_sgd)
    donate: bool = True
    # Optional pure-JAX augmentation applied to each micro-batch INSIDE the
    # compiled round, (micro_batch_dict, rng) -> micro_batch_dict — the
    # TPU-native fix for host-bound preprocessing (the reference crops on
    # the host because GPU Caffe did; on TPU the crop is ~free next to the
    # matmuls, and the host then only ships raw images).  Build one with
    # ``device_crop_mirror_mean``.
    device_preprocess: Any | None = None
    # jax.checkpoint the forward: backward recomputes activations instead
    # of storing them (HBM for FLOPs; big-batch / VGG-class configs)
    remat: bool = False
    # Round-granular fault tolerance: with ``checkpoint_dir`` set, process
    # 0 writes params + per-worker solver state + round counter + RNG +
    # data-cursor every ``checkpoint_every`` completed rounds, each under
    # a checksummed manifest, and a fresh trainer auto-resumes from the
    # newest manifest whose checksum validates (corrupt/partial snapshots
    # are skipped).  ``checkpoint_keep`` bounds disk: older round
    # checkpoints beyond the newest N are pruned.  This is the recovery
    # half of the reference's Spark story — a relaunched job (see
    # ``parallel.resilience.ResilientRunner``) loses at most
    # ``checkpoint_every`` rounds.
    checkpoint_dir: str | None = None
    checkpoint_every: int = 1
    checkpoint_keep: int = 3
    # Elastic degraded mode: allow resuming a checkpoint written by a
    # DIFFERENT worker count — the SparkNet average over k-1 workers is
    # still a valid consensus, so a job that lost a host permanently can
    # re-form on the survivors (params are replicated and restore as-is;
    # stacked per-worker/per-host optimizer state is re-tiered: surviving
    # worker i inherits saved row i mod saved_n).  Strategy mismatches
    # still raise — that is a config error, not membership change.
    elastic: bool = False
    # Numerical-integrity guard: after each averaging step validate the
    # round (finite loss, finite params, optional loss-spike threshold);
    # a poisoned round is DROPPED — the trainer rolls back to the newest
    # valid round checkpoint instead of letting a NaN/Inf be averaged
    # into the master weights and persisted forever.  Requires
    # ``checkpoint_dir`` (a baseline round-0 checkpoint is written at
    # init so rollback is always possible).
    guard_numerics: bool = False
    # > 0: additionally trip when loss exceeds ``loss_spike_factor`` ×
    # the trailing-mean loss (catches divergence before it reaches Inf)
    loss_spike_factor: float = 0.0
    # multiply the effective LR by this on every guard trip (< 1.0 backs
    # off a diverging step size; 1.0 = rollback only).  The scale is a
    # traced input of the compiled round — changing it never recompiles.
    guard_lr_backoff: float = 1.0
    guard_max_trips: int = 3
    # Cross-replica parameter audit: every ``audit_every`` rounds (0 =
    # off), BEFORE the round runs, each replica computes a cheap
    # fingerprint of its resident parameter copy (uint32 bitcast
    # tree-sum — one fused pass, one all_gather) and the mesh compares.
    # Replicated params are an *invariant the hardware can silently
    # break* (a flipped HBM bit, a diverged host): a mismatch means some
    # replica's copy rotted since the last audit, and the next averaging
    # collective would fold it into the master weights forever.  On
    # mismatch the trainer takes the guard's rollback path to the newest
    # checkpoint at or before the last PASSED audit (params/state/iter/
    # RNG restored — the replay is exact and, with one-shot faults,
    # clean), so a bit flip costs at most one audit interval.  Requires
    # ``checkpoint_dir``; shares ``guard_max_trips``.  Note: local_sgd
    # re-averages params every round boundary, which folds (hides) a
    # flip at the next boundary — audit_every=1 is the right cadence
    # there; sync/hierarchical keep per-replica divergence resident, so
    # a coarser cadence still detects.
    audit_every: int = 0
    # Zero-stall outer loop: with ``harvest_lag`` K > 0 the loss, the
    # guard's finite-check verdict, and the audit fingerprints stay
    # ON-DEVICE as futures and are harvested up to K rounds late, so the
    # host never synchronizes with the steady-state round — up to K
    # compiled rounds stay in flight (round pipelining) while host
    # bookkeeping overlaps device compute.  Safety semantics are
    # unchanged, only deferred: a guard/audit trip detected while
    # harvesting round r rolls back to a checkpoint at round <= r (the
    # same exact-RNG replay path), and every in-flight round after r is
    # discarded and replayed.  Checkpoint retention must therefore cover
    # the lag (validated at init: K more rounds may complete before a
    # poison is detected, so the pre-poison checkpoint must outlive
    # them).  0 = today's fully synchronous behavior, bit-identical.
    harvest_lag: int = 0
    # Async checkpointing: round checkpoints snapshot with a
    # NON-BLOCKING device→host copy and serialize/checksum/rename on a
    # background writer thread (utils.checkpoint.AsyncCheckpointWriter),
    # preserving the tmp+rename crash-safety, manifest checksums,
    # pruning, and orphan-tmp sweep byte-for-byte.  Rollback, resume,
    # preemption and fault-injection windows flush the writer first, so
    # recovery semantics are exact.  ``SPARKNET_ASYNC_CKPT=0`` overrides
    # to the synchronous path regardless of this field.
    async_checkpoint: bool = True
    # Compressed τ-boundary weight exchange (parallel/comms.py; ROADMAP
    # item 5b).  "none" keeps the pre-existing fused single-program
    # round — bit-identical to the trainer before codecs existed, BY
    # CONSTRUCTION (no delta arithmetic runs at all).  Any other
    # registered codec ("bf16" / "int8" / "int8_channel" / test-planted
    # ones) splits the round: the compiled local-steps program returns
    # per-tier weights WITHOUT the boundary pmean, an encode program
    # quantizes each tier's delta against the last broadcast state (plus
    # the error-feedback residual, which persists in trainer state and
    # rides checkpoints), the gathered payload is decoded and averaged
    # identically on every replica — so params stay replicated and the
    # cross-replica audit holds under every codec.  Only the strategies
    # that exchange weights at the τ boundary can compress them:
    # local_sgd and hierarchical.  "sync" exchanges per-step GRADIENTS
    # inside the scan and raises at init with any codec but "none".
    comm_codec: str = "none"
    # Overlap the encode→exchange→decode tail with subsequent host work
    # (the harvest-lag discipline of PR 5 applied to the exchange): the
    # three comm programs are DISPATCHED without host blocking, so the
    # next round's feed staging / bookkeeping — and with harvest_lag > 0
    # the next round itself — proceed while the bytes move.  Program
    # order and results are bit-identical to comm_overlap=False; only
    # the host-blocking policy (and therefore the measured
    # stall_s["comm_*"]) changes.  Inert at comm_codec="none", where the
    # exchange already rides inside the one compiled round with zero
    # host stall to hide.
    comm_overlap: bool = False
    # Hybrid model+data sharding (parallel/partition.py; ROADMAP item 2).
    # "off" keeps pure data parallelism — the pre-plan code path byte for
    # byte.  "auto" resolves the zoo default rule table (FC/inner-product
    # weights shard across the mesh's fast axis — chips on a pod mesh,
    # the data axis on a flat mesh — convs and biases stay replicated);
    # anything else is the path of a versioned JSON rule table.  Params
    # then LIVE sharded between rounds (HBM / shard factor), the round
    # bodies gather shards on entry (tiled all_gather — exact) and
    # reduce-scatter at the τ boundary (each position receives only its
    # own shard's bytes), so losses and logical params stay bit-identical
    # to the replicated baseline at codec "none" — by construction:
    # psum_scatter(tiled)/n is bitwise pmean-then-slice, and slicing is
    # not arithmetic.
    shard: str = "off"
    # Per-shard round checkpoints: with a live shard plan, write the
    # sharded leaves as one npz tile per shard (common leaves + manifest
    # unchanged), all fanned through the same (async) writer and each
    # sha256-pinned in the manifest.  Restore joins tiles back to full
    # logical leaves, so a checkpoint written at world N re-tiles onto
    # world M bit-exactly (the elastic contract survives sharding).
    shard_checkpoint: bool = False


class TrainingDivergedError(RuntimeError):
    """The numerical-integrity guard tripped and could not recover:
    no checkpoint to roll back to, or ``guard_max_trips`` exceeded
    (the fault is deterministic — rollback alone cannot outrun it)."""


def device_crop_mirror_mean(crop: int, mirror: bool = True,
                            mean=None, field: str = "data"):
    """Build a ``TrainerConfig.device_preprocess``: random crop to
    (crop, crop) + horizontal mirror + mean subtraction, fused into the
    compiled round.  Caffe-window semantics: a full-size mean is
    subtracted before cropping (== subtracting at each sample's window,
    data_transformer.cpp).  The host then ships raw full-size images and
    does no per-pixel work at all — the TPU-native resolution of the
    reference's measured feed bottleneck (java_data_layer.cpp:36-44)."""
    mean_arr = jnp.asarray(mean, jnp.float32) if mean is not None else None
    # a crop-sized mean (the pycaffe mean-file shape) is subtracted AFTER
    # cropping; a full-size mean before (equivalent to subtracting at each
    # window); anything else should fail clearly, not deep in jit tracing
    mean_after = (mean_arr is not None and mean_arr.ndim >= 2
                  and mean_arr.shape[-2:] == (crop, crop))

    def pre(micro, rng):
        data = micro[field]
        lead = data.shape[:-3]
        c, h, w = data.shape[-3:]
        flat = data.reshape((-1, c, h, w)).astype(jnp.float32)
        if mean_arr is not None and not mean_after:
            if mean_arr.ndim >= 2 and mean_arr.shape[-2:] != (h, w):
                raise ValueError(
                    f"device mean shape {mean_arr.shape} matches neither "
                    f"the full image ({h}, {w}) nor the crop "
                    f"({crop}, {crop})")
            flat = flat - mean_arr
        n = flat.shape[0]
        ky, kx, kf = jax.random.split(rng, 3)
        ys = jax.random.randint(ky, (n,), 0, h - crop + 1)
        xs = jax.random.randint(kx, (n,), 0, w - crop + 1)
        flips = (jax.random.bernoulli(kf, 0.5, (n,)) if mirror
                 else jnp.zeros((n,), bool))

        def one(img, y, x, f):
            win = lax.dynamic_slice(img, (0, y, x), (c, crop, crop))
            if mean_after:
                # crop-sized mean subtracts at unmirrored coordinates
                # (data_transformer.cpp mirrors the subtracted result)
                win = win - mean_arr
            return jnp.where(f, win[:, :, ::-1], win)

        out = jax.vmap(one)(flat, ys, xs, flips)
        return {**micro, field: out.reshape(lead + (c, crop, crop))}

    return pre


def comm_config_from_env(base: TrainerConfig | None = None) -> TrainerConfig:
    """``base`` (or a default TrainerConfig) with the communication
    round shape taken from the registered knobs where they are set:
    ``SPARKNET_TAU`` (steps per round — the paper's swept frontier knob),
    ``SPARKNET_COMM_CODEC``, ``SPARKNET_COMM_OVERLAP``, ``SPARKNET_SHARD``
    (partition rule table: off | auto | path) and
    ``SPARKNET_SHARD_CKPT`` (per-shard round checkpoints).  Unset knobs
    leave ``base``'s fields untouched, so an explicitly-constructed
    config still wins; drivers (tools/train, commbench, sweep harnesses)
    call this so one env var re-shapes a whole launched grid without
    code changes."""
    from ..utils import knobs
    cfg = base or TrainerConfig()
    tau = knobs.get_int("SPARKNET_TAU", 0)
    if tau > 0:
        cfg = dataclasses.replace(cfg, tau=tau)
    codec = knobs.get_str("SPARKNET_COMM_CODEC", "")
    if codec:
        cfg = dataclasses.replace(cfg, comm_codec=codec)
    if knobs.is_set("SPARKNET_COMM_OVERLAP"):
        cfg = dataclasses.replace(
            cfg, comm_overlap=knobs.get_bool("SPARKNET_COMM_OVERLAP", False))
    shard = knobs.get_str("SPARKNET_SHARD", "")
    if shard:
        cfg = dataclasses.replace(cfg, shard=shard)
    if knobs.is_set("SPARKNET_SHARD_CKPT"):
        cfg = dataclasses.replace(
            cfg, shard_checkpoint=knobs.get_bool("SPARKNET_SHARD_CKPT",
                                                 False))
    return cfg


class DistributedTrainer:
    """Owns params (replicated, or per-leaf sharded under a partition
    rule table — ``TrainerConfig.shard``) + (per-device or shared) solver
    state and a compiled per-round train step over a device mesh."""

    def __init__(self, sp: SolverParameter, mesh=None,
                 config: TrainerConfig | None = None, *, seed: int = 0):
        self.sp = sp
        self.config = config or TrainerConfig()
        if self.config.strategy not in ("local_sgd", "sync", "hierarchical"):
            raise ValueError(f"unknown strategy {self.config.strategy!r}")
        from . import comms
        # "none" stays structurally OFF this machinery (comms.py module
        # doc): _codec None routes the round through the pre-codec fused
        # program verbatim
        self._codec = (None if self.config.comm_codec == "none"
                       else comms.get_codec(self.config.comm_codec))
        if self._codec is not None and self.config.strategy == "sync":
            raise ValueError(
                f"comm_codec={self.config.comm_codec!r} needs a τ-boundary "
                f"weight exchange to compress; strategy 'sync' exchanges "
                f"per-step gradients inside the scan (use local_sgd or "
                f"hierarchical, or comm_codec='none')")
        if self.config.strategy == "hierarchical":
            self.mesh = mesh if mesh is not None else make_pod_mesh()
            if (HOST_AXIS not in self.mesh.shape
                    or CHIP_AXIS not in self.mesh.shape):
                raise ValueError(
                    "hierarchical strategy needs a (host, chip) mesh — "
                    "build it with make_pod_mesh()")
            self.n_hosts = self.mesh.shape[HOST_AXIS]
            self.n_chips = self.mesh.shape[CHIP_AXIS]
            self.n_workers = self.n_hosts * self.n_chips
            # batch rows shard over BOTH tiers; weights average over host
            self._batch_axes: tuple[str, ...] = (HOST_AXIS, CHIP_AXIS)
        else:
            self.mesh = mesh if mesh is not None else make_mesh()
            self.n_workers = self.mesh.shape[DATA_AXIS]
            self._batch_axes = (DATA_AXIS,)
        net_param = sp.net_param or sp.train_net_param
        if net_param is None:
            raise ValueError("SolverParameter carries no net definition")
        self.train_net = Net(net_param, NetState(Phase.TRAIN))
        self.test_net = Net(net_param, NetState(Phase.TEST))
        self.rule = make_update_rule(sp)
        self.iter = 0

        rng = jax.random.PRNGKey(seed if seed >= 0 else 0)
        self._rng, init_rng = jax.random.split(rng)
        rep = replicated(self.mesh)
        # same-seed host-side init staged onto the (possibly multi-host)
        # mesh — explicit per-host replication (SURVEY.md §7.3)
        host_params = self.train_net.init(init_rng)
        # hybrid model+data sharding: resolve the partition rule table
        # against this net's shapes at init (parallel/partition.py).
        # None = pure DP — every code path below is then the pre-plan
        # trainer byte for byte.  Shards live on the fast axis: chips on
        # a pod mesh, the one data axis on a flat mesh.
        from . import partition
        if self.config.strategy == "hierarchical":
            shard_axis, n_shards = CHIP_AXIS, self.n_chips
        else:
            shard_axis, n_shards = DATA_AXIS, self.n_workers
        self.shard_plan = partition.resolve_plan(
            self.config.shard, host_params, axis=shard_axis,
            n_shards=n_shards)
        self.shard_plan_id = partition.shard_plan_id(self.shard_plan)
        # per-leaf resident placement: a params-shaped pytree of
        # NamedShardings under a plan, one replicated sharding without
        self._params_sharding = (
            self.shard_plan.sharding_tree(self.mesh, host_params)
            if self.shard_plan is not None else rep)
        self.params: WeightCollection = put_global_tree(
            host_params, self._params_sharding)
        state0 = self.rule.init(host_params)
        if self.config.strategy == "sync":
            self.state = put_global_tree(state0, rep)
        else:
            # per-worker (local_sgd) / per-host (hierarchical) optimizer
            # state: leading axis sharded over that tier, so each update
            # domain keeps its own momentum history between averages
            n, spec = self._state_tier()
            stacked = jax.tree_util.tree_map(
                lambda x: jnp.broadcast_to(x[None], (n,) + x.shape), state0)
            self.state = put_global_tree(
                stacked, NamedSharding(self.mesh, spec))
        self._lr_mults = put_global_tree(
            self.train_net.lr_mult_tree(self.params), rep)
        self._decay_mults = put_global_tree(
            self.train_net.decay_mult_tree(self.params), rep)

        self._round = self._build_round()
        self._test_fwd = None

        # -- compressed-exchange state (comm_codec != "none"): per-tier
        # error-feedback residuals (trainer state: checkpointed, rolled
        # back, re-tiered like stacked optimizer state) and the three
        # compiled comm programs (encode / exchange / decode)
        self.comm_residual = None
        self._comm = None
        if self._codec is not None:
            n, spec = self._state_tier()
            self.comm_residual = put_global_tree(
                jax.tree_util.tree_map(
                    lambda x: np.zeros((n,) + tuple(x.shape), np.float32),
                    self.params),
                NamedSharding(self.mesh, spec))
            self._comm = self._build_comm_programs()

        # -- resilience state: completed-round counter, caller-maintained
        # feed cursor (any JSON value), and the manifest we resumed from
        self.round = 0
        self.data_cursor: Any = None
        self.resumed: dict[str, Any] | None = None
        # -- numerical-integrity guard state: effective-LR scale (backed
        # off on trips; checkpointed so a relaunch keeps it), trip count,
        # and a short trailing window of accepted losses for spike checks
        self.lr_scale = 1.0
        self.guard_trips = 0
        self._loss_history: list[float] = []
        self._finite_check = None
        # -- cross-replica audit state: compiled fingerprint fn, trip
        # count, and the newest round whose audit PASSED (the rollback
        # horizon — checkpoints at or before it are divergence-free)
        self.audit_trips = 0
        self._audit_fn = None
        self._last_audit_ok = 0
        # -- zero-stall outer loop state: in-flight rounds awaiting
        # harvest (device futures: loss, finite verdict, audit
        # fingerprints), per-round harvested losses, the async checkpoint
        # writer (lazy), and per-component host-stall accounting that
        # bench.py's round_overhead leg reads
        self._pending: collections.deque = collections.deque()
        self.round_losses: dict[int, float] = {}
        self._ckpt_writer = None
        self.stall_s = {"loss_fetch": 0.0, "finite_check": 0.0,
                        "audit_fetch": 0.0, "checkpoint": 0.0,
                        "comm_encode": 0.0, "comm_allreduce": 0.0,
                        "comm_decode": 0.0}
        # the FeedStats of the newest input_feed() (if any) — published on
        # round_end heartbeats so fleet-level supervisors can see the data
        # plane's health without any extra channel
        self.feed_stats = None
        # telemetry handles (no-op singletons under SPARKNET_TELEMETRY=0)
        reg = telemetry.get_registry()
        self._m_rounds = reg.counter(
            "trainer_rounds_total", "training rounds run (replays included)")
        self._m_guard = reg.counter(
            "trainer_guard_trips_total", "numerical-guard rollbacks")
        self._m_audit = reg.counter(
            "trainer_audit_trips_total", "cross-replica audit rollbacks")
        self._m_stall = reg.gauge(
            "trainer_stall_seconds", "cumulative host stall by component")
        self._m_pending = reg.gauge(
            "trainer_pending_rounds", "in-flight rounds awaiting harvest")
        if self.config.harvest_lag < 0:
            raise ValueError(
                f"harvest_lag must be >= 0, got {self.config.harvest_lag}")
        if self.config.checkpoint_every < 1:
            raise ValueError(
                f"checkpoint_every must be >= 1, got "
                f"{self.config.checkpoint_every}")
        if self.config.guard_numerics and not self.config.checkpoint_dir:
            raise ValueError(
                "guard_numerics needs checkpoint_dir — rollback is the "
                "guard's only recovery action")
        if self.config.audit_every < 0:
            raise ValueError(
                f"audit_every must be >= 0, got {self.config.audit_every}")
        if self.config.audit_every:
            if not self.config.checkpoint_dir:
                raise ValueError(
                    "audit_every needs checkpoint_dir — rollback is the "
                    "audit's only recovery action")
            horizon = (self.config.checkpoint_every
                       * max(self.config.checkpoint_keep - 1, 0))
            if horizon < self.config.audit_every:
                raise ValueError(
                    f"audit_every={self.config.audit_every} outruns the "
                    f"checkpoint retention (checkpoint_every="
                    f"{self.config.checkpoint_every} x (checkpoint_keep="
                    f"{self.config.checkpoint_keep} - 1) = {horizon} "
                    f"rounds): by the time a mismatch is detected, every "
                    f"pre-divergence checkpoint may be pruned")
        if self.config.harvest_lag and (self.config.guard_numerics
                                        or self.config.audit_every):
            # retention-vs-lag: a poison at round r surfaces up to
            # harvest_lag rounds later (plus up to audit_every rounds of
            # audit cadence), during which fresh checkpoints keep landing
            # and pruning keeps trimming — the newest pre-poison
            # checkpoint (within checkpoint_every-1 rounds of r) must
            # still be on disk when the trip finally asks for it
            horizon = (self.config.checkpoint_every
                       * max(self.config.checkpoint_keep - 1, 0))
            need = (self.config.harvest_lag + self.config.audit_every
                    + self.config.checkpoint_every - 1)
            if horizon < need:
                raise ValueError(
                    f"harvest_lag={self.config.harvest_lag} outruns the "
                    f"checkpoint retention (checkpoint_every="
                    f"{self.config.checkpoint_every} x (checkpoint_keep="
                    f"{self.config.checkpoint_keep} - 1) = {horizon} < "
                    f"{need} rounds of detection latency): by the time a "
                    f"deferred guard/audit verdict trips, every "
                    f"pre-poison checkpoint may be pruned — raise "
                    f"checkpoint_keep or lower harvest_lag")
        if self.config.checkpoint_dir:
            self.resumed = self.resume_latest(self.config.checkpoint_dir)
            if ((self.config.guard_numerics or self.config.audit_every)
                    and self.resumed is None):
                # baseline snapshot: the guard/audit can always roll
                # back, even when the very first round is the poisoned one
                self.save_round_checkpoint()
        from . import health
        health.maybe_beat(self.round, "init")

    def _state_tier(self) -> tuple[int, P]:
        """(leading-axis length, PartitionSpec) of the stacked optimizer
        state for the strategies that keep one state per update domain."""
        if self.config.strategy == "hierarchical":
            return self.n_hosts, P(HOST_AXIS)
        return self.n_workers, P(DATA_AXIS)

    # -- compiled round ---------------------------------------------------
    def _build_round(self):
        sp = self.sp
        net = self.train_net
        rule = self.rule
        tau = self.config.tau
        strategy = self.config.strategy
        lr_mults = self._lr_mults
        decay_mults = self._decay_mults

        iter_size = sp.iter_size
        _, local_update, accum_grads = make_step_fns(
            sp, net, rule, lr_mults, decay_mults,
            remat=self.config.remat, in_scan=True)

        # params owned by forward-state layers (BatchNorm running stats):
        # the only blobs that drift per-shard under sync DP and need
        # re-averaging — pmean'ing the full weight set every step would be
        # a needless full-model collective (VERDICT r1 weak #7)
        state_keys = frozenset(
            key for n in net.nodes if getattr(n.impl, "has_state", False)
            for key in n.owner_keys())

        def split_micro(batches):
            """[tau*iter_size, local_batch, ...] -> [tau, iter_size, ...]
            (the per-step micro-batch runs of solver.cpp:221-224)."""
            return jax.tree_util.tree_map(
                lambda x: x.reshape((tau, iter_size) + x.shape[1:]), batches)

        device_pre = self.config.device_preprocess

        def maybe_preprocess(micro, rng):
            if device_pre is None:
                return micro
            return device_pre(micro, rng)

        def make_psum_step(axis, lr_scale):
            """One per-step-gradient-averaged update over ``axis`` — the
            P2PSync step, shared verbatim by "sync" (over the flat data
            axis) and "hierarchical" (over the chip axis within a host)."""
            def step(carry, micro):
                params, state, it, rng = carry
                rng, sub, pre_rng = jax.random.split(rng, 3)
                ai = lax.axis_index(axis)
                sub = jax.random.fold_in(sub, ai)
                micro = maybe_preprocess(
                    micro, jax.random.fold_in(pre_rng, ai))
                loss, params, grads = accum_grads(params, micro, sub)
                grads = lax.pmean(grads, axis)
                loss = lax.pmean(loss, axis)
                if state_keys:
                    # BN running stats diverge per shard; re-average those
                    # blobs (and only those) so the replication the
                    # out_spec claims over ``axis`` stays truthful
                    params = {
                        k: (lax.pmean(v, axis) if k in state_keys else v)
                        for k, v in params.items()}
                grads = preprocess_grads(sp, params, grads, lr_mults,
                                         decay_mults)
                rate = learning_rate(sp, it) * lr_scale
                params, state = rule.apply(params, grads, state, rate, it,
                                           lr_mults=lr_mults)
                return (params, state, it + 1, rng), loss
            return step

        def sync_body(params, state, it, batches, rng, lr_scale):
            """Per-step grad pmean (P2PSync semantics)."""
            params = maybe_gather(params)
            (params, state, it, _), losses = lax.scan(
                make_psum_step(DATA_AXIS, lr_scale),
                (params, state, it, rng), split_micro(batches))
            if plan is not None:
                # every position computed the same full update (per-step
                # grad pmean); each keeps only its resident shard — a
                # slice, zero communication, exact
                params = plan.take_shard(params, DATA_AXIS)
            return params, state, jnp.mean(losses)

        # compressed exchange (comm_codec != "none"): the τ-boundary
        # weight pmean LEAVES the compiled round — the body returns each
        # tier member's local weights stacked on the tier axis (exactly
        # like the optimizer state), and the encode→exchange→decode
        # programs built by _build_comm_programs do the averaging outside
        compressed = self._codec is not None

        # hybrid sharding: params enter the round in their resident
        # (per-leaf sharded) layout, are widened to full leaves by a
        # tiled all_gather (pure data movement — exact), and leave the
        # round shard-local again at the τ boundary.  plan=None keeps
        # the replicated P() contract untouched.
        plan = self.shard_plan

        def maybe_gather(params):
            return params if plan is None else plan.gather(params)

        def shard_boundary_mean(params, axis):
            """τ-boundary average under a plan: sharded leaves reduce-
            scatter (each position RECEIVES only its own shard's bytes
            — the broadcast shrink this refactor exists for), replicated
            leaves pmean as before.  psum_scatter(tiled)/n is bitwise
            identical to pmean-then-slice, so the parity contract
            holds."""
            out = {}
            for name, blobs in params.items():
                row = []
                for i, b in enumerate(blobs):
                    dim = plan.dim_of(f"{name}/{i}")
                    if dim is None:
                        row.append(lax.pmean(b, axis))
                    else:
                        row.append(lax.psum_scatter(
                            b, axis, scatter_dimension=dim, tiled=True)
                            / plan.n_shards)
                out[name] = row
            return out

        def local_sgd_body(params, state, it, batches, rng, lr_scale):
            """τ local steps, then weight averaging (SparkNet semantics)."""
            params = maybe_gather(params)
            state = jax.tree_util.tree_map(lambda x: x[0], state)
            rng = jax.random.fold_in(rng, lax.axis_index(DATA_AXIS))

            def step(carry, micro):
                params, state, it, rng = carry
                rng, sub, pre_rng = jax.random.split(rng, 3)
                micro = maybe_preprocess(micro, pre_rng)
                params, state, loss = local_update(params, state, it, micro,
                                                   sub, lr_scale)
                return (params, state, it + 1, rng), loss

            (params, state, it, _), losses = lax.scan(
                step, (params, state, it, rng), split_micro(batches))
            # the scalar loss is not part of the compressed exchange (3
            # bytes saved would not buy the lost logging fidelity), so it
            # is pmean'd here on either path
            loss = lax.pmean(jnp.mean(losses), DATA_AXIS)
            if not compressed:
                # the broadcast → reduce → scalarDivide of the reference's
                # outer loop (ImageNetApp.scala:102,178-179), as one ICI
                # collective:
                if plan is None:
                    params = lax.pmean(params, DATA_AXIS)
                else:
                    params = shard_boundary_mean(params, DATA_AXIS)
            else:
                params = jax.tree_util.tree_map(lambda x: x[None], params)
            state = jax.tree_util.tree_map(lambda x: x[None], state)
            return params, state, loss

        def hierarchical_body(params, state, it, batches, rng, lr_scale):
            """Per-step grad pmean over chips (the P2PSync step over the
            fast tier), τ-boundary weight pmean over hosts (the Spark
            round) — the two reference tiers composed on the
            (host, chip) mesh.  BN running stats follow both tiers'
            semantics: re-averaged per step over chips inside the psum
            step, averaged with the weights at the τ boundary over
            hosts."""
            params = maybe_gather(params)
            state = jax.tree_util.tree_map(lambda x: x[0], state)
            rng = jax.random.fold_in(rng, lax.axis_index(HOST_AXIS))
            (params, state, it, _), losses = lax.scan(
                make_psum_step(CHIP_AXIS, lr_scale),
                (params, state, it, rng), split_micro(batches))
            loss = lax.pmean(jnp.mean(losses), HOST_AXIS)
            if not compressed:
                # the cross-host averaging rides DCN once per τ steps —
                # the broadcast → reduce → scalarDivide of the reference's
                # outer loop (ImageNetApp.scala:102,178-179)
                if plan is None:
                    params = lax.pmean(params, HOST_AXIS)
                else:
                    # slice the resident chip shard FIRST, then average
                    # over hosts: the DCN collective moves only shard
                    # bytes, and slice-then-mean == mean-then-slice
                    # elementwise, so parity holds
                    params = plan.take_shard(params, CHIP_AXIS)
                    params = lax.pmean(params, HOST_AXIS)
            else:
                # chips within a host already agree (per-step chip psum);
                # stack one copy per HOST for the compressed DCN exchange
                params = jax.tree_util.tree_map(lambda x: x[None], params)
            state = jax.tree_util.tree_map(lambda x: x[None], state)
            return params, state, loss

        bodies = {"local_sgd": local_sgd_body, "sync": sync_body,
                  "hierarchical": hierarchical_body}
        body = bodies[strategy]
        state_spec = (P() if strategy == "sync"
                      else self._state_tier()[1])
        # params in/out specs derive from the partition rule table: a
        # per-leaf pytree of PartitionSpecs under a plan, P() without
        params_in_spec = (P() if plan is None
                          else plan.spec_tree(self.params))
        params_out_spec = (self._state_tier()[1] if compressed
                           else params_in_spec)
        # batches: [tau, global_batch, ...] sharded on the batch axis
        batch_spec = P(None, self._batch_axes)

        mapped = shard_map(
            body, mesh=self.mesh,
            in_specs=(params_in_spec, state_spec, P(), batch_spec, P(), P()),
            out_specs=(params_out_spec, state_spec, P()),
            **_SM_NOCHECK,
        )
        # compressed path: the replicated input params stay live as the
        # delta reference for encode/decode — only the state may donate
        donate: tuple[int, ...] = ()
        if self.config.donate:
            donate = (1,) if compressed else (0, 1)
        return jax.jit(mapped, donate_argnums=donate)

    def _build_comm_programs(self):
        """The three programs of the compressed exchange.  All replicas
        run identical programs over replicated inputs for decode, so the
        new params are replicated bit-identically by construction — the
        audit invariant holds under every codec with zero tolerance.

        * **encode** (per-tier): ``delta_i = local_i - ref + residual_i``
          then the codec's wire format; the new residual is the exact
          f32 quantization error (error feedback — compression error is
          deferred to round r+1, never dropped).
        * **exchange**: reshard the stacked payload tier→replicated (one
          all-gather).  This is the collective that moves the wire
          bytes — the only traffic the codec is shrinking.
        * **decode**: every replica decodes the same gathered payload,
          means the deltas over the tier axis, and adds the same
          replicated reference back.
        """
        from . import comms
        codec = self._codec

        def enc(local, ref, residual):
            delta = jax.tree_util.tree_map(
                lambda l, r, e: l - r[None] + e, local, ref, residual)
            payload, _, new_res = comms.roundtrip_tree(codec, delta)
            return payload, new_res

        def dec(payload, ref):
            deltas = comms.decode_tree(codec, payload, ref_stacked_like(ref))
            return jax.tree_util.tree_map(
                lambda r, d: r + jnp.mean(d, axis=0), ref, deltas)

        n_tier = self._state_tier()[0]

        def ref_stacked_like(ref):
            # structural template only (decode_tree re-anchors the tree
            # structure from it; values are never read)
            return jax.tree_util.tree_map(
                lambda x: jnp.broadcast_to(x[None], (n_tier,) + x.shape),
                ref)

        rep = replicated(self.mesh)
        # local + residual are consumed; ref params must survive (decode
        # still needs them after encode ran)
        encode = jax.jit(enc, donate_argnums=(0, 2))
        exchange = jax.jit(lambda t: t, out_shardings=rep)
        # every replica decodes the same gathered payload, so the full
        # logical result is identical everywhere; under a shard plan the
        # output lands straight in the per-leaf resident placement (each
        # position stores only its shard of the identical value — the
        # audit's shard invariant holds under every codec)
        decode = jax.jit(dec, out_shardings=self._params_sharding)
        return encode, exchange, decode

    def _run_comm_round(self, batches, rng):
        """One compressed round: local-steps program, then the
        encode→exchange→decode tail.  ``comm_overlap`` is purely a
        host-blocking policy — False inserts a ``block_until_ready``
        after each stage so ``stall_s`` charges the true device time to
        the right component (the roundbench discipline); True dispatches
        all three and returns, letting the tail overlap whatever the
        host does next (feed staging, bookkeeping, or — with
        harvest_lag > 0 — the next round's dispatch).  Same programs,
        same order, bit-identical results either way."""
        overlap = self.config.comm_overlap
        local, self.state, loss = self._round(
            self.params, self.state, jnp.asarray(self.iter), batches, rng,
            jnp.asarray(self.lr_scale, jnp.float32))
        encode, exchange, decode = self._comm
        t0 = time.perf_counter()
        payload, self.comm_residual = encode(
            local, self.params, self.comm_residual)
        if not overlap:
            jax.block_until_ready(payload)
        self.stall_s["comm_encode"] += time.perf_counter() - t0
        t0 = time.perf_counter()
        gathered = exchange(payload)
        if not overlap:
            jax.block_until_ready(gathered)
        self.stall_s["comm_allreduce"] += time.perf_counter() - t0
        t0 = time.perf_counter()
        self.params = decode(gathered, self.params)
        if not overlap:
            jax.block_until_ready(self.params)
        self.stall_s["comm_decode"] += time.perf_counter() - t0
        return loss

    # -- driver API -------------------------------------------------------
    @property
    def input_sharding(self) -> NamedSharding:
        """Sharding for [τ, global_batch, ...] round feeds — batch axis over
        the mesh.  Feeds staged with this (e.g. via ``data.prefetch.
        device_feed``) make ``train_round``'s own device_put a no-op."""
        return NamedSharding(self.mesh, P(None, self._batch_axes))

    @property
    def batches_per_round(self) -> int:
        """Minibatches consumed per round: τ steps × iter_size micro-batches
        (gradient accumulation, reference: solver.cpp:221-224)."""
        return self.config.tau * self.sp.iter_size

    def input_feed(self, rounds: Iterator[Mapping[str, Any]],
                   depth: int | None = None, stats=None,
                   stall_timeout: float | None = None, restarts: int = 1,
                   device_cast: Mapping[str, Any] | None = None):
        """Stage a host round stream for this trainer through the
        parallel feed pipeline (``data.prefetch.device_feed``) with the
        trainer's ``input_sharding`` — decode/transform/transfer overlap
        the compiled round, and ``train_round``'s own device_put becomes
        a no-op.  ``depth`` defaults to ``SPARKNET_FEED_DEPTH`` when set,
        else ``harvest_lag + 1``: a [τ, global_batch, ...] round is large
        in HBM, so the deep default that suits per-step feeds is opt-in
        here — but a pipelined loop (``harvest_lag`` K > 0) keeps K
        compiled rounds in flight and needs that many staged feeds to
        never be the bottleneck.  ``device_cast`` (blob -> dtype) stages
        the host's array as-is and casts AFTER transfer — the raw-uint8
        feed path (records + device-side augmentation) ships 1/4 the
        PCIe bytes of an f32 round.  Close the returned feed (context
        manager) after the loop."""
        from ..data.pipeline import FeedStats, feed_depth
        from ..data.prefetch import device_feed
        if depth is None:
            depth = feed_depth(max(1, self.config.harvest_lag + 1))
        if stats is None:
            stats = FeedStats()
        self.feed_stats = stats
        return device_feed(rounds, depth=depth,
                           sharding=self.input_sharding, stats=stats,
                           stall_timeout=stall_timeout, restarts=restarts,
                           device_cast=device_cast)

    def train_round(self, batches: Mapping[str, Any]) -> float:
        """Run one round (τ steps, each accumulating iter_size
        micro-batches).  ``batches`` maps input blob names to arrays with a
        leading τ·iter_size axis and a batch axis:
        [tau * iter_size, batch, ...].  Single-host, the batch axis is the
        global batch; multi-host, each process passes only ITS rows of the
        global batch (its partitions — the zipPartitions placement,
        reference: ImageNetApp.scala:145) and the global array is assembled
        without any host seeing the whole batch.

        With ``guard_numerics`` the finished round is validated before it
        counts: a non-finite loss, non-finite params, or a loss spike
        rolls the trainer back to the newest valid checkpoint and the
        round is DROPPED — ``self.round`` does not advance, so a
        ``while trainer.round < rounds`` driver naturally replays it.
        The (poisoned) loss is still returned for logging.

        With ``harvest_lag`` K > 0 this call is free of host
        synchronization in the steady state: the loss/guard/audit
        results stay on-device and are harvested once K rounds are in
        flight, so the return value is the loss of a round up to K
        behind (``float('nan')`` until the first harvest; exact
        per-round losses accumulate in ``self.round_losses``).  A trip
        detected at harvest rolls back exactly as the synchronous path
        does — same checkpoint chain, same RNG replay — and discards
        every in-flight round after the poisoned one.  Call ``drain()``
        before reading final params/scores."""
        with telemetry.span("trainer.round", cat="trainer",
                            round=self.round):
            loss_val = self._train_round_impl(batches)
        self._m_rounds.inc()
        self._m_pending.set(len(self._pending))
        for k, v in self.stall_s.items():
            self._m_stall.set(v, comp=k)
        telemetry.get_registry().maybe_snapshot()
        return loss_val

    def _train_round_impl(self, batches: Mapping[str, Any]) -> float:
        from . import health
        from ..utils import faults
        expect = self.batches_per_round
        procs = jax.process_count()
        local_workers = max(self.n_workers // procs, 1)
        for k, v in batches.items():
            if v.shape[0] != expect:
                raise ValueError(
                    f"{k}: leading dim {v.shape[0]} != tau*iter_size "
                    f"{expect}")
            if v.shape[1] % local_workers:
                raise ValueError(
                    f"{k}: batch {v.shape[1]} not divisible by "
                    f"{local_workers} local workers")
        round_idx = self.round
        lag = self.config.harvest_lag
        health.maybe_beat(round_idx, "round_start")
        # deterministic chaos hook: rot one replica's resident param copy
        # (a flipped HBM bit between rounds — the event the audit exists
        # to catch before the next averaging folds it in)
        flip = faults.get_injector().bitflip_rank(round_idx)
        if flip is not None:
            print(f"FAULT: bitflip_params corrupting replica {flip}'s "
                  f"params at round {round_idx}", file=sys.stderr,
                  flush=True)
            self._inject_bitflip(flip)
        audit_fps = None
        if (self.config.audit_every
                and round_idx % self.config.audit_every == 0):
            if lag:
                # fingerprints are computed over the PRE-round params (the
                # invariant the audit checks) but stay on-device; the
                # verdict is harvested with the round's loss
                if self._audit_fn is None:
                    self._audit_fn = self._build_audit()
                audit_fps = self._audit_fn(self.params)
            else:
                t0 = time.perf_counter()
                fps = self.audit_params()
                self.stall_s["audit_fetch"] += time.perf_counter() - t0
                if not self._audit_ok(fps):
                    # round dropped BEFORE it runs; self.round rewinds to
                    # the rollback point, so a while-trainer.round driver
                    # replays
                    self._audit_trip(round_idx, fps)
                    return float("nan")
                self._last_audit_ok = round_idx
        # deterministic chaos hook: poison THIS rank's feed with NaNs (the
        # guard must catch the poison after averaging, no matter which
        # rank produced it — exactly a flaky-HBM / bad-DMA event)
        if faults.get_injector().nan_inject(round_idx):
            print(f"FAULT: nan_inject poisoning round {round_idx} feed",
                  file=sys.stderr, flush=True)
            batches = {
                k: (np.full_like(v, np.nan)
                    if np.issubdtype(np.asarray(v).dtype, np.floating)
                    else v)
                for k, v in batches.items()}
        # pre-shard the feed so each device receives only its slice — no
        # single-device staging (the reference's driver bottleneck); a no-op
        # for feeds already staged via device_feed(input_sharding)
        batches = {k: stage_local(v, self.input_sharding)
                   for k, v in batches.items()}
        self._rng, rng = jax.random.split(self._rng)
        if self._comm is not None:
            loss = self._run_comm_round(batches, rng)
        else:
            self.params, self.state, loss = self._round(
                self.params, self.state, jnp.asarray(self.iter), batches,
                rng, jnp.asarray(self.lr_scale, jnp.float32))
        if lag:
            # zero-stall path: loss + finite verdict stay on-device; the
            # dispatch returns immediately and the verdicts are harvested
            # up to ``lag`` rounds later (below)
            finite = (self._finite_fn()(self.params)
                      if self.config.guard_numerics else None)
            self._pending.append({"round": round_idx, "loss": loss,
                                  "finite": finite, "fps": audit_fps})
            loss_val = float("nan")
        else:
            t0 = time.perf_counter()
            loss_val = float(loss)
            self.stall_s["loss_fetch"] += time.perf_counter() - t0
            if self.config.guard_numerics:
                reason = self._poison_reason(loss_val)
                if reason:
                    self._guard_trip(round_idx, reason)
                    return loss_val   # round dropped; self.round unchanged
                self._loss_history = (self._loss_history + [loss_val])[-8:]
            self.round_losses[round_idx] = loss_val
        prev = self.iter
        self.iter += self.config.tau
        # snapshot-on-schedule at round granularity (Solver::Step checks per
        # iter, reference: solver.cpp:270-277; a compiled round cannot stop
        # mid-scan, so the schedule fires when a boundary was crossed)
        if (self.sp.snapshot and self.sp.snapshot_prefix
                and prev // self.sp.snapshot != self.iter // self.sp.snapshot):
            self.snapshot(f"{self.sp.snapshot_prefix}_iter_{self.iter}.npz")
        self.round += 1
        if (self.config.checkpoint_dir
                and self.round % self.config.checkpoint_every == 0):
            self.save_round_checkpoint()
        health.maybe_beat(round_idx, "round_end", extras=self._beat_extras())
        if lag:
            # keep at most ``lag`` rounds in flight: harvesting the
            # overflow is the ONLY place the steady-state loop can block,
            # and with a healthy device it blocks on a round dispatched
            # K rounds ago — long since finished
            while len(self._pending) > lag:
                h = self._harvest_one()
                if h is not None:
                    loss_val = h
        return loss_val

    def _beat_extras(self) -> dict:
        """Telemetry riding the round_end heartbeat: per-component host
        stalls, trip counters, and the feed pipeline's stats — the fleet
        status view's only window into a running job."""
        extras = {
            "stall_s": {k: round(v, 4) for k, v in self.stall_s.items()},
            "guard_trips": self.guard_trips,
            "audit_trips": self.audit_trips,
        }
        if self.feed_stats is not None:
            extras["feed"] = self.feed_stats.snapshot()
        return extras

    # -- numerical-integrity guard (see TrainerConfig.guard_numerics) -----
    def _finite_fn(self):
        """The jitted all-leaves-finite reduction over the float leaves
        of a (replicated) pytree — one fused pass producing one device
        scalar (fetched immediately on the sync path, harvested late on
        the deferred path)."""
        if self._finite_check is None:
            def check(t):
                leaves = [jnp.all(jnp.isfinite(x))
                          for x in jax.tree_util.tree_leaves(t)
                          if jnp.issubdtype(x.dtype, jnp.floating)]
                return (jnp.all(jnp.stack(leaves)) if leaves
                        else jnp.asarray(True))
            self._finite_check = jax.jit(check)
        return self._finite_check

    def _all_finite(self, tree) -> bool:
        t0 = time.perf_counter()
        out = bool(self._finite_fn()(tree))
        self.stall_s["finite_check"] += time.perf_counter() - t0
        return out

    def _loss_poison_reason(self, loss_val: float) -> str | None:
        """The host-only half of the verdict: non-finite or spiking
        loss.  Shared by the synchronous check and the deferred harvest
        (where the params verdict arrives separately, as the round's own
        pre-computed finite flag)."""
        if not np.isfinite(loss_val):
            return f"non-finite loss {loss_val}"
        factor = self.config.loss_spike_factor
        if factor > 0 and len(self._loss_history) >= 3:
            mean = sum(self._loss_history) / len(self._loss_history)
            if loss_val > factor * mean:
                return (f"loss spike {loss_val:.4g} > {factor:g} x "
                        f"trailing mean {mean:.4g}")
        return None

    def _poison_reason(self, loss_val: float) -> str | None:
        """Why the just-finished round should be rejected, or None."""
        reason = self._loss_poison_reason(loss_val)
        if reason:
            return reason
        if not self._all_finite(self.params):
            return "non-finite parameters after averaging"
        return None

    def _guard_trip(self, round_idx: int, reason: str) -> None:
        """Reject round ``round_idx``: roll back to the newest valid
        checkpoint at or before it (params/state/iter/round/RNG all
        restored, so the replay is exact), optionally back off the LR,
        and count the trip.  The ``max_round`` bound is what keeps the
        deferred-harvest path safe: under a harvest lag, checkpoints for
        rounds AFTER the poisoned one may already exist (and carry the
        poison) — they must not be rollback targets.  On the synchronous
        path no newer checkpoint can exist yet, so the bound is inert.
        All processes take this path together — the decision derives
        from replicated values, so no collective can diverge."""
        self.guard_trips += 1
        self._m_guard.inc()
        rec = telemetry.get_recorder()
        rec.record("guard_trip", round=round_idx, reason=reason,
                   trips=self.guard_trips)
        rec.dump("guard_trip")
        print(f"guard: round {round_idx} REJECTED ({reason}); rolling "
              f"back to last valid checkpoint at round <= {round_idx} "
              f"(trip {self.guard_trips}/{self.config.guard_max_trips})",
              file=sys.stderr, flush=True)
        if self.guard_trips > self.config.guard_max_trips:
            raise TrainingDivergedError(
                f"numerical guard tripped {self.guard_trips} times "
                f"(> guard_max_trips={self.config.guard_max_trips}); "
                f"last reason: {reason}")
        manifest = self.resume_latest(self.config.checkpoint_dir,
                                      max_round=round_idx)
        if manifest is None:
            raise TrainingDivergedError(
                f"round {round_idx} poisoned ({reason}) and no valid "
                f"checkpoint at round <= {round_idx} to roll back to in "
                f"{self.config.checkpoint_dir!r}")
        if self.config.guard_lr_backoff != 1.0:
            self.lr_scale *= self.config.guard_lr_backoff
            print(f"guard: LR scale backed off to {self.lr_scale:g}",
                  file=sys.stderr, flush=True)

    # -- deferred harvesting (see TrainerConfig.harvest_lag) --------------
    def _harvest_one(self) -> float | None:
        with telemetry.span("trainer.harvest", cat="trainer",
                            round=int(self._pending[0]["round"])):
            return self._harvest_one_impl()

    def _harvest_one_impl(self) -> float | None:
        """Resolve the OLDEST in-flight round: fetch its audit verdict,
        loss, and finite-check (in that order — the audit inspected the
        params the round STARTED from, so its verdict comes first, as on
        the synchronous path).  A trip discards every younger in-flight
        round (their inputs descend from the poisoned state), flushes
        the checkpoint writer so the rollback scan sees a settled disk,
        rolls back, and prunes now-invalid newer checkpoints.  Returns
        the harvested loss (poisoned losses included, for logging), or
        None when the round was dropped by the audit before it counted."""
        e = self._pending.popleft()
        round_idx = int(e["round"])
        if e["fps"] is not None:
            t0 = time.perf_counter()
            fps = np.asarray(e["fps"])
            self.stall_s["audit_fetch"] += time.perf_counter() - t0
            if not self._audit_ok(fps):
                self._pending.clear()
                self.flush_checkpoints()
                self._audit_trip(round_idx, fps)
                self._drop_checkpoints_after(self.round)
                return None
            self._last_audit_ok = round_idx
        t0 = time.perf_counter()
        loss_val = float(e["loss"])
        self.stall_s["loss_fetch"] += time.perf_counter() - t0
        if self.config.guard_numerics:
            reason = self._loss_poison_reason(loss_val)
            if reason is None and e["finite"] is not None:
                t0 = time.perf_counter()
                finite = bool(e["finite"])
                self.stall_s["finite_check"] += time.perf_counter() - t0
                if not finite:
                    reason = "non-finite parameters after averaging"
            if reason:
                self._pending.clear()
                self.flush_checkpoints()
                self._guard_trip(round_idx, reason)
                self._drop_checkpoints_after(self.round)
                return loss_val
            self._loss_history = (self._loss_history + [loss_val])[-8:]
        self.round_losses[round_idx] = loss_val
        return loss_val

    def drain(self) -> dict[int, float]:
        """Harvest every in-flight round verdict and flush the async
        checkpoint writer — the end-of-loop (and pre-eval) barrier for
        pipelined training.  After this, ``self.params`` is a validated
        state and every scheduled checkpoint is durable.  Returns the
        per-round harvested losses (``self.round_losses``).

        NOTE a deferred verdict can TRIP here, after the driver's round
        loop already exited: the rollback rewinds ``self.round``, so a
        driver that wants the dropped rounds replayed must re-enter its
        ``while trainer.round < rounds`` loop until the target holds
        after drain (see tests/multihost_driver.py)."""
        while self._pending:
            self._harvest_one()
        self.flush_checkpoints()
        return dict(self.round_losses)

    def flush_checkpoints(self) -> None:
        """Durability barrier over this trainer's async checkpoint
        writes; re-raises any background write failure.  A no-op on the
        synchronous path."""
        if self._ckpt_writer is not None:
            t0 = time.perf_counter()
            try:
                self._ckpt_writer.flush()
            finally:
                self.stall_s["checkpoint"] += time.perf_counter() - t0

    def _drop_checkpoints_after(self, round_idx: int) -> None:
        """Remove checkpoints NEWER than ``round_idx`` — after a deferred
        trip rolled back, snapshots taken during the detection lag
        descend from the poisoned state and must not survive as future
        rollback targets.  (The replay re-writes those round boundaries
        with clean state.)  Process 0 only; inert on the synchronous
        path, where no newer checkpoint can exist at trip time."""
        directory = self.config.checkpoint_dir
        if not directory or jax.process_index() != 0:
            return
        for mpath in glob.glob(os.path.join(directory, "manifest_*.json")):
            r = _manifest_round(mpath)
            if r > round_idx:
                # the glob sweeps per-shard tiles along with the main npz
                for p in (mpath, *glob.glob(os.path.join(
                        directory, f"ckpt_round_{r:08d}*.npz"))):
                    try:
                        os.remove(p)
                    except OSError:
                        pass

    # -- cross-replica parameter audit (see TrainerConfig.audit_every) ----
    def _build_audit(self):
        """Compile the fingerprint collective: each replica bit-casts its
        float param leaves to uint32 and tree-sums them (mod 2**32 — any
        single flipped bit changes the sum), then one all_gather over the
        batch axes returns every replica's fingerprint, replicated, so
        all processes reach the same verdict without extra traffic.

        Under a shard plan each position holds full copies of the
        replicated leaves but only ITS shard of the sharded ones, so one
        scalar per position can no longer be compared mesh-wide.  The
        fingerprint becomes a [n_pos, 2] matrix — column 0 sums the
        replicated leaves (must be unanimous mesh-wide, as before),
        column 1 sums the resident shard content (one uint32 per shard,
        gathered in the same single all_gather; compared within the
        groups of positions that hold the same shard — see
        ``_audit_culprits``)."""
        axes = self._batch_axes
        plan = self.shard_plan

        def leaf_sum(leaf):
            if jnp.issubdtype(leaf.dtype, jnp.floating):
                f32 = (leaf if leaf.dtype == jnp.float32
                       else leaf.astype(jnp.float32))
                bits = lax.bitcast_convert_type(f32, jnp.uint32)
            elif jnp.issubdtype(leaf.dtype, jnp.integer):
                bits = leaf.astype(jnp.uint32)
            else:
                return None
            return jnp.sum(bits, dtype=jnp.uint32)

        def fingerprint(params):
            if plan is None:
                total = jnp.zeros((), jnp.uint32)
                for leaf in jax.tree_util.tree_leaves(params):
                    s = leaf_sum(leaf)
                    if s is not None:
                        total = total + s
                return lax.all_gather(total, axes).reshape(-1)
            total_rep = jnp.zeros((), jnp.uint32)
            total_shard = jnp.zeros((), jnp.uint32)
            for name, blobs in params.items():
                for i, leaf in enumerate(blobs):
                    s = leaf_sum(leaf)
                    if s is None:
                        continue
                    if plan.dim_of(f"{name}/{i}") is None:
                        total_rep = total_rep + s
                    else:
                        total_shard = total_shard + s
            pair = jnp.stack([total_rep, total_shard])
            return lax.all_gather(pair, axes).reshape(-1, 2)

        params_spec = (P() if plan is None
                       else plan.spec_tree(self.params))
        mapped = shard_map(fingerprint, mesh=self.mesh,
                           in_specs=(params_spec,),
                           out_specs=P(), **_SM_NOCHECK)
        return jax.jit(mapped)

    def _audit_groups(self) -> list[list[int]]:
        """Mesh positions (flattened in batch-axes order) that hold
        identical shard content: on the pod mesh every host replicates
        each chip's shard (group = one chip column across hosts); on a
        flat mesh every position owns a distinct shard (singleton
        groups — the shard column is then self-consistent by definition
        and only the replicated column can trip)."""
        if self.config.strategy == "hierarchical":
            return [[h * self.n_chips + c for h in range(self.n_hosts)]
                    for c in range(self.n_chips)]
        return [[i] for i in range(self.n_workers)]

    def _audit_culprits(self, fps: np.ndarray) -> list[int]:
        """Positions whose fingerprints disagree with their comparison
        group's majority.  1-D fps = the replicated-params legacy shape
        (one scalar per position, one mesh-wide group); 2-D fps = the
        sharded shape (column 0 mesh-wide, column 1 per shard group)."""
        fps = np.asarray(fps)
        if fps.ndim == 1:
            checks = [(list(range(fps.shape[0])), fps)]
        else:
            checks = [(list(range(fps.shape[0])), fps[:, 0])]
            checks += [(g, fps[:, 1]) for g in self._audit_groups()]
        culprits: set[int] = set()
        for group, col in checks:
            sel = col[group]
            vals, counts = np.unique(sel, return_counts=True)
            if vals.size <= 1:
                continue
            majority = vals[int(np.argmax(counts))]
            culprits.update(g for g, f in zip(group, sel) if f != majority)
        return sorted(culprits)

    def _audit_ok(self, fps) -> bool:
        return not self._audit_culprits(np.asarray(fps))

    def audit_params(self) -> np.ndarray:
        """Per-replica parameter fingerprints, one uint32 per mesh
        position (replicas of a healthy mesh all return the same value —
        the replication invariant, made checkable)."""
        if self._audit_fn is None:
            self._audit_fn = self._build_audit()
        return np.asarray(self._audit_fn(self.params))

    def _audit_trip(self, round_idx: int, fps: np.ndarray) -> None:
        """A replica's params diverged: roll back to the newest
        checkpoint at or before the last PASSED audit (that state was
        verified consistent; anything newer may carry the rot) — the
        guard's rollback path, RNG replay and all."""
        self.audit_trips += 1
        self.guard_trips += 1
        fps = np.asarray(fps)
        culprits = self._audit_culprits(fps)
        fps_hex = [hex(int(f)) for f in fps.reshape(-1)]
        self._m_audit.inc()
        rec = telemetry.get_recorder()
        rec.record("audit_mismatch", round=round_idx, culprits=culprits,
                   fingerprints=fps_hex,
                   last_ok=self._last_audit_ok)
        rec.dump("audit_mismatch")
        print(f"audit: round {round_idx} REJECTED — cross-replica param "
              f"fingerprints diverge (replicas {culprits} vs the "
              f"majority: {fps_hex}); rolling back to "
              f"a round <= {self._last_audit_ok} checkpoint "
              f"(trip {self.guard_trips}/{self.config.guard_max_trips})",
              file=sys.stderr, flush=True)
        if self.guard_trips > self.config.guard_max_trips:
            raise TrainingDivergedError(
                f"audit tripped at round {round_idx} and the trip budget "
                f"is spent ({self.guard_trips} > guard_max_trips="
                f"{self.config.guard_max_trips}); replicas {culprits} "
                f"keep diverging")
        manifest = self.resume_latest(self.config.checkpoint_dir,
                                      max_round=self._last_audit_ok)
        if manifest is None:
            raise TrainingDivergedError(
                f"round {round_idx}: replicas {culprits} diverged and no "
                f"checkpoint at round <= {self._last_audit_ok} remains "
                f"in {self.config.checkpoint_dir!r}")

    def _inject_bitflip(self, replica: int) -> None:
        """Chaos hook (``bitflip_params@rank:R@round:N``): flip one
        mantissa bit in replica ``replica``'s resident copy of the first
        non-empty param leaf — the replicas now disagree by one bit,
        exactly what a flaky HBM cell produces.  The flipped value stays
        finite, so the numerical guard can NOT catch it; only the audit
        can.  Multi-host: each process flips only the shard it owns."""
        target = tuple(self.mesh.devices.flat)[replica % self.n_workers]
        leaf = None
        for name in sorted(self.params):
            blobs = self.params[name]
            if blobs and blobs[0].size and blobs[0].dtype == jnp.float32:
                leaf = blobs[0]
                break
        if leaf is None:
            return
        arrays = []
        for shard in leaf.addressable_shards:
            data = np.asarray(shard.data)
            if shard.device == target:
                data = np.array(data)       # writable copy
                flat = data.reshape(-1).view(np.uint32)
                flat[0] ^= np.uint32(1 << 22)
            arrays.append(jax.device_put(data, shard.device))
        self.params[name][0] = jax.make_array_from_single_device_arrays(
            leaf.shape, leaf.sharding, arrays)

    def test(self, feed: Iterator[Mapping[str, Any]], num_steps: int,
             ) -> dict[str, Any]:
        """Distributed eval, the zipPartitions contract made SPMD
        (reference: ImageNetApp.scala:108-141): every worker scores ITS
        batch rows independently (net.test() per partition), the
        per-worker scores are masked by a validity flag and psum'd.

        Feed batches may carry ``"__valid__"`` — a float (local_workers,)
        0/1 mask — so partitions of UNEQUAL size eval with reference
        semantics: exhausted workers feed padding rows with valid=0 and
        contribute nothing, exactly like a zipPartitions worker whose
        ``len`` ran out.  Returned totals are RAW sums over worker-batches
        (the reference's accumulated ``v``); ``totals["__test_batches__"]``
        counts the valid worker-batches, so ``score = totals[k] /
        totals["__test_batches__"]`` is the reference's ``100F·v /
        numTestMinibatches`` normalization (ImageNetApp.scala:139-140)."""
        if self._test_fwd is None:
            net = self.test_net
            # per-blob batch-axis decision from producing-layer metadata
            # (LayerImpl.top_has_batch_axis) — NOT from a runtime shape
            # coincidence: a per-class accuracy vector whose length equals
            # the batch must stay element-wise
            has_batch_axis: dict[str, bool] = {}
            for node in net.nodes:
                for i, t in enumerate(node.tops):
                    has_batch_axis[t] = node.impl.top_has_batch_axis(
                        node.lp, i)

            plan = self.shard_plan

            def worker(params, batch, valid):
                # one zipPartitions worker: score the local rows, zero out
                # invalid (padding) batches, sum across the mesh — the
                # result is replicated so every host can fetch it
                if plan is not None:
                    # widen resident shards to full leaves for the
                    # forward (tiled all_gather — exact)
                    params = plan.gather(params)
                out = net.apply(params, batch, train=False)
                v = valid[0]

                def reduce(k, val):
                    if val.ndim and has_batch_axis.get(k, True):
                        val = jnp.sum(val, axis=0)
                    return val * v
                scores = {k: reduce(k, val) for k, val in out.blobs.items()}
                scores["__test_batches__"] = v
                return jax.tree_util.tree_map(
                    lambda t: lax.psum(t, self._batch_axes), scores)

            params_spec = (P() if plan is None
                           else plan.spec_tree(self.params))
            self._test_fwd = jax.jit(shard_map(
                worker, mesh=self.mesh,
                in_specs=(params_spec, P(self._batch_axes),
                          P(self._batch_axes)),
                out_specs=P(), **_SM_NOCHECK))
        sharding = NamedSharding(self.mesh, P(self._batch_axes))
        local_workers = max(self.n_workers // jax.process_count(), 1)
        totals: dict[str, Any] = {}
        last_raw: dict[str, Any] | None = None
        for _ in range(num_steps):
            batch = {}
            try:
                raw = dict(next(feed))
            except StopIteration:
                # every step is a collective, so all hosts must take the
                # same num_steps (pass the global max — cluster.global_max);
                # a host whose local feed ran out keeps participating with
                # fully-invalid padding steps
                if last_raw is None:
                    raise ValueError(
                        "eval feed yielded no batches but num_steps > 0")
                raw = dict(last_raw)
                raw["__valid__"] = np.zeros(local_workers, np.float32)
            valid = np.asarray(raw.pop("__valid__",
                                       np.ones(local_workers)), np.float32)
            last_raw = dict(raw)
            if valid.shape != (local_workers,):
                raise ValueError(
                    f"__valid__ must have shape ({local_workers},) — one "
                    f"flag per local worker — got {valid.shape}")
            for k, v in raw.items():
                if v.shape[0] % local_workers:
                    raise ValueError(
                        f"{k}: eval batch {v.shape[0]} not divisible by "
                        f"{local_workers} local workers")
                batch[k] = stage_local(v, sharding)
            scores = self._test_fwd(self.params, batch,
                                    stage_local(valid, sharding))
            for k, v in scores.items():
                val = float(v) if np.ndim(v) == 0 else np.asarray(v)
                totals[k] = val if k not in totals else totals[k] + val
        return totals

    # -- checkpoint (driver-side averaged weights + per-worker state;
    #    parity target per SURVEY.md §5 checkpoint/resume) ----------------
    def _host_blob(self) -> dict[str, Any]:
        """The full training state as a host-fetchable pytree.  Multi-host
        this is a COLLECTIVE (the sharded per-worker optimizer state is
        all-gathered to replicated) — every process must call it."""
        state = self.state
        if jax.process_count() > 1 and self.config.strategy != "sync":
            state = jax.jit(lambda t: t,
                            out_shardings=replicated(self.mesh))(state)
        params = self.params
        if self.shard_plan is not None:
            # blobs always carry FULL logical leaves: a restore at ANY
            # world size just re-slices per the new plan, which is what
            # keeps the elastic re-tile contract bit-exact.  (The
            # per-shard npz layout is a WRITE-side split of this same
            # full blob — see _save_round_checkpoint_impl.)
            params = jax.jit(lambda t: t,
                             out_shardings=replicated(self.mesh))(params)
        blob: dict[str, Any] = {
            "params": params,
            "state": state,
            "iter": self.iter,
            "round": self.round,
            "rng": np.asarray(self._rng),
            "strategy": self.config.strategy,
            "n_workers": self.n_workers,
            "lr_scale": np.float64(self.lr_scale),
        }
        if self.shard_plan is not None:
            blob["shard_plan"] = self.shard_plan_id  # provenance stamp
        if self.config.strategy == "hierarchical":
            blob["n_hosts"] = self.n_hosts  # state is per-host
        if self.comm_residual is not None:
            # error-feedback residuals are trainer state: a rollback (or
            # relaunch) that replayed params but dropped the residual
            # would silently discard deferred quantization error and
            # break the bit-exact-replay contract under lossy codecs
            res = self.comm_residual
            if jax.process_count() > 1:
                res = jax.jit(lambda t: t,
                              out_shardings=replicated(self.mesh))(res)
            blob["comm_residual"] = res
            blob["comm_codec"] = self.config.comm_codec
        return blob

    @staticmethod
    def _retier_state(state, new_n: int):
        """Re-tile stacked per-worker/per-host optimizer state saved with
        a DIFFERENT tier count: new row i inherits saved row i mod
        saved_n.  Shrinking drops the dead workers' rows; growing seeds a
        rejoined worker from an existing one — both keep the elastic
        continuation deterministic, which is what the bit-for-bit re-form
        contract needs (any fixed rule works; this one is stable under
        repeated shrink/grow)."""
        def fix(x):
            x = np.asarray(x)
            return x[np.arange(new_n) % x.shape[0]]
        return jax.tree_util.tree_map(fix, state)

    def _apply_blob(self, blob: Mapping[str, Any]) -> None:
        saved_strategy = str(np.asarray(blob.get("strategy", "")))
        saved_workers = int(blob["n_workers"]) if "n_workers" in blob else None
        if saved_strategy and saved_strategy != self.config.strategy:
            raise ValueError(
                f"checkpoint strategy {saved_strategy!r} != trainer "
                f"{self.config.strategy!r} (per-worker optimizer state is "
                f"not convertible)")
        elastic = self.config.elastic
        state = blob["state"]
        if saved_workers is not None and saved_workers != self.n_workers:
            if not elastic:
                raise ValueError(
                    f"checkpoint has {saved_workers} workers, mesh has "
                    f"{self.n_workers} (set TrainerConfig.elastic=True to "
                    f"re-form on a different worker set)")
            print(f"elastic: re-forming {saved_workers} -> "
                  f"{self.n_workers} workers (params are the consensus "
                  f"average; stacked optimizer state re-tiled)",
                  file=sys.stderr, flush=True)
            if self.config.strategy == "local_sgd":
                state = self._retier_state(state, self.n_workers)
        if self.config.strategy == "hierarchical" and "n_hosts" in blob:
            saved_hosts = int(blob["n_hosts"])
            if saved_hosts != self.n_hosts:
                if not elastic:
                    raise ValueError(
                        f"checkpoint has {saved_hosts} hosts, mesh has "
                        f"{self.n_hosts} (per-host optimizer state does "
                        f"not re-tile; set TrainerConfig.elastic=True)")
                state = self._retier_state(state, self.n_hosts)
        rep = replicated(self.mesh)
        # full logical params land in this trainer's resident placement:
        # under a shard plan each leaf is sliced per-device by its
        # NamedSharding (put_global's callback), which IS the elastic
        # re-tile — deterministic, arithmetic-free, world-size agnostic
        self.params = put_global_tree(blob["params"], self._params_sharding)
        if self.config.strategy == "sync":
            self.state = put_global_tree(state, rep)
        else:
            self.state = put_global_tree(
                state,
                NamedSharding(self.mesh, self._state_tier()[1]))
        if self.comm_residual is not None:
            n_tier, tier_spec = self._state_tier()
            saved_codec = str(np.asarray(blob.get("comm_codec", "")))
            if "comm_residual" in blob and (
                    saved_codec == self.config.comm_codec):
                res = blob["comm_residual"]
                saved_n = len(jax.tree_util.tree_leaves(res)) and int(
                    jax.tree_util.tree_leaves(res)[0].shape[0])
                if saved_n != n_tier:
                    # same elastic contract as stacked optimizer state:
                    # surviving tier row i inherits saved row i mod saved_n
                    res = self._retier_state(res, n_tier)
                self.comm_residual = put_global_tree(
                    res, NamedSharding(self.mesh, tier_spec))
            else:
                # pre-codec checkpoint (or codec changed): the saved
                # residual is meaningless on this wire format — start
                # error feedback fresh (safe: EF state is an optimization
                # of future rounds, never a correctness input)
                if saved_codec and saved_codec != self.config.comm_codec:
                    print(f"resume: checkpoint residuals are for codec "
                          f"{saved_codec!r}, trainer runs "
                          f"{self.config.comm_codec!r} — resetting error "
                          f"feedback", file=sys.stderr, flush=True)
                self.comm_residual = put_global_tree(
                    jax.tree_util.tree_map(
                        lambda x: np.zeros((n_tier,) + tuple(x.shape),
                                           np.float32), blob["params"]),
                    NamedSharding(self.mesh, tier_spec))
        self.iter = int(blob["iter"])
        if "round" in blob:
            self.round = int(blob["round"])
        if "rng" in blob:
            self._rng = jnp.asarray(blob["rng"])
        if "lr_scale" in blob:
            self.lr_scale = float(np.asarray(blob["lr_scale"]))

    def snapshot(self, path: str) -> None:
        from ..utils.checkpoint import save_checkpoint
        save_checkpoint(path, self._host_blob())

    def restore(self, path: str) -> None:
        from ..utils.checkpoint import load_checkpoint
        self._apply_blob(load_checkpoint(path))

    # -- round-granular checkpoint/resume (the recovery half of the
    #    reference's Spark fault-tolerance story; see TrainerConfig) ------
    def _async_ckpt_enabled(self) -> bool:
        from ..utils.checkpoint import async_checkpoints_enabled
        return self.config.async_checkpoint and async_checkpoints_enabled()

    def save_round_checkpoint(self, directory: str | None = None) -> str | None:
        """Write checkpoint + manifest for the current round.  All
        processes must call (the state fetch is a collective); only
        process 0 touches disk.  Returns the checkpoint path on process 0,
        None elsewhere.

        With async checkpointing on (the default; see
        ``TrainerConfig.async_checkpoint``) the durable write — npz
        serialize, sha256, manifest tmp+rename, prune — runs on a
        background writer thread: this call only starts a non-blocking
        device→host snapshot and enqueues the job, so the next round can
        dispatch immediately.  The fault-injection hooks
        (``crash_in_ckpt``/``corrupt_ckpt``) fire inside the job at the
        same points in the write sequence, and ``flush_checkpoints()``
        is the barrier that restores strict durability where callers
        need it (rollback, preemption, end of run)."""
        with telemetry.span("trainer.ckpt_submit", cat="ckpt",
                            round=self.round):
            return self._save_round_checkpoint_impl(directory)

    def _save_round_checkpoint_impl(
            self, directory: str | None = None) -> str | None:
        from ..utils import faults, knobs
        from ..utils.checkpoint import (
            AsyncCheckpointWriter, CheckpointFencedError, advance_fence,
            check_fence, save_checkpoint, snapshot_tree,
        )
        directory = directory or self.config.checkpoint_dir
        if not directory:
            raise ValueError("no checkpoint directory configured")
        # pin the injector INSTANCE now: the write may run later on the
        # writer thread, and the fault decision belongs to the round that
        # scheduled it, not to whatever the env says at write time
        injector = faults.get_injector()
        t0 = time.perf_counter()
        blob = self._host_blob()
        if jax.process_index() != 0:
            return None
        os.makedirs(directory, exist_ok=True)
        # incarnation fencing: claim the dir with our launch-stamped
        # token (0 = unmanaged, fencing inert).  A zombie writer from a
        # fenced-off incarnation is refused HERE, before any bytes move
        fence_token = knobs.get_int("SPARKNET_FENCE_TOKEN", 0)
        if fence_token:
            advance_fence(directory, fence_token)
        # capture the round-scoped fields NOW — on the async path the
        # trainer's counters will have moved on by write time
        round_now, iter_now = self.round, self.iter
        name = f"ckpt_round_{round_now:08d}.npz"
        path = os.path.join(directory, name)
        manifest = {
            "round": round_now,
            "iter": iter_now,
            "file": name,
            "sha256": None,   # filled in after the npz lands
            "mesh_shape": {k: int(v) for k, v in self.mesh.shape.items()},
            "strategy": self.config.strategy,
            "n_workers": self.n_workers,
            "tau": self.config.tau,
            "data_cursor": self.data_cursor,
        }
        # per-shard checkpoint layout (TrainerConfig.shard_checkpoint):
        # sharded param leaves split into one npz tile per shard, the
        # main npz keeps everything else; the manifest pins every tile's
        # sha256 and the split dims, and appears LAST — so a torn multi-
        # file write is indistinguishable from no checkpoint at all
        plan = self.shard_plan
        shard_ckpt = plan is not None and self.config.shard_checkpoint
        shard_dims = plan.dims_dict() if shard_ckpt else None
        n_shards = plan.n_shards if shard_ckpt else 0
        if plan is not None:
            manifest["shard_plan"] = self.shard_plan_id

        def job() -> None:
            from ..utils.checkpoint import split_sharded_tree
            check_fence(directory, fence_token)
            shard_paths: list[str] = []
            if shard_ckpt:
                common, parts = split_sharded_tree(
                    jax.tree_util.tree_map(np.asarray, blob["params"]),
                    shard_dims, n_shards)
                save_checkpoint(path, {**blob, "params": common})
                shard_entries = []
                for k, part in enumerate(parts):
                    sname = f"ckpt_round_{round_now:08d}.shard{k:02d}.npz"
                    spath = os.path.join(directory, sname)
                    save_checkpoint(spath, part)
                    shard_paths.append(spath)
                    shard_entries.append(
                        {"file": sname, "sha256": _sha256_file(spath)})
                manifest["shards"] = shard_entries
                manifest["shard_dims"] = shard_dims
            else:
                save_checkpoint(path, blob)
            # torn-write chaos window: the npz is durable, the manifest is
            # not yet — crash_in_ckpt kills HERE; resume must treat the
            # orphan npz as if the checkpoint never happened
            injector.on_checkpoint_write(round_now)
            # deterministic chaos hook: scribble the snapshot AFTER it
            # exists (and before/after the manifest — both orders must be
            # survivable; we corrupt after so the manifest's checksum
            # catches it)
            corrupt = injector.corrupt_checkpoint(round_now)
            manifest["sha256"] = _sha256_file(path)
            manifest["fence_token"] = fence_token
            mpath = os.path.join(directory,
                                 f"manifest_{round_now:08d}.json")
            # unique temp name (pid-stamped): a crashed writer's leftover
            # can never collide with — or be half-overwritten into — a
            # live write
            tmp = f"{mpath}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(manifest, f, indent=1)
            # rename-time fence: the LAST gate before the checkpoint
            # becomes visible.  A successor may have claimed the dir
            # while our npz was in flight (the zombie-writer window) —
            # refuse, and leave zero new state behind
            try:
                check_fence(directory, fence_token)
            except CheckpointFencedError:
                for p in (tmp, path, *shard_paths):
                    try:
                        os.remove(p)
                    except OSError:
                        pass
                raise
            os.replace(tmp, mpath)  # manifest appears atomically, last
            if corrupt:
                print(f"FAULT: corrupt_ckpt scribbling {path}",
                      file=sys.stderr, flush=True)
                faults.scribble(path)
            self._prune_checkpoints(directory)

        if self._async_ckpt_enabled():
            # alias-free device copy + async d2h start; the job's
            # np.asarray then lands on a transfer already in flight
            blob = snapshot_tree(blob)
            if self._ckpt_writer is None:
                self._ckpt_writer = AsyncCheckpointWriter()
            self._ckpt_writer.submit(job)
        else:
            job()
        self.stall_s["checkpoint"] += time.perf_counter() - t0
        return path

    def _prune_checkpoints(self, directory: str) -> None:
        keep = max(int(self.config.checkpoint_keep), 1)
        rounds = sorted(
            (_manifest_round(m) for m in
             glob.glob(os.path.join(directory, "manifest_*.json"))),
            reverse=True)
        for r in rounds[keep:]:
            # the glob sweeps per-shard tiles along with the main npz
            for p in (os.path.join(directory, f"manifest_{r:08d}.json"),
                      *glob.glob(os.path.join(
                          directory, f"ckpt_round_{r:08d}*.npz"))):
                try:
                    os.remove(p)
                except OSError:
                    pass
        # sweep temp droppings from writers killed mid-write (ours are
        # already renamed away by now, so anything *.tmp.* is an orphan)
        for p in glob.glob(os.path.join(directory, "*.tmp.*")):
            try:
                os.remove(p)
            except OSError:
                pass

    def resume_latest(self, directory: str,
                      max_round: int | None = None) -> dict[str, Any] | None:
        """Restore from the newest manifest whose checkpoint validates
        (file sha256 against the manifest, then the in-file content
        checksum).  Corrupt or partial snapshots are skipped with a
        warning, falling back to the next-older manifest; a checkpoint
        from an INCOMPATIBLE config (strategy/mesh mismatch) raises — that
        is a config error, not corruption.  ``max_round`` bounds the
        search (the audit's rollback horizon: newer checkpoints may carry
        an unverified divergence).  Returns the manifest resumed from, or
        None when no valid checkpoint exists."""
        from ..utils import knobs
        from ..utils.checkpoint import (
            CheckpointError, advance_fence, flush_all_writers,
            load_checkpoint,
        )
        # async tier: settle every in-flight background write (this
        # trainer's AND any other live instance writing the same
        # directory) before scanning — the newest manifest must not be
        # sitting in a writer queue when we look for it
        flush_all_writers()
        # claim the dir for OUR incarnation before reading: from here a
        # zombie writer from a fenced-off predecessor refuses at its
        # next fence check instead of clobbering what we resume from
        fence_token = knobs.get_int("SPARKNET_FENCE_TOKEN", 0)
        if fence_token and os.path.isdir(directory):
            advance_fence(directory, fence_token)
        for mpath in sorted(
                glob.glob(os.path.join(directory, "manifest_*.json")),
                key=_manifest_round, reverse=True):
            if max_round is not None and _manifest_round(mpath) > max_round:
                continue
            try:
                with open(mpath) as f:
                    manifest = json.load(f)
                path = os.path.join(directory, manifest["file"])
                got = _sha256_file(path)
                if got != manifest["sha256"]:
                    raise CheckpointError(
                        f"manifest sha256 mismatch (manifest "
                        f"{manifest['sha256'][:12]}…, file {got[:12]}…)",
                        path)
                blob = load_checkpoint(path)
                shard_entries = manifest.get("shards") or []
                if shard_entries:
                    # per-shard layout: verify every tile against the
                    # manifest, then join back to full logical leaves (a
                    # corrupt/missing tile fails the WHOLE checkpoint —
                    # fall through to the next-older manifest)
                    from ..utils.checkpoint import join_sharded_tree
                    parts = []
                    for s in shard_entries:
                        spath = os.path.join(directory, s["file"])
                        sgot = _sha256_file(spath)
                        if sgot != s["sha256"]:
                            raise CheckpointError(
                                f"shard sha256 mismatch (manifest "
                                f"{s['sha256'][:12]}…, file "
                                f"{sgot[:12]}…)", spath)
                        parts.append(load_checkpoint(spath))
                    blob["params"] = join_sharded_tree(
                        blob["params"], parts,
                        manifest.get("shard_dims") or {})
            except (OSError, json.JSONDecodeError, KeyError,
                    CheckpointError) as e:
                print(f"resume: skipping {os.path.basename(mpath)}: {e}",
                      file=sys.stderr, flush=True)
                continue
            mesh_shape = manifest.get("mesh_shape")
            if mesh_shape and mesh_shape != {
                    k: int(v) for k, v in self.mesh.shape.items()}:
                if not self.config.elastic:
                    raise ValueError(
                        f"checkpoint mesh shape {mesh_shape} != trainer "
                        f"mesh {dict(self.mesh.shape)} (set TrainerConfig."
                        f"elastic=True to re-form on a different mesh)")
                print(f"elastic: resuming checkpoint of mesh {mesh_shape} "
                      f"on mesh {dict(self.mesh.shape)}",
                      file=sys.stderr, flush=True)
            self._apply_blob(blob)
            self.round = int(manifest.get("round", self.round))
            self.data_cursor = manifest.get("data_cursor")
            # the restore re-broadcasts params to every replica, so the
            # mesh is consistent by construction from here
            self._last_audit_ok = self.round
            telemetry.get_recorder().record(
                "resume", round=self.round, iter=self.iter,
                file=os.path.basename(manifest["file"]))
            print(f"resume: restored round {self.round} "
                  f"(iter {self.iter}) from "
                  f"{os.path.basename(manifest['file'])}",
                  file=sys.stderr, flush=True)
            return manifest
        return None


def _sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _manifest_round(path: str) -> int:
    stem = os.path.basename(path)
    try:
        return int(stem[len("manifest_"):-len(".json")])
    except ValueError:
        return -1
