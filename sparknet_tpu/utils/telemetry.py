"""Unified telemetry plane: metrics registry, span tracer, flight recorder.

Until now every subsystem reported through its own ad-hoc channel —
heartbeat ``extras``, ``FeedStats`` snapshots, ``stall_s`` dicts,
postmortem.json, serving latency stamps — and none of it could be joined
into one timeline.  Both Caffe con Troll (arXiv 1504.04343) and
Caffeinated FPGAs (arXiv 1609.09671) make the same argument from
opposite directions: with a fixed layer library, finding the next
throughput win requires measuring where the time actually goes, with
attribution.  This module is the shared substrate the instrumented seams
(trainer rounds, feed stages, checkpoint writes, restarts, fleet
decisions, serving batches) publish into:

- :class:`MetricsRegistry` — process-local counters / gauges /
  histograms with labels.  Lock-cheap (one lock per metric), rendered as
  Prometheus text exposition (``tools/serve.py`` serves it at
  ``GET /metrics``) and as JSON file snapshots for headless training
  jobs (``SPARKNET_METRICS_SNAP=dir`` — written atomically, throttled,
  plus a final write at exit; ``tools/fleet.py --status`` folds them).
- **Span tracer** — Chrome-trace-event JSONL shards (one per process,
  perfetto/chrome://tracing-loadable after ``tools/obs.py merge``),
  enabled by ``SPARKNET_TRACE_DIR=dir``.  Timestamps are epoch
  microseconds, so shards from different ranks of one run clock-align
  by construction (local rig / NTP-level agreement — the same
  assumption the health plane's beat ages already make).  Every event
  carries the correlation IDs that join the distributed story:
  ``run`` (SPARKNET_RUN_ID, else derived once per process), ``job``
  (SPARKNET_FLEET_JOB), ``inc`` (SPARKNET_INCARNATION), ``rank``
  (SPARKNET_PROC_ID), ``attempt`` (SPARKNET_FAULT_ATTEMPT).
- :class:`FlightRecorder` — a bounded ring of recent structured events
  (``SPARKNET_FLIGHT_EVENTS``, default 256).  The seams record guard
  trips, audit mismatches, rollbacks, feeder restarts, restarts and
  re-forms, fleet scheduling decisions, and SIGTERM receipt; ``dump()``
  writes the tail as JSON next to the trace shards at the moment
  something went wrong (the crash "black box"), and the fleet appends
  the tail into quarantine postmortems.

**Off switch:** ``SPARKNET_TELEMETRY=0`` makes the whole plane a no-op:
``get_registry()`` returns a null registry whose metrics are shared
singletons with pass methods, ``span()`` returns a shared null context
manager, and the recorder drops events — nothing is allocated per
round and no file is ever written.  Tracing additionally requires
``SPARKNET_TRACE_DIR`` even when telemetry is on, so the default
steady-state cost is a few counter increments per round.

Env knobs:
  SPARKNET_TELEMETRY      — "0" disables the whole plane (default on).
  SPARKNET_TRACE_DIR      — write trace_*.jsonl shards + flight dumps here.
  SPARKNET_METRICS_SNAP   — write metrics_rank*.json/.prom snapshots here.
  SPARKNET_METRICS_SNAP_S — min seconds between snapshots (default 2).
  SPARKNET_FLIGHT_EVENTS  — flight-recorder ring size (default 256).
  SPARKNET_RUN_ID         — correlation run id (default: derived).
"""

from __future__ import annotations

import atexit
import collections
import json
import os
import threading
import time
import weakref
from typing import Any, Callable, Iterable, Mapping

from . import knobs

ENV_ENABLE = "SPARKNET_TELEMETRY"
ENV_TRACE_DIR = "SPARKNET_TRACE_DIR"
ENV_SNAP_DIR = "SPARKNET_METRICS_SNAP"
ENV_SNAP_S = "SPARKNET_METRICS_SNAP_S"
ENV_FLIGHT = "SPARKNET_FLIGHT_EVENTS"
ENV_RUN_ID = "SPARKNET_RUN_ID"

# default latency buckets (seconds): sub-ms serving demux through
# multi-second checkpoint writes
DEFAULT_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                   0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)


def enabled() -> bool:
    """Whether the telemetry plane is on (``SPARKNET_TELEMETRY=0`` is
    the global off switch)."""
    return knobs.raw(ENV_ENABLE, "") != "0"


_DERIVED_RUN: str | None = None


def correlation_ids() -> dict[str, Any]:
    """The IDs that join one process's telemetry into the distributed
    story: run / fleet job / incarnation / rank / attempt.  Read from
    the env contract the launcher + fleet already maintain; ``run`` is
    derived once per process when SPARKNET_RUN_ID is absent, so even an
    un-launched local run correlates with itself.  A process that is
    NOT under the launcher (so must not set SPARKNET_PROC_ID — the
    cluster env contract validates the full triple) can still claim a
    distinct shard rank via SPARKNET_TELEMETRY_RANK, which wins."""
    global _DERIVED_RUN
    run = knobs.raw(ENV_RUN_ID)
    if not run:
        if _DERIVED_RUN is None:
            _DERIVED_RUN = f"run-{int(time.time()):x}-{os.getpid()}"
        run = _DERIVED_RUN
    out: dict[str, Any] = {
        "run": run,
        "rank": int(knobs.raw("SPARKNET_TELEMETRY_RANK")
                    or knobs.raw("SPARKNET_PROC_ID", "0") or 0),
        "inc": knobs.get_int("SPARKNET_INCARNATION", 0),
        "attempt": knobs.get_int("SPARKNET_FAULT_ATTEMPT", 0),
    }
    job = knobs.raw("SPARKNET_FLEET_JOB")
    if job:
        out["job"] = job
    return out


# ---------------------------------------------------------------------------
# Metrics
# ---------------------------------------------------------------------------

def _label_key(labels: Mapping[str, Any]) -> tuple:
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


def _escape_label(v: Any) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"')


def _render_labels(key: tuple, extra: str = "") -> str:
    parts = [f'{k}="{_escape_label(v)}"' for k, v in key]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


class _Metric:
    """Base: one named metric with per-labelset children, one lock."""

    kind = "untyped"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._lock = threading.Lock()

    # subclasses: _samples() -> iterable of (labelkey, payload)


class Counter(_Metric):
    kind = "counter"

    def __init__(self, name: str, help: str = ""):
        super().__init__(name, help)
        self._values: dict[tuple, float] = {}

    def inc(self, amount: float = 1.0, **labels) -> None:
        key = _label_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels) -> float:
        with self._lock:
            return self._values.get(_label_key(labels), 0.0)

    def _samples(self):
        with self._lock:
            return list(self._values.items())


class Gauge(_Metric):
    kind = "gauge"

    def __init__(self, name: str, help: str = ""):
        super().__init__(name, help)
        self._values: dict[tuple, float] = {}

    def set(self, value: float, **labels) -> None:
        with self._lock:
            self._values[_label_key(labels)] = float(value)

    def inc(self, amount: float = 1.0, **labels) -> None:
        key = _label_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def dec(self, amount: float = 1.0, **labels) -> None:
        self.inc(-amount, **labels)

    def value(self, **labels) -> float:
        with self._lock:
            return self._values.get(_label_key(labels), 0.0)

    def _samples(self):
        with self._lock:
            return list(self._values.items())


class Histogram(_Metric):
    """Prometheus-style cumulative-bucket histogram."""

    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 buckets: Iterable[float] | None = None):
        super().__init__(name, help)
        self.buckets = tuple(sorted(buckets or DEFAULT_BUCKETS))
        # labelkey -> [per-bucket counts..., +Inf count], sum
        self._counts: dict[tuple, list[int]] = {}
        self._sums: dict[tuple, float] = {}

    def observe(self, value: float, **labels) -> None:
        key = _label_key(labels)
        with self._lock:
            counts = self._counts.get(key)
            if counts is None:
                counts = self._counts[key] = [0] * (len(self.buckets) + 1)
                self._sums[key] = 0.0
            for i, b in enumerate(self.buckets):
                if value <= b:
                    counts[i] += 1
                    break
            else:
                counts[-1] += 1
            self._sums[key] += value

    class _Timer:
        __slots__ = ("_h", "_labels", "_t0")

        def __init__(self, h, labels):
            self._h, self._labels = h, labels

        def __enter__(self):
            self._t0 = time.perf_counter()
            return self

        def __exit__(self, *exc):
            self._h.observe(time.perf_counter() - self._t0, **self._labels)

    def time(self, **labels) -> "Histogram._Timer":
        return self._Timer(self, labels)

    def _samples(self):
        with self._lock:
            return [(k, (list(c), self._sums[k], sum(c)))
                    for k, c in self._counts.items()]


class _NullMetric:
    """Shared no-op stand-in for every metric kind: inc/set/observe all
    swallow their arguments, ``time()`` returns the shared null context
    manager — nothing is allocated, nothing is retained."""

    kind = "null"
    name = "null"

    def inc(self, *a, **kw) -> None:
        pass

    def dec(self, *a, **kw) -> None:
        pass

    def set(self, *a, **kw) -> None:
        pass

    def observe(self, *a, **kw) -> None:
        pass

    def value(self, *a, **kw) -> float:
        return 0.0

    def time(self, **kw):
        return NULL_SPAN


class MetricsRegistry:
    """Name -> metric, idempotent by name (re-asking for an existing
    metric returns the same object; a kind mismatch raises — two seams
    silently sharing one name as different types is a bug)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict[str, _Metric] = {}
        self._collectors: list = []   # weak refs to scrape-time fillers
        self._last_snap = 0.0

    def _get(self, cls, name: str, help: str, **kw) -> _Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if not isinstance(m, cls):
                    raise ValueError(
                        f"metric {name!r} already registered as {m.kind}, "
                        f"asked for {cls.kind}")
                return m
            m = cls(name, help, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get(Gauge, name, help)

    def histogram(self, name: str, help: str = "",
                  buckets: Iterable[float] | None = None) -> Histogram:
        return self._get(Histogram, name, help, buckets=buckets)

    def add_collector(self, fn: Callable[[], None]) -> None:
        """Register a scrape-time filler (called before render/snapshot
        to set point-in-time gauges).  Bound methods are held weakly so
        a dead owner silently unregisters."""
        try:
            ref = weakref.WeakMethod(fn)  # type: ignore[arg-type]
        except TypeError:
            ref = lambda f=fn: f          # plain function: strong, stable
        with self._lock:
            self._collectors.append(ref)

    def _collect(self) -> None:
        with self._lock:
            refs = list(self._collectors)
        live = []
        for ref in refs:
            fn = ref()
            if fn is None:
                continue
            live.append(ref)
            try:
                fn()
            except Exception:
                pass   # a broken collector must not break the scrape
        with self._lock:
            self._collectors = live

    # -- export -----------------------------------------------------------
    def render(self) -> str:
        """Prometheus text exposition (version 0.0.4)."""
        self._collect()
        with self._lock:
            metrics = sorted(self._metrics.items())
        lines: list[str] = []
        for name, m in metrics:
            if m.help:
                lines.append(f"# HELP {name} {m.help}")
            lines.append(f"# TYPE {name} {m.kind}")
            if isinstance(m, Histogram):
                for key, (counts, total, n) in m._samples():
                    cum = 0
                    for b, c in zip(m.buckets, counts):
                        cum += c
                        le = 'le="%g"' % b
                        lines.append(
                            f"{name}_bucket{_render_labels(key, le)} {cum}")
                    cum += counts[-1]
                    inf = 'le="+Inf"'
                    lines.append(
                        f"{name}_bucket{_render_labels(key, inf)} {cum}")
                    lines.append(f"{name}_sum{_render_labels(key)} {total:g}")
                    lines.append(f"{name}_count{_render_labels(key)} {n}")
            else:
                for key, v in m._samples():
                    lines.append(f"{name}{_render_labels(key)} {v:g}")
        return "\n".join(lines) + ("\n" if lines else "")

    def snapshot(self) -> dict[str, Any]:
        """JSON-able view of every metric (the file-snapshot payload)."""
        self._collect()
        with self._lock:
            metrics = sorted(self._metrics.items())
        out: dict[str, Any] = {}
        for name, m in metrics:
            if isinstance(m, Histogram):
                samples = [{"labels": dict(k), "buckets": list(m.buckets),
                            "counts": c, "sum": s, "count": n}
                           for k, (c, s, n) in m._samples()]
            else:
                samples = [{"labels": dict(k), "value": v}
                           for k, v in m._samples()]
            out[name] = {"kind": m.kind, "help": m.help, "samples": samples}
        return out

    def write_snapshot(self, directory: str | None = None) -> str | None:
        """Atomically write ``metrics_rank<R>.json`` (+ ``.prom`` text)
        into ``directory`` (default ``SPARKNET_METRICS_SNAP``); returns
        the json path, or None when no directory is configured."""
        directory = directory or knobs.raw(ENV_SNAP_DIR)
        if not directory:
            return None
        os.makedirs(directory, exist_ok=True)
        corr = correlation_ids()
        doc = {"t": round(time.time(), 3), **corr, "pid": os.getpid(),
               "metrics": self.snapshot()}
        path = os.path.join(directory, f"metrics_rank{corr['rank']}.json")
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
        ppath = os.path.join(directory, f"metrics_rank{corr['rank']}.prom")
        tmp = f"{ppath}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(self.render())
        os.replace(tmp, ppath)
        return path

    def maybe_snapshot(self) -> str | None:
        """Throttled :meth:`write_snapshot` — at most one write per
        ``SPARKNET_METRICS_SNAP_S`` seconds (default 2); a no-op when
        ``SPARKNET_METRICS_SNAP`` is unset.  The hot-loop-safe hook the
        trainer calls each round."""
        if not knobs.is_set(ENV_SNAP_DIR):
            return None
        try:
            min_s = float(knobs.raw(ENV_SNAP_S, "") or 2.0)
        except ValueError:
            min_s = 2.0
        now = time.monotonic()
        with self._lock:
            if now - self._last_snap < min_s:
                return None
            self._last_snap = now
        return self.write_snapshot()


class _NullRegistry:
    """The SPARKNET_TELEMETRY=0 registry: every ask returns the shared
    null metric, every export is empty, nothing is ever written."""

    def counter(self, name: str, help: str = "") -> _NullMetric:
        return NULL_METRIC

    def gauge(self, name: str, help: str = "") -> _NullMetric:
        return NULL_METRIC

    def histogram(self, name: str, help: str = "", buckets=None
                  ) -> _NullMetric:
        return NULL_METRIC

    def add_collector(self, fn) -> None:
        pass

    def render(self) -> str:
        return ""

    def snapshot(self) -> dict:
        return {}

    def write_snapshot(self, directory: str | None = None) -> None:
        return None

    def maybe_snapshot(self) -> None:
        return None


# ---------------------------------------------------------------------------
# Span tracer (Chrome trace events, JSONL shards)
# ---------------------------------------------------------------------------

class _NullSpan:
    """Shared no-op context manager — the disabled-tracing span."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NULL_SPAN = _NullSpan()
NULL_METRIC = _NullMetric()


class Tracer:
    """Buffered Chrome-trace-event writer: one JSONL shard per process
    (``trace_<run>_rank<R>_<pid>.jsonl``), events flushed every
    ``flush_every`` events and at exit.  Thread-safe; timestamps are
    epoch microseconds so independent ranks merge clock-aligned."""

    def __init__(self, directory: str, flush_every: int = 256):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        self.corr = correlation_ids()
        safe_run = "".join(c if c.isalnum() or c in "-_" else "-"
                           for c in str(self.corr["run"]))[:48]
        self.path = os.path.join(
            directory,
            f"trace_{safe_run}_rank{self.corr['rank']}_{os.getpid()}.jsonl")
        self._lock = threading.Lock()
        self._buf: list[str] = []
        self._flush_every = max(int(flush_every), 1)
        label = f"rank{self.corr['rank']}"
        if self.corr.get("job"):
            label += f" {self.corr['job']}"
        if self.corr.get("inc"):
            label += f" inc{self.corr['inc']}"
        self.emit({"name": "process_name", "ph": "M", "pid": os.getpid(),
                   "tid": 0, "args": {"name": label}})

    def emit(self, event: dict) -> None:
        line = json.dumps(event, default=str)
        with self._lock:
            self._buf.append(line)
            if len(self._buf) < self._flush_every:
                return
            buf, self._buf = self._buf, []
        self._write(buf)

    def _write(self, lines: list[str]) -> None:
        try:
            with open(self.path, "a") as f:
                f.write("\n".join(lines) + "\n")
        except OSError:
            pass   # an unwritable trace dir must never kill the workload

    def flush(self) -> None:
        with self._lock:
            buf, self._buf = self._buf, []
        if buf:
            self._write(buf)

    def complete(self, name: str, cat: str, ts_us: float, dur_us: float,
                 args: dict | None = None) -> None:
        ev_args = dict(self.corr)
        if args:
            ev_args.update(args)
        self.emit({"name": name, "cat": cat, "ph": "X",
                   "ts": int(ts_us), "dur": max(int(dur_us), 0),
                   "pid": os.getpid(), "tid": threading.get_ident() & 0xffff,
                   "args": ev_args})

    def instant(self, name: str, cat: str, args: dict | None = None) -> None:
        ev_args = dict(self.corr)
        if args:
            ev_args.update(args)
        self.emit({"name": name, "cat": cat, "ph": "i", "s": "p",
                   "ts": int(time.time() * 1e6),
                   "pid": os.getpid(), "tid": threading.get_ident() & 0xffff,
                   "args": ev_args})


class _Span:
    """Live tracing span: wall-clock anchored, perf_counter-measured."""

    __slots__ = ("_tr", "_name", "_cat", "_args", "_t0", "_p0")

    def __init__(self, tr: Tracer, name: str, cat: str, args: dict):
        self._tr, self._name, self._cat, self._args = tr, name, cat, args

    def __enter__(self):
        self._t0 = time.time()
        self._p0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        dur = time.perf_counter() - self._p0
        self._tr.complete(self._name, self._cat, self._t0 * 1e6,
                          dur * 1e6, self._args)
        return False


# ---------------------------------------------------------------------------
# Flight recorder
# ---------------------------------------------------------------------------

class FlightRecorder:
    """Bounded ring of recent structured events — the crash black box.
    ``record`` is cheap (deque append + optional instant trace event);
    ``dump`` writes the tail as JSON into the trace dir (or an explicit
    directory) at the moment something went wrong, and returns the
    events so callers (fleet postmortems) can embed them."""

    def __init__(self, maxlen: int | None = None):
        if maxlen is None:
            try:
                maxlen = int(knobs.raw(ENV_FLIGHT, "") or 256)
            except ValueError:
                maxlen = 256
        self._events: collections.deque = collections.deque(
            maxlen=max(maxlen, 8))
        self._dump_seq = 0
        self._lock = threading.Lock()

    def record(self, kind: str, **fields) -> None:
        self._events.append(
            {"t": round(time.time(), 6), "kind": kind, **fields})
        tr = get_tracer()
        if tr is not None:
            tr.instant(f"flight.{kind}", "flight", fields)

    def tail(self, n: int | None = None) -> list[dict]:
        evs = list(self._events)
        return evs if n is None else evs[-n:]

    def dump(self, reason: str, directory: str | None = None) -> dict:
        """Snapshot the ring as ``{reason, t, <correlation>, events}``;
        written to ``flight_rank<R>_<seq>_<reason>.json`` when a dump
        directory resolves (explicit arg, else SPARKNET_TRACE_DIR)."""
        doc = {"reason": reason, "t": round(time.time(), 3),
               **correlation_ids(), "pid": os.getpid(),
               "events": self.tail()}
        directory = directory or knobs.raw(ENV_TRACE_DIR)
        if directory:
            with self._lock:
                seq = self._dump_seq
                self._dump_seq += 1
            safe = "".join(c if c.isalnum() or c in "-_" else "-"
                           for c in reason)[:48]
            path = os.path.join(
                directory,
                f"flight_rank{doc['rank']}_{os.getpid()}_{seq:03d}_"
                f"{safe}.json")
            try:
                os.makedirs(directory, exist_ok=True)
                tmp = f"{path}.tmp.{os.getpid()}"
                with open(tmp, "w") as f:
                    json.dump(doc, f, indent=1, default=str)
                os.replace(tmp, path)
            except OSError:
                pass   # best effort: the dump must never mask the fault
        return doc


class _NullRecorder:
    """SPARKNET_TELEMETRY=0 recorder: drops everything."""

    def record(self, kind: str, **fields) -> None:
        pass

    def tail(self, n: int | None = None) -> list:
        return []

    def dump(self, reason: str, directory: str | None = None) -> dict:
        return {"reason": reason, "events": []}


# ---------------------------------------------------------------------------
# Process-global accessors (reset()-able for tests)
# ---------------------------------------------------------------------------

_NULL_REGISTRY = _NullRegistry()
_NULL_RECORDER = _NullRecorder()
_state: dict[str, Any] = {"registry": None, "tracer": None,
                          "tracer_off": False, "recorder": None}
_state_lock = threading.Lock()


def get_registry() -> MetricsRegistry | _NullRegistry:
    reg = _state["registry"]
    if reg is None:
        with _state_lock:
            reg = _state["registry"]
            if reg is None:
                reg = (MetricsRegistry() if enabled() else _NULL_REGISTRY)
                _state["registry"] = reg
    return reg


def get_tracer() -> Tracer | None:
    """The process tracer, or None when tracing is off (telemetry
    disabled or no SPARKNET_TRACE_DIR)."""
    tr = _state["tracer"]
    if tr is not None:
        return tr
    if _state["tracer_off"]:
        return None
    with _state_lock:
        if _state["tracer"] is not None or _state["tracer_off"]:
            return _state["tracer"]
        directory = knobs.raw(ENV_TRACE_DIR)
        if not directory or not enabled():
            _state["tracer_off"] = True
            return None
        _state["tracer"] = Tracer(directory)
        return _state["tracer"]


def get_recorder() -> FlightRecorder | _NullRecorder:
    rec = _state["recorder"]
    if rec is None:
        with _state_lock:
            rec = _state["recorder"]
            if rec is None:
                rec = (FlightRecorder() if enabled() else _NULL_RECORDER)
                _state["recorder"] = rec
    return rec


def tracing() -> bool:
    return get_tracer() is not None


def span(name: str, cat: str = "app", **args):
    """Context manager tracing one span; the shared no-op when tracing
    is off — safe (and free) to leave on hot paths."""
    tr = get_tracer()
    if tr is None:
        return NULL_SPAN
    return _Span(tr, name, cat, args)


def note_span(name: str, seconds: float, cat: str = "app", **args) -> None:
    """Retroactive span: an operation that just finished and took
    ``seconds`` (the FeedStats hook — stage timings are measured by the
    pipeline already; tracing only has to transcribe them)."""
    tr = get_tracer()
    if tr is None:
        return
    tr.complete(name, cat, (time.time() - seconds) * 1e6, seconds * 1e6,
                args)


def instant(name: str, cat: str = "app", **args) -> None:
    tr = get_tracer()
    if tr is not None:
        tr.instant(name, cat, args)


def reset() -> None:
    """Drop every cached singleton (flushing the tracer first) so the
    next accessor re-reads the env — the test hook for flipping
    SPARKNET_TELEMETRY / SPARKNET_TRACE_DIR mid-process."""
    global _DERIVED_RUN
    with _state_lock:
        tr = _state["tracer"]
        if tr is not None:
            tr.flush()
        _state.update(registry=None, tracer=None, tracer_off=False,
                      recorder=None)
        _DERIVED_RUN = None


@atexit.register
def _at_exit() -> None:
    """Final flush: the trace shard's buffered tail and (when
    SPARKNET_METRICS_SNAP is set) one last metrics snapshot."""
    tr = _state["tracer"]
    if tr is not None:
        try:
            tr.flush()
        except Exception:
            pass
    reg = _state["registry"]
    if reg is not None:
        try:
            reg.write_snapshot()
        except Exception:
            pass


# ---------------------------------------------------------------------------
# Snapshot folding (shared by tools/obs.py and tools/fleet.py --status)
# ---------------------------------------------------------------------------

def fold_snapshots(paths: Iterable[str]) -> dict[str, Any]:
    """Fold ``metrics_rank*.json`` snapshot files into one rollup:
    counters sum across files, gauges keep the newest file's value,
    histograms sum counts and sums.  Returns {} when nothing parses."""
    docs = []
    for p in paths:
        try:
            with open(p) as f:
                docs.append(json.load(f))
        except (OSError, json.JSONDecodeError):
            continue
    docs.sort(key=lambda d: d.get("t", 0.0))
    out: dict[str, Any] = {}
    for doc in docs:
        for name, m in (doc.get("metrics") or {}).items():
            kind = m.get("kind")
            agg = out.setdefault(name, {"kind": kind, "samples": {}})
            for s in m.get("samples", ()):
                key = _label_key(s.get("labels") or {})
                if kind == "histogram":
                    cur = agg["samples"].get(key)
                    if cur is None:
                        agg["samples"][key] = {
                            "labels": s.get("labels") or {},
                            "sum": s.get("sum", 0.0),
                            "count": s.get("count", 0)}
                    else:
                        cur["sum"] += s.get("sum", 0.0)
                        cur["count"] += s.get("count", 0)
                elif kind == "counter":
                    cur = agg["samples"].setdefault(
                        key, {"labels": s.get("labels") or {}, "value": 0.0})
                    cur["value"] += s.get("value", 0.0)
                else:   # gauge: newest doc wins (docs are time-sorted)
                    agg["samples"][key] = {"labels": s.get("labels") or {},
                                           "value": s.get("value", 0.0)}
    for agg in out.values():
        agg["samples"] = list(agg["samples"].values())
    return out
