"""Drive the PR-6 fleet tier end-to-end through the public surface.

Run from the repo root: python .drive_r11.py   -> expect DRIVE OK

Flows: (1) a two-job fleet (one with an injected crash) completes with
params bit-identical to a fault-free baseline; (2) preempt/resume — a
self-preempting job (SPARKNET_FAULT=preempt@round:1) AND a late
whole-budget priority-99 job that evicts the running gang, everything
still bit-identical; (3) quarantine — a job that always fails lands in
QUARANTINED with a postmortem.json and the fleet returns rc 3;
(4) journal resume — a finished fleet resumed from its journal stays
finished (runner factory that would explode proves nothing relaunches);
(5) status plumbing — round progress + heartbeat extras (stall_s) are
visible; error-path probes: duplicate name, oversized gang, unknown
model, cmd without {out}.
"""

import os
import sys
import tempfile

for k in list(os.environ):
    if k.startswith("SPARKNET_"):
        os.environ.pop(k)
os.environ.pop("XLA_FLAGS", None)

import numpy as np

from sparknet_tpu.parallel.fleet import (
    COMPLETED, QUARANTINED, FleetError, FleetScheduler, JobSpec,
    format_status,
)
from sparknet_tpu.tools.launch import launch_local

DRIVER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "tests", "multihost_driver.py")
work = tempfile.mkdtemp(prefix="drive_r11_")


def check(name, cond):
    print(f"{'ok ' if cond else 'FAIL'} {name}", flush=True)
    if not cond:
        raise SystemExit(f"DRIVE FAILED at {name}")


def params_equal(a_path, b_path):
    a, b = np.load(a_path), np.load(b_path)
    return all(np.array_equal(a[k], b[k])
               for k in a.files if not k.startswith("__"))


# fault-free baseline (world 4 / rounds 4) and (world 8 / rounds 3)
base4 = os.path.join(work, "base4.npz")
base8 = os.path.join(work, "base8.npz")
rc = launch_local([sys.executable, DRIVER, "--strategy", "sync",
                   "--out", base4, "--local-devices", "4",
                   "--rounds", "4"], nprocs=1, platform="cpu",
                  timeout=300)
check("baseline world=4", rc == 0)
rc = launch_local([sys.executable, DRIVER, "--strategy", "sync",
                   "--out", base8, "--local-devices", "8",
                   "--expect-devices", "8", "--rounds", "3"],
                  nprocs=1, platform="cpu", timeout=300)
check("baseline world=8", rc == 0)

# -- flow 1+2: crash recovery, self-preempt, priority preemption -------
fleet = FleetScheduler(os.path.join(work, "fleet"), 8,
                       tenants={"acme": 8, "beta": 8},
                       preempt_grace_s=20)
crashy = fleet.submit(JobSpec(name="crashy", tenant="acme", world=4,
                              rounds=4, fault="crash@round:2"))
selfpre = fleet.submit(JobSpec(name="selfpre", tenant="beta", world=4,
                               rounds=4, fault="preempt@round:1"))
urgent = fleet.submit(JobSpec(name="urgent", tenant="acme", priority=99,
                              world=8, rounds=3, not_before_s=4.0))
rc = fleet.run(tick_s=0.1, timeout_s=300)
check("fleet drains rc=0", rc == 0)
check("all jobs completed",
      all(j.state == COMPLETED for j in fleet.jobs.values()))
check("crash was restarted (attempts>1)", crashy.restarts_used > 1)
check("preemption exercised",
      selfpre.preempt_count >= 1 or crashy.preempt_count >= 1)
check("crashy bit-identical", params_equal(base4, crashy.out_path))
check("selfpre bit-identical", params_equal(base4, selfpre.out_path))
check("urgent bit-identical", params_equal(base8, urgent.out_path))
check("zero orphans", fleet.live_worker_pids() == {})
st = fleet.status()
text = format_status(st)
check("status table renders", "crashy" in text and "COMPLETED" in text)
hb = [r["heartbeats"] for r in st["jobs"] if r["job"] == "selfpre"][0]
check("heartbeat extras carry stall_s",
      any("stall_s" in (b.get("extras") or {}) for b in hb.values()))

# -- flow 4: journal resume of a finished fleet ------------------------
def explode(job, cmd, env):
    raise AssertionError(f"double launch of {job.name}")

again = FleetScheduler.resume(os.path.join(work, "fleet"),
                              runner_factory=explode)
check("resume keeps completions",
      all(j.state == COMPLETED for j in again.jobs.values()))
check("resumed fleet is a no-op", again.run(tick_s=0.05) == 0)

# -- flow 3: quarantine with post-mortem -------------------------------
f2 = FleetScheduler(os.path.join(work, "fleet2"), 4)
doomed = f2.submit(JobSpec(
    name="doomed", world=2, rounds=1, max_restarts=1, timeout_s=60,
    cmd=(sys.executable, "-c",
         "import sys; sys.stderr.write('artifact at {out}\\n'); "
         "sys.exit(7)")))
check("quarantine rc=3", f2.run(tick_s=0.05, timeout_s=120) == 3)
check("doomed quarantined", doomed.state == QUARANTINED)
pm = os.path.join(doomed.job_dir, "postmortem.json")
check("postmortem written", os.path.exists(pm))
check("gang re-offered", f2.allocator.free_count == 4)

# -- error paths -------------------------------------------------------
try:
    f2.submit(JobSpec(name="doomed", world=1))
    check("duplicate name rejected", False)
except FleetError:
    check("duplicate name rejected", True)
try:
    f2.submit(JobSpec(name="huge", world=64))
    check("oversized gang rejected", False)
except FleetError as e:
    check("oversized gang rejected", "never be placed" in str(e))
try:
    JobSpec(name="x", model="resnet50")
    check("unknown model rejected", False)
except ValueError:
    check("unknown model rejected", True)
try:
    JobSpec(name="x", cmd=("prog", "--flag"))
    check("cmd without {out} rejected", False)
except ValueError:
    check("cmd without {out} rejected", True)

import shutil
shutil.rmtree(work, ignore_errors=True)
print("DRIVE OK")
