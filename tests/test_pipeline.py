"""Parallel feed-pipeline tests: decode-pool ordering and failure
semantics, serial-vs-parallel bit-identity (clean AND under
corrupt_record faults — the quarantine accounting must match the serial
reference exactly), batch-level transform buffers, the decoded-shard LRU
cache, and the deep device feed (cast + stats)."""

import itertools
import threading
import time

import numpy as np
import pytest

from sparknet_tpu.data import (
    BufferRing, DecodePool, DecodeWorkerError, FeedStats, PartitionedDataset,
    ShardCache,
)
from sparknet_tpu.data.db import array_to_datum, db_feed
from sparknet_tpu.data.integrity import (
    DataCorruptionError, Quarantine, QuarantinePolicy,
)
from sparknet_tpu.data.lmdb_io import write_lmdb
from sparknet_tpu.models.dsl import layer
from sparknet_tpu.proto.caffe_pb import Phase
from sparknet_tpu.utils import faults


# ---------------------------------------------------------------------------
# DecodePool
# ---------------------------------------------------------------------------

def test_decode_pool_preserves_order_under_parallelism():
    """Items with adversarial per-item latency must come back in
    submission order — the whole determinism story rests on this."""
    def slow_decode(i):
        time.sleep(0.002 if i % 3 == 0 else 0.0)
        return i * i

    with DecodePool(slow_decode, workers=4) as pool:
        out = list(pool.imap(iter(range(50))))
    assert out == [i * i for i in range(50)]


def test_decode_pool_serial_mode_is_threadless():
    pool = DecodePool(lambda x: x + 1, workers=0)
    assert pool._threads == []
    assert list(pool.imap(iter(range(10)))) == list(range(1, 11))


def test_decode_pool_exception_surfaces_at_its_ordinal():
    """A work-function exception must be re-raised at the failing item's
    position, with good items before AND after still delivered — that is
    what lets the quarantine admit bad records in pull order."""
    def decode(i):
        if i == 3:
            raise DataCorruptionError("rotten", key=i)
        return i

    with DecodePool(decode, workers=3) as pool:
        for i in range(3):
            pool.submit(i)
        pool.submit(3)
        pool.submit(4)
        assert [pool.result() for _ in range(3)] == [0, 1, 2]
        with pytest.raises(DataCorruptionError, match="rotten"):
            pool.result()
        assert pool.result() == 4


def test_decode_pool_worker_crash_is_typed_error_not_hang():
    """A worker thread that DIES (injected thread kill — distinct from a
    raising work function) must surface as DecodeWorkerError on the
    consumer within the liveness poll, never a hang."""
    release = threading.Event()

    def decode(i):
        release.wait(5.0)
        return i

    pool = DecodePool(decode, workers=2)
    try:
        pool.submit(0)
        # kill the pool out from under the in-flight item: close() stops
        # every worker; the consumer's poll must then raise, not wait
        for _ in pool._threads:
            pool._in.put(object())  # noqa: SLF001 — wedge replaced by STOP
        release.set()
        pool._closed = True
        pool.close()
        t0 = time.monotonic()
        with pytest.raises(DecodeWorkerError, match="died"):
            pool.result()
        assert time.monotonic() - t0 < 5.0, "worker death took too long"
    finally:
        release.set()
        pool.close()


def test_decode_pool_imap_source_error_after_drain():
    """An exception from the SOURCE iterator surfaces after every
    already-submitted item is yielded (drain-then-fail, the
    PrefetchIterator contract)."""
    def src():
        yield 1
        yield 2
        raise ValueError("source died")

    with DecodePool(lambda x: x * 10, workers=2) as pool:
        it = pool.imap(src())
        assert next(it) == 10
        assert next(it) == 20
        with pytest.raises(ValueError, match="source died"):
            next(it)


# ---------------------------------------------------------------------------
# db_feed: serial-vs-parallel bit-identity
# ---------------------------------------------------------------------------

def _write_db(tmp_path, n=48, c=3, h=8, w=8, seed=0):
    rng = np.random.default_rng(seed)
    imgs = rng.integers(0, 256, size=(n, c, h, w)).astype(np.uint8)
    labels = rng.integers(0, 10, size=n)
    path = str(tmp_path / "lmdb")
    write_lmdb(path, [(b"%08d" % i, array_to_datum(imgs[i], int(labels[i])))
                      for i in range(n)])
    return path


def _stream(path, workers, n_batches, phase=Phase.TRAIN, seed=7,
            quarantine=None, transform=None):
    lp = layer("d", "Data", [], ["data", "label"],
               data_param={"source": path, "batch_size": 8,
                           "backend": "LMDB"},
               transform_param=transform or {})
    faults.reset_injector()
    feed = db_feed(lp, phase, seed=seed, quarantine=quarantine,
                   workers=workers)
    out = [next(feed) for _ in range(n_batches)]
    feed.close()
    return out


@pytest.mark.parametrize("force_per_record", [False, True])
def test_parallel_stream_bit_identical_to_serial(tmp_path, monkeypatch,
                                                 force_per_record):
    """Fixed seed ⇒ the parallel pipeline's batch stream is bit-identical
    to the serial reference — through the native batch parser AND the
    per-record pool path (native force-disabled)."""
    if force_per_record:
        from sparknet_tpu import native
        monkeypatch.setattr(native, "parse_datum_batch",
                            lambda *a, **k: None)
    path = _write_db(tmp_path)
    transform = {"crop_size": 6, "mirror": True, "scale": 0.5,
                 "mean_value": [10.0, 20.0, 30.0]}
    serial = _stream(path, 0, 12, transform=transform)
    parallel = _stream(path, 4, 12, transform=transform)
    for bs, bp in zip(serial, parallel):
        for k in bs:
            np.testing.assert_array_equal(bs[k], bp[k])
            assert bs[k].dtype == bp[k].dtype


@pytest.mark.chaos
def test_parallel_parity_holds_under_corrupt_record_faults(tmp_path,
                                                           monkeypatch):
    """With corrupt_record faults active the parallel path must quarantine
    the SAME records (counts, sources, epoch accounting) and pull the
    SAME replacements as the serial path — the PR-3 semantics, untouched
    by parallelism."""
    monkeypatch.setenv("SPARKNET_FAULT", "corrupt_record:0.15")
    monkeypatch.setenv("SPARKNET_FAULT_ATTEMPT", "0")
    path = _write_db(tmp_path)
    reports = {}
    streams = {}
    for name, workers in (("serial", 0), ("parallel", 4)):
        q = Quarantine(QuarantinePolicy(max_fraction=0.5), epoch_size=48,
                       source=path)
        streams[name] = _stream(path, workers, 10, quarantine=q)
        reports[name] = q.report()
    for bs, bp in zip(streams["serial"], streams["parallel"]):
        for k in bs:
            np.testing.assert_array_equal(bs[k], bp[k])
    rs, rp = reports["serial"], reports["parallel"]
    assert rs["total_bad"] > 0, "fault injection produced no corruption"
    assert rs == rp


def test_worker_crash_in_db_feed_decode_is_typed(tmp_path, monkeypatch):
    """A non-corruption failure inside decode (a bug, not bad data) must
    propagate as itself — NOT be eaten by the quarantine, NOT hang."""
    from sparknet_tpu import native
    from sparknet_tpu.data import db as db_mod
    monkeypatch.setattr(native, "parse_datum_batch", lambda *a, **k: None)
    real = db_mod.datum_to_array
    calls = {"n": 0}

    def flaky(val, **kw):
        calls["n"] += 1
        if calls["n"] == 12:   # past the geometry peek + first records
            raise RuntimeError("decoder bug, not data rot")
        return real(val, **kw)

    monkeypatch.setattr(db_mod, "datum_to_array", flaky)
    path = _write_db(tmp_path)
    with pytest.raises(RuntimeError, match="decoder bug"):
        _stream(path, 3, 4)


# ---------------------------------------------------------------------------
# transforms: buffers and copy discipline
# ---------------------------------------------------------------------------

def test_buffer_ring_rotates_and_restarts_on_shape_change():
    ring = BufferRing(3)
    a = ring.take((2, 4))
    b = ring.take((2, 4))
    c = ring.take((2, 4))
    assert a is not b and b is not c
    assert ring.take((2, 4)) is a          # rotation wraps
    d = ring.take((3, 3))                  # new shape: new rotation
    assert d.shape == (3, 3)
    with pytest.raises(ValueError):
        BufferRing(1)


def test_transformer_batch_writes_into_out_buffer():
    from sparknet_tpu.data.db import DataTransformer
    lp = layer("d", "Data", [], ["data"], transform_param={
        "crop_size": 6, "mean_value": [10.0], "scale": 2.0})
    imgs = np.random.default_rng(0).integers(
        0, 256, size=(4, 1, 8, 8)).astype(np.float32)
    tf = DataTransformer(lp.sub("transform_param"), Phase.TEST)
    ref = tf.batch(imgs.copy())
    out = np.empty((4, 1, 6, 6), np.float32)
    got = tf.batch(imgs.copy(), out=out)
    assert got is out
    np.testing.assert_array_equal(got, ref)
    # the expected math, independently: center-crop(img - mean) * scale
    manual = (imgs[:, :, 1:7, 1:7] - 10.0) * 2.0
    np.testing.assert_allclose(ref, manual, rtol=1e-6)


def test_transforms_no_copy_when_dtype_matches():
    from sparknet_tpu.data.minibatch import batch_feed
    from sparknet_tpu.data.transforms import scale, subtract_mean
    x = np.ones((2, 3, 4, 4), np.float32)
    y = np.zeros(2, np.float32)
    fed = next(batch_feed(iter([(x, y)])))
    assert fed["data"] is x, "batch_feed copied an already-f32 batch"
    assert fed["label"] is y
    out = np.empty_like(x)
    assert subtract_mean(x, 1.0, out=out) is out
    assert scale(x, 2.0, out=out) is out
    # wrong buffer shape degrades to allocation, never to wrong results
    bad = np.empty((5, 5), np.float32)
    np.testing.assert_array_equal(subtract_mean(x, 1.0, out=bad), x - 1.0)


# ---------------------------------------------------------------------------
# ShardCache
# ---------------------------------------------------------------------------

class _CountingPartition:
    def __init__(self, items):
        self.items = list(items)
        self.materializations = 0

    def __len__(self):
        return len(self.items)

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            self.materializations += 1
        return self.items[idx]


def test_shard_cache_decodes_once_per_partition():
    parts = [_CountingPartition(range(i * 10, i * 10 + 10))
             for i in range(3)]
    ds = PartitionedDataset(parts).cached()
    for _epoch in range(3):
        for pi in range(3):
            assert list(ds.partitions[pi]) == list(parts[pi].items)
    assert [p.materializations for p in parts] == [1, 1, 1]


def test_shard_cache_lru_eviction_and_stats():
    stats = FeedStats()
    cache = ShardCache(max_shards=2, stats=stats)
    parts = [_CountingPartition([i]) for i in range(3)]
    ds = PartitionedDataset(parts).cached(cache=cache)
    _ = ds.partitions[0][0], ds.partitions[1][0]   # fill: {0, 1}
    _ = ds.partitions[2][0]                        # evicts 0
    assert len(cache) == 2
    _ = ds.partitions[1][0]                        # hit
    _ = ds.partitions[0][0]                        # miss: re-materialize
    assert parts[0].materializations == 2
    assert parts[1].materializations == 1
    assert cache.hits >= 1 and cache.misses == 4
    assert stats.snapshot()["cache_misses"] == 4


# ---------------------------------------------------------------------------
# device feed: cast, stats, lifecycle
# ---------------------------------------------------------------------------

def test_device_feed_casts_on_device_and_counts_stats():
    import jax.numpy as jnp

    from sparknet_tpu.data import device_feed
    host = [{"data": np.full((2, 3), i, np.uint8),
             "label": np.ones(2, np.float32)} for i in range(5)]
    stats = FeedStats()
    with device_feed(iter(host), depth=2,
                     device_cast={"data": jnp.float32},
                     stats=stats) as feed:
        got = list(feed)
    assert len(got) == 5
    for i, b in enumerate(got):
        assert b["data"].dtype == jnp.float32
        np.testing.assert_array_equal(np.asarray(b["data"]),
                                      np.full((2, 3), i, np.float32))
    snap = stats.snapshot()
    assert snap["batches"] == 5
    assert snap["device_put_s"] > 0.0


def test_device_feed_depth_env_default(monkeypatch):
    from sparknet_tpu.data import device_feed, feed_depth
    monkeypatch.setenv("SPARKNET_FEED_DEPTH", "6")
    assert feed_depth() == 6
    feed = device_feed(iter([{"x": np.zeros(1, np.float32)}]))
    assert feed._pf._q.maxsize == 6
    feed.close()
    monkeypatch.setenv("SPARKNET_FEED_DEPTH", "0")
    with pytest.raises(ValueError, match="SPARKNET_FEED_DEPTH"):
        device_feed(iter([]))


def test_device_feed_source_error_propagates():
    from sparknet_tpu.data import device_feed

    def bad():
        yield {"x": np.zeros(2, np.float32)}
        raise RuntimeError("feed source exploded")

    with device_feed(bad(), depth=1) as feed:
        next(feed)
        with pytest.raises(RuntimeError, match="feed source exploded"):
            next(feed)


def test_feed_workers_env_knob(monkeypatch):
    from sparknet_tpu.data import feed_workers
    monkeypatch.setenv("SPARKNET_FEED_WORKERS", "3")
    assert feed_workers() == 3
    monkeypatch.setenv("SPARKNET_FEED_WORKERS", "0")
    assert feed_workers() == 0
    monkeypatch.setenv("SPARKNET_FEED_WORKERS", "-1")
    with pytest.raises(ValueError):
        feed_workers()
    monkeypatch.delenv("SPARKNET_FEED_WORKERS")
    assert feed_workers(default=5) == 5


def test_launcher_exports_feed_knobs(monkeypatch):
    """--feed-workers/--feed-depth ride the child env contract."""
    import sparknet_tpu.tools.launch as launch
    seen = {}

    def fake_local(cmd, nprocs, **kw):
        seen.update(kw)
        return 0

    monkeypatch.setattr(launch, "launch_local", fake_local)
    assert launch.main(["--nprocs", "2", "--feed-workers", "4",
                        "--feed-depth", "8", "--", "true"]) == 0
    assert seen["extra_env"] == {"SPARKNET_FEED_WORKERS": 4,
                                 "SPARKNET_FEED_DEPTH": 8}


# ---------------------------------------------------------------------------
# the tier-1 feed-parity smoke (fast, non-slow): tools/feedbench.py
# ---------------------------------------------------------------------------

def test_feedbench_smoke_parity(tmp_path, monkeypatch):
    """The CI gate's own logic, on a tiny budget: serial vs parallel must
    report parity ok (this is the in-tree smoke of the SPARKNET_FEEDBENCH
    gate in tools/run_tier1.sh)."""
    import importlib.util
    import os
    spec = importlib.util.spec_from_file_location(
        "feedbench", os.path.join(os.path.dirname(__file__), "..",
                                  "tools", "feedbench.py"))
    fb = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(fb)
    out = tmp_path / "verdict.json"
    rc = fb.main(["--seconds", "0.4", "--records", "64", "--batch", "16",
                  "--workers", "2", "--out", str(out)])
    assert rc == 0
    import json
    verdict = json.loads(out.read_text())
    assert verdict["ok"] is True
    assert verdict["batches"] > 0
