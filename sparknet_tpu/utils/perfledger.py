"""Performance ledger: the repo's perf history as machine data.

SparkNet's central claim is a wall-clock curve, yet until now this
repo's own perf story lived in ad-hoc artifacts — ``BENCH_r0*.json``,
``BENCH_serving_r07.json``, ``RESULTS_bench_*.json``,
``profiles/*/op_table.json`` — none of which could be joined into a
trajectory or gated against.  This module is the analysis substrate
``tools/perfwatch.py`` drives:

- :class:`PerfLedger` — an append-only, schema-versioned JSONL file
  (``perf/LEDGER.jsonl``).  One entry per (capture, fingerprint): the
  **config fingerprint** (model / dtype / batch / world / device /
  backend), git sha, the correlation IDs from the launcher env contract
  (``utils/telemetry.correlation_ids``), the source artifact path, and
  a flat ``metrics`` map.  Entries only ever append — history is the
  point.
- **Ingesters** — ``entries_from_*`` turn every perf artifact the repo
  emits (bench.py captures incl. their wrapped ``{"parsed": ...}``
  driver form, serveload/BENCH_serving reports, roundbench parity
  reports, ``profiles/*/op_table.json``, and folded
  ``metrics_rank*.json`` telemetry rollups) into ledger entries.
- **Noise-aware baselines** — per (metric, fingerprint key):
  ``median ± k·1.4826·MAD`` over a trailing window.  Small samples
  (< ``min_history`` runs) explicitly refuse to gate, and because the
  device+backend are part of the fingerprint key, a CPU capture never
  gates against TPU baselines (there simply is no baseline for it).
- **Verdicts** — :func:`verdict` classifies a fresh value against its
  band as ``regression`` / ``improvement`` / ``within_band`` /
  ``not_gated``, with per-metric direction (img/s and qps up is good;
  ms and stall seconds down is good).

The ledger stays human-diffable (one JSON object per line) so a perf
regression shows up in code review like any other change.
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
import statistics
import subprocess
import time
from typing import Any, Iterable, Mapping

SCHEMA_VERSION = 1
LEDGER_RELPATH = os.path.join("perf", "LEDGER.jsonl")

# fingerprint fields, in canonical key order
FINGERPRINT_FIELDS = ("model", "dtype", "batch", "world", "device",
                      "backend", "fuse_plan", "replicas", "tune_plan",
                      "feed_source", "tau", "comm_codec", "sharding")

# entries written before the vertical fusion pass existed carry no
# fuse_plan field; they were structurally unfused, so they pool with
# today's explicit "off" captures instead of fragmenting the history.
# Likewise entries before the serving fleet were single-engine captures:
# they read as replicas=1 so the committed serving history keeps gating
# against fresh single-engine runs, while fleet captures (replicas=N)
# band separately.  And entries before the lowering autotuner ran every
# lowering at its hardcoded default, exactly what SPARKNET_TUNE=off runs
# today — they read as tune_plan="off" so r01-r11 bands keep gating.
# Entries before the record-shard feed existed were all LMDB-decode
# captures: they read as feed_source="lmdb" so the committed feed
# history keeps gating, while records captures band separately.
# Entries before communication-efficient rounds (r19) carry no tau /
# comm_codec: every one of them ran the full-precision exchange (codec
# "none"), and the ingesters that know a capture's real τ (roundbench/
# commbench configs, trainer captures) stamp it explicitly — the pooled
# default τ=1 only covers captures whose round shape never mattered to
# their metrics (serving, feed, fusion).
# Entries before hybrid sharding (r20) all ran pure data parallelism:
# they read as sharding="dp" so the committed history keeps gating,
# while plan captures band under their shard_plan_id.
_FINGERPRINT_DEFAULTS = {"fuse_plan": "off", "replicas": 1,
                         "tune_plan": "off", "feed_source": "lmdb",
                         "tau": 1, "comm_codec": "none", "sharding": "dp"}

_REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))


# ---------------------------------------------------------------------------
# Provenance helpers
# ---------------------------------------------------------------------------

_GIT_SHA: dict[str, str | None] = {}


def git_sha(root: str | None = None, short: bool = True) -> str | None:
    """The repo HEAD sha (cached per root), or None outside a checkout —
    a missing sha is recorded honestly, never invented."""
    root = root or _REPO_ROOT
    key = f"{root}:{short}"
    if key not in _GIT_SHA:
        try:
            cmd = ["git", "rev-parse"] + (["--short"] if short else [])
            out = subprocess.run(
                cmd + ["HEAD"], cwd=root, stdout=subprocess.PIPE,
                stderr=subprocess.DEVNULL, timeout=10)
            sha = out.stdout.decode().strip() if out.returncode == 0 else ""
            _GIT_SHA[key] = sha or None
        except (OSError, subprocess.SubprocessError):
            _GIT_SHA[key] = None
    return _GIT_SHA[key]


def fingerprint(model: str | None = None, dtype: str | None = None,
                batch: int | None = None, world: int | None = None,
                device: str | None = None,
                backend: str | None = None,
                fuse_plan: str | None = None,
                replicas: int | None = None,
                tune_plan: str | None = None,
                feed_source: str | None = None,
                tau: int | None = None,
                comm_codec: str | None = None,
                sharding: str | None = None) -> dict[str, Any]:
    """Canonical config fingerprint.  ``backend`` defaults to the
    platform half of ``device`` (``"tpu/TPU v5 lite"`` -> ``"tpu"``) —
    the field the baseline isolation hinges on.  ``fuse_plan`` is the
    vertical-fusion plan id (``Net.fuse_plan_id()``): a fused capture
    and an unfused one are different programs, so they must never pool
    into one baseline band.  ``replicas`` is the serving-fleet size —
    a one-engine capture (the default, 1) and an N-replica routed
    capture are different deployments with different qps bands.
    ``tune_plan`` is the lowering-autotuner table id
    (``Net.tune_plan_id()``): tuned lowerings are a different program
    than the hardcoded defaults ("off"), same isolation argument.
    ``feed_source`` is the input-pipeline source family ("lmdb" decode
    path vs pre-decoded "records" shards): feed throughput bands are
    incomparable across them, so they must not pool.  ``tau`` (steps
    per averaging round) and ``comm_codec`` (the weight-delta wire
    format) shape the round's collective traffic: a τ=10 int8 capture
    and a τ=1 full-precision one are different communication programs
    and must band separately.  ``sharding`` is the partition plan id
    (``parallel.partition.shard_plan_id()``): "dp" is pure data
    parallelism (the historical default), a plan hash is a different
    resident layout with different round collectives — never pooled."""
    if backend is None and device:
        backend = str(device).split("/", 1)[0]
    return {"model": model or "unknown", "dtype": dtype or "unknown",
            "batch": int(batch) if batch is not None else 0,
            "world": int(world) if world is not None else 1,
            "device": device or "unknown",
            "backend": backend or "unknown",
            "fuse_plan": fuse_plan or "off",
            "replicas": int(replicas) if replicas is not None else 1,
            "tune_plan": tune_plan or "off",
            "feed_source": feed_source or "lmdb",
            "tau": int(tau) if tau is not None else 1,
            "comm_codec": comm_codec or "none",
            "sharding": sharding or "dp"}


def fp_key(fp: Mapping[str, Any]) -> str:
    """The fingerprint as one canonical string — the baseline grouping
    key.  Two captures gate against each other iff their keys match, so
    device/dtype/batch isolation is structural, not a special case.
    Fields newer than an entry (fuse_plan) read as their historical
    default, so the committed pre-fusion history keeps gating."""
    def val(k):
        v = fp.get(k)
        return _FINGERPRINT_DEFAULTS.get(k, "unknown") if v is None else v
    return "|".join(f"{k}={val(k)}" for k in FINGERPRINT_FIELDS)


def provenance(result_fp: Mapping[str, Any] | None = None) -> dict[str, Any]:
    """The stamp ``bench.py`` / ``tools/serveload.py`` attach to every
    capture: git sha + the telemetry plane's correlation IDs (+ the
    config fingerprint when the caller knows it)."""
    from . import telemetry
    corr = telemetry.correlation_ids()
    out: dict[str, Any] = {
        "git_sha": git_sha(),
        "captured_at": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
        "run": corr.get("run"),
        "rank": corr.get("rank"),
    }
    if corr.get("job"):
        out["job"] = corr["job"]
    if result_fp is not None:
        out["fingerprint"] = dict(result_fp)
    return out


# ---------------------------------------------------------------------------
# Metric direction
# ---------------------------------------------------------------------------

# explicit overrides win; otherwise suffix heuristics decide
_HIGHER_BETTER_SUFFIX = ("_img_s", "_qps", "_speedup_x", "_shrink_x",
                         "_gbs", "_gflops")
_LOWER_BETTER_SUFFIX = ("_ms", "_s", "_seconds", "_pct_overhead",
                        "_rejected", "_errors", "_mismatches")
_DIRECTION_OVERRIDES = {
    "mfu": True,
    "profile_mfu": True,
    "mfu_device_busy": True,
    "overlap_pct": True,
}


def higher_is_better(metric: str) -> bool | None:
    """True = up is good, False = down is good, None = don't gate
    (unknown direction must never produce a verdict)."""
    if metric in _DIRECTION_OVERRIDES:
        return _DIRECTION_OVERRIDES[metric]
    base = metric.split("/", 1)[0]   # "cat_ms/loop fusion" -> "cat_ms"
    if base in _DIRECTION_OVERRIDES:
        return _DIRECTION_OVERRIDES[base]
    for suf in _HIGHER_BETTER_SUFFIX:
        if base.endswith(suf):
            return True
    for suf in _LOWER_BETTER_SUFFIX:
        if base.endswith(suf):
            return False
    return None


# ---------------------------------------------------------------------------
# Baselines + verdicts
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class Baseline:
    """One (metric, fingerprint) gating band, or the reason there isn't
    one.  ``gated`` False means the sentinel REFUSES to judge — too few
    runs, no matching fingerprint (e.g. a CPU capture against a
    TPU-only ledger), or an unknown metric direction."""

    metric: str
    fpk: str
    n: int
    median: float | None = None
    mad: float | None = None
    lo: float | None = None
    hi: float | None = None
    gated: bool = False
    reason: str = ""


def compute_baseline(metric: str, fpk: str, history: Iterable[float], *,
                     window: int = 8, k: float = 4.0,
                     min_history: int = 3,
                     min_band_frac: float = 0.0) -> Baseline:
    """``median ± max(k·1.4826·MAD, min_band_frac·|median|)`` over the
    trailing ``window`` values.  MAD (not stdev) so one outlier run
    can't blow the band open; ``min_band_frac`` puts a floor under the
    band for noisy rigs (the "wide CPU bands" knob — three identical
    smoke runs otherwise yield MAD 0 and a zero-width band)."""
    vals = [float(v) for v in history][-window:]
    if len(vals) < min_history:
        return Baseline(metric, fpk, n=len(vals), gated=False,
                        reason=f"insufficient history ({len(vals)} run(s) "
                               f"< {min_history}) — refusing to gate")
    if higher_is_better(metric) is None:
        return Baseline(metric, fpk, n=len(vals), gated=False,
                        reason=f"unknown direction for {metric!r}")
    med = statistics.median(vals)
    mad = statistics.median(abs(v - med) for v in vals)
    band = max(k * 1.4826 * mad, min_band_frac * abs(med))
    return Baseline(metric, fpk, n=len(vals), median=med, mad=mad,
                    lo=med - band, hi=med + band, gated=True)


def verdict(metric: str, value: float, baseline: Baseline) -> str:
    """``regression`` / ``improvement`` / ``within_band`` /
    ``not_gated`` for one fresh value against its band."""
    if not baseline.gated:
        return "not_gated"
    up_good = higher_is_better(metric)
    assert up_good is not None   # gated baselines imply a direction
    if baseline.lo <= value <= baseline.hi:
        return "within_band"
    worse = value < baseline.lo if up_good else value > baseline.hi
    return "regression" if worse else "improvement"


# ---------------------------------------------------------------------------
# The ledger
# ---------------------------------------------------------------------------

def make_entry(source: str, path: str | None, fp: Mapping[str, Any],
               metrics: Mapping[str, float], *,
               round_tag: str | None = None, t: float | None = None,
               sha: str | None = None, run: str | None = None,
               rank: int | None = None, job: str | None = None,
               notes: str | None = None) -> dict[str, Any]:
    """One schema-versioned ledger entry.  ``metrics`` is a flat
    name -> number map (non-finite and non-numeric values are
    dropped — a ledger line must always be gateable arithmetic)."""
    clean: dict[str, float] = {}
    for name, v in metrics.items():
        try:
            fv = float(v)
        except (TypeError, ValueError):
            continue
        if fv != fv or fv in (float("inf"), float("-inf")):
            continue
        clean[name] = fv
    entry: dict[str, Any] = {
        "v": SCHEMA_VERSION,
        "t": round(float(t), 3) if t is not None else round(time.time(), 3),
        "round": round_tag,
        "source": source,
        "path": path,
        "sha": sha,
        "fp": dict(fp),
        "metrics": clean,
    }
    if run is not None:
        entry["run"] = run
    if rank is not None:
        entry["rank"] = int(rank)
    if job:
        entry["job"] = job
    if notes:
        entry["notes"] = notes
    return entry


class PerfLedger:
    """Append-only JSONL perf history.  Reads tolerate torn/alien lines
    (skipped, counted); appends are whole-line writes flushed per entry
    so a crash can tear at most the final line."""

    def __init__(self, path: str | None = None):
        self.path = path or os.path.join(_REPO_ROOT, LEDGER_RELPATH)
        self._entries: list[dict] | None = None
        self.skipped_lines = 0

    # -- IO ---------------------------------------------------------------
    def entries(self, reload: bool = False) -> list[dict]:
        if self._entries is not None and not reload:
            return self._entries
        out: list[dict] = []
        self.skipped_lines = 0
        try:
            with open(self.path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        doc = json.loads(line)
                    except json.JSONDecodeError:
                        self.skipped_lines += 1
                        continue
                    if not isinstance(doc, dict) or "metrics" not in doc:
                        self.skipped_lines += 1
                        continue
                    out.append(doc)
        except OSError:
            pass
        out.sort(key=lambda e: (e.get("t") or 0.0))
        self._entries = out
        return out

    def append(self, entry: Mapping[str, Any]) -> None:
        os.makedirs(os.path.dirname(os.path.abspath(self.path)),
                    exist_ok=True)
        with open(self.path, "a") as f:
            f.write(json.dumps(dict(entry), sort_keys=True) + "\n")
            f.flush()
        if self._entries is not None:
            self._entries.append(dict(entry))
            self._entries.sort(key=lambda e: (e.get("t") or 0.0))

    def extend(self, entries: Iterable[Mapping[str, Any]]) -> int:
        n = 0
        for e in entries:
            self.append(e)
            n += 1
        return n

    # -- queries ----------------------------------------------------------
    def history(self, metric: str, fpk: str,
                before_t: float | None = None) -> list[float]:
        """Time-ordered values of one metric for one fingerprint key
        (optionally only strictly before ``before_t`` — so a capture
        already ingested doesn't gate against itself)."""
        out = []
        for e in self.entries():
            if before_t is not None and (e.get("t") or 0.0) >= before_t:
                continue
            if fp_key(e.get("fp") or {}) != fpk:
                continue
            v = (e.get("metrics") or {}).get(metric)
            if v is not None:
                out.append(float(v))
        return out

    def baseline(self, metric: str, fpk: str, *, window: int = 8,
                 k: float = 4.0, min_history: int = 3,
                 min_band_frac: float = 0.0,
                 before_t: float | None = None) -> Baseline:
        hist = self.history(metric, fpk, before_t=before_t)
        return compute_baseline(metric, fpk, hist, window=window, k=k,
                                min_history=min_history,
                                min_band_frac=min_band_frac)

    def fingerprints(self) -> list[str]:
        return sorted({fp_key(e.get("fp") or {}) for e in self.entries()})

    def rounds(self) -> list[str]:
        tags = {e.get("round") for e in self.entries() if e.get("round")}
        return sorted(tags, key=_round_sort_key)


_ROUND_RE = re.compile(r"r(\d+)")


def _round_sort_key(tag: str) -> tuple:
    m = _ROUND_RE.fullmatch(tag or "")
    return (0, int(m.group(1))) if m else (1, tag)


def round_tag_from_path(path: str) -> str | None:
    """``BENCH_r05.json`` / ``BENCH_serving_r07.json`` -> ``r05``/``r07``."""
    m = re.search(r"_r(\d+)\b", os.path.basename(path or ""))
    return f"r{int(m.group(1)):02d}" if m else None


# ---------------------------------------------------------------------------
# Ingesters — every perf artifact the repo emits, one entry shape out
# ---------------------------------------------------------------------------

def _prov_fields(doc: Mapping[str, Any]) -> dict[str, Any]:
    p = doc.get("provenance") or {}
    return {"sha": p.get("git_sha"), "run": p.get("run"),
            "rank": p.get("rank"), "job": p.get("job")}


def _model_from_metric(metric: str | None) -> str | None:
    if not metric:
        return None
    return metric.split("_train_images_per_sec")[0] if (
        metric.endswith("_train_images_per_sec")) else None


def entries_from_bench(doc: Mapping[str, Any], path: str | None = None, *,
                       round_tag: str | None = None,
                       t: float | None = None,
                       device_hint: str | None = None) -> list[dict]:
    """bench.py captures: either the bare one-line JSON or the driver's
    ``{"n", "cmd", "rc", "tail", "parsed"}`` wrapper.  Failed captures
    (rc != 0, value 0, or an ``error`` key) yield no entries — a failed
    run is not a data point."""
    if "parsed" in doc:          # driver wrapper
        if doc.get("rc") != 0:
            return []
        doc = doc["parsed"]
    if not doc or doc.get("error") or not doc.get("value"):
        return []
    prov = _prov_fields(doc)
    device = doc.get("device") or device_hint
    model = _model_from_metric(doc.get("metric")) or "unknown"
    batch = doc.get("batch")
    fuse = doc.get("fuse_plan")
    tune = doc.get("tune_plan")
    out: list[dict] = []

    by_dtype = doc.get("by_dtype") or {
        # pre-round-4 captures measured one dtype and carry it at the
        # top level only
        doc.get("dtype") or "unknown": {
            "images_per_sec": doc.get("value"),
            "eval_images_per_sec": doc.get("eval_images_per_sec"),
            "block_20x256_s": doc.get("block_20x256_s"),
            "mfu": doc.get("mfu"),
        }}
    for dtype, run in by_dtype.items():
        fp = fingerprint(model=model, dtype=dtype, batch=batch, world=1,
                         device=device, fuse_plan=run.get("fuse_plan")
                         or fuse,
                         tune_plan=run.get("tune_plan") or tune)
        metrics = {
            "train_img_s": run.get("images_per_sec"),
            "eval_img_s": run.get("eval_images_per_sec"),
            "block_s": run.get("block_20x256_s"),
            "mfu": run.get("mfu"),
        }
        out.append(make_entry("bench", path, fp,
                              {k: v for k, v in metrics.items()
                               if v is not None},
                              round_tag=round_tag, t=t, **prov))

    feed = doc.get("feed_in_loop") or {}
    if feed and not feed.get("error"):
        fp = fingerprint(model=model,
                         dtype=feed.get("staged_dtype") or doc.get("dtype"),
                         batch=feed.get("batch"), world=1, device=device)
        metrics = {
            "feed_img_s": feed.get("images_per_sec"),
            "feed_step_s": feed.get("step_s"),
            "feed_alone_s": feed.get("feed_alone_s_per_batch"),
            "compute_s": feed.get("compute_s_per_step"),
            "overlap_pct": feed.get("overlap_pct"),
            # PR-4 per-stage breakdown (absent in pre-PR-4 captures) —
            # the fields regress-attribution names a stage from
            "feed_read_s": feed.get("read_s"),
            "feed_decode_s": feed.get("decode_s"),
            "feed_transform_s": feed.get("transform_s"),
            "feed_device_put_s": feed.get("device_put_s"),
        }
        out.append(make_entry("bench_feed", path, fp,
                              {k: v for k, v in metrics.items()
                               if v is not None},
                              round_tag=round_tag, t=t, **prov))

    rec = doc.get("feed_records") or {}
    if rec and not rec.get("error"):
        # the records leg stages uint8 and bands under its own
        # feed_source so it never pools with decode-path feed captures
        fp = fingerprint(model=model, dtype="uint8",
                         batch=rec.get("batch"), world=1, device=device,
                         feed_source=rec.get("feed_source") or "records")
        metrics = {
            "feed_img_s": rec.get("images_per_sec"),
            "feed_serial_img_s": rec.get("serial_img_s"),
            "feed_records_speedup_x": rec.get("speedup_x"),
            "feed_convert_s": rec.get("convert_s"),
            "feed_read_s": rec.get("read_s"),
        }
        out.append(make_entry("bench_feed", path, fp,
                              {k: v for k, v in metrics.items()
                               if v is not None},
                              round_tag=round_tag, t=t, **prov))

    ro = doc.get("round_overhead") or {}
    if ro and not ro.get("error"):
        fp = fingerprint(model=model, dtype=ro.get("dtype", "f32"),
                         batch=ro.get("batch"), world=ro.get("workers"),
                         device=device)
        metrics: dict[str, Any] = {
            "round_bare_s": (ro.get("bare") or {}).get("round_s"),
            "round_sync_s": (ro.get("sync") or {}).get("round_s"),
            "round_async_s": (ro.get("async") or {}).get("round_s"),
            "round_stall_sync_s": (ro.get("sync") or {}).get(
                "stall_total_s_per_round"),
            "round_stall_async_s": (ro.get("async") or {}).get(
                "stall_total_s_per_round"),
        }
        for comp, v in ((ro.get("async") or {}).get(
                "stall_s_per_round") or {}).items():
            metrics[f"stall_{comp}_s"] = v
        out.append(make_entry("bench_round", path, fp,
                              {k: v for k, v in metrics.items()
                               if v is not None},
                              round_tag=round_tag, t=t, **prov))

    sr = doc.get("shard_round") or {}
    if sr and not sr.get("error"):
        # dp vs sharded band separately: the `sharding` fingerprint
        # field keys each leg against its own history, so the sharded
        # round's smaller wire bytes never "regress" the dp baseline
        for mode, shard_id in (("dp", "dp"),
                               ("sharded", sr.get("plan") or "sharded")):
            leg = sr.get(mode) or {}
            if not leg or leg.get("error"):
                continue
            fp = fingerprint(model=model, dtype=sr.get("dtype", "f32"),
                             batch=sr.get("batch"),
                             world=sr.get("workers"), device=device,
                             tau=sr.get("tau"), sharding=shard_id)
            metrics = {
                "shard_round_s": leg.get("round_s"),
                "shard_boundary_bytes": leg.get(
                    "boundary_bytes_per_chip"),
            }
            if mode == "sharded":
                metrics["shard_bytes_shrink_x"] = sr.get(
                    "bytes_shrink_x")
            out.append(make_entry(
                "bench_shard", path, fp,
                {k: v for k, v in metrics.items() if v is not None},
                round_tag=round_tag, t=t,
                notes=None if sr.get("parity_ok", True)
                else "shard parity FAILED", **prov))

    serving = doc.get("serving") or {}
    if serving and not serving.get("error"):
        out.extend(entries_from_serving(serving, path,
                                        round_tag=round_tag, t=t,
                                        device_hint=device))
    return out


def entries_from_serving(doc: Mapping[str, Any], path: str | None = None, *,
                         round_tag: str | None = None,
                         t: float | None = None,
                         device_hint: str | None = None) -> list[dict]:
    """serveload / BENCH_serving reports (also the nested ``serving``
    leg of a bench capture)."""
    if not doc or doc.get("error"):
        return []
    prov = _prov_fields(doc)
    shapes = doc.get("batch_shapes") or []
    fp = fingerprint(model=doc.get("model"), dtype=doc.get("dtype"),
                     batch=max(shapes) if shapes else None, world=1,
                     device=doc.get("device") or device_hint)
    sat = doc.get("saturation") or {}
    b1 = doc.get("batch1") or {}
    over = doc.get("overload") or {}
    v = doc.get("verdicts") or {}
    metrics = {
        "serve_sat_qps": sat.get("achieved_qps"),
        "serve_sat_p99_ms": sat.get("p99_ms"),
        "serve_batch1_qps": b1.get("achieved_qps"),
        "serve_speedup_x": v.get("batching_speedup_x") or doc.get("value"),
        "serve_overload_p99_ms": over.get("p99_ms"),
        "serve_overload_qps": over.get("achieved_qps"),
        "serve_overload_rejected": over.get("rejected"),
    }
    return [make_entry("serving", path, fp,
                       {k: val for k, val in metrics.items()
                        if val is not None},
                       round_tag=round_tag, t=t, **prov)]


def entries_from_serving_fleet(doc: Mapping[str, Any],
                               path: str | None = None, *,
                               round_tag: str | None = None,
                               t: float | None = None,
                               device_hint: str | None = None
                               ) -> list[dict]:
    """serveload ``--fleet`` reports (BENCH_serving_fleet_*): N routed
    replicas.  ``replicas`` rides the fingerprint, so these never pool
    with (or pollute) the single-engine serving bands."""
    if not doc or doc.get("error"):
        return []
    prov = _prov_fields(doc)
    shapes = doc.get("batch_shapes") or []
    fp = fingerprint(model=doc.get("model"), dtype=doc.get("dtype"),
                     batch=max(shapes) if shapes else None, world=1,
                     device=doc.get("device") or device_hint,
                     replicas=doc.get("replicas"))
    sat = doc.get("saturation") or {}
    solo = doc.get("solo") or {}
    v = doc.get("verdicts") or {}
    metrics = {
        "serve_fleet_sat_qps": sat.get("achieved_qps"),
        "serve_fleet_sat_p99_ms": sat.get("p99_ms"),
        "serve_fleet_solo_qps": solo.get("achieved_qps"),
        "serve_fleet_speedup_x": v.get("fleet_scaling_x")
        or doc.get("value"),
        "serve_fleet_mismatches": v.get("exact_mismatches"),
    }
    return [make_entry("serving_fleet", path, fp,
                       {k: val for k, val in metrics.items()
                        if val is not None},
                       round_tag=round_tag, t=t, **prov)]


def entries_from_podsoak(doc: Mapping[str, Any],
                         path: str | None = None, *,
                         round_tag: str | None = None,
                         t: float | None = None,
                         device_hint: str | None = None) -> list[dict]:
    """tools/soak.py ``--pod`` verdicts (SOAK_pod_*): the simulated
    multi-host burn-in.  Folds every episode's serving legs into the
    worst case (min achieved qps, max p99) plus the mean episode wall
    time — the numbers a pod regression would move first.  ``world`` is
    the whole pod's device count, so differently-sized rigs never pool."""
    if doc.get("mode") != "pod" or not doc.get("episodes"):
        return []
    legs = [l for ep in doc["episodes"] for l in ep.get("legs") or []]
    if not legs:
        return []
    prov = _prov_fields(doc)
    eps = doc["episodes"]
    fp = fingerprint(model="lenet", dtype="f32",
                     world=int(doc.get("pod_hosts") or 0)
                     * int(doc.get("devices_per_host") or 0),
                     device=device_hint)
    metrics = {
        "podsoak_min_leg_qps": min(l.get("achieved_qps") or 0.0
                                   for l in legs),
        "podsoak_max_p99_ms": max(l.get("p99_ms") or 0.0 for l in legs),
        "podsoak_errors": sum(l.get("errors") or 0 for l in legs),
        "podsoak_episode_s": sum(ep.get("elapsed_s") or 0.0
                                 for ep in eps) / len(eps),
    }
    return [make_entry("podsoak", path, fp, metrics,
                       round_tag=round_tag, t=t,
                       notes=None if doc.get("ok") else "burn-in FAILED",
                       **prov)]


def entries_from_netsoak(doc: Mapping[str, Any],
                         path: str | None = None, *,
                         round_tag: str | None = None,
                         t: float | None = None,
                         device_hint: str | None = None) -> list[dict]:
    """tools/soak.py ``--net`` verdicts (SOAK_net_*): the network chaos
    legs.  The banded numbers are the partition-recovery wall time (the
    suspend→heal→bit-identical episode end to end), the fenced-ship
    transfer rate, and the fenced-resume episode wall — the costs a
    transport regression would move first."""
    if doc.get("mode") != "net" or not doc.get("episodes"):
        return []
    by_name = {ep.get("episode"): ep for ep in doc["episodes"]}
    prov = _prov_fields(doc)
    fp = fingerprint(model="lenet", dtype="f32", world=4,
                     device=device_hint)
    metrics: dict[str, Any] = {}
    part = by_name.get("partition_suspend_heal")
    if part:
        metrics["netsoak_partition_recovery_s"] = part.get("elapsed_s")
    fenced = by_name.get("fenced_zombie_ship")
    if fenced:
        metrics["netsoak_fenced_resume_s"] = fenced.get("elapsed_s")
        ship = fenced.get("ship") or {}
        wall = ship.get("wall_s")
        if wall and ship.get("bytes"):
            metrics["netsoak_ship_mb_per_s"] = round(
                ship["bytes"] / wall / 1e6, 3)
    slow = by_name.get("slow_link_attribution")
    if slow:
        metrics["netsoak_slow_link_episode_s"] = slow.get("elapsed_s")
    metrics = {k: v for k, v in metrics.items() if v is not None}
    if not metrics:
        return []
    return [make_entry("netsoak", path, fp, metrics,
                       round_tag=round_tag, t=t,
                       notes=None if doc.get("ok") else "net soak FAILED",
                       **prov)]


def entries_from_rollout(doc: Mapping[str, Any],
                         path: str | None = None, *,
                         round_tag: str | None = None,
                         t: float | None = None,
                         device_hint: str | None = None) -> list[dict]:
    """tools/soak.py ``--rollout`` verdicts (SOAK_rollout_*): the
    deployment-plane chaos legs.  The banded numbers are the
    promote-path wall (good canary start→judged→promoted), the breach
    detection-to-rollback wall (planted bad canary), the journal-replay
    resume wall, and the stable-pinned error count (MUST stay 0 — a
    rollout that bleeds onto stable traffic is the regression this
    ledger exists to catch)."""
    if doc.get("mode") != "rollout" or not doc.get("episodes"):
        return []
    by_name = {ep.get("episode"): ep for ep in doc["episodes"]}
    prov = _prov_fields(doc)
    fp = fingerprint(model="lenet", dtype="f32", world=1, replicas=2,
                     device=device_hint)
    metrics: dict[str, Any] = {}
    promo = by_name.get("canary_promote")
    if promo:
        metrics["rollout_promote_s"] = promo.get("elapsed_s")
        metrics["rollout_stable_errors"] = promo.get("stable_errors")
    bad = by_name.get("bad_canary_rollback")
    if bad:
        metrics["rollout_detect_s"] = bad.get("detect_s")
        if bad.get("stable_errors") is not None:
            metrics["rollout_stable_errors"] = (
                (metrics.get("rollout_stable_errors") or 0)
                + bad["stable_errors"])
    kill = by_name.get("controller_kill_resume")
    if kill:
        metrics["rollout_resume_s"] = kill.get("elapsed_s")
    metrics = {k: v for k, v in metrics.items() if v is not None}
    if not metrics:
        return []
    return [make_entry("rollout", path, fp, metrics,
                       round_tag=round_tag, t=t,
                       notes=None if doc.get("ok")
                       else "rollout soak FAILED",
                       **prov)]


def entries_from_roundbench(doc: Mapping[str, Any],
                            path: str | None = None, *,
                            round_tag: str | None = None,
                            t: float | None = None,
                            device_hint: str | None = None) -> list[dict]:
    """tools/roundbench.py parity reports (sync vs async outer loop)."""
    if not doc or "stall_total_sync_s" not in doc:
        return []
    prov = _prov_fields(doc)
    fp = fingerprint(model=doc.get("model"), dtype="f32",
                     batch=doc.get("batch"), world=doc.get("devices"),
                     device=doc.get("device") or device_hint)
    metrics = {
        "roundbench_sync_wall_s": (doc.get("sync") or {}).get("wall_s"),
        "roundbench_async_wall_s": (doc.get("async") or {}).get("wall_s"),
        "roundbench_stall_sync_s": doc.get("stall_total_sync_s"),
        "roundbench_stall_async_s": doc.get("stall_total_async_s"),
    }
    return [make_entry("roundbench", path, fp,
                       {k: v for k, v in metrics.items() if v is not None},
                       round_tag=round_tag, t=t,
                       notes=None if doc.get("ok") else "parity FAILED",
                       **prov)]


def entries_from_commbench(doc: Mapping[str, Any],
                           path: str | None = None, *,
                           round_tag: str | None = None,
                           t: float | None = None,
                           device_hint: str | None = None) -> list[dict]:
    """tools/commbench.py comm-codec gate reports: one entry per codec
    (fingerprinted by its ``comm_codec``, so each wire format bands
    against its own history) carrying the round wall, the per-component
    comm stall (``stall_comm_*_s`` — stage attribution, not gated), and
    the analytic exchange bytes; plus one summary entry on the
    full-precision fingerprint with the headline sync-vs-overlap stall
    and the int8 wire shrink (``_shrink_x`` — higher is better)."""
    if not doc.get("commbench"):
        return []
    prov = _prov_fields(doc)
    tau = doc.get("tau")
    world = doc.get("devices")
    note = None if doc.get("ok") else "commbench gate FAILED"
    out: list[dict] = []
    for codec, leg in (doc.get("codecs") or {}).items():
        fp = fingerprint(model="lenet", dtype="f32",
                         batch=doc.get("batch"), world=world,
                         device=device_hint, tau=tau, comm_codec=codec)
        metrics = {
            "commbench_wall_s": leg.get("wall_s"),
            "comm_stall_s": leg.get("comm_stall_s"),
            "comm_exchange_bytes": leg.get("exchange_bytes"),
        }
        for comp, v in (leg.get("stall_s") or {}).items():
            if comp.startswith("comm_"):
                metrics[f"stall_{comp}_s"] = v
        out.append(make_entry(
            "commbench", path, fp,
            {k: v for k, v in metrics.items() if v is not None},
            round_tag=round_tag, t=t, notes=note, **prov))
    summary = {
        "comm_stall_sync_s": doc.get("comm_stall_sync_s"),
        "comm_stall_overlap_s": doc.get("comm_stall_overlap_s"),
        "comm_bytes_shrink_x": doc.get("comm_bytes_shrink_x"),
        "commbench_wall_s": (doc.get("none") or {}).get("wall_s"),
    }
    summary = {k: v for k, v in summary.items() if v is not None}
    if summary:
        fp = fingerprint(model="lenet", dtype="f32",
                         batch=doc.get("batch"), world=world,
                         device=device_hint, tau=tau, comm_codec="none")
        out.append(make_entry("commbench", path, fp, summary,
                              round_tag=round_tag, t=t, notes=note,
                              **prov))
    return out


def entries_from_shardbench(doc: Mapping[str, Any],
                            path: str | None = None, *,
                            round_tag: str | None = None,
                            t: float | None = None,
                            device_hint: str | None = None) -> list[dict]:
    """tools/shardbench.py hybrid-sharding gate reports: one entry on
    the ``sharding="dp"`` fingerprint (the replicated baseline's round
    wall and analytic boundary bytes) and one on the sharded plan's
    fingerprint (its round wall, per-chip boundary bytes, and the
    headline ``shard_bytes_shrink_x`` — higher is better).  The two
    fingerprints band independently, so the ledger keeps both histories
    without the sharded leg masquerading as a dp speedup."""
    if not doc.get("shardbench"):
        return []
    prov = _prov_fields(doc)
    world = doc.get("devices")
    tau = doc.get("tau")
    note = None if doc.get("ok") else "shardbench gate FAILED"
    out: list[dict] = []
    for mode, shard_id in (("dp", "dp"),
                           ("sharded", doc.get("plan") or "sharded")):
        leg = doc.get(mode) or {}
        if not leg:
            continue
        fp = fingerprint(model=doc.get("model") or "lenet", dtype="f32",
                         batch=doc.get("batch"), world=world,
                         device=device_hint, tau=tau, sharding=shard_id)
        metrics = {
            "shard_round_s": leg.get("round_s"),
            "shard_boundary_bytes": leg.get("boundary_bytes_per_chip"),
            "shard_exchange_bytes": leg.get("exchange_bytes"),
        }
        if mode == "sharded":
            metrics["shard_bytes_shrink_x"] = doc.get(
                "shard_bytes_shrink_x")
            metrics["shard_caffenet_shrink_x"] = (
                doc.get("caffenet") or {}).get("shrink_x")
        out.append(make_entry(
            "shardbench", path, fp,
            {k: v for k, v in metrics.items() if v is not None},
            round_tag=round_tag, t=t, notes=note, **prov))
    return out


def entries_from_op_table(doc: Mapping[str, Any],
                          path: str | None = None, *,
                          round_tag: str | None = None,
                          t: float | None = None) -> list[dict]:
    """``profiles/*/op_table.json``: the summary row plus per-category
    device time and bandwidth (the hotspot worklist's raw material)."""
    summary = doc.get("summary") or {}
    if not summary:
        return []
    fp = fingerprint(model=summary.get("model"),
                     dtype=summary.get("dtype"),
                     batch=summary.get("batch"), world=1,
                     device=summary.get("device"),
                     fuse_plan=summary.get("fuse_plan"),
                     tune_plan=summary.get("tune_plan"))
    # profile captures run with profiling overhead — their MFU/img_s
    # must not pool into the bench baselines, hence the profile_ prefix
    metrics: dict[str, Any] = {
        "step_ms": summary.get("step_ms"),
        "profile_img_s": summary.get("img_s"),
        "profile_mfu": summary.get("mfu"),
        "mfu_device_busy": summary.get("mfu_device_busy"),
        "device_busy_ms": summary.get("device_busy_ms_per_step"),
    }
    for cat in doc.get("by_category") or []:
        name = cat.get("op")
        if not name:
            continue
        metrics[f"cat_ms/{name}"] = cat.get("total_ms")
        metrics[f"cat_gbs/{name}"] = cat.get("gb_per_s")
    mode = summary.get("mode")
    return [make_entry("profile", path, fp,
                       {k: v for k, v in metrics.items() if v is not None},
                       round_tag=round_tag, t=t,
                       notes=f"mode={mode}" if mode else None)]


def entries_from_tuning_table(doc: Mapping[str, Any],
                              path: str | None = None, *,
                              round_tag: str | None = None,
                              t: float | None = None) -> list[dict]:
    """``profiles/<backend>/tuning.json`` (graph/tuner.py): every
    candidate timing at every key becomes a metric, so the next capture
    of the same key gates against this one — the staleness check's
    noise-band argument, but with the ledger's MAD bands and full
    history behind it.  Metric names: ``tune_ms/<key>`` for the winner
    (the ``_ms`` suffix makes lower better, like every other timing),
    ``tune_cand_ms/<key>=<candidate>`` for the rest, and
    ``tune_margin/<key>`` for the winner's lead over the runner-up
    (suffix-less -> higher is better: a shrinking margin is the early
    rot signal)."""
    if doc.get("kind") != "tuning_table":
        return []
    entries = doc.get("entries") or []
    if not entries:
        return []
    prov = doc.get("provenance") or {}
    backend = doc.get("backend") or "unknown"
    device = (prov.get("fingerprint") or {}).get("device")
    if not device or device == "unknown":
        device = backend
    dtypes = {parts[2] for e in entries
              if len(parts := str(e.get("key", "")).split("/")) >= 3}
    fp = fingerprint(model="tuner",
                     dtype=dtypes.pop() if len(dtypes) == 1 else "mixed",
                     batch=0, world=1, device=device, backend=backend,
                     tune_plan=doc.get("table_id"))
    metrics: dict[str, Any] = {}
    for e in entries:
        key, winner = e.get("key"), e.get("winner")
        if not key or not winner:
            continue
        for cand, rec in (e.get("timings") or {}).items():
            if not isinstance(rec, Mapping) or rec.get("ms") is None:
                continue  # typed skip — no measurement, never 0
            if cand == winner:
                metrics[f"tune_ms/{key}"] = rec["ms"]
            else:
                metrics[f"tune_cand_ms/{key}={cand}"] = rec["ms"]
        if e.get("margin") is not None:
            metrics[f"tune_margin/{key}"] = e["margin"]
    if not metrics:
        return []
    ts = [e.get("measured_at") for e in entries
          if isinstance(e.get("measured_at"), (int, float))]
    return [make_entry("tuning", path, fp, metrics, round_tag=round_tag,
                       t=t if t is not None else (max(ts) if ts else None),
                       sha=prov.get("git_sha"), run=prov.get("run"),
                       rank=prov.get("rank"), job=prov.get("job"))]


def entries_from_metrics_rollup(folded: Mapping[str, Any],
                                path: str | None = None, *,
                                round_tag: str | None = None,
                                t: float | None = None,
                                fp: Mapping[str, Any] | None = None
                                ) -> list[dict]:
    """A ``telemetry.fold_snapshots`` rollup (obs.py merge's metrics
    half): the PR-8 stage gauges/histograms become ledger metrics —
    ``feed_stage_seconds{stage}``, ``trainer_stall_seconds{component}``,
    ``ckpt_write_seconds`` mean — so stage attribution has history."""
    metrics: dict[str, float] = {}
    for name in ("feed_stage_seconds", "trainer_stall_seconds"):
        fam = folded.get(name) or {}
        for s in fam.get("samples") or []:
            labels = s.get("labels") or {}
            label = (labels.get("stage") or labels.get("component")
                     or ",".join(f"{k}={v}"
                                 for k, v in sorted(labels.items()))
                     or "all")
            if s.get("value") is not None:
                metrics[f"{name}/{label}"] = s["value"]
    ck = folded.get("ckpt_write_seconds") or {}
    for s in ck.get("samples") or []:
        if s.get("count"):
            metrics["ckpt_write_mean_s"] = s["sum"] / s["count"]
    if not metrics:
        return []
    return [make_entry("telemetry", path, fp or fingerprint(),
                       metrics, round_tag=round_tag, t=t)]


def entries_from_any(doc: Mapping[str, Any], path: str | None = None, *,
                     round_tag: str | None = None, t: float | None = None,
                     device_hint: str | None = None) -> list[dict]:
    """Sniff the artifact type and dispatch; unknown shapes yield []."""
    if round_tag is None and path:
        round_tag = round_tag_from_path(path)
    if "parsed" in doc or str(doc.get("metric", "")).endswith(
            "_train_images_per_sec"):
        return entries_from_bench(doc, path, round_tag=round_tag, t=t,
                                  device_hint=device_hint)
    if doc.get("metric") == "serving_dynamic_vs_batch1_speedup_x":
        return entries_from_serving(doc, path, round_tag=round_tag, t=t,
                                    device_hint=device_hint)
    if doc.get("metric") == "serving_fleet_scaling_x":
        return entries_from_serving_fleet(doc, path, round_tag=round_tag,
                                          t=t, device_hint=device_hint)
    if doc.get("mode") == "pod" and "episodes" in doc:
        return entries_from_podsoak(doc, path, round_tag=round_tag, t=t,
                                    device_hint=device_hint)
    if doc.get("mode") == "net" and "episodes" in doc:
        return entries_from_netsoak(doc, path, round_tag=round_tag, t=t,
                                    device_hint=device_hint)
    if doc.get("mode") == "rollout" and "episodes" in doc:
        return entries_from_rollout(doc, path, round_tag=round_tag, t=t,
                                    device_hint=device_hint)
    if doc.get("kind") == "tuning_table":
        return entries_from_tuning_table(doc, path, round_tag=round_tag,
                                         t=t)
    if "summary" in doc and "by_category" in doc:
        return entries_from_op_table(doc, path, round_tag=round_tag, t=t)
    if doc.get("commbench"):
        return entries_from_commbench(doc, path, round_tag=round_tag,
                                      t=t, device_hint=device_hint)
    if doc.get("shardbench"):
        return entries_from_shardbench(doc, path, round_tag=round_tag,
                                       t=t, device_hint=device_hint)
    if "stall_total_sync_s" in doc:
        return entries_from_roundbench(doc, path, round_tag=round_tag,
                                       t=t, device_hint=device_hint)
    # a folded metrics rollup is a {name: {kind, samples}} map
    if doc and all(isinstance(v, Mapping) and "samples" in v
                   for v in doc.values()):
        return entries_from_metrics_rollup(doc, path, round_tag=round_tag,
                                           t=t)
    return []
