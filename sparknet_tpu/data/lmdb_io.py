"""Read/write LMDB databases without liblmdb.

The reference's ``Data`` layer streams serialized ``Datum`` records out of
an LMDB (or LevelDB) environment via a sequential cursor (reference:
caffe/src/caffe/util/db_lmdb.cpp, caffe/src/caffe/data_reader.cpp:62-109).
This rig has no liblmdb/py-lmdb, so this module implements the LMDB file
format directly:

- ``LmdbReader`` — zero-copy mmap reader: parses the meta pages, walks the
  main DB's B+tree in key order, resolves overflow (BIGDATA) values.
  Handles databases written by real liblmdb (inline or overflow values).
- ``write_lmdb`` — a bulk bottom-up writer (sorted keys -> leaf pages ->
  branch levels -> meta), the ``convert_imageset`` storage path.  Values
  always go to overflow pages (valid LMDB; readers follow F_BIGDATA).

Format reference: the stable LMDB on-disk layout (openldap mdb.c) —
magic 0xBEEFC0DE, 16-byte page headers, 2-byte in-page node offsets,
branch node pgno packed lo/hi/flags, meta pages 0 and 1.
"""

from __future__ import annotations

import mmap
import os
import struct
from typing import Iterable, Iterator

from ..utils.retry import io_retry

MAGIC = 0xBEEFC0DE
VERSION = 1
P_BRANCH, P_LEAF, P_OVERFLOW, P_META = 0x01, 0x02, 0x04, 0x08
P_LEAF2 = 0x20
F_BIGDATA = 0x01
PAGEHDRSZ = 16
P_INVALID = 0xFFFFFFFFFFFFFFFF

# MDB_db: md_pad(u32) md_flags(u16) md_depth(u16) branch/leaf/overflow
# pages + entries + root (5 × u64) — 48 bytes
_DB = struct.Struct("<IHHQQQQQ")
# MDB_meta after the page header: magic, version, address, mapsize
_META_HEAD = struct.Struct("<IIQQ")


class LmdbError(Exception):
    pass


def _db_path(path: str) -> str:
    return os.path.join(path, "data.mdb") if os.path.isdir(path) else path


class LmdbReader:
    """Sequential (key-ordered) reader over an LMDB main database."""

    def __init__(self, path: str):
        self.path = _db_path(path)
        # the open+mmap is a one-shot control-plane edge (NFS blips on a
        # pod fail it transiently) — bounded retry, SPARKNET_IO_* knobs
        self._f = io_retry(open, self.path, "rb",
                           describe=f"open {self.path}")
        try:
            self._mm = mmap.mmap(self._f.fileno(), 0,
                                 access=mmap.ACCESS_READ)
        except (OSError, ValueError):
            self._f.close()
            raise
        meta = self._pick_meta()
        (self.psize, _flags, self.depth, _b, _l, _o,
         self.entries, self.root) = meta

    def _read_meta(self, byte_off: int):
        off = byte_off + PAGEHDRSZ
        magic, version, _addr, _mapsize = _META_HEAD.unpack_from(
            self._mm, off)
        if magic != MAGIC:
            raise LmdbError(f"bad LMDB magic at {byte_off}: {magic:#x}")
        if version not in (VERSION, 999):
            raise LmdbError(f"unsupported LMDB version {version}")
        off += _META_HEAD.size
        db0 = _DB.unpack_from(self._mm, off)
        db1 = _DB.unpack_from(self._mm, off + _DB.size)
        off += 2 * _DB.size
        _last_pg, txnid = struct.unpack_from("<QQ", self._mm, off)
        psize = db0[0]  # mm_psize aliases mm_dbs[0].md_pad
        return txnid, (psize, db1[1], db1[2], db1[3], db1[4], db1[5],
                       db1[6], db1[7])

    def _pick_meta(self):
        """Meta 0 sits at offset 0; meta 1 at one page — whose size comes
        from meta 0 (liblmdb uses the OS page size, not always 4096).  If
        meta 0 is torn, probe the common page sizes for meta 1."""
        metas = []
        psize_guesses = []
        try:
            m0 = self._read_meta(0)
            metas.append(m0)
            psize_guesses.append(m0[1][0])
        except (LmdbError, struct.error):
            psize_guesses.extend((4096, 8192, 16384, 32768, 65536))
        for psize in psize_guesses:
            try:
                metas.append(self._read_meta(psize))
                break
            except (LmdbError, struct.error, IndexError):
                continue
        if not metas:
            raise LmdbError(f"{self.path}: no valid LMDB meta page")
        return max(metas)[1]

    # -- page accessors ---------------------------------------------------
    def _page(self, pgno: int) -> tuple[int, int, int]:
        """(byte offset, flags, nkeys)."""
        off = pgno * self.psize
        flags, lower = struct.unpack_from("<HH", self._mm, off + 10)
        nkeys = (lower - PAGEHDRSZ) // 2
        return off, flags, nkeys

    def _node(self, page_off: int, idx: int):
        ptr, = struct.unpack_from("<H", self._mm,
                                  page_off + PAGEHDRSZ + 2 * idx)
        noff = page_off + ptr
        lo, hi, flags, ksize = struct.unpack_from("<HHHH", self._mm, noff)
        return noff, lo, hi, flags, ksize

    def _leaf_value(self, noff, lo, hi, flags, ksize) -> bytes:
        dsize = lo | (hi << 16)
        data_off = noff + 8 + ksize
        if flags & F_BIGDATA:
            ovpg, = struct.unpack_from("<Q", self._mm, data_off)
            start = ovpg * self.psize + PAGEHDRSZ
            return bytes(self._mm[start:start + dsize])
        return bytes(self._mm[data_off:data_off + dsize])

    def _walk(self, pgno: int) -> Iterator[tuple[bytes, bytes]]:
        off, flags, nkeys = self._page(pgno)
        if flags & P_LEAF:
            if flags & P_LEAF2:
                raise LmdbError("LEAF2 (dupfixed) pages unsupported")
            for i in range(nkeys):
                noff, lo, hi, nflags, ksize = self._node(off, i)
                key = bytes(self._mm[noff + 8:noff + 8 + ksize])
                yield key, self._leaf_value(noff, lo, hi, nflags, ksize)
        elif flags & P_BRANCH:
            for i in range(nkeys):
                _noff, lo, hi, nflags, _ksize = self._node(off, i)
                child = lo | (hi << 16) | (nflags << 32)
                yield from self._walk(child)
        else:
            raise LmdbError(f"unexpected page flags {flags:#x} at {pgno}")

    # -- public API -------------------------------------------------------
    def __len__(self) -> int:
        return self.entries

    def items(self) -> Iterator[tuple[bytes, bytes]]:
        """All (key, value) pairs in key order — the DB cursor loop of
        data_reader.cpp:90-108."""
        if self.root == P_INVALID:
            return
        yield from self._walk(self.root)

    def first(self) -> tuple[bytes, bytes]:
        for kv in self.items():
            return kv
        raise LmdbError("empty database")

    def close(self) -> None:
        self._mm.close()
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# ---------------------------------------------------------------------------
# Bulk writer
# ---------------------------------------------------------------------------

def _even(n: int) -> int:
    return n + (n & 1)


def write_lmdb(path: str, items: Iterable[tuple[bytes, bytes]],
               psize: int = 4096) -> int:
    """Write (key, value) pairs as a fresh LMDB environment; returns the
    entry count.  ``path`` is created as a directory holding ``data.mdb``
    (the subdir layout Caffe's db_lmdb.cpp opens).  Keys are sorted —
    LMDB is a B+tree; Caffe's sequential "%08d_..." keys arrive sorted
    already."""
    pairs = sorted(items)
    for k, _ in pairs:
        if len(k) > 511:
            raise LmdbError(f"key too long for LMDB ({len(k)} > 511)")
    os.makedirs(path, exist_ok=True)
    out = os.path.join(path, "data.mdb")

    pages: list[bytes] = []          # data pages, index = pgno - 2

    def add_page(buf: bytes) -> int:
        pages.append(buf)
        return len(pages) + 1        # pgno (0/1 are meta)

    def page_hdr(pgno: int, flags: int, lower: int, upper: int,
                 overflow_pages: int = 0) -> bytes:
        if flags & P_OVERFLOW:
            return struct.pack("<QHHI", pgno, 0, flags, overflow_pages)
        return struct.pack("<QHHHH", pgno, 0, flags, lower, upper)

    n_overflow = 0

    def write_overflow(value: bytes) -> int:
        nonlocal n_overflow
        npg = max(1, -(-(PAGEHDRSZ + len(value)) // psize))
        first = len(pages) + 2
        buf = page_hdr(first, P_OVERFLOW, 0, 0, npg) + value
        buf += b"\0" * (npg * psize - len(buf))
        for i in range(npg):
            add_page(buf[i * psize:(i + 1) * psize])
        n_overflow += npg
        return first

    # ---- leaf level
    def build_level(nodes: list[tuple[bytes, bytes]], leaf: bool
                    ) -> list[tuple[bytes, int]]:
        """Pack (key, payload) nodes into pages; returns (first key, pgno)
        per page.  Leaf payload = 8-byte overflow pgno (+ size header);
        branch payload = child pgno packed into the node header."""
        out_pages: list[tuple[bytes, int]] = []
        cur: list[bytes] = []
        cur_first: bytes | None = None
        used = 0

        def flush():
            nonlocal cur, cur_first, used
            if not cur:
                return
            pgno = len(pages) + 2
            nptrs = len(cur)
            ptrs = []
            top = psize
            body = bytearray(psize)
            for node in cur:
                top -= _even(len(node))
                ptrs.append(top)
                body[top:top + len(node)] = node
            lower = PAGEHDRSZ + 2 * nptrs
            hdr = page_hdr(pgno, P_LEAF if leaf else P_BRANCH, lower, top)
            body[:PAGEHDRSZ] = hdr
            body[PAGEHDRSZ:PAGEHDRSZ + 2 * nptrs] = struct.pack(
                f"<{nptrs}H", *ptrs)
            add_page(bytes(body))
            out_pages.append((cur_first, pgno))
            cur, cur_first, used = [], None, 0

        for i, (key, payload) in enumerate(nodes):
            if leaf:
                ovpg = write_overflow(payload)
                node = struct.pack("<HHHH", len(payload) & 0xFFFF,
                                  len(payload) >> 16, F_BIGDATA,
                                  len(key)) + key + struct.pack("<Q", ovpg)
            else:
                pgno_child = payload  # int
                node = struct.pack(
                    "<HHHH", pgno_child & 0xFFFF,
                    (pgno_child >> 16) & 0xFFFF,
                    (pgno_child >> 32) & 0xFFFF, len(key)) + key
            need = _even(len(node)) + 2
            if cur and PAGEHDRSZ + used + need > psize:
                flush()
            if not cur:
                cur_first = key
                if not leaf:
                    # leftmost branch node carries an empty key
                    node = struct.pack(
                        "<HHHH", payload & 0xFFFF,
                        (payload >> 16) & 0xFFFF,
                        (payload >> 32) & 0xFFFF, 0)
            cur.append(node)
            used += _even(len(node)) + 2
        flush()
        return out_pages

    depth = 0
    branch_pages = 0
    if pairs:
        level = build_level(pairs, leaf=True)
        leaf_pages = len(level)
        depth = 1
        while len(level) > 1:
            level = build_level([(k, pg) for k, pg in level], leaf=False)
            branch_pages += len(level)
            depth += 1
        root = level[0][1]
    else:
        leaf_pages = 0
        root = P_INVALID

    last_pg = len(pages) + 1
    mapsize = max((last_pg + 1) * psize, 1 << 20)

    def meta(pgno: int) -> bytes:
        buf = page_hdr(pgno, P_META, 0, 0)
        buf += _META_HEAD.pack(MAGIC, VERSION, 0, mapsize)
        buf += _DB.pack(psize, 0, 0, 0, 0, 0, 0, P_INVALID)      # FREE_DBI
        buf += _DB.pack(0, 0, depth, branch_pages, leaf_pages,
                        n_overflow, len(pairs), root)            # MAIN_DBI
        buf += struct.pack("<QQ", last_pg, 1)
        return buf + b"\0" * (psize - len(buf))

    with open(out, "wb") as f:
        f.write(meta(0))
        f.write(meta(1))
        for p in pages:
            f.write(p)
    # lock file so liblmdb-based tools can open the env
    open(os.path.join(path, "lock.mdb"), "wb").close()
    return len(pairs)
