"""CIFAR-10 zoo models: cifar10_quick and cifar10_full.

Architectures per the reference zoo (reference:
caffe/examples/cifar10/cifar10_quick_train_test.prototxt and
cifar10_full_train_test.prototxt; the full model's published accuracy is
~75%, caffe/examples/cifar10/readme.md:81).  These are the nets CifarApp
trains (reference: src/main/scala/apps/CifarApp.scala:62-66).
"""

from __future__ import annotations

from ..proto.caffe_pb import NetParameter, Phase
from .dsl import (
    accuracy_layer, convolution_layer, inner_product_layer, java_data_layer,
    layer, net_param, pooling_layer, relu_layer, softmax_with_loss_layer,
)

_LRB = [{"lr_mult": 1.0}, {"lr_mult": 2.0}]


def _data(train_batch: int, test_batch: int):
    return [
        java_data_layer("cifar_train", ["data", "label"], Phase.TRAIN,
                        (train_batch, 3, 32, 32), (train_batch,)),
        java_data_layer("cifar_test", ["data", "label"], Phase.TEST,
                        (test_batch, 3, 32, 32), (test_batch,)),
    ]


def cifar10_quick(train_batch: int = 100, test_batch: int = 100) -> NetParameter:
    g = lambda std: {"type": "gaussian", "std": std}
    zero = {"type": "constant"}
    return net_param("CIFAR10_quick", _data(train_batch, test_batch) + [
        convolution_layer("conv1", "data", "conv1", num_output=32, kernel=5,
                          pad=2, weight_filler=g(0.0001), bias_filler=zero,
                          param=_LRB),
        pooling_layer("pool1", "conv1", "pool1", pool="MAX", kernel=3, stride=2),
        relu_layer("relu1", "pool1"),
        convolution_layer("conv2", "pool1", "conv2", num_output=32, kernel=5,
                          pad=2, weight_filler=g(0.01), bias_filler=zero,
                          param=_LRB),
        relu_layer("relu2", "conv2"),
        pooling_layer("pool2", "conv2", "pool2", pool="AVE", kernel=3, stride=2),
        convolution_layer("conv3", "pool2", "conv3", num_output=64, kernel=5,
                          pad=2, weight_filler=g(0.01), bias_filler=zero,
                          param=_LRB),
        relu_layer("relu3", "conv3"),
        pooling_layer("pool3", "conv3", "pool3", pool="AVE", kernel=3, stride=2),
        inner_product_layer("ip1", "pool3", "ip1", num_output=64,
                            weight_filler=g(0.1), bias_filler=zero, param=_LRB),
        inner_product_layer("ip2", "ip1", "ip2", num_output=10,
                            weight_filler=g(0.1), bias_filler=zero, param=_LRB),
        softmax_with_loss_layer("loss", ["ip2", "label"]),
        accuracy_layer("accuracy", ["ip2", "label"], phase=Phase.TEST),
    ])


def cifar10_full(train_batch: int = 100, test_batch: int = 100) -> NetParameter:
    g = lambda std: {"type": "gaussian", "std": std}
    zero = {"type": "constant"}

    def lrn_within(name: str, bottom: str, top: str):
        return layer(name, "LRN", [bottom], [top], lrn_param={
            "local_size": 3, "alpha": 5e-05, "beta": 0.75,
            "norm_region": "WITHIN_CHANNEL"})

    return net_param("CIFAR10_full", _data(train_batch, test_batch) + [
        convolution_layer("conv1", "data", "conv1", num_output=32, kernel=5,
                          pad=2, weight_filler=g(0.0001), bias_filler=zero,
                          param=_LRB),
        pooling_layer("pool1", "conv1", "pool1", pool="MAX", kernel=3, stride=2),
        relu_layer("relu1", "pool1"),
        lrn_within("norm1", "pool1", "norm1"),
        convolution_layer("conv2", "norm1", "conv2", num_output=32, kernel=5,
                          pad=2, weight_filler=g(0.01), bias_filler=zero,
                          param=_LRB),
        relu_layer("relu2", "conv2"),
        pooling_layer("pool2", "conv2", "pool2", pool="AVE", kernel=3, stride=2),
        lrn_within("norm2", "pool2", "norm2"),
        convolution_layer("conv3", "norm2", "conv3", num_output=64, kernel=5,
                          pad=2, weight_filler=g(0.01), bias_filler=zero,
                          param=_LRB),
        relu_layer("relu3", "conv3"),
        pooling_layer("pool3", "conv3", "pool3", pool="AVE", kernel=3, stride=2),
        inner_product_layer("ip1", "pool3", "ip1", num_output=10,
                            weight_filler=g(0.01), bias_filler=zero,
                            param=[{"lr_mult": 1.0, "decay_mult": 250.0},
                                   {"lr_mult": 2.0, "decay_mult": 0.0}]),
        softmax_with_loss_layer("loss", ["ip1", "label"]),
        accuracy_layer("accuracy", ["ip1", "label"], phase=Phase.TEST),
    ])
