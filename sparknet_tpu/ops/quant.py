"""Shared quantize/dequantize kernels — one kernel, two customers.

Symmetric linear quantization used by (a) the communication-efficient
round exchange (``parallel/comms.py`` — int8/bf16 weight-*delta* codecs
with error feedback, ROADMAP item 5) and (b) the int8 serving path
(ROADMAP item 3a — per-channel scales calibrated offline).  Both callers
need the exact same arithmetic, so it lives here once: pure ``jnp``
element-wise ops that XLA fuses into whatever program consumes them (on
TPU these are VPU-width element-wise passes; no custom kernel is
warranted — see the tiling discussion in the Pallas guide's quantization
pattern, which only pays off fused into a matmul epilogue).

Conventions
-----------
* **Symmetric, zero-point-free**: ``q = clip(round(x / s), -127, 127)``,
  ``x̂ = q·s``.  Weight deltas and activations are centered near zero, so
  an asymmetric zero point buys nothing and would break the cheap
  "q == 0 ⇒ x̂ == 0" invariant the error-feedback path leans on.
* **Scale granularity via ``keep_axes``**: the scale is one value per
  index of the kept axes, reduced over every other axis.  ``()`` is
  per-tensor; ``(0,)`` per-leading-index (per-channel for a [C, ...]
  weight, per-tier-row for a stacked [n_workers, ...] delta);
  ``(0, 1)`` per-(tier, channel).
* **Zero-safe**: an all-zero tensor (or channel) gets scale 1.0, not
  0/127 — dequantize(quantize(0)) is exactly 0 with no NaN/Inf anywhere
  (the very first round's delta against the init broadcast can be all
  zeros for frozen blobs).
* Kernels are shape-polymorphic and dtype-stable: float32 in, float32
  out of the dequantizers, regardless of the wire dtype.
"""

from __future__ import annotations

import jax.numpy as jnp

INT8_LEVELS = 127  # symmetric int8: wire values in [-127, 127] (no -128)


def _reduce_axes(ndim: int, keep_axes: tuple[int, ...]) -> tuple[int, ...]:
    keep = {a % max(ndim, 1) for a in keep_axes}
    return tuple(i for i in range(ndim) if i not in keep)


def int8_scale(x, keep_axes: tuple[int, ...] = ()):
    """Symmetric per-group scale: amax/127 over the reduced axes,
    keepdims so the scale broadcasts straight back onto ``x``.  Zero
    groups get scale 1.0 (see module conventions)."""
    x = jnp.asarray(x, jnp.float32)
    axes = _reduce_axes(x.ndim, keep_axes)
    amax = jnp.max(jnp.abs(x), axis=axes, keepdims=True) if x.ndim \
        else jnp.abs(x)
    return jnp.where(amax > 0, amax / INT8_LEVELS, jnp.ones_like(amax))


def quantize_int8(x, keep_axes: tuple[int, ...] = ()):
    """x -> (q int8, scale f32).  Round-to-nearest-even onto the
    127-level symmetric grid; the clip is belt-and-braces (amax/127
    scaling already bounds |x/s| by 127 up to rounding)."""
    x = jnp.asarray(x, jnp.float32)
    s = int8_scale(x, keep_axes)
    q = jnp.clip(jnp.round(x / s), -INT8_LEVELS, INT8_LEVELS)
    return q.astype(jnp.int8), s


def dequantize_int8(q, s):
    """(q int8, scale) -> f32.  Exact for q == 0 by construction."""
    return q.astype(jnp.float32) * jnp.asarray(s, jnp.float32)


def quantize_bf16(x):
    """f32 -> bf16 wire format (round-to-nearest-even mantissa drop).
    Subnormal f32 values flush through bf16's wider-exponent subnormals
    without becoming inf/NaN — covered by tests."""
    return jnp.asarray(x, jnp.float32).astype(jnp.bfloat16)


def dequantize_bf16(x):
    """bf16 wire -> f32 (exact: every bf16 value is a f32 value)."""
    return jnp.asarray(x).astype(jnp.float32)
