from .dsl import (
    net_param,
    java_data_layer,
    memory_data_layer,
    convolution_layer,
    pooling_layer,
    inner_product_layer,
    relu_layer,
    lrn_layer,
    dropout_layer,
    concat_layer,
    softmax_layer,
    softmax_with_loss_layer,
    accuracy_layer,
    layer,
    msg,
)
from .lenet import lenet
from .cifar10 import cifar10_quick, cifar10_full
from .alexnet import alexnet, caffenet
from .googlenet import googlenet
from .vgg import vgg16
