"""Object-store abstraction for dataset ingestion.

The reference's workers stream training tars directly from S3
(reference: src/main/scala/loaders/ImageNetLoader.scala:25-38 list
objects, :56-86 stream-untar via AmazonS3Client + TarArchiveInputStream).
This module gives the loader chain the same shape — list keys under a
prefix, open a key as a byte stream — over URL-dispatched backends:

- ``file://`` (or a bare path): local filesystem, fully functional.
- ``s3://bucket/prefix``: via boto3 *when installed*; this build has no
  egress and no boto3, so construction raises a clear error telling the
  operator to install boto3 or stage locally (the reference's ec2/ tier
  likewise assumed AWS tooling existed on workers).
- ``gs://bucket/prefix``: same, via google-cloud-storage.

Every store yields file-like objects, so tarfile can stream without
loading archives whole — the property the bounded-RSS ingestion tier
(imagenet.py) relies on.
"""

from __future__ import annotations

import io
import os
import threading
from typing import BinaryIO, Iterator

from ..utils.retry import io_retry


class ObjectStore:
    """list/open interface over a keyed byte store."""

    def list_keys(self, prefix: str = "") -> list[str]:
        raise NotImplementedError

    def open(self, key: str) -> BinaryIO:
        raise NotImplementedError

    def size(self, key: str) -> int:
        raise NotImplementedError

    def open_range(self, key: str, offset: int, length: int) -> bytes:
        """Random-access read (tar-index lazy decode).  Default: seek."""
        with self.open(key) as f:
            f.seek(offset)
            return f.read(length)

    def close(self) -> None:
        """Release any pooled resources (no-op by default)."""


class _PooledFd:
    """One pooled descriptor: refcounted so eviction under concurrent
    readers defers the close to the last reader out."""

    __slots__ = ("fd", "refs", "evicted")

    def __init__(self, fd: int):
        self.fd = fd
        self.refs = 0
        self.evicted = False


class LocalStore(ObjectStore):
    """Filesystem-backed store; keys are paths relative to ``root``.

    ``open_range`` (the lazy-partition / record-shard hot path — one
    call per record, fanned out over the parallel ranged-read pool) is
    fully thread-safe: reads use per-call ``os.pread`` (positioned read,
    no shared seek cursor to race on) against a small LRU pool of raw
    descriptors.  Only pool bookkeeping happens under the lock; the
    actual IO runs outside it, so N pool workers genuinely read in
    parallel.  A descriptor evicted (or ``close()``d) while readers are
    mid-``pread`` stays open until the last of them releases it —
    eviction can never invalidate a concurrent read."""

    _MAX_HANDLES = 8

    def __init__(self, root: str):
        self.root = root
        self._fds: "dict[str, _PooledFd]" = {}
        self._lock = threading.Lock()

    def _acquire(self, key: str) -> _PooledFd:
        with self._lock:
            h = self._fds.get(key)
            if h is not None:
                # re-insert: plain dicts preserve insertion order, so
                # pop+set keeps the dict LRU-first for eviction
                del self._fds[key]
                self._fds[key] = h
                h.refs += 1
                return h
        # open outside the lock (disk metadata IO must not serialize the
        # pool), then publish — racing openers of the same key keep the
        # first published fd and retire their own
        fd = os.open(os.path.join(self.root, key), os.O_RDONLY)
        with self._lock:
            h = self._fds.get(key)
            if h is not None:
                os.close(fd)
                del self._fds[key]
                self._fds[key] = h
                h.refs += 1
                return h
            h = _PooledFd(fd)
            h.refs = 1
            self._fds[key] = h
            while len(self._fds) > self._MAX_HANDLES:
                oldest = next(iter(self._fds))
                self._evict_locked(oldest)
            return h

    def _evict_locked(self, key: str) -> None:
        h = self._fds.pop(key)
        h.evicted = True
        if h.refs == 0:
            os.close(h.fd)

    def _release(self, h: _PooledFd) -> None:
        with self._lock:
            h.refs -= 1
            if h.evicted and h.refs == 0:
                os.close(h.fd)

    def open_range(self, key: str, offset: int, length: int) -> bytes:
        h = self._acquire(key)
        try:
            return os.pread(h.fd, length, offset)
        finally:
            self._release(h)

    def close(self) -> None:
        with self._lock:
            for key in list(self._fds):
                self._evict_locked(key)

    def __del__(self):  # best-effort fd release
        try:
            self.close()
        except Exception:
            pass

    def list_keys(self, prefix: str = "") -> list[str]:
        out = []
        for dirpath, _, files in os.walk(self.root):
            for f in files:
                rel = os.path.relpath(os.path.join(dirpath, f), self.root)
                if rel.startswith(prefix):
                    out.append(rel)
        return sorted(out)

    def open(self, key: str) -> BinaryIO:
        return open(os.path.join(self.root, key), "rb")

    def size(self, key: str) -> int:
        return os.path.getsize(os.path.join(self.root, key))


class S3Store(ObjectStore):
    """S3-backed store (ImageNetLoader.scala's AmazonS3Client role).
    Requires boto3; reads stream via GetObject (ranged for open_range)."""

    def __init__(self, bucket: str, region: str | None = None):
        try:
            import boto3  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "s3:// sources need boto3, which is not in this build — "
                "stage the tars locally (file://) or install boto3 on the "
                "ingest hosts") from e
        import boto3
        self.bucket = bucket
        self._s3 = boto3.client("s3", region_name=region)

    def list_keys(self, prefix: str = "") -> list[str]:
        keys = []
        paginator = self._s3.get_paginator("list_objects_v2")
        for page in paginator.paginate(Bucket=self.bucket, Prefix=prefix):
            keys.extend(o["Key"] for o in page.get("Contents", []))
        return sorted(keys)

    def open(self, key: str) -> BinaryIO:
        body = self._s3.get_object(Bucket=self.bucket, Key=key)["Body"]
        return io.BufferedReader(body)  # type: ignore[arg-type]

    def size(self, key: str) -> int:
        return self._s3.head_object(Bucket=self.bucket,
                                    Key=key)["ContentLength"]

    def open_range(self, key: str, offset: int, length: int) -> bytes:
        rng = f"bytes={offset}-{offset + length - 1}"
        return self._s3.get_object(Bucket=self.bucket, Key=key,
                                   Range=rng)["Body"].read()


class GCSStore(ObjectStore):
    """GCS-backed store; requires google-cloud-storage."""

    def __init__(self, bucket: str):
        try:
            from google.cloud import storage  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "gs:// sources need google-cloud-storage, which is not in "
                "this build — stage the tars locally (file://) or install "
                "it on the ingest hosts") from e
        from google.cloud import storage
        try:
            self._bucket = storage.Client().bucket(bucket)
        except Exception as e:  # no ADC credentials on this host
            raise RuntimeError(
                f"gs://{bucket} is unreachable from this host ({e}); "
                "stage the tars locally (file://) or configure GCP "
                "credentials on the ingest hosts") from e

    def list_keys(self, prefix: str = "") -> list[str]:
        return sorted(b.name for b in self._bucket.list_blobs(prefix=prefix))

    def open(self, key: str) -> BinaryIO:
        return self._bucket.blob(key).open("rb")

    def size(self, key: str) -> int:
        blob = self._bucket.get_blob(key)
        return blob.size if blob else 0

    def open_range(self, key: str, offset: int, length: int) -> bytes:
        return self._bucket.blob(key).download_as_bytes(
            start=offset, end=offset + length - 1)


class VerifyingStore(ObjectStore):
    """Per-record integrity tier over any store: ``open_range`` reads go
    through bounded transient-I/O retry (``utils.retry.io_retry``) and,
    when a checksum is registered for the (key, offset) range, the
    payload's crc32 is verified — with ONE fresh re-read before declaring
    corruption, so a torn read is distinguished from rot on the medium.
    A durable mismatch raises ``DataCorruptionError`` carrying the key
    and byte offset (the quarantine layer's attribution unit).

    This is the checksum the reference never had: its workers stream-
    untar straight from S3 (ImageNetLoader.scala:56-86) and a flipped
    byte in a JPEG payload is silently decoded or silently dropped.
    Build the checksum index at ingest time (``add_checksum`` per record
    while writing the tar index) and every later read is self-verifying.
    """

    def __init__(self, inner: ObjectStore,
                 checksums: dict[tuple[str, int], int] | None = None):
        self.inner = inner
        self.checksums = dict(checksums or {})

    def add_checksum(self, key: str, offset: int, crc: int) -> None:
        self.checksums[(key, offset)] = crc & 0xFFFFFFFF

    def checksum_range(self, key: str, offset: int, length: int) -> int:
        """Read + register a range's crc32 (the ingest-time half)."""
        from .integrity import crc32
        raw = io_retry(self.inner.open_range, key, offset, length,
                       describe=f"open_range {key}@{offset}")
        crc = crc32(raw)
        self.add_checksum(key, offset, crc)
        return crc

    def open_range(self, key: str, offset: int, length: int) -> bytes:
        from .integrity import DataCorruptionError, crc32
        raw = io_retry(self.inner.open_range, key, offset, length,
                       describe=f"open_range {key}@{offset}")
        expect = self.checksums.get((key, offset))
        if expect is None or crc32(raw) == expect:
            return raw
        # one fresh read: a transient torn read heals, real rot does not
        raw = io_retry(self.inner.open_range, key, offset, length,
                       describe=f"re-read {key}@{offset}")
        got = crc32(raw)
        if got != expect:
            raise DataCorruptionError(
                f"record checksum mismatch: crc32 {got:#010x} != "
                f"expected {expect:#010x} ({length} bytes)",
                source=key, key=key, offset=offset)
        return raw

    def list_keys(self, prefix: str = "") -> list[str]:
        return self.inner.list_keys(prefix)

    def open(self, key: str):
        return self.inner.open(key)

    def size(self, key: str) -> int:
        return self.inner.size(key)

    def close(self) -> None:
        self.inner.close()


def get_store(url: str) -> tuple[ObjectStore, str]:
    """URL -> (store, key prefix).  Bare paths and file:// map to
    LocalStore; s3://bucket/p and gs://bucket/p to their clients."""
    if url.startswith("s3://"):
        bucket, _, prefix = url[5:].partition("/")
        return S3Store(bucket), prefix
    if url.startswith("gs://"):
        bucket, _, prefix = url[5:].partition("/")
        return GCSStore(bucket), prefix
    path = url[7:] if url.startswith("file://") else url
    return LocalStore(path), ""
