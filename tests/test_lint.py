"""sparklint self-tests: planted-violation fixtures per rule family
(trace purity, knob registry, concurrency discipline, deprecation
hygiene), the suppression-comment and baseline round trips, and the
self-run gate — the committed tree must lint clean against the
committed baseline, which is exactly what tools/run_tier1.sh enforces.

Everything here is pure-AST and JAX-free by construction (the analyzer
never imports jax), so the whole module runs in well under a second.
"""

import json
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from sparknet_tpu.analysis import engine  # noqa: E402
from sparknet_tpu.analysis.core import Baseline, SourceFile  # noqa: E402

pytestmark = pytest.mark.lint


def plant(tmp_path, files):
    """Materialize {rel: source} as a scannable project and lint it."""
    for rel, text in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(text))
    return engine.load_project(tmp_path)


def rules_of(findings):
    return {f.rule for f in findings}


# ---------------------------------------------------------------------------
# trace purity
# ---------------------------------------------------------------------------

IMPURE_JIT = """\
    import os
    import random
    import time

    import jax
    import numpy as np


    @jax.jit
    def step(x):
        if os.environ.get("HOME"):          # TP001
            pass
        t = time.time()                      # TP002
        r = random.random()                  # TP003
        open("/tmp/x").read()                # TP004
        print("tracing", t, r)               # TP005
        return np.asarray(x)                 # TP006
"""


def test_purity_flags_every_sin_class_under_jit(tmp_path):
    project = plant(tmp_path, {"sparknet_tpu/mod.py": IMPURE_JIT})
    found = rules_of(engine.run_rules(project, {"purity"}))
    assert {"TP001", "TP002", "TP003", "TP004", "TP005",
            "TP006"} <= found


def test_purity_ignores_untraced_functions(tmp_path):
    # the same sins in a plain helper are host-side code, not findings
    project = plant(tmp_path, {"sparknet_tpu/mod.py": textwrap.dedent(
        IMPURE_JIT).replace("@jax.jit\n", "")})
    assert engine.run_rules(project, {"purity"}) == []


def test_purity_follows_the_call_graph(tmp_path):
    project = plant(tmp_path, {"sparknet_tpu/mod.py": """\
        import os

        import jax


        def helper():
            return os.environ.get("HOME")    # reached from a jit root


        @jax.jit
        def step(x):
            helper()
            return x
    """})
    findings = engine.run_rules(project, {"purity"})
    assert [f.rule for f in findings] == ["TP001"]
    assert findings[0].symbol == "helper"


# ---------------------------------------------------------------------------
# knob registry
# ---------------------------------------------------------------------------

def test_unregistered_knob_read_is_kr001_and_kr002(tmp_path):
    project = plant(tmp_path, {"sparknet_tpu/mod.py": """\
        import os

        x = os.environ.get("SPARKNET_NOT_A_REAL_KNOB")
    """})
    found = rules_of(engine.run_rules(project, {"knobs"}))
    assert "KR001" in found and "KR002" in found


def test_registered_read_outside_registry_is_kr002_only(tmp_path):
    project = plant(tmp_path, {"sparknet_tpu/mod.py": """\
        import os

        x = os.environ.get("SPARKNET_TUNE")
    """})
    found = rules_of(engine.run_rules(project, {"knobs"}))
    assert "KR002" in found and "KR001" not in found


def test_env_writes_and_scrub_pops_are_allowed(tmp_path):
    project = plant(tmp_path, {"sparknet_tpu/mod.py": """\
        import os

        os.environ["SPARKNET_TUNE"] = "off"
        os.environ.pop("SPARKNET_TUNE", None)
    """})
    assert not any(f.rule == "KR002"
                   for f in engine.run_rules(project, {"knobs"}))


def test_unregistered_literal_helper_arg_is_kr001(tmp_path):
    # helper delegation must not launder an unregistered name
    project = plant(tmp_path, {"sparknet_tpu/mod.py": """\
        def _env_float(name, default):
            return default

        x = _env_float("SPARKNET_NOT_A_REAL_KNOB", 1.0)
    """})
    assert "KR001" in rules_of(engine.run_rules(project, {"knobs"}))


def test_committed_registry_has_no_dead_or_undocumented_knobs():
    project = engine.load_project(REPO)
    findings = engine.run_rules(project, {"knobs"})
    assert [f for f in findings if f.rule in ("KR003", "KR004")] == []


# ---------------------------------------------------------------------------
# concurrency discipline
# ---------------------------------------------------------------------------

WORKER = """\
    import threading


    class Worker:
        def __init__(self):
            self.count = 0
            self._t = threading.Thread(target=self._run)

        def _run(self):
            while True:
                try:
                    self.count = self.count + 1
                except Exception:
                    pass

        def reset(self):
            self.count = 0
"""


def test_unguarded_cross_thread_write_is_cd001(tmp_path):
    project = plant(tmp_path, {"sparknet_tpu/mod.py": WORKER})
    assert "CD001" in rules_of(engine.run_rules(project, {"concurrency"}))


def test_unguarded_ok_declaration_silences_cd001(tmp_path):
    src = textwrap.dedent(WORKER).replace(
        "    def __init__",
        '    _unguarded_ok = frozenset({"count"})\n\n    def __init__')
    project = plant(tmp_path, {"sparknet_tpu/mod.py": src})
    assert "CD001" not in rules_of(
        engine.run_rules(project, {"concurrency"}))


def test_lock_guarded_writes_are_not_cd001(tmp_path):
    project = plant(tmp_path, {"sparknet_tpu/mod.py": """\
        import threading


        class Worker:
            def __init__(self):
                self._lock = threading.Lock()
                self.count = 0
                self._t = threading.Thread(target=self._run)

            def _run(self):
                with self._lock:
                    self.count = 1

            def reset(self):
                with self._lock:
                    self.count = 0
    """})
    assert "CD001" not in rules_of(
        engine.run_rules(project, {"concurrency"}))


def test_swallowing_worker_handler_is_cd002(tmp_path):
    project = plant(tmp_path, {"sparknet_tpu/mod.py": WORKER})
    assert "CD002" in rules_of(engine.run_rules(project, {"concurrency"}))


def test_parking_the_error_on_self_satisfies_cd002(tmp_path):
    src = textwrap.dedent(WORKER).replace(
        "            except Exception:\n"
        "                pass",
        "            except Exception as e:\n"
        "                self.err = e")
    project = plant(tmp_path, {"sparknet_tpu/mod.py": src})
    found = rules_of(engine.run_rules(project, {"concurrency"}))
    assert "CD002" not in found
    # still broad — CD003 stays, to be narrowed or baselined with a
    # reason; parking only clears the swallow-in-worker charge
    assert "CD003" in found


def test_plain_overbroad_handler_is_cd003(tmp_path):
    project = plant(tmp_path, {"sparknet_tpu/mod.py": """\
        def f():
            try:
                g()
            except Exception:
                pass
    """})
    assert "CD003" in rules_of(engine.run_rules(project, {"concurrency"}))


# ---------------------------------------------------------------------------
# deprecation hygiene
# ---------------------------------------------------------------------------

def test_removed_knob_mention_is_dp002(tmp_path):
    # SPARKNET_LRN_CUMSUM is a real tombstone in the committed registry
    project = plant(tmp_path, {"sparknet_tpu/mod.py": """\
        import os

        os.environ["SPARKNET" + "_LRN_CUMSUM"] = "1"  # dodge is fine
        PIN = "SPARKNET_LRN_CUMSUM"
    """})
    findings = engine.run_rules(project, {"deprecation"})
    assert [f.rule for f in findings] == ["DP002"]
    assert findings[0].line == 4


def test_dead_symbol_reference_is_dp003(tmp_path):
    project = plant(tmp_path, {"sparknet_tpu/mod.py": """\
        from sparknet_tpu.graph import tuner

        tuner._shim_pin("lrn")
    """})
    assert "DP003" in rules_of(engine.run_rules(project, {"deprecation"}))


# ---------------------------------------------------------------------------
# suppressions
# ---------------------------------------------------------------------------

def test_same_line_and_next_line_suppressions(tmp_path):
    project = plant(tmp_path, {"sparknet_tpu/mod.py": """\
        def f():
            try:
                g()
            except Exception:  # sparklint: disable=CD003
                pass


        def h():
            try:
                g()
            # sparklint: disable-next-line=CD003
            except Exception:
                pass


        def unsuppressed():
            try:
                g()
            except Exception:
                pass
    """})
    findings = engine.run_rules(project, {"concurrency"})
    assert [f.symbol for f in findings] == ["unsuppressed"]


def test_disable_all_suppresses_every_rule(tmp_path):
    project = plant(tmp_path, {"sparknet_tpu/mod.py": """\
        import os

        x = os.environ.get("SPARKNET_NOT_A_REAL_KNOB")  # sparklint: disable=all
    """})
    assert engine.run_rules(project, {"knobs"}) == []


def test_suppression_comment_grammar():
    sf = SourceFile(Path("/x"), "m.py",
                    "a = 1  # sparklint: disable=TP001, CD003\n"
                    "# sparklint: disable-next-line=KR002\n"
                    "b = 2\n")
    assert sf.suppressed(1, "TP001") and sf.suppressed(1, "CD003")
    assert not sf.suppressed(1, "KR002")
    assert sf.suppressed(3, "KR002") and not sf.suppressed(2, "KR002")


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------

def test_baseline_round_trip_covers_by_symbol_not_line(tmp_path):
    project = plant(tmp_path, {"sparknet_tpu/mod.py": """\
        def f():
            try:
                g()
            except Exception:
                pass
    """})
    [finding] = engine.run_rules(project, {"concurrency"})
    entries = [{"rule": finding.rule, "path": finding.path,
                "symbol": finding.symbol, "reason": "planted"}]
    path = tmp_path / "baseline.json"
    path.write_text(Baseline.render(entries))
    baseline = Baseline.load(path)
    kept, covered = engine.apply_baseline([finding], baseline)
    assert kept == [] and covered == [finding]
    assert baseline.unused() == []


def test_unused_baseline_entries_are_reported(tmp_path):
    baseline = Baseline([{"rule": "CD003", "path": "gone.py",
                          "symbol": "f", "reason": "stale"}])
    kept, covered = engine.apply_baseline([], baseline)
    assert kept == [] and covered == []
    assert [e["path"] for e in baseline.unused()] == ["gone.py"]


def test_baseline_rejects_empty_reasons():
    with pytest.raises(ValueError, match="reason"):
        Baseline([{"rule": "CD003", "path": "x.py", "symbol": "f",
                   "reason": "  "}])


def test_committed_baseline_has_no_todo_reasons():
    doc = json.loads((REPO / engine.BASELINE_REL).read_text())
    assert doc["kind"] == "sparklint_baseline"
    todo = [e for e in doc["entries"] if e["reason"].startswith("TODO")]
    assert todo == []


# ---------------------------------------------------------------------------
# the CI gate: committed tree is clean
# ---------------------------------------------------------------------------

def _lint_cli(*args):
    return subprocess.run(
        [sys.executable, str(REPO / "tools" / "lint.py"), *args],
        capture_output=True, text=True, cwd=REPO)


def test_self_run_committed_tree_is_clean():
    res = _lint_cli("run")
    assert res.returncode == 0, res.stdout + res.stderr
    assert "0 error(s)" in res.stdout
    # every grandfathered entry still matches a real finding
    assert "unused baseline entry" not in res.stdout


def test_knobs_md_is_in_sync():
    res = _lint_cli("knobs", "--check")
    assert res.returncode == 0, res.stdout + res.stderr
