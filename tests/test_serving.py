"""Serving plane tests: dynamic micro-batching, admission control,
hot-load/evict, liveness — the batching-core coverage ISSUE 7 demands.

The contracts under test:
- pad-and-mask: a request batched with strangers returns bit-identical
  logits to a solo run padded to the same compiled shape;
- dispatch ordering: a full largest-shape batch goes immediately, a
  partial batch waits exactly until the coalesce deadline;
- overload: typed, bounded rejections (queue bound + tenant QPS), never
  unbounded latency;
- hot-load eviction under the HBM budget (LRU, never the newest);
- a dead engine is a typed EngineDead on every waiter and later submit
  — never a hang (the DecodePool contract, mirrored).
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time

import numpy as np
import pytest

from sparknet_tpu.parallel.serving import (
    EngineDead,
    InferenceEngine,
    ModelHouse,
    Overloaded,
    ServeConfig,
    ServingError,
    UnknownModel,
    deploy_from,
    run_closed_loop,
    solo_references,
    zoo_models,
)

pytestmark = pytest.mark.serving


# ---------------------------------------------------------------------------
# Shared rig: one compiled lenet house per module (warm-up is the slow part)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def lenet_house():
    cfg = ServeConfig(batch_shapes=(1, 4, 8), max_delay_ms=30.0,
                      max_queue=64, dtype="f32", beat_every_s=0.05)
    house = ModelHouse(cfg)
    house.load("lenet")
    return house


def engine_for(house, **overrides) -> InferenceEngine:
    return InferenceEngine(house,
                           dataclasses.replace(house.cfg, **overrides))


def lenet_inputs(n: int, seed: int = 0):
    rng = np.random.default_rng(seed)
    return [rng.normal(size=(1, 28, 28)).astype(np.float32)
            for _ in range(n)]


class _StubModel:
    """House-injectable model with scriptable behavior — the serving
    analog of the fault-injection stand-ins the data plane tests use."""

    def __init__(self, fn=None, in_shape=(2,), classes=3,
                 shapes=(1, 2, 4), param_bytes=128):
        self.name = "stub"
        self.in_shape = tuple(in_shape)
        self.classes = classes
        self.batch_shapes = tuple(shapes)
        self.param_bytes = param_bytes
        self.last_used = 0.0
        self.weights = None
        self.fn = fn

    def pad_shape(self, n: int) -> int:
        for s in self.batch_shapes:
            if s >= n:
                return s
        return self.batch_shapes[-1]

    def infer_async(self, batch):
        if self.fn is not None:
            return self.fn(batch)
        # row i depends only on input row i (per-example net analog)
        return np.tile(batch.sum(axis=1, keepdims=True),
                       (1, self.classes)).astype(np.float32)

    def info(self):
        return {"name": self.name, "stub": True}


def stub_house(stub: _StubModel, **cfg_over) -> ModelHouse:
    cfg_over.setdefault("batch_shapes", stub.batch_shapes)
    cfg_over.setdefault("dtype", "f32")
    house = ModelHouse(ServeConfig(**cfg_over))
    house._models["stub"] = stub
    return house


# ---------------------------------------------------------------------------
# Deploy transform + zoo
# ---------------------------------------------------------------------------

def test_deploy_from_lenet_strips_train_plumbing():
    from sparknet_tpu.models import lenet
    deploy, in_shape = deploy_from(lenet(32, 100), max_batch=8)
    types = [lp.type for lp in deploy.layer]
    assert "JavaData" not in types and "Accuracy" not in types
    assert not any(t.endswith("Loss") for t in types)
    assert types[-1] == "Softmax" and deploy.layer[-1].top == ["prob"]
    # the softmax head sits on the loss layer's logits bottom
    assert deploy.layer[-1].bottom == ["ip2"]
    assert deploy.input == ["data"]
    assert list(deploy.input_shape[0].dim) == [8, 1, 28, 28]
    assert in_shape == (1, 28, 28)


def test_deploy_from_builds_runnable_net_with_matching_param_names():
    import jax

    from sparknet_tpu.graph.net import Net
    from sparknet_tpu.models import lenet
    from sparknet_tpu.proto import NetState, Phase
    deploy, _ = deploy_from(lenet(4, 4), max_batch=4)
    net = Net(deploy, NetState(Phase.TEST))
    params = net.init(jax.random.PRNGKey(0))
    # same layer names as the train net: trained weights load by name
    train_net = Net(lenet(4, 4), NetState(Phase.TRAIN))
    train_params = train_net.init(jax.random.PRNGKey(0))
    assert set(params) == set(train_params)
    out = net.apply(params, {"data": np.zeros((4, 1, 28, 28), np.float32)},
                    train=False).blobs
    probs = np.asarray(out["prob"])
    assert probs.shape == (4, 10)
    np.testing.assert_allclose(probs.sum(axis=1), 1.0, rtol=1e-5)


def test_deploy_from_googlenet_uses_main_head():
    from sparknet_tpu.models import googlenet
    deploy, in_shape = deploy_from(googlenet(1, 1, crop=224), max_batch=4)
    assert in_shape == (3, 224, 224)
    # the TEST-phase head is loss3's classifier; aux heads are TRAIN-only
    assert deploy.layer[-1].type == "Softmax"
    assert "loss3" in deploy.layer[-1].bottom[0]


def test_zoo_registry_names():
    zoo = zoo_models()
    for name in ("lenet", "caffenet", "googlenet", "vgg16",
                 "cifar10_quick"):
        assert name in zoo


# ---------------------------------------------------------------------------
# Pad-and-mask bit-identity (acceptance claim (c))
# ---------------------------------------------------------------------------

def test_batched_with_strangers_bit_identical_to_solo(lenet_house):
    """6 concurrent requests coalesce into one padded batch; every row
    must equal the solo run of that input padded to the same shape."""
    xs = lenet_inputs(6)
    lm = lenet_house.get("lenet")
    refs = solo_references(lm, xs)
    with engine_for(lenet_house, max_delay_ms=60.0) as eng:
        futs = [eng.submit("lenet", x) for x in xs]
        res = [f.result(20.0) for f in futs]
    # they actually rode together (coalescing happened, pad rows exist)
    assert {r.padded_to for r in res} == {8}
    assert all(r.batch_n == 6 for r in res)
    for i, r in enumerate(res):
        assert np.array_equal(r.probs, refs[8][i]), f"row {i} differs"


def test_solo_request_through_engine_matches_reference(lenet_house):
    xs = lenet_inputs(3, seed=7)
    lm = lenet_house.get("lenet")
    refs = solo_references(lm, xs)
    with engine_for(lenet_house, max_delay_ms=0.0) as eng:
        for i, x in enumerate(xs):
            r = eng.classify("lenet", x)
            assert np.array_equal(r.probs, refs[r.padded_to][i])


# ---------------------------------------------------------------------------
# Dispatch ordering: full batch beats deadline; deadline pads the tail
# ---------------------------------------------------------------------------

def test_full_batch_dispatches_before_deadline(lenet_house):
    """With a deliberately huge deadline, a largest-shape batch must
    dispatch immediately — the deadline only governs PARTIAL batches."""
    xs = lenet_inputs(8)
    with engine_for(lenet_house, max_delay_ms=5000.0) as eng:
        t0 = time.monotonic()
        futs = [eng.submit("lenet", x) for x in xs]
        res = [f.result(20.0) for f in futs]
        elapsed = time.monotonic() - t0
    assert elapsed < 2.0, f"full batch waited on the deadline ({elapsed}s)"
    assert all(r.padded_to == 8 and r.batch_n == 8 for r in res)


def test_partial_batch_waits_for_deadline_then_pads(lenet_house):
    delay_ms = 250.0
    with engine_for(lenet_house, max_delay_ms=delay_ms) as eng:
        t0 = time.monotonic()
        fut = eng.submit("lenet", lenet_inputs(1)[0])
        res = fut.result(20.0)
        elapsed = time.monotonic() - t0
    # a lone request rides the smallest compiled shape, after the delay
    assert res.batch_n == 1 and res.padded_to == 1
    assert elapsed >= 0.8 * delay_ms / 1000.0, \
        f"partial batch dispatched at {elapsed * 1e3:.0f} ms, " \
        f"before the {delay_ms} ms deadline"


def test_two_requests_pad_to_middle_shape(lenet_house):
    xs = lenet_inputs(2)
    with engine_for(lenet_house, max_delay_ms=120.0) as eng:
        futs = [eng.submit("lenet", x) for x in xs]
        res = [f.result(20.0) for f in futs]
    assert all(r.batch_n == 2 and r.padded_to == 4 for r in res)


def test_latency_stamps_ride_every_result(lenet_house):
    with engine_for(lenet_house, max_delay_ms=50.0) as eng:
        r = eng.classify("lenet", lenet_inputs(1)[0])
    assert r.total_ms >= r.infer_ms >= 0
    assert r.queue_ms >= 0
    assert r.total_ms == pytest.approx(r.queue_ms + r.infer_ms, abs=5.0)


# ---------------------------------------------------------------------------
# Admission control: typed and bounded
# ---------------------------------------------------------------------------

def test_queue_bound_rejects_typed_and_recovers():
    """A slow model backs the queue up; submits past the bound raise
    Overloaded(queue_full); every ACCEPTED request still completes."""
    stub = _StubModel(fn=lambda b: (time.sleep(0.05),
                                    np.ones((b.shape[0], 3), np.float32)
                                    )[1],
                      shapes=(1,))
    house = stub_house(stub, max_delay_ms=0.0, max_queue=4)
    accepted, rejected = [], 0
    with InferenceEngine(house, house.cfg) as eng:
        for _ in range(25):
            try:
                accepted.append(eng.submit("stub",
                                           np.ones(2, np.float32)))
            except Overloaded as e:
                assert e.reason == "queue_full"
                rejected += 1
        assert rejected > 0, "queue bound never engaged"
        # outstanding work never exceeded the bound
        assert len(accepted) <= 4 + 25 - rejected
        for f in accepted:
            f.result(20.0)                       # all accepted complete
        assert eng.rejected["queue_full"] == rejected


def test_tenant_qps_cap_rejects_only_that_tenant(lenet_house):
    with engine_for(lenet_house, max_delay_ms=0.0,
                    tenant_qps={"acme": 2.0}) as eng:
        x = lenet_inputs(1)[0]
        ok, capped = 0, 0
        for _ in range(10):
            try:
                eng.submit("lenet", x, tenant="acme")
                ok += 1
            except Overloaded as e:
                assert e.reason == "tenant_rate"
                capped += 1
        assert ok >= 1 and capped >= 6  # burst of 2, then the cap bites
        # an uncapped tenant sails through the same instant
        for _ in range(5):
            eng.submit("lenet", x, tenant="other")
        assert eng.rejected["tenant_rate"] == capped


def test_wrong_input_shape_is_typed(lenet_house):
    with engine_for(lenet_house) as eng:
        with pytest.raises(ServingError, match="expects input"):
            eng.submit("lenet", np.zeros((3, 10, 10), np.float32))


def test_unloaded_model_is_typed_not_compiled(lenet_house):
    with engine_for(lenet_house) as eng:
        with pytest.raises(UnknownModel, match="not loaded"):
            eng.submit("vgg16", np.zeros((3, 224, 224), np.float32))
    assert "vgg16" not in lenet_house.loaded()  # no implicit hot-load


# ---------------------------------------------------------------------------
# Hot-load / evict under the HBM budget
# ---------------------------------------------------------------------------

def test_hot_load_eviction_under_hbm_budget():
    cfg = ServeConfig(batch_shapes=(1, 2), max_delay_ms=1.0, dtype="f32")
    probe = ModelHouse(dataclasses.replace(cfg, hbm_budget_mb=1024.0))
    lenet_bytes = probe.load("lenet").param_bytes
    # budget fits lenet alone but not lenet + cifar10_quick
    budget_mb = lenet_bytes * 1.2 / 2**20
    house = ModelHouse(dataclasses.replace(cfg, hbm_budget_mb=budget_mb))
    house.load("lenet")
    assert set(house.loaded()) == {"lenet"}
    house.load("cifar10_quick")
    assert set(house.loaded()) == {"cifar10_quick"}, \
        "LRU model must be evicted when the budget trips"
    assert house.evictions == 1
    # the evicted model is gone for submit (typed), reloadable on demand
    with InferenceEngine(house, house.cfg) as eng:
        with pytest.raises(UnknownModel):
            eng.submit("lenet", np.zeros((1, 28, 28), np.float32))
    house.load("lenet")   # hot reload evicts the now-LRU cifar
    assert set(house.loaded()) == {"lenet"}


def test_explicit_evict_and_reload(lenet_house):
    cfg = ServeConfig(batch_shapes=(1, 2), max_delay_ms=1.0, dtype="f32")
    house = ModelHouse(cfg)
    house.load("cifar10_quick")
    assert house.evict("cifar10_quick") is True
    assert house.evict("cifar10_quick") is False
    assert house.loaded() == {}


def test_oversize_model_admitted_alone_with_note(capsys):
    stub = _StubModel(param_bytes=10 * 2**20)
    house = stub_house(stub, hbm_budget_mb=1.0)
    house._evict_over_budget(keep="stub")
    assert "exceeds" in capsys.readouterr().err
    assert set(house._models) == {"stub"}


# ---------------------------------------------------------------------------
# Dead engine: typed errors, never a hang (the DecodePool contract)
# ---------------------------------------------------------------------------

def test_dispatcher_death_fails_pending_typed_never_hangs():
    """A BaseException out of the hot path kills the engine; the pending
    waiter gets EngineDead within the poll bound, not a hang."""
    boom = KeyboardInterrupt("injected dispatcher death")

    def die(batch):
        raise boom

    stub = _StubModel(fn=die, shapes=(1,))
    house = stub_house(stub, max_delay_ms=0.0)
    eng = InferenceEngine(house, house.cfg)
    fut = eng.submit("stub", np.ones(2, np.float32))
    t0 = time.monotonic()
    with pytest.raises(EngineDead, match="dispatcher died"):
        fut.result(10.0)
    assert time.monotonic() - t0 < 5.0, "dead engine must not hang waiters"
    assert not eng.alive
    with pytest.raises(EngineDead):
        eng.submit("stub", np.ones(2, np.float32))
    eng.stop()   # idempotent on a dead engine


def test_model_failure_fails_batch_but_engine_survives():
    calls = []

    def flaky(batch):
        calls.append(batch.shape[0])
        if len(calls) == 1:
            raise RuntimeError("transient model failure")
        return np.ones((batch.shape[0], 3), np.float32)

    stub = _StubModel(fn=flaky, shapes=(1,))
    house = stub_house(stub, max_delay_ms=0.0)
    with InferenceEngine(house, house.cfg) as eng:
        with pytest.raises(ServingError, match="transient model failure"):
            eng.classify("stub", np.ones(2, np.float32))
        assert eng.alive, "a per-batch failure must not kill the engine"
        r = eng.classify("stub", np.ones(2, np.float32))
        assert r.probs.shape == (3,)
        assert eng.failed == 1 and eng.completed == 1


def test_stop_fails_queued_requests_typed():
    stub = _StubModel(fn=lambda b: (time.sleep(0.2),
                                    np.ones((b.shape[0], 3), np.float32)
                                    )[1],
                      shapes=(1,))
    house = stub_house(stub, max_delay_ms=0.0, max_queue=16)
    eng = InferenceEngine(house, house.cfg)
    futs = [eng.submit("stub", np.ones(2, np.float32)) for _ in range(6)]
    eng.stop()
    outcomes = []
    for f in futs:
        try:
            f.result(10.0)
            outcomes.append("ok")
        except EngineDead:
            outcomes.append("dead")
    # in-flight work may drain; everything still queued dies typed
    assert "dead" in outcomes
    assert set(outcomes) <= {"ok", "dead"}


# ---------------------------------------------------------------------------
# Telemetry: occupancy, stats, beacons
# ---------------------------------------------------------------------------

def test_stats_and_occupancy_histogram(lenet_house):
    xs = lenet_inputs(6)
    with engine_for(lenet_house, max_delay_ms=60.0) as eng:
        futs = [eng.submit("lenet", x) for x in xs]
        for f in futs:
            f.result(20.0)
        st = eng.stats()
    assert st["completed"] == 6
    assert st["occupancy"] == {"8": {6: 1}}
    assert st["p99_ms"] >= st["p50_ms"] >= 0
    assert st["models"]["lenet"]["in_shape"] == [1, 28, 28]
    assert st["queue_depth"] == 0 and st["in_flight"] == 0


def test_engine_publishes_health_beacons(lenet_house, tmp_path,
                                         monkeypatch):
    from sparknet_tpu.parallel import health
    monkeypatch.setenv("SPARKNET_HEARTBEAT_DIR", str(tmp_path))
    monkeypatch.setenv("SPARKNET_PROC_ID", "0")
    eng = engine_for(lenet_house, max_delay_ms=0.0)
    try:
        eng.classify("lenet", lenet_inputs(1)[0])
        deadline = time.monotonic() + 5.0
        beat = None
        while time.monotonic() < deadline:
            beat = health.read_beat(str(tmp_path), 0)
            if beat is not None and beat.extras:
                break
            time.sleep(0.02)
        assert beat is not None and beat.phase == "serving"
        assert beat.extras["serving"] is True
        assert beat.extras["models"] == ["lenet"]
        for key in ("queue_depth", "in_flight_batches", "p50_ms",
                    "p99_ms", "completed", "rejected"):
            assert key in beat.extras
    finally:
        eng.stop()
    final = health.read_beat(str(tmp_path), 0)
    assert final is not None and final.phase == "final"


def test_fleet_status_folds_serving_beat():
    from sparknet_tpu.parallel.fleet import format_status
    status = {
        "devices": {"total": 8, "free": 7},
        "tenants": {"svc": {"used": 1, "quota": 2}},
        "jobs": [{
            "job": "serve-a", "tenant": "svc", "state": "RUNNING",
            "priority": 0, "eff_priority": 0.0, "world": 1,
            "slots": [0], "episodes": 1, "attempts": 0, "preempts": 0,
            "round": 42, "rounds_target": 0,
            "heartbeats": {0: {"round": 42, "phase": "serving",
                               "age_s": 0.5,
                               "extras": {"serving": True,
                                          "queue_depth": 3,
                                          "in_flight": 8,
                                          "p50_ms": 6.0,
                                          "p99_ms": 21.0}}},
        }],
    }
    table = format_status(status)
    assert "serving@42" in table
    assert "q3+8" in table and "p99 21ms" in table


# ---------------------------------------------------------------------------
# Closed-loop harness
# ---------------------------------------------------------------------------

def test_closed_loop_exact_and_live(lenet_house):
    xs = lenet_inputs(8)
    lm = lenet_house.get("lenet")
    refs = solo_references(lm, xs)
    with engine_for(lenet_house, max_delay_ms=3.0) as eng:
        rep = run_closed_loop(eng, "lenet", xs, clients=4, window=4,
                              duration_s=0.5, refs=refs)
    assert rep["completed"] > 0 and rep["errors"] == 0
    assert rep["exact_mismatches"] == 0
    assert rep["p99_ms"] >= rep["p50_ms"] > 0
    assert rep["achieved_qps"] > 0


def test_config_validation():
    with pytest.raises(ValueError, match="batch_shapes"):
        ServeConfig(batch_shapes=(0, 4))
    with pytest.raises(ValueError, match="max_delay_ms"):
        ServeConfig(max_delay_ms=-1.0)
    with pytest.raises(ValueError, match="max_queue"):
        ServeConfig(max_queue=0)
    with pytest.raises(ValueError, match="dtype"):
        ServeConfig(dtype="f16")
    with pytest.raises(ValueError, match="qps cap"):
        ServeConfig(tenant_qps={"a": 0.0})
    with pytest.raises(ValueError, match="inflight"):
        ServeConfig(inflight_batches=0)
    assert ServeConfig(batch_shapes=(8, 1, 4)).batch_shapes == (1, 4, 8)


def test_env_knobs(monkeypatch):
    monkeypatch.setenv("SPARKNET_SERVE_SHAPES", "16,2")
    monkeypatch.setenv("SPARKNET_SERVE_MAX_DELAY_MS", "7.5")
    monkeypatch.setenv("SPARKNET_SERVE_QUEUE", "33")
    monkeypatch.setenv("SPARKNET_SERVE_DTYPE", "f32")
    cfg = ServeConfig()
    assert cfg.batch_shapes == (2, 16)
    assert cfg.max_delay_ms == 7.5
    assert cfg.max_queue == 33
    assert cfg.dtype == "f32"
    monkeypatch.setenv("SPARKNET_SERVE_SHAPES", "nope")
    with pytest.raises(ValueError, match="SPARKNET_SERVE_SHAPES"):
        ServeConfig()


# ---------------------------------------------------------------------------
# Shared preprocessing (classify.py dedup)
# ---------------------------------------------------------------------------

def test_shared_preprocess_helper_matches_local_semantics():
    from sparknet_tpu.classify import preprocess_image, transform_crops
    img_hwc = np.arange(2 * 4 * 4, dtype=np.float32).reshape(4, 4, 2)
    out = preprocess_image(img_hwc, (4, 4))
    assert out.shape == (2, 4, 4)          # HWC -> CHW
    np.testing.assert_array_equal(out[0], img_hwc[:, :, 0])
    swapped = preprocess_image(np.ones((3, 4, 4), np.float32) *
                               np.arange(3, dtype=np.float32)[:, None,
                                                              None],
                               (4, 4), channel_swap=(2, 1, 0),
                               raw_scale=2.0)
    assert swapped[0, 0, 0] == 4.0 and swapped[2, 0, 0] == 0.0
    crops = np.ones((2, 1, 2, 2), np.float32)
    out = transform_crops(crops, mean=0.5, input_scale=10.0)
    np.testing.assert_array_equal(out, np.full_like(crops, 5.0))


def test_classifier_preprocess_delegates_to_shared(tmp_path):
    """Classifier._preprocess and the module-level helper are the same
    code path — the server/client dedup the satellite asks for."""
    from sparknet_tpu.classify import Classifier, preprocess_image
    proto = tmp_path / "deploy.prototxt"
    proto.write_text("""
name: "tiny"
input: "data"
input_shape { dim: 1 dim: 1 dim: 8 dim: 8 }
layer { name: "ip" type: "InnerProduct" bottom: "data" top: "ip"
  inner_product_param { num_output: 2 } }
layer { name: "prob" type: "Softmax" bottom: "ip" top: "prob" }
""")
    c = Classifier(str(proto), image_dims=(8, 8), raw_scale=3.0)
    img = np.random.default_rng(0).normal(size=(8, 8)).astype(np.float32)
    np.testing.assert_array_equal(
        c._preprocess(img),
        preprocess_image(img, (8, 8), raw_scale=3.0))


# ---------------------------------------------------------------------------
# HTTP server e2e (subprocess; the in-tree smoke of tools/serve.py)
# ---------------------------------------------------------------------------

def test_serve_http_end_to_end(tmp_path):
    import signal
    import subprocess
    import sys
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               SPARKNET_HEARTBEAT_DIR=str(tmp_path),
               SPARKNET_PROC_ID="0")
    env.pop("XLA_FLAGS", None)
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.Popen(
        [sys.executable, os.path.join(root, "tools", "serve.py"),
         "--models", "lenet", "--port", "0", "--dtype", "f32",
         "--shapes", "1,4", "--max-delay-ms", "3",
         "--quota", "capped=1"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        env=env, text=True, cwd=root)
    try:
        ready = proc.stdout.readline().strip()
        assert ready.startswith("serving on http://"), ready
        url = ready.split()[2]
        from sparknet_tpu.classify import (
            RemoteClassifier, http_json, remote_classify,
        )
        x = np.random.default_rng(0).normal(size=(1, 28, 28)
                                            ).astype(np.float32)
        d = remote_classify(url, "lenet", x)
        assert len(d["probs"]) == 10 and d["padded_to"] in (1, 4)
        assert d["total_ms"] >= d["infer_ms"] >= 0
        # wire result == local engine math: probs sum to 1
        assert abs(sum(d["probs"]) - 1.0) < 1e-4
        # typed admission over the wire: tenant cap -> HTTP 429
        saw_429 = False
        for _ in range(5):
            try:
                remote_classify(url, "lenet", x, tenant="capped")
            except RuntimeError as e:
                assert "429" in str(e)
                saw_429 = True
        assert saw_429
        # unknown model -> 404 with the typed reason
        with pytest.raises(RuntimeError, match="404"):
            remote_classify(url, "nope", x)
        # healthz + hot-load/evict round trip
        hz = http_json(f"{url}/healthz")
        assert hz["alive"] and hz["completed"] >= 1
        assert hz["slo"]["state"] == "ok"
        # GET /slo: healthy under this trickle (the handful of tenant-cap
        # rejections above sits below slo_min_requests — no page)
        slo = http_json(f"{url}/slo")
        assert slo["state"] == "ok" and slo["breaches"] == []
        assert slo["declared"]["reject_budget"] == 0.02
        assert slo["windows"]["fast"]["requests"] >= 0
        assert http_json(f"{url}/v1/models/load",
                         {"name": "cifar10_quick"})["loaded"]["name"] \
            == "cifar10_quick"
        assert http_json(f"{url}/v1/models/evict",
                         {"name": "cifar10_quick"})["evicted"] is True
        # RemoteClassifier: shared preprocessing + server-side coalesce
        rc = RemoteClassifier(url, "lenet")
        assert (rc.channels, rc.crop) == (1, 28)
        probs = rc.predict([np.random.default_rng(1).normal(
            size=(32, 32)).astype(np.float32)])
        assert probs.shape == (1, 10)
        assert abs(float(probs.sum()) - 1.0) < 1e-4
    finally:
        proc.send_signal(signal.SIGTERM)
        assert proc.wait(timeout=30) == 0


def test_serveload_smoke_gate():
    """The CI servesmoke (run_tier1.sh --servesmoke) must pass: exact
    results, bounded p99 under overload, typed rejections."""
    import subprocess
    import sys
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)
    proc = subprocess.run(
        [sys.executable, os.path.join(root, "tools", "serveload.py"),
         "--smoke"],
        capture_output=True, timeout=240, cwd=root, env=env)
    assert proc.returncode == 0, proc.stderr.decode()[-1500:]
    import json
    rep = json.loads(proc.stdout.decode().strip().splitlines()[-1])
    v = rep["verdicts"]
    assert v["bit_identical"] is True
    assert v["overload_p99_bounded"] is True
    assert v["overload_rejected"] > 0
