#!/usr/bin/env bash
# The tier-1 gate, exactly as ROADMAP.md specifies it, plus the chaos
# (fault-injection) subset — one entry point so CI and humans always run
# the same command.  Usage:
#   tools/run_tier1.sh            # tier-1 (everything not marked slow)
#   tools/run_tier1.sh --chaos    # only the chaos marker subset
#   tools/run_tier1.sh --all      # tier-1, then the chaos subset again
set -o pipefail
cd "$(dirname "$0")/.."

run_tier1() {
  rm -f /tmp/_t1.log
  timeout -k 10 870 env JAX_PLATFORMS=cpu \
    python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors \
    -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
  rc=${PIPESTATUS[0]}
  echo "DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log \
    | tr -cd . | wc -c)"
  return "$rc"
}

# ~1-second sparklint gate (tools/lint.py run) — DEFAULT ON, pure-AST +
# stdlib (no JAX, no devices): the tree must be clean modulo the
# committed tools/lint_baseline.json, and KNOBS.md must match the knob
# registry.  SPARKNET_LINT=0 is the opt-out for rigs that only want the
# pytest surface.
maybe_lint() {
  if [ "${SPARKNET_LINT:-1}" != "0" ]; then
    timeout -k 10 120 python tools/lint.py run       && timeout -k 10 60 python tools/lint.py knobs --check
  fi
}

run_chaos() {
  timeout -k 10 870 env JAX_PLATFORMS=cpu \
    python -m pytest tests/ -q -m 'chaos and not slow' \
    -p no:cacheprovider -p no:xdist -p no:randomly
}

# 2-run chaos soak smoke (tools/soak.py) — opt-in via SPARKNET_SOAK=1 so
# the default tier-1 wall time is untouched; CI rigs that can afford it
# get randomized-but-seeded fault schedules checked for exact recovery.
maybe_soak() {
  if [ "${SPARKNET_SOAK:-}" = "1" ]; then
    timeout -k 10 600 env JAX_PLATFORMS=cpu \
      python tools/soak.py --runs 2 --seed "${SPARKNET_SOAK_SEED:-0}" \
      --out /tmp/_soak.json
  fi
}

# ~2-second serial-vs-parallel feed microbench (tools/feedbench.py) —
# opt-in via SPARKNET_FEEDBENCH=1.  Fails the gate on any parity
# mismatch: the parallel pipeline must be bit-identical to the serial
# reference, including quarantine accounting under corrupt_record
# faults.  (A fast in-tree smoke of the same parity contract always
# runs inside tier-1: tests/test_pipeline.py.)
maybe_feedbench() {
  if [ "${SPARKNET_FEEDBENCH:-}" = "1" ]; then
    timeout -k 10 120 env JAX_PLATFORMS=cpu \
      python tools/feedbench.py --seconds 2 --out /tmp/_feedbench.json \
      && timeout -k 10 120 env JAX_PLATFORMS=cpu \
        python tools/feedbench.py --seconds 2 --corrupt \
          --out /tmp/_feedbench_corrupt.json
  fi
}

# ~3-second record-shard parity gate (tools/feedbench.py --records-leg)
# — opt-in via SPARKNET_RECORDBENCH=1.  Converts a tiny synthetic LMDB
# to pre-decoded record shards and replays the SAME batches from local
# shards, from a VerifyingStore through the tiered ShardCache (RAM +
# disk spill), and warm — all must be bit-identical to the serial
# decode reference (pixels, labels, quarantine admissions), clean and
# under corrupt_record injection, with cold/warm cache-tier hits
# asserted and a planted corrupt shard block quarantined with source
# attribution.
maybe_recordbench() {
  if [ "${SPARKNET_RECORDBENCH:-}" = "1" ]; then
    timeout -k 10 180 env JAX_PLATFORMS=cpu \
      python tools/feedbench.py --seconds 2 --records-leg \
      --out /tmp/_recordbench.json \
      && timeout -k 10 180 env JAX_PLATFORMS=cpu \
        python tools/feedbench.py --seconds 2 --records-leg --corrupt \
          --out /tmp/_recordbench_corrupt.json
  fi
}

# ~60-second two-job fleet chaos smoke (tools/soak.py --fleet 2) — opt-in
# via SPARKNET_FLEETSOAK=1.  Two concurrent jobs under one FleetScheduler
# with pinned crash + preempt schedules, plus a late whole-budget
# high-priority preemptor: every job must finish bit-identical to its
# fault-free baseline, with preempt/resume exercised and zero orphaned
# worker processes.  (The full acceptance run is
# `python tools/soak.py --fleet 4 --fleet-kill`, which additionally
# SIGKILLs the scheduler mid-run and resumes it from its journal.)
maybe_fleetsoak() {
  if [ "${SPARKNET_FLEETSOAK:-}" = "1" ]; then
    timeout -k 10 600 env JAX_PLATFORMS=cpu \
      python tools/soak.py --fleet 2 --seed "${SPARKNET_SOAK_SEED:-0}" \
      --out /tmp/_fleetsoak.json
  fi
}

# ~60-second simulated 3-host pod burn-in slice (tools/soak.py --pod 3
# --pod-slice) — opt-in via SPARKNET_PODSOAK=1.  Two training tenants +
# one replicated serving tenant on a 3-host simulated pod under the
# seeded traffic model, with one host-kill fired mid-leg through the
# host-control channel and one flash crowd: the episode must end with
# both trainings bit-identical to the fault-free baseline, zero
# client-visible serving errors, the serving tier healed, the
# corrupt-upload quarantine burst absorbed-and-typed, and zero orphaned
# workers.  (The full acceptance run adds the host-drain and
# serving-host-loss legs: `python tools/soak.py --pod 3`.)
maybe_podsoak() {
  if [ "${SPARKNET_PODSOAK:-}" = "1" ]; then
    timeout -k 10 600 env JAX_PLATFORMS=cpu \
      python tools/soak.py --pod 3 --pod-slice \
      --seed "${SPARKNET_SOAK_SEED:-0}" --out /tmp/_podsoak.json
  fi
}

# ~60-second network chaos slice (tools/soak.py --net --net-slice) —
# opt-in via SPARKNET_NETSOAK=1.  Two legs over the production ssh wire
# format (SshTransport through a local fake-ssh shim) wrapped in
# ChaosTransport: a symmetric partition mid-round must SUSPEND the gang
# (suspect, not straggler-killed, no restart-budget burn), heal, and
# finish bit-identical to the fault-free baseline; and a fenced
# checkpoint ship — torn first transfer resumed crc-verified onto a
# checkpoint-less host, bit-identical resume, zombie writer refused at
# the fence with a typed error.  (The full acceptance run adds the
# slow-link-attribution leg: `python tools/soak.py --net`.)
maybe_netsoak() {
  if [ "${SPARKNET_NETSOAK:-}" = "1" ]; then
    timeout -k 10 600 env JAX_PLATFORMS=cpu \
      python tools/soak.py --net --net-slice \
      --seed "${SPARKNET_SOAK_SEED:-0}" --out /tmp/_netsoak.json
  fi
}

# ~30-second rollout smoke (tools/soak.py --rollout) — opt-in via
# SPARKNET_ROLLSMOKE=1.  Three deployment-plane legs over a real model
# registry + router + per-version engines: a healthy canary must earn
# promotion (green per-version SLO verdicts over the request floor,
# old stable drained, pinned answers bit-identical across the pointer
# flip), a planted bad_canary fault (NaN-emitting head, failed TYPED
# by the engine) must auto-roll back within the judge window with zero
# stable-pinned errors and a flight dump on disk, and a controller
# killed mid-rollout must resume to exactly one of {fully stable,
# fully promoted} with no orphan replicas.
maybe_rollsmoke() {
  if [ "${SPARKNET_ROLLSMOKE:-}" = "1" ]; then
    timeout -k 10 600 env JAX_PLATFORMS=cpu \
      python tools/soak.py --rollout --seed "${SPARKNET_SOAK_SEED:-0}" \
      --out /tmp/_rollsmoke.json
  fi
}

# ~2-second serving smoke (tools/serveload.py --smoke) — opt-in via
# SPARKNET_SERVESMOKE=1.  In-process engine + closed-loop clients;
# fails the gate unless results are bit-identical to solo references,
# p99 under 2x overload stays inside the admission bound, and the
# overload produces typed rejections (admission control engaged).
maybe_servesmoke() {
  if [ "${SPARKNET_SERVESMOKE:-}" = "1" ]; then
    timeout -k 10 180 env JAX_PLATFORMS=cpu \
      python tools/serveload.py --smoke --out /tmp/_servesmoke.json \
      > /dev/null
  fi
}

# Serving-fleet smoke (tools/serveload.py --fleet 2 --smoke) — opt-in
# via SPARKNET_FLEETSERVESMOKE=1.  Two replica subprocesses placed as
# serve-kind fleet tenants behind the request router: paced load must
# stay error-free and bit-identical to local solo references, a
# SIGKILLed replica must fail over typed-only (zero request errors,
# zero hangs) and heal back to N, and a mid-load scale-down must drain
# losslessly to COMPLETED.  (~10 s on a multicore rig; single-core CI
# boxes pay replica startup serially, hence the generous timeout.)
maybe_fleetservesmoke() {
  if [ "${SPARKNET_FLEETSERVESMOKE:-}" = "1" ]; then
    timeout -k 10 480 env JAX_PLATFORMS=cpu \
      python tools/serveload.py --fleet 2 --smoke \
      --out /tmp/_fleetservesmoke.json > /dev/null
  fi
}

# ~10-second observability smoke (tools/obs.py smoke) — opt-in via
# SPARKNET_OBSSMOKE=1.  Runs a 2-round training per rank (two driver
# runs sharing one SPARKNET_RUN_ID) plus a live tools/serve.py driven
# over HTTP, all with tracing on; fails the gate unless
# `tools/obs.py merge --check` yields a valid merged trace (spans from
# both ranks, correlation IDs on every span, aligned monotonic
# timestamps) and `GET /metrics` parses as Prometheus text.
maybe_obssmoke() {
  if [ "${SPARKNET_OBSSMOKE:-}" = "1" ]; then
    timeout -k 10 300 env JAX_PLATFORMS=cpu \
      python tools/obs.py smoke --out /tmp/_obssmoke.json > /dev/null
  fi
}

# ~10-second sync-vs-async outer-loop parity smoke (tools/roundbench.py)
# — opt-in via SPARKNET_ROUNDBENCH=1.  Fails the gate unless the
# pipelined loop (harvest_lag + AsyncCheckpointWriter) reproduces the
# synchronous loop's round losses, final params, and newest checkpoint
# bit for bit, with ckpt+guard+audit all enabled.  (A fast in-tree smoke
# of the same contract always runs inside tier-1: tests/test_resilience.py.)
maybe_roundbench() {
  if [ "${SPARKNET_ROUNDBENCH:-}" = "1" ]; then
    timeout -k 10 180 env JAX_PLATFORMS=cpu \
      python tools/roundbench.py --rounds 6 --out /tmp/_roundbench.json
  fi
}

# ~15-second comm-codec parity gate (tools/commbench.py) — opt-in via
# SPARKNET_COMMBENCH=1.  Fails the gate unless codec "none" (overlap on
# or off) is bit-identical to the pre-codec trainer, every real codec
# satisfies the error-feedback invariant while a planted
# residual-dropping codec is caught, int8/bf16 delta exchange converges
# inside the declared loss band, overlapped dispatch is bit-identical
# with less measured comm stall, and the int8 wire shrink is >= 3x.  (A
# fast in-tree smoke of the same contracts runs inside tier-1:
# tests/test_comms.py.)
maybe_commbench() {
  if [ "${SPARKNET_COMMBENCH:-}" = "1" ]; then
    timeout -k 10 180 env JAX_PLATFORMS=cpu \
      python tools/commbench.py --out /tmp/_commbench.json
  fi
}

# ~15-second hybrid-sharding parity gate (tools/shardbench.py) — opt-in
# via SPARKNET_SHARDSMOKE=1.  Runs a 2x2-able CPU mesh dryrun and fails
# the gate unless shard="auto" is bit-identical to the replicated
# trainer for all three strategies (codec none) AND composed with the
# int8 exchange, the per-shard checkpoint tiles roundtrip bit-exactly,
# a world-N checkpoint re-tiles onto world-M, the shard-aware audit
# catches a planted one-bit flip with the right culprit and rolls back,
# and the analytic τ-boundary bytes shrink (>= 2x on caffenet-class
# shapes at 8 shards).  (A fast in-tree smoke of the same contracts
# runs inside tier-1: tests/test_partition.py.)
maybe_shardsmoke() {
  if [ "${SPARKNET_SHARDSMOKE:-}" = "1" ]; then
    timeout -k 10 300 env JAX_PLATFORMS=cpu \
      python tools/shardbench.py --out /tmp/_shardbench.json
  fi
}

# ~7-second vertical-fusion parity gate (tools/fusebench.py) — opt-in
# via SPARKNET_FUSEBENCH=1.  Fails the gate unless fused execution
# (SPARKNET_FUSE=all) reproduces per-layer execution bit-for-bit in the
# forward (f32 + bf16), matches gradients inside the documented ulp
# bound on every chain shape (conv+bias+relu, +pool, +LRN), refuses a
# planted unfusable (fan-out) hotspot with a recorded reason, and does
# not slow the LRN-chain train step down.  (A fast in-tree smoke of the
# same contracts always runs inside tier-1: tests/test_fusion.py.)
maybe_fusebench() {
  if [ "${SPARKNET_FUSEBENCH:-}" = "1" ]; then
    timeout -k 10 120 env JAX_PLATFORMS=cpu \
      python tools/fusebench.py --out /tmp/_fusebench.json
  fi
}

# ~10-second lowering-autotuner self-test (tools/tune.py tunebench) —
# opt-in via SPARKNET_TUNEBENCH=1.  Tunes a 2-op synthetic net on CPU
# and fails unless the measured winner beats a planted 3x-work slow
# candidate, a planted numerics-bad candidate is disqualified before it
# can win, SPARKNET_TUNE=off vs the fresh table is forward-bit-identical
# (grads <= 1e-5) through the production layers, the fresh table passes
# the staleness gate, and a planted rotten winner fails it.  (The same
# contracts run in-process in tests/test_tuner.py; the committed-table
# parity tests there cover the real profiles/cpu/tuning.json.)
maybe_tunebench() {
  if [ "${SPARKNET_TUNEBENCH:-}" = "1" ]; then
    timeout -k 10 180 env JAX_PLATFORMS=cpu \
      python tools/tune.py tunebench --json /tmp/_tunebench.json
  fi
}

# ~10-second performance gate (tools/perfwatch.py perfgate) — opt-in
# via SPARKNET_PERFGATE=1.  Runs a ~2s-leg CPU bench smoke through the
# regression sentinel against the committed perf/LEDGER.jsonl (CPU
# fingerprints never gate against the TPU history — wide CPU bands via
# --min-band-pct for rigs that HAVE CPU history), then a sentinel
# self-test: a planted slow feed leg (BENCH_FEED_DELAY_S) must exit
# non-zero with stage attribution naming the decode stage.
maybe_perfgate() {
  if [ "${SPARKNET_PERFGATE:-}" = "1" ]; then
    timeout -k 10 480 env JAX_PLATFORMS=cpu \
      python tools/perfwatch.py perfgate --json /tmp/_perfgate.json
  fi
}

case "${1:-}" in
  --chaos) run_chaos ;;
  --lint)  SPARKNET_LINT=1 maybe_lint ;;
  --soak)  SPARKNET_SOAK=1 maybe_soak ;;
  --fleetsoak) SPARKNET_FLEETSOAK=1 maybe_fleetsoak ;;
  --podsoak) SPARKNET_PODSOAK=1 maybe_podsoak ;;
  --netsoak) SPARKNET_NETSOAK=1 maybe_netsoak ;;
  --rollsmoke) SPARKNET_ROLLSMOKE=1 maybe_rollsmoke ;;
  --feedbench) SPARKNET_FEEDBENCH=1 maybe_feedbench ;;
  --recordbench) SPARKNET_RECORDBENCH=1 maybe_recordbench ;;
  --roundbench) SPARKNET_ROUNDBENCH=1 maybe_roundbench ;;
  --commbench) SPARKNET_COMMBENCH=1 maybe_commbench ;;
  --shardsmoke) SPARKNET_SHARDSMOKE=1 maybe_shardsmoke ;;
  --servesmoke) SPARKNET_SERVESMOKE=1 maybe_servesmoke ;;
  --fleetservesmoke) SPARKNET_FLEETSERVESMOKE=1 maybe_fleetservesmoke ;;
  --obssmoke) SPARKNET_OBSSMOKE=1 maybe_obssmoke ;;
  --perfgate) SPARKNET_PERFGATE=1 maybe_perfgate ;;
  --fusebench) SPARKNET_FUSEBENCH=1 maybe_fusebench ;;
  --tunebench) SPARKNET_TUNEBENCH=1 maybe_tunebench ;;
  --all)   maybe_lint && run_tier1 && run_chaos && maybe_soak \
             && maybe_fleetsoak && maybe_podsoak && maybe_netsoak \
             && maybe_rollsmoke \
             && maybe_feedbench && maybe_recordbench && maybe_servesmoke \
             && maybe_fleetservesmoke && maybe_roundbench \
             && maybe_commbench && maybe_shardsmoke \
             && maybe_obssmoke && maybe_fusebench && maybe_tunebench \
             && maybe_perfgate ;;
  "")      maybe_lint && run_tier1 && maybe_soak && maybe_fleetsoak \
             && maybe_podsoak && maybe_netsoak && maybe_rollsmoke \
             && maybe_feedbench && maybe_recordbench \
             && maybe_servesmoke && maybe_fleetservesmoke \
             && maybe_roundbench && maybe_commbench && maybe_shardsmoke \
             && maybe_obssmoke \
             && maybe_fusebench && maybe_tunebench && maybe_perfgate ;;
  *) echo "usage: $0 [--chaos|--lint|--soak|--fleetsoak|--podsoak|--netsoak|--rollsmoke|--feedbench|--recordbench|--roundbench|--commbench|--shardsmoke|--servesmoke|--fleetservesmoke|--obssmoke|--fusebench|--tunebench|--perfgate|--all]" >&2
     exit 2 ;;
esac
