"""τ × codec sweep: the paper's communication-period knob, judged by
the learning proxy.

SparkNet's central empirical claim is that the communication period τ
trades per-iteration progress against round overhead, with a broad
sweet spot (paper Fig. 6).  PR 19 adds a second axis to that trade:
HOW MUCH each τ-boundary exchange costs on the wire.  This driver runs
the full grid — τ ∈ {--taus} × codec ∈ {--codecs} — through the same
8-way vmapped local-SGD machinery as ``tools/learning_proxy.py`` (the
single-chip restatement of the mesh trainer's ``local_sgd`` strategy),
with the τ-boundary exchange routed through the SAME codec registry
the trainer uses (``parallel/comms.py``): each round's weight delta
against the last broadcast reference is encoded, decoded, averaged,
and the per-worker compression error is carried forward as an
error-feedback residual — exactly the trainer's compressed-exchange
semantics (``DistributedTrainer._build_comm_programs``), restated for
one chip so the whole grid fits a CPU rig in minutes.

Every cell emits the learning-proxy judge's row shape (iter, lr,
train_loss, train_acc, test_acc, wall_s) so the accuracy trajectory
plots on a wall-clock x-axis, plus the analytic per-round exchange
bytes (``comms.exchange_bytes`` over the real encode).  The verdict
per τ: does each lossy codec land inside ``--band`` of codec ``none``
at the SAME τ ("τ-matched band") while shrinking the wire?

Results merge into the learning-proxy RESULTS file under a ``sweep``
key (existing curves untouched); ``tools/plot_learning_proxy.py``
renders the sweep panel alongside the headline figure.

Usage:
  python tools/tausweep.py [--taus 2,10] [--codecs none,bf16,int8]
      [--scale 200] [--out RESULTS_learning_proxy.json]
  (add --platform cpu to force the host backend)
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--taus", default="2,10",
                    help="comma list of communication periods")
    ap.add_argument("--codecs", default="none,bf16,int8",
                    help="comma list of comms.py codec names")
    ap.add_argument("--scale", type=int, default=200,
                    help="schedule divisor vs the published 70k config")
    ap.add_argument("--batch", type=int, default=100,
                    help="per-worker batch (the published config's 100 "
                         "costs ~46ms/image on a 1-core CPU rig — shrink "
                         "it there, it is recorded in the sweep config)")
    ap.add_argument("--base-lr", type=float, default=0.001,
                    help="base learning rate (the published 0.001 needs "
                         "~750 iters before accuracy moves; a short CPU "
                         "grid can raise it — recorded in the config)")
    ap.add_argument("--snr-boost", type=float, default=1.0,
                    help="scale the generator's class-signal-to-noise "
                         "ratio: template amp x this, distractor amp "
                         "and pixel noise / this.  1.0 = the published "
                         "hard-SNR generator, whose chance-level "
                         "plateau runs ~50k samples — a 1-core CPU "
                         "grid cannot cross it, so boost SNR there "
                         "(recorded in the sweep config)")
    ap.add_argument("--workers", type=int, default=8)
    ap.add_argument("--n-train", type=int, default=2000)
    ap.add_argument("--n-test", type=int, default=400)
    ap.add_argument("--eval-every", type=int, default=0,
                    help="iters between eval rows (0 = max_iter//5)")
    ap.add_argument("--band", type=float, default=0.05,
                    help="τ-matched accuracy band vs codec none")
    ap.add_argument("--out", default="RESULTS_learning_proxy.json")
    ap.add_argument("--platform", default=None)
    args = ap.parse_args(argv)
    taus = [int(t) for t in args.taus.split(",")]
    codec_names = args.codecs.split(",")

    import jax
    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    import jax.numpy as jnp
    from jax import lax

    from learning_proxy import build
    from sparknet_tpu.data.synthgen import synth_splits
    from sparknet_tpu.models import cifar10_full
    from sparknet_tpu.parallel import comms
    from sparknet_tpu.solvers.lr_policies import learning_rate

    tree_map = jax.tree_util.tree_map

    # the published schedule, proportionally scaled (learning_proxy.py)
    S = args.scale
    max_iter = 70000 // S
    steps = (60000 // S, 65000 // S)
    batch = args.batch
    sp_text = (
        f"base_lr: {args.base_lr}\nmomentum: 0.9\nweight_decay: 0.004\n"
        'lr_policy: "multistep"\ngamma: 0.1\n'
        f"stepvalue: {steps[0]}\nstepvalue: {steps[1]}\n"
        f"max_iter: {max_iter}\n")
    eval_every = args.eval_every or max(max_iter // 5, 1)

    t0 = time.time()
    data_kw = {}
    if args.snr_boost != 1.0:
        data_kw = dict(amp=0.9 * args.snr_boost,
                       distract_amp=0.7 / args.snr_boost,
                       noise=1.15 / args.snr_boost)
    train_x, train_y, test_x, test_y = synth_splits(args.n_train,
                                                    args.n_test,
                                                    **data_kw)
    train_q = np.clip(np.round(train_x), 0, 255).astype(np.uint8)
    test_q = np.clip(np.round(test_x), 0, 255).astype(np.uint8)
    mean = train_q.astype(np.float32).mean(axis=0, keepdims=True)
    dev = jax.devices()[0]
    print(f"# {dev.platform}/{dev.device_kind}; generated "
          f"{args.n_train}+{args.n_test} images in {time.time() - t0:.1f}s",
          flush=True)
    tx = jax.device_put(jnp.asarray(train_q))
    ty = jax.device_put(jnp.asarray(train_y, jnp.float32))
    vx = jax.device_put(jnp.asarray(test_q))
    vy = jax.device_put(jnp.asarray(test_y, jnp.float32))
    mean_d = jax.device_put(jnp.asarray(mean))

    def prep(img_u8):
        return img_u8.astype(jnp.float32) - mean_d

    sp, train_net, test_net, params0, state0, local_update, _ = build(
        sp_text, cifar10_full(batch, batch))

    @jax.jit
    def accuracy(params, x, y):
        n = x.shape[0]
        nb = n // batch

        def body(c, i):
            sl = lambda a: lax.dynamic_slice_in_dim(a, i * batch, batch)
            out = test_net.apply(
                params, {"data": prep(sl(x)), "label": sl(y)},
                train=False)
            return c + out.blobs["accuracy"], 0.0

        total, _ = lax.scan(body, jnp.zeros(()), jnp.arange(nb))
        return total / nb

    W = args.workers
    part = args.n_train // W
    vm_update = jax.vmap(local_update, in_axes=(0, 0, None, 0, 0))

    def make_rounds(codec, tau):
        """Compiled chunk of rounds with the compressed τ-boundary
        exchange: τ local steps per worker, then delta-vs-reference
        encode/decode with error feedback (the trainer's
        _build_comm_programs semantics on a stacked worker axis)."""

        def rounds(wparams, wstate, ref, res, it0, idxs, rng):
            """idxs: [n_rounds, tau, W, batch] partition-local."""
            def round_body(carry, round_idx):
                wparams, wstate, ref, res, it, rng = carry

                def step(c, step_idx):
                    wparams, wstate, it, rng = c
                    rng, sub = jax.random.split(rng)
                    subs = jax.random.split(sub, W)
                    offs = jnp.arange(W)[:, None] * part
                    b = {"data": prep(tx[step_idx + offs])[:, None],
                         "label": ty[step_idx + offs][:, None]}
                    wparams, wstate, loss = vm_update(wparams, wstate, it,
                                                      b, subs)
                    return (wparams, wstate, it + 1, rng), jnp.mean(loss)

                (wparams, wstate, it, rng), losses = lax.scan(
                    step, (wparams, wstate, it, rng), round_idx)
                delta = tree_map(lambda l, r, e: l - r[None] + e,
                                 wparams, ref, res)
                _, decoded, res = comms.roundtrip_tree(codec, delta)
                ref = tree_map(lambda r, d: r + jnp.mean(d, axis=0),
                               ref, decoded)
                wparams = tree_map(
                    lambda r, x: jnp.broadcast_to(r[None], x.shape),
                    ref, wparams)
                return (wparams, wstate, ref, res, it, rng), \
                    jnp.mean(losses)

            (wparams, wstate, ref, res, it, _), losses = lax.scan(
                round_body, (wparams, wstate, ref, res, it0, rng), idxs)
            return wparams, wstate, ref, res, jnp.mean(losses)

        return jax.jit(rounds)

    bytes_none = comms.exchange_bytes(comms.get_codec("none"), params0, W)

    def run_cell(codec_name, tau, key):
        codec = comms.get_codec(codec_name)
        rounds_fn = make_rounds(codec, tau)
        stack = lambda x: jnp.broadcast_to(x[None], (W,) + x.shape)
        wparams = tree_map(stack, params0)
        wstate = tree_map(stack, state0)
        ref = params0
        res = tree_map(lambda x: jnp.zeros((W,) + x.shape, jnp.float32),
                       params0)
        rng = jax.random.PRNGKey(key)
        rng_idx = np.random.default_rng(11)   # same batches per cell
        rounds_per_eval = max(eval_every // tau, 1)
        curve = []
        it = 0
        t_run = time.time()
        while it < max_iter:
            n_rounds = min(rounds_per_eval, (max_iter - it) // tau)
            if n_rounds == 0:
                break
            idxs = rng_idx.integers(0, part,
                                    size=(n_rounds, tau, W, batch))
            rng, sub = jax.random.split(rng)
            wparams, wstate, ref, res, loss = rounds_fn(
                wparams, wstate, ref, res, it, jnp.asarray(idxs), sub)
            it += n_rounds * tau
            row = {"iter": it,
                   "lr": float(learning_rate(sp, it - 1)),
                   "train_loss": float(loss),
                   "train_acc": float(accuracy(
                       ref, tx[:args.n_test], ty[:args.n_test])),
                   "test_acc": float(accuracy(ref, vx, vy)),
                   "wall_s": round(time.time() - t_run, 1)}
            curve.append(row)
            print(f"tau{tau:<3d} {codec_name:12s} iter {it:5d} "
                  f"loss {row['train_loss']:.3f} "
                  f"test_acc {row['test_acc']:.3f} "
                  f"({row['wall_s']}s)", flush=True)
        cell_bytes = comms.exchange_bytes(codec, params0, W)
        # final_acc averages the last two eval rows: the multistep x0.1
        # drops land in the final fifth of the schedule, so the tail
        # mean spans the converged region and damps single-row eval
        # noise that would otherwise dominate the band verdict
        tail = [r["test_acc"] for r in curve[-2:]]
        return {
            "tau": tau, "codec": codec_name, "curve": curve,
            "final_acc": float(np.mean(tail)),
            "wall_s": round(time.time() - t_run, 1),
            "rounds": max_iter // tau,
            "exchange_bytes_per_round": cell_bytes,
            "bytes_shrink_x": round(bytes_none / cell_bytes, 3),
        }

    cells = {}
    for ti, tau in enumerate(taus):
        for name in codec_names:
            # same init, rng stream, and batch sequence for every codec
            # at a given τ: the codec is the ONLY difference inside a
            # τ-matched comparison
            cells[f"tau{tau}_{name}"] = run_cell(name, tau, 500 + 10 * ti)

    # τ-matched band verdict: every lossy codec vs none at the SAME τ
    band_ok = {}
    for tau in taus:
        base = cells.get(f"tau{tau}_none")
        if base is None:
            continue
        for name in codec_names:
            if name == "none":
                continue
            cell = cells[f"tau{tau}_{name}"]
            drift = abs(cell["final_acc"] - base["final_acc"])
            band_ok[f"tau{tau}_{name}"] = {
                "drift": round(drift, 4),
                "ok": bool(drift <= args.band),
            }

    sweep = {
        "config": {
            "scale": S, "max_iter": max_iter, "stepvalues": list(steps),
            "base_lr": args.base_lr,
            "snr_boost": args.snr_boost,
            "batch": batch, "n_train": args.n_train,
            "n_test": args.n_test, "workers": W,
            "taus": taus, "codecs": codec_names, "band": args.band,
        },
        "device": f"{dev.platform}/{dev.device_kind}",
        "exchange_bytes_none": bytes_none,
        "cells": cells,
        "band_ok": band_ok,
    }

    results = {}
    if os.path.exists(args.out):
        with open(args.out) as f:
            results = json.load(f)
    results["sweep"] = sweep
    with open(args.out, "w") as f:
        json.dump(results, f, indent=1)

    summary = {
        "final_acc": {k: c["final_acc"] for k, c in cells.items()},
        "wall_s": {k: c["wall_s"] for k, c in cells.items()},
        "bytes_shrink_x": {k: c["bytes_shrink_x"]
                           for k, c in cells.items()},
        "band_ok": band_ok,
    }
    print(json.dumps(summary), flush=True)
    return 0 if all(v["ok"] for v in band_ok.values()) else 1


if __name__ == "__main__":
    raise SystemExit(main())
