#!/usr/bin/env python
"""Closed-loop serving load generator — the latency-vs-offered-QPS story.

Drives the serving plane with closed-loop clients and emits one
BENCH-style JSON report covering the three acceptance claims of the
serving subsystem:

(a) **dynamic batching wins**: saturation throughput of the
    micro-batching engine vs a batch=1 engine (same model, same compiled
    kernels, shapes pinned to ``(1,)`` and coalescing off) — the
    Caffe-con-Troll "the harness is the win" number.
(b) **overload degrades into typed rejections, not latency collapse**:
    at 2x the measured saturation QPS the bounded queue + admission
    control keep the p99 of ACCEPTED requests under an explicit bound
    (``2·queue/throughput + 5·p99_sat + delay``) while the rejection
    counters absorb the excess.
(c) **batching never changes answers**: every completed request in every
    paced sweep point is compared bit-for-bit against its solo-run
    reference at the same compiled shape (``solo_references``).

Modes:
  in-process (default)  build the engine here; full report incl. (a)-(c).
  --url http://…        drive a running tools/serve.py over HTTP
                        (timing + rejection legs; exactness needs
                        engine-side references, so it is skipped).
  --smoke               ~2 s CI gate: tiny sweep, hard-asserts (b) and
                        (c) (+ prints (a)); non-zero exit on violation —
                        wired as SPARKNET_SERVESMOKE=1 in run_tier1.sh.
  --fleet N             the serving-fleet legs (WALKTHROUGH §6.14): N
                        replica subprocesses as serve-kind fleet
                        tenants behind the request router — scale-out
                        vs one replica, exactness vs local solo
                        references (replicas init identical params from
                        the shared seed), SIGKILL chaos + typed
                        failover + heal, lossless drain, and tenant
                        isolation (hot model at 2x vs a paced
                        bystander whose GET /slo must stay ok).  With
                        --smoke: the SPARKNET_FLEETSERVESMOKE gate.

Usage:
  JAX_PLATFORMS=cpu python tools/serveload.py --model lenet \
      --seconds 2 --clients 16 --out BENCH_serving_cpu.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _log(msg: str) -> None:
    print(f"[serveload] {msg}", file=sys.stderr, flush=True)


class _ReadyFuture:
    """Future shim for synchronous transports (one HTTP round trip per
    client thread — remote windows degrade to window=1 semantics)."""

    def __init__(self, value):
        self._value = value

    def done(self) -> bool:
        return True

    def result(self, timeout=None):
        return self._value


def make_remote_submit(url: str, model: str, tenant: str):
    """HTTP transport for run_closed_loop: 429s re-raise as the engine's
    typed Overloaded so rejection accounting matches in-process runs."""
    from sparknet_tpu.classify import remote_classify
    from sparknet_tpu.parallel.serving import Overloaded, ServeResult

    def submit(idx: int, x: np.ndarray) -> _ReadyFuture:
        try:
            d = remote_classify(url, model, x, tenant=tenant)
        except RuntimeError as e:
            if "HTTP 429" in str(e):
                raise Overloaded("queue_full", str(e)) from None
            raise
        return _ReadyFuture(ServeResult(
            model=d["model"], probs=np.asarray(d["probs"], np.float32),
            tenant=tenant, request_id=d["request_id"],
            queue_ms=d["queue_ms"], infer_ms=d["infer_ms"],
            total_ms=d["total_ms"], batch_n=d["batch_n"],
            padded_to=d["padded_to"]))

    return submit


def run_report(model: str = "lenet", weights: str | None = None,
               shapes: tuple[int, ...] | None = None,
               delay_ms: float | None = None, queue: int | None = None,
               dtype: str | None = None, clients: int = 8,
               window: int = 16,
               seconds: float = 2.0, inputs_n: int = 32, seed: int = 0,
               fractions: tuple[float, ...] = (0.25, 0.5, 1.0),
               overload_x: float = 2.0,
               url: str | None = None) -> dict:
    """The full load report (see module docstring).  In-process unless
    ``url`` is given."""
    from sparknet_tpu.parallel.serving import (
        InferenceEngine, ModelHouse, ServeConfig, run_closed_loop,
        solo_references,
    )

    base = ServeConfig()
    cfg = ServeConfig(
        batch_shapes=shapes or base.batch_shapes,
        max_delay_ms=base.max_delay_ms if delay_ms is None else delay_ms,
        max_queue=queue or base.max_queue,
        dtype=dtype or base.dtype, seed=seed)
    rng = np.random.default_rng(seed)

    report: dict = {
        "metric": "serving_dynamic_vs_batch1_speedup_x",
        "unit": "x",
        "model": model,
        "mode": "remote" if url else "in_process",
        "clients": clients,
        "window": window,
        "seconds_per_point": seconds,
        "batch_shapes": list(cfg.batch_shapes),
        "max_delay_ms": cfg.max_delay_ms,
        "max_queue": cfg.max_queue,
        "dtype": cfg.dtype,
    }

    if url:
        from sparknet_tpu.classify import http_json
        info = http_json(f"{url.rstrip('/')}/v1/models")["models"]
        if model not in info:
            raise SystemExit(f"server has no model {model!r} "
                             f"(loaded: {sorted(info)})")
        in_shape = tuple(info[model]["in_shape"])
        inputs = [rng.normal(size=in_shape).astype(np.float32)
                  for _ in range(inputs_n)]
        refs = None
        submit = make_remote_submit(url.rstrip("/"), model, "loadgen")
        engine = None
        batch1 = None
        lm = None
    else:
        house = ModelHouse(cfg)
        lm = house.load(model, weights=weights)
        report["model_info"] = lm.info()
        engine = InferenceEngine(house, cfg)
        inputs = [rng.normal(size=lm.in_shape).astype(np.float32)
                  for _ in range(inputs_n)]
        _log(f"building solo references over {len(cfg.batch_shapes)} "
             f"shapes × {inputs_n} inputs")
        refs = solo_references(lm, inputs)
        submit = None

        # leg (a) baseline: batch=1 serving — same kernels, harness off
        b1cfg = ServeConfig(batch_shapes=(1,), max_delay_ms=0.0,
                            max_queue=cfg.max_queue, dtype=cfg.dtype,
                            seed=seed)
        b1house = ModelHouse(b1cfg)
        b1house.load(model, weights=weights)
        with InferenceEngine(b1house, b1cfg) as b1eng:
            batch1 = run_closed_loop(b1eng, model, inputs,
                                     clients=clients, window=window,
                                     duration_s=seconds)
        _log(f"batch1 saturation: {batch1['achieved_qps']} qps "
             f"(p50 {batch1['p50_ms']} ms)")
        report["batch1"] = batch1

    # dynamic saturation (leg (a) numerator, and the yardstick for (b))
    sat = run_closed_loop(engine, model, inputs, clients=clients,
                          window=window, duration_s=seconds, refs=refs,
                          submit=submit)
    _log(f"dynamic saturation: {sat['achieved_qps']} qps "
         f"(p50 {sat['p50_ms']} ms, p99 {sat['p99_ms']} ms)")
    report["saturation"] = sat
    sat_qps = max(sat["achieved_qps"], 1.0)

    # the p99 bound: queue drain time at measured throughput (doubled
    # for slack) + deadline + 5x the saturation p99 — crossing it means
    # the queue is NOT bounding latency, i.e. admission control failed.
    # Declared as the engine's latency SLO so GET /slo and the per-leg
    # slo_* verdicts below judge against the bound this very run
    # measured.
    p99_bound_ms = (2000.0 * cfg.max_queue / sat_qps
                    + 5.0 * max(sat["p99_ms"], 1.0) + cfg.max_delay_ms)
    report["p99_bound_ms"] = round(p99_bound_ms, 1)
    if engine is not None:
        engine.slo.p99_ms = p99_bound_ms
        # fence off the saturation probe: its engine-level rejections
        # are the probe working as intended, not paced-leg budget spend
        engine.slo.reset()

    # paced sweep with the exactness audit at every point (claim (c))
    sweep = []
    for frac in fractions:
        point = run_closed_loop(engine, model, inputs, clients=clients,
                                window=window, duration_s=seconds,
                                offered_qps=max(frac * sat_qps, 1.0),
                                refs=refs, submit=submit)
        point["fraction_of_saturation"] = frac
        _log(f"sweep {frac:.2f}x ({point['offered_qps']} qps offered): "
             f"achieved {point['achieved_qps']} "
             f"p50 {point['p50_ms']} p99 {point['p99_ms']} "
             f"rejected {point['rejected']} "
             f"mismatches {point['exact_mismatches']}")
        sweep.append(point)
    report["sweep"] = sweep
    if engine is not None:
        # SLO verdict over the paced traffic (before overload): must be
        # healthy — paced legs stay inside both the rejection budget
        # and the declared p99 bound
        report["slo_paced"] = engine.slo.evaluate()
        _log(f"slo after paced sweep: {report['slo_paced']['state']} "
             f"(burn fast "
             f"{report['slo_paced']['windows']['fast']['burn']}x)")

    # overload leg (claim (b)): 2x saturation through the bounded queue.
    # Client concurrency must exceed the admission bound or the closed
    # loop can never present more work than the engine accepts — scale
    # the window so clients*window comfortably overfills the queue.
    over_window = max(window,
                      (int(1.5 * cfg.max_queue) + clients - 1) // clients)
    over = run_closed_loop(engine, model, inputs, clients=clients,
                           window=over_window, duration_s=seconds,
                           offered_qps=overload_x * sat_qps,
                           refs=refs, submit=submit)
    over["fraction_of_saturation"] = overload_x
    report["overload"] = over
    _log(f"overload {overload_x}x: achieved {over['achieved_qps']} "
         f"p99 {over['p99_ms']} (bound {p99_bound_ms:.0f}) "
         f"rejected {over['rejected']}")
    if engine is not None:
        # SLO verdict under overload: the rejection budget burns (the
        # typed rejections ARE the error budget spend), so this leg
        # must breach — with a flight-recorder dump capturing the
        # breaching windows
        report["slo_overload"] = engine.slo.evaluate()
        _log(f"slo under overload: {report['slo_overload']['state']} "
             f"(burn fast "
             f"{report['slo_overload']['windows']['fast']['burn']}x, "
             f"dumps {report['slo_overload']['flight_dumps']})")

    if not url:
        import jax
        d = jax.devices()[0]
        report["device"] = f"{d.platform}/{d.device_kind}"
    from sparknet_tpu.utils import perfledger
    report["provenance"] = perfledger.provenance(perfledger.fingerprint(
        model=model, dtype=cfg.dtype, batch=max(cfg.batch_shapes),
        world=1, device=report.get("device")))

    mismatches = sum(p["exact_mismatches"] or 0 for p in sweep)
    mismatches += sat["exact_mismatches"] or 0
    mismatches += over["exact_mismatches"] or 0
    speedup = (round(sat["achieved_qps"]
                     / max(batch1["achieved_qps"], 1e-9), 2)
               if batch1 else None)
    report["value"] = speedup
    report["verdicts"] = {
        # (a) harness win at saturation
        "batching_speedup_x": speedup,
        "batching_beats_4x": (None if speedup is None else speedup >= 4.0),
        # (b) bounded p99 + typed rejections + no throughput collapse
        "overload_rejected": over["rejected"],
        "overload_p99_bounded": over["p99_ms"] <= p99_bound_ms,
        "overload_no_collapse":
            over["achieved_qps"] >= 0.5 * sat_qps,
        # (c) bit-identical to solo runs at every swept QPS
        "exact_mismatches": None if refs is None else mismatches,
        "bit_identical": None if refs is None else mismatches == 0,
        # SLO monitor verdicts (in-process only): paced traffic healthy,
        # overload a declared breach with a flight dump
        "slo_paced_healthy": (report.get("slo_paced", {}).get("state")
                              == "ok" if engine is not None else None),
        "slo_overload_breached": (
            report.get("slo_overload", {}).get("state") == "breach"
            if engine is not None else None),
    }
    if engine is not None:
        report["engine_stats"] = engine.stats()
        engine.stop()
    return report


# ---------------------------------------------------------------------------
# Fleet leg — N replicas behind the request router, as fleet tenants
# ---------------------------------------------------------------------------

def _paced_with_midpoint(router, model, inputs, refs, *, clients, window,
                         seconds, qps, midpoint, tenant="loadgen"):
    """One paced closed loop with a ``midpoint()`` action fired halfway
    through (the kill / scale-down injection point); returns (report,
    midpoint result)."""
    import threading

    from sparknet_tpu.parallel.serving import run_closed_loop

    result = {}

    def fire():
        time.sleep(seconds / 2.0)
        try:
            result["value"] = midpoint()
        except Exception as e:   # surface, don't kill the load thread
            result["error"] = repr(e)

    t = threading.Thread(target=fire, daemon=True)
    t.start()
    rep = run_closed_loop(
        None, model, inputs, clients=clients, window=window,
        duration_s=seconds, offered_qps=qps, refs=refs,
        timeout_s=20.0, tenant=tenant,
        submit=lambda idx, x: router.submit(model, x, tenant=tenant))
    t.join(timeout=seconds + 10.0)
    return rep, result


def run_fleet_report(model: str = "lenet", replicas: int = 3,
                     devices: int | None = None,
                     shapes: tuple[int, ...] = (1, 4, 8),
                     delay_ms: float | None = None,
                     queue: int | None = None, dtype: str | None = None,
                     clients: int = 8, seconds: float = 2.0,
                     inputs_n: int = 16, seed: int = 0,
                     isolation_model: str | None = "cifar10_quick",
                     workdir: str | None = None) -> dict:
    """The fleet acceptance story, one JSON report:

    (a) **scale-out**: saturation qps through the router at N replicas
        vs one replica (same knobs).  The >= 0.8*N claim is only GATED
        when the rig has >= N cores — on fewer cores the replicas
        timeshare one CPU and the ratio measures the scheduler, not the
        architecture (the CPU-vs-TPU "refuse to gate" posture).
    (b) **exactness**: every completed request in every leg is compared
        bit-for-bit against an in-process solo reference built from the
        same config + seed (replica processes init identical params).
    (c) **failover**: one replica SIGKILLed mid-leg; typed failover
        only, zero request errors, zero hangs, and the fleet heals (the
        ResilientRunner relaunches the replica, the router re-admits
        it).
    (d) **lossless scale-down**: a replica drained + released mid-leg;
        every admitted request completes, the job ends COMPLETED.
    (e) **tenant isolation**: the main model driven at 2x saturation
        while ``isolation_model`` stays paced at 0.5x its own — the
        bystander's ``GET /slo`` must stay ok while the hot model's
        autoscaler reacts (scale-up recorded, or up_blocked + typed
        rejections absorbing the excess).
    """
    import signal as _signal
    import tempfile

    from sparknet_tpu.classify import http_json
    from sparknet_tpu.parallel.autoscale import (
        Autoscaler, AutoscaleConfig, fleet_stats_fn,
    )
    from sparknet_tpu.parallel.fleet import COMPLETED, FleetJournal
    from sparknet_tpu.parallel.router import RouterConfig, ServingFleet
    from sparknet_tpu.parallel.serving import (
        ModelHouse, ServeConfig, run_closed_loop, solo_references,
    )

    base = ServeConfig()
    cfg = ServeConfig(
        batch_shapes=shapes or base.batch_shapes,
        max_delay_ms=base.max_delay_ms if delay_ms is None else delay_ms,
        max_queue=queue or base.max_queue,
        dtype=dtype or base.dtype, seed=seed)
    rng = np.random.default_rng(seed)
    cores = os.cpu_count() or 1
    devices = devices or replicas + 1
    workdir = workdir or tempfile.mkdtemp(prefix="sparknet-fleetload-")

    serve_env = {
        "SPARKNET_SERVE_SHAPES": ",".join(str(s)
                                          for s in cfg.batch_shapes),
        "SPARKNET_SERVE_MAX_DELAY_MS": str(cfg.max_delay_ms),
        "SPARKNET_SERVE_QUEUE": str(cfg.max_queue),
        "SPARKNET_SERVE_DTYPE": cfg.dtype,
    }
    report: dict = {
        "metric": "serving_fleet_scaling_x",
        "unit": "x",
        "model": model,
        "replicas": replicas,
        "devices": devices,
        "cores": cores,
        "clients": clients,
        "seconds_per_point": seconds,
        "batch_shapes": list(cfg.batch_shapes),
        "max_delay_ms": cfg.max_delay_ms,
        "max_queue": cfg.max_queue,
        "dtype": cfg.dtype,
        "workdir": workdir,
    }

    # in-process references: same config + seed as every replica, so the
    # remote fleet must be bit-identical to this house's solo rows
    _log(f"building local reference model + solo references for "
         f"{model!r}")
    ref_house = ModelHouse(cfg)
    ref_lm = ref_house.load(model)
    inputs = [rng.normal(size=ref_lm.in_shape).astype(np.float32)
              for _ in range(inputs_n)]
    refs = solo_references(ref_lm, inputs)

    fleet = ServingFleet(
        workdir, devices, serve_env=serve_env,
        router_cfg=RouterConfig(spill_depth=max(cfg.batch_shapes)),
        replica_timeout_s=20.0, preempt_grace_s=15.0)
    autoscaler = Autoscaler(
        fleet_stats_fn(fleet), fleet.scale_up, fleet.scale_down,
        cfg=AutoscaleConfig(max_replicas=max(replicas + 1, 2),
                            up_queue=4.0, cooldown_s=2.0,
                            down_idle_s=3600.0, sample_every_s=0.25),
        state_path=os.path.join(workdir, "autoscale.json"))
    router = fleet.router
    try:
        # -- (a) solo baseline through the router, then the full fleet -
        fleet.ensure(model, 1)
        fleet.run_background()
        fleet.wait_ready(model, 1, timeout_s=240.0)
        _log("replica 1 ready — measuring single-replica saturation")
        solo = run_closed_loop(
            None, model, inputs, clients=clients, window=1,
            duration_s=seconds, refs=refs, timeout_s=20.0,
            submit=lambda idx, x: router.submit(model, x,
                                                tenant="loadgen"))
        _log(f"solo: {solo['achieved_qps']} qps "
             f"(p99 {solo['p99_ms']} ms)")
        report["solo"] = solo

        fleet.ensure(model, replicas)
        fleet.wait_ready(model, replicas, timeout_s=240.0)
        _log(f"{replicas} replicas ready — measuring fleet saturation")
        sat = run_closed_loop(
            None, model, inputs, clients=clients, window=1,
            duration_s=seconds, refs=refs, timeout_s=20.0,
            submit=lambda idx, x: router.submit(model, x,
                                                tenant="loadgen"))
        report["saturation"] = sat
        sat_qps = max(sat["achieved_qps"], 1.0)
        scaling = round(sat["achieved_qps"]
                        / max(replicas * solo["achieved_qps"], 1e-9), 3)
        report["value"] = scaling
        _log(f"fleet: {sat['achieved_qps']} qps across {replicas} "
             f"replicas = {scaling}x per-replica scaling "
             f"({cores} core(s))")
        # autoscaler joins only now: a scale-up racing the baseline
        # legs would steal cycles from the very numbers being compared
        fleet.attach_autoscaler(autoscaler)
        autoscaler.start()

        # -- paced leg: healthy traffic, exactness audited -------------
        paced, _ = _paced_with_midpoint(
            router, model, inputs, refs, clients=clients, window=1,
            seconds=seconds, qps=max(0.5 * sat_qps, 2.0),
            midpoint=lambda: None)
        report["paced"] = paced
        _log(f"paced 0.5x: errors {paced['errors']} "
             f"mismatches {paced['exact_mismatches']}")

        # -- (c) chaos: SIGKILL one replica mid-leg --------------------
        victim = router.home(model)
        victim_pid = router.stats()["replicas"][victim].get("pid")

        def kill():
            _log(f"killing replica {victim} (pid {victim_pid})")
            os.kill(int(victim_pid), _signal.SIGKILL)
            return victim

        chaos, killed = _paced_with_midpoint(
            router, model, inputs, refs, clients=clients, window=1,
            seconds=max(seconds, 1.0), qps=max(0.4 * sat_qps, 2.0),
            midpoint=kill)
        chaos["killed_replica"] = killed.get("value") or killed
        report["chaos"] = chaos
        counts = router.stats()["counts"]
        report["router_counts_after_chaos"] = dict(counts)
        _log(f"chaos: errors {chaos['errors']} "
             f"mismatches {chaos['exact_mismatches']} "
             f"failovers {counts['failovers']} deaths {counts['deaths']}")
        # the ResilientRunner must heal the fleet back to N
        recovered = True
        try:
            fleet.wait_ready(model, replicas, timeout_s=240.0)
        except TimeoutError:
            recovered = False
        report["chaos"]["recovered"] = recovered
        _log(f"fleet healed to {replicas} replicas: {recovered}")

        # -- (d) lossless scale-down mid-load --------------------------
        drain_result: dict = {}

        def scale_down():
            rid = fleet.scale_down(model)
            drain_result["rid"] = rid
            return rid

        drain, _ = _paced_with_midpoint(
            router, model, inputs, refs, clients=clients, window=1,
            seconds=max(seconds, 1.0), qps=max(0.4 * sat_qps, 2.0),
            midpoint=scale_down)
        rid = drain_result.get("rid")
        deadline = time.monotonic() + 60.0
        released = False
        while time.monotonic() < deadline and rid:
            job = fleet.sched.jobs.get(rid)
            if job is not None and job.state == COMPLETED:
                released = True
                break
            time.sleep(0.1)
        drain_events = [e for e in FleetJournal.read(
            os.path.join(workdir, "fleet_journal.jsonl"))
            if e.get("ev") == "drain_done" and e.get("job") == rid]
        drain.update(
            released_replica=rid, released_completed=released,
            drain_clean=bool(drain_events and drain_events[-1]
                             .get("ok")))
        report["drain"] = drain
        _log(f"drain: released {rid} completed={released} "
             f"clean={drain['drain_clean']} errors {drain['errors']} "
             f"mismatches {drain['exact_mismatches']}")

        # -- (e) tenant isolation under single-model overload ----------
        if isolation_model:
            iso: dict = {"model": isolation_model}
            fleet.ensure(isolation_model, 1)
            fleet.wait_ready(isolation_model, 1, timeout_s=240.0)
            iso_rng = np.random.default_rng(seed + 1)
            iso_lm = ref_house.load(isolation_model)
            iso_inputs = [iso_rng.normal(size=iso_lm.in_shape)
                          .astype(np.float32) for _ in range(inputs_n)]
            iso_refs = solo_references(iso_lm, iso_inputs)
            probe = run_closed_loop(
                None, isolation_model, iso_inputs, clients=2, window=1,
                duration_s=min(seconds, 1.0), timeout_s=20.0,
                submit=lambda idx, x: router.submit(
                    isolation_model, x, tenant="bystander"))
            iso["bystander_saturation_qps"] = probe["achieved_qps"]
            results: dict = {}

            def hot():
                results["hot"] = run_closed_loop(
                    None, model, inputs, clients=clients,
                    window=max(2, (2 * cfg.max_queue) // clients
                               // max(replicas, 1)),
                    duration_s=seconds,
                    offered_qps=2.0 * sat_qps, refs=refs,
                    timeout_s=20.0,
                    submit=lambda idx, x: router.submit(
                        model, x, tenant="hot"))

            t = __import__("threading").Thread(target=hot, daemon=True)
            t.start()
            results["bystander"] = run_closed_loop(
                None, isolation_model, iso_inputs, clients=2, window=1,
                duration_s=seconds,
                offered_qps=max(0.5 * probe["achieved_qps"], 1.0),
                refs=iso_refs, timeout_s=20.0,
                submit=lambda idx, x: router.submit(
                    isolation_model, x, tenant="bystander"))
            # the bystander's own replica must still answer "SLO ok"
            # while the hot model burns — per-model verdict, straight
            # from the replica's GET /slo
            slo_docs = {}
            for brid in router.replica_ids(model=isolation_model,
                                           live_only=True):
                url = fleet._endpoints.get(brid)
                if url:
                    try:
                        slo_docs[brid] = http_json(f"{url}/slo",
                                                   timeout=10.0)
                    except RuntimeError as e:
                        slo_docs[brid] = {"state": "breach",
                                          "error": str(e)}
            t.join(timeout=seconds + 30.0)
            iso["hot"] = results.get("hot")
            iso["bystander"] = results.get("bystander")
            iso["bystander_slo"] = slo_docs
            iso["bystander_slo_ok"] = bool(slo_docs) and all(
                d.get("state") == "ok" for d in slo_docs.values())
            iso["autoscale_reaction"] = autoscaler.last.get(model)
            hot_rep = results.get("hot") or {}
            iso["hot_absorbed_typed"] = (hot_rep.get("rejected", 0) > 0
                                         or hot_rep.get("errors", 1) == 0)
            report["isolation"] = iso
            _log(f"isolation: bystander slo_ok="
                 f"{iso['bystander_slo_ok']} errors "
                 f"{(iso['bystander'] or {}).get('errors')} "
                 f"mismatches "
                 f"{(iso['bystander'] or {}).get('exact_mismatches')} | "
                 f"hot rejected {hot_rep.get('rejected')} "
                 f"autoscale {iso['autoscale_reaction']}")

        report["router"] = router.stats()
        report["autoscale"] = {m: dict(d)
                               for m, d in autoscaler.last.items()}
    finally:
        fleet.stop()

    import jax
    d = jax.devices()[0]
    report["device"] = f"{d.platform}/{d.device_kind}"
    from sparknet_tpu.utils import perfledger
    report["provenance"] = perfledger.provenance(perfledger.fingerprint(
        model=model, dtype=cfg.dtype, batch=max(cfg.batch_shapes),
        world=1, device=report["device"], replicas=replicas))

    legs = [report.get(k) for k in ("solo", "saturation", "paced",
                                    "chaos", "drain")]
    legs += [(report.get("isolation") or {}).get("hot"),
             (report.get("isolation") or {}).get("bystander")]
    mismatches = sum((p or {}).get("exact_mismatches") or 0
                     for p in legs)
    counts = report["router"]["counts"]
    iso = report.get("isolation") or {}
    report["verdicts"] = {
        # (a) scale-out — honestly not gated below N cores
        "fleet_scaling_x": scaling,
        "scaling_gated": cores >= replicas,
        "fleet_scales_0p8N": (scaling >= 0.8 if cores >= replicas
                              else None),
        # (b) exactness across every leg, remote replicas vs local solo
        "exact_mismatches": mismatches,
        "bit_identical": mismatches == 0,
        # (c) failover: typed-only, zero errors, healed
        "chaos_errors": report["chaos"]["errors"],
        "chaos_failover_engaged": counts["failovers"] > 0,
        "chaos_recovered": report["chaos"]["recovered"],
        # (d) lossless scale-down
        "drain_errors": report["drain"]["errors"],
        "drain_clean": report["drain"]["drain_clean"],
        "drain_released_completed": report["drain"]
        ["released_completed"],
        # (e) isolation (None when the leg was skipped)
        "bystander_slo_ok": iso.get("bystander_slo_ok"),
        "bystander_errors": (iso.get("bystander") or {}).get("errors"),
        "hot_model_reacted": (
            None if not iso else bool(iso.get("autoscale_reaction"))
            or iso.get("hot_absorbed_typed")),
    }
    return report


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description="closed-loop serving load "
                                             "generator")
    ap.add_argument("--model", default="lenet")
    ap.add_argument("--weights", default=None)
    ap.add_argument("--shapes", default=None,
                    help="compiled batch shapes, e.g. 1,4,16,64")
    ap.add_argument("--delay-ms", type=float, default=None)
    ap.add_argument("--queue", type=int, default=None)
    ap.add_argument("--dtype", choices=("bf16", "f32"), default=None)
    ap.add_argument("--clients", type=int, default=8)
    ap.add_argument("--window", type=int, default=16,
                    help="outstanding requests per client (pipelined "
                         "frontend; total concurrency = clients*window)")
    ap.add_argument("--seconds", type=float, default=2.0,
                    help="duration per sweep point")
    ap.add_argument("--inputs", type=int, default=32,
                    help="distinct-input pool size")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--overload-x", type=float, default=2.0)
    ap.add_argument("--url", default=None,
                    help="drive a running tools/serve.py instead of an "
                         "in-process engine")
    ap.add_argument("--fleet", type=int, default=0, metavar="N",
                    help="fleet leg: N replica subprocesses behind the "
                         "request router (fleet tenants), exactness vs "
                         "local solo references, chaos kill + failover, "
                         "lossless drain, tenant isolation")
    ap.add_argument("--fleet-devices", type=int, default=None,
                    help="device budget for the replica fleet "
                         "(default N+1, so the autoscaler can react)")
    ap.add_argument("--isolation-model", default="cifar10_quick",
                    help="bystander model for the isolation leg "
                         "('' skips it)")
    ap.add_argument("--workdir", default=None,
                    help="fleet state dir for --fleet (default: temp)")
    ap.add_argument("--out", default=None, help="write the JSON report "
                                                "here (stdout always)")
    ap.add_argument("--smoke", action="store_true",
                    help="CI gate: assert bounded p99 under overload + "
                         "bit-identical results (with --fleet: failover "
                         "+ lossless drain too); rc!=0 on violation")
    args = ap.parse_args(argv)

    if args.fleet:
        return fleet_cli(args)

    if args.smoke:
        args.seconds = min(args.seconds, 0.4)
        args.clients = min(args.clients, 4)
        args.window = min(args.window, 16)
        args.queue = args.queue or 32   # overload must trip the bound
        shapes = (1, 4, 8)
        # paced below saturation: pacing AT capacity on the smoke's
        # tiny queue rejects legitimately, which would make the
        # "paced traffic holds its SLO" assert vacuous
        fractions = (0.5,)
    else:
        shapes = (tuple(int(s) for s in args.shapes.split(","))
                  if args.shapes else None)
        fractions = (0.25, 0.5, 1.0)

    report = run_report(
        model=args.model, weights=args.weights, shapes=shapes,
        delay_ms=args.delay_ms, queue=args.queue, dtype=args.dtype,
        clients=args.clients, window=args.window, seconds=args.seconds,
        inputs_n=args.inputs, seed=args.seed, fractions=fractions,
        overload_x=args.overload_x, url=args.url)
    report["captured_at"] = time.strftime("%Y-%m-%dT%H:%M:%S%z")
    line = json.dumps(report)
    print(line, flush=True)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(report, f, indent=1)

    if args.smoke:
        v = report["verdicts"]
        bad = []
        if v["bit_identical"] is False:
            bad.append(f"{v['exact_mismatches']} result mismatches vs "
                       f"solo references")
        if not v["overload_p99_bounded"]:
            bad.append(f"overload p99 {report['overload']['p99_ms']} ms "
                       f"over bound {report['p99_bound_ms']} ms")
        if not v["overload_rejected"]:
            bad.append("overload produced zero rejections (admission "
                       "control never engaged)")
        if v["slo_paced_healthy"] is False:
            bad.append("SLO monitor reported a breach under paced "
                       "traffic")
        if v["slo_overload_breached"] is False:
            bad.append("SLO monitor failed to declare a breach under "
                       "2x overload")
        if bad:
            _log("SMOKE FAIL: " + "; ".join(bad))
            return 1
        _log(f"smoke ok: speedup {v['batching_speedup_x']}x, overload "
             f"p99 {report['overload']['p99_ms']} ms "
             f"<= {report['p99_bound_ms']} ms with "
             f"{v['overload_rejected']} rejections, bit-identical")
    return 0


def fleet_cli(args) -> int:
    """The ``--fleet N`` entry: run the fleet report, smoke-assert the
    lossless/typed/exact contracts when ``--smoke``."""
    if args.smoke:
        args.seconds = min(args.seconds, 0.8)
        args.clients = min(args.clients, 4)
        args.isolation_model = ""      # the ~10s budget skips it
        devices = args.fleet_devices or args.fleet
    else:
        devices = args.fleet_devices or args.fleet + 1
    report = run_fleet_report(
        model=args.model, replicas=args.fleet, devices=devices,
        shapes=(tuple(int(s) for s in args.shapes.split(","))
                if args.shapes else (1, 4, 8)),
        delay_ms=args.delay_ms, queue=args.queue or 64,
        dtype=args.dtype, clients=args.clients, seconds=args.seconds,
        inputs_n=min(args.inputs, 16), seed=args.seed,
        isolation_model=args.isolation_model or None,
        workdir=args.workdir)
    report["captured_at"] = time.strftime("%Y-%m-%dT%H:%M:%S%z")
    print(json.dumps(report), flush=True)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(report, f, indent=1)

    if args.smoke:
        v = report["verdicts"]
        bad = []
        if not v["bit_identical"]:
            bad.append(f"{v['exact_mismatches']} mismatches vs solo "
                       f"references")
        if report["paced"]["errors"]:
            bad.append(f"paced leg saw {report['paced']['errors']} "
                       f"request errors")
        if v["chaos_errors"]:
            bad.append(f"replica kill leaked {v['chaos_errors']} "
                       f"request errors past failover")
        if not v["chaos_failover_engaged"]:
            bad.append("replica kill produced zero failovers (the "
                       "router never noticed)")
        if not v["chaos_recovered"]:
            bad.append("fleet never healed back to N replicas")
        if v["drain_errors"]:
            bad.append(f"scale-down dropped {v['drain_errors']} "
                       f"admitted requests")
        if not v["drain_clean"] or not v["drain_released_completed"]:
            bad.append("scale-down did not drain cleanly to COMPLETED")
        if v["fleet_scales_0p8N"] is False:
            bad.append(f"fleet scaling {v['fleet_scaling_x']}x < 0.8 "
                       f"on a {report['cores']}-core rig")
        if bad:
            _log("FLEET SMOKE FAIL: " + "; ".join(bad))
            return 1
        scaling_note = (f"{v['fleet_scaling_x']}x/replica"
                        if v["scaling_gated"] else
                        f"{v['fleet_scaling_x']}x/replica (not gated: "
                        f"{report['cores']} core(s) < "
                        f"{report['replicas']} replicas)")
        _log(f"fleet smoke ok: {scaling_note}, failovers "
             f"{report['router']['counts']['failovers']}, drain clean, "
             f"bit-identical")
    return 0


if __name__ == "__main__":
    sys.exit(main())
