from .net import Net, NetOutputs, WeightCollection
