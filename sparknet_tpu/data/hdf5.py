"""HDF5 blob IO — the util/hdf5 + HDF5Data/HDF5Output analog.

The reference reads training data from HDF5 (reference:
caffe/src/caffe/layers/hdf5_data_layer.cpp — `source` is a text file
listing .h5 files, each holding one dataset per top blob) and writes blobs
back out (hdf5_output_layer.cpp); blob<->HDF5 conversion in
caffe/src/caffe/util/hdf5.cpp.  Here the same file conventions are read
host-side and fed to the graph as ordinary inputs.
"""

from __future__ import annotations

import os
from typing import Iterator

import numpy as np

from ..utils.retry import io_retry

try:
    import h5py
except ImportError:  # pragma: no cover
    h5py = None


def _open_h5(path: str, mode: str = "r"):
    """h5py.File with bounded retry — DB/file opens are one-shot
    control-plane edges; a transient shared-fs error must not kill a
    multi-hour run (SPARKNET_IO_* knobs)."""
    return io_retry(h5py.File, path, mode, describe=f"h5py.File {path}")


def _require_h5py():
    if h5py is None:
        raise ImportError("h5py is required for HDF5 data support")


def read_source_list(source: str) -> list[str]:
    """The HDF5Data `source` convention: a text file of .h5 paths."""
    base = os.path.dirname(source)
    out = []
    with io_retry(open, source, describe=f"open {source}") as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(line if os.path.isabs(line)
                           else os.path.join(base, line))
    return out


def load_hdf5_blobs(path: str, keys: list[str] | None = None
                    ) -> dict[str, np.ndarray]:
    """All (or the named) datasets of one .h5 file as float32 arrays."""
    _require_h5py()
    with _open_h5(path) as f:
        names = keys if keys is not None else sorted(f.keys())
        return {k: np.asarray(f[k], np.float32) for k in names}


def save_hdf5_blobs(path: str, blobs: dict[str, np.ndarray]) -> None:
    """HDF5Output analog: write named blobs to one .h5 file."""
    _require_h5py()
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with h5py.File(path, "w") as f:
        for k, v in blobs.items():
            f.create_dataset(k, data=np.asarray(v))


def hdf5_feed(source: str, tops: list[str], batch_size: int,
              shuffle: bool = False, seed: int = 0,
              ) -> Iterator[dict[str, np.ndarray]]:
    """Endless minibatch stream over the concatenated listed files — the
    HDF5DataLayer feed (file order preserved; rows optionally shuffled per
    epoch like `hdf5_data_param.shuffle`)."""
    _require_h5py()
    files = read_source_list(source)
    data = {t: [] for t in tops}
    for path in files:
        blobs = load_hdf5_blobs(path, tops)
        for t in tops:
            data[t].append(blobs[t])
    cat = {t: np.concatenate(data[t]) for t in tops}
    n = len(next(iter(cat.values())))
    rng = np.random.default_rng(seed)
    while True:
        order = rng.permutation(n) if shuffle else np.arange(n)
        for i in range(0, n - batch_size + 1, batch_size):
            idx = order[i:i + batch_size]
            yield {t: cat[t][idx] for t in tops}


# ---------------------------------------------------------------------------
# HDF5 snapshot format (SolverParameter.snapshot_format: HDF5)
# ---------------------------------------------------------------------------

def save_model_hdf5(path: str, layer_blobs: "dict[str, list]") -> None:
    """Net::ToHDF5 layout (reference: net.cpp:926-975): group ``data``
    holding one sub-group per layer, datasets ``"0"``, ``"1"``, ... per
    param blob."""
    _require_h5py()
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with h5py.File(path, "w") as f:
        data = f.create_group("data")
        for layer_name, blobs in layer_blobs.items():
            g = data.create_group(layer_name)
            for i, b in enumerate(blobs):
                g.create_dataset(str(i), data=np.asarray(b, np.float32))


def load_model_hdf5(path: str) -> "dict[str, list]":
    """Net::CopyTrainedLayersFromHDF5 reader (reference: net.cpp:889-924):
    {layer_name: [blob0, blob1, ...]}."""
    _require_h5py()
    out: dict[str, list] = {}
    with _open_h5(path) as f:
        data = f["data"]
        for layer_name in data:
            g = data[layer_name]
            out[layer_name] = [np.asarray(g[str(i)], np.float32)
                               for i in range(len(g))]
    return out


def save_state_hdf5(path: str, iteration: int, history: "list",
                    learned_net: str = "", current_step: int = 0) -> None:
    """SGDSolver::SnapshotSolverStateToHDF5 layout (reference:
    sgd_solver.cpp:275-298): scalar ``iter``/``current_step`` ints, a
    ``learned_net`` string, and group ``history`` with datasets
    ``"0"``...``"n-1"`` in learnable-param order."""
    _require_h5py()
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with h5py.File(path, "w") as f:
        f.create_dataset("iter", data=np.int64(iteration))
        f.create_dataset("learned_net", data=learned_net)
        f.create_dataset("current_step", data=np.int64(current_step))
        g = f.create_group("history")
        for i, b in enumerate(history):
            g.create_dataset(str(i), data=np.asarray(b, np.float32))


def load_state_hdf5(path: str) -> dict:
    """RestoreSolverStateFromHDF5 reader (sgd_solver.cpp:321-338):
    {iter, current_step, learned_net, history}."""
    _require_h5py()
    with _open_h5(path) as f:
        learned = ""
        if "learned_net" in f:
            raw = f["learned_net"][()]
            learned = raw.decode() if isinstance(raw, bytes) else str(raw)
        g = f["history"]
        history = [np.asarray(g[str(i)], np.float32) for i in range(len(g))]
        return {
            "iter": int(np.asarray(f["iter"])),
            "current_step": (int(np.asarray(f["current_step"]))
                             if "current_step" in f else 0),
            "learned_net": learned,
            "history": history,
        }


def is_hdf5_file(path: str) -> bool:
    """Sniff the 8-byte HDF5 signature (what caffe keys restore dispatch
    on via the .h5 suffix; magic is sturdier)."""
    try:
        with open(path, "rb") as f:
            return f.read(8) == b"\x89HDF\r\n\x1a\n"
    except OSError:
        return False
