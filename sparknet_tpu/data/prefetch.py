"""Background prefetch + async device transfer.

The reference's JavaData feed path is fully synchronous — every minibatch
blocks the solver on a C→JVM callback, a CPU float copy, and a lazy CPU→GPU
transfer (reference: caffe/src/caffe/layers/java_data_layer.cpp:36-44; hot
spot measured in src/test/scala/apps/CallbackBenchmarkSpec.scala:1-17).
Caffe's own prefetching pipeline (double-buffered background thread,
reference: caffe/include/caffe/data_layers.hpp:63-117 +
util/blocking_queue.cpp) is bypassed by that path.

Here we implement the double-buffering the reference lost: a daemon thread
runs the host preprocessing and starts the host→HBM ``device_put`` ahead of
time, so the TPU step overlaps with the feed — `device_feed` is the
JavaDataLayer replacement."""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Callable, Iterator, Mapping

import jax

from ..utils import faults


class PrefetchIterator:
    """Wrap an iterator; a background thread keeps `depth` items ready.

    ``close()`` stops the producer thread and drops staged items — required
    for endless sources (``RoundFeed.rounds()``), where the producer would
    otherwise stay blocked on the full queue holding device memory for the
    rest of the process (the explicit lifecycle Caffe's InternalThread
    gives its prefetch thread; reference: internal_thread.hpp:29-42).
    Usable as a context manager."""

    _SENTINEL = object()

    def __init__(self, it: Iterator[Any], depth: int = 2,
                 transform: Callable[[Any], Any] | None = None):
        self._q: queue.Queue[Any] = queue.Queue(maxsize=depth)
        self._err: BaseException | None = None
        self._stop = threading.Event()
        self._done = False
        # chaos hook: SPARKNET_FAULT=slow_feed:<dur> models a degraded
        # input pipeline by delaying every produced batch (utils.faults)
        feed_delay = faults.get_injector().feed_delay()

        def put(item: Any) -> bool:
            while not self._stop.is_set():
                try:
                    self._q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def run() -> None:
            try:
                for item in it:
                    if self._stop.is_set():
                        return
                    if feed_delay:
                        time.sleep(feed_delay)
                    if not put(transform(item) if transform else item):
                        return
            except BaseException as e:  # surfaced on next()
                self._err = e
            finally:
                put(self._SENTINEL)

        self._thread = threading.Thread(target=run, daemon=True)
        self._thread.start()

    def __iter__(self) -> "PrefetchIterator":
        return self

    def __next__(self) -> Any:
        if self._done:
            if self._err is not None:
                raise self._err
            raise StopIteration
        item = self._q.get()
        if item is self._SENTINEL:
            self._done = True
            if self._err is not None:
                raise self._err
            raise StopIteration
        return item

    def close(self) -> None:
        """Stop the producer and release staged items."""
        self._stop.set()
        self._done = True
        while True:
            try:
                self._q.get_nowait()
            except queue.Empty:
                break
        self._thread.join(timeout=5.0)

    def __enter__(self) -> "PrefetchIterator":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def device_feed(batches: Iterator[Mapping[str, Any]], depth: int = 2,
                sharding: Any | None = None) -> Iterator[dict[str, jax.Array]]:
    """Prefetch host batches and issue async ``device_put`` ahead of
    consumption — data is in HBM (with the requested sharding) by the time
    the train step asks for it."""

    def put(batch: Mapping[str, Any]) -> dict[str, jax.Array]:
        if sharding is None:
            return {k: jax.device_put(v) for k, v in batch.items()}
        from ..parallel.mesh import stage_local
        return {k: stage_local(v, sharding) for k, v in batch.items()}

    return PrefetchIterator(batches, depth=depth, transform=put)
