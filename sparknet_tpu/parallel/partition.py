"""Regex-driven partition rule tables: hybrid model+data sharding.

SparkNet's rounds replicate the full weight vector on every worker, so
both the τ-boundary broadcast and the resident HBM footprint scale with
total parameter bytes — and the FC layers that dominate CaffeNet/VGG
parameter counts are exactly the ones that shard cleanly along their
``num_output`` dimension.  This module is the policy half of the hybrid
scheme: an ordered rule table of ``(regex, dim)`` pairs is matched
against every parameter leaf (named ``"<layer>/<blob_idx>"``, e.g.
``"fc6/0"`` for the fc6 weight, ``"fc6/1"`` for its bias) and resolved
into a :class:`ShardPlan` — a frozen per-leaf map of which dimension
lives on the mesh's shard axis.  The trainer turns the plan into a
params-pytree of ``NamedSharding``s at init (the mechanism half lives in
``parallel/trainer.py``).

Rule semantics (first-match-wins, Caffe-style per-layer-class policy):

* rules are tried in order; the first regex that ``re.search``-matches a
  leaf name decides that leaf,
* ``dim = None`` means replicate; ``dim = k`` means shard dimension *k*
  across the plan's mesh axis,
* scalar (0-d) leaves are never partitioned, whatever the rule says,
* a matched dim that does not exist or does not divide by the shard
  count falls back to replicated — recorded in ``plan.fallbacks`` so
  the decision is auditable, never silent,
* leaves no rule matches are collected and raised loudly, all at once
  (a rule table that forgets a layer class is a bug, not a default) —
  zoo tables therefore end with an explicit catch-all.

``DEFAULT_RULES`` encodes the zoo default: FC / inner-product weight
blobs shard across chips (their ``num_output`` rows), convolutions and
all biases stay replicated + batch-sharded.  Custom tables load from a
versioned JSON file (``SPARKNET_SHARD=<path>``); an unknown version is
refused, same discipline as the checkpoint/manifest planes.

``shard_plan_id()`` is a content hash over everything that changes the
placement (axis, shard count, per-leaf dims), the same discipline as
``fuse_plan_id``/``tune_plan_id`` — it is stamped into perf-ledger
fingerprints and checkpoint manifests so captures from different
shardings never pool.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import re

import numpy as np

from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

RULE_TABLE_VERSION = 1

# (regex, dim) — first match wins.  FC / inner-product weight blobs
# (blob 0 of ip*/fc*/``*classifier`` layers; shape (num_output, dim_in))
# shard their output rows; everything else — convs, biases, BN state —
# replicates.  The catch-all is explicit: a table with holes raises.
DEFAULT_RULES: tuple[tuple[str, int | None], ...] = (
    (r"(^|/)(fc|ip|classifier)[^/]*/0$", 0),
    (r".*", None),
)


@dataclasses.dataclass(frozen=True)
class ShardPlan:
    """Resolved placement: which dim of which leaf lives on ``axis``.

    ``dims`` maps leaf name -> sharded dimension for the sharded leaves
    only; every other leaf is replicated over ``axis``.  ``fallbacks``
    lists leaves a rule *wanted* sharded but that had to replicate
    (scalar, missing dim, or not divisible by ``n_shards``)."""

    axis: str
    n_shards: int
    table_id: str
    dims: tuple[tuple[str, int], ...]
    fallbacks: tuple[str, ...] = ()

    def __post_init__(self):
        object.__setattr__(self, "_dim_map", dict(self.dims))

    @property
    def sharded(self) -> bool:
        return bool(self.dims)

    def dim_of(self, key: str) -> int | None:
        return self._dim_map.get(key)

    def plan_id(self) -> str:
        """Content hash of the placement (``fuse_plan_id`` discipline)."""
        doc = {"axis": self.axis, "n_shards": self.n_shards,
               "dims": sorted(self.dims)}
        digest = hashlib.sha256(
            json.dumps(doc, sort_keys=True).encode()).hexdigest()[:12]
        return f"shard:{digest}"

    def dims_dict(self) -> dict[str, int]:
        return dict(self.dims)

    # -- pytree derivations ------------------------------------------------

    def _leaf_spec(self, key: str, leaf) -> P:
        dim = self.dim_of(key)
        if dim is None:
            return P()
        return P(*([None] * dim), self.axis)

    def spec_tree(self, params):
        """params-shaped pytree of PartitionSpecs (shard_map in/out specs)."""
        return {name: [self._leaf_spec(f"{name}/{i}", b)
                       for i, b in enumerate(blobs)]
                for name, blobs in params.items()}

    def sharding_tree(self, mesh: Mesh, params):
        """params-shaped pytree of NamedShardings (resolved at trainer
        init — the placement the parameters live in between rounds)."""
        return {name: [NamedSharding(mesh, self._leaf_spec(f"{name}/{i}", b))
                       for i, b in enumerate(blobs)]
                for name, blobs in params.items()}

    # -- in-shard_map helpers (exact: pure data movement) ------------------

    def gather(self, params, axis_name: str | None = None):
        """Inside a shard_map body: widen resident shards to full leaves
        via tiled all_gather (bit-exact — no arithmetic)."""
        ax = axis_name or self.axis
        out = {}
        for name, blobs in params.items():
            row = []
            for i, b in enumerate(blobs):
                dim = self.dim_of(f"{name}/{i}")
                if dim is None:
                    row.append(b)
                else:
                    row.append(lax.all_gather(b, ax, axis=dim, tiled=True))
            out[name] = row
        return out

    def take_shard(self, params, axis_name: str | None = None):
        """Inside a shard_map body: slice this position's own shard out
        of full leaves (bit-exact — no arithmetic)."""
        ax = axis_name or self.axis
        idx = lax.axis_index(ax)
        out = {}
        for name, blobs in params.items():
            row = []
            for i, b in enumerate(blobs):
                dim = self.dim_of(f"{name}/{i}")
                if dim is None:
                    row.append(b)
                else:
                    size = b.shape[dim] // self.n_shards
                    row.append(lax.dynamic_slice_in_dim(
                        b, idx * size, size, axis=dim))
            out[name] = row
        return out


def shard_plan_id(plan: ShardPlan | None) -> str:
    """Ledger/manifest stamp; ``"dp"`` is pure data parallelism (the
    historical default every committed capture predating plans carries)."""
    return plan.plan_id() if plan is not None else "dp"


def load_rule_table(path: str) -> tuple[tuple[tuple[str, int | None], ...], str]:
    """Load a versioned JSON rule table; returns (rules, table_id).

    Format::

        {"version": 1,
         "rules": [{"pattern": "(^|/)fc[^/]*/0$", "dim": 0},
                   {"pattern": ".*", "dim": null}]}

    Unknown versions are refused loudly (forward-compat discipline:
    better to stop than to silently mis-place a model)."""
    with open(path) as f:
        doc = json.load(f)
    version = doc.get("version")
    if version != RULE_TABLE_VERSION:
        raise ValueError(
            f"rule table {path}: version {version!r} != supported "
            f"{RULE_TABLE_VERSION} — refusing to guess its semantics")
    rules = []
    for i, r in enumerate(doc.get("rules", [])):
        pat, dim = r.get("pattern"), r.get("dim")
        if not isinstance(pat, str) or not (dim is None or isinstance(dim, int)):
            raise ValueError(f"rule table {path}: rule #{i} malformed: {r!r}")
        re.compile(pat)   # surface bad regexes at load, not first match
        rules.append((pat, dim))
    if not rules:
        raise ValueError(f"rule table {path}: no rules")
    digest = hashlib.sha256(
        json.dumps(rules, sort_keys=True).encode()).hexdigest()[:12]
    return tuple(rules), f"table:{digest}"


def match_partition_rules(rules, params, n_shards: int):
    """Apply an ordered rule table to a WeightCollection.

    Returns ``(dims, fallbacks, unmatched)`` over leaf names:
    ``dims[name] = k`` for sharded leaves, ``fallbacks`` for leaves a
    rule matched with a dim that could not be honored, ``unmatched`` for
    leaves no rule decided."""
    compiled = [(re.compile(pat), dim) for pat, dim in rules]
    dims: dict[str, int] = {}
    fallbacks: list[str] = []
    unmatched: list[str] = []
    for name in sorted(params):
        for i, leaf in enumerate(params[name]):
            key = f"{name}/{i}"
            for rx, dim in compiled:
                if rx.search(key) is None:
                    continue
                if dim is not None:
                    shape = tuple(leaf.shape)
                    if (len(shape) == 0 or dim >= len(shape)
                            or shape[dim] % n_shards):
                        fallbacks.append(key)
                    else:
                        dims[key] = dim
                break
            else:
                unmatched.append(key)
    return dims, fallbacks, unmatched


def resolve_plan(mode: str, params, *, axis: str, n_shards: int,
                 ) -> ShardPlan | None:
    """Resolve the ``SPARKNET_SHARD`` / ``TrainerConfig.shard`` knob into
    a plan against concrete parameter shapes (``jax.eval_shape`` structs
    work too — only ``.shape`` is consulted).

    ``""``/``"off"`` or a single-shard axis -> ``None`` (pure DP, the
    pre-plan code path byte for byte).  ``"auto"`` -> :data:`DEFAULT_RULES`;
    anything else is a JSON rule-table path.  A table that leaves leaves
    undecided raises, listing every hole."""
    mode = (mode or "off").strip()
    if mode.lower() in ("", "off", "0", "dp"):
        return None
    if n_shards <= 1:
        return None
    if mode.lower() == "auto":
        rules, table_id = DEFAULT_RULES, f"auto-v{RULE_TABLE_VERSION}"
    else:
        rules, table_id = load_rule_table(mode)
    dims, fallbacks, unmatched = match_partition_rules(rules, params, n_shards)
    if unmatched:
        raise ValueError(
            f"partition rule table {table_id} leaves {len(unmatched)} "
            f"leaves undecided: {unmatched} — add rules (or a catch-all "
            f"'.*' -> replicate) so every placement is deliberate")
    if not dims:
        return None
    return ShardPlan(axis=axis, n_shards=n_shards, table_id=table_id,
                     dims=tuple(sorted(dims.items())),
                     fallbacks=tuple(fallbacks))


def boundary_bytes_per_chip(params, plan: ShardPlan | None,
                            n_shards: int | None = None) -> int:
    """Analytic bytes ONE chip receives at the τ-boundary to end the
    round in its resident layout (codec ``none``).

    Pure DP all-reduce leaves every chip holding the full averaged
    vector, so the per-chip landing cost is total parameter bytes; under
    a plan, sharded leaves land as 1/n tiles and only replicated leaves
    arrive in full — the broadcast shrinks by the FC shard factor."""
    n = n_shards if n_shards is not None else (plan.n_shards if plan else 1)
    total = 0
    for name, blobs in params.items():
        for i, leaf in enumerate(blobs):
            nbytes = 1
            for d in leaf.shape:
                nbytes *= int(d)
            nbytes *= np.dtype(leaf.dtype).itemsize
            if plan is not None and plan.dim_of(f"{name}/{i}") is not None:
                nbytes //= n
            total += nbytes
    return total
