"""Checkpoint IO.

The reference snapshots model + solver state (momentum history, iter) as
binaryproto or HDF5 (reference: caffe/src/caffe/solver.cpp:447-459,
solvers/sgd_solver.cpp:242-296) and restores via ``Solver::Restore``
(solver.cpp:510).  Here a checkpoint is any pytree, written as an ``.npz``
of flattened leaves plus a pickled treedef-free key list — no pickle of
arbitrary objects, so checkpoints are portable and safe to load.
"""

from __future__ import annotations

import json
import os
from typing import Any

import jax
import numpy as np


def _flatten(tree: Any, prefix: str, out: dict[str, np.ndarray],
             meta: dict[str, Any]) -> None:
    if isinstance(tree, dict):
        meta[prefix] = {"kind": "dict", "keys": sorted(tree.keys())}
        for k in sorted(tree.keys()):
            _flatten(tree[k], f"{prefix}/{k}", out, meta)
    elif isinstance(tree, (list, tuple)):
        meta[prefix] = {"kind": "list", "len": len(tree)}
        for i, v in enumerate(tree):
            _flatten(v, f"{prefix}/{i}", out, meta)
    else:
        meta[prefix] = {"kind": "leaf"}
        out[prefix] = np.asarray(tree)


def _unflatten(prefix: str, data: dict[str, np.ndarray],
               meta: dict[str, Any]) -> Any:
    info = meta[prefix]
    if info["kind"] == "dict":
        return {k: _unflatten(f"{prefix}/{k}", data, meta) for k in info["keys"]}
    if info["kind"] == "list":
        return [_unflatten(f"{prefix}/{i}", data, meta) for i in range(info["len"])]
    return data[prefix]


def save_checkpoint(path: str, tree: Any) -> None:
    arrays: dict[str, np.ndarray] = {}
    meta: dict[str, Any] = {}
    host_tree = jax.tree_util.tree_map(np.asarray, tree)
    _flatten(host_tree, "root", arrays, meta)
    tmp = path + ".tmp"
    np.savez(tmp, __meta__=np.frombuffer(
        json.dumps(meta).encode(), dtype=np.uint8), **arrays)
    # np.savez appends .npz to the temp name
    os.replace(tmp + ".npz" if not tmp.endswith(".npz") else tmp, path)


def load_checkpoint(path: str) -> Any:
    with np.load(path) as z:
        meta = json.loads(bytes(z["__meta__"]).decode())
        data = {k: z[k] for k in z.files if k != "__meta__"}
    return _unflatten("root", data, meta)
