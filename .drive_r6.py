"""Drive the resilient-runtime PR end-to-end through the public surface.

Run from repo root: python .drive_r6.py
"""
import os
import shutil
import subprocess
import sys
import tempfile

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
os.environ["JAX_PLATFORM_NAME"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")

import numpy as np

print("== 1. base training still works (happy path) ==")
import itertools
from sparknet_tpu.models import lenet
from sparknet_tpu.proto import load_solver_prototxt_with_net
from sparknet_tpu.solvers import Solver
from sparknet_tpu.data.minibatch import batch_feed

rng = np.random.default_rng(0)
xs = rng.normal(scale=0.3, size=(128, 1, 28, 28)).astype(np.float32)
ys = rng.integers(0, 10, size=128)
for i, k in enumerate(ys):
    xs[i, :, int(k) % 28, :] += 2.0
batches = [(xs[i:i + 32], ys[i:i + 32].astype(np.float32))
           for i in range(0, 128, 32)]
sp = load_solver_prototxt_with_net(
    'base_lr: 0.05\nmomentum: 0.9\nlr_policy: "fixed"\n', lenet(32, 32))
solver = Solver(sp, seed=0)
solver.set_train_data(batch_feed(itertools.cycle(batches), None))
l0 = solver.step(5)
l1 = solver.step(35)
print(f"loss {l0:.3f} -> {l1:.3f}")
assert l1 < l0, "loss did not drop"

print("== 2. round-granular checkpoint/resume via DistributedTrainer ==")
from sparknet_tpu.parallel import DistributedTrainer, TrainerConfig, make_mesh

ckdir = tempfile.mkdtemp()


def round_batch(r):
    g = np.random.default_rng(500 + r)
    return {"data": g.normal(size=(2, 16, 1, 28, 28)).astype(np.float32),
            "label": g.integers(0, 10, size=(2, 16)).astype(np.float32)}


cfg = TrainerConfig(strategy="local_sgd", tau=2, checkpoint_dir=ckdir,
                    checkpoint_every=1, checkpoint_keep=3)
sp2 = load_solver_prototxt_with_net(
    'base_lr: 0.05\nmomentum: 0.9\nlr_policy: "fixed"\n', lenet(16, 16))
tr = DistributedTrainer(sp2, make_mesh(4), cfg, seed=0)
for r in range(3):
    tr.train_round(round_batch(r))
tr2 = DistributedTrainer(sp2, make_mesh(4), cfg, seed=123)
assert tr2.resumed and tr2.round == 3 and tr2.iter == 6, tr2.resumed
tr.train_round(round_batch(3))
tr2.train_round(round_batch(3))
np.testing.assert_allclose(np.asarray(tr2.params["conv1"][0]),
                           np.asarray(tr.params["conv1"][0]))
print(f"resumed at round 3, continuation exact; files: "
      f"{sorted(os.listdir(ckdir))}")

print("== 3. corrupt newest snapshot -> fallback to previous manifest ==")
from sparknet_tpu.utils import faults
faults.scribble(os.path.join(ckdir, "ckpt_round_00000004.npz"))
tr3 = DistributedTrainer(sp2, make_mesh(4), cfg, seed=5)
assert tr3.resumed and tr3.round == 3, (tr3.resumed, tr3.round)
print(f"fell back to {tr3.resumed['file']}")
shutil.rmtree(ckdir)

print("== 4. ResilientRunner: real crash -> restart -> exact recovery ==")
from sparknet_tpu.parallel import ResilientRunner, RestartPolicy
from sparknet_tpu.tools.launch import launch_local

DRIVER = os.path.join("tests", "multihost_driver.py")
td = tempfile.mkdtemp()
base, out, ck = (os.path.join(td, n) for n in ("base.npz", "out.npz", "ck"))
env_backup = dict(os.environ)
os.environ.pop("XLA_FLAGS", None)
try:
    rc = launch_local([sys.executable, DRIVER, "--strategy", "sync",
                       "--out", base, "--rounds", "4",
                       "--local-devices", "4"], nprocs=1, platform="cpu",
                      timeout=240)
    assert rc == 0, rc
    runner = ResilientRunner(
        [sys.executable, DRIVER, "--strategy", "sync", "--out", out,
         "--rounds", "4", "--local-devices", "4", "--ckpt-dir", ck],
        nprocs=1, platform="cpu", timeout=240,
        policy=RestartPolicy(max_restarts=2, backoff_base=0.2),
        extra_env={"SPARKNET_FAULT": "crash@round:3"})
    rc = runner.run()
finally:
    os.environ.clear()
    os.environ.update(env_backup)
assert rc == 0, f"no recovery, rc={rc}"
assert [a.returncode for a in runner.attempts] == [43, 0]
a, b = np.load(base), np.load(out)
for k in a.files:
    if not k.startswith("__"):
        np.testing.assert_allclose(a[k], b[k], rtol=1e-5, atol=1e-6)
print(f"recovered in {len(runner.attempts)} attempts; params identical "
      f"to fault-free run")
shutil.rmtree(td)

print("== 5. error paths ==")
from sparknet_tpu.utils.checkpoint import CheckpointError, load_checkpoint
try:
    load_checkpoint("/tmp/definitely_absent_ckpt.npz")
    raise AssertionError("expected CheckpointError")
except CheckpointError as e:
    print(f"missing ckpt -> CheckpointError: {e}")
try:
    faults.parse_faults("explode@round:1")
    raise AssertionError("expected ValueError")
except ValueError as e:
    print(f"bad fault spec -> ValueError: {e}")
from sparknet_tpu.parallel import cluster
os.environ["SPARKNET_COORDINATOR"] = "127.0.0.1:9"
os.environ.pop("SPARKNET_NUM_PROCS", None)
os.environ.pop("SPARKNET_PROC_ID", None)
try:
    cluster.init_cluster_from_env()
    raise AssertionError("expected ValueError")
except ValueError as e:
    print(f"partial env contract -> ValueError: {e}")
finally:
    os.environ.pop("SPARKNET_COORDINATOR", None)

print("ALL DRIVES PASSED")
